"""Pytest root conftest: force an 8-virtual-device CPU mesh for all tests.

This is the TPU-world upgrade of the reference's test affordances
(SURVEY.md §4: injectable telemetry, mock fleet, dry-run): real mesh/pjit/
FSDP semantics on one host, no TPU required.

Note: the environment may import jax at interpreter startup (sitecustomize)
with a TPU platform preset, so ``JAX_PLATFORMS`` env alone is too late —
``jax.config.update`` is authoritative. ``XLA_FLAGS`` is still honoured
because the CPU client is created lazily, at first device query.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
