"""Pytest root conftest: force an 8-virtual-device CPU mesh for all tests.

This is the TPU-world upgrade of the reference's test affordances
(SURVEY.md §4: injectable telemetry, mock fleet, dry-run): real mesh/pjit/
FSDP semantics on one host, no TPU required.

Note: the environment may import jax at interpreter startup (sitecustomize)
with a TPU platform preset, so ``JAX_PLATFORMS`` env alone is too late —
``jax.config.update`` is authoritative. ``XLA_FLAGS`` is still honoured
because the CPU client is created lazily, at first device query.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_per_module():
    """Drop compiled programs between test modules.

    A single-process run of the FULL suite (fast + slow, 430 tests)
    accumulates every module's jitted executables in the CPU client and
    aborts (SIGABRT inside XLA:CPU execution) in the final module —
    reproducible at ~the 420th test, gone when either half runs alone.
    Per-module cache clearing bounds the accumulation; modules recompile
    their own programs anyway (shapes differ across modules), so the
    only cost is losing cross-module cache hits that barely exist."""
    yield
    jax.clear_caches()
