"""Flash-attention kernel microbenchmark (real chip).

The round-3 roofline put the flash kernels at 16.2% of the headline step,
VPU-bound on the softmax chain (RESULTS.md:171-174 names it the next
lever). This times the kernel in isolation — fwd and fwd+bwd — at the
headline shapes, so kernel changes get an honest before/after.

Run: ``python benchmarks/flash_microbench.py`` (prints one JSON line per
shape/mode).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import json
import time


def main() -> None:
    import sys

    import jax
    import jax.numpy as jnp

    from tpu_engine.ops import _flash_pallas
    from tpu_engine.ops.flash_attention import mha

    # --bwd-block N: sweep the backward tile cap (see _flash_bwd).
    if "--bwd-block" in sys.argv:
        cap = int(sys.argv[sys.argv.index("--bwd-block") + 1])
        _flash_pallas._BWD_BLOCK_CAP = cap
        print(json.dumps({"bwd_block_cap": cap}))

    shapes = [
        # (tag, BH, S, D, window)  — BH = batch × heads after GQA expand
        ("llama7b_seq4096", 32, 4096, 128, 0),
        ("llama7b_seq8192", 32, 8192, 128, 0),
        ("mistral_win4096_seq8192", 32, 8192, 128, 4096),
    ]
    rng = jax.random.PRNGKey(0)
    for idx, (tag, BH, S, D, window) in enumerate(shapes):
        # Deterministic per-shape seed (hash() is salted per interpreter —
        # the before/after runs this file exists for must see identical data).
        ks = jax.random.split(jax.random.fold_in(rng, idx), 3)
        q = jax.random.normal(ks[0], (1, S, BH, D), jnp.bfloat16)
        k = jax.random.normal(ks[1], (1, S, BH, D), jnp.bfloat16)
        v = jax.random.normal(ks[2], (1, S, BH, D), jnp.bfloat16)

        # Timing through a remote/tunneled runtime: per-dispatch overhead is
        # several ms, so the iteration loop lives INSIDE the jit — a scan
        # whose carry chains each iteration's output into the next input
        # (data dependence defeats CSE; the Pallas call is opaque to DCE).
        # One dispatch runs N kernels; the returned scalar forces sync.
        N = 32

        def fwd_loop(q, k, v):
            def body(qq, _):
                return mha(qq, k, v, window=window), None
            out, _ = jax.lax.scan(body, q, None, length=N)
            return out[0, 0, 0, 0]

        def loss(q, k, v):
            return jnp.sum(mha(q, k, v, window=window).astype(jnp.float32) ** 2)

        def fwdbwd_loop(q, k, v):
            def body(qq, _):
                dq, _, _ = jax.grad(loss, argnums=(0, 1, 2))(qq, k, v)
                return dq.astype(qq.dtype), None
            out, _ = jax.lax.scan(body, q, None, length=N)
            return out[0, 0, 0, 0]

        for mode, f in (("fwd", fwd_loop), ("fwd_bwd", fwdbwd_loop)):
            fn = jax.jit(f)
            float(fn(q, k, v))  # compile + one sync
            reps = 3
            t0 = time.perf_counter()
            for _ in range(reps):
                s = fn(q, k, v)
            float(s)
            ms = (time.perf_counter() - t0) / (reps * N) * 1e3
            # Causal attention FLOPs: 2·S·S·D per (bh) for qk, same for pv,
            # halved by causality; windowed further reduced.
            ctx = min(S, window) if window else S
            approx = BH * (2 * 2 * S * ctx * D) * (0.5 if not window else 1.0)
            if mode == "fwd_bwd":
                approx *= 3.5  # bwd ≈ 2.5x fwd for flash
            print(json.dumps({
                "shape": tag, "mode": mode, "bh": BH, "seq": S,
                "window": window, "ms": round(ms, 3),
                "approx_tflops": round(approx / ms / 1e9, 1),
            }))


if __name__ == "__main__":
    main()
