"""Ring attention: Pallas-kernel hops vs dense-einsum hops (AOT memory A/B).

Round-2 carry-over: ring attention computed each visiting K/V block with a
dense fp32 einsum — materialising a [B, H, S_local, S_local] score tensor
per hop. Round 3 routes every hop through the Pallas flash kernel
(``ops._flash_pallas.flash_fwd_lse``: the kernel's log-sum-exp output
merges hops online, differentiably), so no score tensor exists at any
scale. This benchmark AOT-compiles a long-context training step both ways
and lets ``memory_analysis`` (or the OOM) tell the story.

Run: ``python benchmarks/ring_flash.py``   (results in RESULTS.md)
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import json
import time


def main() -> None:
    import tpu_engine.parallel.ring_attention as ra
    from benchmarks.aot import aot_lowered

    orig = ra._ring_attention_local

    def dense_local(q, k, v, axis_name, causal=True, interpret=False,
                    use_flash=True):
        return orig(q, k, v, axis_name, causal=causal, interpret=interpret,
                    use_flash=False)

    for mode in ("flash", "dense"):
        ra._ring_attention_local = orig if mode == "flash" else dense_local
        t0 = time.time()
        try:
            comp = aot_lowered(
                "llama-1b", "v5e:4x4", dict(data=1, fsdp=4, sequence=4),
                micro=1, accum=1, seq=32768,
                overrides={"activation_checkpointing": True},
            ).compile()
            ma = comp.memory_analysis()
            print(json.dumps({
                "ring_hops": mode, "seq": 32768,
                "device_args_gib": round(ma.argument_size_in_bytes / 2**30, 2),
                "device_temp_gib": round(ma.temp_size_in_bytes / 2**30, 2),
                "compile_s": round(time.time() - t0, 1),
            }))
        except Exception as e:  # OOM is the result, not a failure
            print(json.dumps({
                "ring_hops": mode, "seq": 32768, "error": str(e)[:200],
            }))
    ra._ring_attention_local = orig


if __name__ == "__main__":
    main()
