"""Measured MoE training throughput/MFU on the real chip.

Round-4 verdict weakness 2: MoE had zero performance evidence — the
`8x7b` preset is AOT-fit-checked by `preset_fit_sweep.py`, and THIS
script supplies the measured row: a Mixtral-shaped model scaled to fit
one 16 GiB chip (8 experts, top-2 routing, capacity-factor dense
dispatch — the exact `_moe_mlp` path the 8x7b preset trains), timed
through the same harness discipline as `bench.py` (warmup, min of three
10-step windows).

MFU uses ACTIVE-parameter FLOPs (`train_flops_per_token` counts top-k
experts only), so the number is honest about routed compute: the
capacity-factor overhead (dispatch/combine einsums, dropped-token
padding) shows up as LOST utilisation, not hidden accounting. A dense
model of the same active shape is measured alongside — the gap IS the
routing tax.

Run: ``python benchmarks/moe_bench.py``.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import json
import time

import jax

MOE = dict(
    name="moe-mid", vocab_size=32_000, d_model=1024, n_layers=8,
    n_heads=16, n_kv_heads=8, d_ff=2816, max_seq_len=2048,
    n_experts=8, top_k=2,
)
# Same everything, one always-on expert-sized MLP — the active compute
# twin (top_k=2 of d_ff F ≈ dense with 2F; router/dispatch absent).
DENSE = dict(
    name="dense-twin", vocab_size=32_000, d_model=1024, n_layers=8,
    n_heads=16, n_kv_heads=8, d_ff=2 * 2816, max_seq_len=2048,
)


def _measure(model_cfg, micro: int) -> dict:
    from tpu_engine.mesh_runtime import MeshConfig, MeshRuntime
    from tpu_engine.models import transformer as tfm
    from tpu_engine.profiler import peak_flops_per_chip
    from tpu_engine.sharding import ShardingStage, TPUTrainConfig
    from tpu_engine.train import build_train_program

    cfg = TPUTrainConfig(
        model_name="gpt-tiny",  # overridden by model_cfg below
        sharding_stage=ShardingStage.DISABLED,
        mesh=MeshConfig(data=1),
        micro_batch_size=micro,
        gradient_accumulation_steps=1,
        seq_len=2048,
        precision="bf16",
        moment_dtype="bf16",
        activation_checkpointing=True,
        total_steps=100,
        warmup_steps=2,
    )
    mc = tfm.ModelConfig(**model_cfg)
    prog = build_train_program(cfg, model_cfg=mc,
                               runtime=MeshRuntime(cfg.mesh))
    state = prog.init(jax.random.PRNGKey(0))
    batch = prog.synthetic_batch(0)
    for _ in range(3):
        state, metrics = prog.step(state, batch)
    float(metrics["loss"])
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(10):
            state, metrics = prog.step(state, batch)
        float(metrics["loss"])
        best = min(best, (time.perf_counter() - t0) / 10)
    tokens_per_step = micro * cfg.seq_len
    tokens_per_sec = tokens_per_step / best
    fpt = tfm.train_flops_per_token(mc, cfg.seq_len)
    peak = peak_flops_per_chip(jax.devices()[0])
    return {
        "model": mc.name,
        "params_m": round(tfm.param_count(mc) / 1e6, 1),
        "active_params_m": round(tfm.active_param_count(mc) / 1e6, 1),
        "micro_batch": micro,
        "step_ms": round(best * 1e3, 2),
        "tokens_per_sec": round(tokens_per_sec, 1),
        "mfu_pct": round(100 * tokens_per_sec * fpt / peak, 2) if peak else None,
    }


def main() -> None:
    if jax.devices()[0].platform != "tpu":
        print(json.dumps({"skipped": "needs a local TPU"}))
        return
    moe = _measure(MOE, micro=8)
    ragged = _measure(dict(MOE, name="moe-mid-ragged", moe_impl="ragged"),
                      micro=8)
    dense = _measure(DENSE, micro=8)
    print(json.dumps(moe), flush=True)
    print(json.dumps(ragged), flush=True)
    print(json.dumps(dense), flush=True)
    print(json.dumps({
        "metric": "moe_throughput",
        "moe_tokens_per_sec": moe["tokens_per_sec"],
        "moe_mfu_pct": moe["mfu_pct"],
        "ragged_tokens_per_sec": ragged["tokens_per_sec"],
        "ragged_mfu_pct": ragged["mfu_pct"],
        "dense_twin_tokens_per_sec": dense["tokens_per_sec"],
        "dense_twin_mfu_pct": dense["mfu_pct"],
        "routing_tax_dense_dispatch": round(
            1 - moe["tokens_per_sec"] / dense["tokens_per_sec"], 3
        ),
        "routing_tax_ragged": round(
            1 - ragged["tokens_per_sec"] / dense["tokens_per_sec"], 3
        ),
    }))


if __name__ == "__main__":
    main()
