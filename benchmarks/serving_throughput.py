"""Continuous-batching serving throughput: per-step vs chunked decode.

Two scenarios, both on the real chip (prints one JSON line per mode):

1. **Unloaded burst** (round-3 measurement, kept for continuity): 8
   requests submitted at once into an 8-slot pool, drained to empty.
2. **Sustained mixed load** (round-3 verdict item 2's done condition):
   slots kept permanently full — every completion immediately replaced by
   a fresh submission, HALF the requests sampled (temperature 0.8), a
   non-empty queue throughout. Round 3's chunk path required
   ``all_greedy and queue_empty`` and so disengaged in exactly this
   scenario; round 4 samples inside the dispatch, so the chunk path must
   hold its advantage under load.

Through a remote/tunneled runtime the chunk mode's round-trip
amortisation is the whole story; on a local TPU VM both modes rise but
the ordering stands.

Run: ``python benchmarks/serving_throughput.py``.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import json
import time


def _drain(srv, rids):
    while not all(srv.result(r)["status"] == "done" for r in rids):
        srv.step()


def bench_burst(params, cfg, prompt, chunk):
    from tpu_engine.serving import ContinuousBatcher

    srv = ContinuousBatcher(params, cfg, max_slots=8, max_len=512,
                            chunk_steps=chunk)
    r0 = srv.submit(prompt, max_new_tokens=32)  # warm: compiles the path
    _drain(srv, [r0])
    t0 = time.time()
    rids = [srv.submit(prompt, max_new_tokens=128) for _ in range(8)]
    _drain(srv, rids)
    dt = time.time() - t0
    toks = 8 * 128
    return {
        "scenario": "burst_greedy", "chunk_steps": chunk, "slots": 8,
        "tokens": toks, "sec": round(dt, 2),
        "tokens_per_sec": round(toks / dt, 1),
    }


def bench_sustained(params, cfg, prompt, chunk, total_requests=48):
    """Slots never drain: each completion immediately enqueues a fresh
    request (so the queue is non-empty whenever a slot frees mid-chunk),
    and every other request samples at temperature 0.8."""
    from tpu_engine.serving import ContinuousBatcher

    srv = ContinuousBatcher(params, cfg, max_slots=8, max_len=512,
                            chunk_steps=chunk)
    temp = lambda i: 0.8 if i % 2 else 0.0
    warm = [srv.submit(prompt, max_new_tokens=16, temperature=t)
            for t in (0.0, 0.8)]  # compile greedy+sampled paths
    _drain(srv, warm)

    submitted = 0
    live: list[int] = []
    # Keep 10 in flight (8 slots + 2 queued) until the budget is spent.
    def top_up():
        nonlocal submitted
        while submitted < total_requests and len(live) < 10:
            live.append(srv.submit(prompt, max_new_tokens=64,
                                   temperature=temp(submitted)))
            submitted += 1

    t0 = time.time()
    top_up()
    done_tokens = 0
    while live:
        srv.step()
        still = []
        for rid in live:
            res = srv.result(rid)
            if res["status"] == "done":
                done_tokens += len(res["tokens"])
            else:
                still.append(rid)
        live[:] = still
        top_up()
    dt = time.time() - t0
    return {
        "scenario": "sustained_mixed", "chunk_steps": chunk, "slots": 8,
        "requests": total_requests, "sampled_fraction": 0.5,
        "tokens": done_tokens, "sec": round(dt, 2),
        "tokens_per_sec": round(done_tokens / dt, 1),
    }


def bench_speculative(params, cfg, draft_params, draft_cfg, prompt, gamma,
                      tag):
    """Spec-decode burst: 8 greedy requests, slots full. Reported against
    the chunked burst at the same load."""
    from tpu_engine.serving import ContinuousBatcher

    srv = ContinuousBatcher(params, cfg, max_slots=8, max_len=512,
                            draft_params=draft_params, draft_cfg=draft_cfg,
                            spec_gamma=gamma)
    r0 = srv.submit(prompt, max_new_tokens=16)
    _drain(srv, [r0])
    t0 = time.time()
    rids = [srv.submit(prompt, max_new_tokens=128) for _ in range(8)]
    _drain(srv, rids)
    dt = time.time() - t0
    toks = 8 * 128
    st = srv.stats()
    return {
        "scenario": f"burst_speculative_{tag}", "gamma": gamma, "slots": 8,
        "tokens": toks, "sec": round(dt, 2),
        "tokens_per_sec": round(toks / dt, 1),
        "spec_accept_rate": st.get("spec_accept_rate"),
    }


def main() -> None:
    import jax
    import jax.numpy as jnp

    from tpu_engine.models import transformer as tfm

    cfg = tfm.MODEL_CONFIGS["gpt-125m"]
    params = tfm.init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.bfloat16)
    prompt = list(range(1, 65))

    out = []
    for chunk in (1, 16):
        out.append(bench_burst(params, cfg, prompt, chunk))
        print(json.dumps(out[-1]))
    for chunk in (1, 16):
        out.append(bench_sustained(params, cfg, prompt, chunk))
        print(json.dumps(out[-1]))
    sus = {o["chunk_steps"]: o["tokens_per_sec"]
           for o in out if o["scenario"] == "sustained_mixed"}
    print(json.dumps({
        "metric": "serving_sustained_chunk_speedup",
        "value": round(sus[16] / sus[1], 2),
        "unit": "x_vs_per_step",
    }))

    # Speculative bounds. No distilled draft exists in-image (zero egress,
    # random inits — a fresh small model's argmax never agrees with the
    # target's), so measure the two honest endpoints: acceptance ceiling
    # (draft == target: alpha ~= 1 at worst-case draft cost) and floor (a
    # 2-layer random draft: alpha ~= 1/(gamma+1), pure overhead).
    print(json.dumps(bench_speculative(
        params, cfg, params, cfg, prompt, gamma=7, tag="ceiling")))
    draft_cfg = cfg.with_(name="gpt-125m-d2", n_layers=2)
    draft_params = tfm.init_params(jax.random.PRNGKey(5), draft_cfg,
                                   dtype=jnp.bfloat16)
    print(json.dumps(bench_speculative(
        params, cfg, draft_params, draft_cfg, prompt, gamma=4, tag="floor")))


if __name__ == "__main__":
    main()
