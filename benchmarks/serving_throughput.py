"""Continuous-batching serving throughput: per-step vs chunked decode.

Real-chip A/B behind the RESULTS.md serving table: 8 concurrent requests
through an 8-slot pool, per-step decode (one host round-trip per token)
vs chunked greedy decode (``chunk_steps`` tokens per dispatch, in-scan
argmax feedback). Through a remote/tunneled runtime the chunk mode's
round-trip amortisation is the whole story; on a local TPU VM both modes
rise but the ordering stands.

Run: ``python benchmarks/serving_throughput.py`` (real TPU; prints one
JSON line per mode).
"""

from __future__ import annotations

import json
import time


def main() -> None:
    import jax
    import jax.numpy as jnp

    from tpu_engine.models import transformer as tfm
    from tpu_engine.serving import ContinuousBatcher

    cfg = tfm.MODEL_CONFIGS["gpt-125m"]
    params = tfm.init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.bfloat16)
    prompt = list(range(1, 65))

    for chunk in (1, 16):
        srv = ContinuousBatcher(params, cfg, max_slots=8, max_len=512,
                                chunk_steps=chunk)
        # Warm: one request end-to-end compiles prefill + decode/chunk.
        r0 = srv.submit(prompt, max_new_tokens=32)
        while srv.result(r0)["status"] != "done":
            srv.step()
        t0 = time.time()
        rids = [srv.submit(prompt, max_new_tokens=128) for _ in range(8)]
        while not all(srv.result(r)["status"] == "done" for r in rids):
            srv.step()
        dt = time.time() - t0
        toks = 8 * 128
        print(json.dumps({
            "chunk_steps": chunk, "slots": 8, "tokens": toks,
            "sec": round(dt, 2), "tokens_per_sec": round(toks / dt, 1),
        }))


if __name__ == "__main__":
    main()
