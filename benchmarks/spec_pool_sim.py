"""Fleet speculative decoding pools A/B: plain chunked decode vs
draft/verify pools with acceptance-aware spill.

Runs :func:`tpu_engine.twin.spec_pool_ab` — the twin serving lane with a
seeded bursty multi-tenant trace at EQUAL chips through the REAL
:class:`~tpu_engine.serving_fleet.FleetRouter`, a real
:class:`~tpu_engine.historian.MetricHistorian` carrying the per-tenant
``serving.spec.accept_rate`` series, and a real
:class:`~tpu_engine.spec_pool.SpecSpillController` consulting it on the
control cadence — and prints the A/B plus the bench line
(``JAX_PLATFORMS=cpu python -m benchmarks.spec_pool_sim``).

Exit gates (process exits 1 when any fails):

- ``spec_beats_plain_tokens_per_chip`` — tokens/sec/chip improves >=
  1.2x at equal chips on the bursty trace (offered load saturates plain
  decode; the speculative pools absorb it);
- ``p99_no_worse`` — end-to-end p99 latency no worse than plain;
- ``low_alpha_tenant_spilled`` — the junk-draft tenant (sustained α far
  below the floor) is spilled back to plain chunked decode by the
  historian-consulting rule, with an audited fired DecisionRecord;
- ``spilled_tenant_not_below_plain_baseline`` — the spilled tenant's
  p99 is no worse than it would have been without speculation (a bad
  draft can never make serving slower than the baseline);
- ``deterministic_repeat`` — a second spec run is byte-identical;
- ``draft_hbm_rejected`` — ``estimate_serving_hbm`` refuses an
  oversubscribed colocated draft with a structured reason;
- ``draft_plan_feasible`` — ``plan_serving_pool(role="draft")`` finds a
  propose-latency-ranked layout inside small fragmented headroom.
"""

from __future__ import annotations

import json

from tpu_engine.twin import spec_pool_ab, spec_pool_bench_line


def main() -> None:
    res = spec_pool_ab(seed=0)
    print(json.dumps({
        "plain": res["plain"],
        "spec": res["spec"],
        "tokens_per_sec_per_chip_ratio": res["tokens_per_sec_per_chip_ratio"],
        "p99_ratio": res["p99_ratio"],
        "low_alpha_tenant": res["low_alpha_tenant"],
        "low_alpha_tenant_p99_ratio": res["low_alpha_tenant_p99_ratio"],
        "spill_decisions_fired": res["spill_decisions_fired"],
        "draft_hbm_rejection": res["draft_hbm_rejection"],
        "spec_replica_gib": res["spec_replica_gib"],
        "draft_plan_label": res["draft_plan_label"],
        "gates": res["gates"],
        "ok": res["ok"],
    }, indent=2))
    line = spec_pool_bench_line(seed=0, ab=res)
    print(json.dumps(line))
    if not (res["ok"] and line["ok"]):
        raise SystemExit(1)


if __name__ == "__main__":
    main()
