"""Fleet-scheduler simulation: mixed-priority trace + real preempt-resume.

Two phases, both deterministic and both runnable on CPU
(``JAX_PLATFORMS=cpu python -m benchmarks.scheduler_sim``):

**Phase A — 20-job mixed-priority trace on the mock fleet.** FakeJobs
(thread-backed, timed "work", honoring the scheduler's stop/preempt verbs)
drive :class:`~tpu_engine.scheduler.FleetScheduler` against
``TPUManager.get_mock_fleet()`` (8 chips, chip 5 hot → 7 healthy). Measures
makespan, mean admission wait, and goodput (completed work-seconds per
wall-second) against the analytic **serial FIFO** baseline the reference
launcher amounts to (one job at a time, submission order, no queue). The
trace includes:

- a HIGH-priority gang-8 job that can never be placed (7 healthy chips) —
  backfill admits the jobs behind it while its skip reason says why, and it
  is cancelled at the end (chip 5 never heals);
- a CRITICAL job arriving mid-trace that preempts the lowest-priority
  running job through the emergency-save seam; the victim requeues and
  finishes with **zero lost work** (progress survives the preempt);
- per-device HBM demands that make the reservation ledger matter (two
  5 GiB jobs cannot stack on one 9.6 GiB-free chip).

**Phase C — warm-admission virtual lane.** A seeded single-slot queue of
jobs over a handful of mesh layouts, priced through a real (in-memory)
:class:`~tpu_engine.compile_index.CompileCacheIndex`: the first job on a
layout compiles cold, later ones hit the warm cache. The same job list is
admitted twice — strict FIFO vs warm-preferring (the scheduler/planner's
cache-aware admission: among queued jobs, one whose layout the index says
is warm goes first). Warm-preferring front-loads cache hits, so mean
admission wait drops; the delta is the cache-aware-admission headline.

**Phase B — real checkpoint-preempt-requeue round trip.** A LOW-priority
gpt-tiny job (40 steps, checkpoint interval beyond the horizon so only the
emergency save can persist progress) is preempted by a HIGH-priority job on
a one-slot scheduler: watcher fires → synchronous Orbax save → requeue →
HIGH runs → LOW re-admitted and resumes from exactly the saved step.
Asserts ``resumed_from_step == step at preemption`` — zero lost steps.

Prints one JSON document; ``bench.py`` reuses :func:`run_trace` for its
scheduler metric line.
"""

from __future__ import annotations

import json
import os
import random
import sys
import tempfile
import threading
import time
from typing import Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu_engine.goodput import GoodputLedger, set_ledger  # noqa: E402
from tpu_engine.hbm_estimate import HBMEstimate, gang_size  # noqa: E402
from tpu_engine.mesh_runtime import MeshConfig  # noqa: E402
from tpu_engine.scheduler import (  # noqa: E402
    FleetScheduler,
    JobPriority,
    SubmissionState,
)
from tpu_engine.sharding import TPUTrainConfig  # noqa: E402
from tpu_engine.supervisor import JobStatus  # noqa: E402
from tpu_engine.tpu_manager import TPUManager  # noqa: E402
from tpu_engine.twin import warm_admission_lane  # noqa: E402

# ---------------------------------------------------------------------------
# Phase A: FakeJob trace on the mock fleet.
# ---------------------------------------------------------------------------

_TICK = 0.02  # one FakeJob "step" in seconds


class _FakeWatcher:
    """The one verb the scheduler speaks to a watcher."""

    def __init__(self, job: "FakeJob"):
        self._job = job

    def simulate_interruption(self) -> None:
        self._job._preempt.set()


class FakeJob:
    """Thread-backed stand-in for TrainingJob: timed work instead of train
    steps, same lifecycle surface the scheduler drives (status / is_alive /
    start / join / _stop / watcher). Progress lives in a shared registry
    keyed by submission id, so a preempted attempt's work survives — the
    FakeJob analogue of the emergency checkpoint."""

    def __init__(self, sub, duration_s: float, progress: dict[str, float]):
        self.job_id = sub.job_id
        self.config = sub.config
        self.status = JobStatus.PENDING
        self.error: Optional[str] = None
        self._stop = threading.Event()
        self._preempt = threading.Event()
        self.watcher = _FakeWatcher(self)
        self._progress = progress
        self._key = sub.submission_id
        self.duration_s = duration_s
        done = progress.get(self._key, 0.0)
        self.current_step = int(done / _TICK)
        self.resumed_from_step = self.current_step or None
        self._thread = threading.Thread(target=self._run, daemon=True)

    @property
    def is_alive(self) -> bool:
        return self._thread.is_alive()

    def start(self) -> None:
        self._thread.start()

    def join(self, timeout: Optional[float] = None) -> None:
        self._thread.join(timeout)

    def describe(self) -> dict:
        return {
            "job_id": self.job_id,
            "status": self.status.value,
            "current_step": self.current_step,
        }

    def _run(self) -> None:
        self.status = JobStatus.RUNNING
        done = self._progress.get(self._key, 0.0)
        while done < self.duration_s:
            if self._stop.is_set():
                self._progress[self._key] = done
                self.status = JobStatus.STOPPED
                return
            if self._preempt.is_set():
                self._progress[self._key] = done  # the "emergency save"
                self.status = JobStatus.PREEMPTED
                return
            time.sleep(_TICK)
            done += _TICK
            self.current_step = int(done / _TICK)
        self._progress[self._key] = self.duration_s
        self.status = JobStatus.COMPLETED


def _trace_config(tag: int, gang: int) -> TPUTrainConfig:
    """One trace job's config. ``micro_batch_size`` carries the trace tag
    (FakeJobs never train, the field is free); the mesh encodes the gang."""
    fsdp = min(gang, 4)
    return TPUTrainConfig(
        model_name="gpt-tiny",
        mesh=MeshConfig(data=gang // fsdp, fsdp=fsdp),
        micro_batch_size=tag,
        seq_len=32,
        precision="fp32",
        total_steps=10,
        activation_checkpointing=False,
        checkpoint_dir=f"/tmp/sched_sim/{tag}",  # preemptibility flag only
    )


# (priority, gang devices, duration s, per-device HBM GiB) per trace job.
# Healthy mock chips have 9.6 GiB free, so two 5 GiB jobs cannot share a
# chip — the reservation ledger must spread or serialise them.
_TRACE: list[tuple[JobPriority, int, float, float]] = [
    (JobPriority.NORMAL, 4, 0.50, 2.0),
    (JobPriority.LOW, 2, 0.70, 5.0),
    (JobPriority.NORMAL, 1, 0.30, 1.0),
    (JobPriority.LOW, 4, 0.60, 3.0),
    (JobPriority.HIGH, 2, 0.40, 2.0),
    (JobPriority.NORMAL, 2, 0.50, 5.0),
    (JobPriority.LOW, 1, 0.80, 1.5),
    (JobPriority.NORMAL, 4, 0.40, 2.5),
    (JobPriority.HIGH, 1, 0.30, 1.0),
    (JobPriority.LOW, 2, 0.60, 4.0),
    (JobPriority.NORMAL, 1, 0.50, 2.0),
    (JobPriority.LOW, 4, 0.70, 3.0),
    (JobPriority.NORMAL, 2, 0.40, 1.5),
    (JobPriority.HIGH, 4, 0.50, 2.0),
    (JobPriority.LOW, 1, 0.30, 1.0),
    (JobPriority.NORMAL, 2, 0.60, 2.5),
    (JobPriority.LOW, 2, 0.50, 3.5),
    (JobPriority.NORMAL, 1, 0.40, 1.0),
    (JobPriority.LOW, 4, 0.60, 2.0),
    (JobPriority.NORMAL, 2, 0.50, 1.5),
]
_CRITICAL_LATECOMER = (JobPriority.CRITICAL, 4, 0.60, 2.0)


def run_trace(max_concurrent_jobs: int = 3) -> dict:
    """Phase A. Returns the measured trace metrics vs the serial baseline."""
    # Fresh process-wide ledger: the scheduler's submit/finish hooks track
    # and finalize every submission's trace through it, so Phase A gets a
    # real wall-clock decomposition for free (FakeJobs record no attempt
    # spans — queue wait comes from submit events + admission spans, the
    # rest of the root window counts productive).
    ledger = GoodputLedger()
    set_ledger(ledger)
    progress: dict[str, float] = {}
    durations: dict[int, float] = {}
    hbm_by_tag: dict[int, float] = {}

    def factory(sub):
        return FakeJob(sub, durations[sub.config.micro_batch_size], progress)

    def estimate(cfg, n_avail):
        # Trace jobs carry their HBM demand out-of-band (keyed by tag);
        # everything else about the estimate mirrors the analytic plane.
        gib = hbm_by_tag[cfg.micro_batch_size]
        return HBMEstimate(
            model_name=cfg.model_name, gang_devices=gang_size(cfg, n_avail),
            params_gib=gib, grads_gib=0.0, opt_gib=0.0, working_gib=0.0,
            activations_gib=0.0, logits_gib=0.0, device_total_gib=gib,
            host_gib=0.0,
        )

    sched = FleetScheduler(
        max_concurrent_jobs=max_concurrent_jobs,
        fleet_fn=TPUManager.get_mock_fleet,
        job_factory=factory,
        estimate_fn=estimate,
        backfill_depth=4,
        poll_interval_s=0.02,
    )

    t0 = time.time()
    subs = []
    for i, (prio, gang, dur, gib) in enumerate(_TRACE):
        tag = i + 1
        durations[tag] = dur
        hbm_by_tag[tag] = gib
        subs.append(sched.submit(_trace_config(tag, gang), priority=prio))

    # The unplaceable head: gang 8 > 7 healthy chips, HIGH priority so it
    # sits at the front of the queue and backfill must route around it.
    blocked_tag = len(_TRACE) + 1
    durations[blocked_tag] = 1.0
    hbm_by_tag[blocked_tag] = 1.0
    blocked = sched.submit(
        _trace_config(blocked_tag, gang=8), priority=JobPriority.HIGH
    )

    # Mid-trace CRITICAL arrival → preempts a running lower-priority job.
    time.sleep(0.3)
    prio, gang, dur, gib = _CRITICAL_LATECOMER
    crit_tag = len(_TRACE) + 2
    durations[crit_tag] = dur
    hbm_by_tag[crit_tag] = gib
    crit = sched.submit(_trace_config(crit_tag, gang), priority=prio)

    deadline = time.time() + 120
    while time.time() < deadline:
        open_subs = [
            s for s in subs + [crit]
            if s.state not in (SubmissionState.COMPLETED, SubmissionState.FAILED,
                               SubmissionState.CANCELLED)
        ]
        if not open_subs:
            break
        time.sleep(0.05)
    makespan = time.time() - t0

    # Chip 5 never heals: the gang-8 job is honestly unplaceable — cancel.
    blocked_reason = blocked.last_skip_reason
    sched.cancel(blocked.submission_id)
    stats = sched.stats()
    sched.shutdown()

    finished = [s for s in subs + [crit] if s.state == SubmissionState.COMPLETED]
    assert len(finished) == len(_TRACE) + 1, (
        f"{len(finished)} of {len(_TRACE) + 1} jobs completed; "
        f"states: {[s.state.value for s in subs + [crit]]}"
    )
    work_done = sum(durations[s.config.micro_batch_size] for s in finished)
    waits = [s.wait_s for s in finished if s.wait_s is not None]

    # Serial FIFO baseline (the reference's launcher: one at a time, strict
    # submission order, the unplaceable job refused rather than queued):
    # makespan = sum of durations, each job waits for every prior job.
    serial_durs = [d for (_, _, d, _) in _TRACE] + [_CRITICAL_LATECOMER[2]]
    serial_makespan = sum(serial_durs)
    acc, serial_waits = 0.0, []
    for d in serial_durs:
        serial_waits.append(acc)
        acc += d

    crit_progress = progress.get(crit.submission_id, 0.0)
    preempt_victims = [s for s in subs if s.preemptions > 0]
    gp = ledger.snapshot()
    return {
        "jobs": len(_TRACE) + 1,
        "slots": max_concurrent_jobs,
        "healthy_chips": 7,
        "makespan_s": round(makespan, 2),
        "serial_makespan_s": round(serial_makespan, 2),
        "speedup_vs_serial": round(serial_makespan / makespan, 2),
        "mean_wait_s": round(sum(waits) / len(waits), 3) if waits else 0.0,
        "serial_mean_wait_s": round(sum(serial_waits) / len(serial_waits), 3),
        "goodput_work_s_per_wall_s": round(work_done / makespan, 2),
        "serial_goodput": 1.0,
        "preemptions": stats["preemptions_total"],
        "requeues": stats["requeues_total"],
        "preempted_jobs_completed": all(
            s.state == SubmissionState.COMPLETED for s in preempt_victims
        ),
        "zero_lost_work": all(
            abs(progress[s.submission_id]
                - durations[s.config.micro_batch_size]) < 1e-6
            for s in preempt_victims
        ),
        "critical_completed": crit.state == SubmissionState.COMPLETED,
        "critical_work_s": round(crit_progress, 2),
        "gang8_skip_reason": blocked_reason,
        "gang8_final_state": blocked.state.value,
        "goodput_ledger": {
            "categories_s": {
                c: v for c, v in gp["categories"].items() if v > 0
            },
            "goodput_fraction": gp["goodput_fraction"],
            "traces_accounted": gp["traces_accounted"],
            "invariant_violations": gp["invariant_violations"],
        },
    }


# ---------------------------------------------------------------------------
# Phase C: warm-admission virtual lane (no threads, no sleeps — the twin's
# single-slot queue over a seeded job list, priced through a real
# CompileCacheIndex).
# ---------------------------------------------------------------------------

SIM_COLD_COMPILE_S = 15.0  # first compile of a layout (virtual seconds)
SIM_WARM_COMPILE_S = 1.5   # persistent-cache hit on a layout already seen


def _admission_lane(
    jobs: list[tuple[str, float]], prefer_warm: bool
) -> dict:
    """Cache-aware admission A/B leg — one slot, compile + work per job;
    the lane itself lives in :func:`tpu_engine.twin.warm_admission_lane`."""
    return warm_admission_lane(
        jobs, prefer_warm,
        cold_compile_s=SIM_COLD_COMPILE_S,
        warm_compile_s=SIM_WARM_COMPILE_S,
    )


def run_warm_admission(seed: int = 0, n_jobs: int = 16) -> dict:
    """Phase C. Same seeded job list, FIFO vs warm-preferring admission."""
    rng = random.Random(seed)
    layouts = [f"sim|data{g}xfsdp2" for g in (1, 2, 4)]
    jobs = [
        (rng.choice(layouts), round(rng.uniform(4.0, 12.0), 2))
        for _ in range(n_jobs)
    ]
    fifo = _admission_lane(jobs, prefer_warm=False)
    warm = _admission_lane(jobs, prefer_warm=True)
    return {
        "seed": seed,
        "jobs": n_jobs,
        "layouts": len(layouts),
        "cold_compile_s": SIM_COLD_COMPILE_S,
        "warm_compile_s": SIM_WARM_COMPILE_S,
        "fifo": fifo,
        "warm_preferring": warm,
        "mean_wait_fifo_s": fifo["mean_wait_s"],
        "mean_wait_warm_s": warm["mean_wait_s"],
        "wait_reduction_pct": round(
            100.0 * (1.0 - warm["mean_wait_s"] / fifo["mean_wait_s"]), 2
        ) if fifo["mean_wait_s"] else 0.0,
    }


# ---------------------------------------------------------------------------
# Phase B: real gpt-tiny checkpoint-preempt-requeue round trip.
# ---------------------------------------------------------------------------


def run_preempt_resume(low_steps: int = 40, high_steps: int = 5) -> dict:
    """Phase B. Returns the round-trip facts; asserts zero lost steps."""
    with tempfile.TemporaryDirectory(prefix="sched_sim_") as root:
        cfg = dict(
            model_name="gpt-tiny",
            mesh=MeshConfig(data=1, fsdp=1),
            micro_batch_size=1,
            seq_len=32,
            precision="fp32",
            activation_checkpointing=False,
            warmup_steps=1,
            # Interval beyond the horizon: ONLY the preemption emergency
            # save can persist progress — if resume works, it worked.
            checkpoint_interval_steps=1000,
        )
        sched = FleetScheduler(
            max_concurrent_jobs=1, checkpoint_root=root, poll_interval_s=0.05
        )
        try:
            import jax.numpy as jnp

            def slow_batch(step: int):
                # gpt-tiny steps take ~2 ms on CPU once compiled — the whole
                # 40-step run would outrace the preemption. Throttle the LOW
                # job's input pipeline so the preempt lands mid-run.
                time.sleep(0.02)
                return jnp.zeros((1, 1, cfg["seq_len"]), jnp.int32)

            low = sched.submit(
                TPUTrainConfig(total_steps=low_steps, **cfg),
                priority=JobPriority.LOW,
                job_kwargs={"data_fn": slow_batch},
            )
            deadline = time.time() + 300
            while time.time() < deadline:
                if low.job is not None and low.job.current_step >= 3:
                    break
                time.sleep(0.1)
            assert low.job is not None and low.job.current_step >= 3, (
                "low-priority job never got going"
            )

            high = sched.submit(
                TPUTrainConfig(total_steps=high_steps, **cfg),
                priority=JobPriority.HIGH,
            )
            high = sched.wait(high.submission_id, timeout=300)
            assert high.state == SubmissionState.COMPLETED, high.describe()

            low = sched.wait(low.submission_id, timeout=300)
            assert low.state == SubmissionState.COMPLETED, low.describe()
            assert low.preemptions == 1 and low.attempts == 2, low.describe()
            saved_step = low.job.resumed_from_step
            assert saved_step is not None and saved_step >= 3
            assert low.job.current_step == low_steps
            return {
                "low_total_steps": low_steps,
                "high_total_steps": high_steps,
                "preempted_at_step": saved_step,
                "resumed_from_step": saved_step,
                "zero_lost_steps": True,
                "low_attempts": low.attempts,
                "low_preemptions": low.preemptions,
                "high_wait_s": round(high.wait_s or 0.0, 2),
                "stats": sched.stats(),
            }
        finally:
            sched.shutdown()


def main() -> None:
    trace = run_trace()
    print(json.dumps({"phase": "trace", **trace}, indent=2))
    warm = run_warm_admission()
    print(json.dumps({"phase": "warm_admission", **warm}, indent=2))
    roundtrip = run_preempt_resume()
    print(json.dumps({"phase": "preempt_resume", **roundtrip}, indent=2))
    ok = (
        trace["speedup_vs_serial"] >= 1.0
        and trace["zero_lost_work"]
        and roundtrip["zero_lost_steps"]
        and warm["mean_wait_warm_s"] < warm["mean_wait_fifo_s"]
    )
    print(json.dumps({
        "metric": "scheduler_goodput_vs_serial_fifo",
        "value": trace["goodput_work_s_per_wall_s"],
        "unit": "work-seconds per wall-second (serial FIFO = 1.0)",
        "speedup_vs_serial": trace["speedup_vs_serial"],
        "zero_lost_steps": roundtrip["zero_lost_steps"],
        "ok": ok,
    }))
    print(json.dumps({
        "metric": "scheduler_warm_admission_wait",
        "value": warm["wait_reduction_pct"],
        "unit": "% mean-wait reduction, warm-preferring vs FIFO admission",
        "mean_wait_fifo_s": warm["mean_wait_fifo_s"],
        "mean_wait_warm_s": warm["mean_wait_warm_s"],
        "ok": ok,
    }))
    if not ok:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
