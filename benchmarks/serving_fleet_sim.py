"""Serving-fleet sim: autoscaled replicas vs a static single replica.

Deterministic discrete-event comparison (virtual clock — no threads, no
JAX, identical numbers every run) of two fleet policies on the same
seeded bursty open-loop request trace:

- **static-1** — what ``tpu_engine/serving.py`` alone gives you: one
  decode replica; every burst queues behind its slot pool.
- **autoscaled** — this repo's :class:`ServingFleet` control plane: the
  REAL :class:`~tpu_engine.serving_fleet.FleetRouter` (throughput ×
  free-slot smooth WRR + shared-prefix affinity) and the REAL
  :class:`~tpu_engine.serving_fleet.ReplicaAutoscaler` (sliding-window
  queue depth + p99 SLO, scale-down hysteresis) drive replica count
  between min and max. New replicas pay a startup delay (scheduler
  admission + weight load + compile), exactly the lag hysteresis exists
  to hide.

Replicas are capacity models, not transformers: ``SLOTS`` concurrent
requests each decoding ``per-slot tokens/sec`` (one replica runs on a
degraded host at a fraction of that — the router's weights, not a
health-check binary, decide how much traffic it still deserves). A
request's prompt opens with one of a few shared system prefixes;
replica-side prefix caches skip the prefill for resident prefixes, which
is what router affinity is for.

Reports aggregate tokens/sec (and per chip-second, so extra replicas
don't get their throughput for free), p50/p99 latency vs the SLO, the
replica-count trace, router weights and affinity hit rate;
``bench.py`` reuses :func:`run_trace` for its serving-fleet line.

A second experiment (PR 12) A/Bs **symmetric vs disaggregated** serving
at EQUAL total chips on a long-prefill-heavy bursty trace. The symmetric
fleet models the real ``ContinuousBatcher`` interference: a chunked
prefill monopolizes the MXU, so co-resident decode slots crawl while any
prefill is in flight — slots stay occupied longer, admission stalls, and
p99 TTFT compounds. The disaggregated fleet (``tpu_engine/disagg.py``)
runs planner-placed pools — prefill layout ranked by the compute
roofline, decode by KV-pool capacity, both from the REAL
:func:`tpu_engine.placement.plan_serving_pool` — with a host-side KV
handoff between them; decode never stalls and TTFT is the prefill-pool
latency. ``main()`` exit-gates the A/B: disaggregated must beat
symmetric p99 TTFT with tokens/sec no worse, and the JSON records both
configurations' planner-chosen layouts.

Run: ``python -m benchmarks.serving_fleet_sim [--seed N]``.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu_engine.serving_fleet import (  # noqa: E402
    AutoscalerConfig,
    FleetRouter,
    ReplicaAutoscaler,
)

SIM_DURATION_S = 600.0
DT_S = 0.05                  # sim tick
CONTROL_PERIOD_S = 1.0       # autoscaler / router refresh cadence
SLOTS = 8                    # decode slots per replica
TOKENS_PER_SLOT_S = 30.0     # healthy per-slot decode rate
DEGRADED_FRACTION = 0.4      # replica 0 runs on a slow host at this rate
PREFILL_S = 1.2              # full prefill latency (cold prefix)
PREFILL_HIT_S = 0.15         # prefix-cache hit: decode-only prefill remainder
STARTUP_DELAY_S = 25.0       # admission + weight load + compile for a new replica
CHIPS_PER_REPLICA = 1
BASE_RATE_RPS = 1.0          # open-loop arrivals outside bursts
BURST_RATE_RPS = 14.0        # arrivals inside a burst window
BURST_EVERY_S = 120.0
BURST_LEN_S = 35.0
N_PREFIXES = 4               # shared system prompts
PREFIX_LEN = 32
MEAN_NEW_TOKENS = 96
P99_SLO_MS = 25_000.0
# Latency percentiles are steady-state: the first burst cycle is warmup
# (it lands on the min fleet by construction — what it measures is the
# startup delay, not the policy). Throughput counts everything.
WARMUP_S = BURST_EVERY_S

AUTOSCALER = AutoscalerConfig(
    min_replicas=1,
    max_replicas=8,
    target_queue_per_replica=4.0,
    low_water_queue_per_replica=0.5,
    p99_slo_ms=P99_SLO_MS,
    window_s=20.0,
    scale_up_cooldown_s=3.0,
    scale_down_cooldown_s=90.0,
)


def request_trace(seed: int) -> list[dict]:
    """Seeded bursty open-loop arrivals: [{t, prefix_id, prompt, n_new}]."""
    rng = random.Random(seed)
    out, t = [], 0.0
    while t < SIM_DURATION_S:
        in_burst = (t % BURST_EVERY_S) < BURST_LEN_S
        rate = BURST_RATE_RPS if in_burst else BASE_RATE_RPS
        t += rng.expovariate(rate)
        if t >= SIM_DURATION_S:
            break
        pid = rng.randrange(N_PREFIXES)
        # Prompt = shared prefix tokens + a unique tail (router affinity
        # keys on the first tokens; the tail keeps requests distinct).
        prompt = [pid * PREFIX_LEN + i for i in range(PREFIX_LEN)]
        prompt.append(10_000 + len(out))
        out.append({
            "t": t,
            "prefix_id": pid,
            "prompt": prompt,
            "n_new": max(8, int(rng.expovariate(1.0 / MEAN_NEW_TOKENS))),
        })
    return out


class SimReplica:
    """Capacity model of one decode replica: a slot pool, a per-slot decode
    rate, and a prefix cache that skips prefill for resident prefixes."""

    def __init__(self, rid: str, rate_fraction: float, ready_at: float):
        self.rid = rid
        self.rate = TOKENS_PER_SLOT_S * rate_fraction
        self.ready_at = ready_at
        self.active: list[dict] = []      # {req, prefill_left, tokens_left}
        self.prefix_cache: set[int] = set()
        self.tokens_out = 0.0
        self.draining = False

    def ready(self, now: float) -> bool:
        return now >= self.ready_at

    def free_slots(self, now: float) -> int:
        if not self.ready(now) or self.draining:
            return 0
        return SLOTS - len(self.active)

    def admit(self, req: dict) -> None:
        hit = req["prefix_id"] in self.prefix_cache
        self.prefix_cache.add(req["prefix_id"])
        self.active.append({
            "req": req,
            "prefill_left": PREFILL_HIT_S if hit else PREFILL_S,
            "tokens_left": float(req["n_new"]),
            "hit": hit,
        })

    def step(self, now: float, dt: float, done: list[dict]) -> None:
        if not self.ready(now):
            return
        for sl in list(self.active):
            if sl["prefill_left"] > 0:
                sl["prefill_left"] -= dt
                continue
            produced = min(self.rate * dt, sl["tokens_left"])
            sl["tokens_left"] -= produced
            self.tokens_out += produced
            if sl["tokens_left"] <= 0:
                sl["req"]["done_at"] = now
                sl["req"]["replica"] = self.rid
                sl["req"]["prefix_hit"] = sl["hit"]
                done.append(sl["req"])
                self.active.remove(sl)

    def router_stats(self, now: float) -> dict:
        # tokens/sec the router would measure: rate × busy slots (plus a
        # trickle when idle so a fresh replica is not weight-zero).
        busy = sum(1 for s in self.active if s["prefill_left"] <= 0)
        return {
            "tokens_per_sec": self.rate * max(busy, 0.2),
            "free_slots": self.free_slots(now),
            "slots": SLOTS,
        }


def _percentile(vals: list[float], q: float) -> float:
    if not vals:
        return 0.0
    vals = sorted(vals)
    return vals[min(int(q * (len(vals) - 1)), len(vals) - 1)]


def _simulate(trace: list[dict], autoscale: bool) -> dict:
    router = FleetRouter(affinity_tokens=PREFIX_LEN)
    scaler = ReplicaAutoscaler(AUTOSCALER)
    replicas: dict[str, SimReplica] = {
        # Replica 0 is the degraded host — present from t=0 in both modes;
        # in static mode it is the whole fleet.
        "r0": SimReplica("r0", DEGRADED_FRACTION, ready_at=0.0)
    }
    next_rid = 1
    queue: list[dict] = []
    done: list[dict] = []
    idx = 0
    next_control = 0.0
    replica_trace: list[tuple[float, int]] = []
    chip_seconds = 0.0
    t = 0.0
    while t < SIM_DURATION_S or queue or any(r.active for r in replicas.values()):
        if t > SIM_DURATION_S * 3:  # safety: a sim bug must not spin forever
            break
        while idx < len(trace) and trace[idx]["t"] <= t:
            queue.append(trace[idx])
            idx += 1

        if t >= next_control:
            next_control = t + CONTROL_PERIOD_S
            up = {
                r.rid: r.router_stats(t)
                for r in replicas.values()
                if r.ready(t) and not r.draining
            }
            router.update(up)
            ready_n = len(up)
            # Change-point trace: one entry per replica-count transition
            # keeps the bench JSON line readable.
            if not replica_trace or replica_trace[-1][1] != ready_n:
                replica_trace.append((round(t, 1), ready_n))
            if autoscale and ready_n > 0:
                lat = [
                    (r["done_at"] - r["t"]) * 1000.0
                    for r in done[-256:]
                ]
                desired = scaler.observe(
                    t, len(queue), _percentile(lat, 0.99) if lat else None, ready_n
                )
                booting = sum(
                    1 for r in replicas.values()
                    if not r.ready(t) and not r.draining
                )
                while desired > ready_n + booting:
                    replicas[f"r{next_rid}"] = SimReplica(
                        f"r{next_rid}", 1.0, ready_at=t + STARTUP_DELAY_S
                    )
                    next_rid += 1
                    booting += 1
                if desired < ready_n:
                    # Drain the emptiest ready replica (never the last one).
                    cands = sorted(
                        (r for r in replicas.values()
                         if r.ready(t) and not r.draining and r.rid != "r0"),
                        key=lambda r: len(r.active),
                    )
                    for r in cands[: ready_n - desired]:
                        r.draining = True

        # Dispatch through the real router (affinity keys on the prefix).
        # Route only while the fleet has a free slot — an overloaded fleet
        # must queue, not spin the router on unplaceable requests.
        free_total = sum(r.free_slots(t) for r in replicas.values())
        placed = 0
        while queue and free_total > 0:
            req = queue[0]
            rid = router.route(req["prompt"])
            rep = replicas.get(rid) if rid else None
            if rep is not None and rep.free_slots(t) > 0:
                rep.admit(queue.pop(0))
                free_total -= 1
                placed += 1
            else:
                # Router picked a full/draining replica: stop this tick,
                # weights refresh at the next control period.
                break
            if placed > SLOTS * len(replicas):
                break

        for r in list(replicas.values()):
            r.step(t, DT_S, done)
            if r.draining and not r.active:
                del replicas[r.rid]
        chip_seconds += DT_S * CHIPS_PER_REPLICA * sum(
            1 for r in replicas.values() if r.ready(t)
        )
        t += DT_S

    lat_ms = [
        (r["done_at"] - r["t"]) * 1000.0 for r in done if r["t"] >= WARMUP_S
    ]
    # Count tokens from completed requests, not replica counters — drained
    # replicas leave the dict and would take their counters with them.
    total_tokens = float(sum(req["n_new"] for req in done))
    makespan = max((r["done_at"] for r in done), default=DT_S)
    p99 = _percentile(lat_ms, 0.99)
    return {
        "completed": len(done),
        "total_tokens": total_tokens,
        "tokens_per_sec": total_tokens / makespan,
        "tokens_per_sec_per_chip": total_tokens / max(chip_seconds, DT_S),
        "p50_ms": round(_percentile(lat_ms, 0.50), 1),
        "p99_ms": round(p99, 1),
        "p99_within_slo": p99 <= P99_SLO_MS,
        "makespan_s": round(makespan, 1),
        "replica_trace": replica_trace,
        "max_replicas_used": max(n for _, n in replica_trace),
        "prefix_hit_rate": round(
            sum(1 for r in done if r.get("prefix_hit")) / max(len(done), 1), 3
        ),
        "router": router.stats(),
        "autoscaler": scaler.stats(),
    }


def run_trace(seed: int = 0) -> dict:
    trace = request_trace(seed)
    auto = _simulate(trace, autoscale=True)
    static = _simulate(trace, autoscale=False)
    return {
        "seed": seed,
        "n_requests": len(trace),
        "autoscaled": auto,
        "static_1_replica": static,
        "throughput_improvement": round(
            auto["tokens_per_sec"] / max(static["tokens_per_sec"], 1e-9), 2
        ),
        "p99_improvement": round(
            static["p99_ms"] / max(auto["p99_ms"], 1e-9), 2
        ),
        "p99_slo_ms": P99_SLO_MS,
    }


# ---------------------------------------------------------------------------
# Symmetric vs disaggregated A/B (PR 12) — equal chips, long-prefill trace
# ---------------------------------------------------------------------------

TOTAL_CHIPS = 8              # equal-chips budget for BOTH configurations
PREFILL_CHIPS = 6            # disagg split: prefill-heavy trace → prefill-heavy pool
DECODE_CHIPS = TOTAL_CHIPS - PREFILL_CHIPS
LONG_PREFILL_MEAN_S = 1.5    # one prompt's prefill seconds on ONE chip (tp=1)
LONG_PREFILL_MIN_S = 0.3
LONG_MEAN_NEW = 96
LONG_BASE_RPS = 0.4
LONG_BURST_RPS = 3.0
HANDOFF_S = 0.05             # host-side KV wire latency (not on the TTFT path)
# Chunked-prefill interference in a SYMMETRIC replica: while a prefill
# chunk owns the MXU, co-resident decode steps run at this fraction of
# their clean cadence (a decode step is ~an order of magnitude shorter
# than a prefill chunk), and the prefill itself loses the decode share.
INTERFERENCE_DECODE = 0.15
INTERFERENCE_PREFILL = 0.85
PLAN_MODEL = "llama-7b"
PLAN_MAX_LEN = 2048
PLAN_HBM_GIB = 24.0
PLAN_INFLIGHT = 4            # prefill pool's in-flight handoff window


def long_prefill_trace(seed: int) -> list[dict]:
    """Seeded bursty arrivals with heavy, variable prefill cost:
    [{t, prompt, prefill_units, n_new}] — ``prefill_units`` is seconds of
    prefill work at tp=1."""
    rng = random.Random(seed + 7919)
    out, t = [], 0.0
    while t < SIM_DURATION_S:
        in_burst = (t % BURST_EVERY_S) < BURST_LEN_S
        t += rng.expovariate(LONG_BURST_RPS if in_burst else LONG_BASE_RPS)
        if t >= SIM_DURATION_S:
            break
        pid = rng.randrange(N_PREFIXES)
        prompt = [pid * PREFIX_LEN + i for i in range(PREFIX_LEN)]
        prompt.append(10_000 + len(out))
        out.append({
            "t": t,
            "prompt": prompt,
            "prefill_units": max(
                LONG_PREFILL_MIN_S, rng.expovariate(1.0 / LONG_PREFILL_MEAN_S)
            ),
            "n_new": max(8, int(rng.expovariate(1.0 / LONG_MEAN_NEW))),
        })
    return out


class SymReplica:
    """One chip, both phases. Prefills serialize (one chunked prefill at a
    time owns the MXU); while one is in flight every decoding slot crawls
    at the interference rate — the slot-starvation feedback that kills
    symmetric p99 TTFT under prefill bursts."""

    def __init__(self, rid: str):
        self.rid = rid
        self.active: list[dict] = []

    def free_slots(self) -> int:
        return SLOTS - len(self.active)

    def admit(self, req: dict, now: float) -> None:
        self.active.append({
            "req": req, "prefill_left": req["prefill_units"],
            "tokens_left": float(req["n_new"]),
        })

    def step(self, now: float, dt: float, done: list[dict],
             ttfts: list[float]) -> None:
        pre = next((s for s in self.active if s["prefill_left"] > 0), None)
        decode_rate = TOKENS_PER_SLOT_S
        if pre is not None:
            pre["prefill_left"] -= dt * INTERFERENCE_PREFILL
            if pre["prefill_left"] <= 0:
                pre["req"]["first_token_at"] = now + dt
                ttfts.append((now + dt - pre["req"]["t"]) * 1000.0)
            decode_rate *= INTERFERENCE_DECODE
        for sl in list(self.active):
            if sl["prefill_left"] > 0 or sl is pre:
                continue
            sl["tokens_left"] -= decode_rate * dt
            if sl["tokens_left"] <= 0:
                sl["req"]["done_at"] = now + dt
                done.append(sl["req"])
                self.active.remove(sl)

    def router_stats(self) -> dict:
        busy = sum(1 for s in self.active if s["prefill_left"] <= 0)
        return {
            "tokens_per_sec": TOKENS_PER_SLOT_S * max(busy, 0.2),
            "free_slots": self.free_slots(),
            "slots": SLOTS,
        }


def _simulate_symmetric_long(trace: list[dict]) -> dict:
    router = FleetRouter(affinity_tokens=PREFIX_LEN)
    replicas = [SymReplica(f"s{i}") for i in range(TOTAL_CHIPS)]
    by_id = {r.rid: r for r in replicas}
    queue: list[dict] = []
    done: list[dict] = []
    ttfts: list[float] = []
    idx, t, next_control = 0, 0.0, 0.0
    while t < SIM_DURATION_S or queue or any(r.active for r in replicas):
        if t > SIM_DURATION_S * 6:
            break
        while idx < len(trace) and trace[idx]["t"] <= t:
            queue.append(trace[idx])
            idx += 1
        if t >= next_control:
            next_control = t + CONTROL_PERIOD_S
            router.update({r.rid: r.router_stats() for r in replicas})
        while queue and any(r.free_slots() > 0 for r in replicas):
            rid = router.route(queue[0]["prompt"])
            rep = by_id.get(rid) if rid else None
            if rep is None or rep.free_slots() <= 0:
                break  # router picked a full replica; weights refresh next tick
            rep.admit(queue.pop(0), t)
        for r in replicas:
            r.step(t, DT_S, done, ttfts)
        t += DT_S
    return _ab_metrics(done, ttfts, t)


def _simulate_disagg(trace: list[dict], prefill_plan, decode_plan,
                     prefill_speedup: float) -> dict:
    """Planner-placed pools: ``prefill_plan.replicas`` serial prefill
    servers (each ``prefill_speedup`` × one chip, the roofline ratio the
    planner predicted for its tensor-parallel choice) feeding
    ``decode_plan.replicas`` decode-only replicas through a ``HANDOFF_S``
    KV wire. Decode never shares the MXU with a prefill."""
    # Per-slot decode rate: the pool's chips stream the same aggregate
    # HBM bandwidth as the symmetric fleet's per-chip 8×30 tok/s; more
    # slots trade per-slot speed for concurrency (the KV-capacity axis).
    dec_rate = (TOKENS_PER_SLOT_S * SLOTS * decode_plan.tensor_parallel
                / decode_plan.max_slots)
    prefill_router = FleetRouter(affinity_tokens=PREFIX_LEN)
    decode_router = FleetRouter(affinity_tokens=PREFIX_LEN)
    pre = [{"rid": f"p{i}", "job": None} for i in range(prefill_plan.replicas)]
    dec = [{"rid": f"d{i}", "active": []} for i in range(decode_plan.replicas)]
    queue: list[dict] = []          # awaiting a prefill server
    handoff: list[dict] = []        # KV on the wire / awaiting a decode slot
    done: list[dict] = []
    ttfts: list[float] = []
    idx, t, next_control = 0, 0.0, 0.0
    while (t < SIM_DURATION_S or queue or handoff
           or any(p["job"] for p in pre) or any(d["active"] for d in dec)):
        if t > SIM_DURATION_S * 6:
            break
        while idx < len(trace) and trace[idx]["t"] <= t:
            queue.append(trace[idx])
            idx += 1
        if t >= next_control:
            next_control = t + CONTROL_PERIOD_S
            prefill_router.update({
                p["rid"]: {
                    "tokens_per_sec": prefill_speedup * TOKENS_PER_SLOT_S,
                    "free_slots": 0 if p["job"] else 1, "slots": 1,
                } for p in pre
            })
            decode_router.update({
                d["rid"]: {
                    "tokens_per_sec": dec_rate * max(len(d["active"]), 0.2),
                    "free_slots": decode_plan.max_slots - len(d["active"]),
                    "slots": decode_plan.max_slots,
                } for d in dec
            })
        # Route waiting prompts onto idle prefill servers.
        while queue and any(p["job"] is None for p in pre):
            rid = prefill_router.route(queue[0]["prompt"])
            srv = next((p for p in pre if p["rid"] == rid), None)
            if srv is None or srv["job"] is not None:
                break
            req = queue.pop(0)
            srv["job"] = {
                "req": req,
                "left": req["prefill_units"] / prefill_speedup,
            }
        # Advance prefills; completion IS the first token (prefill logits).
        for p in pre:
            job = p["job"]
            if job is None:
                continue
            job["left"] -= DT_S
            if job["left"] <= 0:
                req = job["req"]
                req["first_token_at"] = t + DT_S
                ttfts.append((t + DT_S - req["t"]) * 1000.0)
                req["handoff_ready"] = t + DT_S + HANDOFF_S
                handoff.append(req)
                p["job"] = None
        # Deliver arrived handoffs into reserved decode slots.
        for req in list(handoff):
            if req["handoff_ready"] > t:
                continue
            rid = decode_router.route(req["prompt"])
            rep = next((d for d in dec if d["rid"] == rid), None)
            if rep is None or len(rep["active"]) >= decode_plan.max_slots:
                break
            handoff.remove(req)
            rep["active"].append({"req": req, "tokens_left": float(req["n_new"])})
        for d in dec:
            for sl in list(d["active"]):
                sl["tokens_left"] -= dec_rate * DT_S
                if sl["tokens_left"] <= 0:
                    sl["req"]["done_at"] = t + DT_S
                    done.append(sl["req"])
                    d["active"].remove(sl)
        t += DT_S
    return _ab_metrics(done, ttfts, t)


def _ab_metrics(done: list[dict], ttfts: list[float], t_end: float) -> dict:
    lat_ms = [(r["done_at"] - r["t"]) * 1000.0 for r in done
              if r["t"] >= WARMUP_S]
    steady_ttfts = [
        (r["first_token_at"] - r["t"]) * 1000.0 for r in done
        if r["t"] >= WARMUP_S and "first_token_at" in r
    ]
    total_tokens = float(sum(r["n_new"] for r in done))
    makespan = max((r["done_at"] for r in done), default=DT_S)
    return {
        "completed": len(done),
        "total_tokens": total_tokens,
        "tokens_per_sec": round(total_tokens / makespan, 2),
        "tokens_per_sec_per_chip": round(
            total_tokens / (makespan * TOTAL_CHIPS), 2),
        "ttft_p50_ms": round(_percentile(steady_ttfts, 0.50), 1),
        "ttft_p99_ms": round(_percentile(steady_ttfts, 0.99), 1),
        "p50_ms": round(_percentile(lat_ms, 0.50), 1),
        "p99_ms": round(_percentile(lat_ms, 0.99), 1),
        "makespan_s": round(makespan, 1),
    }


def run_disagg_ab(seed: int = 0) -> dict:
    """Symmetric vs disaggregated at TOTAL_CHIPS on the long-prefill
    trace; layouts chosen by the real planner and recorded in the output."""
    from tpu_engine.placement import plan_serving_pool

    pre_plans = plan_serving_pool(
        PLAN_MODEL, "prefill", PREFILL_CHIPS, hbm_free_gib=PLAN_HBM_GIB,
        max_len=PLAN_MAX_LEN, inflight_handoffs=PLAN_INFLIGHT)
    dec_plans = plan_serving_pool(
        PLAN_MODEL, "decode", DECODE_CHIPS, hbm_free_gib=PLAN_HBM_GIB,
        max_len=PLAN_MAX_LEN)
    sym_plans = plan_serving_pool(
        PLAN_MODEL, "decode", TOTAL_CHIPS, hbm_free_gib=PLAN_HBM_GIB,
        max_len=PLAN_MAX_LEN)
    pre_plan = next(p for p in pre_plans if p.feasible)
    dec_plan = next(p for p in dec_plans if p.feasible)
    sym_plan = next(p for p in sym_plans if p.feasible)
    # The planner's own roofline ratio: how much faster the chosen prefill
    # layout runs one prompt than a single tp=1 chip would.
    tp1 = next(p for p in pre_plans if p.tensor_parallel == 1)
    prefill_speedup = tp1.predicted_prefill_s / pre_plan.predicted_prefill_s

    trace = long_prefill_trace(seed)
    sym = _simulate_symmetric_long(trace)
    dis = _simulate_disagg(trace, pre_plan, dec_plan, prefill_speedup)
    gates = {
        "disagg_beats_symmetric_p99_ttft": dis["ttft_p99_ms"] < sym["ttft_p99_ms"],
        # "No worse" with a 1% deterministic-sim tolerance.
        "disagg_tokens_per_sec_no_worse": (
            dis["tokens_per_sec"] >= 0.99 * sym["tokens_per_sec"]),
    }
    return {
        "seed": seed,
        "total_chips": TOTAL_CHIPS,
        "n_requests": len(trace),
        "layouts": {
            "symmetric": sym_plan.label,
            "disagg_prefill": pre_plan.label,
            "disagg_decode": dec_plan.label,
            "prefill_speedup": round(prefill_speedup, 2),
        },
        "symmetric": sym,
        "disagg": dis,
        "ttft_p99_improvement": round(
            sym["ttft_p99_ms"] / max(dis["ttft_p99_ms"], 1e-9), 2),
        "gates": gates,
        "gates_pass": all(gates.values()),
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    out = {
        "autoscale_vs_static": run_trace(args.seed),
        "disagg_ab": run_disagg_ab(args.seed),
    }
    print(json.dumps(out, indent=2))
    if not out["disagg_ab"]["gates_pass"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
