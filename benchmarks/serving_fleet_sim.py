"""Serving-fleet sim: autoscaled replicas vs a static single replica.

Thin scenario definition over the digital twin (``tpu_engine/twin.py``):
the seeded traces come from the twin's synthetic traffic generators, the
fleet loop is the twin's open-loop tick driver, and the autoscaled lane
is :func:`tpu_engine.twin.replay_serving_fleet` — CLI flags, exit gates
and JSON metric lines are unchanged from the pre-twin benchmark.

Deterministic discrete-event comparison (virtual clock — no threads, no
JAX, identical numbers every run) of two fleet policies on the same
seeded bursty open-loop request trace:

- **static-1** — what ``tpu_engine/serving.py`` alone gives you: one
  decode replica; every burst queues behind its slot pool.
- **autoscaled** — this repo's :class:`ServingFleet` control plane: the
  REAL :class:`~tpu_engine.serving_fleet.FleetRouter` (throughput ×
  free-slot smooth WRR + shared-prefix affinity) and the REAL
  :class:`~tpu_engine.serving_fleet.ReplicaAutoscaler` (sliding-window
  queue depth + p99 SLO, scale-down hysteresis) drive replica count
  between min and max. New replicas pay a startup delay (scheduler
  admission + weight load + compile), exactly the lag hysteresis exists
  to hide.

Replicas are capacity models, not transformers: ``SLOTS`` concurrent
requests each decoding ``per-slot tokens/sec`` (one replica runs on a
degraded host at a fraction of that — the router's weights, not a
health-check binary, decide how much traffic it still deserves). A
request's prompt opens with one of a few shared system prefixes;
replica-side prefix caches skip the prefill for resident prefixes, which
is what router affinity is for.

Reports aggregate tokens/sec (and per chip-second, so extra replicas
don't get their throughput for free), p50/p99 latency vs the SLO, the
replica-count trace, router weights and affinity hit rate;
``bench.py`` reuses :func:`run_trace` for its serving-fleet line.

A second experiment (PR 12) A/Bs **symmetric vs disaggregated** serving
at EQUAL total chips on a long-prefill-heavy bursty trace. The symmetric
fleet models the real ``ContinuousBatcher`` interference: a chunked
prefill monopolizes the MXU, so co-resident decode slots crawl while any
prefill is in flight — slots stay occupied longer, admission stalls, and
p99 TTFT compounds. The disaggregated fleet (``tpu_engine/disagg.py``)
runs planner-placed pools — prefill layout ranked by the compute
roofline, decode by KV-pool capacity, both from the REAL
:func:`tpu_engine.placement.plan_serving_pool` — with a host-side KV
handoff between them; decode never stalls and TTFT is the prefill-pool
latency. ``main()`` exit-gates the A/B: disaggregated must beat
symmetric p99 TTFT with tokens/sec no worse, and the JSON records both
configurations' planner-chosen layouts.

Run: ``python -m benchmarks.serving_fleet_sim [--seed N]``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu_engine.serving_fleet import (  # noqa: E402
    AutoscalerConfig,
    FleetRouter,
)
from tpu_engine.twin import (  # noqa: E402
    ServingTwinParams,
    SlotReplica,
    bursty_arrivals,
    replay_serving_fleet,
    run_open_loop,
    serving_metrics,
)

# The shipped scenario parameters; the twin's dataclass carries them, the
# module-level constants remain the stable public surface tests import.
SERVING = ServingTwinParams()

SIM_DURATION_S = SERVING.duration_s
DT_S = SERVING.dt_s               # sim tick
CONTROL_PERIOD_S = SERVING.control_period_s  # autoscaler / router cadence
SLOTS = SERVING.slots             # decode slots per replica
TOKENS_PER_SLOT_S = SERVING.tokens_per_slot_s  # healthy per-slot decode rate
DEGRADED_FRACTION = SERVING.degraded_fraction  # replica 0's slow-host rate
PREFILL_S = SERVING.prefill_s     # full prefill latency (cold prefix)
PREFILL_HIT_S = SERVING.prefill_hit_s  # prefix-cache hit remainder
STARTUP_DELAY_S = SERVING.startup_delay_s  # admission + load + compile
CHIPS_PER_REPLICA = SERVING.chips_per_replica
BASE_RATE_RPS = 1.0          # open-loop arrivals outside bursts
BURST_RATE_RPS = 14.0        # arrivals inside a burst window
BURST_EVERY_S = 120.0
BURST_LEN_S = 35.0
N_PREFIXES = 4               # shared system prompts
PREFIX_LEN = SERVING.prefix_len
MEAN_NEW_TOKENS = 96
P99_SLO_MS = SERVING.p99_slo_ms
# Latency percentiles are steady-state: the first burst cycle is warmup
# (it lands on the min fleet by construction — what it measures is the
# startup delay, not the policy). Throughput counts everything.
WARMUP_S = SERVING.warmup_s

AUTOSCALER = AutoscalerConfig(
    min_replicas=1,
    max_replicas=8,
    target_queue_per_replica=4.0,
    low_water_queue_per_replica=0.5,
    p99_slo_ms=P99_SLO_MS,
    window_s=20.0,
    scale_up_cooldown_s=3.0,
    scale_down_cooldown_s=90.0,
)

# Back-compat alias: the capacity replica model now lives in the twin.
SimReplica = SlotReplica


def request_trace(seed: int) -> list[dict]:
    """Seeded bursty open-loop arrivals: [{t, prefix_id, prompt, n_new}]."""
    return bursty_arrivals(
        seed,
        duration_s=SIM_DURATION_S,
        base_rps=BASE_RATE_RPS,
        burst_rps=BURST_RATE_RPS,
        burst_every_s=BURST_EVERY_S,
        burst_len_s=BURST_LEN_S,
        n_prefixes=N_PREFIXES,
        prefix_len=PREFIX_LEN,
        mean_new_tokens=MEAN_NEW_TOKENS,
    )


def _simulate(trace: list[dict], autoscale: bool) -> dict:
    return replay_serving_fleet(trace, autoscale, AUTOSCALER, SERVING)


def run_trace(seed: int = 0) -> dict:
    trace = request_trace(seed)
    auto = _simulate(trace, autoscale=True)
    static = _simulate(trace, autoscale=False)
    return {
        "seed": seed,
        "n_requests": len(trace),
        "autoscaled": auto,
        "static_1_replica": static,
        "throughput_improvement": round(
            auto["tokens_per_sec"] / max(static["tokens_per_sec"], 1e-9), 2
        ),
        "p99_improvement": round(
            static["p99_ms"] / max(auto["p99_ms"], 1e-9), 2
        ),
        "p99_slo_ms": P99_SLO_MS,
    }


# ---------------------------------------------------------------------------
# Symmetric vs disaggregated A/B (PR 12) — equal chips, long-prefill trace
# ---------------------------------------------------------------------------

TOTAL_CHIPS = 8              # equal-chips budget for BOTH configurations
PREFILL_CHIPS = 6            # disagg split: prefill-heavy trace → prefill-heavy pool
DECODE_CHIPS = TOTAL_CHIPS - PREFILL_CHIPS
LONG_PREFILL_MEAN_S = 1.5    # one prompt's prefill seconds on ONE chip (tp=1)
LONG_PREFILL_MIN_S = 0.3
LONG_MEAN_NEW = 96
LONG_BASE_RPS = 0.4
LONG_BURST_RPS = 3.0
HANDOFF_S = 0.05             # host-side KV wire latency (not on the TTFT path)
# Chunked-prefill interference in a SYMMETRIC replica: while a prefill
# chunk owns the MXU, co-resident decode steps run at this fraction of
# their clean cadence (a decode step is ~an order of magnitude shorter
# than a prefill chunk), and the prefill itself loses the decode share.
INTERFERENCE_DECODE = 0.15
INTERFERENCE_PREFILL = 0.85
PLAN_MODEL = "llama-7b"
PLAN_MAX_LEN = 2048
PLAN_HBM_GIB = 24.0
PLAN_INFLIGHT = 4            # prefill pool's in-flight handoff window


def long_prefill_trace(seed: int) -> list[dict]:
    """Seeded bursty arrivals with heavy, variable prefill cost:
    [{t, prompt, prefill_units, n_new}] — ``prefill_units`` is seconds of
    prefill work at tp=1."""
    return bursty_arrivals(
        seed,
        duration_s=SIM_DURATION_S,
        base_rps=LONG_BASE_RPS,
        burst_rps=LONG_BURST_RPS,
        burst_every_s=BURST_EVERY_S,
        burst_len_s=BURST_LEN_S,
        n_prefixes=N_PREFIXES,
        prefix_len=PREFIX_LEN,
        mean_new_tokens=LONG_MEAN_NEW,
        prefill_mean_s=LONG_PREFILL_MEAN_S,
        prefill_min_s=LONG_PREFILL_MIN_S,
        seed_offset=7919,
    )


class SymReplica:
    """One chip, both phases. Prefills serialize (one chunked prefill at a
    time owns the MXU); while one is in flight every decoding slot crawls
    at the interference rate — the slot-starvation feedback that kills
    symmetric p99 TTFT under prefill bursts."""

    def __init__(self, rid: str):
        self.rid = rid
        self.active: list[dict] = []

    def free_slots(self) -> int:
        return SLOTS - len(self.active)

    def admit(self, req: dict, now: float) -> None:
        self.active.append({
            "req": req, "prefill_left": req["prefill_units"],
            "tokens_left": float(req["n_new"]),
        })

    def step(self, now: float, dt: float, done: list[dict],
             ttfts: list[float]) -> None:
        pre = next((s for s in self.active if s["prefill_left"] > 0), None)
        decode_rate = TOKENS_PER_SLOT_S
        if pre is not None:
            pre["prefill_left"] -= dt * INTERFERENCE_PREFILL
            if pre["prefill_left"] <= 0:
                pre["req"]["first_token_at"] = now + dt
                ttfts.append((now + dt - pre["req"]["t"]) * 1000.0)
            decode_rate *= INTERFERENCE_DECODE
        for sl in list(self.active):
            if sl["prefill_left"] > 0 or sl is pre:
                continue
            sl["tokens_left"] -= decode_rate * dt
            if sl["tokens_left"] <= 0:
                sl["req"]["done_at"] = now + dt
                done.append(sl["req"])
                self.active.remove(sl)

    def router_stats(self) -> dict:
        busy = sum(1 for s in self.active if s["prefill_left"] <= 0)
        return {
            "tokens_per_sec": TOKENS_PER_SLOT_S * max(busy, 0.2),
            "free_slots": self.free_slots(),
            "slots": SLOTS,
        }


def _simulate_symmetric_long(trace: list[dict]) -> dict:
    router = FleetRouter(affinity_tokens=PREFIX_LEN)
    replicas = [SymReplica(f"s{i}") for i in range(TOTAL_CHIPS)]
    by_id = {r.rid: r for r in replicas}
    queue: list[dict] = []
    done: list[dict] = []
    ttfts: list[float] = []

    def control(t: float) -> None:
        router.update({r.rid: r.router_stats() for r in replicas})

    def tick(t: float) -> None:
        while queue and any(r.free_slots() > 0 for r in replicas):
            rid = router.route(queue[0]["prompt"])
            rep = by_id.get(rid) if rid else None
            if rep is None or rep.free_slots() <= 0:
                break  # router picked a full replica; weights refresh next tick
            rep.admit(queue.pop(0), t)
        for r in replicas:
            r.step(t, DT_S, done, ttfts)

    run_open_loop(
        trace, dt=DT_S, duration_s=SIM_DURATION_S,
        pending=lambda: queue or any(r.active for r in replicas),
        arrive=queue.append, tick=tick, control=control,
        control_period_s=CONTROL_PERIOD_S, safety_factor=6.0,
    )
    return _ab_metrics(done, ttfts)


def _simulate_disagg(trace: list[dict], prefill_plan, decode_plan,
                     prefill_speedup: float) -> dict:
    """Planner-placed pools: ``prefill_plan.replicas`` serial prefill
    servers (each ``prefill_speedup`` × one chip, the roofline ratio the
    planner predicted for its tensor-parallel choice) feeding
    ``decode_plan.replicas`` decode-only replicas through a ``HANDOFF_S``
    KV wire. Decode never shares the MXU with a prefill."""
    # Per-slot decode rate: the pool's chips stream the same aggregate
    # HBM bandwidth as the symmetric fleet's per-chip 8×30 tok/s; more
    # slots trade per-slot speed for concurrency (the KV-capacity axis).
    dec_rate = (TOKENS_PER_SLOT_S * SLOTS * decode_plan.tensor_parallel
                / decode_plan.max_slots)
    prefill_router = FleetRouter(affinity_tokens=PREFIX_LEN)
    decode_router = FleetRouter(affinity_tokens=PREFIX_LEN)
    pre = [{"rid": f"p{i}", "job": None} for i in range(prefill_plan.replicas)]
    dec = [{"rid": f"d{i}", "active": []} for i in range(decode_plan.replicas)]
    queue: list[dict] = []          # awaiting a prefill server
    handoff: list[dict] = []        # KV on the wire / awaiting a decode slot
    done: list[dict] = []
    ttfts: list[float] = []

    def control(t: float) -> None:
        prefill_router.update({
            p["rid"]: {
                "tokens_per_sec": prefill_speedup * TOKENS_PER_SLOT_S,
                "free_slots": 0 if p["job"] else 1, "slots": 1,
            } for p in pre
        })
        decode_router.update({
            d["rid"]: {
                "tokens_per_sec": dec_rate * max(len(d["active"]), 0.2),
                "free_slots": decode_plan.max_slots - len(d["active"]),
                "slots": decode_plan.max_slots,
            } for d in dec
        })

    def tick(t: float) -> None:
        # Route waiting prompts onto idle prefill servers.
        while queue and any(p["job"] is None for p in pre):
            rid = prefill_router.route(queue[0]["prompt"])
            srv = next((p for p in pre if p["rid"] == rid), None)
            if srv is None or srv["job"] is not None:
                break
            req = queue.pop(0)
            srv["job"] = {
                "req": req,
                "left": req["prefill_units"] / prefill_speedup,
            }
        # Advance prefills; completion IS the first token (prefill logits).
        for p in pre:
            job = p["job"]
            if job is None:
                continue
            job["left"] -= DT_S
            if job["left"] <= 0:
                req = job["req"]
                req["first_token_at"] = t + DT_S
                ttfts.append((t + DT_S - req["t"]) * 1000.0)
                req["handoff_ready"] = t + DT_S + HANDOFF_S
                handoff.append(req)
                p["job"] = None
        # Deliver arrived handoffs into reserved decode slots.
        for req in list(handoff):
            if req["handoff_ready"] > t:
                continue
            rid = decode_router.route(req["prompt"])
            rep = next((d for d in dec if d["rid"] == rid), None)
            if rep is None or len(rep["active"]) >= decode_plan.max_slots:
                break
            handoff.remove(req)
            rep["active"].append({"req": req, "tokens_left": float(req["n_new"])})
        for d in dec:
            for sl in list(d["active"]):
                sl["tokens_left"] -= dec_rate * DT_S
                if sl["tokens_left"] <= 0:
                    sl["req"]["done_at"] = t + DT_S
                    done.append(sl["req"])
                    d["active"].remove(sl)

    run_open_loop(
        trace, dt=DT_S, duration_s=SIM_DURATION_S,
        pending=lambda: (queue or handoff or any(p["job"] for p in pre)
                         or any(d["active"] for d in dec)),
        arrive=queue.append, tick=tick, control=control,
        control_period_s=CONTROL_PERIOD_S, safety_factor=6.0,
    )
    return _ab_metrics(done, ttfts)


def _ab_metrics(done: list[dict], ttfts: list[float],
                t_end: float = 0.0) -> dict:
    return serving_metrics(
        done, ttfts, warmup_s=WARMUP_S, total_chips=TOTAL_CHIPS, dt_s=DT_S
    )


def run_disagg_ab(seed: int = 0) -> dict:
    """Symmetric vs disaggregated at TOTAL_CHIPS on the long-prefill
    trace; layouts chosen by the real planner and recorded in the output."""
    from tpu_engine.placement import plan_serving_pool

    pre_plans = plan_serving_pool(
        PLAN_MODEL, "prefill", PREFILL_CHIPS, hbm_free_gib=PLAN_HBM_GIB,
        max_len=PLAN_MAX_LEN, inflight_handoffs=PLAN_INFLIGHT)
    dec_plans = plan_serving_pool(
        PLAN_MODEL, "decode", DECODE_CHIPS, hbm_free_gib=PLAN_HBM_GIB,
        max_len=PLAN_MAX_LEN)
    sym_plans = plan_serving_pool(
        PLAN_MODEL, "decode", TOTAL_CHIPS, hbm_free_gib=PLAN_HBM_GIB,
        max_len=PLAN_MAX_LEN)
    pre_plan = next(p for p in pre_plans if p.feasible)
    dec_plan = next(p for p in dec_plans if p.feasible)
    sym_plan = next(p for p in sym_plans if p.feasible)
    # The planner's own roofline ratio: how much faster the chosen prefill
    # layout runs one prompt than a single tp=1 chip would.
    tp1 = next(p for p in pre_plans if p.tensor_parallel == 1)
    prefill_speedup = tp1.predicted_prefill_s / pre_plan.predicted_prefill_s

    trace = long_prefill_trace(seed)
    sym = _simulate_symmetric_long(trace)
    dis = _simulate_disagg(trace, pre_plan, dec_plan, prefill_speedup)
    gates = {
        "disagg_beats_symmetric_p99_ttft": dis["ttft_p99_ms"] < sym["ttft_p99_ms"],
        # "No worse" with a 1% deterministic-sim tolerance.
        "disagg_tokens_per_sec_no_worse": (
            dis["tokens_per_sec"] >= 0.99 * sym["tokens_per_sec"]),
    }
    return {
        "seed": seed,
        "total_chips": TOTAL_CHIPS,
        "n_requests": len(trace),
        "layouts": {
            "symmetric": sym_plan.label,
            "disagg_prefill": pre_plan.label,
            "disagg_decode": dec_plan.label,
            "prefill_speedup": round(prefill_speedup, 2),
        },
        "symmetric": sym,
        "disagg": dis,
        "ttft_p99_improvement": round(
            sym["ttft_p99_ms"] / max(dis["ttft_p99_ms"], 1e-9), 2),
        "gates": gates,
        "gates_pass": all(gates.values()),
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    out = {
        "autoscale_vs_static": run_trace(args.seed),
        "disagg_ab": run_disagg_ab(args.seed),
    }
    print(json.dumps(out, indent=2))
    if not out["disagg_ab"]["gates_pass"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
