"""Prefix-cache TTFT benefit, measured on the real chip.

A 1024-token system prompt is prefilled once; later requests sharing it
paste the cached KV lanes and ingest only their suffix. TTFT for the
warm request should drop by roughly the shared chunks' dispatch cost
(through the tunneled runtime each chunk is ~a dispatch round-trip; on
local silicon it is the chunk's forward time — the mechanism saves the
larger of the two in each regime).

Run: ``python benchmarks/prefix_cache_bench.py``.
"""

from __future__ import annotations

import json
import time

import jax
import numpy as np


def main() -> None:
    from tpu_engine.models import transformer as tfm
    from tpu_engine.serving import ContinuousBatcher

    cfg = tfm.MODEL_CONFIGS["gpt-125m"]
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)
    system = rng.integers(1, cfg.vocab_size, 1024).tolist()
    suffixes = [rng.integers(1, cfg.vocab_size, 24).tolist() for _ in range(3)]

    srv = ContinuousBatcher(params, cfg, max_slots=4, max_len=2048,
                            chunk_steps=8, prefill_chunk=256,
                            prefix_cache_tokens=4096)

    def run_one(prompt):
        rid = srv.submit(prompt, max_new_tokens=8)
        t_end = time.time() + 600
        while time.time() < t_end:
            srv.step()
            if srv.result(rid)["status"] == "done":
                return srv.result(rid)["ttft_ms"]
        raise TimeoutError

    # Warmup compiles (prefill chunks at the measured cache shape, paste,
    # decode) on an UNSHARED same-length prompt, so the cold row measures
    # dispatches, not XLA compiles.
    run_one(rng.integers(1, cfg.vocab_size, 1048).tolist())

    cold = run_one(system + suffixes[0])     # prefills all 1048 tokens
    warm = [run_one(system + s) for s in suffixes[1:]]
    st = srv.stats()["prefix_cache"]
    print(json.dumps({
        "metric": "prefix_cache_ttft",
        "device": str(jax.devices()[0].device_kind),
        "system_tokens": 1024, "prefill_chunk": 256,
        "cold_ttft_ms": cold,
        # warm[0] pays the one-time paste-kernel compile; warm[1:] is the
        # steady state the cache exists for.
        "first_warm_ttft_ms": round(warm[0], 1),
        "steady_warm_ttft_ms": round(warm[-1], 1),
        "steady_speedup": round(cold / warm[-1], 2),
        "cache": st,
    }))


if __name__ == "__main__":
    main()
