"""Prefix-cache TTFT benefit, measured on the real chip.

A 1024-token system prompt is prefilled once; later requests sharing it
paste the cached KV lanes and ingest only their suffix. TTFT for the
warm request should drop by roughly the shared chunks' dispatch cost
(through the tunneled runtime each chunk is ~a dispatch round-trip; on
local silicon it is the chunk's forward time — the mechanism saves the
larger of the two in each regime).

Run: ``python benchmarks/prefix_cache_bench.py``.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    from tpu_engine.models import transformer as tfm
    from tpu_engine.serving import ContinuousBatcher

    cfg = tfm.MODEL_CONFIGS["gpt-125m"]
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)
    system = rng.integers(1, cfg.vocab_size, 1024).tolist()
    suffixes = [rng.integers(1, cfg.vocab_size, 24).tolist() for _ in range(3)]

    srv = ContinuousBatcher(params, cfg, max_slots=4, max_len=2048,
                            chunk_steps=8, prefill_chunk=256,
                            prefix_cache_tokens=4096)

    def run_one(prompt):
        rid = srv.submit(prompt, max_new_tokens=8)
        t_end = time.time() + 600
        while time.time() < t_end:
            srv.step()
            if srv.result(rid)["status"] == "done":
                return srv.result(rid)["ttft_ms"]
        raise TimeoutError

    # Warmup compiles (prefill chunks at the measured cache shape, paste,
    # decode) on an UNSHARED same-length prompt, so the cold row measures
    # dispatches, not XLA compiles.
    run_one(rng.integers(1, cfg.vocab_size, 1048).tolist())

    # Steady-state timings are the min of 3 runs after a discarded
    # compile-paying first run — per-dispatch tunnel latency jitters by
    # hundreds of ms, which would otherwise drown the signal. Cold runs
    # use DISTINCT unshared prompts (an identical re-run would hit).
    cold = min(
        run_one(rng.integers(1, cfg.vocab_size, 1048).tolist())
        for _ in range(3)
    )
    run_one(system + suffixes[0])                # creates the system entry
    first_warm = run_one(system + suffixes[1])   # pays the paste compile
    warm = min(run_one(system + suffixes[2]) for _ in range(3))
    # Token-granular reuse (round 5): a prompt diverging MID-chunk from
    # the stored prefix — shares 1000 of its 1024 tokens — reuses
    # floor(1000/64)=960 tokens of KV; the old boundary-keyed lookup
    # reused ZERO here. Every timed run uses a FRESH divergence (distinct
    # token at position 1000), because a repeated identical prompt would
    # hit its OWN full boundary entry from the previous run and measure
    # resubmit reuse instead of the genuine 960-token partial hit.
    def misaligned(i: int) -> list[int]:
        return (system[:1000] + [(system[1000] + 1 + i) % cfg.vocab_size]
                + rng.integers(1, cfg.vocab_size, 24).tolist())

    run_one(misaligned(0))                       # pays this shape's compiles
    partial = min(run_one(misaligned(1 + k)) for k in range(3))
    # And the identical-resubmit case (chunk-aligned prompt), the classic
    # shared-system-prompt dedupe the old lookup could never hit.
    aligned = system[:1024]
    run_one(list(aligned))                       # pays this bucket's compiles
    resub = min(run_one(list(aligned)) for _ in range(3))
    st = srv.stats()["prefix_cache"]
    print(json.dumps({
        "metric": "prefix_cache_ttft",
        "device": str(jax.devices()[0].device_kind),
        "system_tokens": 1024, "prefill_chunk": 256,
        "cold_ttft_ms": round(cold, 1),
        "first_warm_ttft_ms": round(first_warm, 1),
        "steady_warm_ttft_ms": round(warm, 1),
        "steady_speedup": round(cold / warm, 2),
        "partial_hit_ttft_ms": round(partial, 1),
        "partial_hit_speedup": round(cold / partial, 2),
        "aligned_resubmit_ttft_ms": round(resub, 1),
        "aligned_resubmit_speedup": round(cold / resub, 2),
        "cache": st,
    }))


if __name__ == "__main__":
    main()
