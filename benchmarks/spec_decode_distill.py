"""Speculative decoding with a REALISTIC distilled draft, end to end.

Round-4 verdict weakness 3: speculative decoding was measured only at
ceiling (draft == target, α = 0.833) and floor (random draft, α = 0.200)
— no realistic draft existed in-image. This closes the gap with zero
egress, the way a production draft is actually made:

1. **Train a target** (4-layer, d_model 256) on a low-entropy synthetic
   bigram language (each token has a dominant successor) — a stand-in
   for natural text's predictability, learnable in minutes on one chip.
2. **Distill a draft** (1 layer, d_model 128 — ~14× fewer active layer
   FLOPs) by training it on the TARGET's own greedy streams
   (sequence-level knowledge distillation: the draft learns to imitate
   the argmax behaviour that speculative verify actually tests).
3. **Measure**: serve the target with the distilled draft
   (`ContinuousBatcher(draft_params=...)`) on held-out prompts and read
   the real `spec_accept_rate` (α = mean accepted / (gamma+1)) and
   tok/s; serve plain chunked decode (chunk = gamma+1 — the same tokens
   per dispatch) as the honest baseline.

The whole recipe lives in :func:`run`, parameterized so the tier-1 suite
can drive a tiny-dims / few-steps pass on CPU (``tests/test_spec_pool.py``
— the only end-to-end draft-production path must not silently rot);
``main()`` keeps the measured full-size run TPU-gated.

Run: ``python benchmarks/spec_decode_distill.py``.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import json
import time

import jax
import numpy as np

VOCAB = 512
SEQ = 256
GAMMA = 4
TRAIN_STEPS = 300
DISTILL_STEPS = 300


def _bigram_sampler(seed: int, vocab: int = VOCAB):
    """A peaked bigram language: every token has one dominant successor
    (p = 0.85), mass elsewhere uniform. Entropy is low but not zero —
    the target will be confidently right most of the time, like natural
    text under a good LM."""
    rng = np.random.default_rng(seed)
    succ = rng.permutation(vocab)

    def sample(n_rows: int, seq: int, seed2: int) -> np.ndarray:
        r = np.random.default_rng(seed2)
        out = np.empty((n_rows, seq), np.int32)
        tok = r.integers(0, vocab, n_rows)
        for j in range(seq):
            out[:, j] = tok
            follow = r.random(n_rows) < 0.85
            tok = np.where(follow, succ[tok], r.integers(0, vocab, n_rows))
        return out

    return sample


def _train(model_kw: dict, data: "callable", steps: int, seed: int,
           seq: int = SEQ, micro_batch: int = 32):
    from tpu_engine.mesh_runtime import MeshConfig, MeshRuntime
    from tpu_engine.models import transformer as tfm
    from tpu_engine.sharding import ShardingStage, TPUTrainConfig
    from tpu_engine.train import build_train_program

    cfg = TPUTrainConfig(
        model_name="gpt-tiny", sharding_stage=ShardingStage.DISABLED,
        # data=-1 absorbs however many devices the host exposes (1 on a
        # plain CPU run, 8 under the test suite's forced host devices) —
        # micro_batch just has to stay divisible by the device count.
        mesh=MeshConfig(data=-1), micro_batch_size=micro_batch,
        gradient_accumulation_steps=1, seq_len=seq, precision="bf16",
        learning_rate=3e-4, warmup_steps=min(20, max(steps // 4, 1)),
        total_steps=steps,
        activation_checkpointing=False, seed=seed,
    )
    mc = tfm.ModelConfig(**model_kw)
    prog = build_train_program(cfg, model_cfg=mc,
                               runtime=MeshRuntime(cfg.mesh))
    state = prog.init(jax.random.PRNGKey(seed))
    loss = None
    for i in range(steps):
        batch = jax.numpy.asarray(
            data(cfg.micro_batch_size, seq, 1000 * seed + i)[None]
        )
        state, metrics = prog.step(state, batch)
        loss = metrics["loss"]
    return jax.device_get(state["params"]), mc, float(loss)


def _serve_collect(params, mc, prompts, max_new, max_len: int = SEQ, **kw):
    """Run every prompt through a batcher; returns (streams, tok/s, stats)."""
    from tpu_engine.serving import ContinuousBatcher

    srv = ContinuousBatcher(params, mc, max_slots=8, max_len=max_len,
                            **kw)
    rids = [srv.submit(list(p), max_new_tokens=max_new) for p in prompts]
    t0 = time.perf_counter()
    deadline = t0 + 900
    while time.perf_counter() < deadline:
        srv.step()
        if all(srv.result(r)["status"] == "done" for r in rids):
            break
    dt = time.perf_counter() - t0
    streams = [srv.result(r)["tokens"] for r in rids]
    toks = sum(len(s) for s in streams)
    return streams, toks / dt, srv.stats()


def run(
    *,
    vocab: int = VOCAB,
    seq: int = SEQ,
    gamma: int = GAMMA,
    train_steps: int = TRAIN_STEPS,
    distill_steps: int = DISTILL_STEPS,
    target_kw: dict = None,
    draft_kw: dict = None,
    micro_batch: int = 32,
    prompt_len: int = 16,
    n_kd_prompts: int = 64,
    n_eval_prompts: int = 16,
    max_new: int = 128,
) -> dict:
    """The full distill recipe (train target → KD corpus → distill draft
    → spec-vs-chunked measurement) at caller-chosen scale. Defaults are
    the measured benchmark; the tier-1 smoke passes tiny dims/steps and
    runs the identical code path on CPU."""
    sample = _bigram_sampler(7, vocab)

    target_kw = target_kw or dict(
        name="spec-target", vocab_size=vocab, d_model=256,
        n_layers=4, n_heads=8, n_kv_heads=8, d_ff=1024,
        max_seq_len=seq)
    draft_kw = draft_kw or dict(
        name="spec-draft", vocab_size=vocab, d_model=128,
        n_layers=1, n_heads=4, n_kv_heads=4, d_ff=512,
        max_seq_len=seq)

    t0 = time.time()
    tgt_params, tgt_cfg, tgt_loss = _train(
        target_kw, sample, train_steps, 0, seq=seq, micro_batch=micro_batch)
    t_target = time.time() - t0

    # -- sequence-level KD corpus: the target's own greedy streams -------
    kd_prompts = [sample(1, prompt_len, 10_000 + i)[0].tolist()
                  for i in range(n_kd_prompts)]
    kd_streams, _, _ = _serve_collect(
        tgt_params, tgt_cfg, kd_prompts, max_new=seq - prompt_len,
        max_len=seq, chunk_steps=16,
    )
    kd_rows = np.stack([
        np.concatenate([np.asarray(p, np.int32), np.asarray(s, np.int32)])
        for p, s in zip(kd_prompts, kd_streams)
    ])  # [n_kd_prompts, seq]

    def kd_data(n_rows: int, seq2: int, seed2: int) -> np.ndarray:
        r = np.random.default_rng(seed2)
        return kd_rows[r.integers(0, kd_rows.shape[0], n_rows), :seq2]

    t0 = time.time()
    dr_params, dr_cfg, dr_loss = _train(
        draft_kw, kd_data, distill_steps, 1, seq=seq,
        micro_batch=micro_batch)
    t_draft = time.time() - t0

    # -- measurement: same held-out prompts, spec vs chunked -------------
    prompts = [sample(1, prompt_len, 99_000 + i)[0].tolist()
               for i in range(n_eval_prompts)]
    spec_streams, spec_tps, spec_stats = _serve_collect(
        tgt_params, tgt_cfg, prompts, max_new, max_len=seq,
        draft_params=dr_params, draft_cfg=dr_cfg, spec_gamma=gamma,
    )
    plain_streams, plain_tps, _ = _serve_collect(
        tgt_params, tgt_cfg, prompts, max_new, max_len=seq,
        chunk_steps=gamma + 1,
    )
    agree = np.mean([
        np.mean(np.asarray(a[: len(b)]) == np.asarray(b[: len(a)]))
        for a, b in zip(spec_streams, plain_streams)
    ])
    return {
        "metric": "spec_decode_distilled_draft",
        "target": {"layers": target_kw["n_layers"],
                   "d_model": target_kw["d_model"],
                   "final_loss": round(tgt_loss, 3),
                   "train_s": round(t_target, 1)},
        "draft": {"layers": draft_kw["n_layers"],
                  "d_model": draft_kw["d_model"],
                  "final_loss": round(dr_loss, 3),
                  "distill_s": round(t_draft, 1)},
        "gamma": gamma,
        "alpha_accept_rate": spec_stats.get("spec_accept_rate"),
        "spec_rounds": spec_stats.get("spec_rounds"),
        "spec_tokens_accepted": spec_stats.get("spec_tokens_accepted"),
        "spec_tokens_proposed": spec_stats.get("spec_tokens_proposed"),
        "spec_tokens_per_sec": round(spec_tps, 1),
        "chunked_baseline_tokens_per_sec": round(plain_tps, 1),
        "spec_vs_chunked": round(spec_tps / plain_tps, 2),
        "stream_agreement": round(float(agree), 3),
    }


def main() -> None:
    if jax.devices()[0].platform != "tpu":
        print(json.dumps({"skipped": "needs a local TPU"}))
        return
    print(json.dumps(run()))


if __name__ == "__main__":
    main()
