"""Reshard plane A/B: topology-changing resume vs topology-locked restart.

Runs :func:`tpu_engine.twin.reshard_ab` — the same seeded chip-fault
trace through same-topology warm self-heal (PR 10's MTTR reference,
re-derived in-process), the reshard-resume policy that lands every
recovery on a *different* mesh factorization (data4×fsdp2 ↔ data2×fsdp4,
shrunk 3×2), and the topology-locked die-and-restart baseline that loses
steps waiting for the exact mesh — plus the REAL-executor Orbax restore
round trip (byte-parity leaves across factorizations on the 8-device
host grid) and the REAL gpt-tiny held-KV / prefix-payload pool migration
(``JAX_PLATFORMS=cpu python -m benchmarks.reshard_sim``).

Exit gates (process exits 1 when any fails):

- ``zero_lost_steps`` — reshard resume replays no step twice;
- ``mttr_within_budget`` — topology-changing MTTR <= 1.5x the warm
  same-topology mean on the same trace;
- ``beats_topology_locked`` — lower wall clock than the policy that
  waits for the saved topology (which also loses steps);
- ``roundtrip_byte_parity`` — every restored leaf's bytes match the
  source on both alternate factorizations;
- ``held_requests_complete`` — 100% of held ``hold_kv`` requests finish
  decode on the destination pool, none left behind;
- ``int8_parity_within_bound`` — stitched streams within the documented
  one-token-per-request int8 bound vs the unified baseline;
- ``prefix_migrates_both_paths`` — the resident prefix crosses both the
  replica→replica and host-tier rehydration legs;
- ``deterministic_repeat`` — a second seeded replay is byte-identical.
"""

from __future__ import annotations

import json

from tpu_engine.twin import reshard_ab, reshard_bench_line


def main() -> None:
    res = reshard_ab(seed=0)
    print(json.dumps({
        "same_topology": res["same_topology"],
        "reshard": res["reshard"],
        "topology_locked": res["topology_locked"],
        "roundtrip": res["roundtrip"],
        "migration": res["migration"],
        "mttr_ratio": res["mttr_ratio"],
        "mttr_budget_s": res["mttr_budget_s"],
        "gates": res["gates"],
        "ok": res["ok"],
    }, indent=2))
    line = reshard_bench_line(seed=0, ab=res)
    print(json.dumps(line))
    if not (res["ok"] and line["ok"]):
        raise SystemExit(1)


if __name__ == "__main__":
    main()
