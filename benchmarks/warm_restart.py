"""Cold vs warm resume-to-first-step on the real chip.

Measures the MTTR compile component the persistent XLA compilation cache
removes (SURVEY.md §7 hard part c): two fresh processes build the same
train program and run one step — the first with an empty cache (cold), the
second reusing it (warm). Prints one JSON line per phase and a summary.

Usage (on a TPU host):  python benchmarks/warm_restart.py [--model llama-1b]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import json
import shutil
import subprocess
import tempfile

_CHILD = r"""
import json, os, time
t0 = time.perf_counter()
import jax
from tpu_engine.compile_cache import enable_compilation_cache
from tpu_engine.mesh_runtime import MeshConfig
from tpu_engine.sharding import ShardingStage, TPUTrainConfig
from tpu_engine.train import build_train_program

enable_compilation_cache(os.environ["WARM_RESTART_CACHE"])
cfg = TPUTrainConfig(
    model_name=os.environ.get("WARM_RESTART_MODEL", "llama-1b"),
    sharding_stage=ShardingStage.FULL_PARTITIONING,
    mesh=MeshConfig(data=1, fsdp=jax.device_count()),
    micro_batch_size=int(os.environ.get("WARM_RESTART_BATCH", "4")),
    seq_len=int(os.environ.get("WARM_RESTART_SEQ", "2048")),
)
t_import = time.perf_counter()
prog = build_train_program(cfg)
state = prog.init(jax.random.PRNGKey(0))
jax.block_until_ready(state)
t_init = time.perf_counter()
batch = prog.synthetic_batch(0)
state, metrics = prog.step(state, batch)
jax.block_until_ready(metrics)
t_first_step = time.perf_counter()
print(json.dumps({
    "import_s": round(t_import - t0, 2),
    "init_s": round(t_init - t_import, 2),
    "first_step_s": round(t_first_step - t_init, 2),
    "resume_to_first_step_s": round(t_first_step - t0, 2),
}))
"""


def run_child(cache_dir: str, model: str, batch: int, seq: int) -> dict:
    env = dict(os.environ)
    env.update(
        WARM_RESTART_CACHE=cache_dir,
        WARM_RESTART_MODEL=model,
        WARM_RESTART_BATCH=str(batch),
        WARM_RESTART_SEQ=str(seq),
    )
    out = subprocess.run(
        [sys.executable, "-c", _CHILD], env=env, capture_output=True, text=True,
        timeout=1800,
    )
    if out.returncode != 0:
        raise RuntimeError(f"child failed:\n{out.stderr[-2000:]}")
    return json.loads(out.stdout.strip().splitlines()[-1])


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="llama-1b")
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--keep-cache", action="store_true")
    args = ap.parse_args()

    cache = tempfile.mkdtemp(prefix="warm-restart-cache-")
    try:
        cold = run_child(cache, args.model, args.batch, args.seq)
        print(json.dumps({"phase": "cold", **cold}))
        warm = run_child(cache, args.model, args.batch, args.seq)
        print(json.dumps({"phase": "warm", **warm}))
        speedup = (
            cold["resume_to_first_step_s"] / warm["resume_to_first_step_s"]
            if warm["resume_to_first_step_s"] > 0
            else float("inf")
        )
        print(json.dumps({
            "metric": "warm_restart_resume_to_first_step",
            "model": args.model,
            "cold_s": cold["resume_to_first_step_s"],
            "warm_s": warm["resume_to_first_step_s"],
            "speedup": round(speedup, 2),
        }))
    finally:
        if not args.keep_cache:
            shutil.rmtree(cache, ignore_errors=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
