"""Control-plane scale lane: 100k jobs / 1M requests, flat overhead.

Runs :func:`tpu_engine.twin.ctl_scale_profile` — the twin-driven lane
that pushes ~100k submissions through the real
:class:`~tpu_engine.scheduler.FleetScheduler` and ~1M serving requests
through the real :class:`~tpu_engine.serving_fleet.FleetRouter`, with
the real :class:`~tpu_engine.historian.MetricHistorian` and
:class:`~tpu_engine.historian.IncidentCorrelator` ingesting the whole
run under the virtual clock — and prints the profile plus the bench
line (``JAX_PLATFORMS=cpu python -m benchmarks.ctl_scale``).

Exit gates (process exits 1 when any fails):

- ``deterministic`` — five runs of the small config produce
  byte-identical deterministic counts (jobs, routes, incidents);
- ``overhead_flat_1k_to_100k`` — marginal control cost per job and per
  request at 100k jobs / 1M requests is <= 1.25x the small (1k/10k)
  config's median (the per-fleet-second overheads are reported too, but
  the tiny config spends a large share of its wall in half-empty
  ramp/drain tails, so the marginal cost is the scale-clean signal);
- ``all_jobs_completed`` / ``requests_routed_98pct`` — nothing wedges
  at depth;
- ``rings_bounded`` — recorder spans/events, historian raw windows,
  incident store, and scheduler finished-history all sit at or under
  their caps after the big run (the live set is bounded, which is what
  keeps the overhead flat in the first place).

Measured with ``time.process_time()`` and the collector paused (the
lane separately proves the live set is bounded, so steady-state GC cost
is flat); when the ratio gate trips, profile the frames with
``python tools/ctl_profile.py --jobs 100000 --requests 1000000``.
"""

from __future__ import annotations

import json

from tpu_engine.twin import ctl_scale_bench_line, ctl_scale_profile


def main() -> None:
    prof = ctl_scale_profile(seed=0)
    print(json.dumps({
        "small": {k: prof["small"][k] for k in (
            "params", "phases", "control_s", "sim_fleet_s", "work_fleet_s",
            "overhead_us_per_fleet_s", "control_us_per_job",
            "control_us_per_request", "rings",
        )},
        "big": {k: prof["big"][k] for k in (
            "params", "phases", "control_s", "sim_fleet_s", "work_fleet_s",
            "overhead_us_per_fleet_s", "control_us_per_job",
            "control_us_per_request", "rings",
        )},
        "overhead_small_us_per_fleet_s": prof["overhead_small_us_per_fleet_s"],
        "overhead_small_spread_us": prof["overhead_small_spread_us"],
        "overhead_big_us_per_fleet_s": prof["overhead_big_us_per_fleet_s"],
        "per_job_us": prof["per_job_us"],
        "per_request_us": prof["per_request_us"],
        "overhead_ratio": prof["overhead_ratio"],
        "gates": prof["gates"],
        "ok": prof["ok"],
    }, indent=2))
    line = ctl_scale_bench_line(seed=0, profile=prof)
    print(json.dumps(line))
    if not (prof["ok"] and line["ok"]):
        raise SystemExit(1)


if __name__ == "__main__":
    main()
