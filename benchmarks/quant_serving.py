"""int8 serving, measured on the real chip.

Two measurements (run: ``python benchmarks/quant_serving.py [7b|1b]``):

1. **llama-7b actually SERVES on one v5e chip** (int8 weights + int8 KV
   pool — the config ``benchmarks/serving_fit.py`` proves at 12.5 GiB).
   The quantized tree is built leaf-by-leaf ON the device (a full bf16
   7B tree plus its int8 copy would not fit during conversion), then a
   stock :class:`ContinuousBatcher` serves a full-slot batch and the
   decode throughput is measured. bf16 cannot run this at all: weights
   alone (12.6 GiB) leave no room for a pool or temporaries.

2. **llama-1b bf16 vs int8 chunked-decode A/B** — decode re-reads every
   weight per token, so weight-only int8 halves the dominant HBM
   traffic. Both modes run the same batcher, same prompts, same chunk;
   the tunnel's per-dispatch overhead is constant across modes, so the
   per-dispatch time DELTA isolates the on-chip difference.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

GIB = 2**30


class _QuantSite:
    """Sentinel marking a kernel the builder should quantize on arrival."""

    def __init__(self, sds):
        self.sds = sds


def _leafwise_quantized_params(cfg, dtype=jnp.bfloat16, quantize=True):
    """Random serving weights built one leaf at a time on the device,
    quantizing each projection kernel as it lands — peak HBM stays
    (int8 tree so far) + one bf16 leaf + quant temps, never
    bf16-tree + int8-tree (a 7B tree cannot afford both)."""
    from tpu_engine.models import transformer as tfm
    from tpu_engine.quant import _walk, quantize_weight

    shapes = jax.eval_shape(
        lambda k: tfm.init_params(k, cfg, dtype=dtype), jax.random.PRNGKey(0)
    )
    key_box = [jax.random.PRNGKey(7)]
    quant = jax.jit(quantize_weight)

    def fill(sds):
        key_box[0], sub = jax.random.split(key_box[0])
        return jax.jit(
            lambda k: (jax.random.normal(k, sds.shape, jnp.float32)
                       * 0.02).astype(sds.dtype)
        )(sub)

    def build(leaf):
        if isinstance(leaf, _QuantSite):
            w = fill(leaf.sds)
            qw = quant(w)
            jax.block_until_ready(qw.q)
            w.delete()
            return qw
        return fill(leaf)

    marked = _walk(shapes, _QuantSite) if quantize else shapes
    return jax.tree.map(
        build, marked, is_leaf=lambda x: isinstance(x, _QuantSite)
    )


def _drain(srv, rids, timeout=1200):
    t_end = time.time() + timeout
    while time.time() < t_end:
        srv.step()
        if all(srv.result(r)["status"] == "done" for r in rids):
            return True
    return False


def serve_7b_one_chip() -> None:
    from tpu_engine.models import transformer as tfm
    from tpu_engine.serving import ContinuousBatcher

    cfg = tfm.MODEL_CONFIGS["llama-7b"]
    t0 = time.time()
    params = _leafwise_quantized_params(cfg)
    build_s = time.time() - t0
    srv = ContinuousBatcher(params, cfg, max_slots=8, max_len=1024,
                            chunk_steps=16, prefill_chunk=256,
                            kv_quant=True)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, cfg.vocab_size, 64).tolist() for _ in range(8)]

    # Warmup round: compiles prefill + decode chunk.
    rids = [srv.submit(p, max_new_tokens=16) for p in prompts]
    assert _drain(srv, rids), "warmup did not finish"

    n_new = 96
    rids = [srv.submit(p, max_new_tokens=n_new) for p in prompts]
    t0 = time.time()
    assert _drain(srv, rids), "timed decode did not finish"
    dt = time.time() - t0
    toks = 8 * n_new
    print(json.dumps({
        "metric": "llama7b_int8_serving_one_chip",
        "device": str(jax.devices()[0].device_kind),
        "slots": 8, "max_len": 1024, "chunk_steps": 16,
        "weights": "int8", "kv_pool": "int8",
        "param_build_s": round(build_s, 1),
        "tokens": toks, "wall_s": round(dt, 2),
        "tok_per_s": round(toks / dt, 1),
        "note": "bf16 weights alone (12.6 GiB) cannot serve on this chip",
    }))


def ab_1b() -> None:
    from tpu_engine.models import transformer as tfm
    from tpu_engine.serving import ContinuousBatcher

    cfg = tfm.MODEL_CONFIGS["llama-1b"]
    rng = np.random.default_rng(1)
    prompts = [rng.integers(1, cfg.vocab_size, 64).tolist() for _ in range(8)]
    chunk = 64
    K = 6  # timed pure-decode dispatches
    out = {}
    for mode in ("bf16", "int8"):
        params = _leafwise_quantized_params(cfg, quantize=(mode == "int8"))
        srv = ContinuousBatcher(params, cfg, max_slots=8, max_len=2048,
                                chunk_steps=chunk, prefill_chunk=256,
                                kv_quant=(mode == "int8"))
        # Submit long-running requests; settle until every slot is mid-
        # generation (prefills done, compiles warm) so each subsequent
        # step() is exactly ONE full-occupancy decode dispatch. The
        # budget covers every settle-phase chunk plus the timed window
        # with slack — a slot finishing mid-window would silently
        # deflate the denominator's real token count.
        settle = len(prompts) + 3
        rids = [srv.submit(p, max_new_tokens=(settle + K + 2) * chunk)
                for p in prompts]
        for _ in range(settle):
            srv.step()
        assert srv.stats()["active_slots"] == 8
        assert srv.stats()["prefilling"] == 0
        t0 = time.time()
        for _ in range(K):
            srv.step()
        dt = time.time() - t0
        st = srv.stats()
        assert st["active_slots"] == 8 and st["queued"] == 0, (
            "a slot finished inside the timed window — tok/s would be "
            f"overcounted: {st}"
        )
        out[mode] = dict(
            tok_per_s=round(8 * chunk * K / dt, 1),
            ms_per_dispatch=round(1e3 * dt / K, 1),
        )
        jax.tree.map(
            lambda a: a.delete() if hasattr(a, "delete") else None, params
        )
        del srv, params, rids
    delta = out["bf16"]["ms_per_dispatch"] - out["int8"]["ms_per_dispatch"]
    print(json.dumps({
        "metric": "llama1b_serving_decode_ab",
        "device": str(jax.devices()[0].device_kind),
        "slots": 8, "chunk_steps": chunk, "timed_dispatches": K,
        "bf16": out["bf16"], "int8": out["int8"],
        "speedup": round(out["int8"]["tok_per_s"] / out["bf16"]["tok_per_s"], 2),
        "on_chip_ms_saved_per_dispatch": round(delta, 1),
        "note": "full-occupancy decode dispatches only; the constant "
                "tunnel overhead cancels in the per-dispatch delta",
    }))


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "1b"
    if which == "7b":
        serve_7b_one_chip()
    else:
        ab_1b()
