"""GPipe vs 1F1B vs zero-bubble pipeline schedules: three-way A/B.

Three measurement planes (numbers in RESULTS.md §Pipeline):

- ``--aot``: libtpu AOT compile of llama-7b (pipe=4, fsdp=4, v5e:4x4,
  full remat) at growing microbatch counts; ``memory_analysis()``
  reports the per-device temp memory each schedule actually needs. This
  is where the manual-vjp schedules' O(P) in-flight activation bound
  shows up against GPipe-by-autodiff's O(M + P) saved stage buffers,
  and where ZB's bounded P-1-entry deferred-W stash is priced (the
  acceptance bar is within ~15% of 1F1B; measured +1.5% at M=8,
  -4.2% at M=32). ``--attn flash --seq 4096`` reproduces the round-3
  flash-path table on a toolchain whose Mosaic can lower the kernel;
  the default (xla, seq 2048) compiles on this container's older
  jax/libtpu — see RESULTS.md §Zero-bubble for both tables.
- ``--wall``: wall-clock PER SAMPLE on the 8-virtual-device CPU mesh at
  growing M. ZB must beat 1F1B at EQUAL M here: it removes whole lane
  programs from the non-steady ticks (warmup drops the backward wave and
  the exit loss, drain drops the forward wave and the weight-gradient
  einsums), not just tick-count arithmetic — so the win survives the CPU
  backend's indifference to tick counts (see run_wall's honest-negative
  note for GPipe).
- ``--ticks``: the analytic per-stage tick/busy-lane account
  (``pipeline_zb.schedule_account``) for all three schedules — lane cost
  in F-units, burned (masked-lane) compute, busy fraction.

Run: ``python benchmarks/pipeline_schedule.py --aot|--wall|--ticks``
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import json
import time


def run_aot(attn: str = "xla", seq: int = 2048) -> None:
    from benchmarks.aot import aot_lowered

    for sched, M in (("gpipe", 8), ("gpipe", 16), ("1f1b", 8),
                     ("1f1b", 16), ("1f1b", 32), ("zb", 8),
                     ("zb", 16), ("zb", 32)):
        t0 = time.time()
        try:
            comp = aot_lowered(
                "llama-7b", "v5e:4x4", dict(data=1, fsdp=4, pipe=4),
                micro=1, accum=M, seq=seq,
                overrides={
                    "attention_impl": attn,
                    "pipeline_schedule": sched,
                    "activation_checkpointing": True,
                },
            ).compile()
            ma = comp.memory_analysis()
            # NOTE: cost_analysis().flops is NOT reported — XLA counts a
            # lax.scan body once regardless of trip count, so "per-sample
            # FLOPs" from it halves every time M doubles (verified: 1f1b
            # M=8/16/32 all report the same per-STEP flops). Schedule
            # arithmetic lives in RESULTS.md §Pipeline instead.
            print(json.dumps({
                "schedule": sched, "microbatches": M,
                "device_args_gib": round(ma.argument_size_in_bytes / 2**30, 2),
                "device_temp_gib": round(ma.temp_size_in_bytes / 2**30, 2),
                "compile_s": round(time.time() - t0, 1),
            }))
        except Exception as e:  # OOM is a *result* here, not a failure
            print(json.dumps({
                "schedule": sched, "microbatches": M,
                "error": str(e)[:200],
            }))


def run_wall() -> None:
    import os

    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")  # 8-virtual-device CPU mesh

    from benchmarks.aot import build_program

    from tpu_engine.mesh_runtime import MeshConfig, MeshRuntime
    from tpu_engine.models import transformer as tfm
    from tpu_engine.sharding import ShardingStage, TPUTrainConfig
    from tpu_engine.train import build_train_program

    # A compute-dominated config with a REAL bubble: 8 layers × 256-dim
    # (2 layers/stage at pipe=4) so per-tick schedule overhead is small
    # against the stage matmuls — at P=4, GPipe's memory-feasible M=8
    # carries a (P-1)/(M+P-1) = 27% bubble that M=32 shrinks to 9%.
    # (gpt-tiny at pipe=2 measures only per-tick overhead: the bubble
    # swing is 6% while 1F1B's masked-lane overhead is ~13% — schedule
    # arithmetic is invisible there. gpt-125m-class stages compile for
    # tens of minutes on the CPU backend — too big for this plane.)
    model_cfg = tfm.MODEL_CONFIGS["gpt-tiny"].with_(
        name="gpt-mid-bench", d_model=256, n_heads=8, n_kv_heads=8,
        d_ff=1024, n_layers=8, vocab_size=2048,
    )
    micro = 1
    results = {}
    for sched, M in (("gpipe", 8), ("gpipe", 16), ("1f1b", 8),
                     ("1f1b", 16), ("1f1b", 32), ("zb", 8),
                     ("zb", 16), ("zb", 32)):
        cfg = TPUTrainConfig(
            model_name="gpt-tiny",  # shape comes from model_cfg below
            sharding_stage=ShardingStage.FULL_PARTITIONING,
            mesh=MeshConfig(data=1, fsdp=2, pipe=4),
            micro_batch_size=micro, gradient_accumulation_steps=M,
            seq_len=256, attention_impl="xla", pipeline_schedule=sched,
            activation_checkpointing=True,
        )
        prog = build_train_program(
            cfg, model_cfg=model_cfg,
            runtime=MeshRuntime(cfg.mesh, devices=jax.devices()[:8]),
        )
        state = prog.init(jax.random.PRNGKey(0))
        batch = prog.synthetic_batch(seed=0)
        for _ in range(2):
            state, m = prog.step(state, batch)
        float(m["loss"])
        t0 = time.perf_counter()
        n = 3
        for _ in range(n):
            state, m = prog.step(state, batch)
        float(m["loss"])
        step_ms = (time.perf_counter() - t0) / n * 1e3
        per_sample = step_ms / (M * micro)
        results[(sched, M)] = per_sample
        print(json.dumps({
            "schedule": sched, "microbatches": M,
            "samples_per_step": M * micro,
            "step_ms": round(step_ms, 1),
            "per_sample_ms": round(per_sample, 2),
        }))
    # The CPU backend CANNOT exhibit pipeline-schedule arithmetic: its
    # per-tick cost grows with M (cache pressure from the O(M) saved
    # buffers — observe GPipe's own per-sample time WORSENING from M=8 to
    # M=16 where tick counts predict a 14% improvement), and the
    # masked-SPMD 1F1B pays a large per-tick manual-vjp overhead there.
    # Report the measurement and the diagnostic ratio honestly; the
    # TPU-honest planes are the AOT memory wall (--aot: GPipe M=16 OOMs,
    # 1F1B fits through M=32) and tick arithmetic (RESULTS.md §Pipeline).
    best_1f1b = min(results[("1f1b", 16)], results[("1f1b", 32)])
    gpipe_scaling = results[("gpipe", 16)] / results[("gpipe", 8)]
    print(json.dumps({
        "metric": "pipeline_cpu_wall_per_sample",
        "gpipe_m8_per_sample_ms": round(results[("gpipe", 8)], 2),
        "best_1f1b_per_sample_ms": round(best_1f1b, 2),
        "gpipe_m16_over_m8_per_sample": round(gpipe_scaling, 3),
        "tick_arithmetic_predicts": 0.864,  # (19/16)/(11/8)
        "cpu_backend_follows_tick_arithmetic": gpipe_scaling < 1.0,
    }))
    # ZB vs 1F1B at EQUAL M — the zero-bubble acceptance bar. Unlike the
    # GPipe comparison above, this one is NOT tick-count arithmetic: at
    # the same M, zb's non-steady ticks simply execute less program, so
    # the CPU backend should show the win directly.
    print(json.dumps({
        "metric": "pipeline_cpu_wall_zb_vs_1f1b_equal_m",
        **{
            f"m{M}_ratio": round(results[("zb", M)] / results[("1f1b", M)], 3)
            for M in (8, 16, 32)
        },
        "zb_wins_all_m": all(
            results[("zb", M)] < results[("1f1b", M)] for M in (8, 16, 32)
        ),
    }))


def run_ticks() -> None:
    from tpu_engine.parallel.pipeline_zb import schedule_account

    for P in (4, 8):
        for M in (8, 16, 32):
            for sched in ("gpipe", "1f1b", "zb"):
                print(json.dumps(schedule_account(sched, P, M)))


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--aot", action="store_true")
    ap.add_argument("--wall", action="store_true")
    ap.add_argument("--ticks", action="store_true")
    ap.add_argument("--attn", choices=("xla", "flash"), default="xla")
    ap.add_argument("--seq", type=int, default=2048)
    args = ap.parse_args()
    if not (args.aot or args.wall or args.ticks):
        ap.error("pass --aot, --wall and/or --ticks")
    if args.aot:
        run_aot(attn=args.attn, seq=args.seq)
    if args.wall:
        run_wall()
    if args.ticks:
        run_ticks()
