"""GPipe vs 1F1B pipeline schedules: memory and step-time A/B.

Two measurement planes (numbers in RESULTS.md):

- ``--aot``: libtpu AOT compile of llama-7b (pipe=4, fsdp=4, v5e:4x4,
  seq 4096, flash, full remat) at growing microbatch counts;
  ``memory_analysis()`` reports the per-device temp memory each schedule
  actually needs. This is where 1F1B's O(P) in-flight activation bound
  shows up against GPipe-by-autodiff's O(M + P) saved stage buffers.
- ``--wall``: wall-clock per optimizer step on the 8-virtual-device CPU
  mesh (gpt-tiny). In the masked-SPMD formulation the 1F1B warmup/drain
  lanes burn compute rather than idling, so at equal M it is slightly
  SLOWER — the schedule's value is spending the saved memory on more
  microbatches (amortising the (P-1)/M bubble) or bigger ones.

Run: ``python benchmarks/pipeline_schedule.py --aot|--wall``
"""

from __future__ import annotations

import argparse
import json
import time


def run_aot() -> None:
    from benchmarks.aot import aot_lowered

    for M in (8, 16):
        for sched in ("gpipe", "1f1b"):
            t0 = time.time()
            try:
                comp = aot_lowered(
                    "llama-7b", "v5e:4x4", dict(data=1, fsdp=4, pipe=4),
                    micro=1, accum=M, seq=4096,
                    overrides={
                        "attention_impl": "flash",
                        "pipeline_schedule": sched,
                        "activation_checkpointing": True,
                    },
                ).compile()
                ma = comp.memory_analysis()
                print(json.dumps({
                    "schedule": sched, "microbatches": M,
                    "device_args_gib": round(ma.argument_size_in_bytes / 2**30, 2),
                    "device_temp_gib": round(ma.temp_size_in_bytes / 2**30, 2),
                    "compile_s": round(time.time() - t0, 1),
                }))
            except Exception as e:  # OOM is a *result* here, not a failure
                print(json.dumps({
                    "schedule": sched, "microbatches": M,
                    "error": str(e)[:200],
                }))


def run_wall() -> None:
    import jax

    from benchmarks.aot import build_program

    for sched in ("gpipe", "1f1b"):
        prog = build_program(
            "gpt-tiny", dict(data=1, fsdp=2, model=2, pipe=2),
            micro=2, accum=8, seq=128,
            overrides={
                "attention_impl": "xla", "pipeline_schedule": sched,
                "activation_checkpointing": True,
            },
            devices=jax.devices()[:8],
        )
        state = prog.init(jax.random.PRNGKey(0))
        batch = prog.synthetic_batch(seed=0)
        for _ in range(2):
            state, m = prog.step(state, batch)
        float(m["loss"])
        t0 = time.perf_counter()
        n = 10
        for _ in range(n):
            state, m = prog.step(state, batch)
        float(m["loss"])
        print(json.dumps({
            "schedule": sched,
            "step_ms": round((time.perf_counter() - t0) / n * 1e3, 1),
        }))


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--aot", action="store_true")
    ap.add_argument("--wall", action="store_true")
    args = ap.parse_args()
    if not (args.aot or args.wall):
        ap.error("pass --aot and/or --wall")
    if args.aot:
        run_aot()
    if args.wall:
        run_wall()
