"""Client-side TTFT: SSE streaming vs completion polling, on the chip.

The engine always tracked server-side TTFT (first emission into the
request record); what a CLIENT experienced before round 5 was
time-to-COMPLETION, because `/result` polling only pays off when the
whole stream is done. This measures the difference end to end through
real HTTP: one aiohttp control plane, one serving instance on the real
device, one request — the streaming client clocks its first token at the
first SSE event; the polling client clocks first-token-visible at the
poll that returns status=done.

Run: ``python benchmarks/streaming_ttft.py``.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import threading
import time

import httpx
import jax
from aiohttp import web

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from backend.main import create_app  # noqa: E402

N_NEW = 128
CHUNK_STEPS = 8


def _serve_app() -> tuple[int, asyncio.AbstractEventLoop, threading.Thread]:
    loop = asyncio.new_event_loop()
    started = threading.Event()
    state: dict = {}

    def run():
        asyncio.set_event_loop(loop)
        runner = web.AppRunner(create_app())
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, "127.0.0.1", 0)
        loop.run_until_complete(site.start())
        state["port"] = runner.addresses[0][1]
        started.set()
        loop.run_forever()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    started.wait(timeout=30)
    return state["port"], loop, t


def _submit(c: httpx.Client) -> int:
    return c.post(
        "/api/v1/serving/submit",
        json={"prompt": list(range(1, 33)), "max_new_tokens": N_NEW},
    ).json()["request_id"]


def _stream_timings(c: httpx.Client, rid: int, t0: float) -> dict:
    first = done = None
    events = 0
    with c.stream("GET", f"/api/v1/serving/stream/{rid}", timeout=600) as r:
        for line in r.iter_lines():
            if not line.startswith("data: "):
                continue
            e = json.loads(line[len("data: "):])
            if e["tokens"] and first is None:
                first = time.perf_counter() - t0
            events += 1
            if e["status"] in ("done", "failed"):
                done = time.perf_counter() - t0
    return {"first_s": first, "done_s": done, "events": events}


def _poll_timings(c: httpx.Client, rid: int, t0: float) -> dict:
    while True:
        body = c.get(f"/api/v1/serving/result/{rid}").json()
        if body["status"] in ("done", "failed"):
            return {"done_s": time.perf_counter() - t0}
        time.sleep(0.05)


def main() -> None:
    port, loop, _ = _serve_app()
    with httpx.Client(base_url=f"http://127.0.0.1:{port}", timeout=600) as c:
        r = c.post("/api/v1/serving/start",
                   json={"model_name": "gpt-125m", "max_slots": 4,
                         "max_len": 512, "decode_chunk_steps": CHUNK_STEPS})
        assert r.status_code == 200, r.text
        # Warm up compiles so both clients measure dispatches.
        rid = _submit(c)
        _poll_timings(c, rid, time.perf_counter())

        t0 = time.perf_counter()
        rid = _submit(c)
        stream = _stream_timings(c, rid, t0)

        t0 = time.perf_counter()
        rid = _submit(c)
        poll = _poll_timings(c, rid, t0)

        c.post("/api/v1/serving/stop")
    loop.call_soon_threadsafe(loop.stop)
    if stream["first_s"] is None or stream["done_s"] is None:
        raise SystemExit(f"stream produced no tokens (server error?): {stream}")
    print(json.dumps({
        "metric": "serving_client_ttft",
        "device": str(jax.devices()[0].device_kind),
        "max_new_tokens": N_NEW, "decode_chunk_steps": CHUNK_STEPS,
        "stream_first_token_s": round(stream["first_s"], 3),
        "stream_done_s": round(stream["done_s"], 3),
        "stream_events": stream["events"],
        "poll_first_visible_s": round(poll["done_s"], 3),
        "client_ttft_speedup": round(poll["done_s"] / stream["first_s"], 2),
    }))


if __name__ == "__main__":
    main()
