"""Placement-planner cost model A/B: predicted ranking vs reality.

Two measurement planes (numbers in RESULTS.md §PR 7):

- ``--sweep``: measured CPU-mesh steps across a fixed 7-layout sweep
  (same global batch — 16 samples × seq 512 per step — so every layout
  does the same useful work) on the 8-virtual-device mesh, vs the
  planner's predicted step time for the SAME configs
  (``PlacementPlanner.predict``). Reports Spearman rank correlation and
  whether the planner's top pick is the measured-fastest layout (or
  within 5% of it). Default model is the 8-layer/256-dim ``gpt-mid``
  shape (same as ``pipeline_schedule.py --wall``): gpt-tiny measures
  only per-tick overhead on CPU, which buries the bubble/comm terms the
  model ranks by. ``--size tiny`` is the fast variant ``bench.py`` uses.
  The prediction's absolute seconds assume a TPU roofline and are
  meaningless on CPU; the claim under test is the ORDER. Known honest
  negative: the CPU SPMD partitioner hits "involuntary full
  rematerialization" on stage-3 gather layouts, inflating them ~7x in a
  way no TPU exhibits — both stage-3 rows land slowest on CPU while the
  model (correctly, for ICI) prices them mid-pack. The correlation is
  reported over the full sweep anyway.
- ``--aot``: the planner ranks llama-7b layouts against a described
  v5e:4x4 fleet (16 chips × 16 GiB, the HBM gate live), then the top-3
  feasible plans are AOT-lowered via ``benchmarks/aot.py`` — proof the
  search never emits a layout the real builder rejects at scale, with
  ``memory_analysis()`` alongside each plan's ``estimate_job_hbm``
  projection.

Run: ``python benchmarks/placement_plan.py --sweep|--aot``
``bench.py`` imports :func:`run_sweep` for its placement JSON line.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import json
import time

# Each entry: (name, mesh axes, sharding stage, micro, accum, schedule)
# with the (micro, accum) split keeping the global batch at 16 samples:
# data·fsdp·micro·accum = 16. seq 512 keeps the roofline compute term
# comparable to the collective terms (at toy seq everything is
# comm-bound and the predicted margins collapse into ties); the
# (micro, accum) splits are varied so no two layouts are priced
# identically under the overlap model.
SWEEP_LAYOUTS = (
    ("fsdp8_s2", dict(fsdp=8), 2, 2, 1, None),
    ("fsdp8_s3", dict(fsdp=8), 3, 1, 2, None),
    ("data8", dict(data=8), 3, 2, 1, None),
    ("data4_fsdp2", dict(data=4, fsdp=2), 3, 1, 2, None),
    ("data2_model4", dict(data=2, model=4), 3, 2, 4, None),
    ("data2_pipe4_gpipe", dict(data=2, pipe=4), 3, 1, 8, "gpipe"),
    ("data2_pipe4_zb", dict(data=2, pipe=4), 3, 1, 8, "zb"),
)
SEQ = 512
GANG = 8


def _sweep_model(size: str):
    from tpu_engine.models import transformer as tfm

    if size == "tiny":
        # 4 layers so the pipe=4 sweep rows can stage it (gpt-tiny's 2
        # cannot); still small enough for bench.py's budget.
        return tfm.MODEL_CONFIGS["gpt-tiny"].with_(
            name="gpt-tiny-bench", n_layers=4
        )
    # The pipeline_schedule.py --wall shape: 2 layers/stage at pipe=4,
    # big enough that stage matmuls dominate per-tick schedule overhead.
    return tfm.MODEL_CONFIGS["gpt-tiny"].with_(
        name="gpt-mid-bench", d_model=256, n_heads=8, n_kv_heads=8,
        d_ff=1024, n_layers=8, vocab_size=2048,
    )


def _sweep_config(mesh_axes, stage, micro, accum, schedule):
    from tpu_engine.mesh_runtime import MeshConfig
    from tpu_engine.sharding import ShardingStage, TPUTrainConfig

    return TPUTrainConfig(
        model_name="gpt-tiny",  # shape comes from the model_cfg override
        sharding_stage=ShardingStage(stage),
        mesh=MeshConfig(**mesh_axes),
        micro_batch_size=micro,
        gradient_accumulation_steps=accum,
        seq_len=SEQ,
        attention_impl="xla",
        pipeline_schedule=schedule or "auto",
    )


def _spearman(xs: list[float], ys: list[float]) -> float:
    """Spearman rank correlation (no ties expected in wall-clock data)."""
    n = len(xs)

    def ranks(vals):
        order = sorted(range(n), key=lambda i: vals[i])
        r = [0] * n
        for rank, i in enumerate(order):
            r[i] = rank
        return r

    rx, ry = ranks(xs), ranks(ys)
    d2 = sum((a - b) ** 2 for a, b in zip(rx, ry))
    return 1.0 - 6.0 * d2 / (n * (n * n - 1))


def run_sweep(size: str = "mid", iters: int = 3, warmup: int = 2) -> dict:
    """Measured-vs-predicted layout sweep; returns the summary dict."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    if jax.default_backend() != "tpu":
        jax.config.update("jax_platforms", "cpu")

    from tpu_engine.mesh_runtime import MeshRuntime
    from tpu_engine.placement import PlacementPlanner
    from tpu_engine.train import build_train_program

    model_cfg = _sweep_model(size)
    planner = PlacementPlanner()
    rows = []
    for name, mesh_axes, stage, micro, accum, schedule in SWEEP_LAYOUTS:
        cfg = _sweep_config(mesh_axes, stage, micro, accum, schedule)
        predicted = planner.predict(
            cfg, gang=GANG, model_cfg=model_cfg
        ).predicted_step_time_s
        prog = build_train_program(
            cfg, model_cfg=model_cfg,
            runtime=MeshRuntime(cfg.mesh, devices=jax.devices()[:GANG]),
        )
        state = prog.init(jax.random.PRNGKey(0))
        batch = prog.synthetic_batch(seed=0)
        for _ in range(warmup):
            state, m = prog.step(state, batch)
        float(m["loss"])
        # min-of-iters: wall noise on the CPU backend is one-sided (GC,
        # scheduler jitter), so the minimum is the honest per-step cost.
        best = float("inf")
        for _ in range(iters):
            t0 = time.perf_counter()
            state, m = prog.step(state, batch)
            float(m["loss"])
            best = min(best, time.perf_counter() - t0)
        rows.append({
            "layout": name,
            "predicted_s": predicted,
            "measured_ms": round(best * 1e3, 2),
        })
        print(json.dumps(rows[-1]))

    predicted = [r["predicted_s"] for r in rows]
    measured = [r["measured_ms"] for r in rows]
    rho = _spearman(predicted, measured)
    top = min(rows, key=lambda r: r["predicted_s"])
    fastest = min(measured)
    summary = {
        "metric": "placement_rank_correlation",
        "value": round(rho, 3),
        "unit": "Spearman rho (predicted vs measured step time)",
        "model": model_cfg.name,
        "layouts": len(rows),
        "top_pick": top["layout"],
        "top_pick_measured_ms": top["measured_ms"],
        "fastest_measured_ms": round(fastest, 2),
        "top_pick_within_5pct": top["measured_ms"] <= fastest * 1.05,
        "rows": rows,
    }
    print(json.dumps(summary))
    return summary


def run_aot(top_k: int = 3) -> None:
    """Plan llama-7b on a described v5e:4x4 fleet, AOT-lower the top-k."""
    from types import SimpleNamespace

    from benchmarks.aot import TopologyUnavailable, aot_lowered

    from tpu_engine.placement import PlacementPlanner
    from tpu_engine.sharding import ShardingStage, TPUTrainConfig

    # 16 chips of v5e with the full 16 GiB free: the HBM gate is live, so
    # full-replica layouts that cannot fit a 7b are filtered out BEFORE
    # lowering rather than discovered as compile OOMs.
    fleet = [
        SimpleNamespace(index=i, hbm_free_gb=16.0, hbm_total_gb=16.0)
        for i in range(16)
    ]
    cfg = TPUTrainConfig(
        model_name="llama-7b",
        sharding_stage=ShardingStage.FULL_PARTITIONING,
        micro_batch_size=1,
        gradient_accumulation_steps=8,
        # seq 512: XLA attention (below) materializes S×S score
        # temporaries that grow with pipe depth (measured +4G at pipe=2
        # up to +9G at pipe=16 over the estimate at seq 1024); at 512
        # they shrink 4x, so every plan the widened gate admits stays
        # under the 15.75 GiB ceiling even at the worst overshoot.
        seq_len=512,
        activation_checkpointing=True,
        # This container's jax/libtpu Mosaic rejects the flash kernel
        # under stage-3 gathers ("Unsupported implicit dim change") — a
        # toolchain bug, not a layout property. XLA attention lowers the
        # identical mesh/collective structure, which is what this plane
        # validates.
        attention_impl="xla",
    )
    # 75% margin here (product default is 35%): the xla-attention
    # fallback above materializes S×S score tensors that the flash-path
    # estimator never charges, and the measured compile footprints run
    # 1.3-2.0x the projection (e.g. fsdp2xpipe8·s2 est 10.19 GiB ->
    # 17.43 GiB real). The wider gate keeps this plane's top picks out
    # of that band; on the flash path the 35% default is the right gate.
    planner = PlacementPlanner(hbm_margin_frac=0.75)
    result = planner.plan(cfg, devices=fleet, gang=16)
    print(json.dumps({
        "model": "llama-7b", "gang": 16,
        "evaluated": result.evaluated,
        "feasible": len(result.plans),
        "hbm_rejected": len(result.infeasible),
    }))
    for rank, p in enumerate(result.plans[:top_k], 1):
        mesh_axes = {k: v for k, v in p.mesh.items() if v > 1}
        t0 = time.time()
        try:
            comp = aot_lowered(
                "llama-7b", "v5e:4x4", mesh_axes or {"data": 1},
                micro=p.micro_batch_size,
                accum=p.gradient_accumulation_steps, seq=512,
                overrides={
                    "sharding_stage": p.sharding_stage,
                    "pipeline_schedule": p.pipeline_schedule,
                    "activation_checkpointing": True,
                    "attention_impl": "xla",
                },
            ).compile()
            ma = comp.memory_analysis()
            print(json.dumps({
                "rank": rank, "layout": p.label,
                "predicted_step_s": round(p.predicted_step_time_s, 4),
                "planner_hbm_gib": round(
                    p.hbm_estimate.device_total_gib, 2
                ) if p.hbm_estimate else None,
                "aot_args_gib": round(ma.argument_size_in_bytes / 2**30, 2),
                "aot_temp_gib": round(ma.temp_size_in_bytes / 2**30, 2),
                "compile_s": round(time.time() - t0, 1),
            }))
        except TopologyUnavailable as e:
            print(json.dumps({
                "rank": rank, "layout": p.label,
                "skipped": f"topology unavailable: {str(e)[:120]}",
            }))
        except Exception as e:  # a lowering failure IS a planner bug
            print(json.dumps({
                "rank": rank, "layout": p.label, "error": str(e)[:200],
            }))


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--sweep", action="store_true")
    ap.add_argument("--aot", action="store_true")
    ap.add_argument("--size", choices=("mid", "tiny"), default="mid")
    ap.add_argument("--iters", type=int, default=3)
    args = ap.parse_args()
    if not (args.sweep or args.aot):
        ap.error("pass --sweep and/or --aot")
    if args.sweep:
        run_sweep(size=args.size, iters=args.iters)
    if args.aot:
        run_aot()
