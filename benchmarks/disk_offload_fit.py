"""Disk-tier optimizer offload: what it buys in HBM, measured.

Compiles the REAL step programs (``MODEL`` below) on the local TPU
backend and reports the per-device memory XLA allocated:

- in-memory AdamW (`build_train_program` default): the donated train
  state carries fp32 masters + mu + nu (12 bytes/param) through every
  step;
- disk tier (`optimizer_offload="disk"`): the device state is bf16
  params only (2 bytes/param); the jitted program is forward/backward/
  clip, and masters+moments live in memmap spill files
  (``tpu_engine/disk_offload.py``).

Run: ``python benchmarks/disk_offload_fit.py`` (needs the local chip;
step math parity with the in-memory path is pinned by
``tests/test_disk_offload.py``). Wall-clock per step is reported for
the disk tier but is tunnel-regime-bound here: the host update fetches
the full fp32 gradient tree over the remote runtime each step — on a
real TPU-VM (local PCIe + NVMe) that transfer is the documented price
of the tier, paid for models whose optimizer state cannot fit anywhere
else.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import json
import tempfile
import time

import jax
import jax.numpy as jnp

GIB = 2**30
# gpt-125m keeps the gradient fetch small enough to measure through the
# tunneled runtime; the device-state shrink is byte-arithmetic (12 ->
# 2 bytes/param) and model-size-independent.
MODEL = "gpt-125m"


def main() -> None:
    from tpu_engine.mesh_runtime import MeshConfig
    from tpu_engine.sharding import OffloadDevice, Precision, TPUTrainConfig
    from tpu_engine.train import build_train_program

    if jax.devices()[0].platform != "tpu":
        print(json.dumps({"skipped": "needs a local TPU"}))
        return

    # micro_batch 8: enough device compute per step that the DPU overlap
    # regime is visible — with micro=1 the host walk dominates (265 s vs
    # ~15 s device through the tunnel) and hiding the device step is
    # marginal by construction. DPU's win is bounded by
    # (host+device)/max(host, device) in every regime; the bench reports
    # both sides so the ratio is interpretable on local silicon too.
    base = dict(
        model_name=MODEL, mesh=MeshConfig(), micro_batch_size=8,
        gradient_accumulation_steps=1, seq_len=2048,
        precision=Precision.BF16, total_steps=10, warmup_steps=2,
        activation_checkpointing=True,
    )

    out = {}
    for mode in ("in_memory", "disk", "disk_overlap"):
        kw = dict(base)
        spill = None
        if mode.startswith("disk"):
            spill = tempfile.mkdtemp(prefix="spill_")
            kw.update(optimizer_offload=OffloadDevice.DISK,
                      optimizer_spill_dir=spill,
                      disk_update_overlap=mode == "disk_overlap")
        prog = build_train_program(TPUTrainConfig(**kw))
        state = prog.init(jax.random.PRNGKey(0))
        batch = prog.synthetic_batch(0)
        # Warm compile + one step so the report reflects the steady state.
        t0 = time.time()
        state, _ = prog.step(state, batch)
        jax.block_until_ready(state["params"])
        warm_s = time.time() - t0
        # Steady state over several steps; the overlap mode's walks drain
        # in the background, so the flush at the end charges the final
        # in-flight walk to the measured window (pipeline fill + drain
        # both inside the timing — honest steady-state amortisation).
        n_meas = 2
        t0 = time.time()
        for _ in range(n_meas):
            state, metrics = prog.step(state, batch)
        if prog.flush is not None:
            state = prog.flush(state)
        jax.block_until_ready(state["params"])
        step_s = (time.time() - t0) / n_meas

        state_gib = sum(
            leaf.size * leaf.dtype.itemsize
            for leaf in jax.tree.leaves(state)
        ) / GIB
        row = {
            "mode": mode, "model": MODEL,
            "device_state_gib": round(state_gib, 2),
            "warm_step_s": round(warm_s, 2),
            "loss": round(float(metrics["loss"]), 3),
        }
        if mode.startswith("disk"):
            # The host update's device_get is a real sync, so wall time
            # is meaningful here; the in-memory step is async through
            # the tunnel (block_until_ready returns at enqueue — the
            # verify-skill gotcha) so its wall is not reported.
            row["step_wall_s"] = round(step_s, 2)
            row["spill_gib_on_disk"] = round(
                prog.disk_store.spill_bytes() / GIB, 2
            )
        out[mode] = row
        print(json.dumps(row), flush=True)
    print(json.dumps({
        "metric": "disk_tier_device_state_shrink",
        "in_memory_gib": out["in_memory"]["device_state_gib"],
        "disk_gib": out["disk"]["device_state_gib"],
        "shrink": round(
            out["in_memory"]["device_state_gib"]
            / max(out["disk"]["device_state_gib"], 1e-9), 2
        ),
    }))
    print(json.dumps({
        "metric": "disk_tier_overlap_speedup",
        "serial_step_s": out["disk"]["step_wall_s"],
        "overlap_step_s": out["disk_overlap"]["step_wall_s"],
        "speedup": round(
            out["disk"]["step_wall_s"]
            / max(out["disk_overlap"]["step_wall_s"], 1e-9), 2
        ),
    }))


if __name__ == "__main__":
    main()
