"""Trace-driven MFU breakdown of the headline bench config (VERDICT r2 #3).

Captures a ``jax.profiler`` device trace of the exact ``bench.py`` headline
config (llama-1b, micro-batch 6, bf16 Adam mu, full remat, Pallas flash) on
the real chip, converts the xplane with ``xprof`` (the tensorboard profiler
backend, present in the image), and prints:

- per-HLO-category self-time split (matmul fusions, Pallas custom-calls,
  elementwise loop fusions, data formatting, …);
- per-category achieved FLOP rates / memory bandwidth / roofline bound as
  measured by the profiler itself;
- device-busy vs host gap (device self-time vs wall step time).

Run: ``python benchmarks/trace_breakdown.py``  (real TPU required)
Prints one JSON line per category plus a summary; paste into RESULTS.md.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import glob
import json
import shutil
import time
from collections import defaultdict

import jax


def capture(logdir: str = "/tmp/tpu_engine_trace", steps: int = 3):
    """Build the headline config, warm up, trace ``steps`` steps.

    Returns (wall seconds per step, xplane path).
    """
    from benchmarks.aot import build_program
    from tpu_engine.sharding import ShardingStage

    # The exact bench.py headline config (keep in lockstep).
    program = build_program(
        "llama-1b", {"data": 1}, micro=6, seq=2048,
        overrides={
            "moment_dtype": "bf16", "activation_checkpointing": True,
            "sharding_stage": ShardingStage.DISABLED,
            "attention_impl": "auto", "precision": "bf16",
        },
    )
    state = program.init(jax.random.PRNGKey(0))
    batch = program.synthetic_batch(seed=0)
    for _ in range(3):
        state, m = program.step(state, batch)
    float(m["loss"])  # sync

    shutil.rmtree(logdir, ignore_errors=True)
    t0 = time.perf_counter()
    with jax.profiler.trace(logdir):
        for _ in range(steps):
            state, m = program.step(state, batch)
        float(m["loss"])
    wall = (time.perf_counter() - t0) / steps
    (xplane,) = glob.glob(os.path.join(logdir, "plugins/profile/*/*.xplane.pb"))
    return wall, xplane


def hlo_category_split(xplane: str) -> tuple[list[dict], float]:
    """(per-category rows, total device self-time seconds per capture)."""
    from xprof.convert import raw_to_tool_data

    data, _ = raw_to_tool_data.xspace_to_tool_data([xplane], "hlo_stats", {})
    table = json.loads(data if isinstance(data, str) else data.decode())
    cols = [c["id"] for c in table["cols"]]

    def get(row, key):
        return row["c"][cols.index(key)].get("v")

    agg = defaultdict(lambda: {"self_us": 0.0, "flops": 0.0, "bw": 0.0, "n": 0})
    for r in table["rows"]:
        cat = get(r, "category")
        a = agg[cat]
        t = float(get(r, "total_self_time") or 0)
        a["self_us"] += t
        # time-weighted achieved rates (profiler-measured, per op)
        a["flops"] += t * float(get(r, "model_flop_rate") or 0)
        a["bw"] += t * float(get(r, "measured_memory_bw") or 0)
        a["n"] += 1
    total = sum(a["self_us"] for a in agg.values())
    rows = []
    for cat, a in sorted(agg.items(), key=lambda kv: -kv[1]["self_us"]):
        rows.append({
            "category": cat,
            "self_time_pct": round(100 * a["self_us"] / total, 1),
            "achieved_gflops": round(a["flops"] / a["self_us"]) if a["self_us"] else 0,
            "achieved_gbps": round(a["bw"] / a["self_us"], 1) if a["self_us"] else 0,
            "ops": a["n"],
        })
    return rows, total / 1e6


def main() -> None:
    steps = 3
    wall, xplane = capture(steps=steps)
    rows, device_s = hlo_category_split(xplane)
    device_per_step = device_s / steps
    for r in rows:
        if r["self_time_pct"] >= 0.3:
            print(json.dumps(r))
    print(json.dumps({
        "summary": True,
        "wall_ms_per_step": round(wall * 1e3, 1),
        "device_ms_per_step": round(device_per_step * 1e3, 1),
        "device_busy_pct": round(100 * device_per_step / wall, 1),
    }))


if __name__ == "__main__":
    main()
