"""Trace-driven MFU breakdown of the headline bench config (VERDICT r2 #3).

Captures a ``jax.profiler`` device trace of the exact ``bench.py`` headline
config (llama-1b, micro-batch 6, bf16 Adam mu, full remat, Pallas flash) on
the real chip, converts the xplane with ``xprof`` (the tensorboard profiler
backend, present in the image), and prints:

- per-HLO-category self-time split (matmul fusions, Pallas custom-calls,
  elementwise loop fusions, data formatting, …);
- per-category achieved FLOP rates / memory bandwidth / roofline bound as
  measured by the profiler itself;
- device-busy vs host gap (device self-time vs wall step time).

Run: ``python benchmarks/trace_breakdown.py``  (real TPU required for the
xprof HLO split; ``--no-hlo --model gpt-tiny`` runs anywhere)
Prints one JSON line per category plus a summary; paste into RESULTS.md.

The capture itself is also recorded in the flight recorder
(``tpu_engine/tracing.py``): build/compile, warmup and the profiled window
become spans, and ``--perfetto-out PATH`` writes them as
Chrome-trace/Perfetto JSON — a CPU-viable export that needs neither a TPU
nor the xprof converter.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import glob
import json
import shutil
import time
from collections import defaultdict
from typing import Optional

import jax

from tpu_engine.tracing import FlightRecorder


def capture(
    logdir: str = "/tmp/tpu_engine_trace",
    steps: int = 3,
    model: str = "llama-1b",
    micro: int = 6,
    seq: int = 2048,
    mesh_axes: Optional[dict] = None,
    recorder: Optional[FlightRecorder] = None,
):
    """Build ``model``, warm up, trace ``steps`` steps.

    Defaults are the exact bench.py headline config (keep in lockstep).
    ``mesh_axes`` defaults to the single-device ``{"data": 1}`` headline
    layout; pass e.g. ``{"data": 8}`` on the 8-virtual-device CPU harness.
    Returns (wall seconds per step, xplane path).
    """
    from benchmarks.aot import build_program
    from tpu_engine.sharding import ShardingStage

    rec = recorder or FlightRecorder()
    trace_id = rec.new_trace_id()
    root = rec.start_span(
        f"trace_breakdown:{model}", kind="job", trace_id=trace_id,
        attrs={"model": model, "micro": micro, "seq": seq, "steps": steps},
    )
    with rec.start_span("compile", kind="compile", trace_id=trace_id,
                        parent=root):
        program = build_program(
            model, mesh_axes or {"data": 1}, micro=micro, seq=seq,
            overrides={
                "moment_dtype": "bf16", "activation_checkpointing": True,
                "sharding_stage": ShardingStage.DISABLED,
                "attention_impl": "auto", "precision": "bf16",
            },
        )
        state = program.init(jax.random.PRNGKey(0))
    batch = program.synthetic_batch(seed=0)
    with rec.start_span("warmup", kind="step", trace_id=trace_id,
                        parent=root):
        for _ in range(3):
            state, m = program.step(state, batch)
        float(m["loss"])  # sync

    shutil.rmtree(logdir, ignore_errors=True)
    cap_span = rec.start_span("profile_capture", kind="profile",
                              trace_id=trace_id, parent=root)
    t0 = time.perf_counter()
    with jax.profiler.trace(logdir):
        for _ in range(steps):
            state, m = program.step(state, batch)
        float(m["loss"])
    wall = (time.perf_counter() - t0) / steps
    cap_span.end(wall_s_per_step=round(wall, 4))
    root.end()
    (xplane,) = glob.glob(os.path.join(logdir, "plugins/profile/*/*.xplane.pb"))
    return wall, xplane


def hlo_category_split(xplane: str) -> tuple[list[dict], float]:
    """(per-category rows, total device self-time seconds per capture)."""
    from xprof.convert import raw_to_tool_data

    data, _ = raw_to_tool_data.xspace_to_tool_data([xplane], "hlo_stats", {})
    table = json.loads(data if isinstance(data, str) else data.decode())
    cols = [c["id"] for c in table["cols"]]

    def get(row, key):
        return row["c"][cols.index(key)].get("v")

    agg = defaultdict(lambda: {"self_us": 0.0, "flops": 0.0, "bw": 0.0, "n": 0})
    for r in table["rows"]:
        cat = get(r, "category")
        a = agg[cat]
        t = float(get(r, "total_self_time") or 0)
        a["self_us"] += t
        # time-weighted achieved rates (profiler-measured, per op)
        a["flops"] += t * float(get(r, "model_flop_rate") or 0)
        a["bw"] += t * float(get(r, "measured_memory_bw") or 0)
        a["n"] += 1
    total = sum(a["self_us"] for a in agg.values())
    rows = []
    for cat, a in sorted(agg.items(), key=lambda kv: -kv[1]["self_us"]):
        rows.append({
            "category": cat,
            "self_time_pct": round(100 * a["self_us"] / total, 1),
            "achieved_gflops": round(a["flops"] / a["self_us"]) if a["self_us"] else 0,
            "achieved_gbps": round(a["bw"] / a["self_us"], 1) if a["self_us"] else 0,
            "ops": a["n"],
        })
    return rows, total / 1e6


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--model", default="llama-1b")
    parser.add_argument("--micro", type=int, default=6)
    parser.add_argument("--seq", type=int, default=2048)
    parser.add_argument("--steps", type=int, default=3)
    parser.add_argument(
        "--data", type=int, default=1,
        help="data-axis mesh size (must equal the visible device count)",
    )
    parser.add_argument("--logdir", default="/tmp/tpu_engine_trace")
    parser.add_argument(
        "--no-hlo", action="store_true",
        help="skip the xprof HLO-category split (CPU / no-xprof runs)",
    )
    parser.add_argument(
        "--perfetto-out", default=None, metavar="PATH",
        help="write the capture's flight-recorder spans as "
        "Chrome-trace/Perfetto JSON",
    )
    args = parser.parse_args()
    recorder = FlightRecorder()
    wall, xplane = capture(
        logdir=args.logdir, steps=args.steps, model=args.model,
        micro=args.micro, seq=args.seq, mesh_axes={"data": args.data},
        recorder=recorder,
    )
    summary = {
        "summary": True,
        "model": args.model,
        "wall_ms_per_step": round(wall * 1e3, 1),
    }
    if not args.no_hlo:
        rows, device_s = hlo_category_split(xplane)
        device_per_step = device_s / args.steps
        for r in rows:
            if r["self_time_pct"] >= 0.3:
                print(json.dumps(r))
        summary["device_ms_per_step"] = round(device_per_step * 1e3, 1)
        summary["device_busy_pct"] = round(100 * device_per_step / wall, 1)
    if args.perfetto_out:
        doc = recorder.export_chrome_trace()
        with open(args.perfetto_out, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        summary["perfetto_out"] = {
            "path": args.perfetto_out,
            "trace_events": len(doc["traceEvents"]),
        }
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
