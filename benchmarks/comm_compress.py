"""ZeRO++ comm-compression A/B: bytes on the wire + step time, 4 variants.

Compiles and times the real train step for the gpt-tiny model on an
8-device hybrid mesh (``data=4, fsdp=2, dcn_data=2`` — two simulated
2x2-device slices) at four compression levels:

- ``off``       — the GSPMD baseline (implicit fp32 collectives);
- ``qwz``       — int8 block-quantized weight all-gather;
- ``qwz_hpz``   — + the secondary int8 partition (gathers read
  pre-quantized codes; quantize leaves the microbatch hot path);
- ``qwz_hpz_qgz`` — + hierarchical int8 cross-slice gradient reduction.

For each variant it parses the compiled HLO and applies the standard ring
cost model per collective (``comm_compress.collective_stats``), splitting
the wire bytes into intra-slice (ICI) and cross-slice (DCN) using the
partition→slice map — the DCN column is the number that matters at
multislice scale — plus a wall-clock step time and the final-loss delta
versus the baseline over a short training run.

Run: ``python benchmarks/comm_compress.py [--steps 8]``
Prints one JSON line per variant + a summary line with the cross-slice
reduction factor. CPU-runnable (8 virtual devices) by design: byte
accounting is backend-independent, and wall-clock on CPU only shows the
quantize/dequantize overhead, not the DCN win it buys.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import json
import time

VARIANTS = (
    ("off", dict()),
    ("qwz", dict(comm_quant_weights=True)),
    ("qwz_hpz", dict(comm_quant_weights=True, comm_secondary_weights=True)),
    ("qwz_hpz_qgz", dict(comm_quant_weights=True, comm_secondary_weights=True,
                         comm_quant_grads=True)),
)


def build_program(extra: dict, model_name: str, block: int):
    from tpu_engine import train as tr
    from tpu_engine.mesh_runtime import MeshConfig, MeshRuntime
    from tpu_engine.sharding import TPUTrainConfig

    cfg = TPUTrainConfig(
        model_name=model_name,
        mesh=MeshConfig(data=4, fsdp=2, dcn_data=2),
        micro_batch_size=2, gradient_accumulation_steps=2, seq_len=64,
        precision="fp32", param_dtype="fp32",
        learning_rate=1e-2, warmup_steps=2, total_steps=100,
        sharding_stage=3, comm_quant_block_size=block,
        **extra,
    )
    runtime = MeshRuntime(cfg.mesh, slice_assignments=[0, 0, 0, 0, 1, 1, 1, 1])
    return tr.build_train_program(cfg, runtime=runtime)


def measure(prog, steps: int) -> dict:
    import jax

    from tpu_engine import comm_compress as cc

    state = prog.init(jax.random.PRNGKey(0))
    batch = prog.synthetic_batch(0)

    # Byte accounting from the compiled step's HLO.
    lowered = prog.step.lower(state, batch) if hasattr(prog.step, "lower") \
        else None
    stats = None
    if lowered is not None:
        hlo = lowered.compile().as_text()
        slice_of = cc.slice_of_partition(
            dict(prog.mesh.shape), prog.config.mesh.dcn_data
        )
        stats = cc.collective_stats(hlo, slice_of)

    # Short training run: loss trajectory + steady-state step time.
    losses = []
    t0 = None
    for i in range(steps):
        state, metrics = prog.step(state, batch)
        losses.append(float(metrics["loss"]))
        if i == 0:  # exclude compile from timing
            jax.block_until_ready(state["params"])
            t0 = time.perf_counter()
    jax.block_until_ready(state["params"])
    dt_ms = (time.perf_counter() - t0) / max(steps - 1, 1) * 1e3

    return {
        "final_loss": losses[-1],
        "first_loss": losses[0],
        "step_time_ms": round(dt_ms, 2),
        "total_wire_bytes": stats["total_wire_bytes"] if stats else None,
        "cross_slice_bytes": stats["cross_slice_bytes"] if stats else None,
        "n_collectives": len(stats["collectives"]) if stats else None,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--model", default="gpt-tiny")
    ap.add_argument("--block", type=int, default=64)
    args = ap.parse_args()

    if "--xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""
    ):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    if jax.device_count() < 8:
        raise SystemExit("needs 8 devices (set JAX_PLATFORMS=cpu for virtual)")

    results = {}
    for name, extra in VARIANTS:
        prog = build_program(extra, args.model, args.block)
        r = measure(prog, args.steps)
        results[name] = r
        print(json.dumps({"variant": name, **r}))
        del prog
        jax.clear_caches()

    base, full = results["off"], results["qwz_hpz_qgz"]
    summary = {
        "metric": "comm_compress_cross_slice_reduction",
        "value": round(base["cross_slice_bytes"] / max(full["cross_slice_bytes"], 1), 2)
        if base["cross_slice_bytes"] else None,
        "unit": "x fewer cross-slice bytes (qwz+hpz+qgz vs off)",
        "total_reduction": round(
            base["total_wire_bytes"] / max(full["total_wire_bytes"], 1), 2
        ) if base["total_wire_bytes"] else None,
        "final_loss_delta": round(
            abs(full["final_loss"] - base["final_loss"]), 4
        ),
        "mesh": "data=4 fsdp=2 dcn_data=2 (8 devices, 2 slices)",
        "backend": jax.default_backend(),
    }
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
