"""Shared AOT-compile plumbing for benchmarks and tpu_aot tests.

One canonical way to build the sharded train program against a described
TPU topology (libtpu compile-only — no chips needed) so the per-site
boilerplate (topology → MeshRuntime → build_train_program → eval_shape →
lower) doesn't drift across benchmarks/ and tests/.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp


def build_program(
    model: str,
    mesh_axes: dict[str, int],
    micro: int = 1,
    accum: int = 1,
    seq: int = 4096,
    overrides: Optional[dict[str, Any]] = None,
    devices=None,
):
    """The sharded train program for ``model`` on ``mesh_axes``.

    ``devices``: topology or runtime devices (defaults to the current
    backend's). ``overrides`` may carry any extra ``TPUTrainConfig``
    fields, plus ``sharding_stage``.
    """
    from tpu_engine.mesh_runtime import MeshConfig, MeshRuntime
    from tpu_engine.sharding import ShardingStage, TPUTrainConfig
    from tpu_engine.train import build_train_program

    overrides = dict(overrides or {})
    stage = overrides.pop("sharding_stage", ShardingStage.FULL_PARTITIONING)
    cfg = TPUTrainConfig(
        model_name=model,
        sharding_stage=stage,
        mesh=MeshConfig(**mesh_axes),
        micro_batch_size=micro,
        gradient_accumulation_steps=accum,
        seq_len=seq,
        **overrides,
    )
    runtime = MeshRuntime(cfg.mesh, devices=devices) if devices is not None else None
    return build_train_program(cfg, runtime=runtime)


def aot_lowered(
    model: str,
    topo_name: str,
    mesh_axes: dict[str, int],
    micro: int = 1,
    accum: int = 1,
    seq: int = 4096,
    overrides: Optional[dict[str, Any]] = None,
):
    """Lower the train step against a described TPU topology.

    Returns the ``Lowered`` step — call ``.compile()`` (optionally with
    ``compiler_options``) to get memory/cost analyses and HLO text.
    Raises whatever ``get_topology_desc`` raises when no libtpu is
    available; tests wrap this in a skip.
    """
    from jax.experimental import topologies

    topo = topologies.get_topology_desc(topo_name, platform="tpu")
    prog = build_program(model, mesh_axes, micro, accum, seq, overrides,
                         devices=topo.devices)
    state_shape = jax.eval_shape(prog.init, jax.random.PRNGKey(0))
    batch = jax.ShapeDtypeStruct(prog.global_batch_shape(), jnp.int32)
    return prog.step.lower(state_shape, batch)
