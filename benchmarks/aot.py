"""Shared AOT-compile plumbing for benchmarks and tpu_aot tests.

One canonical way to build the sharded train program against a described
TPU topology (libtpu compile-only — no chips needed) so the per-site
boilerplate (topology → MeshRuntime → build_train_program → eval_shape →
lower) doesn't drift across benchmarks/ and tests/.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from typing import Any, Optional

import jax
import jax.numpy as jnp


class TopologyUnavailable(Exception):
    """No libtpu / described-topology support in this environment.

    Tests catch THIS (and only this) to skip — so a real lowering or
    config regression still fails instead of silently skipping."""


def topology(topo_name: str):
    """Resolve a described TPU topology, or raise :class:`TopologyUnavailable`."""
    from jax.experimental import topologies

    try:
        return topologies.get_topology_desc(topo_name, platform="tpu")
    except Exception as e:
        raise TopologyUnavailable(f"{topo_name}: {e}") from e


def build_program(
    model: str,
    mesh_axes: dict[str, int],
    micro: int = 1,
    accum: int = 1,
    seq: int = 4096,
    overrides: Optional[dict[str, Any]] = None,
    devices=None,
):
    """The sharded train program for ``model`` on ``mesh_axes``.

    ``devices``: topology or runtime devices (defaults to the current
    backend's). ``overrides`` may carry any extra ``TPUTrainConfig``
    fields, plus ``sharding_stage``.
    """
    from tpu_engine.mesh_runtime import MeshConfig, MeshRuntime
    from tpu_engine.sharding import ShardingStage, TPUTrainConfig
    from tpu_engine.train import build_train_program

    overrides = dict(overrides or {})
    stage = overrides.pop("sharding_stage", ShardingStage.FULL_PARTITIONING)
    cfg = TPUTrainConfig(
        model_name=model,
        sharding_stage=stage,
        mesh=MeshConfig(**mesh_axes),
        micro_batch_size=micro,
        gradient_accumulation_steps=accum,
        seq_len=seq,
        **overrides,
    )
    runtime = MeshRuntime(cfg.mesh, devices=devices) if devices is not None else None
    return build_train_program(cfg, runtime=runtime)


def aot_lowered(
    model: str,
    topo_name: str,
    mesh_axes: dict[str, int],
    micro: int = 1,
    accum: int = 1,
    seq: int = 4096,
    overrides: Optional[dict[str, Any]] = None,
):
    """Lower the train step against a described TPU topology.

    Returns the ``Lowered`` step — call ``.compile()`` (optionally with
    ``compiler_options``) to get memory/cost analyses and HLO text.
    Raises :class:`TopologyUnavailable` when no libtpu is available —
    tests catch exactly that for their skip, so build/lowering failures
    still fail loudly.
    """
    topo = topology(topo_name)
    prog = build_program(model, mesh_axes, micro, accum, seq, overrides,
                         devices=topo.devices)
    state_shape = jax.eval_shape(prog.init, jax.random.PRNGKey(0))
    batch = jax.ShapeDtypeStruct(prog.global_batch_shape(), jnp.int32)
    return prog.step.lower(state_shape, batch)
