"""Chaos trace: MTTR + goodput under chip faults, self-heal vs die-and-restart.

Deterministic discrete-event comparison (virtual clock — no threads, no
sleeps, identical numbers every run) of the two recovery policies on the
same seeded chip-fault trace drawn from :meth:`FaultPlan.random`:

- **die-and-restart** — what the reference amounts to: an external monitor
  notices the dead job (poll latency), the gang waits for the failed chip
  to be replaced (a full mesh is required to restart), the job restarts
  from the last *periodic* checkpoint, re-running every step since it.
- **self-heal** — this repo's supervisor path: detection is in-band (the
  per-step health check), a synchronous emergency save persists the
  *current* step, the scheduler re-admits on an elastically shrunk mesh
  (throughput degrades ∝ chips while degraded, zero steps lost), and a
  grow-back preempt-resume restores the full mesh once the chip recovers.

Both policies pay the same per-event chip-recovery time; the difference is
what training does meanwhile. Reports per-fault MTTR (time from fault to
the next useful step) and goodput (useful full-mesh step-seconds per
wall-second); ``bench.py`` reuses :func:`run_trace` for its chaos line.

The self-heal resume overhead is split into admit + compile, and the
compile leg is priced through a real (in-memory) ``CompileCacheIndex``:
the first resume onto a given shrunk layout compiles cold, later resumes
onto a layout the index has seen are warm cache hits, and grow-backs pay
only the warm relink because the scheduler's background precompile runs
the cold compile off the critical path. The same trace is replayed with
the index off (every resume cold) — the on/off MTTR delta is the fleet
compile cache's headline number. Compile spans carry ``cache_hit`` so the
goodput lane's ``compile`` category splits warm vs cold.

With ``--trace-out PATH`` the self-heal run also records its lifecycle in
a ``FlightRecorder`` on the virtual clock — each fault's
detect → emergency-save → requeue → shrink-admit → resume (→ grow-back)
chain as causally-linked spans under one job trace — and writes it as
Chrome-trace/Perfetto JSON (load in ``ui.perfetto.dev``).

Run: ``JAX_PLATFORMS=cpu python -m benchmarks.chaos [--seed N]
[--trace-out /tmp/chaos_trace.json]``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu_engine import hetero as hetero_mod  # noqa: E402
from tpu_engine.compile_index import CompileCacheIndex  # noqa: E402
from tpu_engine.faults import (  # noqa: E402
    FaultInjector,
    FaultKind,
    FaultPlan,
    FaultSpec,
)
from tpu_engine.goodput import (  # noqa: E402
    CATEGORIES,
    GoodputLedger,
    SLOBurnRateAlerter,
)
from tpu_engine.tracing import FlightRecorder  # noqa: E402

# Model: 8-chip gang, fsdp=2 inner axis — a shrunk mesh must keep the
# model axis intact, so usable chips come in multiples of 2.
N_CHIPS = 8
MODEL_AXIS = 2
MIN_CHIPS = 2
TOTAL_STEPS = 1_000
STEP_TIME_S = 0.5          # full-mesh step time
CKPT_INTERVAL_STEPS = 100  # periodic checkpoint cadence (both policies)
CKPT_SAVE_S = 5.0          # synchronous save cost (periodic and emergency)
RESUME_ADMIT_S = 5.0       # requeue + re-admit on a live plane
COLD_COMPILE_S = 15.0      # XLA compile of a layout the cache has not seen
WARM_COMPILE_S = 1.5       # persistent-cache hit: deserialize + relink only
DIE_DETECT_S = 30.0        # external monitor poll latency (die-and-restart)
DIE_RESTART_S = 120.0      # cold restart: reschedule + init + compile
CHIP_RECOVERY_BASE_S = 60.0
CHIP_RECOVERY_PER_DURATION_S = 30.0


def chip_fault_trace(seed: int, n_faults: int = 12) -> list[dict]:
    """Chip-unhealthy events from a seeded plan: (step, device, recovery_s).

    Draws a larger random plan and keeps the chip faults — same seed,
    same trace, both policies replay it identically."""
    plan = FaultPlan.random(
        seed, n_faults=n_faults * 3, max_step=TOTAL_STEPS, n_devices=N_CHIPS
    )
    events, seen_steps = [], set()
    for s in plan.specs:
        if s.kind is not FaultKind.CHIP_UNHEALTHY or s.at_step is None:
            continue
        if s.at_step in seen_steps:  # one fault per step keeps both sims simple
            continue
        seen_steps.add(s.at_step)
        events.append({
            "step": int(s.at_step),
            "device": int(s.device_index or 0),
            "recovery_s": CHIP_RECOVERY_BASE_S
            + CHIP_RECOVERY_PER_DURATION_S * float(s.duration_steps or 1),
        })
    events.sort(key=lambda e: e["step"])
    return events[:n_faults]


def _usable(healthy: int) -> int:
    return max(MIN_CHIPS, (healthy // MODEL_AXIS) * MODEL_AXIS)


def _layout_key(use: int) -> str:
    """Index key for the shrunk-mesh layout running on ``use`` chips."""
    return f"chaos|data{use // MODEL_AXIS}xfsdp{MODEL_AXIS}"


def seed_initial_compile(index: CompileCacheIndex) -> None:
    """The job's own startup compile put the full-mesh layout in the cache."""
    index.record(
        _layout_key(N_CHIPS), COLD_COMPILE_S, cache_hit=False,
        label=_layout_key(N_CHIPS).split("|", 1)[1], model="chaos", via="chaos",
    )


def _resume_compile(index: Optional[CompileCacheIndex], use: int) -> tuple[float, bool]:
    """Compile cost of a shrink-resume onto ``use`` chips: (seconds, warm)."""
    if index is None:  # index off: a fresh process always compiles cold
        return COLD_COMPILE_S, False
    key = _layout_key(use)
    if index.is_warm(key):
        index.record(key, WARM_COMPILE_S, cache_hit=True, via="chaos")
        return WARM_COMPILE_S, True
    index.record(key, COLD_COMPILE_S, cache_hit=False,
                 label=key.split("|", 1)[1], model="chaos", via="chaos")
    return COLD_COMPILE_S, False


def _grow_compile(index: Optional[CompileCacheIndex], use: int) -> tuple[float, bool]:
    """Compile cost of a grow-back preempt-resume onto ``use`` chips.

    With the index on, the scheduler precompiles the target layout in the
    background *before* preempting (``precompile_before_grow``), so the
    cold compile never lands on the critical path — the resume pays only
    the warm relink either way; a never-seen layout is recorded as a
    background precompile."""
    if index is None:
        return COLD_COMPILE_S, False
    key = _layout_key(use)
    if not index.is_warm(key):
        index.record(key, COLD_COMPILE_S, cache_hit=False,
                     label=key.split("|", 1)[1], model="chaos",
                     via="precompile")
    index.record(key, WARM_COMPILE_S, cache_hit=True, via="chaos")
    return WARM_COMPILE_S, True


def simulate_self_heal(
    events: list[dict],
    recorder: Optional[FlightRecorder] = None,
    trace_id: Optional[str] = None,
    compile_index: Optional[CompileCacheIndex] = None,
) -> dict:
    clock = 0.0
    healthy = N_CHIPS
    pending: list[float] = []  # clocks at which a failed chip becomes healthy
    mttrs: list[float] = []
    grow_backs = 0
    degraded_s = 0.0
    warm_resumes = 0
    cold_resumes = 0
    compile_s_total = 0.0
    i = 0
    # Flight-recorder lane (virtual-clock timestamps — the recorder takes
    # explicit t0/t1 everywhere for exactly this). Each fault's recovery
    # chain links causally: detect -> emergency_save -> requeue ->
    # shrink_admit -> resume; a later grow_back chains off the resume.
    root = chain_tail = None
    if recorder is not None:
        trace_id = trace_id or recorder.new_trace_id()
        root = recorder.start_span(
            "job:chaos-self-heal", kind="job", trace_id=trace_id, t0=0.0,
            attrs={"n_chips": N_CHIPS, "total_steps": TOTAL_STEPS},
        )
    for step in range(1, TOTAL_STEPS + 1):
        # Grow back as soon as a chip has recovered: preempt-save-resume at
        # the larger mesh (the scheduler's _maybe_grow pass).
        while pending and pending[0] <= clock and healthy < N_CHIPS:
            pending.pop(0)
            healthy += 1
            if _usable(healthy) > _usable(healthy - 1):
                g_compile_s, g_warm = _grow_compile(compile_index, _usable(healthy))
                g_admit_end = clock + CKPT_SAVE_S + RESUME_ADMIT_S
                if recorder is not None:
                    recorder.record_span(
                        "grow_back", kind="admission", trace_id=trace_id,
                        parent=chain_tail or root, t0=clock, t1=g_admit_end,
                        attrs={"step": step, "mesh": _usable(healthy)},
                    )
                    recorder.record_span(
                        "compile", kind="compile", trace_id=trace_id,
                        parent=chain_tail or root, t0=g_admit_end,
                        t1=g_admit_end + g_compile_s,
                        attrs={"cache_hit": g_warm,
                               "compile_s": g_compile_s,
                               "layout": _layout_key(_usable(healthy))},
                    )
                clock = g_admit_end + g_compile_s
                compile_s_total += g_compile_s
                warm_resumes += 1 if g_warm else 0
                cold_resumes += 0 if g_warm else 1
                grow_backs += 1
        use = _usable(healthy)
        step_t = STEP_TIME_S * N_CHIPS / use
        clock += step_t
        if use < N_CHIPS:
            degraded_s += step_t
        if step % CKPT_INTERVAL_STEPS == 0:
            if recorder is not None:
                recorder.record_span(
                    "checkpoint_save", kind="checkpoint_save",
                    trace_id=trace_id, parent=root, t0=clock,
                    t1=clock + CKPT_SAVE_S, attrs={"step": step},
                )
            clock += CKPT_SAVE_S
        if i < len(events) and step >= events[i]["step"]:
            ev = events[i]
            i += 1
            healthy -= 1
            # Detection is the in-band health check on this very step;
            # emergency save persists `step`, shrink-resume follows. The
            # compile leg is warm iff the index has seen this layout.
            compile_s, warm = _resume_compile(compile_index, _usable(healthy))
            down = CKPT_SAVE_S + RESUME_ADMIT_S + compile_s
            admit_end = clock + CKPT_SAVE_S + RESUME_ADMIT_S
            if recorder is not None:
                detect = recorder.record_span(
                    "detect", kind="fault", trace_id=trace_id, parent=root,
                    t0=clock, t1=clock,
                    attrs={"step": step, "device": ev["device"]},
                )
                save = recorder.record_span(
                    "emergency_save", kind="emergency_save",
                    trace_id=trace_id, parent=detect, t0=clock,
                    t1=clock + CKPT_SAVE_S, attrs={"step": step},
                )
                requeue = recorder.record_span(
                    "requeue", kind="scheduler", trace_id=trace_id,
                    parent=save, t0=clock + CKPT_SAVE_S,
                    t1=clock + CKPT_SAVE_S, attrs={"step": step},
                )
                admit = recorder.record_span(
                    "shrink_admit", kind="admission", trace_id=trace_id,
                    parent=requeue, t0=clock + CKPT_SAVE_S, t1=admit_end,
                    attrs={"step": step, "mesh": _usable(healthy)},
                )
                comp = recorder.record_span(
                    "compile", kind="compile", trace_id=trace_id,
                    parent=admit, t0=admit_end, t1=admit_end + compile_s,
                    attrs={"cache_hit": warm, "compile_s": compile_s,
                           "layout": _layout_key(_usable(healthy))},
                )
                chain_tail = recorder.record_span(
                    "resume", kind="supervisor", trace_id=trace_id,
                    parent=comp, t0=clock + down, t1=clock + down,
                    attrs={"from_step": step},
                )
            clock += down
            compile_s_total += compile_s
            warm_resumes += 1 if warm else 0
            cold_resumes += 0 if warm else 1
            mttrs.append(step_t + down)
            pending.append(clock + ev["recovery_s"])
            pending.sort()
    wall = clock
    if root is not None:
        root.end(t1=wall, faults=len(mttrs), grow_backs=grow_backs)
    return {
        "policy": "self-heal",
        "compile_index": compile_index is not None,
        "wall_s": round(wall, 1),
        "steps_run": TOTAL_STEPS,
        "lost_steps": 0,
        "faults": len(mttrs),
        "grow_backs": grow_backs,
        "degraded_step_s": round(degraded_s, 1),
        "warm_resumes": warm_resumes,
        "cold_resumes": cold_resumes,
        "compile_s_total": round(compile_s_total, 1),
        "mttr_mean_s": round(sum(mttrs) / len(mttrs), 2) if mttrs else 0.0,
        "mttr_max_s": round(max(mttrs), 2) if mttrs else 0.0,
        "goodput": round(TOTAL_STEPS * STEP_TIME_S / wall, 4),
    }


def simulate_die_and_restart(events: list[dict]) -> dict:
    clock = 0.0
    step = 0
    last_ckpt = 0
    lost_steps = 0
    steps_run = 0
    mttrs: list[float] = []
    i = 0
    while step < TOTAL_STEPS:
        clock += STEP_TIME_S
        step += 1
        steps_run += 1
        if step % CKPT_INTERVAL_STEPS == 0:
            last_ckpt = step
            clock += CKPT_SAVE_S
        if i < len(events) and step >= events[i]["step"]:
            ev = events[i]
            i += 1  # each fault fires once, even though step rolls back
            lost = step - last_ckpt
            lost_steps += lost
            # Nothing runs until the chip is replaced (full mesh required),
            # then a cold restart replays everything since the checkpoint.
            down = DIE_DETECT_S + ev["recovery_s"] + DIE_RESTART_S
            clock += down
            mttrs.append(down + lost * STEP_TIME_S)
            step = last_ckpt
    wall = clock
    return {
        "policy": "die-and-restart",
        "wall_s": round(wall, 1),
        "steps_run": steps_run,
        "lost_steps": lost_steps,
        "faults": len(mttrs),
        "grow_backs": 0,
        "degraded_step_s": 0.0,
        "mttr_mean_s": round(sum(mttrs) / len(mttrs), 2) if mttrs else 0.0,
        "mttr_max_s": round(max(mttrs), 2) if mttrs else 0.0,
        "goodput": round(TOTAL_STEPS * STEP_TIME_S / wall, 4),
    }


# -- heterogeneous sharding lane ----------------------------------------------
# A second, independent trace: no chips die, but one host runs sustained-slow
# (a seeded faults.py HOST_SLOW plan). The synchronous gang gates every step
# on that host unless the heterogeneity plane (tpu_engine/hetero.py) reweights
# the per-process row assignment. Three policies replay the identical plan on
# the same virtual clock: rebalance-off (uniform rows forever), rebalance-on
# (a live HeteroRebalancer fed by the injector's host-slow signals), and
# shrink (evict the slow host, 7-chip uniform gang). Goodput here is measured
# against the *heterogeneous* ideal — every host contributing exactly its
# capacity — so rebalance can approach 1.0 while shrink, which throws the
# slow host's remaining 75% away, cannot.
HET_HOSTS = 8
HET_GLOBAL_MICRO = 128
HET_STEPS = 400
HET_TAIL_STEPS = 100       # steady-state window: the last N steps
HET_CHECK_EVERY = 10       # rebalance consult cadence (steps)
HET_SHRINK_AT_STEP = 25    # when the shrink policy evicts the slow host
# Reported per-step stall while uniformly loaded; the slow host's true rate
# is STEP/(STEP+stall) = 0.75 — the headline 25%-degraded host.
HET_SLOW_S = STEP_TIME_S / 3.0


def host_slow_plan(seed: int) -> FaultPlan:
    """Sustained host-slow on one seeded host: fires every step."""
    import random as _random

    host = _random.Random(seed).randrange(HET_HOSTS)
    return FaultPlan(seed=seed, specs=[
        FaultSpec(
            kind=FaultKind.HOST_SLOW, at_step=1, device_index=host,
            slow_s=round(HET_SLOW_S, 6), count=HET_STEPS,
        )
    ])


def simulate_hetero(
    policy: str,
    plan: FaultPlan,
    recorder: Optional[FlightRecorder] = None,
    trace_id: Optional[str] = None,
) -> dict:
    """Replay ``plan`` under one policy on the virtual clock.

    The injector is the only degradation source: a consumed HOST_SLOW spec
    both slows the simulated host (truth) and feeds the ThroughputTracker
    (signal) — exactly the supervisor's ``take_host_slow`` seam."""
    inj = FaultInjector(plan)
    inj.arm()
    rate = [1.0] * HET_HOSTS           # ground-truth relative rates
    rows_u = HET_GLOBAL_MICRO // HET_HOSTS
    vclock = 0.0
    tracker = hetero_mod.ThroughputTracker(HET_HOSTS)
    reb = hetero_mod.HeteroRebalancer(
        tracker, HET_GLOBAL_MICRO, dry_run=False, cooldown_s=30.0,
        min_gain=0.01, clock=lambda: vclock,
        recorder=recorder, trace_id=trace_id,
    )
    assignment = list(reb.assignment)
    active = list(range(HET_HOSTS))
    shrunk = False
    downtime_s = 0.0
    rebalance_step: Optional[int] = None
    ideal_wall = 0.0
    tail_wall = tail_ideal = 0.0
    for step in range(1, HET_STEPS + 1):
        spec = inj.take_host_slow(step)
        if spec is not None:
            idx = int(spec.device_index or 0)
            rate[idx] = STEP_TIME_S / (STEP_TIME_S + float(spec.slow_s))
            tracker.note_host_slow(idx, float(spec.slow_s), STEP_TIME_S)
        if policy == "shrink" and not shrunk and step >= HET_SHRINK_AT_STEP:
            # Evict the slow host: emergency save + re-admit + cold compile,
            # then a 7-host uniform gang carries the full global batch.
            shrunk = True
            slow_host = min(range(HET_HOSTS), key=lambda h: rate[h])
            active = [h for h in range(HET_HOSTS) if h != slow_host]
            assignment = hetero_mod.uniform_assignment(
                HET_GLOBAL_MICRO, len(active)
            )
            downtime_s = CKPT_SAVE_S + RESUME_ADMIT_S + COLD_COMPILE_S
            vclock += downtime_s
        # Synchronous gang: the step ends when the slowest member finishes
        # its rows; a host's nominal pace is rows_u rows per STEP_TIME_S.
        step_s = max(
            assignment[j] * STEP_TIME_S / (rows_u * rate[h])
            for j, h in enumerate(active)
        )
        ideal_s = HET_GLOBAL_MICRO * STEP_TIME_S / (rows_u * sum(rate))
        vclock += step_s
        ideal_wall += ideal_s
        tracker.observe_step(step_s)
        if policy == "rebalance-on" and step % HET_CHECK_EVERY == 0:
            r_plan = reb.maybe_rebalance(step)
            if r_plan is not None:
                assignment = list(r_plan.assignment)
                if rebalance_step is None:
                    rebalance_step = step
        if step > HET_STEPS - HET_TAIL_STEPS:
            tail_wall += step_s
            tail_ideal += ideal_s
    return {
        "policy": policy,
        "wall_s": round(vclock, 1),
        "ideal_wall_s": round(ideal_wall, 1),
        "downtime_s": round(downtime_s, 1),
        "goodput": round(ideal_wall / vclock, 4),
        "steady_goodput": round(tail_ideal / tail_wall, 4),
        "assignment": list(assignment),
        "active_hosts": len(active),
        "rebalance_step": rebalance_step,
        "rebalancer": reb.stats() if policy == "rebalance-on" else None,
    }


def run_hetero_lane(
    seed: int = 0, recorder: Optional[FlightRecorder] = None
) -> dict:
    """Rebalance-on vs rebalance-off vs shrink on one seeded slow-host plan."""
    plan = host_slow_plan(seed)
    trace_id = recorder.new_trace_id() if recorder is not None else None
    on = simulate_hetero("rebalance-on", plan, recorder=recorder,
                         trace_id=trace_id)
    off = simulate_hetero("rebalance-off", plan)
    shrink = simulate_hetero("shrink", plan)
    return {
        "seed": seed,
        "params": {
            "n_hosts": HET_HOSTS,
            "global_micro": HET_GLOBAL_MICRO,
            "steps": HET_STEPS,
            "slow_host_rate": round(
                STEP_TIME_S / (STEP_TIME_S + HET_SLOW_S), 4
            ),
            "slow_host": int(plan.specs[0].device_index or 0),
            "check_every_steps": HET_CHECK_EVERY,
        },
        "rebalance_on": on,
        "rebalance_off": off,
        "shrink": shrink,
        "steady_goodput_on": on["steady_goodput"],
        "steady_goodput_off": off["steady_goodput"],
        "steady_goodput_shrink": shrink["steady_goodput"],
        "goodput_recovered": round(
            on["steady_goodput"] - off["steady_goodput"], 4
        ),
    }


def goodput_lane(
    recorder: FlightRecorder, trace_id: str, wall: float
) -> dict:
    """Account the self-heal trace through the REAL goodput ledger (the
    same decomposition live submissions get), then replay the SLO
    burn-rate alerter over the run's virtual clock.

    The fault plan is deterministic, so the alert progression is too:
    the clean head of the run evaluates ok, the first fault cluster
    burns the short+long windows past ``warning_burn``, and the
    sustained degraded tail past ``page_burn``. Alert transitions land
    as ``slo_alert`` events on the recorder's ``fleet`` timeline and
    per-window counter samples as a Perfetto counter track — both ride
    the same Chrome-trace export as the recovery chains they explain."""
    ledger = GoodputLedger(clock=lambda: wall, bucket_s=60.0,
                           history_buckets=256)
    ledger.track(trace_id, tenant="chaos", workload="training",
                 full_gang=N_CHIPS)
    d = ledger.finalize(recorder, trace_id, now=wall)
    assert d is not None
    cats = d["categories"]
    sum_error_pct = abs(sum(cats.values()) - d["wall_s"]) / d["wall_s"] * 100
    alerter = SLOBurnRateAlerter(
        ledger,
        goodput_target=0.88,
        short_window_s=120.0,
        long_window_s=600.0,
        warning_burn=1.5,
        page_burn=3.0,
        recorder=recorder,
        clock=lambda: wall,
    )
    progression = ["ok"]
    t = 0.0
    while t <= wall + 60.0:
        out = alerter.evaluate(now=t)
        g = out["goodput"]
        if g["state"] != progression[-1]:
            progression.append(g["state"])
        recorder.counter(
            "goodput_burn",
            {
                "goodput_fraction_short": g["short_fraction"] or 1.0,
                "burn_short": g["short_burn"] or 0.0,
                "burn_long": g["long_burn"] or 0.0,
            },
            trace_id=trace_id,
            ts=t,
        )
        t += 60.0
    split = d.get("compile_split") or {}
    return {
        "breakdown_s": {c: round(cats[c], 2) for c in CATEGORIES},
        "breakdown_pct": {
            c: round(100.0 * cats[c] / d["wall_s"], 2) for c in CATEGORIES
        },
        "compile_split_s": {
            "warm_s": round(float(split.get("warm_s", 0.0)), 2),
            "cold_s": round(float(split.get("cold_s", 0.0)), 2),
        },
        "wall_s": round(d["wall_s"], 1),
        "goodput_fraction": round(d["goodput_fraction"], 4),
        "sum_error_pct": round(sum_error_pct, 6),
        "slo": {
            "target": alerter.goodput_target,
            "warning_burn": alerter.warning_burn,
            "page_burn": alerter.page_burn,
            "progression": progression,
            "alert_count": len(alerter.alerts),
            "alerts": list(alerter.alerts),
        },
    }


def run_trace(
    seed: int = 0,
    n_faults: int = 12,
    recorder: Optional[FlightRecorder] = None,
) -> dict:
    # The goodput lane needs the recorded spans even when the caller does
    # not want a trace export — record into a private recorder then.
    recorder = recorder or FlightRecorder()
    trace_id = recorder.new_trace_id()
    events = chip_fault_trace(seed, n_faults=n_faults)
    # Primary lane: compile index ON (a real in-memory CompileCacheIndex,
    # pre-seeded with the job's own startup compile). The same trace is
    # replayed with the index OFF — every resume pays the cold compile.
    index = CompileCacheIndex(path=None, default_cold_s=COLD_COMPILE_S)
    seed_initial_compile(index)
    heal = simulate_self_heal(
        events, recorder=recorder, trace_id=trace_id, compile_index=index
    )
    heal_off = simulate_self_heal(events, compile_index=None)
    die = simulate_die_and_restart(events)
    goodput = goodput_lane(recorder, trace_id, heal["wall_s"])
    mttr_on = heal["mttr_mean_s"]
    mttr_off = heal_off["mttr_mean_s"]
    return {
        "seed": seed,
        "params": {
            "n_chips": N_CHIPS,
            "model_axis": MODEL_AXIS,
            "total_steps": TOTAL_STEPS,
            "step_time_s": STEP_TIME_S,
            "ckpt_interval_steps": CKPT_INTERVAL_STEPS,
            "resume_admit_s": RESUME_ADMIT_S,
            "cold_compile_s": COLD_COMPILE_S,
            "warm_compile_s": WARM_COMPILE_S,
        },
        "fault_events": events,
        "self_heal": heal,
        "self_heal_index_off": heal_off,
        "die_and_restart": die,
        "goodput": goodput,
        "goodput_improvement": round(heal["goodput"] / die["goodput"], 3),
        "mttr_reduction": round(
            die["mttr_mean_s"] / mttr_on, 3
        ) if mttr_on else None,
        "steps_saved": die["lost_steps"],
        "compile_cache": {
            "mttr_on_s": mttr_on,
            "mttr_off_s": mttr_off,
            "mttr_warm_reduction_pct": round(
                100.0 * (1.0 - mttr_on / mttr_off), 2
            ) if mttr_off else 0.0,
            "warm_resumes": heal["warm_resumes"],
            "cold_resumes": heal["cold_resumes"],
            "wall_saved_s": round(heal_off["wall_s"] - heal["wall_s"], 1),
            "index": index.stats(),
        },
    }


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--faults", type=int, default=12)
    parser.add_argument(
        "--trace-out", default=None, metavar="PATH",
        help="write the self-heal run as Chrome-trace/Perfetto JSON",
    )
    args = parser.parse_args()
    recorder = FlightRecorder() if args.trace_out else None
    trace = run_trace(args.seed, n_faults=args.faults, recorder=recorder)
    trace["hetero"] = run_hetero_lane(args.seed, recorder=recorder)
    if recorder is not None:
        doc = recorder.export_chrome_trace()
        with open(args.trace_out, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        trace["trace_out"] = {
            "path": args.trace_out,
            "trace_events": len(doc["traceEvents"]),
        }
    print(json.dumps(trace, indent=2))
    gp = trace["goodput"]
    cc = trace["compile_cache"]
    ok = (
        trace["self_heal"]["lost_steps"] == 0
        and trace["goodput_improvement"] > 1.0
        and (trace["mttr_reduction"] or 0.0) > 1.0
        # The warm-start index must beat the index-off lane outright.
        and cc["mttr_on_s"] < cc["mttr_off_s"]
        # Ledger invariant: the category breakdown re-derives the wall
        # clock from spans alone — must sum to it within 1%.
        and gp["sum_error_pct"] < 1.0
        # The seeded fault plan drives the alerter through a full
        # escalation before anything else happens.
        and gp["slo"]["progression"][:3] == ["ok", "warning", "page"]
    )
    print(json.dumps({
        "metric": "chaos_goodput_self_heal_vs_die_restart",
        "value": trace["goodput_improvement"],
        "unit": "x goodput under faults (die-and-restart = 1.0)",
        "mttr_reduction": trace["mttr_reduction"],
        "zero_lost_steps": trace["self_heal"]["lost_steps"] == 0,
        "ok": ok,
    }))
    print(json.dumps({
        "metric": "chaos_compile_cache_warm_start",
        "value": cc["mttr_warm_reduction_pct"],
        "unit": "% MTTR reduction, compile index on vs off",
        "mttr_on_s": cc["mttr_on_s"],
        "mttr_off_s": cc["mttr_off_s"],
        "warm_resumes": cc["warm_resumes"],
        "cold_resumes": cc["cold_resumes"],
        "wall_saved_s": cc["wall_saved_s"],
        "ok": ok,
    }))
    print(json.dumps({
        "metric": "chaos_goodput_breakdown",
        "value": gp["goodput_fraction"],
        "unit": "productive fraction of self-heal wall clock",
        "breakdown_pct": gp["breakdown_pct"],
        "sum_error_pct": gp["sum_error_pct"],
        "slo_progression": gp["slo"]["progression"],
        "alert_count": gp["slo"]["alert_count"],
        "ok": ok,
    }))
    het = trace["hetero"]
    het_ok = (
        # Headline: the rebalanced gang retains >= 90% of the heterogeneous
        # ideal on a 25%-degraded host...
        het["steady_goodput_on"] >= 0.90
        # ...while the uniform gang gates on the slow host...
        and het["steady_goodput_off"] <= 0.80
        # ...and beats shrinking, which discards the host's remaining 75%.
        and het["steady_goodput_on"] > het["steady_goodput_shrink"]
        # The rebalance preserved the declared global batch exactly.
        and sum(het["rebalance_on"]["assignment"]) == HET_GLOBAL_MICRO
    )
    print(json.dumps({
        "metric": "chaos_hetero_rebalance_goodput",
        "value": het["steady_goodput_on"],
        "unit": "steady-state goodput fraction of heterogeneous ideal",
        "rebalance_off": het["steady_goodput_off"],
        "shrink": het["steady_goodput_shrink"],
        "goodput_recovered": het["goodput_recovered"],
        "assignment": het["rebalance_on"]["assignment"],
        "ok": het_ok,
    }))
    if not (ok and het_ok):
        raise SystemExit(1)


if __name__ == "__main__":
    main()
