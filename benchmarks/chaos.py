"""Chaos scenario: MTTR + goodput under chip faults, self-heal vs die-restart.

Thin scenario definition over the digital twin (``tpu_engine/twin.py``):
the seeded chip-fault timeline, the self-heal / die-and-restart / hetero
policy lanes, the goodput + SLO accounting, and this file's CLI flags,
exit gates and JSON metric lines are unchanged from the pre-twin
benchmark — but the virtual-clock engine, the recovery-chain recording,
and the goodput lane now live in the twin, shared with the other sims
and with trace replay.

Deterministic discrete-event comparison (virtual clock — no threads, no
sleeps, identical numbers every run) of two recovery policies on the
same seeded chip-fault trace drawn from :meth:`FaultPlan.random`:

- **die-and-restart** — what the reference amounts to: an external monitor
  notices the dead job (poll latency), the gang waits for the failed chip
  to be replaced, the job restarts from the last *periodic* checkpoint.
- **self-heal** — this repo's supervisor path: in-band detection, a
  synchronous emergency save, re-admission on an elastically shrunk mesh
  (zero steps lost), and a grow-back once the chip recovers.

The self-heal compile leg is priced through a real (in-memory)
``CompileCacheIndex`` — the on/off MTTR delta is the fleet compile
cache's headline number. A second lane replays a seeded HOST_SLOW plan
under rebalance-on / rebalance-off / shrink (``tpu_engine/hetero.py``).

With ``--trace-out PATH`` the self-heal run also records its lifecycle
(detect → emergency-save → requeue → shrink-admit → resume chains) as
Chrome-trace/Perfetto JSON; with ``--trace-jsonl PATH`` the recorder
persists JSONL the twin can re-ingest (``POST /api/v1/twin/replay``).

Run: ``JAX_PLATFORMS=cpu python -m benchmarks.chaos [--seed N]
[--trace-out /tmp/chaos_trace.json]``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu_engine import twin as twin_mod  # noqa: E402
from tpu_engine.compile_index import CompileCacheIndex  # noqa: E402
from tpu_engine.faults import FaultPlan  # noqa: E402
from tpu_engine.tracing import FlightRecorder  # noqa: E402
from tpu_engine.twin import (  # noqa: E402
    HeteroTwinParams,
    TrainTwinParams,
)

# The shipped scenario parameters; the twin's dataclasses carry them, the
# module-level constants remain the stable public surface tests import.
PARAMS = TrainTwinParams()
HET_PARAMS = HeteroTwinParams()

N_CHIPS = PARAMS.n_chips
MODEL_AXIS = PARAMS.model_axis
MIN_CHIPS = PARAMS.min_chips
TOTAL_STEPS = PARAMS.total_steps
STEP_TIME_S = PARAMS.step_time_s
CKPT_INTERVAL_STEPS = PARAMS.ckpt_interval_steps
CKPT_SAVE_S = PARAMS.ckpt_save_s
RESUME_ADMIT_S = PARAMS.resume_admit_s
COLD_COMPILE_S = PARAMS.cold_compile_s
WARM_COMPILE_S = PARAMS.warm_compile_s
DIE_DETECT_S = PARAMS.die_detect_s
DIE_RESTART_S = PARAMS.die_restart_s
CHIP_RECOVERY_BASE_S = PARAMS.chip_recovery_base_s
CHIP_RECOVERY_PER_DURATION_S = PARAMS.chip_recovery_per_duration_s

HET_HOSTS = HET_PARAMS.hosts
HET_GLOBAL_MICRO = HET_PARAMS.global_micro
HET_STEPS = HET_PARAMS.steps
HET_TAIL_STEPS = HET_PARAMS.tail_steps
HET_CHECK_EVERY = HET_PARAMS.check_every
HET_SHRINK_AT_STEP = HET_PARAMS.shrink_at_step
HET_SLOW_S = HET_PARAMS.slow_s


def chip_fault_trace(seed: int, n_faults: int = 12) -> list[dict]:
    """Chip-unhealthy events from a seeded plan: (step, device, recovery_s)."""
    return twin_mod.chip_fault_timeline(seed, n_faults=n_faults, params=PARAMS)


def seed_initial_compile(index: CompileCacheIndex) -> None:
    """The job's own startup compile put the full-mesh layout in the cache."""
    twin_mod.seed_initial_compile(index, PARAMS)


def simulate_self_heal(
    events: list[dict],
    recorder: Optional[FlightRecorder] = None,
    trace_id: Optional[str] = None,
    compile_index: Optional[CompileCacheIndex] = None,
) -> dict:
    return twin_mod.replay_self_heal(
        events, PARAMS, recorder=recorder, trace_id=trace_id,
        compile_index=compile_index,
    )


def simulate_die_and_restart(events: list[dict]) -> dict:
    return twin_mod.replay_die_and_restart(events, PARAMS)


def host_slow_plan(seed: int) -> FaultPlan:
    """Sustained host-slow on one seeded host: fires every step."""
    return twin_mod.host_slow_plan(seed, HET_PARAMS)


def simulate_hetero(
    policy: str,
    plan: FaultPlan,
    recorder: Optional[FlightRecorder] = None,
    trace_id: Optional[str] = None,
) -> dict:
    return twin_mod.replay_hetero(
        policy, plan, HET_PARAMS, recorder=recorder, trace_id=trace_id
    )


def run_hetero_lane(
    seed: int = 0, recorder: Optional[FlightRecorder] = None
) -> dict:
    """Rebalance-on vs rebalance-off vs shrink on one seeded slow-host plan."""
    return twin_mod.run_hetero_ab(seed, HET_PARAMS, recorder=recorder)


def run_autopilot_lane(seed: int = 0) -> dict:
    """Autopilot armed vs off vs dry-run on the seeded slow-host chaos
    plan (see :func:`tpu_engine.twin.autopilot_lane`)."""
    return twin_mod.autopilot_lane(seed, HET_PARAMS)


def goodput_lane(
    recorder: FlightRecorder, trace_id: str, wall: float
) -> dict:
    """Account the self-heal trace through the REAL goodput ledger + SLO
    burn-rate alerter (see :func:`tpu_engine.twin.goodput_lane`)."""
    return twin_mod.goodput_lane(recorder, trace_id, wall, full_gang=N_CHIPS)


def run_trace(
    seed: int = 0,
    n_faults: int = 12,
    recorder: Optional[FlightRecorder] = None,
) -> dict:
    # The goodput lane needs the recorded spans even when the caller does
    # not want a trace export — record into a private recorder then.
    recorder = recorder or FlightRecorder()
    trace_id = recorder.new_trace_id()
    events = chip_fault_trace(seed, n_faults=n_faults)
    # Primary lane: compile index ON (a real in-memory CompileCacheIndex,
    # pre-seeded with the job's own startup compile). The same trace is
    # replayed with the index OFF — every resume pays the cold compile.
    index = CompileCacheIndex(path=None, default_cold_s=COLD_COMPILE_S)
    seed_initial_compile(index)
    heal = simulate_self_heal(
        events, recorder=recorder, trace_id=trace_id, compile_index=index
    )
    heal_off = simulate_self_heal(events, compile_index=None)
    die = simulate_die_and_restart(events)
    goodput = goodput_lane(recorder, trace_id, heal["wall_s"])
    mttr_on = heal["mttr_mean_s"]
    mttr_off = heal_off["mttr_mean_s"]
    return {
        "seed": seed,
        "params": {
            "n_chips": N_CHIPS,
            "model_axis": MODEL_AXIS,
            "total_steps": TOTAL_STEPS,
            "step_time_s": STEP_TIME_S,
            "ckpt_interval_steps": CKPT_INTERVAL_STEPS,
            "resume_admit_s": RESUME_ADMIT_S,
            "cold_compile_s": COLD_COMPILE_S,
            "warm_compile_s": WARM_COMPILE_S,
        },
        "fault_events": events,
        "self_heal": heal,
        "self_heal_index_off": heal_off,
        "die_and_restart": die,
        "goodput": goodput,
        "goodput_improvement": round(heal["goodput"] / die["goodput"], 3),
        "mttr_reduction": round(
            die["mttr_mean_s"] / mttr_on, 3
        ) if mttr_on else None,
        "steps_saved": die["lost_steps"],
        "compile_cache": {
            "mttr_on_s": mttr_on,
            "mttr_off_s": mttr_off,
            "mttr_warm_reduction_pct": round(
                100.0 * (1.0 - mttr_on / mttr_off), 2
            ) if mttr_off else 0.0,
            "warm_resumes": heal["warm_resumes"],
            "cold_resumes": heal["cold_resumes"],
            "wall_saved_s": round(heal_off["wall_s"] - heal["wall_s"], 1),
            "index": index.stats(),
        },
    }


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--faults", type=int, default=12)
    parser.add_argument(
        "--trace-out", default=None, metavar="PATH",
        help="write the self-heal run as Chrome-trace/Perfetto JSON",
    )
    parser.add_argument(
        "--trace-jsonl", default=None, metavar="PATH",
        help="persist the recorder as JSONL the twin can re-ingest",
    )
    args = parser.parse_args()
    recorder = None
    if args.trace_out or args.trace_jsonl:
        recorder = FlightRecorder(persist_path=args.trace_jsonl or None)
    trace = run_trace(args.seed, n_faults=args.faults, recorder=recorder)
    trace["hetero"] = run_hetero_lane(args.seed, recorder=recorder)
    trace["autopilot"] = run_autopilot_lane(args.seed)
    if recorder is not None and args.trace_out:
        doc = recorder.export_chrome_trace()
        with open(args.trace_out, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        trace["trace_out"] = {
            "path": args.trace_out,
            "trace_events": len(doc["traceEvents"]),
        }
    print(json.dumps(trace, indent=2))
    gp = trace["goodput"]
    cc = trace["compile_cache"]
    ok = (
        trace["self_heal"]["lost_steps"] == 0
        and trace["goodput_improvement"] > 1.0
        and (trace["mttr_reduction"] or 0.0) > 1.0
        # The warm-start index must beat the index-off lane outright.
        and cc["mttr_on_s"] < cc["mttr_off_s"]
        # Ledger invariant: the category breakdown re-derives the wall
        # clock from spans alone — must sum to it within 1%.
        and gp["sum_error_pct"] < 1.0
        # The seeded fault plan drives the alerter through a full
        # escalation before anything else happens.
        and gp["slo"]["progression"][:3] == ["ok", "warning", "page"]
    )
    print(json.dumps({
        "metric": "chaos_goodput_self_heal_vs_die_restart",
        "value": trace["goodput_improvement"],
        "unit": "x goodput under faults (die-and-restart = 1.0)",
        "mttr_reduction": trace["mttr_reduction"],
        "zero_lost_steps": trace["self_heal"]["lost_steps"] == 0,
        "ok": ok,
    }))
    print(json.dumps({
        "metric": "chaos_compile_cache_warm_start",
        "value": cc["mttr_warm_reduction_pct"],
        "unit": "% MTTR reduction, compile index on vs off",
        "mttr_on_s": cc["mttr_on_s"],
        "mttr_off_s": cc["mttr_off_s"],
        "warm_resumes": cc["warm_resumes"],
        "cold_resumes": cc["cold_resumes"],
        "wall_saved_s": cc["wall_saved_s"],
        "ok": ok,
    }))
    print(json.dumps({
        "metric": "chaos_goodput_breakdown",
        "value": gp["goodput_fraction"],
        "unit": "productive fraction of self-heal wall clock",
        "breakdown_pct": gp["breakdown_pct"],
        "sum_error_pct": gp["sum_error_pct"],
        "slo_progression": gp["slo"]["progression"],
        "alert_count": gp["slo"]["alert_count"],
        "ok": ok,
    }))
    het = trace["hetero"]
    het_ok = (
        # Headline: the rebalanced gang retains >= 90% of the heterogeneous
        # ideal on a 25%-degraded host...
        het["steady_goodput_on"] >= 0.90
        # ...while the uniform gang gates on the slow host...
        and het["steady_goodput_off"] <= 0.80
        # ...and beats shrinking, which discards the host's remaining 75%.
        and het["steady_goodput_on"] > het["steady_goodput_shrink"]
        # The rebalance preserved the declared global batch exactly.
        and sum(het["rebalance_on"]["assignment"]) == HET_GLOBAL_MICRO
    )
    print(json.dumps({
        "metric": "chaos_hetero_rebalance_goodput",
        "value": het["steady_goodput_on"],
        "unit": "steady-state goodput fraction of heterogeneous ideal",
        "rebalance_off": het["steady_goodput_off"],
        "shrink": het["steady_goodput_shrink"],
        "goodput_recovered": het["goodput_recovered"],
        "assignment": het["rebalance_on"]["assignment"],
        "ok": het_ok,
    }))
    ap = trace["autopilot"]
    # The lane's own gates already cover: armed goodput >= off, the armed
    # loop drained exactly the seeded slow host, dry-run produced the
    # decision stream with zero actuations, every decision carries
    # historian query inputs + incident links, and the correlator holds
    # the decision as the incident's action leg with the right source.
    ap_ok = ap["ok"] and ap["steady_goodput_on"] >= ap["steady_goodput_off"]
    print(json.dumps({
        "metric": "chaos_autopilot_goodput",
        "value": ap["steady_goodput_on"],
        "unit": "steady-state chaos goodput, autopilot armed (off = baseline)",
        "autopilot_off": ap["steady_goodput_off"],
        "autopilot_dry_run": ap["steady_goodput_dry"],
        "goodput_recovered": ap["goodput_recovered"],
        "decisions_armed": ap["armed"]["decisions_total"],
        "actuations_armed": ap["armed"]["actuations_total"],
        "actuations_dry_run": ap["dry_run"]["actuations_total"],
        "gates": ap["gates"],
        "ok": ap_ok,
    }))
    if not (ok and het_ok and ap_ok):
        raise SystemExit(1)


if __name__ == "__main__":
    main()
