"""Single-chip MFU sweep: remat policy × loss chunking × micro-batch for
the llama-1b headline config. Each variant runs in a fresh subprocess so
HBM fragmentation / leaked buffers from one config can't skew the next.

Usage: python benchmarks/mfu_sweep.py            # run all variants
       python benchmarks/mfu_sweep.py --one KEY  # child mode (internal)
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import json
import subprocess
import time

VARIANTS: dict[str, dict] = {
    # round-1 headline (51.35% driver-captured)
    "mb8-nothing": dict(micro_batch_size=8, remat_policy="nothing_saveable"),
    "mb8-attn": dict(micro_batch_size=8, remat_policy="save_attn_out"),
    "mb8-qkv": dict(micro_batch_size=8, remat_policy="save_qkv_attn_out"),
    "mb8-dots": dict(micro_batch_size=8, remat_policy="dots_with_no_batch_dims_saveable"),
    "mb8-chunk512": dict(micro_batch_size=8, loss_chunk_size=512),
    "mb12-chunk512": dict(micro_batch_size=12, loss_chunk_size=512),
    "mb16-chunk512": dict(micro_batch_size=16, loss_chunk_size=512),
    "mb16-chunk512-qkv": dict(micro_batch_size=16, loss_chunk_size=512,
                              remat_policy="save_qkv_attn_out"),
    "mb4-noremat": dict(micro_batch_size=4, activation_checkpointing=False),
    "mb6-noremat-chunk512": dict(micro_batch_size=6,
                                 activation_checkpointing=False,
                                 loss_chunk_size=512),
    # bf16 Adam first moment frees ~2 GiB of state at 1B params — the
    # lever that brings the mb8 configs back inside the (tightened)
    # runtime memory envelope.
    "mb8-mubf16": dict(micro_batch_size=8, moment_dtype="bf16"),
    "mb8-mubf16-chunk512": dict(micro_batch_size=8, moment_dtype="bf16",
                                loss_chunk_size=512),
    "mb6-mubf16": dict(micro_batch_size=6, moment_dtype="bf16"),
    "mb4-plain": dict(micro_batch_size=4),
}


def run_one(key: str) -> None:
    import jax

    from tpu_engine.mesh_runtime import MeshConfig, MeshRuntime
    from tpu_engine.models import transformer as tfm
    from tpu_engine.profiler import peak_flops_per_chip
    from tpu_engine.sharding import ShardingStage, TPUTrainConfig
    from tpu_engine.train import build_train_program

    over = dict(VARIANTS[key])
    base = dict(
        model_name="llama-1b", sharding_stage=ShardingStage.DISABLED,
        mesh=MeshConfig(data=1), seq_len=2048, attention_impl="auto",
        precision="bf16", activation_checkpointing=True,
    )
    base.update(over)
    cfg = TPUTrainConfig(**base)
    program = build_train_program(cfg, runtime=MeshRuntime(cfg.mesh))
    state = program.init(jax.random.PRNGKey(0))
    batch = program.synthetic_batch(seed=0)
    for _ in range(2):
        state, metrics = program.step(state, batch)
    float(metrics["loss"])
    iters = 10
    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = program.step(state, batch)
    float(metrics["loss"])
    dt = (time.perf_counter() - t0) / iters
    accum, gmicro, seq = program.global_batch_shape()
    tps = accum * gmicro * seq / dt
    fpt = tfm.train_flops_per_token(program.model_config, cfg.seq_len)
    peak = peak_flops_per_chip(jax.devices()[0]) or 197e12
    print(json.dumps({
        "variant": key, "mfu_pct": round(100 * tps * fpt / peak, 2),
        "tokens_per_sec": round(tps, 1), "step_ms": round(dt * 1e3, 1),
    }))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--one")
    args = ap.parse_args()
    if args.one:
        run_one(args.one)
        return 0
    for key in VARIANTS:
        for attempt in range(3):
            out = subprocess.run(
                [sys.executable, __file__, "--one", key],
                capture_output=True, text=True, timeout=900, env=os.environ,
            )
            if out.returncode == 0:
                print(out.stdout.strip().splitlines()[-1], flush=True)
                break
            err = out.stderr + out.stdout
            # The tunnel's remote-compile service 500s transiently; a real
            # OOM ("Ran out of memory") is permanent — don't retry those.
            if "Ran out of memory" in err or attempt == 2:
                import re

                m = re.search(r"Ran out of memory[^\n]*", err)
                m2 = re.search(r"\w+Error: [^\n]*", err)
                short = (m.group(0) if m else m2.group(0) if m2 else err[-180:])[:180]
                print(json.dumps({"variant": key, "error": short}), flush=True)
                break
            time.sleep(15)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
