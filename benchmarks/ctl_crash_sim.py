"""Durable control plane A/B: crash + journal restore vs no-crash run.

Runs :func:`tpu_engine.twin.ctl_crash_ab` — the same seeded storm
(training submissions, chaos preemptions from ``FaultPlan.random``,
serving traffic over capacity) through the REAL FleetScheduler +
ServingFleet, write-ahead journaled to a
:class:`~tpu_engine.journal.ControlPlaneJournal`, with a
``FaultKind.CONTROLPLANE_CRASH`` consumed mid-storm: the scheduler and
fleet objects are dropped on the floor (torn half-written journal line
included), live reality diverges (every third running training job and
one replica die with the host, the rest keep running orphaned), and
fresh objects recover via ``FleetScheduler.restore`` +
``ServingFleet.re_adopt`` (``JAX_PLATFORMS=cpu python -m
benchmarks.ctl_crash_sim``).

Exit gates (process exits 1 when any fails):

- ``zero_lost_submissions`` — every job the dead process had accepted
  completes after recovery;
- ``zero_duplicated_submissions`` — no accepted job is re-launched as a
  second submission;
- ``held_requests_complete`` — every serving request accepted before the
  kill (done, in-flight, or still queued) is answered;
- ``orphans_readopted`` — still-running jobs are re-adopted from
  ``live_jobs``, never restarted;
- ``vanished_training_requeued`` — jobs that died with the host requeue
  at their original seq;
- ``vanished_replica_redispatched`` — the dead replica is replaced up to
  the journaled desired count;
- ``no_phantom_double_grants`` — re-entered HBM reservations stay within
  device capacity (the double-grant audit finds nothing on a consistent
  journal);
- ``double_recovery_identical`` — two restores from the same journal
  bytes produce byte-identical ``snapshot_state()`` digests;
- ``torn_tail_skipped_not_raised`` — the mid-append torn line is counted
  and skipped, never raised;
- ``mttr_within_budget`` — crash-recovery MTTR <= 1.5x the no-crash
  completion of the same storm, clocked from the same poll.
"""

from __future__ import annotations

import json

from tpu_engine.twin import ctl_crash_ab, ctl_crash_bench_line


def main() -> None:
    res = ctl_crash_ab(seed=0)
    print(json.dumps({
        "baseline": res["baseline"],
        "crashed": res["crashed"],
        "mttr_ratio": res["mttr_ratio"],
        "mttr_budget_s": res["mttr_budget_s"],
        "gates": res["gates"],
        "ok": res["ok"],
    }, indent=2))
    line = ctl_crash_bench_line(seed=0, ab=res)
    print(json.dumps(line))
    if not (res["ok"] and line["ok"]):
        raise SystemExit(1)


if __name__ == "__main__":
    main()
