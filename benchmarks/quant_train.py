"""AQT-style int8 quantized-training A/B: microbench, step time, loss parity.

Three measurements for ``quant_training="int8"``
(``tpu_engine/quant_train.py``):

1. **Quantized-dot microbench** — ``int8_einsum`` vs plain bf16
   ``jnp.einsum`` on a llama-1b-shaped projection matmul, forward and
   forward+backward. On TPU the int8 MXU path runs up to 2× the bf16
   rate; on CPU the wall clock instead SHOWS the quantize/dequantize
   overhead (no int8 matmul units) — the ratio is reported either way,
   honestly labelled with the backend.
2. **End-to-end step-time A/B** — the real train step, quant off vs on,
   same model/config/seed; MFU on recognised TPU chips.
3. **Loss parity** — both variants trained ≥8 steps from the same seed
   on the same synthetic batch; reports per-step |Δloss| and the final
   delta (acceptance bar: |Δloss| ≤ 0.01 after 8 steps).

Run: ``python benchmarks/quant_train.py [--steps 8] [--model gpt-tiny]``
Prints one JSON line per measurement + a summary line. CPU-runnable by
design (the parity number is backend-independent; the speed ratios are
roofline-meaningful only on TPU).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import json
import time


def _time_fn(fn, *args, iters: int = 20) -> float:
    """Median-of-3-windows wall clock per call (compile excluded)."""
    import jax

    jax.block_until_ready(fn(*args))  # compile
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def microbench(on_tpu: bool) -> dict:
    """int8_einsum vs bf16 einsum on a llama-1b projection shape."""
    import jax
    import jax.numpy as jnp

    from tpu_engine.quant_train import int8_einsum

    # llama-1b MLP up-projection shape (d_model=2048, d_ff=5504) at a
    # training-sized token batch; scaled down off-TPU to keep CPU runs fast.
    if on_tpu:
        b, s, d, f = 4, 2048, 2048, 5504
    else:
        b, s, d, f = 2, 256, 512, 1376
    h = jax.random.normal(jax.random.PRNGKey(0), (b, s, d), jnp.bfloat16)
    w = jax.random.normal(jax.random.PRNGKey(1), (d, f), jnp.bfloat16)

    bf16_fwd = jax.jit(lambda a, k: jnp.einsum("bsi,io->bso", a, k))
    int8_fwd = jax.jit(lambda a, k: int8_einsum("bsi,io->bso", a, k))
    t_bf16 = _time_fn(bf16_fwd, h, w)
    t_int8 = _time_fn(int8_fwd, h, w)

    def g(fn):
        return jax.jit(jax.grad(lambda a, k: jnp.sum(
            fn("bsi,io->bso", a, k).astype(jnp.float32) ** 2), argnums=(0, 1)))

    t_bf16_bwd = _time_fn(g(jnp.einsum), h, w)
    t_int8_bwd = _time_fn(g(int8_einsum), h, w)

    return {
        "metric": "quant_dot_microbench",
        "shape": f"bsi,io->bso b={b} s={s} i={d} o={f} (bf16 operands)",
        "bf16_fwd_ms": round(t_bf16 * 1e3, 3),
        "int8_fwd_ms": round(t_int8 * 1e3, 3),
        "fwd_speed_ratio": round(t_bf16 / t_int8, 3),
        "bf16_fwdbwd_ms": round(t_bf16_bwd * 1e3, 3),
        "int8_fwdbwd_ms": round(t_int8_bwd * 1e3, 3),
        "fwdbwd_speed_ratio": round(t_bf16_bwd / t_int8_bwd, 3),
        "note": ">1 = int8 faster; on CPU the ratio shows quantize "
        "overhead, not the MXU win (no int8 matmul units)",
    }


def build_program(model_name: str, quant: str, seq_len: int, on_tpu: bool):
    from tpu_engine import train as tr
    from tpu_engine.mesh_runtime import MeshConfig
    from tpu_engine.sharding import TPUTrainConfig

    cfg = TPUTrainConfig(
        model_name=model_name,
        mesh=MeshConfig(data=1),
        micro_batch_size=2, seq_len=seq_len,
        precision="bf16" if on_tpu else "fp32",
        # lr 1e-3: the parity protocol needs a healthy (sub-chaotic)
        # trajectory — at 1e-2 the loss drops >2 nats in 8 steps and ANY
        # perturbation (quantization or not) diverges the trajectories
        # far beyond the per-step quantization error being measured.
        learning_rate=1e-3, warmup_steps=2, total_steps=100,
        sharding_stage=0, activation_checkpointing=False,
        attention_impl="auto", quant_training=quant,
    )
    return tr.build_train_program(cfg)


def train_ab(model_name: str, steps: int, seq_len: int, on_tpu: bool) -> dict:
    """End-to-end step-time + loss-parity A/B, same seed and batch."""
    import jax

    from tpu_engine.models import transformer as tfm
    from tpu_engine.profiler import peak_flops_per_chip

    runs = {}
    for quant in ("none", "int8"):
        prog = build_program(model_name, quant, seq_len, on_tpu)
        state = prog.init(jax.random.PRNGKey(0))
        batch = prog.synthetic_batch(seed=0)
        losses = []
        t0 = None
        for i in range(steps):
            state, metrics = prog.step(state, batch)
            losses.append(float(metrics["loss"]))
            if i == 0:  # exclude compile from timing
                jax.block_until_ready(state["params"])
                t0 = time.perf_counter()
        jax.block_until_ready(state["params"])
        dt = (time.perf_counter() - t0) / max(steps - 1, 1)
        accum, global_micro, seq = prog.global_batch_shape()
        runs[quant] = {
            "losses": losses,
            "step_time_ms": round(dt * 1e3, 2),
            "tokens_per_step": accum * global_micro * seq,
            "model_cfg": prog.model_config,
        }
        del prog, state
        jax.clear_caches()

    base, q = runs["none"], runs["int8"]
    deltas = [abs(a - b) for a, b in zip(base["losses"], q["losses"])]
    out = {
        "metric": "quant_train_e2e_ab",
        "model": model_name,
        "steps": steps,
        "bf16_step_time_ms": base["step_time_ms"],
        "int8_step_time_ms": q["step_time_ms"],
        "step_time_ratio": round(
            base["step_time_ms"] / max(q["step_time_ms"], 1e-9), 3
        ),
        "loss_delta_final": round(deltas[-1], 5),
        "loss_delta_max": round(max(deltas), 5),
        "bf16_loss_drop": round(base["losses"][0] - base["losses"][-1], 4),
        "bf16_losses": [round(x, 4) for x in base["losses"]],
        "int8_losses": [round(x, 4) for x in q["losses"]],
    }
    peak = peak_flops_per_chip() if on_tpu else None
    if peak:
        fpt = tfm.train_flops_per_token(base["model_cfg"], seq_len)
        for name, r in (("bf16", base), ("int8", q)):
            tps = r["tokens_per_step"] / (r["step_time_ms"] / 1e3)
            out[f"{name}_mfu_pct"] = round(100 * tps * fpt / peak, 2)
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--model", default=None,
                    help="default: llama-1b on TPU, gpt-tiny elsewhere")
    ap.add_argument("--seq-len", type=int, default=None)
    args = ap.parse_args()

    import jax

    on_tpu = jax.default_backend() == "tpu"
    model = args.model or ("llama-1b" if on_tpu else "gpt-tiny")
    seq_len = args.seq_len or (2048 if on_tpu else 128)

    micro = microbench(on_tpu)
    micro["backend"] = jax.default_backend()
    print(json.dumps(micro))
    jax.clear_caches()

    ab = train_ab(model, max(args.steps, 8), seq_len, on_tpu)
    ab["backend"] = jax.default_backend()
    print(json.dumps(ab))

    summary = {
        "metric": "quant_train_summary",
        "fwd_speed_ratio": micro["fwd_speed_ratio"],
        "step_time_ratio": ab["step_time_ratio"],
        "loss_delta_final": ab["loss_delta_final"],
        "parity_ok": ab["loss_delta_final"] <= 0.01,
        "backend": jax.default_backend(),
    }
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
