"""Comm-overlap A/B: prove the ``comm.py`` knobs change the TPU schedule.

The reference's comm tuning is DeepSpeed's ``overlap_comm``/bucket knobs
(``ai_engine/deepspeed_launcher.py:133-142``); ours is XLA's async-collective
fusion + latency-hiding scheduler (``tpu_engine/comm.py:29-37``). Round-2
VERDICT item 2: nothing *measured* that those flags do anything. This
benchmark AOT-compiles the llama-7b FSDP train step for a described v5e:4x4
(16-chip) topology three times — flags ON, flags OFF, and compiler default —
via per-compile ``compiler_options`` (no env mutation, no backend restart)
and reports, per variant:

- per-kind collective counts, split async (``*-start``/``*-done`` pairs)
  vs blocking;
- scheduled overlap distance: how many scheduled instructions sit between
  each async start and its matching done (the compute XLA placed under the
  in-flight collective — the direct analogue of NCCL overlap);
- per-device memory (overlap's cost: in-flight buffers live longer).

Run: ``python benchmarks/comm_overlap.py [--model llama-7b --topo v5e:4x4]``
Prints one JSON line per variant; paste the summary into RESULTS.md.

Wall-clock A/B needs a real multi-chip slice (the flags are TPU-only — the
CPU dry-run mesh neither accepts ``xla_tpu_*`` options nor shares the TPU
scheduler), so scheduled-placement + memory deltas are the strongest
single-host evidence available.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import json
import re
import time

COMM_ON = {
    "xla_tpu_enable_async_collective_fusion": "true",
    "xla_tpu_enable_async_collective_fusion_fuse_all_gather": "true",
    "xla_tpu_overlap_compute_collective_tc": "true",
    "xla_tpu_enable_latency_hiding_scheduler": "true",
    "xla_latency_hiding_scheduler_rerun": "1",
}
COMM_OFF = {
    "xla_tpu_enable_async_collective_fusion": "false",
    "xla_tpu_enable_async_collective_fusion_fuse_all_gather": "false",
    "xla_tpu_overlap_compute_collective_tc": "false",
    "xla_tpu_enable_latency_hiding_scheduler": "false",
}

_KINDS = ("all-gather", "all-reduce", "reduce-scatter", "collective-permute",
          "all-to-all")


def overlap_stats(hlo_text: str) -> dict:
    """Counts + scheduled start→done distances for every collective kind.

    Works on the post-scheduling ``compiled.as_text()``: within each
    computation, instructions appear in execution order, so the line count
    between ``X-start`` and its ``X-done`` approximates how much work XLA
    scheduled under the in-flight collective.
    """
    async_by_kind: dict[str, int] = {k: 0 for k in _KINDS}
    blocking_by_kind: dict[str, int] = {k: 0 for k in _KINDS}
    starts: dict[str, int] = {}
    distances: list[int] = []
    # TPU async-collective *fusion* spells overlap as custom-call pairs
    # (AsyncCollectiveStart → fusion computation → AsyncCollectiveDone)
    # rather than HLO -start/-done ops. The Done consumes a fusion, not the
    # Start, so name-matching is impossible from text — pair FIFO in
    # schedule order (starts and dones appear in execution order within a
    # scheduled computation), which is exact when pairs don't interleave
    # and a close approximation when they do.
    cc_pairs = 0
    cc_open: list[int] = []
    cc_distances: list[int] = []
    for i, line in enumerate(hlo_text.splitlines()):
        if 'custom_call_target="AsyncCollectiveStart"' in line:
            cc_open.append(i)
            continue
        if 'custom_call_target="AsyncCollectiveDone"' in line:
            cc_pairs += 1
            if cc_open:
                cc_distances.append(i - cc_open.pop(0))
            continue
        op = re.search(
            r"= [^=]*?\b((?:%s)(?:-start|-done)?)\(" % "|".join(_KINDS), line
        )
        if op is None:
            continue
        name = op.group(1)
        kind = next(k for k in _KINDS if name.startswith(k))
        if name.endswith("-start"):
            async_by_kind[kind] += 1
            m = re.search(r"%(\S+) =", line)
            if m:
                starts[m.group(1)] = i
        elif name.endswith("-done"):
            m = re.search(r"-done\(%?([^),]+)", line)
            if m and m.group(1) in starts:
                distances.append(i - starts[m.group(1)])
        else:
            blocking_by_kind[kind] += 1
    # Headline distances pool BOTH overlap spellings: HLO -start/-done ops
    # and the async-fusion custom-call pairs.
    pooled = distances + cc_distances
    return {
        "async_fusion_pairs": cc_pairs,
        "async_fusion_distance_mean": (
            round(sum(cc_distances) / len(cc_distances), 1)
            if cc_distances else 0.0
        ),
        "async_total": sum(async_by_kind.values()),
        "blocking_total": sum(blocking_by_kind.values()),
        "async_by_kind": {k: v for k, v in async_by_kind.items() if v},
        "blocking_by_kind": {k: v for k, v in blocking_by_kind.items() if v},
        "overlap_distance_mean": (
            round(sum(pooled) / len(pooled), 1) if pooled else 0.0
        ),
        "overlap_distance_p90": (
            sorted(pooled)[int(0.9 * (len(pooled) - 1))] if pooled else 0
        ),
        "overlap_distance_max": max(pooled) if pooled else 0,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="llama-7b")
    ap.add_argument("--topo", default="v5e:4x4")
    ap.add_argument("--fsdp", type=int, default=16)
    ap.add_argument("--data", type=int, default=1)
    ap.add_argument("--seq", type=int, default=4096)
    args = ap.parse_args()

    from benchmarks.aot import aot_lowered

    lowered = aot_lowered(
        args.model, args.topo, dict(data=args.data, fsdp=args.fsdp),
        seq=args.seq, overrides={"attention_impl": "flash"},
    )

    for variant, opts in (("comm_on", COMM_ON), ("comm_off", COMM_OFF),
                          ("compiler_default", None)):
        t0 = time.time()
        comp = (lowered.compile(compiler_options=opts) if opts
                else lowered.compile())
        ma = comp.memory_analysis()
        rec = {
            "variant": variant,
            "model": args.model,
            "topology": args.topo,
            "compile_s": round(time.time() - t0, 1),
            **overlap_stats(comp.as_text()),
            "device_args_gib": round(ma.argument_size_in_bytes / 2**30, 3),
            "device_temp_gib": round(ma.temp_size_in_bytes / 2**30, 3),
        }
        print(json.dumps(rec))


if __name__ == "__main__":
    main()
