"""AOT fit-tuning sweep for the 7b/13b/70b presets.

Compiles candidate (mesh, batch, chunking) combinations against described
v5e topologies and reports per-device HBM so the shipped presets can be
ones that PROVABLY fit their target slice — unlike the reference's, whose
GPU sizing was never validated anywhere.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import json
import time

import jax
import jax.numpy as jnp

CANDIDATES = [
    # (name, model, topology, mesh, micro, seq, overrides)
    ("7b-v5e8-mb2", "llama-7b", "v5e:2x4", dict(data=1, fsdp=8), 2, 4096,
     {"optimizer_offload": "host"}),
    ("7b-v5e8-mb1", "llama-7b", "v5e:2x4", dict(data=1, fsdp=8), 1, 4096,
     {"optimizer_offload": "host"}),
    ("13b-v5e16-mb1", "llama-13b", "v5e:4x4", dict(data=1, fsdp=16), 1, 4096,
     {"optimizer_offload": "host", "param_offload": "host",
      "loss_chunk_size": 1024}),
    ("13b-v5e8-mb1-chunk", "llama-13b", "v5e:2x4", dict(data=1, fsdp=8), 1, 4096,
     {"optimizer_offload": "host", "param_offload": "host",
      "loss_chunk_size": 1024}),
    ("70b-v5e256-fsdp64", "llama-70b", "v5e:16x16", dict(data=4, fsdp=64), 1, 4096,
     {"optimizer_offload": "host", "param_offload": "host",
      "loss_chunk_size": 1024}),
    ("70b-v5e64-fsdp64", "llama-70b", "v5e:8x8", dict(data=1, fsdp=64), 1, 4096,
     {"optimizer_offload": "host", "param_offload": "host",
      "loss_chunk_size": 1024}),
    # The 8x7b MoE preset's declared slice (round-4 verdict weakness 2:
    # the ONLY preset never AOT-fit-verified): experts ride the "model"
    # axis (EP), attention is TP over the same axis, fsdp=4 shards the
    # rest — 32 chips (v5e:4x8).
    ("8x7b-v5e32-ep8", "moe-8x7b", "v5e:4x8", dict(data=1, fsdp=4, model=8),
     1, 4096, {"optimizer_offload": "host"}),
    ("8x7b-v5e32-ep8-chunk", "moe-8x7b", "v5e:4x8",
     dict(data=1, fsdp=4, model=8), 1, 4096,
     {"optimizer_offload": "host", "loss_chunk_size": 1024}),
    # 4.7 GiB over on 32 chips (measured above) — two escape paths:
    ("8x7b-v5e32-ep8-paramhost", "moe-8x7b", "v5e:4x8",
     dict(data=1, fsdp=4, model=8), 1, 4096,
     {"optimizer_offload": "host", "param_offload": "host",
      "loss_chunk_size": 1024}),
    ("8x7b-v5e64-ep8", "moe-8x7b", "v5e:8x8",
     dict(data=1, fsdp=8, model=8), 1, 4096,
     {"optimizer_offload": "host", "loss_chunk_size": 1024}),
]


def main() -> int:
    from jax.experimental import topologies

    from benchmarks.hbm_projection import _build

    gib = 2**30
    for name, model, topo_name, mesh_axes, micro, seq, overrides in CANDIDATES:
        t0 = time.time()
        try:
            topo = topologies.get_topology_desc(topo_name, platform="tpu")
            prog = _build(model, mesh_axes, micro, 1, seq, overrides,
                          devices=topo.devices)
            state_shape = jax.eval_shape(prog.init, jax.random.PRNGKey(0))
            batch = jax.ShapeDtypeStruct(prog.global_batch_shape(), jnp.int32)
            comp = prog.step.lower(state_shape, batch).compile()
            ma = comp.memory_analysis()
            peak = (ma.argument_size_in_bytes + ma.temp_size_in_bytes) / gib
            print(json.dumps({
                "candidate": name, "topology": topo_name, "mesh": mesh_axes,
                "micro": micro,
                "device_args_gib": round(ma.argument_size_in_bytes / gib, 2),
                "device_temp_gib": round(ma.temp_size_in_bytes / gib, 2),
                "device_peak_gib": round(peak, 2),
                "fits_16gib_hbm": peak < 15.5,
                "compile_s": round(time.time() - t0, 1),
            }), flush=True)
        except Exception as e:  # noqa: BLE001
            print(json.dumps({
                "candidate": name,
                "error": f"{type(e).__name__}: {e}"[:260],
                "compile_s": round(time.time() - t0, 1),
            }), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
