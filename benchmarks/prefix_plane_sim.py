"""Fleet prefix plane A/B: per-replica LRU vs radix index + host tier.

Runs :func:`tpu_engine.twin.prefix_plane_ab` — the twin serving lane
with a seeded many-tenant shared-prefix trace (32 hot system prompts vs
4 replicas x 4 resident prefixes, so half the working set cannot be
device-resident anywhere) through the REAL
:class:`~tpu_engine.serving_fleet.FleetRouter`, baseline vs with a real
:class:`~tpu_engine.prefix_plane.PrefixPlane` attached — and prints the
A/B plus the bench line
(``JAX_PLATFORMS=cpu python -m benchmarks.prefix_plane_sim``).

Exit gates (process exits 1 when any fails):

- ``plane_beats_baseline_p99_ttft_2x`` — p99 TTFT on repeated shared
  prefixes improves >= 2x at equal chips;
- ``tokens_per_sec_no_worse`` — throughput within 1% of baseline;
- ``deterministic_repeat`` — a second plane run is byte-identical;
- ``host_tier_absorbs_overflow`` — replica-cache evictions actually
  land in (and rehydrate from) the host tier;
- ``host_budget_rejected`` — ``estimate_serving_hbm`` refuses an
  oversubscribed host budget with a structured reason.
"""

from __future__ import annotations

import json

from tpu_engine.twin import prefix_plane_ab, prefix_plane_bench_line


def main() -> None:
    res = prefix_plane_ab(seed=0)
    print(json.dumps({
        "baseline": res["baseline"],
        "plane": res["plane"],
        "ttft_p99_improvement": res["ttft_p99_improvement"],
        "tokens_per_sec_ratio": res["tokens_per_sec_ratio"],
        "host_tier_gib": res["host_tier_gib"],
        "host_budget_rejection": res["host_budget_rejection"],
        "gates": res["gates"],
        "ok": res["ok"],
    }, indent=2))
    line = prefix_plane_bench_line(seed=0, ab=res)
    print(json.dumps(line))
    if not (res["ok"] and line["ok"]):
        raise SystemExit(1)


if __name__ == "__main__":
    main()
