"""Inter-token latency for RUNNING slots during an admission burst.

Round-3 verdict item 3: admission used to prefill every admitted prompt
sequentially before any decode step — a burst of admissions stalled all
running slots for the full prompts' forwards. Round 4 ingests prompts in
bounded ``prefill_chunk`` dispatches, at most one chunk per engine step,
interleaved with decode. This measures what running requests actually
feel: per-token emission gaps (engine-side timestamps, no polling noise)
for slots that were decoding when a burst of long prompts arrived —
small ``prefill_chunk`` bounds the worst gap, large chunks (the
monolithic-prefill regime) stretch it.

Run: ``python benchmarks/serving_latency.py`` (real chip; one JSON line
per prefill_chunk setting).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import json
import time


class _TimestampingBatcher:
    """Benchmark-side shim: records an engine-side timestamp per emitted
    token without touching product code."""

    def __new__(cls, *a, **kw):
        from tpu_engine.serving import ContinuousBatcher

        class Timestamped(ContinuousBatcher):
            def __init__(self, *args, **kwargs):
                super().__init__(*args, **kwargs)
                self.emit_times: dict[int, list[float]] = {}

            def _emit(self, req, slot, tok):
                self.emit_times.setdefault(req.id, []).append(
                    time.perf_counter()
                )
                super()._emit(req, slot, tok)

        return Timestamped(*a, **kw)


def run_one(params, cfg, prefill_chunk: int) -> dict:
    srv = _TimestampingBatcher(
        params, cfg, max_slots=8, max_len=1024, chunk_steps=8,
        prefill_chunk=prefill_chunk, prefill_pad_to=64,
    )
    # Warm all compiled shapes: a short request end-to-end, plus one
    # long-prompt request so the burst's prefill shapes are cached.
    w1 = srv.submit(list(range(1, 33)), max_new_tokens=24)
    w2 = srv.submit(list(range(1, 513)), max_new_tokens=8)
    while not all(srv.result(w)["status"] == "done" for w in (w1, w2)):
        srv.step()

    # 4 running decode requests, into steady state.
    running = [srv.submit(list(range(1, 33)), max_new_tokens=400)
               for _ in range(4)]
    while min(len(srv.result(r)["tokens"]) for r in running) < 24:
        srv.step()

    # THE BURST: 4 long prompts land at once.
    burst_t = time.perf_counter()
    burst = [srv.submit(list(range(1, 513)), max_new_tokens=16)
             for _ in range(4)]
    while not all(srv.result(b)["status"] == "done" for b in burst):
        srv.step()
    # Keep decoding a moment past the burst so trailing gaps are captured.
    for _ in range(4):
        srv.step()

    # Inter-token gaps of the RUNNING requests, within the burst window.
    end_t = time.perf_counter()
    gaps = []
    for r in running:
        ts = [t for t in srv.emit_times[r] if burst_t - 0.5 <= t <= end_t]
        gaps += [b - a for a, b in zip(ts, ts[1:])]
    gaps.sort()
    pct = lambda p: round(gaps[min(int(len(gaps) * p), len(gaps) - 1)] * 1e3, 1)
    return {
        "prefill_chunk": prefill_chunk,
        "burst_prompts": 4, "prompt_len": 512,
        "running_slots": 4, "gaps_measured": len(gaps),
        "intertoken_p50_ms": pct(0.50),
        "intertoken_p95_ms": pct(0.95),
        "intertoken_max_ms": round(gaps[-1] * 1e3, 1),
        "burst_window_s": round(end_t - burst_t, 2),
    }


def main() -> None:
    import jax
    import jax.numpy as jnp

    from tpu_engine.models import transformer as tfm

    cfg = tfm.MODEL_CONFIGS["gpt-125m"]
    params = tfm.init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.bfloat16)
    # 512 = the whole prompt in one dispatch (the round-3 monolithic
    # regime); 128/64 = bounded interleave.
    for chunk in (512, 128, 64):
        print(json.dumps(run_one(params, cfg, chunk)))


if __name__ == "__main__":
    main()
