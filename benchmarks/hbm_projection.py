"""7B/13B/70B-scale memory + MFU projection without 7B-scale hardware.

Three independent measurement planes (VERDICT round-1 item 5):

1. **AOT compile against described TPU topologies** (``--aot``): libtpu
   compiles the REAL sharded train step for v5e meshes up to 16x16 (256
   chips — the BASELINE north-star hardware) without any chips attached,
   and ``compiled.memory_analysis()`` reports the per-device HBM the XLA
   compiler actually allocated (arguments + temporaries), while
   ``cost_analysis()`` reports per-device FLOPs per step. This is the
   strongest available evidence that a preset fits its target slice.

2. **eval_shape arithmetic** (``--table``): pure state accounting — bytes
   per device of params / grads / optimizer state at each ZeRO stage ×
   offload mode, from the sharding specs alone. No compile, runs anywhere.

3. **Single-layer microbenchmark on the real chip** (``--layer``): one
   llama-7b decoder block, seq 4096, fwd+bwd wall time on the attached TPU
   — anchors the 7B MFU projection with measured silicon numbers.

Each mode prints JSON lines; paste the summary into benchmarks/RESULTS.md.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import json
import time

import jax
import jax.numpy as jnp


# (name, model, topology, mesh axes, micro_batch, accum, seq, offload)
AOT_CONFIGS = [
    # The BASELINE north star: Llama-2-7B-scale FSDP on v5e-256.
    ("northstar-7b-v5e256", "llama-7b", "v5e:16x16",
     dict(data=16, fsdp=16), 2, 1, 4096, {}),
    # The shipped presets at their native mesh sizes.
    ("preset-7b-v5e4", "llama-7b", "v5e:2x2",
     dict(data=1, fsdp=4), 2, 1, 4096, {"optimizer_offload": "host"}),
    ("preset-13b-v5e8", "llama-13b", "v5e:2x4",
     dict(data=1, fsdp=8), 1, 1, 4096,
     {"optimizer_offload": "host", "param_offload": "host"}),
    ("preset-13b-v5e8-no-offload", "llama-13b", "v5e:2x4",
     dict(data=1, fsdp=8), 1, 1, 4096, {}),
    ("preset-70b-v5e16", "llama-70b", "v5e:4x4",
     dict(data=2, fsdp=8), 1, 1, 4096,
     {"optimizer_offload": "host", "param_offload": "host"}),
    ("70b-v5e256", "llama-70b", "v5e:16x16",
     dict(data=16, fsdp=16), 1, 1, 4096,
     {"optimizer_offload": "host", "param_offload": "host"}),
]


def _build(model, mesh_axes, micro, accum, seq, overrides, devices=None):
    from tpu_engine.mesh_runtime import MeshConfig, MeshRuntime
    from tpu_engine.sharding import ShardingStage, TPUTrainConfig
    from tpu_engine.train import build_train_program

    overrides = dict(overrides)
    stage = overrides.pop("sharding_stage", ShardingStage.FULL_PARTITIONING)
    cfg = TPUTrainConfig(
        model_name=model,
        sharding_stage=stage,
        mesh=MeshConfig(**mesh_axes),
        micro_batch_size=micro,
        gradient_accumulation_steps=accum,
        seq_len=seq,
        **overrides,
    )
    runtime = MeshRuntime(cfg.mesh, devices=devices) if devices else None
    return build_train_program(cfg, runtime=runtime)


def run_aot() -> None:
    from jax.experimental import topologies

    gib = 2**30
    for name, model, topo_name, mesh_axes, micro, accum, seq, overrides in AOT_CONFIGS:
        t0 = time.time()
        try:
            topo = topologies.get_topology_desc(topo_name, platform="tpu")
            prog = _build(model, mesh_axes, micro, accum, seq, overrides,
                          devices=topo.devices)
            state_shape = jax.eval_shape(prog.init, jax.random.PRNGKey(0))
            batch = jax.ShapeDtypeStruct(prog.global_batch_shape(), jnp.int32)
            comp = prog.step.lower(state_shape, batch).compile()
            ma = comp.memory_analysis()
            ca = comp.cost_analysis() or {}
            args_gib = ma.argument_size_in_bytes / gib
            temp_gib = ma.temp_size_in_bytes / gib
            peak_gib = args_gib + temp_gib  # outputs alias the donated args
            print(json.dumps({
                "config": name, "model": model, "topology": topo_name,
                "mesh": mesh_axes, "micro_batch": micro, "seq_len": seq,
                "offload": overrides,
                "device_args_gib": round(args_gib, 2),
                "device_temp_gib": round(temp_gib, 2),
                "device_peak_gib": round(peak_gib, 2),
                "fits_16gib_hbm": peak_gib < 16.0,
                "flops_per_step_per_device": ca.get("flops"),
                "compile_s": round(time.time() - t0, 1),
            }))
        except Exception as e:  # noqa: BLE001 — keep the sweep going
            print(json.dumps({
                "config": name, "error": f"{type(e).__name__}: {e}"[:300],
                "compile_s": round(time.time() - t0, 1),
            }))


def run_table() -> None:
    """Pure eval_shape accounting: per-device state bytes by stage/offload."""
    from tpu_engine.sharding import ShardingStage

    # The estimator lives in tpu_engine/hbm_estimate.py now (the fleet
    # scheduler's admission gate uses the analytic plane of the same module).
    from tpu_engine.hbm_estimate import per_device_bytes

    gib = 2**30

    from jax.experimental import topologies

    topo_for = {4: "v5e:2x2", 8: "v5e:2x4", 16: "v5e:4x4"}
    for model, fsdp in (("llama-7b", 4), ("llama-13b", 8), ("llama-70b", 16)):
        devices = topologies.get_topology_desc(
            topo_for[fsdp], platform="tpu"
        ).devices
        for stage in (0, 1, 2, 3):
            for offload in ({}, {"optimizer_offload": "host"},
                            {"optimizer_offload": "host", "param_offload": "host"}):
                if offload.get("param_offload") and stage < 3:
                    continue
                try:
                    cfg_over = dict(offload)
                    prog = _build(model, dict(data=1, fsdp=fsdp), 1, 1, 4096,
                                  {**cfg_over, "sharding_stage": ShardingStage(stage)},
                                  devices=devices)
                    state_shape = jax.eval_shape(prog.init, jax.random.PRNGKey(0))
                    sh = prog.state_shardings
                    p_dev = per_device_bytes(state_shape["params"], sh["params"], False)
                    p_host = per_device_bytes(state_shape["params"], sh["params"], True)
                    o_dev = per_device_bytes(state_shape["opt_state"], sh["opt_state"], False)
                    o_host = per_device_bytes(state_shape["opt_state"], sh["opt_state"], True)
                    print(json.dumps({
                        "model": model, "fsdp": fsdp, "stage": stage,
                        "offload": offload,
                        "params_dev_gib": round(p_dev / gib, 3),
                        "params_host_gib": round(p_host / gib, 3),
                        "opt_dev_gib": round(o_dev / gib, 3),
                        "opt_host_gib": round(o_host / gib, 3),
                    }))
                except Exception as e:  # noqa: BLE001
                    print(json.dumps({
                        "model": model, "stage": stage, "offload": offload,
                        "error": f"{type(e).__name__}: {e}"[:200],
                    }))


def run_layer() -> None:
    """One llama-7b decoder block fwd+bwd on the attached chip, seq 4096."""
    from tpu_engine.models import transformer as tfm

    if jax.devices()[0].platform not in ("tpu",) and "axon" not in str(
        jax.devices()[0].platform
    ):
        print(json.dumps({"error": "no TPU attached; --layer needs real silicon"}))
        return
    cfg = tfm.MODEL_CONFIGS["llama-7b"]
    D, F = cfg.d_model, cfg.d_ff
    B, S = 1, 4096
    rng = jax.random.PRNGKey(0)
    layer = jax.eval_shape(lambda: tfm.init_params(rng, cfg, dtype=jnp.bfloat16))
    # Materialise ONE layer's params (full init would blow the single chip).
    one_layer = jax.tree.map(
        lambda s: jax.random.normal(rng, s.shape[1:], jnp.bfloat16) * 0.02
        if s.shape and s.shape[0] == cfg.n_layers
        else None,
        layer["layers"],
    )
    x = jax.random.normal(rng, (B, S, D), jnp.bfloat16)
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None, :], (B, S))

    def block_loss(layer_params, x):
        out, _ = tfm._block(x, layer_params, cfg, positions, mesh=None,
                            tag_names=False)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    grad_fn = jax.jit(jax.value_and_grad(block_loss))
    v, g = grad_fn(one_layer, x)
    jax.block_until_ready(g)
    n_iter = 20
    t0 = time.perf_counter()
    for _ in range(n_iter):
        v, g = grad_fn(one_layer, x)
    jax.block_until_ready(g)
    dt = (time.perf_counter() - t0) / n_iter
    # Per-layer train FLOPs: 6 × layer params × tokens + attention term.
    layer_params = sum(
        int(jnp.size(p)) for p in jax.tree.leaves(one_layer) if p is not None
    )
    attn_flops = 12 * S * S * D * B  # fwd+bwd causal attention (dense upper bound /2)
    flops = 6 * layer_params * B * S + attn_flops
    from tpu_engine.profiler import peak_flops_per_chip

    peak = peak_flops_per_chip() or 197e12
    mfu = flops / dt / peak
    print(json.dumps({
        "metric": "llama7b_single_layer_fwd_bwd",
        "seq_len": S, "batch": B,
        "step_time_ms": round(dt * 1e3, 2),
        "layer_params": layer_params,
        "model_flops": flops,
        "mfu_anchor": round(mfu, 4),
        "device_kind": jax.devices()[0].device_kind,
    }))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--aot", action="store_true")
    ap.add_argument("--table", action="store_true")
    ap.add_argument("--layer", action="store_true")
    args = ap.parse_args()
    if not (args.aot or args.table or args.layer):
        args.table = True
    if args.table:
        run_table()
    if args.layer:
        run_layer()
    if args.aot:
        run_aot()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
