"""AOT memory proof: llama-7b SERVING fits a v5e:2x2 (TP=4) slot pool.

Round-3 verdict item 1(b): the framework could *train* 7B-class models
across chips but not serve them — a llama-7b at bf16 (~12.6 GiB weights
+ KV pool) cannot sit on one 16 GiB v5e chip. This compiles the REAL
serving dispatches (``tpu_engine.serving.decode_chunk`` and the chunked
prefill forward) against a described v5e:2x2 topology with the exact
shardings :class:`ContinuousBatcher` uses under ``mesh=`` (params TP
over the ``model`` axis, KV pool kv-heads sharded, donated pool), and
reports the per-device HBM the XLA compiler actually allocated.

No chips required (AOT topology compile); run:
``python benchmarks/serving_fit.py``. Prints one JSON line per program
plus a combined-fit line.
"""

from __future__ import annotations

import json
import time
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

GIB = 2**30

# Serving shape under proof: 8 concurrent slots, 2k context each.
MODEL = "llama-7b"
TOPOLOGY = "v5e:2x2"
TP = 4
MAX_SLOTS = 8
MAX_LEN = 2048
CHUNK_STEPS = 16
PREFILL_CHUNK = 256


def main() -> None:
    from jax.experimental import topologies

    from tpu_engine.mesh_runtime import MeshConfig, build_mesh
    from tpu_engine.models import transformer as tfm
    from tpu_engine.serving import (
        SlotCache, decode_chunk, init_slot_cache, _prefill_forward,
    )
    from tpu_engine.generate import KVCache, init_cache

    cfg = tfm.MODEL_CONFIGS[MODEL]
    topo = topologies.get_topology_desc(TOPOLOGY, platform="tpu")
    mesh = build_mesh(MeshConfig(model=TP), devices=topo.devices)
    rep = NamedSharding(mesh, P())
    kv_sh = NamedSharding(mesh, P(None, None, None, "model", None))

    def sds(tree, sharding_tree):
        return jax.tree.map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
            tree, sharding_tree,
        )

    # Params: bf16 serving weights, TP/FSDP-sharded exactly as a trained
    # job's snapshot (fsdp axis is size 1 here — pure TP serving).
    from tpu_engine.sharding import (
        ShardingStage, named_shardings, param_pspecs,
    )
    p_shape = jax.eval_shape(
        partial(tfm.init_params, cfg=cfg, dtype=jnp.bfloat16),
        jax.random.PRNGKey(0),
    )
    p_sh = named_shardings(
        mesh, param_pspecs(tfm.logical_axes(cfg), ShardingStage.FULL_PARTITIONING)
    )
    params_abs = sds(p_shape, p_sh)
    params_gib = sum(
        s.dtype.itemsize * int(jnp.prod(jnp.asarray(sh.shard_shape(s.shape))))
        for s, sh in zip(jax.tree.leaves(p_shape), jax.tree.leaves(
            p_sh, is_leaf=lambda x: isinstance(x, NamedSharding)))
    ) / GIB

    # The slot pool, sharded as ContinuousBatcher shards it.
    cache_shape = jax.eval_shape(
        partial(init_slot_cache, cfg, MAX_SLOTS, MAX_LEN, jnp.bfloat16)
    )
    cache_sh = SlotCache(k=kv_sh, v=kv_sh, lengths=rep, pos=None, ring=False)
    cache_abs = sds(cache_shape, cache_sh)
    pool_gib = 2 * (
        cache_shape.k.dtype.itemsize
        * int(jnp.prod(jnp.asarray(kv_sh.shard_shape(cache_shape.k.shape))))
    ) / GIB

    vec = lambda dt: jax.ShapeDtypeStruct((MAX_SLOTS,), dt, sharding=rep)
    key_abs = jax.ShapeDtypeStruct((2,), jnp.uint32, sharding=rep)

    results = {}
    for name, build in (
        ("decode_chunk", lambda: jax.jit(
            partial(decode_chunk, cfg=cfg, n_steps=CHUNK_STEPS,
                    compute_dtype=jnp.bfloat16),
            donate_argnums=(2,), out_shardings=(rep, cache_sh),
        ).lower(
            params_abs, vec(jnp.int32), cache_abs, vec(jnp.bool_),
            vec(jnp.float32), vec(jnp.int32), vec(jnp.int32), key_abs,
        )),
        ("prefill_chunk", lambda: jax.jit(
            partial(_prefill_forward, cfg=cfg, compute_dtype=jnp.bfloat16),
            donate_argnums=(2,),
        ).lower(
            params_abs,
            jax.ShapeDtypeStruct((1, PREFILL_CHUNK), jnp.int32, sharding=rep),
            sds(
                jax.eval_shape(partial(init_cache, cfg, 1, MAX_LEN,
                                       dtype=jnp.bfloat16)),
                KVCache(k=kv_sh, v=kv_sh, pos=rep, length=rep, ring=False),
            ),
            jax.ShapeDtypeStruct((), jnp.int32, sharding=rep),
        )),
    ):
        t0 = time.time()
        comp = build().compile()
        ma = comp.memory_analysis()
        args_gib = ma.argument_size_in_bytes / GIB
        temp_gib = ma.temp_size_in_bytes / GIB
        results[name] = dict(args=args_gib, temp=temp_gib)
        print(json.dumps({
            "program": name, "model": MODEL, "topology": TOPOLOGY, "tp": TP,
            "slots": MAX_SLOTS, "max_len": MAX_LEN,
            "device_args_gib": round(args_gib, 2),
            "device_temp_gib": round(temp_gib, 2),
            "device_peak_gib": round(args_gib + temp_gib, 2),
            "compile_s": round(time.time() - t0, 1),
        }))

    # Steady-state residency: params + pool + one prefill c1 cache + the
    # larger of the two programs' temporaries (they never run concurrently
    # — the engine thread serialises dispatches).
    c1_gib = 2 * (
        2 * cfg.n_layers * 1 * MAX_LEN * cfg.n_kv_heads * cfg.head_dim // TP
    ) / GIB
    combined = (
        results["decode_chunk"]["args"] + c1_gib
        + max(results["decode_chunk"]["temp"], results["prefill_chunk"]["temp"])
    )
    print(json.dumps({
        "metric": "llama7b_serving_fit_v5e_2x2_tp4",
        "params_gib_per_device": round(params_gib, 2),
        "kv_pool_gib_per_device": round(pool_gib, 2),
        "prefill_c1_gib_per_device": round(c1_gib, 2),
        "combined_peak_gib_per_device": round(combined, 2),
        "fits_16gib_hbm": combined < 16.0,
        "headroom_gib": round(16.0 - combined, 2),
    }))


if __name__ == "__main__":
    main()
