"""AOT memory proofs: llama-7b SERVING fits — and int8 shrinks the bill.

Round-3 verdict item 1(b) established the gap (the framework could
*train* 7B-class models but not serve them); the round-4 bf16 proof put
llama-7b serving on a v5e:2x2 (TP=4). The int8 rows extend it: weight-only
int8 (``tpu_engine/quant.py``) + int8 KV pool (``init_slot_cache
kv_quant``) roughly halve both components, putting llama-7b serving on
a SINGLE 16 GiB v5e chip — no mesh at all.

Each row compiles the REAL serving dispatches
(``tpu_engine.serving.decode_chunk`` + the chunked prefill forward) with
the exact shardings :class:`ContinuousBatcher` uses and reports the
per-device HBM the XLA compiler actually allocated:

- TP rows compile against a described v5e:2x2 topology (no chips
  needed);
- the single-chip row compiles against the local TPU backend (a real
  v5e chip — skipped off-TPU) since libtpu rejects a 1x1 topology
  descriptor.

Run: ``python benchmarks/serving_fit.py``. One JSON line per program,
plus a combined-fit line per row.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import json
import time
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

GIB = 2**30

MODEL = "llama-7b"
CHUNK_STEPS = 16
PREFILL_CHUNK = 256

# (label, tp, weight int8?, kv int8?, slots, max_len). tp=1 compiles on
# the local chip. The single-chip row uses 8 slots x 1024 context: the
# decode scan double-buffers the pool within a step (layer-scan input and
# output stacks coexist), so 8 x 2048 lands ~0.8 GiB over one chip's HBM
# — at 8 x 1024 (or 4 x 2048, same bytes) it fits with >3 GiB headroom.
ROWS = (
    ("bf16_v5e_2x2_tp4", 4, False, False, 8, 2048),
    ("int8_v5e_2x2_tp4", 4, True, True, 8, 2048),
    ("int8_v5e_1chip", 1, True, True, 8, 1024),
)


def _per_device_gib(shapes, shardings) -> float:
    """Bytes of one device's shards of an abstract tree (int8 leaves
    count 1 byte — the sharded twin of ``quantized_param_bytes``)."""
    return sum(
        s.dtype.itemsize * int(jnp.prod(jnp.asarray(sh.shard_shape(s.shape))))
        for s, sh in zip(
            jax.tree.leaves(shapes),
            jax.tree.leaves(shardings,
                            is_leaf=lambda x: isinstance(x, NamedSharding)),
        )
    ) / GIB


def run_row(label: str, tp: int, w_int8: bool, kv_int8: bool,
            max_slots: int, max_len: int) -> None:
    from tpu_engine.generate import KVCache, init_cache
    from tpu_engine.mesh_runtime import MeshConfig, build_mesh
    from tpu_engine.models import transformer as tfm
    from tpu_engine.quant import quantize_params, quantize_pspecs, \
        quantized_param_bytes
    from tpu_engine.serving import (
        SlotCache, decode_chunk, init_slot_cache, _prefill_forward,
    )
    from tpu_engine.sharding import (
        ShardingStage, named_shardings, param_pspecs,
    )

    cfg = tfm.MODEL_CONFIGS[MODEL]
    if tp > 1:
        from jax.experimental import topologies

        topo = topologies.get_topology_desc("v5e:2x2", platform="tpu")
        mesh = build_mesh(MeshConfig(model=tp), devices=topo.devices)
        topology = "v5e:2x2"
    else:
        if jax.devices()[0].platform != "tpu":
            print(json.dumps({"row": label, "skipped": "needs a local TPU"}))
            return
        mesh = build_mesh(MeshConfig())  # 1-device mesh on the real chip
        topology = str(jax.devices()[0].device_kind)
    rep = NamedSharding(mesh, P())
    model_ax = "model" if tp > 1 else None
    kv_sh = NamedSharding(mesh, P(None, None, None, model_ax, None))

    def sds(tree, sharding_tree):
        return jax.tree.map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
            tree, sharding_tree,
        )

    # Params: bf16 (or int8-quantized) serving weights, sharded exactly as
    # the batcher receives them.
    bf16_shape = jax.eval_shape(
        partial(tfm.init_params, cfg=cfg, dtype=jnp.bfloat16),
        jax.random.PRNGKey(0),
    )
    p_shape = bf16_shape
    p_specs = param_pspecs(tfm.logical_axes(cfg),
                           ShardingStage.FULL_PARTITIONING)
    if w_int8:
        p_shape = jax.eval_shape(quantize_params, bf16_shape)
        p_specs = quantize_pspecs(p_specs, p_shape)
        assert quantized_param_bytes(p_shape) < \
            0.55 * quantized_param_bytes(bf16_shape), \
            "int8 tree should be < 55% of the bf16 tree"
    p_sh = named_shardings(mesh, p_specs)
    params_abs = sds(p_shape, p_sh)
    params_gib = _per_device_gib(p_shape, p_sh)

    # The slot pool, sharded as ContinuousBatcher shards it.
    cache_shape = jax.eval_shape(
        partial(init_slot_cache, cfg, max_slots, max_len, jnp.bfloat16,
                kv_quant=kv_int8)
    )
    cache_sh = SlotCache(
        k=kv_sh, v=kv_sh, lengths=rep, pos=None, ring=False,
        k_scale=kv_sh if kv_int8 else None,
        v_scale=kv_sh if kv_int8 else None,
    )
    cache_abs = sds(cache_shape, cache_sh)
    pool_gib = _per_device_gib(cache_shape, cache_sh)

    vec = lambda dt: jax.ShapeDtypeStruct((max_slots,), dt, sharding=rep)
    key_abs = jax.ShapeDtypeStruct((2,), jnp.uint32, sharding=rep)
    c1_shape = jax.eval_shape(
        partial(init_cache, cfg, 1, max_len, dtype=jnp.bfloat16,
                kv_quant=kv_int8)
    )
    c1_sh = KVCache(k=kv_sh, v=kv_sh, pos=rep, length=rep, ring=False,
                    k_scale=kv_sh if kv_int8 else None,
                    v_scale=kv_sh if kv_int8 else None)
    c1_gib = _per_device_gib(c1_shape, c1_sh)

    results = {}
    for name, build in (
        ("decode_chunk", lambda: jax.jit(
            partial(decode_chunk, cfg=cfg, n_steps=CHUNK_STEPS,
                    compute_dtype=jnp.bfloat16),
            donate_argnums=(2,), out_shardings=(rep, cache_sh),
        ).lower(
            params_abs, vec(jnp.int32), cache_abs, vec(jnp.bool_),
            vec(jnp.float32), vec(jnp.int32), vec(jnp.int32), key_abs,
        )),
        ("prefill_chunk", lambda: jax.jit(
            partial(_prefill_forward, cfg=cfg, compute_dtype=jnp.bfloat16),
            donate_argnums=(2,),
        ).lower(
            params_abs,
            jax.ShapeDtypeStruct((1, PREFILL_CHUNK), jnp.int32, sharding=rep),
            sds(c1_shape, c1_sh),
            jax.ShapeDtypeStruct((), jnp.int32, sharding=rep),
        )),
    ):
        t0 = time.time()
        comp = build().compile()
        ma = comp.memory_analysis()
        args_gib = ma.argument_size_in_bytes / GIB
        temp_gib = ma.temp_size_in_bytes / GIB
        results[name] = dict(args=args_gib, temp=temp_gib)
        print(json.dumps({
            "row": label, "program": name, "model": MODEL,
            "topology": topology, "tp": tp,
            "slots": max_slots, "max_len": max_len,
            "device_args_gib": round(args_gib, 2),
            "device_temp_gib": round(temp_gib, 2),
            "device_peak_gib": round(args_gib + temp_gib, 2),
            "compile_s": round(time.time() - t0, 1),
        }))

    # Steady-state residency: params + pool + one prefill c1 cache + the
    # larger of the two programs' temporaries (they never run concurrently
    # — the engine thread serialises dispatches).
    combined = (
        results["decode_chunk"]["args"] + c1_gib
        + max(results["decode_chunk"]["temp"], results["prefill_chunk"]["temp"])
    )
    print(json.dumps({
        "metric": f"llama7b_serving_fit_{label}",
        "params_gib_per_device": round(params_gib, 2),
        "kv_pool_gib_per_device": round(pool_gib, 2),
        "prefill_c1_gib_per_device": round(c1_gib, 2),
        "combined_peak_gib_per_device": round(combined, 2),
        "fits_16gib_hbm": combined < 16.0,
        "headroom_gib": round(16.0 - combined, 2),
    }))


def main() -> None:
    for row in ROWS:
        run_row(*row)


if __name__ == "__main__":
    main()
