"""Profile the control-plane scale lane: where do the control seconds go?

Runs :func:`tpu_engine.twin.scale_lane` under :mod:`cProfile` and prints
the top cumulative frames — the first stop when the ctl_scale flatness
gate (``tools/bench_sentinel.py``, ``benchmarks/ctl_scale.py``) reports
the overhead ratio creeping up. A frame whose per-call time grows
between ``--jobs 1000`` and ``--jobs 100000`` is the superlinear cost;
a frame that merely scales with the job count is the workload.

Run::

    JAX_PLATFORMS=cpu python tools/ctl_profile.py                # small config
    JAX_PLATFORMS=cpu python tools/ctl_profile.py --jobs 20000 --requests 200000
    JAX_PLATFORMS=cpu python tools/ctl_profile.py --top 40 --sort tottime
"""

from __future__ import annotations

import argparse
import cProfile
import io
import json
import os
import pstats
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--jobs", type=int, default=1_000,
                    help="submissions through the real scheduler")
    ap.add_argument("--requests", type=int, default=10_000,
                    help="requests through the real router")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--top", type=int, default=20,
                    help="frames to print (default 20)")
    ap.add_argument("--sort", default="cumulative",
                    choices=("cumulative", "tottime", "ncalls"),
                    help="pstats sort key (default cumulative)")
    args = ap.parse_args(argv)

    from tpu_engine.twin import ScaleLaneParams, scale_lane

    params = ScaleLaneParams(n_jobs=args.jobs, n_requests=args.requests)
    prof = cProfile.Profile()
    prof.enable()
    result = scale_lane(seed=args.seed, params=params)
    prof.disable()

    print(json.dumps({
        "jobs": args.jobs,
        "requests": args.requests,
        "overhead_us_per_fleet_s": result["overhead_us_per_fleet_s"],
        "phases": result["phases"],
    }, indent=2))
    out = io.StringIO()
    stats = pstats.Stats(prof, stream=out)
    stats.strip_dirs().sort_stats(args.sort).print_stats(args.top)
    print(out.getvalue())
    return 0


if __name__ == "__main__":
    sys.exit(main())
