"""Bench regression sentinel: deterministic ``bench.py`` scalars vs BASELINE.

``bench.py`` prints one JSON line per metric. Most values are hardware
timings (useless to gate in CI), but a subset is **CPU-stable**: the
chaos virtual-clock account, the goodput-ledger breakdown it feeds, and
the analytic pipeline-schedule tick account are bit-deterministic on any
machine. Those scalars live in ``BASELINE.json`` under ``"bench"``; this
tool re-derives them and fails (exit 1) when any tracked scalar drifts
by more than ``--threshold`` (default 15%) — the tier-1 gate that
catches "the refactor silently changed the numbers".

Modes:

- ``--run-quick`` (the CI mode, ``.github/workflows/tier1.yml``):
  re-computes just the deterministic metrics in-process — no devices, no
  timed compute, a few seconds on CPU.
- ``--input PATH|-`` — compare a saved ``bench.py`` JSON-lines output
  (``-`` = stdin) instead; hardware-timing keys are skipped via the
  noisy-key allowlist, so a full TPU bench log can be checked too.
- ``--update`` — write the observed values back as the new baseline
  (run after an *intentional* change, commit the diff).

Keys are compared flattened one level (``breakdown_pct.productive``).
Keys in :data:`NOISY_KEYS` (or ``--allow``) are never gated; metrics or
keys missing from the baseline are reported as ``new`` (not failures),
so adding a bench line never breaks CI until it is baselined.

Run: ``JAX_PLATFORMS=cpu python tools/bench_sentinel.py --run-quick``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Iterable, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_THRESHOLD = 0.15

# Wall-clock / load dependent keys: never gated, any machine any value.
NOISY_KEYS = {
    "makespan_s",
    "mean_wait_s",
    "serial_mean_wait_s",
    "step_time_ms",
    "bf16_step_time_ms",
    "int8_step_time_ms",
    "per_sample_ms",
    "1f1b_per_sample_ms",
    "tokens_per_sec",
    "tokens_per_sec_per_chip",
    "p50_ms",
    "p99_ms",
    "static_p99_ms",
    "dt_ms",
    "high_wait_s",
    "speedup_vs_serial",
    "goodput_work_s_per_wall_s",
    "loss_delta_final",
    "fleet_seconds_per_cpu_second",
    "ingest_samples_per_sec",
    "query_avg_us",
    # ctl_scale: nested wall-time profile + overhead ratio (prefix match
    # skips "overhead.*" / "phases.*"); flatness regressions are still
    # gated through the deterministic gates.* booleans.
    "overhead",
    "phases",
}


def _flatten(line: dict) -> dict[str, float]:
    """Numeric scalars of one metric line, nested dicts one level deep."""
    out: dict[str, float] = {}
    for k, v in line.items():
        if k == "metric":
            continue
        if isinstance(v, bool):
            out[k] = float(v)
        elif isinstance(v, (int, float)):
            out[k] = float(v)
        elif isinstance(v, dict):
            for kk, vv in v.items():
                if isinstance(vv, (int, float)) and not isinstance(vv, bool):
                    out[f"{k}.{kk}"] = float(vv)
    return out


def collect_quick() -> list[dict]:
    """Re-derive the deterministic bench lines in-process (no timing)."""
    from benchmarks.chaos import run_hetero_lane
    from benchmarks.chaos import run_trace as chaos_trace
    from benchmarks.scheduler_sim import run_warm_admission
    from benchmarks.serving_fleet_sim import run_disagg_ab
    from tpu_engine.parallel.pipeline_zb import schedule_account
    from tpu_engine.twin import (
        autopilot_bench_line,
        ctl_crash_bench_line,
        ctl_scale_bench_line,
        historian_bench_line,
        prefix_plane_bench_line,
        reshard_bench_line,
        spec_pool_bench_line,
        twin_bench_line,
    )

    trace = chaos_trace(seed=0)
    ab = run_disagg_ab(seed=0)
    gp = trace["goodput"]
    cc = trace["compile_cache"]
    warm = run_warm_admission(seed=0)
    het = run_hetero_lane(seed=0)
    zb = schedule_account("zb", 4, 16)
    f1b = schedule_account("1f1b", 4, 16)
    return [
        {
            "metric": "chaos_goodput_self_heal_vs_die_restart",
            "value": trace["goodput_improvement"],
            "mttr_reduction": trace["mttr_reduction"],
            "mttr_mean_s": trace["self_heal"]["mttr_mean_s"],
            "baseline_mttr_mean_s": trace["die_and_restart"]["mttr_mean_s"],
            "steps_saved": trace["steps_saved"],
            "zero_lost_steps": trace["self_heal"]["lost_steps"] == 0,
        },
        {
            "metric": "goodput_ledger_chaos_breakdown",
            "value": gp["goodput_fraction"],
            "breakdown_pct": gp["breakdown_pct"],
            "sum_error_pct": gp["sum_error_pct"],
            "alert_count": gp["slo"]["alert_count"],
            "sum_to_wall_ok": gp["sum_error_pct"] < 1.0,
        },
        {
            "metric": "compile_cache_warm_start",
            "value": cc["mttr_warm_reduction_pct"],
            "mttr_on_s": cc["mttr_on_s"],
            "mttr_off_s": cc["mttr_off_s"],
            "warm_resumes": cc["warm_resumes"],
            "cold_resumes": cc["cold_resumes"],
            "wall_saved_s": cc["wall_saved_s"],
            "mean_wait_fifo_s": warm["mean_wait_fifo_s"],
            "mean_wait_warm_s": warm["mean_wait_warm_s"],
            "wait_reduction_pct": warm["wait_reduction_pct"],
        },
        {
            "metric": "hetero_rebalance_goodput",
            "value": het["steady_goodput_on"],
            "rebalance_off": het["steady_goodput_off"],
            "shrink": het["steady_goodput_shrink"],
            "goodput_recovered": het["goodput_recovered"],
            "rebalance_step": het["rebalance_on"]["rebalance_step"],
            "global_batch_preserved": (
                sum(het["rebalance_on"]["assignment"])
                == het["params"]["global_micro"]
            ),
        },
        {
            "metric": "pipeline_schedule_zb_vs_1f1b",
            "ticks": zb["ticks"],
            "busy_fraction": round(zb["busy_fraction"], 4),
            "1f1b_busy_fraction": round(f1b["busy_fraction"], 4),
            "burned_cost_vs_1f1b": round(
                zb["burned_cost"] / f1b["burned_cost"], 3
            ),
        },
        {
            "metric": "serving_disagg_ttft_p99_vs_symmetric",
            "value": ab["ttft_p99_improvement"],
            "symmetric_ttft_p99_ms": ab["symmetric"]["ttft_p99_ms"],
            "disagg_ttft_p99_ms": ab["disagg"]["ttft_p99_ms"],
            "symmetric_tokens_per_sec": ab["symmetric"]["tokens_per_sec"],
            "disagg_tokens_per_sec": ab["disagg"]["tokens_per_sec"],
            "gates_pass": ab["gates_pass"],
        },
        twin_bench_line(seed=0),
        historian_bench_line(seed=0),
        autopilot_bench_line(seed=0),
        ctl_scale_bench_line(seed=0),
        prefix_plane_bench_line(seed=0),
        reshard_bench_line(seed=0),
        spec_pool_bench_line(seed=0),
        ctl_crash_bench_line(seed=0),
    ]


def read_lines(path: str) -> list[dict]:
    """Parse ``bench.py`` output: one JSON object per non-empty line."""
    fh = sys.stdin if path == "-" else open(path, encoding="utf-8")
    try:
        out = []
        for raw in fh:
            raw = raw.strip()
            if not raw or not raw.startswith("{"):
                continue
            try:
                obj = json.loads(raw)
            except json.JSONDecodeError:
                continue
            if isinstance(obj, dict) and "metric" in obj:
                out.append(obj)
        return out
    finally:
        if fh is not sys.stdin:
            fh.close()


def compare(
    lines: Iterable[dict],
    baseline: dict[str, dict[str, float]],
    threshold: float,
    allow: Optional[set[str]] = None,
) -> dict[str, Any]:
    """Gate observed metric lines against the baseline scalars.

    Returns ``{"ok", "regressions": [...], "new": [...], "checked": N}``;
    a regression is any tracked key whose relative delta exceeds
    ``threshold`` (absolute delta when the baseline value is 0)."""
    allow = NOISY_KEYS | (allow or set())
    regressions, new, checked = [], [], 0
    for line in lines:
        name = line["metric"]
        base = baseline.get(name)
        if base is None:
            new.append({"metric": name})
            continue
        obs = _flatten(line)
        for key, val in sorted(obs.items()):
            if key in allow or key.split(".")[0] in allow:
                continue
            if key not in base:
                new.append({"metric": name, "key": key, "observed": val})
                continue
            bv = float(base[key])
            checked += 1
            delta = abs(val - bv) if bv == 0 else abs(val - bv) / abs(bv)
            if delta > threshold:
                regressions.append({
                    "metric": name,
                    "key": key,
                    "baseline": bv,
                    "observed": val,
                    "rel_delta": round(delta, 4),
                })
    return {
        "ok": not regressions,
        "threshold": threshold,
        "checked": checked,
        "regressions": regressions,
        "new": new,
    }


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "--baseline",
        default=os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "BASELINE.json",
        ),
    )
    parser.add_argument(
        "--input", default=None, metavar="PATH",
        help="bench.py JSON-lines output to check ('-' = stdin)",
    )
    parser.add_argument(
        "--run-quick", action="store_true",
        help="re-derive the deterministic metrics in-process (CI mode)",
    )
    parser.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD)
    parser.add_argument(
        "--allow", action="append", default=[], metavar="KEY",
        help="extra noisy key to skip (repeatable)",
    )
    parser.add_argument(
        "--update", action="store_true",
        help="write the observed scalars back as the new baseline",
    )
    args = parser.parse_args()
    if not args.run_quick and args.input is None:
        parser.error("one of --run-quick / --input is required")

    lines = collect_quick() if args.run_quick else read_lines(args.input)
    with open(args.baseline, encoding="utf-8") as f:
        doc = json.load(f)
    if args.update:
        bench = doc.setdefault("bench", {})
        for line in lines:
            tracked = {
                k: v for k, v in _flatten(line).items()
                if k not in NOISY_KEYS and k.split(".")[0] not in NOISY_KEYS
            }
            if tracked:
                bench[line["metric"]] = tracked
        with open(args.baseline, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
        print(json.dumps({"updated": sorted(bench), "path": args.baseline}))
        return

    report = compare(
        lines, doc.get("bench", {}), args.threshold, set(args.allow)
    )
    print(json.dumps(report, indent=2))
    if not report["ok"]:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
