"""Flight recorder: span/event invariants, bounded drops, Chrome-trace
export, step-time anomaly attribution (deterministic via the host-slow
fault seam), the auto-trace hook, and the full chaos lifecycle chain
(detect → emergency-save → requeue → shrink-admit → resume) recorded as
causally-linked spans under one job trace.
"""

import json

import pytest

from tpu_engine import faults, tracing
from tpu_engine.faults import FaultKind, FaultPlan, FaultSpec
from tpu_engine.mesh_runtime import MeshConfig
from tpu_engine.scheduler import FleetScheduler, SubmissionState
from tpu_engine.sharding import Precision, ShardingStage, TPUTrainConfig
from tpu_engine.supervisor import JobStatus, TrainingJob
from tpu_engine.tpu_manager import TPUManager
from tpu_engine.tracing import FlightRecorder, StepTimeAnomalyDetector


@pytest.fixture(autouse=True)
def _clean_process_state():
    """Fresh recorder per test (the integration paths write to the
    process-wide one) and no leaked fault plan."""
    faults.clear_active()
    prev = tracing.get_recorder()
    tracing.set_recorder(FlightRecorder())
    yield
    tracing.set_recorder(prev)
    faults.clear_active()


def tiny_config(tmp, **kw) -> TPUTrainConfig:
    base = dict(
        model_name="gpt-tiny",
        sharding_stage=ShardingStage.FULL_PARTITIONING,
        mesh=MeshConfig(data=2, fsdp=4),
        micro_batch_size=1,
        gradient_accumulation_steps=1,
        seq_len=32,
        precision=Precision.FP32,
        total_steps=10,
        activation_checkpointing=False,
        checkpoint_dir=str(tmp),
        checkpoint_interval_steps=100,
        log_every_steps=1,
    )
    base.update(kw)
    return TPUTrainConfig(**base)


# ---------------------------------------------------------------------------
# recorder invariants
# ---------------------------------------------------------------------------


def test_span_lifecycle_and_causal_links():
    rec = FlightRecorder()
    root = rec.start_span("job:x", kind="job", t0=1.0)
    tid = root.trace_id
    assert rec.trace_root(tid) == root.span_id
    # Children inherit the parent's trace; parent_id forms the causal link.
    child = rec.start_span("attempt", kind="attempt", parent=root, t0=2.0)
    assert child.trace_id == tid and child.parent_id == root.span_id
    child.end(t1=3.0, status="ok")
    root.end(t1=4.0)
    spans = rec.spans(trace_id=tid)
    assert [s["name"] for s in spans] == ["job:x", "attempt"]
    assert spans[1]["duration_s"] == 1.0
    assert spans[1]["attrs"]["status"] == "ok"
    traces = rec.traces()
    assert traces[0]["trace_id"] == tid
    assert traces[0]["root_name"] == "job:x" and traces[0]["spans"] == 2


def test_end_clamps_reversed_timestamps():
    rec = FlightRecorder()
    s = rec.record_span("x", t0=5.0, t1=4.0)  # virtual-clock skew
    assert s.t1 == 5.0 and s.duration_s == 0.0


def test_bounded_buffers_count_drops():
    rec = FlightRecorder(max_spans=4, max_events=4)
    for i in range(10):
        rec.record_span(f"s{i}", t0=float(i), t1=float(i))
        rec.event(f"e{i}", trace_id="t", ts=float(i))
    assert len(rec.spans(limit=0)) == 4
    assert len(rec.events(limit=0)) == 4
    st = rec.stats()
    # Nothing silent: totals keep counting, evictions are accounted for.
    assert st["spans_total"] == 10 and st["spans_dropped"] == 6
    assert st["events_total"] == 10 and st["events_dropped"] == 6


def test_cancel_drops_span_without_recording():
    rec = FlightRecorder()
    s = rec.start_span("retry-pass", t0=0.0)
    s.cancel()
    assert rec.spans(limit=0) == []


def test_jsonl_persistence_bounded_rotation(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    rec = FlightRecorder(persist_path=path, persist_max_bytes=400)
    for i in range(20):
        rec.record_span(f"span{i}", trace_id="t", t0=float(i), t1=float(i))
    st = rec.stats()["persist"]
    assert st["rotations"] >= 1 and st["errors"] == 0
    assert st["bytes"] <= 400
    # Both generations hold valid JSONL records.
    for p in (path, path + ".1"):
        with open(p) as f:
            recs = [json.loads(line) for line in f]
        assert all(r["record"] == "span" for r in recs)


def test_export_chrome_trace_format():
    rec = FlightRecorder()
    root = rec.start_span("job:x", kind="job", t0=1.0)
    child = rec.start_span("save", kind="checkpoint_save", parent=root, t0=2.0)
    child.end(t1=3.0)
    root.end(t1=4.0)
    rec.event("requeue", kind="scheduler", trace_id=root.trace_id, ts=2.5)
    doc = rec.export_chrome_trace(trace_id=root.trace_id)
    evs = doc["traceEvents"]
    assert all("ph" in e and "ts" in e and "pid" in e for e in evs)
    phases = {e["ph"] for e in evs}
    assert {"M", "X", "i"} <= phases
    # Causal parent link rides as a Chrome flow arrow (start + finish).
    assert "s" in phases and "f" in phases
    # Spans are complete events with a duration; instants carry scope.
    for e in evs:
        if e["ph"] == "X":
            assert e["dur"] >= 0
        if e["ph"] == "i":
            assert e["s"] == "t"
    # Non-metadata timestamps are sorted (Perfetto requirement).
    body = [e["ts"] for e in evs if e["ph"] != "M"]
    assert body == sorted(body)
    # pid lane is named after the trace via process_name metadata.
    meta = [e for e in evs if e["ph"] == "M" and e["name"] == "process_name"]
    assert meta and root.trace_id in meta[0]["args"]["name"]


# ---------------------------------------------------------------------------
# anomaly detection + attribution
# ---------------------------------------------------------------------------


def test_detector_warmup_baseline_and_sustained():
    det = StepTimeAnomalyDetector(warmup=3, ratio=1.5, min_excess_s=0.01,
                                  sustained_k=2)
    assert det.baseline_s is None
    for s in range(1, 4):
        assert det.observe(s, 0.1) is None  # warming up
    assert det.baseline_s == pytest.approx(0.1)
    a1 = det.observe(4, 0.5)
    assert a1 is not None and not a1["sustained"]
    assert a1["excess_s"] == pytest.approx(0.4)
    a2 = det.observe(5, 0.5)
    assert a2 is not None and a2["sustained"]
    # Outliers never entered the baseline — no normalising-away.
    assert det.baseline_s == pytest.approx(0.1)
    assert det.observe(6, 0.1) is None  # recovery resets the streak
    assert det.consecutive == 0
    assert det.summary()["flagged_total"] == 2


def test_attribution_priority_order():
    rec = FlightRecorder()
    tid = rec.new_trace_id()
    # Only a checkpoint save overlaps → checkpoint-save.
    rec.record_span("save", kind="checkpoint_save", trace_id=tid,
                    t0=10.0, t1=11.0)
    assert rec.attribute(tid, 9.5, 11.5) == "checkpoint-save"
    # A fault event in the same window outranks it.
    rec.event("host-slow", kind="fault", trace_id=tid, ts=10.5)
    assert rec.attribute(tid, 9.5, 11.5) == "host-slow"
    # Disjoint window → unknown.
    assert rec.attribute(tid, 100.0, 101.0) == "unknown"


def test_record_anomaly_counts_by_cause():
    rec = FlightRecorder()
    rec.record_anomaly("host-slow", trace_id="t", ts=1.0)
    rec.record_anomaly("host-slow", trace_id="t", ts=2.0)
    rec.record_anomaly("unknown", trace_id="t", ts=3.0)
    st = rec.stats()
    assert st["anomalies_total"] == 3
    assert st["anomalies_by_cause"] == {"host-slow": 2, "unknown": 1}
    evs = rec.events(trace_id="t", kind="anomaly", limit=0)
    assert [e["name"] for e in evs][:2] == ["step_anomaly:host-slow"] * 2


def test_host_slow_anomaly_attributed_deterministically(tmp_path):
    """The acceptance seam: an injected host-slow stall at a known step is
    flagged by the sliding baseline AND attributed to the injected cause
    (the supervisor records the fault event before the anomaly check)."""
    faults.activate(FaultPlan(seed=0, specs=[
        FaultSpec(kind=FaultKind.HOST_SLOW, at_step=8, slow_s=3.0, count=2),
    ]))
    det = StepTimeAnomalyDetector(warmup=3, ratio=1.5, min_excess_s=0.05)
    job = TrainingJob("anom-job", tiny_config(tmp_path / "ckpt"),
                      anomaly_detector=det)
    job.start()
    job.join(timeout=300)
    assert job.status == JobStatus.COMPLETED, job.error
    assert job.anomalies_total >= 1
    assert job.last_anomaly["cause"] == "host-slow"
    assert job.last_anomaly["step"] in (8, 9)
    d = job.describe()
    assert d["trace_id"] == job.trace_id
    assert d["last_anomaly"]["cause"] == "host-slow"
    rec = tracing.get_recorder()
    anoms = rec.events(trace_id=job.trace_id, kind="anomaly", limit=0)
    assert any(e["name"] == "step_anomaly:host-slow" for e in anoms)


class _FakeTraceSession:
    def __init__(self):
        self.calls = []

    def start(self, log_dir, duration_s=None):
        self.calls.append((log_dir, duration_s))
        return {"log_dir": log_dir}


def test_sustained_regression_auto_starts_trace(tmp_path):
    """Opt-in hook: sustained slow steps auto-start ONE bounded capture."""
    faults.activate(FaultPlan(seed=0, specs=[
        FaultSpec(kind=FaultKind.HOST_SLOW, at_step=6, slow_s=3.0, count=3),
    ]))
    det = StepTimeAnomalyDetector(warmup=3, ratio=1.5, min_excess_s=0.05,
                                  sustained_k=2)
    fake = _FakeTraceSession()
    job = TrainingJob(
        "auto-trace-job", tiny_config(tmp_path / "ckpt"),
        anomaly_detector=det, anomaly_trace_session=fake,
        anomaly_trace_dir=str(tmp_path / "anomtrace"),
    )
    job.start()
    job.join(timeout=300)
    assert job.status == JobStatus.COMPLETED, job.error
    # Three anomalous steps, one capture (no retry storm), bounded duration.
    assert fake.calls == [(str(tmp_path / "anomtrace"), 30.0)]
    evs = tracing.get_recorder().events(trace_id=job.trace_id, limit=0)
    assert any(e["name"] == "auto_trace_started" for e in evs)


# ---------------------------------------------------------------------------
# the chaos lifecycle chain, end to end through the real scheduler
# ---------------------------------------------------------------------------


def test_chaos_lifecycle_recorded_as_causal_chain(tmp_path):
    """Chip death at step 3 → the whole recovery lifecycle lands on ONE
    trace: submit → admission → attempt → detect/emergency-save → requeue
    → shrink-admit → resume, causally linked, exportable as Chrome JSON."""
    mgr = TPUManager()
    faults.activate(FaultPlan(seed=1, specs=[
        FaultSpec(kind=FaultKind.CHIP_UNHEALTHY, at_step=3, device_index=5),
    ]))
    cfg = tiny_config(
        tmp_path / "ckpt", mesh=MeshConfig(data=4, fsdp=2), total_steps=6,
        checkpoint_interval_steps=2, elastic_min_devices=2,
    )
    sched = FleetScheduler(
        max_concurrent_jobs=1, fleet_fn=mgr.get_fleet_status,
        poll_interval_s=0.05,
    )
    try:
        sub = sched.submit(cfg, job_kwargs={"auto_rollback": False})
        sub = sched.wait(sub.submission_id, timeout=600)
        assert sub.state == SubmissionState.COMPLETED
    finally:
        sched.shutdown()

    rec = tracing.get_recorder()
    spans = rec.spans(trace_id=sub.trace_id, limit=0)
    kinds = {s["kind"] for s in spans}
    assert {"job", "admission", "attempt", "compile", "emergency_save",
            "final_save"} <= kinds
    events = rec.events(trace_id=sub.trace_id, limit=0)
    ev_names = {e["name"] for e in events}
    assert {"submit", "requeue", "shrink_admit", "resume"} <= ev_names

    # Causality: both attempts hang off the job root; the root closed with
    # the terminal state.
    root_id = rec.trace_root(sub.trace_id)
    attempts = [s for s in spans if s["kind"] == "attempt"]
    assert len(attempts) == 2
    assert all(a["parent_id"] == root_id for a in attempts)
    (root,) = [s for s in spans if s["span_id"] == root_id]
    assert root["t1"] is not None and root["attrs"]["submission_id"]
    assert attempts[0]["attrs"]["preemption_reason"].startswith("self-heal")
    assert attempts[1]["attrs"]["resumed_from_step"] == 3

    # And it exports as a loadable Chrome trace.
    doc = rec.export_chrome_trace(trace_id=sub.trace_id)
    json.loads(json.dumps(doc))  # serialisable
    evs = doc["traceEvents"]
    assert all("ph" in e and "ts" in e and "pid" in e for e in evs)
    body = [e["ts"] for e in evs if e["ph"] != "M"]
    assert body == sorted(body)
    assert {e["ph"] for e in evs} >= {"X", "i", "s", "f"}


# ---------------------------------------------------------------------------
# FaultInjector event-log truncation is accounted, never silent
# ---------------------------------------------------------------------------


def test_fault_injector_counts_dropped_events():
    inj = faults.FaultInjector(FaultPlan(seed=0, specs=[]))
    inj.MAX_EVENTS = 5
    for i in range(12):
        inj.record("external", step=i, detail=f"obs {i}")
    assert len(inj.events) == 5
    assert inj.events_dropped == 7
    # Still monotonic after further drops, and surfaced in describe().
    inj.record("external", step=99)
    assert inj.events_dropped == 8
    d = inj.describe()
    assert d["events_dropped"] == 8
    assert inj.describe_full()["events_dropped"] == 8
    # The retained window is the newest events.
    assert [e.step for e in inj.events] == [8, 9, 10, 11, 99]


def test_fault_records_mirror_onto_recorder():
    rec = tracing.get_recorder()
    inj = faults.FaultInjector(FaultPlan(seed=0, specs=[]))
    inj.record("external", step=7, detail="mirror me")
    evs = rec.events(trace_id="fleet", kind="fault", limit=0)
    assert any(e["name"] == "external" and e["attrs"]["step"] == 7
               for e in evs)


# ---------------------------------------------------------------------------
# benchmark exports produce Perfetto-loadable trace files
# ---------------------------------------------------------------------------


def _assert_perfetto_loadable(path):
    with open(path) as f:
        doc = json.load(f)
    evs = doc["traceEvents"]
    assert evs, "empty trace"
    assert all("ph" in e and "ts" in e and "pid" in e for e in evs)
    body = [e["ts"] for e in evs if e["ph"] != "M"]
    assert body == sorted(body), "timestamps must be monotonic"
    return doc


def test_chaos_benchmark_writes_perfetto_trace(tmp_path, monkeypatch, capsys):
    from benchmarks import chaos

    out = str(tmp_path / "chaos_trace.json")
    monkeypatch.setattr(
        "sys.argv",
        ["chaos", "--seed", "0", "--trace-out", out],
    )
    chaos.main()  # raises SystemExit(1) if the policy comparison regresses
    doc = _assert_perfetto_loadable(out)
    names = {e.get("name") for e in doc["traceEvents"]}
    # The recovery chain the benchmark simulates, span by span.
    assert {"detect", "emergency_save", "requeue", "shrink_admit",
            "resume", "grow_back"} <= names
    # Causal links exported as flow arrows.
    assert any(e["ph"] == "s" for e in doc["traceEvents"])
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["ok"]


def test_trace_breakdown_capture_writes_perfetto_trace(tmp_path):
    from benchmarks.trace_breakdown import capture

    rec = FlightRecorder()
    wall, xplane = capture(
        logdir=str(tmp_path / "xplane"), steps=1, model="gpt-tiny",
        micro=1, seq=64, mesh_axes={"data": 8}, recorder=rec,
    )
    assert wall > 0
    out = str(tmp_path / "tb_trace.json")
    with open(out, "w") as f:
        json.dump(rec.export_chrome_trace(), f)
    doc = _assert_perfetto_loadable(out)
    names = {e.get("name") for e in doc["traceEvents"]}
    assert {"compile", "warmup", "profile_capture"} <= names
