"""Ulysses (all-to-all) attention correctness: forward + gradients vs full
attention, plus end-to-end sequence-parallel training parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_engine.mesh_runtime import MeshConfig, build_mesh
from tpu_engine.ops.flash_attention import mha
from tpu_engine.parallel.ulysses_attention import ulysses_mha
from tpu_engine.sharding import Precision, ShardingStage, TPUTrainConfig
from tpu_engine.train import build_train_program


def _rand_qkv(key, B=4, S=64, H=4, KV=4, D=16, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, S, H, D), dtype)
    k = jax.random.normal(kk, (B, S, KV, D), dtype)
    v = jax.random.normal(kv, (B, S, KV, D), dtype)
    return q, k, v


@pytest.mark.parametrize("seq_axis", [2, 4])
def test_ulysses_matches_full_attention(seq_axis):
    mesh = build_mesh(MeshConfig(sequence=seq_axis))
    q, k, v = _rand_qkv(jax.random.PRNGKey(0))
    ref = mha(q, k, v, causal=True, force_xla=True)
    out = jax.jit(lambda q, k, v: ulysses_mha(q, k, v, mesh=mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ulysses_gqa_expands_when_kv_indivisible():
    # KV=2 heads over a 4-way sequence axis → expands to full heads pre-swap.
    mesh = build_mesh(MeshConfig(sequence=4))
    q, k, v = _rand_qkv(jax.random.PRNGKey(1), H=8, KV=2)
    ref = mha(q, k, v, causal=True, force_xla=True)
    out = jax.jit(lambda q, k, v: ulysses_mha(q, k, v, mesh=mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ulysses_gqa_preserved_when_divisible():
    # KV=4 over a 2-way axis divides evenly: GQA ratio survives the swap.
    mesh = build_mesh(MeshConfig(sequence=2))
    q, k, v = _rand_qkv(jax.random.PRNGKey(4), H=8, KV=4)
    ref = mha(q, k, v, causal=True, force_xla=True)
    out = jax.jit(lambda q, k, v: ulysses_mha(q, k, v, mesh=mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ulysses_gradients_match():
    mesh = build_mesh(MeshConfig(sequence=4))
    q, k, v = _rand_qkv(jax.random.PRNGKey(2), S=32)

    def loss_uly(q, k, v):
        return jnp.sum(ulysses_mha(q, k, v, mesh=mesh) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha(q, k, v, causal=True, force_xla=True) ** 2)

    g_uly = jax.jit(jax.grad(loss_uly, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_uly, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-4)


def test_ulysses_with_combined_mesh_axes():
    # All-to-all SP composes with data/fsdp/model sharding; the per-device
    # head count after the model split (4/2=2) still divides sequence=2.
    mesh = build_mesh(MeshConfig(data=1, fsdp=2, sequence=2, model=2))
    q, k, v = _rand_qkv(jax.random.PRNGKey(3), B=4, S=32, H=4, KV=4)
    ref = mha(q, k, v, causal=True, force_xla=True)
    out = jax.jit(lambda q, k, v: ulysses_mha(q, k, v, mesh=mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ulysses_head_divisibility_fails_fast():
    # gpt-tiny has 4 heads; model=2 leaves 2 per device — not divisible by
    # sequence=4. Must fail at build time, not from inside the shard_map.
    cfg = TPUTrainConfig(
        model_name="gpt-tiny",
        mesh=MeshConfig(data=1, fsdp=1, sequence=4, model=2),
        attention_impl="ulysses",
        seq_len=64,
        precision=Precision.FP32,
    )
    with pytest.raises(ValueError, match="divisible by"):
        build_train_program(cfg)


def test_ulysses_training_matches_ring_and_baseline():
    # Same global batch: attention_impl="ulysses" over a 4-way sequence axis
    # must reproduce the non-SP trajectory (and hence the ring one, which
    # test_sequence_parallel_train already pins to the baseline).
    def cfg(**kw):
        base = dict(
            model_name="gpt-tiny",
            sharding_stage=ShardingStage.FULL_PARTITIONING,
            mesh=MeshConfig(data=2, fsdp=4),
            micro_batch_size=1,
            gradient_accumulation_steps=1,
            seq_len=64,
            precision=Precision.FP32,
            learning_rate=1e-2,
            warmup_steps=2,
            total_steps=100,
            activation_checkpointing=False,
        )
        base.update(kw)
        return TPUTrainConfig(**base)

    def run(c, n=3):
        prog = build_train_program(c)
        state = prog.init(jax.random.PRNGKey(0))
        losses = []
        for _ in range(n):
            state, m = prog.step(state, prog.synthetic_batch(0))
            losses.append(float(m["loss"]))
        return prog, losses

    prog_uly, losses_uly = run(
        cfg(mesh=MeshConfig(data=1, fsdp=2, sequence=4), micro_batch_size=4,
            attention_impl="ulysses")
    )
    assert prog_uly.model_config.attention_impl == "ulysses"
    _, losses_ref = run(cfg(mesh=MeshConfig(data=2, fsdp=4), micro_batch_size=1))
    np.testing.assert_allclose(losses_uly, losses_ref, rtol=1e-3)
    assert losses_uly[-1] < losses_uly[0]


# Compile-heavy module: excluded from the fast core run (pytest -m "not slow").
pytestmark = pytest.mark.slow
