"""ZeRO++ comm compression (``tpu_engine/comm_compress.py``): quantize
round-trip bounds, the compressed train step's loss parity with the fp32
GSPMD path, int8 actually on the wire (compiled-HLO byte accounting), hpZ
store consistency, and the config validators that keep impossible combos
from reaching the SPMD partitioner (which aborts, not raises, on them)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_engine import comm_compress as cc
from tpu_engine.mesh_runtime import MeshConfig, MeshRuntime
from tpu_engine.sharding import (
    OffloadDevice, Precision, ShardingStage, TPUTrainConfig,
)
from tpu_engine.train import build_train_program


# ---------------------------------------------------------------------------
# Quantization numerics
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("block", [16, 64, 256])
def test_roundtrip_error_bound(block):
    """Per-block absmax/127 scales ⇒ round-trip error ≤ half a quantization
    step of the block's own scale — checked per block, not globally (the
    global bound would be weaker than what blocking buys)."""
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 3 * block + 7)) * 5.0
    codes, scales = cc.blockwise_quantize(x, block)
    nb = -(-x.shape[-1] // block)
    assert codes.shape == (4, nb * block) and codes.dtype == jnp.int8
    assert scales.shape == (4, nb) and scales.dtype == jnp.float32
    y = cc.blockwise_dequantize(codes, scales, block, last=x.shape[-1])
    err = np.abs(np.asarray(y - x))
    # err[i, j] ≤ scale_of_block(j)/2  (+eps for the division rounding)
    per_elem_bound = np.repeat(np.asarray(scales), block, axis=-1)[
        :, : x.shape[-1]
    ]
    assert np.all(err <= per_elem_bound / 2 + 1e-6)


def test_roundtrip_exact_on_grid():
    """Values already on the int8 grid survive exactly (scale = absmax/127,
    codes hit integers)."""
    x = jnp.arange(-127, 128, dtype=jnp.float32).reshape(1, 255) * 0.5
    codes, scales = cc.blockwise_quantize(x, 255)
    y = cc.blockwise_dequantize(codes, scales, 255, last=255)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x), rtol=1e-6)


def test_stochastic_rounding_unbiased():
    """floor(v + u) with u~U[0,1) is unbiased: the mean dequantized value
    over many keys converges to the input (nearest rounding would sit a
    deterministic fraction of a step off)."""
    x = jnp.full((1, 64), 0.3)
    deqs = []
    for i in range(300):
        codes, scales = cc.blockwise_quantize(
            x, 64, key=jax.random.PRNGKey(i)
        )
        deqs.append(cc.blockwise_dequantize(codes, scales, 64, last=64))
    mean = float(jnp.mean(jnp.stack(deqs)))
    step = 0.3 / 127  # one quantization step
    assert abs(mean - 0.3) < step / 5, (mean, step)


def test_slice_groups():
    intra, cross = cc.data_slice_groups(4, 2)
    assert intra == [[0, 1], [2, 3]]
    assert cross == [[0, 2], [1, 3]]
    intra1, cross1 = cc.data_slice_groups(4, 4)
    assert intra1 == [[0], [1], [2], [3]]
    assert cross1 == [[0, 1, 2, 3]]
    with pytest.raises(ValueError, match="divisible"):
        cc.data_slice_groups(4, 3)


# ---------------------------------------------------------------------------
# Compressed training: parity + wire bytes (shared compiled programs)
# ---------------------------------------------------------------------------


def _cfg(**kw) -> TPUTrainConfig:
    base = dict(
        model_name="gpt-tiny",
        sharding_stage=ShardingStage.FULL_PARTITIONING,
        mesh=MeshConfig(data=4, fsdp=2, dcn_data=2),
        micro_batch_size=2,
        gradient_accumulation_steps=2,
        seq_len=32,
        precision=Precision.FP32,
        param_dtype=Precision.FP32,
        learning_rate=1e-2,
        warmup_steps=2,
        total_steps=100,
        comm_quant_block_size=64,
    )
    base.update(kw)
    return TPUTrainConfig(**base)


def _hybrid_runtime(cfg) -> MeshRuntime:
    # Two simulated slices over the 8 virtual CPU devices: data indices
    # {0,1} on slice 0, {2,3} on slice 1 (the mesh lays whole slices as
    # outer data blocks).
    return MeshRuntime(cfg.mesh, slice_assignments=[0, 0, 0, 0, 1, 1, 1, 1])


def _run(prog, n, seed=0):
    state = prog.init(jax.random.PRNGKey(prog.config.seed))
    batch = prog.synthetic_batch(seed)  # fixed batch → loss must drop
    losses = []
    for _ in range(n):
        state, metrics = prog.step(state, batch)
        losses.append(float(metrics["loss"]))
    return state, losses


@pytest.fixture(scope="module")
def baseline_run():
    cfg = _cfg()
    prog = build_train_program(cfg, runtime=_hybrid_runtime(cfg))
    state, losses = _run(prog, 6)
    return prog, state, losses


@pytest.fixture(scope="module")
def compressed_run():
    cfg = _cfg(comm_quant_weights=True, comm_secondary_weights=True,
               comm_quant_grads=True)
    prog = build_train_program(cfg, runtime=_hybrid_runtime(cfg))
    state, losses = _run(prog, 6)
    return prog, state, losses


def test_loss_parity(baseline_run, compressed_run):
    """qwZ+hpZ+qgZ training tracks the fp32-comm GSPMD path: same batch,
    same init, |Δloss| within tolerance at every step — and both actually
    train (loss drops)."""
    _, _, base = baseline_run
    _, _, comp = compressed_run
    assert base[-1] < base[0] and comp[-1] < comp[0]
    for b, c in zip(base, comp):
        assert abs(b - c) < 0.05, (base, comp)


def test_qwz_only_loss_parity(baseline_run):
    """qwZ alone (no secondary store, no grad quant) also tracks fp32."""
    cfg = _cfg(comm_quant_weights=True)
    prog = build_train_program(cfg, runtime=_hybrid_runtime(cfg))
    state, losses = _run(prog, 4)
    assert "hpz" not in state
    _, _, base = baseline_run
    for b, c in zip(base, losses):
        assert abs(b - c) < 0.05


def test_int8_on_wire_and_cross_slice_reduction(baseline_run, compressed_run):
    """The compiled step's HLO must show int8 all-gathers (the wire dtype
    IS the operand dtype — a dequant fused below the gather would move
    fp32), and ring-model byte accounting must show the ≥3x cross-slice
    reduction the subsystem exists for."""
    base_prog, base_state, _ = baseline_run
    comp_prog, comp_state, _ = compressed_run
    slice_of = cc.slice_of_partition(
        dict(comp_prog.mesh.shape), comp_prog.config.mesh.dcn_data
    )
    assert slice_of == [0, 0, 0, 0, 1, 1, 1, 1]

    def hlo_of(prog, state):
        batch = jax.ShapeDtypeStruct(prog.global_batch_shape(), jnp.int32)
        return prog.step.lower(
            jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), state
            ),
            batch,
        ).compile().as_text()

    comp_hlo = hlo_of(comp_prog, comp_state)
    assert "s8[" in comp_hlo and "all-gather" in comp_hlo
    comp_stats = cc.collective_stats(comp_hlo, slice_of)
    base_stats = cc.collective_stats(hlo_of(base_prog, base_state), slice_of)
    assert base_stats["cross_slice_bytes"] > 0
    reduction = base_stats["cross_slice_bytes"] / max(
        comp_stats["cross_slice_bytes"], 1
    )
    assert reduction >= 3.0, (base_stats, comp_stats)
    # Total wire volume must shrink too, not just move intra-slice.
    assert comp_stats["total_wire_bytes"] < base_stats["total_wire_bytes"]


def test_hpz_store_consistency(compressed_run):
    """The secondary store is exactly blockwise_quantize of the primary
    partition's local shards (refresh ran after the last update), and its
    leaves are int8 codes + fp32 scales sharded like the params."""
    prog, state, _ = compressed_run
    assert "hpz" in state
    block = prog.config.comm_quant_block_size
    codes_tree = state["hpz"]["codes"]
    q_codes = codes_tree["layers"]["q"]["kernel"]
    assert q_codes.dtype == jnp.int8
    # Verify one leaf end-to-end: quantizing the current param shard
    # reproduces the stored codes.
    w = state["params"]["layers"]["q"]["kernel"]
    expect_codes, expect_scales = cc.blockwise_quantize(
        jnp.asarray(w), block
    )
    np.testing.assert_array_equal(
        np.asarray(q_codes), np.asarray(expect_codes)
    )
    np.testing.assert_allclose(
        np.asarray(state["hpz"]["scales"]["layers"]["q"]["kernel"]),
        np.asarray(expect_scales), rtol=1e-6,
    )
    # Norm scales are not quantized — pruned (None) in the secondary store.
    assert codes_tree["final_norm"]["scale"] is None


def test_compressed_on_plain_fsdp_mesh():
    """No dcn axis (single slice): qwZ still works — the data-axis grad
    reduction degenerates to a plain psum and loss still drops."""
    cfg = _cfg(mesh=MeshConfig(data=2, fsdp=4), comm_quant_weights=True)
    prog = build_train_program(cfg)
    _, losses = _run(prog, 6)
    assert losses[-1] < losses[0] * 0.9, losses


# ---------------------------------------------------------------------------
# Config/build-time rejections
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "kw, match",
    [
        (dict(comm_secondary_weights=True), "requires comm_quant_weights"),
        (dict(comm_quant_weights=True,
              sharding_stage=ShardingStage.GRADIENT_PARTITIONING),
         "sharding_stage=3"),
        (dict(comm_quant_grads=True, pipeline_schedule="1f1b"), "1f1b"),
        (dict(comm_quant_weights=True,
              grad_allreduce_dtype=Precision.BF16), "redundant"),
        (dict(comm_quant_weights=True, lora_rank=4), "LoRA"),
        (dict(comm_quant_weights=True,
              param_offload=OffloadDevice.HOST), "param_offload"),
        (dict(comm_quant_weights=True, mesh=MeshConfig(data=2, fsdp=2,
                                                       model=2)), "model=1"),
        (dict(comm_quant_weights=True, attention_impl="flash"), "flash"),
    ],
)
def test_config_rejections(kw, match):
    base = dict(
        model_name="gpt-tiny", sharding_stage=ShardingStage.FULL_PARTITIONING,
        mesh=MeshConfig(data=2, fsdp=4), seq_len=32,
    )
    base.update(kw)
    with pytest.raises(ValueError, match=match):
        TPUTrainConfig(**base)


def test_disk_offload_rejection(tmp_path):
    with pytest.raises(ValueError, match="disk"):
        _cfg(comm_quant_weights=True,
             optimizer_offload=OffloadDevice.DISK,
             optimizer_spill_dir=str(tmp_path))


def test_moe_rejected_at_build():
    cfg = TPUTrainConfig(
        model_name="moe-tiny",
        sharding_stage=ShardingStage.FULL_PARTITIONING,
        mesh=MeshConfig(data=2, fsdp=4), seq_len=32,
        comm_quant_weights=True,
    )
    with pytest.raises(ValueError, match="MoE"):
        build_train_program(cfg)


# ---------------------------------------------------------------------------
# Plan / API surface
# ---------------------------------------------------------------------------


def test_compression_plan():
    from tpu_engine.comm import compression_plan

    off = compression_plan(_cfg())
    assert off["enabled"] is False
    on = compression_plan(
        _cfg(comm_quant_weights=True, comm_quant_grads=True,
             comm_quant_block_size=256)
    )
    assert on["enabled"] is True
    assert on["block_size"] == 256
    # int8 + fp32/256 scales vs fp32 ⇒ 4 / (1 + 4/256) ≈ 3.94x
    assert 3.9 < on["weight_gather_volume_factor"] < 4.0
    assert 3.9 < on["cross_slice_grad_volume_factor"] < 4.0


def test_launcher_plan_includes_compression():
    from tpu_engine.launcher import TPULauncher

    plan = TPULauncher().generate_plan(_cfg(comm_quant_weights=True))
    assert plan["comm_compression"]["quant_weight_gather"] is True


def test_http_launch_request_fields():
    """The launch API accepts the new knobs and surfaces validator
    failures as a 422, not a job-thread crash."""
    from backend.http import ApiError
    from backend.routers.training import TrainingLaunchRequest, _to_config

    req = TrainingLaunchRequest(
        model_name="gpt-tiny", seq_len=32,
        mesh=MeshConfig(data=2, fsdp=4),
        comm_quant_weights=True, comm_quant_grads=True,
        comm_quant_block_size=128,
    )
    cfg = _to_config(req)
    assert cfg.comm_quant_weights and cfg.comm_quant_grads
    assert cfg.comm_quant_block_size == 128

    bad = TrainingLaunchRequest(
        model_name="gpt-tiny", seq_len=32,
        mesh=MeshConfig(data=2, fsdp=4),
        comm_secondary_weights=True,  # hpZ without qwZ
    )
    with pytest.raises(ApiError):
        _to_config(bad)
