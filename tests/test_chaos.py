"""Chaos round trip: inject chip fault → detect → emergency-save → shrink → resume.

The acceptance test for the self-healing path. Fast tier: a tiny CPU-mesh
job loses a chip at step 3 and must finish on a shrunk mesh with zero steps
lost beyond the emergency save. Slow tier: per-step **loss parity** — after
the shrink the resumed run must reproduce the uninterrupted run's losses,
because the elastic re-admission preserves the declared effective batch
(accum scales up as dp shrinks) and the data is keyed by global row index.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_engine import faults
from tpu_engine import scheduler as scheduler_mod
from tpu_engine.faults import FaultKind, FaultPlan, FaultSpec
from tpu_engine.mesh_runtime import MeshConfig
from tpu_engine.scheduler import FleetScheduler, SubmissionState
from tpu_engine.sharding import TPUTrainConfig
from tpu_engine.supervisor import JobStatus, TrainingJob
from tpu_engine.tpu_manager import TPUManager


@pytest.fixture(autouse=True)
def _no_process_injector():
    faults.clear_active()
    yield
    faults.clear_active()


def chaos_cfg(tmp, **kw) -> TPUTrainConfig:
    base = dict(
        model_name="gpt-tiny",
        mesh=MeshConfig(data=4, fsdp=2),
        micro_batch_size=1,
        gradient_accumulation_steps=1,
        seq_len=32,
        precision="fp32",
        total_steps=6,
        activation_checkpointing=False,
        checkpoint_dir=str(tmp / "ckpt"),
        checkpoint_interval_steps=2,
        elastic_min_devices=2,
        log_every_steps=1,
    )
    base.update(kw)
    return TPUTrainConfig(**base)


def test_chaos_round_trip_shrink_and_resume(tmp_path):
    """Chip 5 dies at step 3 → emergency save @3 → requeue → re-admit on a
    data=3 × fsdp=2 mesh over the 6 pinned healthy chips → resume from 3 →
    complete step 6. Zero steps lost beyond the emergency save."""
    mgr = TPUManager()
    inj = faults.activate(FaultPlan(seed=1, specs=[
        FaultSpec(kind=FaultKind.CHIP_UNHEALTHY, at_step=3, device_index=5),
    ]))
    jobs = []

    def factory(sub):
        job = scheduler_mod._default_job_factory(sub)
        jobs.append(job)
        return job

    sched = FleetScheduler(
        max_concurrent_jobs=1, fleet_fn=mgr.get_fleet_status,
        job_factory=factory, poll_interval_s=0.05,
    )
    try:
        sub = sched.submit(chaos_cfg(tmp_path), job_kwargs={"auto_rollback": False})
        sub = sched.wait(sub.submission_id, timeout=600)
        assert sub.state == SubmissionState.COMPLETED

        # Attempt 1: detected the injected fault, emergency-saved, preempted.
        first, second = jobs
        assert first.status == JobStatus.PREEMPTED
        assert first.preemption_reason.startswith("self-heal: unhealthy device(s) [5]")
        assert first.recovery_state == "saved"
        assert first.unhealthy_devices == [5]
        assert first.current_step == 3
        kinds = [e["kind"] for e in first.recovery_events]
        assert kinds[0] == "detected"
        assert "saved" in kinds

        # Attempt 2: shrunk admission on the healthy remainder, zero lost steps.
        assert sub.admitted_gang == 6
        assert sub.shrunk_mesh["data"] == 3 and sub.shrunk_mesh["fsdp"] == 2
        assert second.resumed_from_step == 3  # exactly the emergency save
        assert second.current_step == 6
        assert second.elastic_mesh["data"] == 3
        assert second.status == JobStatus.COMPLETED

        # Scheduler counters tell the same story.
        st = sched.stats()
        assert st["self_heal_requeues_total"] == 1
        assert st["elastic_shrinks_total"] == 1
        assert st["requeues_total"] == 1

        # Structured event log: activation precedes detection.
        ev = [(e.kind, e.step) for e in inj.events]
        assert ("chip-unhealthy", 3) in ev
        assert ev.index(("chip-unhealthy", 3)) < ev.index(("recovery:detected", 3))
    finally:
        sched.shutdown()


def _row_data_fn(accum: int, rows: int, seq: int, vocab_cap: int = 97):
    """Batches keyed by (step, global row): mesh-shape independent content.

    Row ``g`` of step ``s`` holds the same tokens whether the global batch
    is laid out (3 accum × 8 rows) or (4 accum × 6 rows) — the flattened
    a-major order is identical, so losses must match across the resize.
    """
    def data_fn(step: int) -> jax.Array:
        n = accum * rows
        out = np.empty((n, seq), np.int64)
        for g in range(n):
            rng = np.random.default_rng(977 * step + g + 1)
            out[g] = rng.integers(0, vocab_cap, size=seq)
        return jnp.asarray(out.astype(np.int32).reshape(accum, rows, seq))
    return data_fn


def _train_losses(path: str) -> dict[int, float]:
    import json

    losses: dict[int, float] = {}
    with open(path) as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("kind") == "train":
                losses[int(rec["step"])] = float(rec["loss"])
    return losses


@pytest.mark.slow
def test_loss_parity_across_elastic_shrink(tmp_path):
    """Per-step losses after the shrink match the uninterrupted run.

    Declared batch: micro 1 × accum 3 × dp 8 (data 4 × fsdp 2) = 24.
    Shrunk:         micro 1 × accum 4 × dp 6 (data 3 × fsdp 2) = 24 — exact.
    """
    total_steps = 8
    common = dict(
        total_steps=total_steps,
        gradient_accumulation_steps=3,
        checkpoint_interval_steps=50,  # only the emergency save can persist
    )

    # Baseline: uninterrupted run on the full mesh.
    base_cfg = chaos_cfg(
        tmp_path / "base", **common,
        metrics_log_path=str(tmp_path / "base.jsonl"),
    )
    baseline = TrainingJob(
        "baseline", base_cfg, data_fn=_row_data_fn(3, 8, base_cfg.seq_len),
        auto_rollback=False,
    )
    baseline.start()
    baseline.join(timeout=600)
    assert baseline.status == JobStatus.COMPLETED
    base_losses = _train_losses(str(tmp_path / "base.jsonl"))
    assert set(base_losses) == set(range(1, total_steps + 1))

    # Chaos run: same data, chip 5 dies at step 3.
    mgr = TPUManager()
    faults.activate(FaultPlan(seed=2, specs=[
        FaultSpec(kind=FaultKind.CHIP_UNHEALTHY, at_step=3, device_index=5),
    ]))
    chaos_log = str(tmp_path / "chaos.jsonl")
    cfg = chaos_cfg(tmp_path / "chaos", **common, metrics_log_path=chaos_log)
    jobs = []

    def factory(sub):
        c = sub.config
        dp_full = c.mesh.data * c.mesh.fsdp
        declared = c.micro_batch_size * c.gradient_accumulation_steps * dp_full
        # The scheduler pins devices on a shrunk admission (shrunk_mesh is
        # recorded only after the factory returns); dp = the pinned count.
        devices = sub.job_kwargs.get("devices")
        dp = len(devices) if devices else dp_full
        rows = c.micro_batch_size * dp
        accum = -(-declared // rows)
        assert accum * rows == declared, "parity needs an exact batch split"
        sub.job_kwargs["data_fn"] = _row_data_fn(accum, rows, c.seq_len)
        job = scheduler_mod._default_job_factory(sub)
        jobs.append(job)
        return job

    sched = FleetScheduler(
        max_concurrent_jobs=1, fleet_fn=mgr.get_fleet_status,
        job_factory=factory, poll_interval_s=0.05,
    )
    try:
        sub = sched.submit(cfg, job_kwargs={"auto_rollback": False})
        sub = sched.wait(sub.submission_id, timeout=600)
        assert sub.state == SubmissionState.COMPLETED
        assert jobs[-1].resumed_from_step == 3
        assert jobs[-1].elastic_mesh["data"] == 3
    finally:
        sched.shutdown()

    chaos_losses = _train_losses(chaos_log)
    assert set(chaos_losses) >= set(range(1, total_steps + 1))
    for step in range(1, total_steps + 1):
        assert chaos_losses[step] == pytest.approx(base_losses[step], abs=5e-3), (
            f"step {step}: chaos {chaos_losses[step]} vs baseline {base_losses[step]}"
        )
    # Steps up to the fault ran on the identical mesh — bit-for-bit close;
    # the post-shrink steps are the ones the tolerance is really for.
    assert chaos_losses[1] == pytest.approx(base_losses[1], abs=1e-6)
