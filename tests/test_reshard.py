"""Reshard plane: topology-changing resume and pool migration.

Four tiers in one file:

- **Topology/plan units** — manifest round trip, the compatibility rule
  (pipe extent changes refused, everything else bridgeable), plan byte
  accounting, the cost model, and the host abstract form.
- **Real-executor round trips** — a sharded pytree saved under
  ``data4×fsdp2`` through the real Orbax manager restores byte-parity
  onto ``data2×fsdp4`` and a shrunk ``3×2`` mesh; the parity gate
  quarantines and raises on a corrupted re-placement; injected restore
  corruption rides the manager's existing fall-back path untouched.
- **Real-engine migration** — held ``hold_kv`` requests drain onto a
  pool of different chunk/lane geometry and int8 storage and complete;
  prefix payloads cross the replica→replica and host-tier legs.
- **Scheduler/planner wiring** — the structured
  ``no_topology_compatible_checkpoint:<model>`` skip on both the auto
  and fixed-config admission paths, and the planner's reshard ranking
  term (same-topology band, remap pricing, inert without a manifest).
"""

import json
from types import SimpleNamespace

import numpy as np
import pytest

from tests.test_scheduler import StubJob, cfg, wait_until
from tpu_engine import reshard
from tpu_engine.checkpoint import TrainCheckpointManager
from tpu_engine.faults import FaultInjector, FaultKind, FaultPlan, FaultSpec
from tpu_engine.mesh_runtime import MeshConfig
from tpu_engine.placement import PlacementPlanner
from tpu_engine.scheduler import FleetScheduler, SubmissionState
from tpu_engine.tpu_manager import TPUManager


@pytest.fixture(autouse=True)
def _fresh_stats():
    reshard._reset_stats_for_tests()
    yield


@pytest.fixture
def sched_factory():
    created = []

    def make(**kw):
        jobs = []

        def factory(sub):
            job = StubJob(sub)
            jobs.append(job)
            return job

        kw.setdefault("job_factory", factory)
        kw.setdefault("poll_interval_s", 0.01)
        kw.setdefault("grow_back_cooldown_s", 0.0)
        s = FleetScheduler(**kw)
        s._stub_jobs = jobs
        created.append(s)
        return s

    yield make
    for s in created:
        for j in getattr(s, "_stub_jobs", []):
            j.finish()
        s.shutdown()


# ---------------------------------------------------------------------------
# Topology manifest + compatibility rule
# ---------------------------------------------------------------------------


def test_normalize_and_same_topology():
    assert reshard.normalize_topology({"data": 4, "fsdp": 2}) == {
        "data": 4, "fsdp": 2, "pipe": 1, "sequence": 1, "model": 1,
    }
    assert reshard.same_topology({"data": 4, "fsdp": 2},
                                 {"data": 4, "fsdp": 2, "pipe": 1})
    assert not reshard.same_topology({"data": 4, "fsdp": 2},
                                     {"data": 2, "fsdp": 4})


def test_topology_compatible_rules():
    ok, why = reshard.topology_compatible(
        {"data": 4, "fsdp": 2}, {"data": 2, "fsdp": 4}
    )
    assert ok and why == ""
    # Shrink + model-axis change: still bridgeable.
    ok, _ = reshard.topology_compatible(
        {"data": 4, "fsdp": 2}, {"data": 3, "fsdp": 2}
    )
    assert ok
    # Pipe extent change: stage-stacked state, refused with the reason.
    ok, why = reshard.topology_compatible(
        {"data": 4, "fsdp": 2}, {"data": 2, "fsdp": 2, "pipe": 2}
    )
    assert not ok and "pipe extent" in why


def test_topology_manifest_round_trip(tmp_path):
    assert reshard.read_topology(str(tmp_path)) is None
    reshard.write_topology(str(tmp_path), {"data": 4, "fsdp": 2},
                           extra={"job_id": "j1"})
    got = reshard.read_topology(str(tmp_path))
    assert got == {"data": 4, "fsdp": 2, "pipe": 1, "sequence": 1, "model": 1}
    doc = json.loads((tmp_path / reshard.TOPOLOGY_FILE).read_text())
    assert doc["job_id"] == "j1"
    # Unreadable manifest → None, never a raise.
    (tmp_path / reshard.TOPOLOGY_FILE).write_text("{torn")
    assert reshard.read_topology(str(tmp_path)) is None


def test_write_topology_never_raises(tmp_path):
    reshard.write_topology(str(tmp_path / "nope" / "deeper"), {"data": 2})


# ---------------------------------------------------------------------------
# Plan + cost model
# ---------------------------------------------------------------------------


def _abstract_tree():
    import jax

    return {
        "w": jax.ShapeDtypeStruct((16, 8), np.float32),
        "b": jax.ShapeDtypeStruct((8,), np.float32),
    }


def test_build_reshard_plan_accounts_bytes():
    plan = reshard.build_reshard_plan(
        _abstract_tree(), {"data": 4, "fsdp": 2}, {"data": 2, "fsdp": 4}
    )
    assert plan.compatible and not plan.is_same_topology
    assert plan.leaves == 2
    assert plan.total_bytes == (16 * 8 + 8) * 4
    assert plan.bytes_to_remap == plan.total_bytes
    assert plan.summary()["predicted_reshard_s"] > 0
    st = reshard.reshard_stats()
    assert st["plans_built_total"] == 1
    assert st["last_plan_bytes"] == plan.total_bytes
    assert st["last_plan_leaves"] == 2


def test_same_topology_plan_remaps_nothing():
    plan = reshard.build_reshard_plan(
        _abstract_tree(), {"data": 4, "fsdp": 2}, {"fsdp": 2, "data": 4}
    )
    assert plan.is_same_topology and plan.bytes_to_remap == 0
    assert plan.summary()["predicted_reshard_s"] == 0.0


def test_incompatible_plan_carries_reason():
    plan = reshard.build_reshard_plan(
        _abstract_tree(), {"pipe": 2}, {"pipe": 1}
    )
    assert not plan.compatible and "pipe extent" in plan.reason


def test_reshard_cost_model():
    assert reshard.reshard_cost_s(0) == 0.0
    assert reshard.reshard_cost_s(-5) == 0.0
    cost = reshard.reshard_cost_s(reshard.RESHARD_BANDWIDTH_BYTES_S)
    assert cost == pytest.approx(reshard.RESHARD_FIXED_OVERHEAD_S + 1.0)
    # The planner's pricing input: params + fp32 master + two moments.
    from tpu_engine.models import transformer as tfm

    bytes_ = reshard.state_bytes_for_model("gpt-tiny")
    assert bytes_ == tfm.param_count(tfm.MODEL_CONFIGS["gpt-tiny"]) * 12
    assert reshard.state_bytes_for_model("nope-9b") is None


def test_host_abstract_like_strips_shardings():
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    mesh = Mesh(np.array(jax.devices()[:2]).reshape(2), ("fsdp",))
    sharded = {
        "w": jax.ShapeDtypeStruct(
            (16, 8), np.float32,
            sharding=NamedSharding(mesh, PartitionSpec("fsdp")),
        )
    }
    host = reshard.host_abstract_like(sharded)
    assert host["w"].shape == (16, 8) and host["w"].dtype == np.float32
    assert getattr(host["w"], "sharding", None) is None


# ---------------------------------------------------------------------------
# Real-executor restore round trips
# ---------------------------------------------------------------------------


def _mesh(data, fsdp):
    import jax
    from jax.sharding import Mesh

    return Mesh(
        np.array(jax.devices()[: data * fsdp]).reshape(data, fsdp),
        ("data", "fsdp"),
    )


def _host_state(seed=0):
    rng = np.random.default_rng(seed)
    return {
        "params": {"w": rng.standard_normal((16, 8)).astype(np.float32)},
        "opt": {"mu": rng.standard_normal((16, 8)).astype(np.float32)},
    }


def _specs():
    from jax.sharding import PartitionSpec

    return {"params": {"w": PartitionSpec("fsdp")},
            "opt": {"mu": PartitionSpec("fsdp")}}


def _placed(state, mesh):
    import jax
    from jax.sharding import NamedSharding

    return jax.tree.map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
        state, _specs(),
    )


def _abstract(state, mesh):
    import jax
    from jax.sharding import NamedSharding

    return jax.tree.map(
        lambda leaf, spec: jax.ShapeDtypeStruct(
            leaf.shape, leaf.dtype, sharding=NamedSharding(mesh, spec)
        ),
        state, _specs(),
    )


def test_restore_resharded_across_factorizations(tmp_path):
    """The tentpole round trip: saved on data4×fsdp2, resumed byte-parity
    on data2×fsdp4 AND a shrunk 6-device 3×2 mesh."""
    host = _host_state()
    want = reshard.leaf_checksums(host)
    mgr = TrainCheckpointManager(str(tmp_path), async_save=False)
    assert mgr.save(100, _placed(host, _mesh(4, 2)), wait=True)
    reshard.write_topology(str(tmp_path),
                           reshard.mesh_topology(_mesh(4, 2)))
    for d, f in ((2, 4), (3, 2)):
        step, state, report = reshard.restore_resharded(
            mgr, _abstract(host, _mesh(d, f))
        )
        assert step == 100 and report["parity_ok"] is True
        assert report["plan"]["src_topology"]["data"] == 4
        assert report["plan"]["dst_topology"]["data"] == d
        assert report["bytes_remapped"] == report["plan"]["total_bytes"] > 0
        assert reshard.leaf_checksums(state) == want
        # The restored leaves actually live on the target factorization.
        mesh = state["params"]["w"].sharding.mesh
        assert dict(mesh.shape) == {"data": d, "fsdp": f}
    st = reshard.reshard_stats()
    assert st["plans_applied_total"] == 2
    assert st["parity_checks_total"] == 2 and st["parity_failures_total"] == 0


def test_restore_resharded_manager_method(tmp_path):
    """checkpoint.TrainCheckpointManager grows the seam directly."""
    host = _host_state(1)
    mgr = TrainCheckpointManager(str(tmp_path), async_save=False)
    assert mgr.save(7, _placed(host, _mesh(4, 2)), wait=True)
    reshard.write_topology(str(tmp_path), {"data": 4, "fsdp": 2})
    step, state = mgr.restore_resharded(_abstract(host, _mesh(2, 4)))
    assert step == 7
    assert reshard.leaf_checksums(state) == reshard.leaf_checksums(host)


def test_restore_resharded_refuses_pipe_change(tmp_path):
    mgr = TrainCheckpointManager(str(tmp_path), async_save=False)
    step, state, report = reshard.restore_resharded(
        mgr, _abstract(_host_state(), _mesh(2, 4)),
        saved_topology={"data": 2, "fsdp": 2, "pipe": 2},
    )
    assert step is None and state is None
    assert "incompatible topology" in report["error"]


def test_restore_resharded_no_checkpoint(tmp_path):
    mgr = TrainCheckpointManager(str(tmp_path), async_save=False)
    step, state, report = reshard.restore_resharded(
        mgr, _abstract(_host_state(), _mesh(2, 4)),
        saved_topology={"data": 4, "fsdp": 2},
    )
    assert step is None and state is None
    assert report["error"] == "no restorable checkpoint"


def test_parity_gate_quarantines_and_raises(tmp_path, monkeypatch):
    """A re-placement that changes any leaf's bytes must never resume
    silently: the step is quarantined and ReshardParityError raised."""
    import jax

    host = _host_state(2)
    mgr = TrainCheckpointManager(str(tmp_path), async_save=False)
    assert mgr.save(5, _placed(host, _mesh(4, 2)), wait=True)
    real_put = jax.device_put

    def corrupting_put(x, *a, **kw):
        out = real_put(x, *a, **kw)
        if getattr(x, "shape", None) == (16, 8):
            return real_put(np.zeros_like(np.asarray(out)), *a, **kw)
        return out

    monkeypatch.setattr(jax, "device_put", corrupting_put)
    with pytest.raises(reshard.ReshardParityError, match="parity failure"):
        reshard.restore_resharded(
            mgr, _abstract(host, _mesh(2, 4)),
            saved_topology={"data": 4, "fsdp": 2},
        )
    assert 5 in mgr.quarantined_steps()
    st = reshard.reshard_stats()
    assert st["parity_failures_total"] == 1
    assert st["plans_applied_total"] == 0


def test_injected_restore_corruption_falls_back_through_reshard(tmp_path):
    """The faults.py restore-corruption seam rides the manager's existing
    quarantine-and-fall-back path inside a resharded restore too."""
    mgr = TrainCheckpointManager(str(tmp_path), async_save=False)
    old = _host_state(3)
    new = _host_state(4)
    assert mgr.save(1, _placed(old, _mesh(4, 2)), wait=True)
    assert mgr.save(2, _placed(new, _mesh(4, 2)), wait=True)
    inj = FaultInjector(FaultPlan(specs=[
        FaultSpec(kind=FaultKind.CHECKPOINT_RESTORE_CORRUPTION, at_step=2),
    ]))
    inj.arm()
    mgr._fault_injector = inj
    step, state, report = reshard.restore_resharded(
        mgr, _abstract(old, _mesh(2, 4)),
        saved_topology={"data": 4, "fsdp": 2},
    )
    # Step 2 "corrupted" → quarantined → step 1 resharded instead.
    assert step == 1 and report["parity_ok"] is True
    assert reshard.leaf_checksums(state) == reshard.leaf_checksums(old)
    assert 2 in mgr.quarantined_steps()


# ---------------------------------------------------------------------------
# Real-engine migration (held KV + prefix payloads)
# ---------------------------------------------------------------------------


def _engine(**kw):
    from tpu_engine.serving_fleet import ServingReplicaSpec, build_replica_engine

    base = dict(model_name="gpt-tiny", max_slots=2, max_len=96,
                prefill_chunk=16)
    base.update(kw)
    return build_replica_engine(ServingReplicaSpec(**base))


def _drive(engine, rid, steps=400):
    for _ in range(steps):
        if engine.result(rid)["status"] == "done":
            break
        engine.step()
    out = engine.result(rid)
    assert out["status"] == "done", out
    return out


def test_migrate_held_requests_across_pool_geometries():
    """Held hold_kv requests drain onto a pool of different chunk/lane
    geometry AND int8 storage; all complete, none left behind."""
    src = _engine()
    dst = _engine(max_slots=4, max_len=128, prefill_chunk=32, kv_quant=True)
    prompts = [[11, 7, 23, 42, 5], [3, 1, 4, 15, 9, 2]]
    for p in prompts:
        _drive(src, src.submit(p, max_new_tokens=1, hold_kv=True))
    assert src.held_requests() == [0, 1]

    res = reshard.migrate_held_requests(src, dst, max_new_tokens=4,
                                        now_s=2.5)
    assert res["migrated"] == 2 and res["wire_bytes"] > 0
    assert res["mttr_s"] == 2.5
    assert src.held_requests() == []
    for dst_rid in res["mapping"].values():
        out = _drive(dst, dst_rid)
        assert len(out["tokens"]) == 4
    reshard.note_migrated_completions(len(res["mapping"]))
    st = reshard.reshard_stats()
    assert st["migrations_total"] == 1
    assert st["held_requests_migrated_total"] == 2
    assert st["held_requests_completed_total"] == 2
    assert st["last_migration_mttr_s"] == 2.5


def test_migrate_prefix_and_host_rehydration():
    src = _engine(max_slots=2, prefix_cache_tokens=256)
    dst = _engine(max_slots=2, prefix_cache_tokens=256, kv_quant=True,
                  prefill_chunk=32, max_len=128)
    system = np.random.default_rng(7).integers(1, 250, 64).tolist()
    for tail in ([9, 9], [8, 8]):
        _drive(src, src.submit(system + tail, max_new_tokens=2))
    key = max(src._prefix_cache._entries, key=len)

    assert reshard.migrate_prefix(src, dst, list(key))
    assert dst.stats()["prefix_cache"]["entries"] == 1
    assert not reshard.migrate_prefix(src, dst, [1, 2, 3])  # not resident

    from tpu_engine.prefix_plane import HostKVTier

    tier = HostKVTier(budget_bytes=64 << 20, clock=lambda: 0.0)
    assert tier.put(key, handoff=src.export_prefix(list(key)), now=0.0)
    assert reshard.rehydrate_from_host(tier, list(key), dst, now=1.0)
    assert not reshard.rehydrate_from_host(tier, [4, 5, 6], dst, now=1.0)
    assert reshard.reshard_stats()["prefix_payloads_migrated_total"] == 2


def test_rebucket_for_pool_counts():
    from tests.test_disagg import _fake_handoff

    h, k, _v = _fake_handoff(T=5)
    out = reshard.rebucket_for_pool(h, chunk=8, max_lanes=16, kv_quant=False)
    assert out.length == 5
    np.testing.assert_allclose(out.k, k, rtol=1e-6)
    st = reshard.reshard_stats()
    assert st["kv_rebuckets_total"] == 1
    assert st["kv_rebucket_bytes_total"] == out.wire_bytes()


# ---------------------------------------------------------------------------
# Planner ranking term
# ---------------------------------------------------------------------------


def _chips(n, free=12.0, total=16.0):
    return [
        SimpleNamespace(index=i, hbm_free_gb=free, hbm_total_gb=total)
        for i in range(n)
    ]


def pcfg(**kw):
    from tpu_engine.sharding import TPUTrainConfig

    base = dict(
        model_name="gpt-tiny",
        mesh=MeshConfig(data=2, fsdp=4),
        micro_batch_size=2,
        gradient_accumulation_steps=2,
        seq_len=64,
    )
    base.update(kw)
    return TPUTrainConfig(**base)


def test_planner_inert_without_saved_topology():
    result = PlacementPlanner().plan(pcfg(), devices=_chips(8), gang=8)
    assert result.plans
    assert all(p.reshard_same_topology is None for p in result.plans)
    assert all(p.predicted_reshard_s == 0.0 for p in result.plans)


def test_planner_prefers_same_topology_within_band():
    planner = PlacementPlanner()
    # Widen the band so the ranking term (not the step-time estimator's
    # layout preference) is what this test exercises.
    planner.prefer_same_topology_max_slowdown_pct = 1000.0
    saved = {"data": 2, "fsdp": 4}
    result = planner.plan(pcfg(), devices=_chips(8), gang=8,
                          saved_topology=saved)
    assert result.plans
    head = result.best
    assert head.reshard_same_topology is True
    assert head.predicted_reshard_s == 0.0
    assert planner.stats()["reshard_tiebreaks_total"] >= 1
    # Topology-changing alternatives got priced, not rejected.
    changed = [p for p in result.plans if p.reshard_same_topology is False]
    assert changed and all(p.predicted_reshard_s > 0 for p in changed)
    assert "predicted_reshard_s" in result.table()[0]


def test_planner_rejects_pipe_extent_change():
    planner = PlacementPlanner()
    saved = {"data": 2, "fsdp": 2, "pipe": 2}
    result = planner.plan(pcfg(), devices=_chips(8), gang=8,
                          saved_topology=saved)
    # gpt-tiny enumerates pipe ∈ {1, 2}: pipe=1 layouts are refused with
    # the structured reason, pipe=2 layouts stay feasible.
    refused = [p for p in result.infeasible
               if (p.skip_reason or "").startswith(
                   "no_topology_compatible_checkpoint")]
    assert refused
    assert all(p.mesh["pipe"] == 2 for p in result.plans)
    assert planner.stats()["topology_rejected_total"] == len(refused)


# ---------------------------------------------------------------------------
# Scheduler: the structured skip on both admission paths
# ---------------------------------------------------------------------------


def test_fixed_config_skip_no_topology_compatible_checkpoint(
    sched_factory, tmp_path
):
    reshard.write_topology(str(tmp_path), {"data": 1, "fsdp": 2, "pipe": 2})
    s = sched_factory(max_concurrent_jobs=2, fleet_fn=TPUManager.get_mock_fleet)
    sub = s.submit(cfg(checkpoint_dir=str(tmp_path)))
    assert wait_until(
        lambda: sub.last_skip_reason == "no_topology_compatible_checkpoint:gpt-tiny"
    )
    assert sub.state == SubmissionState.QUEUED
    (entry,) = s.queue_state()["queued"]
    assert entry["last_skip_reason"] == \
        "no_topology_compatible_checkpoint:gpt-tiny"


def test_fixed_config_compatible_manifest_admits(sched_factory, tmp_path):
    # Different data/fsdp factorization but same pipe extent: bridgeable,
    # admission proceeds.
    reshard.write_topology(str(tmp_path), {"data": 2, "fsdp": 1})
    s = sched_factory(max_concurrent_jobs=2, fleet_fn=TPUManager.get_mock_fleet)
    sub = s.submit(cfg(checkpoint_dir=str(tmp_path)))
    assert wait_until(lambda: sub.state == SubmissionState.RUNNING)


def test_auto_placement_skip_no_topology_compatible_checkpoint(
    sched_factory, tmp_path
):
    # pipe=5 divides nothing the planner can stage for gpt-tiny (2
    # layers), so every enumerated layout is refused on topology.
    reshard.write_topology(str(tmp_path), {"data": 1, "fsdp": 1, "pipe": 5})
    s = sched_factory(max_concurrent_jobs=2, fleet_fn=TPUManager.get_mock_fleet)
    sub = s.submit(cfg(
        mesh=MeshConfig(data=-1, fsdp=1),
        checkpoint_dir=str(tmp_path),
        auto_place=True,
    ))
    assert wait_until(
        lambda: sub.last_skip_reason == "no_topology_compatible_checkpoint:gpt-tiny"
    )
    assert sub.state == SubmissionState.QUEUED


# ---------------------------------------------------------------------------
# Twin lane: deterministic replay + gates at reduced size
# ---------------------------------------------------------------------------


def test_replay_reshard_resume_zero_lost_steps_and_deterministic():
    from tpu_engine.compile_index import CompileCacheIndex
    from tpu_engine.twin import (
        TrainTwinParams,
        chip_fault_timeline,
        replay_reshard_resume,
        replay_self_heal,
        seed_initial_compile,
    )

    params = TrainTwinParams(layout_prefix="reshard")
    events = chip_fault_timeline(0, n_faults=12, params=params)
    assert events

    def run(fn):
        idx = CompileCacheIndex()
        seed_initial_compile(idx, params)
        return fn(events, params, compile_index=idx) if fn is replay_self_heal \
            else fn(events, params, state_bytes=12_000_000_000,
                    compile_index=idx)

    rs = run(replay_reshard_resume)
    assert rs == run(replay_reshard_resume)  # byte-identical repeat
    assert rs["lost_steps"] == 0
    assert rs["topology_changes"] >= rs["faults"] > 0
    assert rs["reshard_s_total"] > 0
    same = run(replay_self_heal)
    # Topology freedom costs the remap leg but stays within the exit
    # gate's 1.5× budget of the warm same-topology mean.
    assert same["mttr_mean_s"] < rs["mttr_mean_s"] <= 1.5 * same["mttr_mean_s"]


def test_reshard_roundtrip_report_gates():
    from tpu_engine.twin import reshard_roundtrip_report

    rep = reshard_roundtrip_report(seed=0)
    assert rep["ok"], rep
    assert len(rep["targets"]) == 2
    assert all(t["byte_parity_vs_source"] for t in rep["targets"])
