"""Prometheus exposition lint: scrape ``/metrics`` and validate text-format
conformance (version 0.0.4) for every exported ``tpu_engine_*`` family —
HELP/TYPE pairing and ordering, no duplicate families, valid sample syntax,
escaped label values, counter naming. Pure-python: the renderer is
hand-rolled (no client library in the image), so nothing else checks that
a new family added to ``backend/routers/metrics.py`` actually parses."""

import asyncio
import re
import threading

import httpx
import pytest
from aiohttp import web

from backend.main import create_app

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?P<labels>\{[^{}]*\})?"
    r" (?P<value>[-+]?(?:[0-9]*\.)?[0-9]+(?:[eE][-+]?[0-9]+)?|NaN|[+-]?Inf)$"
)
# One label pair: name="value" with only escaped \, " and newline inside.
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\\n]|\\[\\"n])*)"')


@pytest.fixture(scope="module")
def client():
    loop = asyncio.new_event_loop()
    started = threading.Event()
    state = {}

    def run():
        asyncio.set_event_loop(loop)
        runner = web.AppRunner(create_app())
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, "127.0.0.1", 0)
        loop.run_until_complete(site.start())
        state["port"] = runner.addresses[0][1]
        started.set()
        loop.run_forever()
        loop.run_until_complete(runner.cleanup())

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert started.wait(timeout=30)
    with httpx.Client(base_url=f"http://127.0.0.1:{state['port']}", timeout=60) as c:
        yield c
    loop.call_soon_threadsafe(loop.stop)
    t.join(timeout=10)


def _scrape(client) -> str:
    r = client.get("/metrics")
    assert r.status_code == 200
    assert "version=0.0.4" in r.headers["Content-Type"]
    return r.text


def test_exposition_format_conformance(client):
    text = _scrape(client)
    helped, typed = {}, {}
    current_family = None
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line:
            continue
        loc = f"line {lineno}: {line!r}"
        if line.startswith("# HELP "):
            parts = line.split(" ", 3)
            assert len(parts) == 4 and parts[3].strip(), f"empty HELP — {loc}"
            family = parts[2]
            assert family not in helped, f"duplicate HELP for {family} — {loc}"
            helped[family] = True
            current_family = family
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ")
            assert len(parts) == 4, loc
            family, mtype = parts[2], parts[3]
            assert mtype in ("gauge", "counter", "histogram"), loc
            assert family not in typed, f"duplicate TYPE for {family} — {loc}"
            # TYPE must directly follow this family's HELP (grouped output).
            assert family == current_family, f"TYPE without HELP — {loc}"
            typed[family] = mtype
            continue
        assert not line.startswith("#"), f"unknown comment — {loc}"
        m = _SAMPLE_RE.match(line)
        assert m, f"malformed sample — {loc}"
        name = m.group("name")
        labels = m.group("labels")
        # Samples are grouped under their family's HELP/TYPE header.
        # Histogram families expose the conventional suffixed sample
        # names; _bucket samples must carry an `le` label.
        if typed.get(current_family) == "histogram":
            allowed = {
                current_family + s for s in ("_bucket", "_sum", "_count")
            }
            assert name in allowed, (
                f"sample {name} outside histogram family "
                f"({current_family}) — {loc}"
            )
            if name.endswith("_bucket"):
                assert labels and 'le="' in labels, f"_bucket without le — {loc}"
        else:
            assert name == current_family, (
                f"sample {name} outside its family block ({current_family}) — {loc}"
            )
        if labels:
            inner = labels[1:-1]
            # Consuming every pair proves no unescaped quote slipped through.
            consumed = ",".join(
                f'{k}="{v}"' for k, v in _LABEL_RE.findall(inner)
            )
            assert consumed == inner, f"label escaping broken — {loc}"
        float(m.group("value"))  # parses as a number
    assert helped, "no families exported"
    # Every family has BOTH a HELP and a TYPE, and only the repo prefix.
    assert set(helped) == set(typed)
    for family in helped:
        assert family.startswith("tpu_engine_"), family


def test_counter_families_follow_naming_convention(client):
    text = _scrape(client)
    for line in text.splitlines():
        if line.startswith("# TYPE "):
            _, _, family, mtype = line.split(" ")
            if mtype == "counter":
                assert family.endswith("_total"), (
                    f"counter {family} must end in _total"
                )


def test_histogram_families_conform(client):
    """Every histogram family: cumulative monotone buckets, a +Inf bucket,
    and +Inf == _count per label set."""
    text = _scrape(client)
    hist_families = [
        line.split(" ")[2]
        for line in text.splitlines()
        if line.startswith("# TYPE ") and line.endswith(" histogram")
    ]
    assert "tpu_engine_scheduler_admission_wait_seconds" in hist_families
    for family in hist_families:
        # label-set (minus le) -> [(le, value)], count
        buckets: dict[str, list[tuple[float, float]]] = {}
        counts: dict[str, float] = {}
        for line in text.splitlines():
            m = _SAMPLE_RE.match(line)
            if not m or not m.group("name").startswith(family):
                continue
            name = m.group("name")
            pairs = dict(_LABEL_RE.findall(m.group("labels") or "{}"))
            le = pairs.pop("le", None)
            key = ",".join(f"{k}={v}" for k, v in sorted(pairs.items()))
            value = float(m.group("value"))
            if name == family + "_bucket":
                bound = float("inf") if le == "+Inf" else float(le)
                buckets.setdefault(key, []).append((bound, value))
            elif name == family + "_count":
                counts[key] = value
        assert buckets, f"histogram {family} exported no buckets"
        for key, series in buckets.items():
            series.sort()
            values = [v for _, v in series]
            assert values == sorted(values), (
                f"{family}{{{key}}} buckets not cumulative: {series}"
            )
            assert series[-1][0] == float("inf"), f"{family}{{{key}}} missing +Inf"
            assert series[-1][1] == counts.get(key), (
                f"{family}{{{key}}} +Inf bucket != _count"
            )


def test_goodput_slo_families_always_present(client):
    """The goodput/SLO plane exports even when nothing has been accounted —
    burn-rate alerting rules must never go 'no data'."""
    text = _scrape(client)
    for family in (
        "tpu_engine_goodput_wall_seconds_total",
        "tpu_engine_goodput_tracked_traces",
        "tpu_engine_goodput_invariant_violations_total",
        "tpu_engine_slo_goodput_target",
        "tpu_engine_telemetry_stale_scopes_dropped_total",
    ):
        assert re.search(rf"^{family}[ {{]", text, re.M), family
    assert re.search(r'^tpu_engine_slo_state\{slo="goodput"\} ', text, re.M)
    assert re.search(r'^tpu_engine_slo_state\{slo="serving_p99"\} ', text, re.M)


def test_trace_families_always_present(client):
    """The flight-recorder health plane exports even when idle — an
    alerting rule on drops must never go 'no data'."""
    text = _scrape(client)
    for family in (
        "tpu_engine_trace_spans_dropped_total",
        "tpu_engine_trace_events_dropped_total",
        "tpu_engine_trace_open_spans",
        "tpu_engine_trace_traces_total",
    ):
        assert re.search(rf"^{family} ", text, re.M), family


def test_historian_incident_families_always_present(client):
    """The historian/incident plane exports even before any history is
    retained — dashboards over retention health and incident counts must
    never go 'no data', and every incident trigger is a labelled series
    from the first scrape."""
    text = _scrape(client)
    for family in (
        "tpu_engine_historian_series",
        "tpu_engine_historian_samples_total",
        "tpu_engine_historian_raw_samples",
        "tpu_engine_historian_rollup_buckets",
        "tpu_engine_historian_ticks_total",
        "tpu_engine_historian_series_evicted_total",
        "tpu_engine_historian_estimated_bytes",
        "tpu_engine_incident_open",
        "tpu_engine_incident_opened_total",
        "tpu_engine_incident_resolved_total",
        "tpu_engine_incident_correlated_records_total",
        "tpu_engine_hetero_host_health",
        "tpu_engine_metrics_scrape_seconds",
    ):
        assert re.search(rf"^{family}[ {{]", text, re.M), family
    for trigger in ("fault", "anomaly", "slo_alert"):
        assert re.search(
            rf'^tpu_engine_incident_opened_total\{{trigger="{trigger}"\}} ',
            text, re.M,
        ), trigger
    # The scrape records into the historian, so by the second scrape the
    # store retains at least the scrape-time series it just wrote.
    text2 = _scrape(client)
    m = re.search(r"^tpu_engine_historian_samples_total (\d+)", text2, re.M)
    assert m and int(m.group(1)) > 0, "scrape did not retain history"


def test_autopilot_families_always_present(client):
    """The autopilot plane exports even before the loop ever ticked — a
    burn-rate rule on suppressions or a 'shadow mode left on' alert must
    never go 'no data', and every outcome/rule/reason is a labelled
    series from the first scrape."""
    text = _scrape(client)
    for family in (
        "tpu_engine_autopilot_armed",
        "tpu_engine_autopilot_ticks_total",
        "tpu_engine_autopilot_decisions_retained",
        "tpu_engine_autopilot_decisions_dropped_total",
    ):
        assert re.search(rf"^{family} ", text, re.M), family
    from tpu_engine.autopilot import RULES, SUPPRESSION_REASONS

    for outcome in ("fired", "suppressed"):
        assert re.search(
            rf'^tpu_engine_autopilot_decisions_total\{{outcome="{outcome}"\}} ',
            text, re.M,
        ), outcome
    for rule in RULES:
        assert re.search(
            rf'^tpu_engine_autopilot_actuations_total\{{rule="{rule}"\}} ',
            text, re.M,
        ), rule
    for reason in SUPPRESSION_REASONS:
        assert re.search(
            rf'^tpu_engine_autopilot_suppressions_total\{{reason="{reason}"\}} ',
            text, re.M,
        ), reason


def test_twin_families_always_present(client):
    """The digital-twin plane exports even before any replay ran — an
    alerting rule on ingest skips must never go 'no data', and every
    skip reason is a labelled series from the first scrape."""
    text = _scrape(client)
    for family in (
        "tpu_engine_twin_replays_total",
        "tpu_engine_twin_ab_runs_total",
        "tpu_engine_twin_ingest_files_total",
        "tpu_engine_twin_ingest_lines_total",
        "tpu_engine_twin_replayed_spans_total",
        "tpu_engine_twin_replayed_events_total",
        "tpu_engine_twin_fleet_seconds_total",
        "tpu_engine_twin_cpu_seconds_total",
        "tpu_engine_twin_replay_speedup",
    ):
        assert re.search(rf"^{family}[ {{]", text, re.M), family
    from tpu_engine.twin import SKIP_REASONS

    for reason in SKIP_REASONS:
        assert re.search(
            rf'^tpu_engine_twin_ingest_skipped_lines_total\{{reason="{reason}"\}} ',
            text, re.M,
        ), reason


def test_prefix_plane_families_always_present(client):
    """The fleet prefix plane exports even with no plane attached — the
    counters render at zero from the first scrape so dashboards and
    alerting rules never need absent()."""
    text = _scrape(client)
    for family in (
        "tpu_engine_prefix_plane_lookups_total",
        "tpu_engine_prefix_plane_index_hits_total",
        "tpu_engine_prefix_plane_host_hits_total",
        "tpu_engine_prefix_plane_host_stores_total",
        "tpu_engine_prefix_plane_host_evictions_total",
        "tpu_engine_prefix_plane_rehydrations_total",
        "tpu_engine_prefix_plane_hit_tokens_total",
        "tpu_engine_prefix_plane_index_prefixes",
        "tpu_engine_prefix_plane_host_entries",
        "tpu_engine_prefix_plane_host_bytes",
    ):
        assert re.search(rf"^{family}[ {{]", text, re.M), family


def test_reshard_families_always_present(client):
    """The reshard plane exports even before anything reshards — the
    counters render at zero from the first scrape so dashboards and
    alerting rules never need absent()."""
    text = _scrape(client)
    for family in (
        "tpu_engine_reshard_plans_built_total",
        "tpu_engine_reshard_plans_applied_total",
        "tpu_engine_reshard_bytes_remapped_total",
        "tpu_engine_reshard_parity_checks_total",
        "tpu_engine_reshard_parity_failures_total",
        "tpu_engine_reshard_kv_rebuckets_total",
        "tpu_engine_reshard_kv_rebucket_bytes_total",
        "tpu_engine_reshard_migrations_total",
        "tpu_engine_reshard_held_requests_migrated_total",
        "tpu_engine_reshard_held_requests_completed_total",
        "tpu_engine_reshard_prefix_payloads_migrated_total",
        "tpu_engine_reshard_last_plan_bytes",
        "tpu_engine_reshard_last_plan_leaves",
        "tpu_engine_reshard_last_migration_mttr_seconds",
    ):
        assert re.search(rf"^{family}[ {{]", text, re.M), family


def test_journal_and_ctl_recovery_families_always_present(client):
    """The durable-control-plane families export even before any journal
    is attached or restore has run — zeros from the first scrape so crash
    dashboards never need absent(). Skip reasons render as labels."""
    text = _scrape(client)
    for family in (
        "tpu_engine_journal_attached",
        "tpu_engine_journal_bytes",
        "tpu_engine_journal_max_bytes",
        "tpu_engine_journal_appends_total",
        "tpu_engine_journal_snapshots_total",
        "tpu_engine_journal_rotations_total",
        "tpu_engine_journal_append_errors_total",
        "tpu_engine_journal_reads_total",
        "tpu_engine_journal_read_lines_total",
        "tpu_engine_journal_read_skipped_lines_total",
        "tpu_engine_ctl_recovery_restores_total",
        "tpu_engine_ctl_recovery_records_replayed_total",
        "tpu_engine_ctl_recovery_jobs_readopted_total",
        "tpu_engine_ctl_recovery_requeued_total",
        "tpu_engine_ctl_recovery_double_grants_total",
        "tpu_engine_ctl_recovery_replicas_readopted_total",
        "tpu_engine_ctl_recovery_replicas_redispatched_total",
        "tpu_engine_ctl_recovery_requests_recovered_total",
        "tpu_engine_ctl_recovery_last_mttr_seconds",
    ):
        assert re.search(rf"^{family}[ {{]", text, re.M), family
    for reason in ("torn_tail", "parse_error", "unknown_schema", "unknown_record"):
        assert re.search(
            rf'^tpu_engine_journal_read_skipped_lines_total\{{reason="{reason}"\}} ',
            text, re.M,
        ), reason


def test_serving_spec_families_always_present(client):
    """Per-replica speculative telemetry exports even with no serving
    engine registered (and with a non-speculative one) — rendered at
    zero so fleet acceptance dashboards never need absent()."""
    text = _scrape(client)
    for family in (
        "tpu_engine_serving_spec_decoding",
        "tpu_engine_serving_spec_accept_rate",
        "tpu_engine_serving_spec_rounds_total",
        "tpu_engine_serving_spec_accepted_tokens_total",
        "tpu_engine_serving_spec_proposed_tokens_total",
    ):
        assert re.search(rf"^{family}[ {{]", text, re.M), family


def test_spec_pool_families_always_present(client):
    """The speculative pool plane exports even before any spec fleet
    exists — the counters render at zero from the first scrape so
    dashboards and alerting rules never need absent()."""
    text = _scrape(client)
    for family in (
        "tpu_engine_spec_pool_requests_total",
        "tpu_engine_spec_pool_draft_legs_total",
        "tpu_engine_spec_pool_verify_legs_total",
        "tpu_engine_spec_pool_plain_legs_total",
        "tpu_engine_spec_pool_canary_probes_total",
        "tpu_engine_spec_pool_accepted_tokens_total",
        "tpu_engine_spec_pool_proposed_tokens_total",
        "tpu_engine_spec_pool_spills_total",
        "tpu_engine_spec_pool_restores_total",
        "tpu_engine_spec_pool_spill_decisions_total",
        "tpu_engine_spec_pool_draft_cache_invalidations_total",
        "tpu_engine_spec_pool_tenants_total",
        "tpu_engine_spec_pool_tenants_spilled",
    ):
        assert re.search(rf"^{family}[ {{]", text, re.M), family
