"""Prometheus exposition lint: scrape ``/metrics`` and validate text-format
conformance (version 0.0.4) for every exported ``tpu_engine_*`` family —
HELP/TYPE pairing and ordering, no duplicate families, valid sample syntax,
escaped label values, counter naming. Pure-python: the renderer is
hand-rolled (no client library in the image), so nothing else checks that
a new family added to ``backend/routers/metrics.py`` actually parses."""

import asyncio
import re
import threading

import httpx
import pytest
from aiohttp import web

from backend.main import create_app

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?P<labels>\{[^{}]*\})?"
    r" (?P<value>[-+]?(?:[0-9]*\.)?[0-9]+(?:[eE][-+]?[0-9]+)?|NaN|[+-]?Inf)$"
)
# One label pair: name="value" with only escaped \, " and newline inside.
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\\n]|\\[\\"n])*)"')


@pytest.fixture(scope="module")
def client():
    loop = asyncio.new_event_loop()
    started = threading.Event()
    state = {}

    def run():
        asyncio.set_event_loop(loop)
        runner = web.AppRunner(create_app())
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, "127.0.0.1", 0)
        loop.run_until_complete(site.start())
        state["port"] = runner.addresses[0][1]
        started.set()
        loop.run_forever()
        loop.run_until_complete(runner.cleanup())

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert started.wait(timeout=30)
    with httpx.Client(base_url=f"http://127.0.0.1:{state['port']}", timeout=60) as c:
        yield c
    loop.call_soon_threadsafe(loop.stop)
    t.join(timeout=10)


def _scrape(client) -> str:
    r = client.get("/metrics")
    assert r.status_code == 200
    assert "version=0.0.4" in r.headers["Content-Type"]
    return r.text


def test_exposition_format_conformance(client):
    text = _scrape(client)
    helped, typed = {}, {}
    current_family = None
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line:
            continue
        loc = f"line {lineno}: {line!r}"
        if line.startswith("# HELP "):
            parts = line.split(" ", 3)
            assert len(parts) == 4 and parts[3].strip(), f"empty HELP — {loc}"
            family = parts[2]
            assert family not in helped, f"duplicate HELP for {family} — {loc}"
            helped[family] = True
            current_family = family
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ")
            assert len(parts) == 4, loc
            family, mtype = parts[2], parts[3]
            assert mtype in ("gauge", "counter"), loc
            assert family not in typed, f"duplicate TYPE for {family} — {loc}"
            # TYPE must directly follow this family's HELP (grouped output).
            assert family == current_family, f"TYPE without HELP — {loc}"
            typed[family] = mtype
            continue
        assert not line.startswith("#"), f"unknown comment — {loc}"
        m = _SAMPLE_RE.match(line)
        assert m, f"malformed sample — {loc}"
        name = m.group("name")
        # Samples are grouped under their family's HELP/TYPE header.
        assert name == current_family, (
            f"sample {name} outside its family block ({current_family}) — {loc}"
        )
        labels = m.group("labels")
        if labels:
            inner = labels[1:-1]
            # Consuming every pair proves no unescaped quote slipped through.
            consumed = ",".join(
                f'{k}="{v}"' for k, v in _LABEL_RE.findall(inner)
            )
            assert consumed == inner, f"label escaping broken — {loc}"
        float(m.group("value"))  # parses as a number
    assert helped, "no families exported"
    # Every family has BOTH a HELP and a TYPE, and only the repo prefix.
    assert set(helped) == set(typed)
    for family in helped:
        assert family.startswith("tpu_engine_"), family


def test_counter_families_follow_naming_convention(client):
    text = _scrape(client)
    for line in text.splitlines():
        if line.startswith("# TYPE "):
            _, _, family, mtype = line.split(" ")
            if mtype == "counter":
                assert family.endswith("_total"), (
                    f"counter {family} must end in _total"
                )


def test_trace_families_always_present(client):
    """The flight-recorder health plane exports even when idle — an
    alerting rule on drops must never go 'no data'."""
    text = _scrape(client)
    for family in (
        "tpu_engine_trace_spans_dropped_total",
        "tpu_engine_trace_events_dropped_total",
        "tpu_engine_trace_open_spans",
        "tpu_engine_trace_traces_total",
    ):
        assert re.search(rf"^{family} ", text, re.M), family
