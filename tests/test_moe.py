"""Mixture-of-Experts family: routing math, aux loss, expert-parallel
sharding, and end-to-end training."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_engine.mesh_runtime import MeshConfig
from tpu_engine.models import transformer as tfm
from tpu_engine.sharding import ShardingStage, TPUTrainConfig, param_pspecs
from tpu_engine.train import build_train_program

CFG = tfm.MODEL_CONFIGS["moe-tiny"]


def test_param_tree_matches_logical_tree():
    params = jax.eval_shape(lambda: tfm.init_params(jax.random.PRNGKey(0), CFG))
    axes = tfm.logical_axes(CFG)
    p_flat = jax.tree_util.tree_structure(params)
    a_flat = jax.tree_util.tree_structure(
        jax.tree.map(lambda x: 0, axes, is_leaf=lambda x: isinstance(x, tuple))
    )
    assert p_flat == a_flat
    # Rank agreement: every logical tuple matches its array rank.
    def check(p, a):
        assert len(a) == p.ndim, (p.shape, a)
    jax.tree.map(check, params, axes, is_leaf=lambda x: isinstance(x, tuple))


def test_param_counts():
    dense = CFG.with_(n_experts=0)
    # MoE adds (E-1)x the MLP weights plus the router.
    extra = CFG.n_layers * (
        (CFG.n_experts - 1) * 3 * CFG.d_model * CFG.d_ff
        + CFG.d_model * CFG.n_experts
    )
    assert tfm.param_count(CFG) == tfm.param_count(dense) + extra
    # Active params only count top_k experts.
    inactive = CFG.n_layers * (CFG.n_experts - CFG.top_k) * 3 * CFG.d_model * CFG.d_ff
    assert tfm.active_param_count(CFG) == tfm.param_count(CFG) - inactive


def test_moe_forward_shape_and_aux():
    params = tfm.init_params(jax.random.PRNGKey(0), CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, CFG.vocab_size)
    logits, aux = tfm.forward_and_aux(params, tokens, CFG, compute_dtype=jnp.float32)
    assert logits.shape == (2, 64, CFG.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()
    # Near-uniform router at init → load-balance loss ≈ E * E*(1/E)*(1/E) = 1.
    assert 0.8 < float(aux) < 1.5


def test_dense_forward_aux_is_zero():
    dense = tfm.MODEL_CONFIGS["gpt-tiny"]
    params = tfm.init_params(jax.random.PRNGKey(0), dense)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, dense.vocab_size)
    _, aux = tfm.forward_and_aux(params, tokens, dense, compute_dtype=jnp.float32)
    assert float(aux) == 0.0


def test_expert_capacity_static():
    assert CFG.expert_capacity(256) == int(1.25 * 2 * 256 / 4)
    assert CFG.with_(capacity_factor=0.01).expert_capacity(256) == 1  # floor


def test_expert_parallel_sharding_specs():
    """Expert kernels shard expert→model; mlp stays local (no axis reuse)."""
    specs = param_pspecs(tfm.logical_axes(CFG), ShardingStage.FULL_PARTITIONING)
    gate = tuple(specs["layers"]["gate"]["kernel"])
    # (layers, expert, embed, mlp) → ("pipe", "model", "fsdp") [trailing None trimmed]
    assert gate == ("pipe", "model", "fsdp")
    router = tuple(specs["layers"]["router"]["kernel"])
    assert "model" not in router  # router output dim (E) replicated
    # Dense models are unchanged by the priority rule.
    dense_specs = param_pspecs(
        tfm.logical_axes(tfm.MODEL_CONFIGS["gpt-tiny"]), ShardingStage.FULL_PARTITIONING
    )
    assert tuple(dense_specs["layers"]["gate"]["kernel"]) == ("pipe", "fsdp", "model")


def test_moe_grads_reach_all_experts():
    """With top-2 of 4 experts over a 64-token batch, every expert should
    receive gradient (routing is near-uniform at init)."""
    params = tfm.init_params(jax.random.PRNGKey(0), CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, CFG.vocab_size)

    def loss(p):
        logits, aux = tfm.forward_and_aux(p, tokens, CFG, compute_dtype=jnp.float32)
        tgt = tokens[:, 1:]
        lp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), -1)
        ll = jnp.take_along_axis(lp, tgt[..., None], -1)
        return -jnp.mean(ll) + 0.01 * aux

    grads = jax.grad(loss)(params)
    g = np.asarray(grads["layers"]["gate"]["kernel"])  # [L, E, D, F]
    per_expert = np.abs(g).sum(axis=(0, 2, 3))
    assert (per_expert > 0).all(), per_expert
    assert np.abs(np.asarray(grads["layers"]["router"]["kernel"])).sum() > 0


def test_moe_training_end_to_end_with_expert_parallelism():
    """Full sharded train: data x fsdp x model(=EP) mesh, loss decreases on
    a repeated batch."""
    cfg = TPUTrainConfig(
        model_name="moe-tiny",
        sharding_stage=ShardingStage.FULL_PARTITIONING,
        mesh=MeshConfig(data=2, fsdp=2, model=2),
        micro_batch_size=2,
        gradient_accumulation_steps=1,
        seq_len=64,
        precision="fp32",
        total_steps=8,
        warmup_steps=1,
        learning_rate=5e-3,
        activation_checkpointing=False,
    )
    prog = build_train_program(cfg)
    state = prog.init(jax.random.PRNGKey(0))
    batch = prog.synthetic_batch(0)
    losses = []
    for _ in range(8):
        state, metrics = prog.step(state, batch)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def test_ragged_moe_matches_dense_when_nothing_drops():
    """moe_impl='ragged' (sort + lax.ragged_dot, round 5) computes the
    SAME function as dense dispatch whenever the capacity factor is
    large enough that dense drops no token: both renormalise the top-k
    gates to sum 1 and both pick experts greedily-by-probability (top_k
    tie-break = lowest index, same as iterative argmax)."""
    big_cf = CFG.with_(capacity_factor=8.0)       # nothing can drop
    ragged = big_cf.with_(moe_impl="ragged")
    params = tfm.init_params(jax.random.PRNGKey(0), CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, CFG.vocab_size)
    ld, auxd = tfm.forward_and_aux(params, tokens, big_cf, compute_dtype=jnp.float32)
    lr_, auxr = tfm.forward_and_aux(params, tokens, ragged, compute_dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(lr_), np.asarray(ld), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(float(auxr), float(auxd), rtol=1e-5)


def test_ragged_moe_grads_and_training():
    """Gradients reach every expert through the sort/gather/ragged_dot
    chain, and end-to-end training decreases the loss."""
    ragged = CFG.with_(moe_impl="ragged")
    params = tfm.init_params(jax.random.PRNGKey(0), ragged)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, CFG.vocab_size)

    def loss(p):
        logits, aux = tfm.forward_and_aux(p, tokens, ragged, compute_dtype=jnp.float32)
        tgt = tokens[:, 1:]
        lp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), -1)
        ll = jnp.take_along_axis(lp, tgt[..., None], -1)
        return -jnp.mean(ll) + 0.01 * aux

    grads = jax.grad(loss)(params)
    g = np.asarray(grads["layers"]["gate"]["kernel"])
    assert (np.abs(g).sum(axis=(0, 2, 3)) > 0).all()
    assert np.abs(np.asarray(grads["layers"]["router"]["kernel"])).sum() > 0

    cfg = TPUTrainConfig(
        model_name="moe-tiny",
        sharding_stage=ShardingStage.FULL_PARTITIONING,
        mesh=MeshConfig(data=2, fsdp=4),
        micro_batch_size=2,
        gradient_accumulation_steps=1,
        seq_len=64,
        precision="fp32",
        total_steps=8,
        warmup_steps=1,
        learning_rate=5e-3,
        activation_checkpointing=False,
    )
    prog = build_train_program(cfg, model_cfg=ragged)
    state = prog.init(jax.random.PRNGKey(0))
    batch = prog.synthetic_batch(0)
    losses = []
    for _ in range(8):
        state, metrics = prog.step(state, batch)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def test_moe_impl_config_override():
    """moe_impl rides TPUTrainConfig (and the HTTP launch request) like
    the attention_impl/sliding_window overrides: it re-targets the model
    config at build time, and setting it on a dense model is an error."""
    cfg = TPUTrainConfig(
        model_name="moe-tiny",
        sharding_stage=ShardingStage.FULL_PARTITIONING,
        mesh=MeshConfig(data=2, fsdp=4),
        micro_batch_size=2, seq_len=64, precision="fp32",
        moe_impl="ragged",
    )
    prog = build_train_program(cfg)
    assert prog.model_config.moe_impl == "ragged"
    for impl in ("ragged", "dense"):  # 'dense' must not slip through the
        #                               matches-the-default short-circuit
        with pytest.raises(ValueError, match="dense model"):
            build_train_program(TPUTrainConfig(
                model_name="gpt-tiny", mesh=MeshConfig(data=-1),
                micro_batch_size=2, seq_len=64, precision="fp32",
                moe_impl=impl,
            ))


def test_ragged_moe_rejects_expert_parallelism():
    cfg = TPUTrainConfig(
        model_name="moe-tiny",
        sharding_stage=ShardingStage.FULL_PARTITIONING,
        mesh=MeshConfig(data=2, fsdp=2, model=2),
        micro_batch_size=2, seq_len=64, precision="fp32",
    )
    with pytest.raises(ValueError, match="ragged"):
        build_train_program(
            cfg, model_cfg=CFG.with_(moe_impl="ragged")
        )


# Compile-heavy module: excluded from the fast core run (pytest -m "not slow").
pytestmark = pytest.mark.slow
