"""Live telemetry stack: libtpu SDK parsing, derived duty cycle, overlay
merge into the fleet manager, health thresholds firing on the live schema.

The fake ``tpumonitoring`` module speaks the exact string formats documented
by ``libtpu.sdk.tpumonitoring.get_metric(name).description()`` (captured on
a real v5e host — see tpu_engine/telemetry.py module docstring), so these
tests exercise the same parse path production hits.
"""

import time

import pytest

from tpu_engine import telemetry
from tpu_engine.telemetry import (
    DerivedDutySource,
    LibtpuSdkSource,
    parse_float_list,
    parse_indexed_scores,
    parse_link_scores,
)
from tpu_engine.tpu_manager import TPUHealthStatus, TPUManager


@pytest.fixture(autouse=True)
def _restore_sources():
    yield
    telemetry.set_sources(None)
    telemetry.derived_duty().reset()


# -- parsers (documented formats) -------------------------------------------


def test_parse_float_list_documented_format():
    assert parse_float_list(["0.00", "20.00", "0.00", "0.00"]) == [0.0, 20.0, 0.0, 0.0]


def test_parse_float_list_tolerates_indexed_entries():
    assert parse_float_list(["0: 12.5", "1: 37.5", "junk"]) == [12.5, 37.5]


def test_parse_throttle_scores_documented_format():
    # "['0-0', '1-1', '2-0', '3-0']" — chip 1 throttled by 10%.
    assert parse_indexed_scores(["0-0", "1-1", "2-0", "3-0"]) == {0: 0, 1: 1, 2: 0, 3: 0}


def test_parse_ici_links_documented_format():
    links = parse_link_scores(["tray1.chip3.ici0.int: 0", "tray1.chip3.ici1.int: 10"])
    assert links == [("tray1.chip3.ici0.int", 0), ("tray1.chip3.ici1.int", 10)]


def test_ici_link_alert_severity_bands():
    alerts = telemetry.ici_link_alerts(
        [("a", 0), ("b", 3), ("c", 7), ("d", 10)]
    )
    assert len(alerts) == 3  # score 0 is healthy, no alert
    assert "transient" in alerts[0] and "b" in alerts[0]
    assert "persistent" in alerts[1] and "c" in alerts[1]
    assert alerts[2].startswith("CRITICAL") and "d" in alerts[2]


# -- libtpu SDK source -------------------------------------------------------


class FakeMetric:
    def __init__(self, data):
        self._data = data

    def data(self):
        return self._data


class FakeMonitoring:
    """Stand-in for libtpu.sdk.tpumonitoring with the documented data shapes."""

    def __init__(self, metrics):
        self.metrics = metrics

    def list_supported_metrics(self):
        return list(self.metrics)

    def get_metric(self, name):
        return FakeMetric(self.metrics[name])


def _fake_monitoring_4chip():
    gib = 2**30
    return FakeMonitoring(
        {
            "duty_cycle_pct": ["62.00", "97.50", "12.00", "0.00"],
            # Two cores per chip — per-chip means: 55, 90, 10, 0.
            "tensorcore_util": [
                "50.00", "60.00", "88.00", "92.00", "10.00", "10.00", "0.00", "0.00",
            ],
            "hbm_capacity_total": [str(16 * gib)] * 4,
            "hbm_capacity_usage": [str(4 * gib), str(14 * gib), str(gib), "0"],
            "tpu_throttle_score": ["0-0", "1-7", "2-1", "3-0"],
            "ici_link_health": ["tray0.chip1.ici0.int: 10", "tray0.chip2.ici1.int: 0"],
        }
    )


def test_libtpu_sdk_source_sample():
    src = LibtpuSdkSource(monitoring=_fake_monitoring_4chip())
    snap = src.sample(4)
    assert snap is not None and snap.source == "libtpu_sdk"
    assert [c["duty_cycle_pct"] for c in snap.per_chip] == [62.0, 97.5, 12.0, 0.0]
    assert [c["tensorcore_util_pct"] for c in snap.per_chip] == [55.0, 90.0, 10.0, 0.0]
    assert snap.per_chip[1]["hbm_used_gb"] == 14.0
    assert snap.per_chip[1]["throttle_score"] == 7
    assert snap.ici_links == [("tray0.chip1.ici0.int", 10), ("tray0.chip2.ici1.int", 0)]


def test_libtpu_sdk_source_empty_data_is_none():
    # The remote-tunnel case: SDK importable, every metric empty.
    empty = FakeMonitoring({n: [] for n in _fake_monitoring_4chip().metrics})
    assert LibtpuSdkSource(monitoring=empty).sample(4) is None


def test_libtpu_sdk_source_missing_module_is_none():
    src = LibtpuSdkSource()
    src._probed, src._monitoring = True, None
    assert src.sample(4) is None


# -- derived duty source -----------------------------------------------------


def test_derived_duty_from_step_timings():
    src = DerivedDutySource()
    for _ in range(10):
        src.observe(device_s=0.08, wall_s=0.1)
    snap = src.sample(2)
    assert snap is not None
    assert [c["duty_cycle_pct"] for c in snap.per_chip] == [80.0, 80.0]


def test_derived_duty_expires_when_idle():
    src = DerivedDutySource(max_age_s=0.05)
    src.observe(device_s=0.5, wall_s=1.0)
    assert src.sample(1) is not None
    time.sleep(0.08)
    assert src.sample(1) is None


def test_derived_duty_empty_before_any_step():
    assert DerivedDutySource().sample(1) is None


def test_derived_duty_scoped_to_job_devices():
    """A job driving a subset of the host's chips must not stamp its duty
    cycle onto the idle chips (round-2 review finding)."""
    import jax

    src = DerivedDutySource()
    first_four = [int(d.id) for d in jax.devices()[:4]]
    src.observe(device_s=0.8, wall_s=1.0, device_ids=first_four)
    snap = src.sample(8)
    assert [bool(c) for c in snap.per_chip] == [True] * 4 + [False] * 4
    assert snap.per_chip[0]["duty_cycle_pct"] == 80.0


def test_derived_duty_concurrent_jobs_do_not_blend():
    """Two jobs on disjoint chip subsets keep separate duty readings
    (round-2 review finding: a shared window would blend their timings)."""
    import jax

    src = DerivedDutySource()
    ids = [int(d.id) for d in jax.devices()]
    src.observe(device_s=0.9, wall_s=1.0, device_ids=ids[:4])   # busy job
    src.observe(device_s=0.1, wall_s=1.0, device_ids=ids[4:8])  # idle-ish job
    snap = src.sample(8)
    assert [c.get("duty_cycle_pct") for c in snap.per_chip] == (
        [90.0] * 4 + [10.0] * 4
    )


# -- overlay merge + live-path health ---------------------------------------


def test_overlay_priority_first_source_wins():
    libtpu = LibtpuSdkSource(monitoring=_fake_monitoring_4chip())
    derived = DerivedDutySource()
    derived.observe(0.5, 1.0)  # 50% — must NOT override libtpu's numbers
    telemetry.set_sources([libtpu, derived])
    overlay = telemetry.sample_overlay(4)
    assert overlay.per_chip[0]["duty_cycle_pct"] == 62.0
    assert overlay.sources == ["libtpu_sdk"]


def test_overlay_falls_back_to_derived():
    telemetry.set_sources([LibtpuSdkSource(monitoring=FakeMonitoring({}))])
    derived = DerivedDutySource()
    derived.observe(0.9, 1.0)
    telemetry.set_sources([LibtpuSdkSource(monitoring=FakeMonitoring({})), derived])
    overlay = telemetry.sample_overlay(2)
    assert overlay.sources == ["derived"]
    assert overlay.per_chip[0]["duty_cycle_pct"] == 90.0


def test_live_fleet_health_fires_from_libtpu_schema():
    """The VERDICT gap: thresholds must fire on the LIVE path, fed by the
    telemetry stack — not only on injected snapshots."""
    telemetry.set_sources([LibtpuSdkSource(monitoring=_fake_monitoring_4chip())])
    fleet = TPUManager().get_fleet_status()  # 8 CPU test devices
    assert fleet.telemetry_sources == ["libtpu_sdk"]
    # chip 1: duty 97.5 >= 95 (warning) AND throttle 7 >= 6 (critical).
    chip1 = fleet.devices[1]
    assert chip1.duty_cycle_pct == 97.5
    assert chip1.throttle_score == 7
    assert chip1.health_status == TPUHealthStatus.CRITICAL
    assert any("throttled by 70%" in a for a in chip1.alerts)
    assert any("duty cycle" in a for a in chip1.alerts)
    # chip 2: throttle 1 → warning only.
    assert fleet.devices[2].health_status == TPUHealthStatus.WARNING
    # ICI link problems surface as fleet alerts.
    assert any("ICI link tray0.chip1.ici0.int unusable" in a for a in fleet.fleet_alerts)
    assert fleet.ici_links[0] == ("tray0.chip1.ici0.int", 10)


def test_live_fleet_derived_duty_when_sdk_unreachable():
    """The axon-tunnel case: only the engine-derived source has data."""
    derived = DerivedDutySource()
    for _ in range(5):
        derived.observe(device_s=0.45, wall_s=0.5)
    telemetry.set_sources([derived])
    fleet = TPUManager().get_fleet_status()
    assert fleet.telemetry_sources == ["derived"]
    assert all(d.duty_cycle_pct == 90.0 for d in fleet.devices)
    assert fleet.average_duty_cycle_pct == 90.0


def test_supervisor_feed_helper():
    telemetry.observe_step(device_s=0.3, wall_s=0.4)
    snap = telemetry.derived_duty().sample(1)
    assert snap is not None and snap.per_chip[0]["duty_cycle_pct"] == 75.0


def test_injected_metrics_bypass_overlay():
    # Injected snapshots are the canned-telemetry seam; live sources must
    # not leak into them.
    derived = DerivedDutySource()
    derived.observe(0.9, 1.0)
    telemetry.set_sources([derived])
    fleet = TPUManager().get_fleet_status(
        metrics=[{"index": 0, "hbm_total_gb": 16.0, "hbm_used_gb": 1.0}]
    )
    assert fleet.telemetry_sources == []
    assert fleet.devices[0].duty_cycle_pct is None


# -- tpu-info CLI fallback source (SURVEY §2.2; reference nvidia-smi parse
# seam, gpu_manager.py:100-117) ---------------------------------------------

_TPU_INFO_OUTPUT = """\
TPU Chips
┏━━━━━━━━━━━━━┳━━━━━━━━━━━━━┳━━━━━━━━━┳━━━━━┓
┃ Chip        ┃ Type        ┃ Devices ┃ PID ┃
┡━━━━━━━━━━━━━╇━━━━━━━━━━━━━╇━━━━━━━━━╇━━━━━┩
│ /dev/accel0 │ TPU v5 lite │ 1       │ 777 │
│ /dev/accel1 │ TPU v5 lite │ 1       │ 777 │
└─────────────┴─────────────┴─────────┴─────┘
TPU Runtime Utilization
┏━━━━━━━━┳━━━━━━━━━━━━━━━━━━━━━━━┳━━━━━━━━━━━━┓
┃ Device ┃ Memory usage          ┃ Duty cycle ┃
┡━━━━━━━━╇━━━━━━━━━━━━━━━━━━━━━━━╇━━━━━━━━━━━━┩
│ 0      │ 1.50 GiB / 15.75 GiB  │     12.00% │
│ 1      │ 14.20 GiB / 15.75 GiB │     97.50% │
└────────┴───────────────────────┴────────────┘
TensorCore Utilization
┏━━━━━━━━━┳━━━━━━━━━━━━━━━━━━━━━━━━┓
┃ Chip ID ┃ TensorCore Utilization ┃
┡━━━━━━━━━╇━━━━━━━━━━━━━━━━━━━━━━━━┩
│ 0       │ 34.20%                 │
│ 1       │ 88.00%                 │
└─────────┴────────────────────────┘
"""


def test_tpu_info_cli_source_parses_canned_output():
    src = telemetry.TpuInfoCliSource(runner=lambda: _TPU_INFO_OUTPUT)
    snap = src.sample(2)
    assert snap is not None and snap.source == "tpu_info_cli"
    assert snap.per_chip[0] == {
        "hbm_used_gb": 1.5, "hbm_total_gb": 15.75,
        "duty_cycle_pct": 12.0, "tensorcore_util_pct": 34.2,
        "holder_pid": 777,
    }
    assert snap.per_chip[1]["duty_cycle_pct"] == 97.5
    assert snap.per_chip[1]["hbm_used_gb"] == 14.2
    # Chips-table PID column (the process HOLDING each chip — possibly one
    # this control plane never launched; reference gpu_manager.py:174-184).
    assert snap.per_chip[1]["holder_pid"] == 777


def test_tpu_info_cli_holder_pid_absent_when_cell_empty():
    text = _TPU_INFO_OUTPUT.replace("│ 777 │", "│     │")
    fields = telemetry.TpuInfoCliSource.parse(text)
    assert "holder_pid" not in fields.get(0, {})
    assert fields[0]["hbm_used_gb"] == 1.5  # other tables still parse


def test_tpu_info_cli_source_degrades_to_none():
    assert telemetry.TpuInfoCliSource(runner=lambda: "").sample(2) is None
    assert telemetry.TpuInfoCliSource(runner=lambda: "no tables here").sample(2) is None

    def boom():
        raise RuntimeError("binary exploded")

    assert telemetry.TpuInfoCliSource(runner=boom).sample(2) is None
    # No runner + no binary on PATH → None, never an exception.
    assert telemetry.TpuInfoCliSource(binary="definitely-not-a-binary").sample(2) is None


def test_tpu_info_cli_registered_between_sdk_and_derived():
    names = [type(s).__name__ for s in telemetry.sources()]
    assert names == ["LibtpuSdkSource", "TpuInfoCliSource", "DerivedDutySource"]


def test_overlay_sdk_beats_cli_beats_derived():
    sdk = LibtpuSdkSource(monitoring=FakeMonitoring({"duty_cycle_pct": ["50.00", "60.00"]}))
    cli = telemetry.TpuInfoCliSource(runner=lambda: _TPU_INFO_OUTPUT)
    telemetry.set_sources([sdk, cli, telemetry.derived_duty()])
    overlay = telemetry.sample_overlay(2)
    # SDK wins on duty; CLI fills what the SDK lacks (HBM, tensorcore).
    assert overlay.per_chip[0]["duty_cycle_pct"] == 50.0
    assert overlay.per_chip[0]["tensorcore_util_pct"] == 34.2
    assert overlay.per_chip[0]["hbm_total_gb"] == 15.75
    assert overlay.sources == ["libtpu_sdk", "tpu_info_cli"]


def test_tpu_info_cli_rate_limits_subprocess_invocations(monkeypatch):
    src = telemetry.TpuInfoCliSource(min_interval_s=60.0)
    calls = []

    def fake_invoke():
        calls.append(1)
        return _TPU_INFO_OUTPUT

    monkeypatch.setattr(src, "_invoke", fake_invoke)
    for _ in range(5):
        assert src.sample(2) is not None
    assert len(calls) == 1  # one fork per interval, cached in between
