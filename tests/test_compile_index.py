"""Fleet compile cache: layout-keyed warm-start index, cache-aware
placement ranking, and precompile-before-grow-back through the real
FleetScheduler — all analytic / thread-stubbed, so everything is tier-1.

The headline perf claims (chaos MTTR with the index on vs off, warm-
preferring admission mean wait) are asserted here against the seeded
virtual-clock benchmarks, so a refactor that erases the win fails CI.
"""

import json
import threading
import time

import pytest

from tpu_engine import compile_index, faults
from tpu_engine.compile_index import (
    SIDECAR_NAME,
    CompileCacheIndex,
    PrecompileWorker,
    index_key,
    key_for_config,
    label_for_config,
    model_digest,
    runtime_fingerprint,
)
from tpu_engine.faults import FaultKind, FaultPlan, FaultSpec
from tpu_engine.mesh_runtime import MeshConfig
from tpu_engine.placement import PlacementPlanner
from tpu_engine.scheduler import FleetScheduler, SubmissionState
from tpu_engine.sharding import TPUTrainConfig
from tpu_engine.supervisor import JobStatus
from tpu_engine.tpu_manager import TPUManager


@pytest.fixture(autouse=True)
def _clean_process_state():
    """No fault plan or process-wide index leaks across tests."""
    faults.clear_active()
    compile_index.reset_index()
    yield
    faults.clear_active()
    compile_index.reset_index()


def cfg(**kw):
    base = dict(
        model_name="gpt-tiny",
        mesh=MeshConfig(data=1, fsdp=2),
        micro_batch_size=1,
        seq_len=32,
        precision="fp32",
        total_steps=5,
        activation_checkpointing=False,
        checkpoint_dir="/tmp/compile_index_test",  # preemptibility flag only
    )
    base.update(kw)
    return TPUTrainConfig(**base)


def wait_until(pred, timeout=5.0, interval=0.01):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


# ---------------------------------------------------------------------------
# index: keying, warm/cold ledger, EMA
# ---------------------------------------------------------------------------


def test_record_marks_warm_and_zeroes_expected_compile():
    idx = CompileCacheIndex()
    key = "digest|rt|data2xfsdp4·s3"
    assert not idx.is_warm(key)
    # Nothing measured anywhere yet → the pessimistic default.
    assert idx.expected_compile_s(key) == idx.default_cold_s
    idx.record(key, 12.0, cache_hit=False, label="data2xfsdp4·s3", model="gpt-tiny")
    assert idx.is_warm(key)
    assert idx.expected_compile_s(key) == 0.0  # warm → next admission is free
    st = idx.stats()
    assert st["entries"] == 1 and st["warm_entries"] == 1
    assert st["misses_total"] == 1 and st["hits_total"] == 0
    assert st["cold_compile_s_total"] == 12.0
    # A later hit on the same layout counts as a hit, stays warm.
    idx.record(key, 0.4, cache_hit=True)
    assert idx.stats()["hits_total"] == 1 and idx.is_warm(key)


def test_cold_ema_per_layout_with_global_fallback():
    idx = CompileCacheIndex(ema_alpha=0.3)
    idx.record("k1", 10.0, cache_hit=False)
    assert idx.expected_cold_s("k1") == 10.0
    idx.record("k1", 20.0, cache_hit=False)
    # EMA: 0.7 * 10 + 0.3 * 20 = 13.0 (per-layout and global move together
    # here — k1 is the only layout ever measured).
    assert idx.expected_cold_s("k1") == pytest.approx(13.0)
    # A never-seen layout predicts the global cold EMA, not the default.
    assert idx.expected_compile_s("k-unseen") == pytest.approx(13.0)
    assert idx.stats()["global_cold_ema_s"] == pytest.approx(13.0)


def test_key_helpers_are_deterministic_and_layout_sensitive():
    c = cfg(mesh=MeshConfig(data=2, fsdp=4))
    assert key_for_config(c) == key_for_config(c)
    assert runtime_fingerprint() in key_for_config(c)
    assert model_digest(c) == model_digest(c)
    # A different model shape digests differently …
    assert model_digest(c) != model_digest(cfg(seq_len=64))
    # … and a different mesh labels differently under the same digest.
    lbl_a = label_for_config(c)
    lbl_b = label_for_config(c, mesh={"data": 4, "fsdp": 2}, gang=8)
    assert lbl_a != lbl_b
    assert index_key(lbl_a, c) != index_key(lbl_b, c)


def test_sidecar_round_trip_and_merge(tmp_path):
    path = str(tmp_path / SIDECAR_NAME)
    idx = CompileCacheIndex(path=path)
    idx.record("k1", 7.0, cache_hit=False, label="lay1", model="gpt-tiny")
    doc = json.loads((tmp_path / SIDECAR_NAME).read_text())
    assert doc["version"] == 1 and "k1" in doc["entries"]
    # A fresh process pointed at the same sidecar starts warm.
    reborn = CompileCacheIndex(path=path)
    assert reborn.is_warm("k1")
    assert reborn.expected_cold_s("k1") == 7.0
    # attach_dir merges what a previous process persisted without
    # clobbering this process's own observations.
    other = CompileCacheIndex()
    other.record("k2", 3.0, cache_hit=False)
    other.attach_dir(str(tmp_path))
    assert other.is_warm("k1") and other.is_warm("k2")
    assert other.stats()["sidecar_path"] == path
    # … and persists the merged view back for the next process.
    merged = json.loads((tmp_path / SIDECAR_NAME).read_text())
    assert set(merged["entries"]) == {"k1", "k2"}


def test_sidecar_tolerates_torn_and_garbage_files(tmp_path):
    """A half-written or garbage sidecar (host died mid-write) must warn,
    count, and start fresh — never raise."""
    path = tmp_path / SIDECAR_NAME
    # Torn file: valid prefix of a JSON document, cut mid-append.
    path.write_text('{"version": 1, "entries": {"k1": {"warm": tr')
    idx = CompileCacheIndex(path=str(path))
    st = idx.stats()
    assert st["entries"] == 0
    assert st["sidecar_load_errors_total"] == 1
    # The fresh index still works and persists over the torn file.
    idx.record("k2", 2.0, cache_hit=False)
    assert json.loads(path.read_text())["entries"].keys() == {"k2"}

    # Valid JSON but not an object: same degradation path.
    path.write_text('[1, 2, 3]')
    idx2 = CompileCacheIndex(path=str(path))
    assert idx2.stats()["sidecar_load_errors_total"] == 1
    # Valid object whose "entries" is the wrong shape.
    path.write_text('{"version": 1, "entries": "oops"}')
    idx3 = CompileCacheIndex(path=str(path))
    assert idx3.stats()["sidecar_load_errors_total"] == 1
    # attach_dir over garbage also degrades to the counter.
    path.write_text("\x00\x01 not json")
    idx4 = CompileCacheIndex()
    idx4.record("mine", 1.0, cache_hit=False)
    idx4.attach_dir(str(tmp_path))
    assert idx4.stats()["sidecar_load_errors_total"] == 1
    assert idx4.is_warm("mine")  # own observations survive the bad merge


def test_lru_bound_evicts_oldest(tmp_path):
    clock = iter(range(100))
    idx = CompileCacheIndex(
        path=str(tmp_path / SIDECAR_NAME), max_entries=3,
        clock=lambda: float(next(clock)),
    )
    for i in range(5):
        idx.record(f"k{i}", 1.0, cache_hit=False)
    st = idx.stats()
    assert st["entries"] == 3 and st["evictions_total"] == 2
    assert not idx.is_warm("k0") and not idx.is_warm("k1")
    assert idx.is_warm("k4")
    # The bound holds on disk too — the sidecar can never grow unbounded.
    doc = json.loads((tmp_path / SIDECAR_NAME).read_text())
    assert len(doc["entries"]) == 3


def test_invalidate_drops_warmth():
    idx = CompileCacheIndex()
    idx.record("k1", 5.0, cache_hit=False)
    idx.record("k2", 5.0, cache_hit=False)
    assert idx.invalidate("k1") == 1
    assert not idx.is_warm("k1") and idx.is_warm("k2")
    assert idx.invalidate() == 1  # wipe-the-cache-dir path
    assert idx.stats()["entries"] == 0


# ---------------------------------------------------------------------------
# planner: warm annotation + warm-first ranking band
# ---------------------------------------------------------------------------


def test_planner_annotates_warm_and_tiebreaks_within_band():
    c = cfg(mesh=MeshConfig(data=2, fsdp=4), micro_batch_size=2)
    idx = CompileCacheIndex()
    # Unbounded band: ANY warm feasible layout outranks every cold one.
    planner = PlacementPlanner(
        compile_index=idx, prefer_warm_max_slowdown_pct=10_000.0
    )
    cold = planner.plan(c, n_avail=8)
    assert len(cold.plans) >= 2
    assert all(p.compile_warm is False for p in cold.plans)
    assert all(p.expected_compile_s == idx.default_cold_s for p in cold.plans)
    assert planner.warm_tiebreaks_total == 0
    # Warm the layout the cold ranking put LAST; with the band wide open it
    # must now rank first, and the planner counts the inversion.
    slowest = cold.plans[-1]
    idx.record(idx.key_for_plan(slowest), 9.0, cache_hit=False)
    warm = planner.plan(c, n_avail=8)
    assert warm.plans[0].label == slowest.label
    assert warm.plans[0].compile_warm is True
    assert warm.plans[0].expected_compile_s == 0.0
    assert planner.warm_tiebreaks_total == 1


def test_planner_band_bounds_the_warm_preference():
    """A warm plan slower than the band never wins on warmth alone."""
    c = cfg(mesh=MeshConfig(data=2, fsdp=4), micro_batch_size=2)
    idx = CompileCacheIndex()
    planner = PlacementPlanner(compile_index=idx, prefer_warm_max_slowdown_pct=0.0)
    cold = planner.plan(c, n_avail=8)
    fastest, slowest = cold.plans[0], cold.plans[-1]
    assert fastest.predicted_step_time_s < slowest.predicted_step_time_s
    idx.record(idx.key_for_plan(slowest), 9.0, cache_hit=False)
    again = planner.plan(c, n_avail=8)
    assert again.plans[0].label == fastest.label  # ranking unchanged
    assert planner.warm_tiebreaks_total == 0
    assert planner.stats()["warm_tiebreaks_total"] == 0
    assert planner.stats()["compile_index_attached"] is True


# ---------------------------------------------------------------------------
# precompile worker: success, injected failure, bounded queue
# ---------------------------------------------------------------------------


def test_precompile_worker_warms_index():
    idx = CompileCacheIndex()
    compiled = []
    worker = PrecompileWorker(idx, compile_fn=compiled.append)
    try:
        assert worker.request("k1", label="lay1") == "queued"
        assert wait_until(lambda: worker.status("k1") == "warm")
        assert idx.is_warm("k1")
        assert compiled and compiled[0].key == "k1"
        assert idx.entries()[0]["last_via"] == "precompile"
        st = worker.stats()
        assert st["completed_total"] == 1 and st["failed_total"] == 0
        # Re-requesting a warm key is a no-op.
        assert worker.request("k1") == "warm"
    finally:
        worker.shutdown()


def test_precompile_worker_fails_under_injected_fault():
    faults.activate(FaultPlan(
        seed=7,
        specs=[FaultSpec(kind=FaultKind.PRECOMPILE_ERROR, at_step=0)],
    ))
    idx = CompileCacheIndex()
    compiled = []
    worker = PrecompileWorker(idx, compile_fn=compiled.append)
    try:
        assert worker.request("k1") == "queued"
        assert wait_until(lambda: worker.status("k1") == "failed")
        assert not idx.is_warm("k1")
        assert not compiled  # the fault fires before the compile attempt
        assert worker.stats()["failed_total"] == 1
        # The fault spec is spent (count=1): a retry succeeds.
        assert worker.request("k1") == "queued"
        assert wait_until(lambda: worker.status("k1") == "warm")
        assert idx.is_warm("k1")
    finally:
        worker.shutdown()


def test_precompile_worker_bounds_pending():
    gate = threading.Event()
    idx = CompileCacheIndex()
    worker = PrecompileWorker(idx, compile_fn=lambda t: gate.wait(5.0), max_pending=1)
    try:
        assert worker.request("k1") == "queued"
        assert worker.request("k2") == "rejected"
        assert worker.stats()["rejected_total"] == 1
    finally:
        gate.set()
        worker.shutdown()


# ---------------------------------------------------------------------------
# scheduler: precompile-before-grow-back round trip
# ---------------------------------------------------------------------------


class StubWatcher:
    def __init__(self):
        self.fired = threading.Event()

    def simulate_interruption(self):
        self.fired.set()


class StubJob:
    """Thread-backed TrainingJob stand-in (see tests/test_scheduler.py)."""

    def __init__(self, sub):
        self.job_id = sub.job_id
        self.config = sub.config
        self.status = JobStatus.PENDING
        self.error = None
        self.current_step = 0
        self.watcher = StubWatcher()
        self._stop = threading.Event()
        self._done = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    @property
    def is_alive(self):
        return self._thread.is_alive()

    def start(self):
        self._thread.start()

    def join(self, timeout=None):
        self._thread.join(timeout)

    def describe(self):
        return {"job_id": self.job_id, "status": self.status.value}

    def finish(self):
        self._done.set()

    def _run(self):
        self.status = JobStatus.RUNNING
        while not self._done.is_set():
            if self._stop.is_set():
                self.status = JobStatus.STOPPED
                return
            if self.watcher.fired.is_set():
                self.status = JobStatus.PREEMPTED  # the "emergency save"
                return
            self._done.wait(0.005)
        self.status = JobStatus.COMPLETED


def _chip(i, **kw):
    base = dict(
        index=i, device_kind="TPU v5e", hbm_total_gb=16.0, hbm_used_gb=4.0,
        duty_cycle_pct=50.0, temperature_c=50.0,
    )
    base.update(kw)
    return base


def _degraded_fleet():
    mgr = TPUManager()
    return mgr.get_fleet_status(
        metrics=[_chip(0, temperature_c=91.0)] + [_chip(i) for i in range(1, 8)]
    )


def _healthy_fleet():
    mgr = TPUManager()
    return mgr.get_fleet_status(metrics=[_chip(i) for i in range(8)])


@pytest.fixture
def sched_factory():
    created = []

    def make(**kw):
        jobs = []

        def factory(sub):
            job = StubJob(sub)
            jobs.append(job)
            return job

        kw.setdefault("job_factory", factory)
        kw.setdefault("poll_interval_s", 0.01)
        kw.setdefault("grow_back_cooldown_s", 0.0)
        s = FleetScheduler(**kw)
        s._stub_jobs = jobs
        created.append(s)
        return s

    yield make
    for s in created:
        for j in getattr(s, "_stub_jobs", []):
            j.finish()
        s.shutdown()


def elastic_cfg(**kw):
    base = dict(mesh=MeshConfig(data=4, fsdp=2), elastic_min_devices=2)
    base.update(kw)
    return cfg(**base)


def _grow_back_round_trip(sched_factory, **sched_kw):
    """Shrunk admission on a degraded fleet, heal, grow back to the full
    gang, complete — returns the scheduler for counter assertions."""
    fleet_holder = {"fleet": _degraded_fleet()}
    s = sched_factory(
        max_concurrent_jobs=1, fleet_fn=lambda: fleet_holder["fleet"], **sched_kw
    )
    sub = s.submit(elastic_cfg())
    assert wait_until(lambda: sub.state == SubmissionState.RUNNING)
    assert sub.admitted_gang == 6
    fleet_holder["fleet"] = _healthy_fleet()
    assert wait_until(
        lambda: sub.state == SubmissionState.RUNNING and sub.admitted_gang == 8,
        timeout=10.0,
    )
    assert sub.shrunk_mesh is None and sub.attempts == 2
    # Round trip intact: the resize was checkpoint-requeue-readmit, nothing
    # was dropped, and the job can run to completion on the full gang.
    s._stub_jobs[-1].finish()
    assert wait_until(lambda: sub.state == SubmissionState.COMPLETED)
    assert s.stats()["reserved_hbm_gib"] == 0.0
    return s


def test_grow_back_waits_for_background_precompile(sched_factory):
    idx = CompileCacheIndex()
    warmed = []
    s = _grow_back_round_trip(
        sched_factory, compile_index=idx, precompile_fn=warmed.append
    )
    st = s.stats()
    cc = st["compile_cache"]
    assert st["grow_backs_total"] == 1
    # The grow was gated: a background precompile of the target layout ran
    # first, and the preempt only fired once the index said warm.
    assert cc["precompiles_started_total"] == 1
    assert cc["grow_back_warm_total"] == 1 and cc["grow_back_cold_total"] == 0
    assert cc["precompile"]["completed_total"] == 1
    assert len(warmed) == 1 and warmed[0].gang == 8
    assert idx.is_warm(warmed[0].key)
    assert idx.entries()[0]["last_via"] == "precompile"


def test_grow_back_proceeds_cold_under_precompile_error(sched_factory):
    """An injected precompile-error must delay the grow-back, never wedge
    it: the resize proceeds cold and the job still completes."""
    faults.activate(FaultPlan(
        seed=7,
        specs=[FaultSpec(kind=FaultKind.PRECOMPILE_ERROR, at_step=0, count=5)],
    ))
    idx = CompileCacheIndex()
    warmed = []
    s = _grow_back_round_trip(
        sched_factory, compile_index=idx, precompile_fn=warmed.append
    )
    cc = s.stats()["compile_cache"]
    assert s.stats()["grow_backs_total"] == 1
    assert cc["precompiles_started_total"] >= 1
    assert cc["grow_back_cold_total"] == 1 and cc["grow_back_warm_total"] == 0
    assert cc["precompile"]["failed_total"] >= 1
    assert not warmed  # the fault fires before the compile body


def test_grow_back_deadline_unwedges_a_stuck_precompile(sched_factory):
    """A precompiler that never finishes only holds the resize until the
    deadline; then the grow proceeds cold."""
    gate = threading.Event()
    idx = CompileCacheIndex()
    s = _grow_back_round_trip(
        sched_factory,
        compile_index=idx,
        precompile_fn=lambda t: gate.wait(30.0),
        precompile_deadline_s=0.2,
    )
    gate.set()
    cc = s.stats()["compile_cache"]
    assert cc["grow_back_cold_total"] == 1 and cc["grow_back_warm_total"] == 0


def test_grow_back_gate_disabled_is_the_old_behavior(sched_factory):
    called = []
    s = _grow_back_round_trip(
        sched_factory,
        precompile_before_grow=False,
        compile_index=CompileCacheIndex(),
        precompile_fn=called.append,
    )
    cc = s.stats()["compile_cache"]
    assert not called
    assert cc["precompiles_started_total"] == 0
    assert cc["grow_back_warm_total"] == 0 and cc["grow_back_cold_total"] == 0


# ---------------------------------------------------------------------------
# headline numbers: the benches must keep showing the win
# ---------------------------------------------------------------------------


def test_chaos_mttr_lower_with_index_on():
    from benchmarks.chaos import run_trace

    trace = run_trace(seed=0)
    cc = trace["compile_cache"]
    assert cc["mttr_on_s"] < cc["mttr_off_s"]
    assert cc["mttr_warm_reduction_pct"] > 0
    assert cc["warm_resumes"] > 0 and cc["wall_saved_s"] > 0
    # Warm-start must not cost correctness: still zero lost steps.
    assert trace["self_heal"]["lost_steps"] == 0
    assert trace["self_heal_index_off"]["lost_steps"] == 0


def test_warm_admission_sim_reduces_mean_wait():
    from benchmarks.scheduler_sim import run_warm_admission

    res = run_warm_admission(seed=0)
    assert res["mean_wait_warm_s"] < res["mean_wait_fifo_s"]
    assert res["wait_reduction_pct"] > 0
    # Honest win: same work, same compiles — only the order changes.
    assert res["warm_preferring"]["cold_compiles"] == res["fifo"]["cold_compiles"]
    assert res["warm_preferring"]["makespan_s"] == pytest.approx(
        res["fifo"]["makespan_s"]
    )
