"""Weight-only int8 quantization (``tpu_engine/quant.py``).

Load-bearing invariants:

- power-of-two scales make the quantized forward BIT-EXACT vs the
  unquantized bf16 forward (exponent-shift scaling commutes with the
  dot) — so the dispatch plumbing is pinned with zero tolerance;
- random weights stay within the per-channel absmax error bound and
  the end-to-end logits stay strongly correlated with fp32;
- serving through :class:`ContinuousBatcher` with a quantized tree
  emits streams identical to :func:`generate` on the same tree (the
  serving-consistency invariant every other serving feature pins);
- the pspec mirror shards a quantized tree the way its source params
  were sharded (8-virtual-device CPU mesh).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_engine.generate import generate
from tpu_engine.models import transformer as tfm
from tpu_engine.quant import (
    QuantWeight,
    dequantize_weight,
    quantize_params,
    quantize_pspecs,
    quantize_weight,
)


def _params(name="gpt-tiny", seed=0):
    cfg = tfm.MODEL_CONFIGS[name]
    return cfg, tfm.init_params(jax.random.PRNGKey(seed), cfg)


def _pow2_params(params):
    """Snap every quantization-site kernel to exactly-representable int8
    codes times per-output-channel power-of-two scales; quantizing such a
    kernel is lossless and its scale multiplies bf16 values exactly.
    Sites come from the PRODUCTION walker (``quant._walk``) so this test
    keeps pinning every kernel the transform actually quantizes."""
    from tpu_engine.quant import _walk

    counter = [0]

    def snap(leaf):
        w = np.asarray(leaf, np.float32)
        counter[0] += 1
        k = jax.random.fold_in(jax.random.PRNGKey(7), counter[0])
        codes = np.asarray(jax.random.randint(k, w.shape, -127, 128), np.float32)
        # Force at least one |code| == 127 per output channel so absmax
        # quantization recovers exactly these codes and scales.
        codes[..., 0, :] = 127.0
        exp = (np.asarray(
            jax.random.randint(jax.random.fold_in(k, 1), w.shape[:-2] + (1,) + w.shape[-1:], -9, -5)
        )).astype(np.float32)
        return jnp.asarray(codes * np.exp2(exp), jnp.float32)

    return _walk(params, snap)


def test_quantize_roundtrip_error_bound():
    w = jax.random.normal(jax.random.PRNGKey(0), (64, 32), jnp.float32)
    qw = quantize_weight(w)
    assert qw.q.dtype == jnp.int8
    assert qw.scale.shape == (1, 32)
    err = np.abs(np.asarray(dequantize_weight(qw) - w))
    # Symmetric absmax: |error| <= scale/2 per element.
    bound = np.asarray(qw.scale) / 2 + 1e-9
    assert (err <= bound).all()


def test_pow2_quantization_is_lossless():
    _, params = _params()
    p2 = _pow2_params(params)
    w = p2["layers"]["q"]["kernel"]
    qw = quantize_weight(w)
    np.testing.assert_array_equal(
        np.asarray(dequantize_weight(qw)), np.asarray(w)
    )


@pytest.mark.parametrize("name", ["gpt-tiny", "gpt2-tiny", "gemma-tiny",
                                  "qwen-tiny", "moe-tiny"])
def test_quantized_forward_bitexact_on_pow2_weights(name):
    """With power-of-two per-channel scales, (h @ q) * s == h @ (q * s)
    exactly in floating point — the quantized dispatch must be bit-equal
    to the plain bf16 forward across every architecture family."""
    cfg, params = _params(name)
    params = _pow2_params(params)
    qparams = quantize_params(params)
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                              cfg.vocab_size, jnp.int32)
    ref = tfm.forward(params, toks, cfg)
    got = tfm.forward(qparams, toks, cfg)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


def test_quantized_logits_close_to_fp32_random_weights():
    cfg, params = _params("gpt-tiny", seed=3)
    qparams = quantize_params(params)
    toks = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0,
                              cfg.vocab_size, jnp.int32)
    ref = np.asarray(tfm.forward(params, toks, cfg,
                                 compute_dtype=jnp.float32)).ravel()
    got = np.asarray(tfm.forward(qparams, toks, cfg,
                                 compute_dtype=jnp.float32)).ravel()
    corr = np.corrcoef(ref, got)[0, 1]
    assert corr > 0.999, f"quantized logits decorrelated: r={corr}"


def test_quantize_params_structure_and_guards():
    cfg, params = _params("moe-tiny")
    qparams = quantize_params(params)
    layers = qparams["layers"]
    for k in ("q", "k", "v", "o", "gate", "up", "down"):
        assert isinstance(layers[k]["kernel"], QuantWeight)
    # Router, norms, embeddings stay full precision.
    assert not isinstance(layers["router"]["kernel"], QuantWeight)
    assert not isinstance(qparams["embed"]["embedding"], QuantWeight)
    assert isinstance(qparams["lm_head"]["kernel"], QuantWeight)
    # MoE expert scale carries the expert dim: [L, E, 1, F].
    g = layers["gate"]["kernel"]
    assert g.scale.shape == g.q.shape[:-2] + (1,) + g.q.shape[-1:]
    with pytest.raises(ValueError, match="already"):
        quantize_params(qparams)


def test_gpt2_biases_survive_quantization():
    cfg, params = _params("gpt2-tiny")
    qparams = quantize_params(params)
    assert isinstance(qparams["layers"]["fc"]["kernel"], QuantWeight)
    np.testing.assert_array_equal(
        np.asarray(qparams["layers"]["fc"]["bias"]),
        np.asarray(params["layers"]["fc"]["bias"]),
    )


def test_generate_quantized_deterministic_and_matches_pow2():
    cfg, params = _params()
    params = _pow2_params(params)
    qparams = quantize_params(params)
    prompt = jax.random.randint(jax.random.PRNGKey(5), (1, 8), 0,
                                cfg.vocab_size, jnp.int32)
    ref = generate(params, prompt, cfg, max_new_tokens=12)
    got = generate(qparams, prompt, cfg, max_new_tokens=12)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))
    again = generate(qparams, prompt, cfg, max_new_tokens=12)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(again))


def test_moe_decode_quantized_runs():
    cfg, params = _params("moe-tiny")
    qparams = quantize_params(params)
    prompt = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    out = generate(qparams, prompt, cfg, max_new_tokens=6)
    assert out.shape == (1, 10)
    assert (np.asarray(out) >= 0).all()


def test_serving_quantized_matches_generate():
    from tpu_engine.serving import ContinuousBatcher

    cfg, params = _params()
    qparams = quantize_params(params)
    prompts = [[3, 1, 4, 1, 5], [9, 2, 6, 5], [3, 5, 8, 9, 7, 9]]
    N = 10
    b = ContinuousBatcher(qparams, cfg, max_slots=2, max_len=64,
                          chunk_steps=4)
    ids = [b.submit(p, max_new_tokens=N) for p in prompts]
    for _ in range(200):
        b.step()
        if all(b.result(i)["status"] == "done" for i in ids):
            break
    for p, i in zip(prompts, ids):
        ref = generate(qparams, jnp.asarray([p], jnp.int32), cfg,
                       max_new_tokens=N)
        assert b.result(i)["tokens"] == np.asarray(ref)[0, len(p):].tolist()


def test_quantized_pspec_mirror_shards_on_mesh():
    from tpu_engine.mesh_runtime import MeshConfig, build_mesh
    from tpu_engine.models.transformer import logical_axes
    from tpu_engine.sharding import (
        ShardingStage, named_shardings, param_pspecs,
    )

    cfg, params = _params()
    qparams = quantize_params(params)
    pspecs = param_pspecs(logical_axes(cfg), ShardingStage.FULL_PARTITIONING)
    qspecs = quantize_pspecs(pspecs, qparams)
    # q inherits the kernel's spec; scale drops the contracted dim.
    qk = qspecs["layers"]["q"]["kernel"]
    assert qk.q == pspecs["layers"]["q"]["kernel"]
    assert qk.scale[-1] == qk.q[-1] if len(qk.q) else True
    mesh = build_mesh(MeshConfig(fsdp=2, model=4))
    sharded = jax.device_put(qparams, named_shardings(mesh, qspecs))
    qkern = sharded["layers"]["q"]["kernel"]
    # The heads dim (last) shards over "model" for q and scale alike.
    assert qkern.q.sharding.spec[-1] == "model"
    assert qkern.scale.sharding.spec[-1] == "model"

    # Sharded serving from the quantized tree matches single-device.
    from tpu_engine.serving import ContinuousBatcher

    prompt = [2, 7, 1, 8, 2, 8]
    N = 8
    ref = generate(qparams, jnp.asarray([prompt], jnp.int32), cfg,
                   max_new_tokens=N)
    b = ContinuousBatcher(sharded, cfg, max_slots=2, max_len=64,
                          chunk_steps=4, mesh=mesh)
    rid = b.submit(prompt, max_new_tokens=N)
    for _ in range(100):
        b.step()
        if b.result(rid)["status"] == "done":
            break
    assert b.result(rid)["tokens"] == np.asarray(ref)[0, len(prompt):].tolist()


def test_quantized_snapshot_roundtrip(tmp_path):
    """save_quantized / load_quantized: bit-identical tree back (codes,
    scales, and full-precision leaves incl. bf16), streams unchanged."""
    from tpu_engine.quant import load_quantized, save_quantized

    cfg, params = _params("gpt2-tiny")  # biases + tied head in the tree
    params = jax.tree.map(lambda a: a.astype(jnp.bfloat16), params)
    qparams = quantize_params(params)
    save_quantized(qparams, str(tmp_path / "snap"))
    loaded = load_quantized(str(tmp_path / "snap"))

    a_leaves = jax.tree.leaves(qparams)
    b_leaves = jax.tree.leaves(loaded)
    assert len(a_leaves) == len(b_leaves)
    for a, b in zip(a_leaves, b_leaves):
        assert a.dtype == b.dtype
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    prompt = jnp.asarray([[5, 6, 7, 8]], jnp.int32)
    ref = generate(qparams, prompt, cfg, max_new_tokens=8)
    got = generate(loaded, prompt, cfg, max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


def test_quantized_snapshot_sharded_load(tmp_path):
    from tpu_engine.mesh_runtime import MeshConfig, build_mesh
    from tpu_engine.models.transformer import logical_axes
    from tpu_engine.quant import load_quantized, save_quantized
    from tpu_engine.serving import ContinuousBatcher
    from tpu_engine.sharding import (
        ShardingStage, named_shardings, param_pspecs,
    )

    cfg, params = _params()
    qparams = quantize_params(params)
    save_quantized(qparams, str(tmp_path / "snap"))

    mesh = build_mesh(MeshConfig(fsdp=2, model=4))
    qsh = named_shardings(mesh, quantize_pspecs(
        param_pspecs(logical_axes(cfg), ShardingStage.FULL_PARTITIONING),
        qparams,
    ))
    loaded = load_quantized(str(tmp_path / "snap"), shardings=qsh)
    qk = loaded["layers"]["q"]["kernel"]
    assert qk.q.sharding.spec[-1] == "model"

    prompt = [3, 1, 4, 1, 5]
    ref = generate(qparams, jnp.asarray([prompt], jnp.int32), cfg,
                   max_new_tokens=6)
    srv = ContinuousBatcher(loaded, cfg, max_slots=2, max_len=64,
                            chunk_steps=3, mesh=mesh)
    rid = srv.submit(prompt, max_new_tokens=6)
    for _ in range(60):
        srv.step()
        if srv.result(rid)["status"] == "done":
            break
    assert srv.result(rid)["tokens"] == np.asarray(ref)[0, len(prompt):].tolist()


def test_save_quantized_rejects_plain_tree(tmp_path):
    from tpu_engine.quant import save_quantized

    _, params = _params()
    with pytest.raises(ValueError, match="no QuantWeight"):
        save_quantized(params, str(tmp_path / "snap"))
