"""Control-plane API tests: real aiohttp server on an ephemeral port, driven
with httpx against the real engine on the 8-virtual-device CPU mesh — the
reference has no tests at all (SURVEY.md §4)."""

import asyncio
import threading
import time

import httpx
import pytest
from aiohttp import web

from backend.main import create_app


@pytest.fixture(scope="module")
def client():
    loop = asyncio.new_event_loop()
    started = threading.Event()
    state = {}

    def run():
        asyncio.set_event_loop(loop)
        runner = web.AppRunner(create_app())
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, "127.0.0.1", 0)
        loop.run_until_complete(site.start())
        state["port"] = runner.addresses[0][1]
        started.set()
        loop.run_forever()
        loop.run_until_complete(runner.cleanup())

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert started.wait(timeout=30)
    with httpx.Client(base_url=f"http://127.0.0.1:{state['port']}", timeout=60) as c:
        yield c
    loop.call_soon_threadsafe(loop.stop)
    t.join(timeout=10)


# -- assembly ---------------------------------------------------------------


def test_root_and_health(client):
    r = client.get("/")
    assert r.status_code == 200
    assert "features" in r.json()
    h = client.get("/health").json()
    assert h["status"] == "healthy"
    assert h["devices"] == 8


def test_cors_headers(client):
    r = client.get("/health")
    assert r.headers.get("Access-Control-Allow-Origin") == "*"


def test_openapi_schema_and_docs(client):
    """Machine-readable API schema (round-4 verdict gap 1 — FastAPI gives
    the reference this for free; the aiohttp port now generates it from
    the live route table and the same pydantic models parse_body uses)."""
    spec = client.get("/openapi.json").json()
    assert spec["openapi"].startswith("3.")
    paths = spec["paths"]
    # Every mounted surface is present (spot-check one route per router).
    for p in ("/api/v1/tpu/fleet", "/api/v1/training/launch",
              "/api/v1/monitoring/ingest", "/api/v1/topology",
              "/api/v1/profile/trace/start", "/api/v1/serving/start",
              "/api/v1/serving/stream/{request_id}", "/metrics",
              "/health", "/"):
        assert p in paths, p
    assert len(paths) >= 35
    # Request schemas come from the real pydantic models.
    start = paths["/api/v1/serving/start"]["post"]
    ref = start["requestBody"]["content"]["application/json"]["schema"]["$ref"]
    assert ref == "#/components/schemas/ServingStartRequest"
    schema = spec["components"]["schemas"]["ServingStartRequest"]
    assert "max_slots" in schema["properties"]
    assert "TrainingLaunchRequest" in spec["components"]["schemas"]
    # Response model annotation on the fleet route.
    fleet200 = paths["/api/v1/tpu/fleet"]["get"]["responses"]["200"]
    assert fleet200["content"]["application/json"]["schema"]["$ref"].endswith(
        "TPUFleetStatus")
    # Path params are typed.
    dev = paths["/api/v1/tpu/devices/{index}"]["get"]["parameters"][0]
    assert dev["name"] == "index" and dev["schema"]["type"] == "integer"
    # Docs page is self-contained HTML.
    r = client.get("/docs")
    assert r.status_code == 200
    assert r.headers["content-type"].startswith("text/html")
    assert "/openapi.json" in r.text


def test_topology_is_mounted_and_real(client):
    # The reference's topology router exists but is never mounted (SURVEY §2 C9).
    r = client.get("/api/v1/topology")
    assert r.status_code == 200
    body = r.json()
    assert body["num_devices"] == 8
    assert body["mesh"]["axes"]["data"] == 8


# -- tpu router -------------------------------------------------------------


def test_fleet_and_mock(client):
    fleet = client.get("/api/v1/tpu/fleet").json()
    assert fleet["total_devices"] == 8
    mock = client.get("/api/v1/tpu/fleet/mock").json()
    assert mock["total_devices"] == 8
    assert mock["available_devices"] == 7
    assert mock["devices"][5]["health_status"] == "warning"


def test_select_and_device_detail(client):
    best = client.get("/api/v1/tpu/select").json()
    assert best is not None and "index" in best
    assert client.get("/api/v1/tpu/devices/0").status_code == 200
    assert client.get("/api/v1/tpu/devices/99").status_code == 404
    assert client.get("/api/v1/tpu/select", params={"min_free_hbm_gb": "bogus"}).status_code == 422


def test_alerts_endpoint(client):
    r = client.get("/api/v1/tpu/alerts").json()
    assert "total_alerts" in r and "alerts" in r


# -- training router --------------------------------------------------------


def test_launch_dry_run_default(client):
    r = client.post("/api/v1/training/launch", json={"model_name": "gpt-125m"})
    assert r.status_code == 200
    body = r.json()
    assert body["status"] == "dry_run"  # dry_run defaults True at the API layer
    assert body["plan"]["sharding"]["stage"] == 3
    # No job created by a dry run.
    jobs = client.get("/api/v1/training/jobs").json()["jobs"]
    assert body["job_id"] not in [j["job_id"] for j in jobs]


def test_config_generate(client):
    r = client.post(
        "/api/v1/training/config/generate",
        json={"model_name": "llama-7b", "sharding_stage": 1, "mesh": {"data": 1, "fsdp": 4}},
    )
    assert r.status_code == 200
    plan = r.json()["plan"]
    assert plan["sharding"]["semantics"]["optimizer_state"] == "sharded over fsdp"
    assert plan["sharding"]["semantics"]["params"] == "replicated"


def test_presets_listing(client):
    r = client.get("/api/v1/training/presets").json()
    assert {"125m", "7b", "13b", "70b"} <= set(r)
    assert r["7b"]["effective_batch_size"] == 128  # reference's 7b eff. batch


def test_comm_flags_rejected_for_live_server_launch(client):
    """XLA process flags cannot act in a running server: a live preset
    launch that overrides them is a 422, not a silent no-op (round-1
    review finding); a dry run may still carry them (plan generation)."""
    r = client.post(
        "/api/v1/training/launch/preset",
        json={"preset_name": "125m",
              "overrides": {"xla_extra_flags": "--xla_foo=1"},
              "dry_run": False},
    )
    assert r.status_code == 422
    assert "worker CLI" in r.text
    r = client.post(
        "/api/v1/training/launch/preset",
        json={"preset_name": "125m",
              "overrides": {"async_collectives": False}, "dry_run": True},
    )
    assert r.status_code == 200


def test_unknown_launch_fields_are_422(client):
    # extra="forbid": typos and unsupported knobs fail loudly instead of
    # being silently dropped.
    r = client.post(
        "/api/v1/training/launch",
        json={"model_name": "gpt-tiny", "async_collectives": True},
    )
    assert r.status_code == 422


def test_preset_launch_not_found_and_overrides(client):
    assert (
        client.post("/api/v1/training/launch/preset", json={"preset_name": "900b"}).status_code
        == 404
    )
    r = client.post(
        "/api/v1/training/launch/preset",
        json={"preset_name": "7b", "overrides": {"micro_batch_size": 4}, "dry_run": True},
    )
    assert r.status_code == 200
    assert r.json()["plan"]["batch"]["micro_batch_size"] == 4


def test_invalid_bodies_rejected(client):
    r = client.post(
        "/api/v1/training/launch", json={"model_name": "gpt-125m", "precision": "fp64"}
    )
    assert r.status_code == 422
    r = client.post(
        "/api/v1/training/launch", json={"micro_batch_size": -1}
    )
    assert r.status_code == 422
    r = client.post(
        "/api/v1/training/launch",
        content=b"not json",
        headers={"content-type": "application/json"},
    )
    assert r.status_code == 422


def test_real_launch_job_lifecycle(client):
    r = client.post(
        "/api/v1/training/launch",
        json={
            "model_name": "gpt-tiny",
            "mesh": {"data": 2, "fsdp": 4},
            "micro_batch_size": 1,
            "seq_len": 32,
            "precision": "fp32",
            "total_steps": 4,
            "activation_checkpointing": False,
            "warmup_steps": 1,
            "dry_run": False,
        },
    )
    assert r.status_code == 200
    job_id = r.json()["job_id"]
    assert r.json()["status"] == "launched"

    deadline = time.time() + 240
    status = None
    while time.time() < deadline:
        status = client.get(f"/api/v1/training/jobs/{job_id}").json()
        if status["status"] in ("completed", "failed"):
            break
        time.sleep(1)
    assert status["status"] == "completed", status
    assert status["current_step"] == 4

    # Unified job identity: the monitoring routes see the supervisor's monitor.
    summary = client.get(f"/api/v1/monitoring/summary/{job_id}").json()
    assert summary["total_steps_seen"] == 4
    curve = client.get(f"/api/v1/monitoring/loss-curve/{job_id}").json()
    assert len(curve["losses"]) == 4
    assert job_id in client.get("/api/v1/monitoring/jobs").json()["jobs"]

    # Supervisor-owned monitors are read-only over HTTP: writes must 409.
    r = client.post(
        "/api/v1/monitoring/ingest/single",
        json={"job_id": job_id, "step": 999, "loss": 1e9},
    )
    assert r.status_code == 409
    assert client.post(f"/api/v1/monitoring/reset/{job_id}").status_code == 409
    assert client.post("/api/v1/monitoring/create", json={"job_id": job_id}).status_code == 409
    # The fake metric did not pollute the real history.
    assert client.get(f"/api/v1/monitoring/summary/{job_id}").json()["total_steps_seen"] == 4


def test_stop_unknown_job(client):
    assert client.post("/api/v1/training/jobs/nope/stop").status_code == 404


# -- monitoring router ------------------------------------------------------


def test_monitor_create_ingest_summary_reset(client):
    jid = "external-job-1"
    r = client.post("/api/v1/monitoring/create", json={"job_id": jid})
    assert r.json()["created"]
    # Idempotent re-create reports created:false (config is NOT replaced).
    assert client.post("/api/v1/monitoring/create", json={"job_id": jid}).json()["created"] is False

    metrics = [{"step": i, "loss": 2.0 + 0.001 * i} for i in range(30)]
    r = client.post("/api/v1/monitoring/ingest", json={"job_id": jid, "metrics": metrics})
    assert r.status_code == 200 and r.json() == []

    r = client.post(
        "/api/v1/monitoring/ingest/single", json={"job_id": jid, "step": 30, "loss": 50.0}
    )
    alerts = r.json()
    assert any(a["alert_type"] == "loss_spike" for a in alerts)

    summary = client.get(f"/api/v1/monitoring/summary/{jid}").json()
    assert summary["total_steps_seen"] == 31
    assert summary["alerts_by_type"]["loss_spike"] == 1

    assert client.post(f"/api/v1/monitoring/reset/{jid}").json()["reset"]
    assert client.get(f"/api/v1/monitoring/summary/{jid}").json()["total_steps_seen"] == 0

    # DELETE is the reference's exact route spelling
    # (reference monitoring.py:119) — endpoint compat.
    client.post(
        "/api/v1/monitoring/ingest/single", json={"job_id": jid, "step": 1, "loss": 2.0}
    )
    assert client.delete(f"/api/v1/monitoring/reset/{jid}").json()["reset"]
    assert client.get(f"/api/v1/monitoring/summary/{jid}").json()["total_steps_seen"] == 0


def test_monitor_divergence_alert_over_http(client):
    jid = "external-job-2"
    r = client.post(
        "/api/v1/monitoring/ingest/single", json={"job_id": jid, "step": 0, "loss": 2e9}
    )
    assert any(
        a["alert_type"] == "divergence" and a["severity"] == "critical" for a in r.json()
    )
    alerts = client.get(f"/api/v1/monitoring/alerts/{jid}").json()
    assert len(alerts) == 1


def test_monitor_404s(client):
    assert client.get("/api/v1/monitoring/summary/ghost").status_code == 404
    assert client.get("/api/v1/monitoring/loss-curve/ghost").status_code == 404
    assert client.post("/api/v1/monitoring/reset/ghost").status_code == 404


# -- profiling routes --------------------------------------------------------


def test_profile_trace_routes(client, tmp_path_factory):
    assert client.get("/api/v1/profile/trace").json()["active"] is False
    # Stop with no active trace → 409.
    assert client.post("/api/v1/profile/trace/stop").status_code == 409

    log_dir = str(tmp_path_factory.mktemp("trace"))
    r = client.post("/api/v1/profile/trace/start", json={"log_dir": log_dir})
    assert r.status_code == 200 and r.json()["active"] is True
    # Second start while active → 409.
    assert client.post("/api/v1/profile/trace/start", json={}).status_code == 409
    out = client.post("/api/v1/profile/trace/stop").json()
    assert out["active"] is False and out["log_dir"] == log_dir


def test_profile_job_routes(client):
    assert client.get("/api/v1/profile/jobs/ghost").status_code == 404

    # Launch a tiny supervised job; its profile must expose the breakdown.
    r = client.post(
        "/api/v1/training/launch",
        json={
            "model_name": "gpt-tiny",
            "mesh": {"data": 2, "fsdp": 4},
            "seq_len": 32,
            "precision": "fp32",
            "total_steps": 3,
            "max_steps": 3,
            "warmup_steps": 1,
            "activation_checkpointing": False,
            "dry_run": False,
        },
    )
    job_id = r.json()["job_id"]
    for _ in range(120):
        d = client.get(f"/api/v1/training/jobs/{job_id}").json()
        if d["status"] in ("completed", "failed"):
            break
        time.sleep(0.5)
    assert d["status"] == "completed"
    prof = client.get(f"/api/v1/profile/jobs/{job_id}").json()["profile"]
    assert prof["steps_seen"] == 3
    assert set(prof["phases"]) == {"data", "dispatch", "device", "other"}
    assert d["profile"]["steps_seen"] == 3  # also embedded in job describe()


def test_generate_from_job(client):
    r = client.post(
        "/api/v1/training/launch",
        json={
            "model_name": "gpt-tiny",
            "mesh": {"data": 2, "fsdp": 4},
            "micro_batch_size": 1,
            "seq_len": 32,
            "precision": "fp32",
            "total_steps": 2,
            "activation_checkpointing": False,
            "warmup_steps": 1,
            "dry_run": False,
        },
    )
    job_id = r.json()["job_id"]
    deadline = time.time() + 240
    while time.time() < deadline:
        if client.get(f"/api/v1/training/jobs/{job_id}").json()["status"] in (
            "completed", "failed",
        ):
            break
        time.sleep(1)

    r = client.post(
        f"/api/v1/training/jobs/{job_id}/generate",
        json={"prompt_tokens": [[1, 2, 3, 4]], "max_new_tokens": 5},
    )
    assert r.status_code == 200, r.text
    body = r.json()
    assert body["tokens"][0][:4] == [1, 2, 3, 4]
    assert len(body["new_tokens"][0]) == 5
    # Sampling params flow through; same seed → same tokens.
    j = {"prompt_tokens": [[5, 6, 7]], "max_new_tokens": 4,
         "temperature": 0.9, "top_k": 20, "top_p": 0.9, "seed": 11}
    a = client.post(f"/api/v1/training/jobs/{job_id}/generate", json=j).json()
    b = client.post(f"/api/v1/training/jobs/{job_id}/generate", json=j).json()
    assert a["tokens"] == b["tokens"]

    # int8 KV cache over HTTP: greedy output matches the bf16 cache (the
    # quantisation error is far below random-init logit gaps).
    g = {"prompt_tokens": [[1, 2, 3, 4]], "max_new_tokens": 5}
    full = client.post(f"/api/v1/training/jobs/{job_id}/generate", json=g).json()
    q = client.post(
        f"/api/v1/training/jobs/{job_id}/generate", json={**g, "kv_cache": "int8"}
    ).json()
    assert q["tokens"] == full["tokens"]
    # Unknown kv_cache values are a 422.
    r = client.post(
        f"/api/v1/training/jobs/{job_id}/generate",
        json={**g, "kv_cache": "int4"},
    )
    assert r.status_code == 422
    # int8 + speculative is rejected (no silent full-precision fallback).
    r = client.post(
        f"/api/v1/training/jobs/{job_id}/generate",
        json={**g, "kv_cache": "int8", "draft_hf_checkpoint": "/nope"},
    )
    assert r.status_code == 422 and "speculative" in r.text

    # Ragged prompts are a 422, not a crash.
    r = client.post(
        f"/api/v1/training/jobs/{job_id}/generate",
        json={"prompt_tokens": [[1, 2], [3]]},
    )
    assert r.status_code == 422
    # Unknown job is a 404.
    r = client.post(
        "/api/v1/training/jobs/nope/generate", json={"prompt_tokens": [[1]]}
    )
    assert r.status_code == 404


def test_lora_request_validation(client):
    # lora knobs without lora_rank → 422 at request time.
    r = client.post(
        "/api/v1/training/launch",
        json={"model_name": "gpt-tiny", "lora_targets": ["q"]},
    )
    assert r.status_code == 422
    # Bad target name → 422 at request time, not an async job failure.
    r = client.post(
        "/api/v1/training/launch",
        json={"model_name": "gpt-tiny", "lora_rank": 4, "lora_targets": ["query"]},
    )
    assert r.status_code == 422
    # MoE expert MLPs cannot take adapters.
    r = client.post(
        "/api/v1/training/launch",
        json={"model_name": "moe-tiny", "lora_rank": 4, "lora_targets": ["gate"]},
    )
    assert r.status_code == 422
    # Valid LoRA dry-run sails through.
    r = client.post(
        "/api/v1/training/launch",
        json={"model_name": "gpt-tiny", "lora_rank": 4},
    )
    assert r.status_code == 200


def test_loss_curve_includes_eval(client):
    r = client.post(
        "/api/v1/training/launch",
        json={
            "model_name": "gpt-tiny",
            "mesh": {"data": 2, "fsdp": 4},
            "micro_batch_size": 1,
            "seq_len": 32,
            "precision": "fp32",
            "total_steps": 4,
            "activation_checkpointing": False,
            "warmup_steps": 1,
            "eval_interval_steps": 2,
            "eval_batches": 1,
            "dry_run": False,
        },
    )
    job_id = r.json()["job_id"]
    deadline = time.time() + 240
    while time.time() < deadline:
        if client.get(f"/api/v1/training/jobs/{job_id}").json()["status"] in (
            "completed", "failed",
        ):
            break
        time.sleep(1)
    curve = client.get(f"/api/v1/monitoring/loss-curve/{job_id}").json()
    assert curve["eval_steps"] == [2, 4]
    assert len(curve["eval_losses"]) == 2

    # GET mirror of the supervisor's bounded eval history (VERDICT r2 #9).
    hist = client.get(f"/api/v1/training/jobs/{job_id}/eval")
    assert hist.status_code == 200
    body = hist.json()
    assert [p["step"] for p in body["history"]] == [2, 4]
    assert body["latest_step"] == 4
    assert body["latest_perplexity"] > 0
    assert client.get("/api/v1/training/jobs/nope/eval").status_code == 404


def test_job_checkpoints_listing(client, tmp_path_factory):
    ckpt_dir = str(tmp_path_factory.mktemp("api_ckpt"))
    r = client.post(
        "/api/v1/training/launch",
        json={
            "model_name": "gpt-tiny",
            "mesh": {"data": 2, "fsdp": 4},
            "micro_batch_size": 1,
            "seq_len": 32,
            "precision": "fp32",
            "total_steps": 4,
            "activation_checkpointing": False,
            "warmup_steps": 1,
            "checkpoint_dir": ckpt_dir,
            "checkpoint_interval_steps": 2,
            "dry_run": False,
        },
    )
    job_id = r.json()["job_id"]
    deadline = time.time() + 240
    while time.time() < deadline:
        if client.get(f"/api/v1/training/jobs/{job_id}").json()["status"] in (
            "completed", "failed",
        ):
            break
        time.sleep(1)
    ck = client.get(f"/api/v1/training/jobs/{job_id}/checkpoints").json()
    assert ck["checkpoint_dir"] == ckpt_dir
    assert ck["latest"] == 4
    assert set(ck["steps"]) >= {2, 4}
    assert ck["stable"] == 4  # final save is marked stable at completion
    # Unknown job → 404.
    assert client.get("/api/v1/training/jobs/nope/checkpoints").status_code == 404
    # Job without checkpointing → uniform empty schema.
    r2 = client.post(
        "/api/v1/training/launch",
        json={
            "model_name": "gpt-tiny", "mesh": {"data": 2, "fsdp": 4},
            "micro_batch_size": 1, "seq_len": 32, "precision": "fp32",
            "total_steps": 1, "activation_checkpointing": False,
            "warmup_steps": 1, "dry_run": False,
        },
    )
    jid2 = r2.json()["job_id"]
    deadline = time.time() + 120
    while time.time() < deadline:
        if client.get(f"/api/v1/training/jobs/{jid2}").json()["status"] in (
            "completed", "failed",
        ):
            break
        time.sleep(1)
    empty = client.get(f"/api/v1/training/jobs/{jid2}/checkpoints").json()
    assert empty == {"job_id": jid2, "checkpoint_dir": None, "steps": [],
                     "latest": None, "stable": None}


def test_text_generation_and_job_delete(client, tmp_path_factory):
    tokenizers = __import__("tokenizers")
    d = tmp_path_factory.mktemp("toktxt")
    corpus = d / "c.txt"
    corpus.write_text("\n".join(["the quick brown fox jumps over the lazy dog"] * 100))
    tok = tokenizers.Tokenizer(tokenizers.models.BPE(unk_token="[UNK]"))
    tok.pre_tokenizer = tokenizers.pre_tokenizers.Whitespace()
    tok.train([str(corpus)], tokenizers.trainers.BpeTrainer(
        vocab_size=120, special_tokens=["[UNK]"]))
    tok_path = str(d / "tok.json")
    tok.save(tok_path)

    r = client.post(
        "/api/v1/training/launch",
        json={
            "model_name": "gpt-tiny",
            "mesh": {"data": 2, "fsdp": 4},
            "micro_batch_size": 1,
            "seq_len": 32,
            "precision": "fp32",
            "total_steps": 2,
            "activation_checkpointing": False,
            "warmup_steps": 1,
            "dry_run": False,
        },
    )
    job_id = r.json()["job_id"]
    deadline = time.time() + 240
    while time.time() < deadline:
        if client.get(f"/api/v1/training/jobs/{job_id}").json()["status"] in (
            "completed", "failed",
        ):
            break
        time.sleep(1)

    # Text in → text out (unequal prompt lengths are fine: row-wise decode).
    g = client.post(
        f"/api/v1/training/jobs/{job_id}/generate",
        json={"prompt_text": ["the quick brown", "lazy dog"],
              "tokenizer_json": tok_path, "max_new_tokens": 4},
    )
    assert g.status_code == 200, g.text
    body = g.json()
    assert len(body["new_text"]) == 2
    assert all(isinstance(t, str) for t in body["new_text"])
    # Exactly one prompt form is required.
    assert client.post(
        f"/api/v1/training/jobs/{job_id}/generate",
        json={"prompt_text": ["x"], "prompt_tokens": [[1]],
              "tokenizer_json": tok_path},
    ).status_code == 422
    assert client.post(
        f"/api/v1/training/jobs/{job_id}/generate", json={"prompt_text": ["x"]}
    ).status_code == 422
    # Out-of-vocab token ids are a 422, not a silent clip.
    assert client.post(
        f"/api/v1/training/jobs/{job_id}/generate",
        json={"prompt_tokens": [[100000]]},
    ).status_code == 422

    # Terminal job can be deleted; then it is gone.
    assert client.delete(f"/api/v1/training/jobs/{job_id}").status_code == 200
    assert client.get(f"/api/v1/training/jobs/{job_id}").status_code == 404
    assert client.delete(f"/api/v1/training/jobs/{job_id}").status_code == 404


def test_prometheus_metrics_endpoint(client):
    """/metrics exports both telemetry planes in Prometheus text format."""
    # Admission cap is 1: wait for earlier tests' jobs to finish first.
    deadline = time.time() + 240
    while time.time() < deadline:
        jobs = client.get("/api/v1/training/jobs").json()["jobs"]
        if all(j["status"] in ("completed", "failed", "stopped") for j in jobs):
            break
        time.sleep(1)
    # Launch a tiny job so the training plane has something to export.
    r = client.post("/api/v1/training/launch", json={
        "model_name": "gpt-tiny", "mesh": {"data": 2, "fsdp": 4},
        "micro_batch_size": 1, "seq_len": 32, "precision": "fp32",
        "total_steps": 3, "warmup_steps": 1, "dry_run": False,
    })
    assert r.status_code == 200 and r.json()["status"] == "launched", r.text
    job_id = r.json()["job_id"]
    deadline = time.time() + 240  # fresh budget for this job's completion
    body = {}
    while time.time() < deadline:
        body = client.get(f"/api/v1/training/jobs/{job_id}").json()
        if body.get("status") in ("completed", "failed"):
            break
        time.sleep(1)
    assert body.get("status") == "completed", body

    m = client.get("/metrics")
    assert m.status_code == 200
    assert m.headers["content-type"].startswith("text/plain")
    body = m.text
    assert "tpu_engine_fleet_up 1" in body
    assert "tpu_engine_fleet_devices_total" in body
    assert f'tpu_engine_job_step{{job_id="{job_id}",model="gpt-tiny"}}' in body
    assert f'tpu_engine_job_info{{job_id="{job_id}",model="gpt-tiny",status=' in body
    # External HTTP-ingest jobs are exported too (second namespace).
    r2 = client.post("/api/v1/monitoring/ingest/single", json={
        "job_id": "ext-scrape-job", "step": 1, "loss": 2.5,
        "learning_rate": 1e-4,
    })
    assert r2.status_code == 200, r2.text
    body = client.get("/metrics").text
    assert 'tpu_engine_job_loss{job_id="ext-scrape-job",model="external"} 2.5' in body
    # Serving plane: down by default; up with slot/throughput gauges once
    # a server runs (round-4 hygiene: chunk depth + occupancy scrapeable).
    assert "tpu_engine_serving_up 0" in body
    r3 = client.post("/api/v1/serving/start",
                     json={"model_name": "gpt-tiny", "max_slots": 2,
                           "max_len": 64, "kv_cache": "int8",
                           "prefix_cache_tokens": 256})
    assert r3.status_code == 200, r3.text
    try:
        body = client.get("/metrics").text
        assert "tpu_engine_serving_up 1" in body
        assert "tpu_engine_serving_slots 2" in body
        assert "tpu_engine_serving_chunk_steps" in body
        assert "tpu_engine_serving_sharded 0" in body
        assert "tpu_engine_serving_kv_quant 1" in body
        assert "tpu_engine_serving_prefix_cache_entries 0" in body
        assert "tpu_engine_serving_prefix_cache_misses_total 0" in body
    finally:
        client.post("/api/v1/serving/stop")
    # Proper exposition format: versioned content type, HELP/TYPE per
    # family preceding its samples (round-1 advisor finding).
    assert "version=0.0.4" in m.headers["content-type"]
    assert "# HELP tpu_engine_fleet_up" in body
    assert "# TYPE tpu_engine_fleet_up gauge" in body
    seen_families = set()
    for line in body.strip().splitlines():
        if line.startswith("# TYPE "):
            seen_families.add(line.split()[2])
            continue
        if line.startswith("#"):
            continue
        name = line.split("{")[0].split(" ")[0]
        assert line.startswith("tpu_engine_"), line
        assert name in seen_families, f"samples before TYPE for {name}"
        float(line.rsplit(" ", 1)[1])


def test_speculative_generate_over_http(client, tmp_path_factory):
    """End-to-end over HTTP only: train a job, export its weights as an HF
    checkpoint, use that export as the speculative draft (a perfect draft),
    and check the output equals plain greedy generation in the minimum
    number of target forward passes."""
    # Admission cap is 1: wait for jobs from earlier tests to reach a
    # terminal state before launching.
    deadline = time.time() + 240
    while time.time() < deadline:
        jobs = client.get("/api/v1/training/jobs").json()["jobs"]
        if all(j["status"] in ("completed", "failed", "stopped") for j in jobs):
            break
        time.sleep(1)
    r = client.post("/api/v1/training/launch", json={
        "model_name": "gpt-tiny", "mesh": {"data": 2, "fsdp": 4},
        "micro_batch_size": 1, "seq_len": 32, "precision": "fp32",
        "total_steps": 3, "warmup_steps": 1, "dry_run": False,
    })
    assert r.status_code == 200 and r.json()["status"] == "launched", r.text
    job_id = r.json()["job_id"]
    deadline = time.time() + 240
    body = {}
    while time.time() < deadline:
        body = client.get(f"/api/v1/training/jobs/{job_id}").json()
        if body.get("status") in ("completed", "failed"):
            break
        time.sleep(1)
    assert body.get("status") == "completed", body

    out_dir = str(tmp_path_factory.mktemp("spec-draft"))
    r = client.post(f"/api/v1/training/jobs/{job_id}/export",
                    json={"out_dir": out_dir}, timeout=120)
    assert r.status_code == 200, r.text

    prompt = [[3, 1, 4, 1, 5]]
    greedy = client.post(f"/api/v1/training/jobs/{job_id}/generate", json={
        "prompt_tokens": prompt, "max_new_tokens": 14,
    }, timeout=180)
    assert greedy.status_code == 200, greedy.text

    spec = client.post(f"/api/v1/training/jobs/{job_id}/generate", json={
        "prompt_tokens": prompt, "max_new_tokens": 14,
        "draft_hf_checkpoint": out_dir, "gamma": 4,
    }, timeout=300)
    assert spec.status_code == 200, spec.text
    body = spec.json()
    assert body["speculative"] is True
    assert body["tokens"] == greedy.json()["tokens"]
    assert body["target_forward_passes"] == 3  # ceil(14 / (gamma+1))

    # Sampling params are rejected for the speculative path.
    bad = client.post(f"/api/v1/training/jobs/{job_id}/generate", json={
        "prompt_tokens": prompt, "max_new_tokens": 4,
        "draft_hf_checkpoint": out_dir, "temperature": 0.7,
    })
    assert bad.status_code == 422


# Compile-heavy module: excluded from the fast core run (pytest -m "not slow").
pytestmark = pytest.mark.slow


def test_serving_lifecycle_over_http(client):
    # Exactly one of job_id / model_name.
    r = client.post("/api/v1/serving/start", json={})
    assert r.status_code == 422
    # No instance yet → submit is a 409.
    assert client.post("/api/v1/serving/submit",
                       json={"prompt": [1, 2]}).status_code == 409

    r = client.post("/api/v1/serving/start",
                    json={"model_name": "gpt-tiny", "max_slots": 2,
                          "max_len": 64})
    assert r.status_code == 200 and r.json()["started"]
    # Double start rejected.
    assert client.post("/api/v1/serving/start",
                       json={"model_name": "gpt-tiny"}).status_code == 409
    try:
        rid = client.post(
            "/api/v1/serving/submit",
            json={"prompt": [3, 4, 5], "max_new_tokens": 4},
        ).json()["request_id"]
        deadline = time.time() + 120
        while time.time() < deadline:
            body = client.get(f"/api/v1/serving/result/{rid}").json()
            if body["status"] == "done":
                break
            time.sleep(0.2)
        assert body["status"] == "done"
        assert len(body["tokens"]) == 4
        st = client.get("/api/v1/serving/stats").json()
        assert st["tokens_generated"] >= 4
        assert client.get("/api/v1/serving/result/9999").status_code == 404
    finally:
        assert client.post("/api/v1/serving/stop").json()["stopped"]
    assert client.post("/api/v1/serving/stop").status_code == 404


def test_serving_stream_sse(client):
    """Token streaming over HTTP (round-4 verdict weakness 4): SSE events
    deliver tokens incrementally, and their concatenation equals the
    polled result exactly."""
    import json

    r = client.post("/api/v1/serving/start",
                    json={"model_name": "gpt-tiny", "max_slots": 1,
                          "max_len": 64, "decode_chunk_steps": 2})
    assert r.status_code == 200, r.text
    try:
        assert client.get("/api/v1/serving/stream/777").status_code == 404
        rid = client.post(
            "/api/v1/serving/submit",
            json={"prompt": [3, 4, 5], "max_new_tokens": 10},
        ).json()["request_id"]
        events = []
        with client.stream("GET", f"/api/v1/serving/stream/{rid}",
                           timeout=120) as resp:
            assert resp.status_code == 200
            assert resp.headers["content-type"].startswith("text/event-stream")
            for line in resp.iter_lines():
                if line.startswith("data: "):
                    events.append(json.loads(line[len("data: "):]))
        # Incremental delivery: more than one token-bearing event, each
        # picking up exactly where the previous left off.
        token_events = [e for e in events if e["tokens"]]
        assert len(token_events) >= 2, events
        concat = []
        for e in events:
            assert e["offset"] == len(concat)
            concat.extend(e["tokens"])
        final = events[-1]
        assert final["status"] == "done"
        assert final["all_tokens"] == concat and len(concat) == 10
        assert "ttft_ms" in final
        polled = client.get(f"/api/v1/serving/result/{rid}").json()
        assert polled["tokens"] == concat
    finally:
        client.post("/api/v1/serving/stop")


def test_serving_from_sharded_trained_job(client):
    """Round-4 headline over HTTP: train on an fsdp×tp mesh, then serve
    from the job_id — the batcher inherits the job's mesh and TP/FSDP
    param shardings, and streams match the job's own generate endpoint
    (which decodes the same trained weights)."""
    r = client.post(
        "/api/v1/training/launch",
        json={
            "model_name": "gpt-tiny",
            "mesh": {"fsdp": 2, "model": 4},
            "micro_batch_size": 2,
            "seq_len": 32,
            "precision": "fp32",
            "total_steps": 2,
            "activation_checkpointing": False,
            "warmup_steps": 1,
            "dry_run": False,
        },
    )
    assert r.status_code == 200, r.text
    job_id = r.json()["job_id"]
    deadline = time.time() + 240
    while time.time() < deadline:
        if client.get(f"/api/v1/training/jobs/{job_id}").json()["status"] in (
            "completed", "failed",
        ):
            break
        time.sleep(1)
    assert client.get(
        f"/api/v1/training/jobs/{job_id}"
    ).json()["status"] == "completed"

    prompt = [5, 6, 7, 8]
    ref = client.post(
        f"/api/v1/training/jobs/{job_id}/generate",
        json={"prompt_tokens": [prompt], "max_new_tokens": 6},
    ).json()["new_tokens"][0]

    r = client.post("/api/v1/serving/start",
                    json={"job_id": job_id, "max_slots": 2, "max_len": 64})
    assert r.status_code == 200, r.text
    assert r.json()["sharded"] is True
    try:
        rid = client.post(
            "/api/v1/serving/submit",
            json={"prompt": prompt, "max_new_tokens": 6},
        ).json()["request_id"]
        deadline = time.time() + 120
        while time.time() < deadline:
            body = client.get(f"/api/v1/serving/result/{rid}").json()
            if body["status"] in ("done", "failed"):
                break
            time.sleep(0.2)
        assert body["status"] == "done", body
        assert body["tokens"] == ref
        assert client.get("/api/v1/serving/stats").json()["sharded"] is True
    finally:
        client.post("/api/v1/serving/stop")


def test_serving_quantized_over_http(client):
    """quantize="int8" serves a weight-only-quantized tree (round 4):
    the started instance reports the mode, decodes deterministically, and
    the sharded variant composes (quantized pspec mirror on the mesh)."""
    r = client.post("/api/v1/serving/start",
                    json={"model_name": "gpt-tiny", "max_slots": 2,
                          "max_len": 64, "quantize": "int8",
                          "kv_cache": "int8"})
    assert r.status_code == 200, r.text
    assert r.json()["quantize"] == "int8"
    assert client.get("/api/v1/serving/stats").json()["kv_quant"] is True
    try:
        rid = client.post(
            "/api/v1/serving/submit",
            json={"prompt": [3, 4, 5], "max_new_tokens": 4},
        ).json()["request_id"]
        deadline = time.time() + 120
        while time.time() < deadline:
            body = client.get(f"/api/v1/serving/result/{rid}").json()
            if body["status"] in ("done", "failed"):
                break
            time.sleep(0.2)
        assert body["status"] == "done", body
        first = body["tokens"]
        assert len(first) == 4
    finally:
        client.post("/api/v1/serving/stop")

    # Sharded + quantized: same stream (weight values identical; layout
    # must not change the tokens).
    r = client.post("/api/v1/serving/start",
                    json={"model_name": "gpt-tiny", "max_slots": 2,
                          "max_len": 64, "quantize": "int8",
                          "tensor_parallel": 4, "fsdp": 2})
    assert r.status_code == 200, r.text
    assert r.json()["sharded"] is True
    try:
        rid = client.post(
            "/api/v1/serving/submit",
            json={"prompt": [3, 4, 5], "max_new_tokens": 4},
        ).json()["request_id"]
        deadline = time.time() + 120
        while time.time() < deadline:
            body = client.get(f"/api/v1/serving/result/{rid}").json()
            if body["status"] in ("done", "failed"):
                break
            time.sleep(0.2)
        assert body["status"] == "done", body
        assert body["tokens"] == first
    finally:
        client.post("/api/v1/serving/stop")

    # Unknown mode rejected by the schema.
    assert client.post(
        "/api/v1/serving/start",
        json={"model_name": "gpt-tiny", "quantize": "int4"},
    ).status_code == 422


def test_quantized_snapshot_export_and_serve(client, tmp_path):
    """Round 4: train -> export {"format": "int8"} -> serve from the
    self-describing snapshot; the served stream matches generate() on the
    loaded snapshot tree."""
    r = client.post(
        "/api/v1/training/launch",
        json={
            "model_name": "gpt-tiny", "micro_batch_size": 2, "seq_len": 32,
            "precision": "fp32", "total_steps": 2, "warmup_steps": 1,
            "activation_checkpointing": False, "dry_run": False,
        },
    )
    assert r.status_code == 200, r.text
    job_id = r.json()["job_id"]
    deadline = time.time() + 240
    while time.time() < deadline:
        if client.get(f"/api/v1/training/jobs/{job_id}").json()["status"] in (
            "completed", "failed",
        ):
            break
        time.sleep(1)

    snap = str(tmp_path / "snap")
    r = client.post(f"/api/v1/training/jobs/{job_id}/export",
                    json={"out_dir": snap, "format": "int8"})
    assert r.status_code == 200, r.text
    assert r.json()["format"] == "int8"

    # Serving from the snapshot needs no model_name and no quantize flag.
    assert client.post("/api/v1/serving/start",
                       json={"snapshot_dir": snap, "quantize": "int8"}
                       ).status_code == 422
    assert client.post("/api/v1/serving/start",
                       json={"snapshot_dir": str(tmp_path / "nope")}
                       ).status_code == 404
    r = client.post("/api/v1/serving/start",
                    json={"snapshot_dir": snap, "max_slots": 2,
                          "max_len": 64})
    assert r.status_code == 200, r.text
    assert r.json()["model"] == "gpt-tiny"
    try:
        prompt = [5, 6, 7, 8]
        rid = client.post(
            "/api/v1/serving/submit",
            json={"prompt": prompt, "max_new_tokens": 6},
        ).json()["request_id"]
        deadline = time.time() + 120
        while time.time() < deadline:
            body = client.get(f"/api/v1/serving/result/{rid}").json()
            if body["status"] in ("done", "failed"):
                break
            time.sleep(0.2)
        assert body["status"] == "done", body

        import jax.numpy as jnp
        import numpy as np

        from tpu_engine.generate import generate
        from tpu_engine.quant import load_quantized, load_quantized_config

        cfg = load_quantized_config(snap)
        tree = load_quantized(snap)
        ref = generate(tree, jnp.asarray([prompt], jnp.int32), cfg,
                       max_new_tokens=6)
        assert body["tokens"] == np.asarray(ref)[0, len(prompt):].tolist()
    finally:
        client.post("/api/v1/serving/stop")


# -- fault injection + recovery ---------------------------------------------


def test_faults_inject_status_heal_clear(client):
    from tpu_engine import faults as faults_mod

    try:
        # Nothing armed yet.
        assert client.get("/api/v1/faults").json()["armed"] is False
        # Neither explicit specs nor a random plan → 400.
        assert client.post("/api/v1/faults/inject", json={}).status_code == 400
        # A chip fault without a device_index → 400 from spec validation.
        r = client.post("/api/v1/faults/inject", json={
            "faults": [{"kind": "chip-unhealthy", "at_step": 3}],
        })
        assert r.status_code == 400
        # Valid plan arms the process-wide injector.
        r = client.post("/api/v1/faults/inject", json={
            "faults": [
                {"kind": "chip-unhealthy", "at_step": 3, "device_index": 5},
                {"kind": "host-slow", "at_step": 2, "slow_s": 1.5},
            ],
            "seed": 11,
        })
        assert r.status_code == 202, r.text
        body = r.json()
        assert body["armed"] is True and len(body["specs"]) == 2
        assert faults_mod.get_active() is not None
        # Status reflects the armed plan; heal is recorded.
        assert client.get("/api/v1/faults").json()["armed"] is True
        r = client.post("/api/v1/faults/heal", json={"device_index": 5})
        assert r.status_code == 200
        assert r.json()["healed_faults"] == 1
        # Clear disarms.
        assert client.delete("/api/v1/faults").json()["was_armed"] is True
        assert faults_mod.get_active() is None
        assert client.post(
            "/api/v1/faults/heal", json={"device_index": 5}
        ).status_code == 409
    finally:
        faults_mod.clear_active()


def test_recovery_endpoint_and_fault_metrics(client):
    from tpu_engine import faults as faults_mod

    try:
        r = client.get("/api/v1/recovery")
        assert r.status_code == 200
        body = r.json()
        for key in ("self_heal_requeues_total", "elastic_shrinks_total",
                    "grow_backs_total", "running_shrunk"):
            assert key in body["scheduler"]
        assert body["fault_injection"]["armed"] is False
        # Arm a plan: the Prometheus plane picks it up.
        client.post("/api/v1/faults/inject", json={
            "faults": [{"kind": "telemetry-nan", "at_step": 1,
                        "device_index": 0}],
        })
        text = client.get("/metrics").text
        assert "tpu_engine_fault_injection_armed 1.0" in text
        assert "tpu_engine_fault_specs_active 1.0" in text
        assert "tpu_engine_recovery_self_heal_requeues_total" in text
        assert "tpu_engine_recovery_running_shrunk_jobs" in text
    finally:
        faults_mod.clear_active()


def test_scheduler_plan_endpoint(client):
    """POST /api/v1/scheduler/plan: the ranked layout table without
    enqueueing — enumerate → prune → HBM-filter → rank over the live
    fleet, plus the planner's counter plane on /metrics."""
    r = client.post("/api/v1/scheduler/plan", json={
        "model_name": "gpt-tiny", "mesh": {"data": 2, "fsdp": 4},
        "micro_batch_size": 2, "gradient_accumulation_steps": 2,
        "seq_len": 64, "top_k": 5,
    })
    assert r.status_code == 200, r.text
    body = r.json()
    assert body["gang"] == 8 and body["feasible"] > 0
    rows = body["ranked_plans"]
    assert rows and rows[0]["rank"] == 1
    # Ranked ascending by predicted step time; every row is a full layout.
    times = [row["predicted_step_time_s"] for row in rows]
    assert times == sorted(times)
    assert {"mesh", "sharding_stage", "pipeline_schedule"} <= rows[0].keys()
    assert body["pruned_count"] > 0 and "planner_stats" in body
    # Unknown model → structured 422, same reason the scheduler uses.
    r = client.post("/api/v1/scheduler/plan", json={"model_name": "nope-9b"})
    assert r.status_code == 422
    assert "no_estimate:nope-9b" in r.json()["detail"]
    # The planner counter plane is scrapeable.
    text = client.get("/metrics").text
    assert "tpu_engine_placement_plans_evaluated_total" in text
    assert "tpu_engine_placement_no_estimate_refusals_total" in text


def test_scheduler_submit_auto_placement(client):
    """placement="auto" hands the mesh to the planner; unknown models are
    refused with the structured no_estimate reason."""
    r = client.post("/api/v1/scheduler/submit", json={
        "model_name": "nope-9b", "placement": "auto",
    })
    assert r.status_code == 422
    assert "no_estimate:nope-9b" in r.json()["detail"]
    r = client.post("/api/v1/scheduler/submit", json={
        "model_name": "gpt-tiny", "mesh": {"data": -1, "fsdp": 2},
        "micro_batch_size": 1, "seq_len": 32, "precision": "fp32",
        "total_steps": 2, "max_steps": 2, "warmup_steps": 1,
        "placement": "auto",
    })
    assert r.status_code == 202, r.text
    body = r.json()
    assert body["auto_place"] is True
    sub_id = body["submission_id"]
    deadline = time.time() + 240
    while time.time() < deadline:
        body = client.get(f"/api/v1/scheduler/submissions/{sub_id}").json()
        if body["state"] in ("completed", "failed"):
            break
        time.sleep(1)
    assert body["state"] == "completed", body
    plan = body["placement_plan"]
    assert plan and plan["label"] and plan["feasible"] > 0
    assert body["predicted_step_time_s"] > 0
    text = client.get("/metrics").text
    assert "tpu_engine_placement_auto_admissions_total 1" in text
