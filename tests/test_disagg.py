"""Disaggregated prefill/decode serving: KV handoff, pools, chaos.

Three tiers in one file:

- **Real-engine parity** — a gpt-tiny prefill engine hands its KV to a
  separate decode engine; the stitched stream must be token-for-token
  identical to running the whole request on one replica (fp wire), and
  within a one-token bound for the int8 wire. This is the measured
  int8-KV-on-a-real-engine result the ROADMAP asked for.
- **Wire/cache unit properties** — quantization round-trip bounds, lane
  bucketing, geometry/invariant validation, per-pool HBM admission.
- **Fleet machinery on stubs** — the :class:`DisaggServingFleet` phase
  machine over the real scheduler, including a chaos round trip that
  preempts the decode replica (through the ``faults.py`` seam) while it
  holds handed-off KV and asserts the request re-prefills and completes.
"""

import dataclasses
import threading
import time
import types

import numpy as np
import pytest

from tests.test_serving_fleet import StubTrainJob, mock_fleet_fn, wait_until
from tpu_engine.disagg import (
    DisaggServingFleet,
    KVHandoff,
    _np_quantize,
    extract_slot_kv,
    handoff_to_cache,
    rebucket_handoff,
)
from tpu_engine.faults import FaultInjector, FaultKind, FaultPlan, FaultSpec
from tpu_engine.hbm_estimate import estimate_serving_hbm
from tpu_engine.placement import plan_serving_pool
from tpu_engine.scheduler import FleetScheduler, SubmissionState
from tpu_engine.serving_fleet import (
    AutoscalerConfig,
    ReplicaAutoscaler,
    ServingFleet,
    ServingReplicaSpec,
)


@pytest.fixture
def sched_factory():
    created = []

    def make(**kw):
        jobs = []

        def factory(sub):
            job = StubTrainJob(sub)
            jobs.append(job)
            return job

        kw.setdefault("job_factory", factory)
        kw.setdefault("poll_interval_s", 0.01)
        kw.setdefault("grow_back_cooldown_s", 0.0)
        s = FleetScheduler(**kw)
        s._stub_jobs = jobs
        created.append(s)
        return s

    yield make
    for s in created:
        for j in getattr(s, "_stub_jobs", []):
            j.finish()
        s.shutdown()


def _one(autoscaler_n=1):
    return ReplicaAutoscaler(
        AutoscalerConfig(min_replicas=autoscaler_n, max_replicas=autoscaler_n)
    )


# ---------------------------------------------------------------------------
# Real-engine KV handoff parity (the measured result)
# ---------------------------------------------------------------------------

PROMPT = [11, 7, 23, 42, 5]
MAX_NEW = 8


def tiny_spec(**kw):
    base = dict(
        model_name="gpt-tiny", max_slots=2, max_len=96, prefill_chunk=16
    )
    base.update(kw)
    return ServingReplicaSpec(**base)


def drive(engine, rid, steps=400):
    for _ in range(steps):
        if engine.result(rid)["status"] == "done":
            break
        engine.step()
    out = engine.result(rid)
    assert out["status"] == "done", out
    return out


def extract(engine, rid, quantize=False, steps=50):
    engine.request_handoff(rid, quantize=quantize)
    for _ in range(steps):
        engine.step()
        h = engine.take_handoff(rid)
        if h is not None:
            return h
    raise AssertionError("engine never serviced the handoff order")


@pytest.fixture(scope="module")
def engines():
    """Shared gpt-tiny engines (same seed → identical weights): a prefill
    source, an fp decode destination, and a kv_quant decode destination."""
    from tpu_engine.serving_fleet import build_replica_engine

    return {
        "prefill": build_replica_engine(tiny_spec()),
        "decode": build_replica_engine(tiny_spec()),
        "decode_kvq": build_replica_engine(tiny_spec(kv_quant=True)),
    }


@pytest.fixture(scope="module")
def baseline_tokens(engines):
    """The whole request on one replica — the parity reference."""
    out = drive(
        engines["decode"], engines["decode"].submit(PROMPT, MAX_NEW)
    )
    assert len(out["tokens"]) == MAX_NEW
    return list(out["tokens"])


def test_fp_handoff_token_identical(engines, baseline_tokens):
    pre, dec = engines["prefill"], engines["decode"]
    out = drive(pre, pre.submit(PROMPT, max_new_tokens=1, hold_kv=True))
    assert len(out["tokens"]) == 1
    # The prefill pool's first token IS the TTFT token — and must agree
    # with the unified baseline before any handoff happens.
    assert out["tokens"][0] == baseline_tokens[0]
    assert pre.stats()["held_slots"] == 1

    h = extract(pre, out["id"])
    assert not h.quantized
    # Resident-KV invariant: every history token except the last emitted.
    assert h.length == len(PROMPT) + 1 - 1 == len(PROMPT)
    assert h.last_token == out["tokens"][0]
    assert pre.stats()["held_slots"] == 0
    assert pre.stats()["handoffs_out"] >= 1

    got = drive(dec, dec.submit_prefilled(h, max_new_tokens=MAX_NEW - 1))
    assert [out["tokens"][0], *got["tokens"]] == baseline_tokens
    assert dec.stats()["handoffs_in"] >= 1


def test_int8_wire_parity_within_bound(engines, baseline_tokens):
    pre, dec = engines["prefill"], engines["decode"]
    out = drive(pre, pre.submit(PROMPT, max_new_tokens=1, hold_kv=True))
    h = extract(pre, out["id"], quantize=True)
    assert h.quantized and h.dtype == "int8"
    assert h.k.dtype == np.int8 and h.k_scale.dtype == np.float32
    # One fp32 scale per (layer, lane, kv-head) — the kv_quant pool layout.
    assert h.k_scale.shape == (*h.k.shape[:-1], 1)
    # int8 codes + scales vs the fp32 wire: better than half the bytes.
    fp_bytes = 2 * h.k.size * 4
    assert h.wire_bytes() < 0.5 * fp_bytes

    got = drive(dec, dec.submit_prefilled(h, max_new_tokens=MAX_NEW - 1))
    stitched = [out["tokens"][0], *got["tokens"]]
    # Documented bound: absmax-per-head int8 KV may flip at most one
    # argmax over an 8-token greedy stream (empirically zero on gpt-tiny).
    mismatches = sum(a != b for a, b in zip(stitched, baseline_tokens))
    assert len(stitched) == len(baseline_tokens)
    assert mismatches <= 1


def test_int8_wire_into_kv_quant_pool(engines):
    """int8 codes ingest byte-for-byte into an int8 slot pool."""
    pre, dec = engines["prefill"], engines["decode_kvq"]
    out = drive(pre, pre.submit(PROMPT, max_new_tokens=1, hold_kv=True))
    h = extract(pre, out["id"], quantize=True)
    got = drive(dec, dec.submit_prefilled(h, max_new_tokens=4))
    assert len(got["tokens"]) == 4


def test_fp_wire_into_kv_quant_pool(engines):
    """fp wire → int8 pool: the insert quantizes host-side on ingestion."""
    pre, dec = engines["prefill"], engines["decode_kvq"]
    out = drive(pre, pre.submit(PROMPT, max_new_tokens=1, hold_kv=True))
    h = extract(pre, out["id"])
    assert not h.quantized
    got = drive(dec, dec.submit_prefilled(h, max_new_tokens=4))
    assert len(got["tokens"]) == 4


def test_quantized_pool_ships_codes_directly(engines):
    """Extraction from a kv_quant pool is always int8 — dequantizing on
    the wire would add error AND bytes — and int8 → fp ingestion works."""
    pre, dec = engines["decode_kvq"], engines["decode"]
    out = drive(pre, pre.submit(PROMPT, max_new_tokens=1, hold_kv=True))
    h = extract(pre, out["id"])  # quantize NOT requested
    assert h.quantized
    got = drive(dec, dec.submit_prefilled(h, max_new_tokens=4))
    assert len(got["tokens"]) == 4


def test_submit_prefilled_validates_wire(engines):
    pre, dec = engines["prefill"], engines["decode"]
    out = drive(pre, pre.submit(PROMPT, max_new_tokens=1, hold_kv=True))
    h = extract(pre, out["id"])
    with pytest.raises(ValueError, match="inconsistent"):
        dec.submit_prefilled(dataclasses.replace(h, length=h.length + 1))
    bad_geom = dataclasses.replace(h, head_dim=h.head_dim + 1)
    with pytest.raises(ValueError):
        dec.submit_prefilled(bad_geom)


# ---------------------------------------------------------------------------
# Wire/cache unit properties (no engine)
# ---------------------------------------------------------------------------


def _fake_handoff(L=2, T=5, KV=2, HD=4, quantized=False, seed=0):
    rng = np.random.default_rng(seed)
    k = rng.standard_normal((L, T, KV, HD)).astype(np.float32)
    v = rng.standard_normal((L, T, KV, HD)).astype(np.float32)
    kw = dict(
        prompt=[1, 2, 3, 4, 5], emitted=[9], length=T, n_layers=L,
        n_kv_heads=KV, head_dim=HD,
    )
    if quantized:
        qk, sk = _np_quantize(k)
        qv, sv = _np_quantize(v)
        return KVHandoff(dtype="int8", quantized=True, k=qk, v=qv,
                         k_scale=sk, v_scale=sv, **kw), k, v
    return KVHandoff(dtype="float32", quantized=False, k=k, v=v, **kw), k, v


def test_np_quantize_roundtrip_bound():
    rng = np.random.default_rng(3)
    a = (rng.standard_normal((4, 16)) * 10).astype(np.float32)
    q, scale = _np_quantize(a)
    assert q.dtype == np.int8 and scale.shape == (4, 1)
    # Symmetric absmax rounding: worst-case error is half a code step.
    assert np.all(np.abs(a - q.astype(np.float32) * scale)
                  <= scale / 2 + 1e-6)


def test_handoff_to_cache_buckets_and_pads():
    import jax.numpy as jnp

    h, k, _v = _fake_handoff()
    cache = handoff_to_cache(
        h, dtype=jnp.float32, kv_quant=False, chunk=4, max_lanes=16
    )
    # T=5 buckets up to the next chunk multiple (8), not max_lanes.
    assert cache.k.shape == (2, 1, 8, 2, 4)
    assert int(cache.length) == 5 and not cache.ring
    np.testing.assert_allclose(np.asarray(cache.k[:, 0, :5]), k, rtol=1e-6)
    assert np.all(np.asarray(cache.k[:, 0, 5:]) == 0)  # padding lanes
    assert cache.k_scale is None


def test_handoff_to_cache_quantizes_fp_wire_for_int8_pool():
    import jax.numpy as jnp

    h, k, _v = _fake_handoff()
    cache = handoff_to_cache(
        h, dtype=jnp.float32, kv_quant=True, chunk=8, max_lanes=8
    )
    assert cache.k.dtype == jnp.int8
    assert cache.k_scale is not None
    deq = (np.asarray(cache.k[:, 0, :5], dtype=np.float32)
           * np.asarray(cache.k_scale[:, 0, :5]))
    assert np.max(np.abs(deq - k)) <= np.max(np.abs(k)) / 127 + 1e-6


def test_handoff_to_cache_dequantizes_int8_wire_for_fp_pool():
    import jax.numpy as jnp

    h, k, _v = _fake_handoff(quantized=True)
    cache = handoff_to_cache(
        h, dtype=jnp.float32, kv_quant=False, chunk=8, max_lanes=8
    )
    assert cache.k.dtype == jnp.float32
    got = np.asarray(cache.k[:, 0, :5])
    assert np.max(np.abs(got - k)) <= np.max(np.abs(k)) / 127 + 1e-6


def test_handoff_to_cache_rejects_overlong_payload():
    import jax.numpy as jnp

    h, _k, _v = _fake_handoff(T=5)
    with pytest.raises(ValueError, match="exceeds destination pool lanes"):
        handoff_to_cache(h, dtype=jnp.float32, kv_quant=False,
                         chunk=4, max_lanes=4)


def _quant_bound(a):
    return np.max(np.abs(a)) / 127 + 1e-6


def test_rebucket_fp_wire_fp_pool_unequal_geometry():
    # chunk 4/16 lanes → chunk 7/21 lanes: values survive exactly.
    h, k, v = _fake_handoff(T=5)
    out = rebucket_handoff(h, chunk=7, max_lanes=21, kv_quant=False)
    assert out.dtype == "float32" and not out.quantized
    assert out.length == h.length
    assert (out.prompt, out.emitted) == (h.prompt, h.emitted)
    np.testing.assert_allclose(out.k, k, rtol=1e-6)
    np.testing.assert_allclose(out.v, v, rtol=1e-6)


def test_rebucket_fp_wire_int8_pool_unequal_geometry():
    # An fp wire landing on a kv_quant pool ships the pool's own codes.
    h, k, v = _fake_handoff(T=6)
    out = rebucket_handoff(h, chunk=4, max_lanes=12, kv_quant=True)
    assert out.quantized and out.k.dtype == np.int8
    assert out.k_scale is not None
    deq_k = out.k.astype(np.float32) * out.k_scale
    deq_v = out.v.astype(np.float32) * out.v_scale
    assert np.max(np.abs(deq_k - k)) <= _quant_bound(k)
    assert np.max(np.abs(deq_v - v)) <= _quant_bound(v)


def test_rebucket_int8_wire_fp_pool_unequal_geometry():
    # int8 wire dequantizes into an fp pool within the one-step bound.
    h, k, v = _fake_handoff(T=5, quantized=True)
    out = rebucket_handoff(h, chunk=3, max_lanes=9, kv_quant=False)
    assert out.dtype == "float32" and not out.quantized
    assert np.max(np.abs(out.k - k)) <= _quant_bound(k)
    assert np.max(np.abs(out.v - v)) <= _quant_bound(v)


def test_rebucket_int8_wire_int8_pool_unequal_geometry():
    # Codes ship straight through the staging cache: byte-identical.
    h, k, v = _fake_handoff(T=5, quantized=True)
    out = rebucket_handoff(h, chunk=8, max_lanes=24, kv_quant=True)
    assert out.quantized and out.k.dtype == np.int8
    np.testing.assert_array_equal(out.k, h.k)
    np.testing.assert_array_equal(out.v, h.v)
    np.testing.assert_allclose(out.k_scale, h.k_scale, rtol=1e-6)
    assert np.max(np.abs(out.k.astype(np.float32) * out.k_scale - k)) \
        <= _quant_bound(k)
    assert np.max(np.abs(out.v.astype(np.float32) * out.v_scale - v)) \
        <= _quant_bound(v)


def test_rebucket_rejects_overlong_payload():
    h, _k, _v = _fake_handoff(T=5)
    with pytest.raises(ValueError, match="exceeds destination pool lanes"):
        rebucket_handoff(h, chunk=4, max_lanes=4, kv_quant=False)


def test_extract_rejects_ring_pools():
    cache = types.SimpleNamespace(ring=True)
    with pytest.raises(ValueError, match="ring"):
        extract_slot_kv(cache, 0, 4, cfg=None, prompt=[1], emitted=[])


def test_kvhandoff_last_token_and_wire_bytes():
    h, _k, _v = _fake_handoff()
    assert h.last_token == 9  # last emitted
    assert h.wire_bytes() == h.k.nbytes + h.v.nbytes
    hq, _k, _v = _fake_handoff(quantized=True)
    assert hq.wire_bytes() == (hq.k.nbytes + hq.v.nbytes
                               + hq.k_scale.nbytes + hq.v_scale.nbytes)
    no_emit = dataclasses.replace(h, emitted=[])
    assert no_emit.last_token == 5  # falls back to the prompt tail


# ---------------------------------------------------------------------------
# Per-pool HBM admission
# ---------------------------------------------------------------------------


def test_prefill_pool_estimate_sizes_kv_to_inflight():
    kw = dict(max_slots=64, max_len=2048)
    uni = estimate_serving_hbm("gpt-125m", **kw)
    pre = estimate_serving_hbm(
        "gpt-125m", pool_role="prefill", inflight_handoffs=4, **kw
    )
    dec = estimate_serving_hbm("gpt-125m", pool_role="decode", **kw)
    # Prefill KV shrinks to the handoff window; decode pays the full pool.
    # abs tolerance: the estimator rounds the reported plane to 4 decimals.
    assert pre.kv_pool_gib == pytest.approx(
        uni.kv_pool_gib * 4 / 64, abs=1e-4
    )
    assert dec.kv_pool_gib == uni.kv_pool_gib
    assert dec.device_total_gib == uni.device_total_gib
    assert "in-flight handoff" in " / ".join(pre.notes)


@pytest.mark.parametrize("slots,inflight", [(8, 2), (16, 16), (4, 32)])
def test_prefill_pool_kv_scaling_property(slots, inflight):
    uni = estimate_serving_hbm("gpt-tiny", max_slots=slots, max_len=256)
    pre = estimate_serving_hbm(
        "gpt-tiny", max_slots=slots, max_len=256,
        pool_role="prefill", inflight_handoffs=inflight,
    )
    eff = min(slots, inflight)
    assert pre.kv_pool_gib == pytest.approx(
        uni.kv_pool_gib * eff / slots, abs=1e-4
    )


def test_estimate_rejects_bad_pool_role():
    with pytest.raises(ValueError, match="pool_role"):
        estimate_serving_hbm("gpt-tiny", 4, 128, pool_role="bogus")


def test_disagg_decode_pool_oversubscription_queues(sched_factory):
    """The decode pool's KV plane is gated per-pool: a decode spec that
    exceeds per-device headroom queues with a structured reason while the
    (handoff-window-sized) prefill pool of the SAME shape admits."""
    big = dict(model_name="gpt-125m", max_slots=64, max_len=8192)
    assert ServingReplicaSpec(**big).estimate().device_total_gib > 9.6
    s = sched_factory(max_concurrent_jobs=4, fleet_fn=mock_fleet_fn)
    fleet = DisaggServingFleet(
        s,
        ServingReplicaSpec(**big, inflight_handoffs=4),
        ServingReplicaSpec(**big),
        prefill_autoscaler=_one(), decode_autoscaler=_one(),
        engine_factory=DisaggStubEngine,
    )
    fleet.start()
    assert wait_until(lambda: len(fleet.prefill.running_replicas()) == 1)
    time.sleep(0.15)
    (dec_sub,) = fleet.decode._replicas.values()
    assert dec_sub.state == SubmissionState.QUEUED
    assert "have that headroom" in dec_sub.last_skip_reason
    (pre_sub,) = fleet.prefill._replicas.values()
    assert pre_sub.estimate.kv_pool_gib < dec_sub.estimate.kv_pool_gib
    fleet.stop()


# ---------------------------------------------------------------------------
# DisaggServingFleet on stub engines (phase machine + chaos)
# ---------------------------------------------------------------------------


class _FakeHandoff:
    """Wire payload stand-in carrying only what the fleet plane reads."""

    def __init__(self, prompt, emitted):
        self.prompt = list(prompt)
        self.emitted = list(emitted)
        self.length = len(self.prompt) + len(self.emitted) - 1
        self.quantized = False

    def wire_bytes(self):
        return 64 * self.length


class DisaggStubEngine:
    """StubEngine plus the disaggregated surface: hold_kv, handoff
    extraction orders, and wire ingestion. Tokens are a deterministic
    function of history length, so a re-prefilled request reproduces the
    same stream — mirroring the real engine's greedy determinism."""

    def __init__(self, spec):
        self.slots = int(spec.max_slots)
        self._reqs = {}
        self._seq = 0
        self._handoffs = {}
        self.handoffs_out = 0
        self.handoffs_in = 0
        self._lock = threading.Lock()

    def submit(self, prompt, max_new_tokens=64, temperature=0.0,
               hold_kv=False):
        with self._lock:
            self._seq += 1
            self._reqs[self._seq] = {
                "prompt": list(prompt), "need": int(max_new_tokens),
                "tokens": [], "first_at": None, "hold_kv": bool(hold_kv),
            }
            return self._seq

    def submit_prefilled(self, handoff, max_new_tokens=64, temperature=0.0):
        history = list(handoff.prompt) + list(handoff.emitted)
        if handoff.length != len(history) - 1:
            raise ValueError("wire payload is inconsistent")
        with self._lock:
            self._seq += 1
            self.handoffs_in += 1
            self._reqs[self._seq] = {
                "prompt": history, "need": int(max_new_tokens),
                "tokens": [], "first_at": time.time(), "hold_kv": False,
            }
            return self._seq

    def step(self):
        out = 0
        with self._lock:
            for r in self._reqs.values():
                if len(r["tokens"]) < r["need"]:
                    r["tokens"].append(len(r["prompt"]) + len(r["tokens"]))
                    if r["first_at"] is None:
                        r["first_at"] = time.time()
                    out += 1
        return out

    def result(self, rid):
        with self._lock:
            r = self._reqs[rid]
            done = len(r["tokens"]) >= r["need"]
            return {
                "status": "done" if done else "running",
                "tokens": list(r["tokens"]),
                "first_token_at": r["first_at"],
            }

    def request_handoff(self, rid, quantize=False):
        with self._lock:
            r = self._reqs[rid]
            if not r["hold_kv"]:
                raise ValueError(f"request {rid} was not submitted hold_kv")
            self._handoffs[rid] = _FakeHandoff(r["prompt"], r["tokens"])

    def take_handoff(self, rid):
        with self._lock:
            h = self._handoffs.pop(rid, None)
            if h is not None:
                self.handoffs_out += 1
            return h

    def stats(self):
        with self._lock:
            active = sum(
                1 for r in self._reqs.values()
                if len(r["tokens"]) < r["need"]
            )
            held = len(self._handoffs)
        return {
            "slots": self.slots, "active_slots": active, "prefilling": 0,
            "queued": 0, "tokens_per_sec_recent": 100.0,
            "held_slots": held, "queued_handoffs": 0,
            "handoffs_out": self.handoffs_out,
            "handoffs_in": self.handoffs_in,
        }


def make_disagg(sched, **kw):
    kw.setdefault("prefill_autoscaler", _one())
    kw.setdefault("decode_autoscaler", _one())
    kw.setdefault("engine_factory", DisaggStubEngine)
    spec = dict(model_name="gpt-tiny", max_slots=4, max_len=128)
    return DisaggServingFleet(
        sched,
        ServingReplicaSpec(**spec, inflight_handoffs=2),
        ServingReplicaSpec(**spec),
        **kw,
    )


def _pools_up(fleet):
    return (len(fleet.prefill.running_replicas()) == 1
            and len(fleet.decode.running_replicas()) == 1)


def test_disagg_fleet_stitches_prefill_and_decode(sched_factory):
    s = sched_factory(max_concurrent_jobs=4, fleet_fn=mock_fleet_fn)
    fleet = make_disagg(s)
    fleet.start()
    assert wait_until(lambda: _pools_up(fleet))
    fids = [fleet.submit_request([i, i + 1, i + 2], max_new_tokens=5)
            for i in range(3)]
    outs = [fleet.wait(f, timeout=10.0) for f in fids]
    for out in outs:
        assert out["status"] == "done"
        # One token off the prefill logits + the decode pool's remainder.
        assert len(out["tokens"]) == 5
        assert out["prefill_replica"] is not None
        assert out["decode_replica"] is not None
        assert out["prefill_replica"] != out["decode_replica"]
        assert out.get("ttft_ms") is not None
    st = fleet.status()
    assert st["completed_total"] == 3 and st["failed_total"] == 0
    assert st["tokens_total"] == 15
    assert st["handoffs_total"] == 3
    assert st["handoff_bytes_total"] > 0
    assert st["reprefills_total"] == 0
    assert st["ttft_p50_ms"] is not None and st["ttft_p99_ms"] is not None
    fleet.stop()


def test_disagg_fleet_single_token_skips_decode(sched_factory):
    """max_new_tokens=1 is satisfied entirely by the prefill pool."""
    s = sched_factory(max_concurrent_jobs=4, fleet_fn=mock_fleet_fn)
    fleet = make_disagg(s)
    fleet.start()
    assert wait_until(lambda: _pools_up(fleet))
    out = fleet.wait(
        fleet.submit_request([5, 6, 7], max_new_tokens=1), timeout=10.0
    )
    assert out["status"] == "done" and len(out["tokens"]) == 1
    assert out["decode_replica"] is None
    fleet.stop()


def test_chaos_decode_preemption_reprefills_and_completes(sched_factory):
    """A decode replica holding handed-off KV dies through the faults.py
    preemption seam; the fleet re-prefills the request from scratch on
    the re-admitted replica and completes it."""
    inj = FaultInjector(FaultPlan(specs=[
        FaultSpec(kind=FaultKind.PREEMPTION_SIGNAL, at_step=1)
    ]))
    inj.arm()
    s = sched_factory(max_concurrent_jobs=4, fleet_fn=mock_fleet_fn)
    fleet = make_disagg(s, decode_fault_injector=inj)
    fleet.start()
    assert wait_until(lambda: _pools_up(fleet))
    # Enough decode tokens that the replica is mid-request when the fault
    # fires (the injector's step counter is the replica's token counter).
    fid = fleet.submit_request([1, 2, 3], max_new_tokens=32)
    out = fleet.wait(fid, timeout=20.0)
    assert out["status"] == "done"
    assert len(out["tokens"]) == 32
    assert out["redispatches"] >= 1
    assert fleet.reprefills_total >= 1
    assert inj.counters.get("preemption-signal") == 1
    (dec_sub,) = fleet.decode._replicas.values()
    assert dec_sub.preemptions >= 1
    assert dec_sub.attempts >= 2  # re-admitted after the preempt
    fleet.stop()


def test_requeue_gives_up_after_max_redispatch(sched_factory):
    s = sched_factory(max_concurrent_jobs=4, fleet_fn=mock_fleet_fn)
    fleet = make_disagg(s, max_redispatch=2)
    fleet.start()
    assert wait_until(lambda: _pools_up(fleet))
    fid = fleet.submit_request([1, 2], max_new_tokens=4)
    with fleet._lock:
        r = fleet._requests[fid]
        for _ in range(3):
            fleet._requeue_locked(fid, r, "test-forced")
    out = fleet.result(fid)
    assert out["status"] == "failed"
    assert "re-dispatches" in fleet._requests[fid]["error"]
    assert fleet.failed_total == 1
    fleet.stop()


# ---------------------------------------------------------------------------
# Fleet TTFT + autoscaler TTFT SLO (satellite)
# ---------------------------------------------------------------------------


def test_serving_fleet_status_reports_ttft(sched_factory):
    s = sched_factory(max_concurrent_jobs=2, fleet_fn=mock_fleet_fn)
    fleet = ServingFleet(
        s, ServingReplicaSpec(model_name="gpt-tiny", max_slots=4, max_len=128),
        autoscaler=_one(), engine_factory=DisaggStubEngine,
    )
    fleet.start()
    assert wait_until(lambda: len(fleet.running_replicas()) == 1)
    rids = [fleet.submit_request([1, 2], max_new_tokens=3) for _ in range(4)]
    assert all(
        wait_until(lambda r=r: fleet.result(r)["status"] == "done")
        for r in rids
    )
    st = fleet.status()
    assert st["ttft_p50_ms"] is not None and st["ttft_p50_ms"] >= 0
    assert st["ttft_p99_ms"] >= st["ttft_p50_ms"]
    pct = fleet.ttft_percentiles()
    assert pct["p50"] == st["ttft_p50_ms"]
    fleet.stop()


def test_autoscaler_ttft_slo_breach_scales_up():
    a = ReplicaAutoscaler(AutoscalerConfig(
        min_replicas=1, max_replicas=4, ttft_slo_ms=200.0,
    ))
    # End-to-end p99 is healthy; only TTFT is breached.
    assert a.observe(0.0, queue_depth=0.0, p99_ms=100.0, n_replicas=2,
                     ttft_p99_ms=900.0) == 3
    assert "TTFT SLO" in a.last_reason


def test_autoscaler_ignores_ttft_without_slo():
    a = ReplicaAutoscaler(AutoscalerConfig(min_replicas=1, max_replicas=4))
    assert a.observe(0.0, queue_depth=0.0, p99_ms=100.0, n_replicas=2,
                     ttft_p99_ms=9000.0) == 2


# ---------------------------------------------------------------------------
# Planner: per-pool layout choice
# ---------------------------------------------------------------------------


def test_plan_serving_pool_prefill_ranks_by_latency():
    plans = plan_serving_pool(
        "gpt-125m", "prefill", 4, hbm_free_gib=24.0, max_len=2048,
        inflight_handoffs=4,
    )
    feas = [p for p in plans if p.feasible]
    assert feas and feas[0].role == "prefill"
    # Slots pinned to the handoff window, not the candidate slot grid.
    assert all(p.max_slots == 4 for p in plans)
    assert all(
        feas[0].predicted_prefill_s <= p.predicted_prefill_s for p in feas
    )
    # More tensor parallelism lowers single-prompt latency on this model.
    assert feas[0].tensor_parallel > 1
    assert feas[0].label.startswith("prefill·tp")


def test_plan_serving_pool_decode_ranks_by_throughput():
    plans = plan_serving_pool(
        "gpt-125m", "decode", 4, hbm_free_gib=24.0, max_len=2048
    )
    feas = [p for p in plans if p.feasible]
    assert feas and all(
        feas[0].predicted_decode_tok_s >= p.predicted_decode_tok_s
        for p in feas
    )
    assert feas[0].predicted_decode_tok_s > 0


def test_plan_serving_pool_infeasible_carries_reason():
    plans = plan_serving_pool(
        "gpt-125m", "decode", 4, hbm_free_gib=0.05, max_len=2048
    )
    assert plans and all(not p.feasible for p in plans)
    assert all("free" in p.skip_reason for p in plans)


def test_plan_serving_pool_edges():
    assert plan_serving_pool("no-such-model", "decode", 4) == []
    with pytest.raises(ValueError):
        plan_serving_pool("gpt-tiny", "unified", 4)
    # Deterministic: same inputs, same ranking.
    a = plan_serving_pool("gpt-125m", "decode", 8, max_len=1024)
    b = plan_serving_pool("gpt-125m", "decode", 8, max_len=1024)
    assert [p.label for p in a] == [p.label for p in b]


def test_disagg_ab_sim_gates_and_layouts():
    """The A/B the bench gates on: disagg wins p99 TTFT at equal chips
    without giving up throughput, and both layouts are planner-chosen."""
    from benchmarks.serving_fleet_sim import run_disagg_ab

    ab = run_disagg_ab(seed=0)
    assert ab["gates_pass"], ab["gates"]
    assert ab["disagg"]["ttft_p99_ms"] < ab["symmetric"]["ttft_p99_ms"]
    lay = ab["layouts"]
    assert lay["disagg_prefill"].startswith("prefill·")
    assert lay["disagg_decode"].startswith("decode·")
    assert lay["symmetric"].startswith("decode·")
    assert lay["prefill_speedup"] > 1.0
