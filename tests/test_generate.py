"""KV-cache decode correctness: prefill+decode logits must match the
training forward pass position-for-position (dense models), plus sampling
and MoE-decode behavior."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_engine.generate import (
    forward_with_cache,
    generate,
    init_cache,
    sample_token,
)
from tpu_engine.models import transformer as tfm


def _setup(name="gpt-tiny", seed=0, B=2, S=16):
    cfg = tfm.MODEL_CONFIGS[name]
    params = tfm.init_params(jax.random.PRNGKey(seed), cfg)
    tokens = jax.random.randint(
        jax.random.PRNGKey(seed + 1), (B, S), 0, cfg.vocab_size, jnp.int32
    )
    return cfg, params, tokens


def test_prefill_then_decode_matches_forward():
    cfg, params, tokens = _setup()
    B, S = tokens.shape
    full = tfm.forward(params, tokens, cfg, compute_dtype=jnp.float32)

    prefill_len = 5
    cache = init_cache(cfg, B, S, dtype=jnp.float32)
    logits, cache = forward_with_cache(
        params, tokens[:, :prefill_len], cache, cfg, compute_dtype=jnp.float32
    )
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(full[:, :prefill_len]), atol=2e-4, rtol=2e-4
    )
    # Teacher-forced single-token decode for the remaining positions.
    for t in range(prefill_len, S):
        logits, cache = forward_with_cache(
            params, tokens[:, t : t + 1], cache, cfg, compute_dtype=jnp.float32
        )
        np.testing.assert_allclose(
            np.asarray(logits[:, 0]), np.asarray(full[:, t]), atol=2e-4, rtol=2e-4
        )
    assert int(cache.length) == S


def test_decode_gqa_model():
    # A GQA variant (KV heads < heads) exercises the cache repeat path.
    cfg, params, tokens = _setup()
    cfg = cfg.with_(n_kv_heads=cfg.n_heads // 2)
    params = tfm.init_params(jax.random.PRNGKey(3), cfg)
    full = tfm.forward(params, tokens, cfg, compute_dtype=jnp.float32)
    cache = init_cache(cfg, tokens.shape[0], tokens.shape[1], dtype=jnp.float32)
    logits, _ = forward_with_cache(
        params, tokens, cache, cfg, compute_dtype=jnp.float32
    )
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(full), atol=2e-4, rtol=2e-4
    )


def test_greedy_generate_shape_and_determinism():
    cfg, params, tokens = _setup(S=8)
    out1 = generate(params, tokens, cfg, max_new_tokens=6, compute_dtype=jnp.float32)
    out2 = generate(params, tokens, cfg, max_new_tokens=6, compute_dtype=jnp.float32)
    assert out1.shape == (2, 8 + 6)
    assert out1.dtype == jnp.int32
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    np.testing.assert_array_equal(np.asarray(out1[:, :8]), np.asarray(tokens))
    assert int(jnp.min(out1)) >= 0 and int(jnp.max(out1)) < cfg.vocab_size


def test_greedy_matches_stepwise_argmax():
    # generate() must reproduce manual argmax teacher-forcing on its own output.
    cfg, params, tokens = _setup(B=1, S=4)
    out = generate(params, tokens, cfg, max_new_tokens=3, compute_dtype=jnp.float32)
    seq = out
    for t in range(4, 7):
        logits = tfm.forward(params, seq[:, :t], cfg, compute_dtype=jnp.float32)
        expect = jnp.argmax(logits[:, -1], axis=-1)
        np.testing.assert_array_equal(np.asarray(seq[:, t]), np.asarray(expect))


def test_sampling_reproducible_and_temperature():
    cfg, params, tokens = _setup(S=8)
    rng = jax.random.PRNGKey(42)
    a = generate(params, tokens, cfg, max_new_tokens=5, rng=rng,
                 temperature=1.0, top_k=50, compute_dtype=jnp.float32)
    b = generate(params, tokens, cfg, max_new_tokens=5, rng=rng,
                 temperature=1.0, top_k=50, compute_dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sample_token_greedy_vs_topk():
    logits = jnp.array([[0.0, 5.0, 1.0, -2.0]])
    assert int(sample_token(logits, jax.random.PRNGKey(0))[0]) == 1
    # top_k=1 sampling always picks the argmax regardless of temperature.
    t = sample_token(logits, jax.random.PRNGKey(7), temperature=2.0, top_k=1)
    assert int(t[0]) == 1


def test_moe_decode_runs_and_is_finite():
    cfg, params, tokens = _setup(name="moe-tiny")
    out = generate(params, tokens, cfg, max_new_tokens=4, compute_dtype=jnp.float32)
    assert out.shape == (2, 16 + 4)
    cache = init_cache(cfg, 2, 16, dtype=jnp.float32)
    logits, _ = forward_with_cache(params, tokens, cache, cfg, compute_dtype=jnp.float32)
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_top_p_filters_tail():
    # One dominant token (~97% mass): top_p=0.5 must always pick it.
    logits = jnp.array([[8.0, 4.0, 3.0, 2.0]])
    for seed in range(20):
        t = sample_token(
            logits, jax.random.PRNGKey(seed), temperature=1.0, top_p=0.5
        )
        assert int(t[0]) == 0
    # top_p=1.0 keeps the full distribution: other tokens appear.
    seen = {
        int(sample_token(logits, jax.random.PRNGKey(s), temperature=2.0, top_p=1.0)[0])
        for s in range(200)
    }
    assert len(seen) > 1


def test_sampling_param_sweep_does_not_recompile():
    from tpu_engine.generate import _generate_jit

    cfg, params, tokens = _setup(S=8)
    base = _generate_jit._cache_size()
    generate(params, tokens, cfg, max_new_tokens=3, temperature=0.7,
             top_p=0.9, compute_dtype=jnp.float32)
    after_first = _generate_jit._cache_size()
    generate(params, tokens, cfg, max_new_tokens=3, temperature=1.3,
             top_p=0.5, compute_dtype=jnp.float32)
    generate(params, tokens, cfg, max_new_tokens=3, temperature=0.2,
             top_p=0.95, compute_dtype=jnp.float32)
    assert _generate_jit._cache_size() == after_first > base


def test_sliding_window_decode_matches_forward():
    """Windowed decode must match the windowed training forward position-
    for-position — seq 24 > window 6, so old keys really drop out."""
    cfg, params, tokens = _setup(S=24)
    cfg = cfg.with_(sliding_window=6)
    B, S = tokens.shape
    full = tfm.forward(params, tokens, cfg, compute_dtype=jnp.float32)

    cache = init_cache(cfg, B, S, dtype=jnp.float32)
    logits, cache = forward_with_cache(
        params, tokens[:, :4], cache, cfg, compute_dtype=jnp.float32
    )
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(full[:, :4]), atol=2e-4, rtol=2e-4
    )
    for t in range(4, S):
        logits, cache = forward_with_cache(
            params, tokens[:, t : t + 1], cache, cfg, compute_dtype=jnp.float32
        )
        np.testing.assert_allclose(
            np.asarray(logits[:, 0]), np.asarray(full[:, t]), atol=2e-4, rtol=2e-4
        )


def test_rolling_cache_matches_forward():
    """Ring-buffer cache: a windowed model decodes with O(window) cache
    slots; logits must still match the full training forward even after
    the buffer has wrapped several times."""
    cfg, params, tokens = _setup(S=40)
    cfg = cfg.with_(sliding_window=6)
    B, S = tokens.shape
    full = tfm.forward(params, tokens, cfg, compute_dtype=jnp.float32)

    prefill = 4
    cache = init_cache(cfg, B, S, dtype=jnp.float32, max_chunk=prefill)
    assert cache.max_len == 6 + prefill - 1  # O(window), not O(seq)
    logits, cache = forward_with_cache(
        params, tokens[:, :prefill], cache, cfg, compute_dtype=jnp.float32
    )
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(full[:, :prefill]), atol=2e-4, rtol=2e-4
    )
    for t in range(prefill, S):  # wraps the 9-slot buffer 4+ times
        logits, cache = forward_with_cache(
            params, tokens[:, t : t + 1], cache, cfg, compute_dtype=jnp.float32
        )
        np.testing.assert_allclose(
            np.asarray(logits[:, 0]), np.asarray(full[:, t]), atol=2e-4, rtol=2e-4,
            err_msg=f"position {t}",
        )


def test_rolling_cache_rejects_oversized_chunk():
    cfg, params, tokens = _setup(S=32)
    cfg = cfg.with_(sliding_window=8)
    cache = init_cache(cfg, 2, 32, dtype=jnp.float32, max_chunk=4)  # 11 slots
    with pytest.raises(ValueError, match="cache slots"):
        forward_with_cache(params, tokens[:, :8], cache, cfg,
                           compute_dtype=jnp.float32)


def test_windowed_generate_end_to_end():
    """generate() on a windowed model allocates an O(window) cache and
    produces identical tokens to a full-size-cache run."""
    cfg, params, tokens = _setup(S=8)
    wcfg = cfg.with_(sliding_window=5)
    out = generate(params, tokens, wcfg, max_new_tokens=20,
                   compute_dtype=jnp.float32)
    assert out.shape == (2, 28)
    # Reference: same model, cache big enough to never wrap.
    cache = init_cache(wcfg, 2, 28, dtype=jnp.float32)
    toks = tokens
    logits, cache = forward_with_cache(params, toks, cache, wcfg,
                                       compute_dtype=jnp.float32)
    nxt = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)
    for _ in range(20):
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
        logits, cache = forward_with_cache(params, nxt[:, None], cache, wcfg,
                                           compute_dtype=jnp.float32)
        nxt = jnp.argmax(logits[:, 0], -1).astype(jnp.int32)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(toks))


def test_windowed_generate_short_run():
    """Short generations on windowed models (max_new_tokens < window-1)
    allocate a full-size (non-ring) cache and must not trip the ring guard."""
    cfg, params, tokens = _setup(S=8)
    out = generate(params, tokens, cfg.with_(sliding_window=5),
                   max_new_tokens=2, compute_dtype=jnp.float32)
    assert out.shape == (2, 10)


def test_ring_decode_requires_full_window():
    """T=1 decode on a ring cache with fewer slots than the window must
    raise, not silently drop in-window keys."""
    from tpu_engine.generate import KVCache

    cfg, params, tokens = _setup(S=8)
    cfg = cfg.with_(sliding_window=8)
    small = init_cache(cfg, 2, 4, dtype=jnp.float32)
    small = KVCache(k=small.k, v=small.v, pos=small.pos, length=small.length,
                    ring=True)  # force ring with M=4 < window=8
    with pytest.raises(ValueError, match="cache slots"):
        forward_with_cache(params, tokens[:, :1], small, cfg,
                           compute_dtype=jnp.float32)


# ---------------------------------------------------------------------------
# Speculative decoding
# ---------------------------------------------------------------------------


def test_speculative_matches_greedy():
    """Speculative decode must equal plain greedy decoding of the target
    exactly — with a perfect draft (same model) and an adversarial one
    (different random init, frequent rejections)."""
    from tpu_engine.generate import speculative_generate

    cfg, params, _ = _setup()
    draft = tfm.init_params(jax.random.PRNGKey(9), cfg)
    prompt = jnp.asarray([[3, 1, 4, 1, 5]], jnp.int32)
    ref = generate(params, prompt, cfg, max_new_tokens=24,
                   compute_dtype=jnp.float32)

    same, rounds = speculative_generate(params, params, prompt, cfg, cfg, 24,
                                        gamma=4, compute_dtype=jnp.float32,
                                        return_stats=True)
    np.testing.assert_array_equal(np.asarray(same), np.asarray(ref))
    # A perfect draft (same model) must accept all gamma proposals every
    # round: 24 tokens / (gamma+1) per round = 5 rounds. More means the
    # draft cache has holes (e.g. its own last proposal never ingested).
    assert rounds == 5, rounds

    diff = speculative_generate(params, draft, prompt, cfg, cfg, 24,
                                gamma=3, compute_dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(diff), np.asarray(ref))


def test_speculative_windowed_ring_cache():
    """Speculative rewind composes with the sliding-window ring cache."""
    from tpu_engine.generate import speculative_generate

    cfg, params, _ = _setup()
    wcfg = cfg.with_(sliding_window=6)
    draft = tfm.init_params(jax.random.PRNGKey(9), wcfg)
    prompt = jnp.asarray([[3, 1, 4, 1, 5]], jnp.int32)
    ref = generate(params, prompt, wcfg, max_new_tokens=24,
                   compute_dtype=jnp.float32)
    spec = speculative_generate(params, draft, prompt, wcfg, wcfg, 24,
                                gamma=3, compute_dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(spec), np.asarray(ref))


def test_speculative_validation():
    from tpu_engine.generate import speculative_generate

    cfg, params, tokens = _setup()
    with pytest.raises(ValueError, match="batch size 1"):
        speculative_generate(params, params, tokens, cfg, cfg, 4)
    with pytest.raises(ValueError, match="gamma"):
        speculative_generate(params, params, tokens[:1], cfg, cfg, 4, gamma=0)


def test_gpt2_decode_matches_forward():
    """GPT-2 decode (learned positions at embed, biases, LayerNorm) must
    match the training forward position-for-position."""
    cfg, params, tokens = _setup(name="gpt2-tiny")
    B, S = tokens.shape
    full = tfm.forward(params, tokens, cfg, compute_dtype=jnp.float32)
    cache = init_cache(cfg, B, S, dtype=jnp.float32)
    logits, cache = forward_with_cache(params, tokens[:, :5], cache, cfg,
                                       compute_dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(full[:, :5]),
                               atol=2e-4, rtol=2e-4)
    for t in range(5, S):
        logits, cache = forward_with_cache(params, tokens[:, t:t+1], cache, cfg,
                                           compute_dtype=jnp.float32)
        np.testing.assert_allclose(np.asarray(logits[:, 0]), np.asarray(full[:, t]),
                                   atol=2e-4, rtol=2e-4)


def test_gemma_decode_matches_forward():
    """Gemma decode (sqrt(d)-scaled embeddings, zero-centred RMSNorm,
    GeGLU, decoupled head_dim, MQA, tied head) must match the training
    forward position-for-position."""
    cfg, params, tokens = _setup(name="gemma-tiny")
    B, S = tokens.shape
    full = tfm.forward(params, tokens, cfg, compute_dtype=jnp.float32)
    cache = init_cache(cfg, B, S, dtype=jnp.float32)
    logits, cache = forward_with_cache(params, tokens[:, :5], cache, cfg,
                                       compute_dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(full[:, :5]),
                               atol=2e-4, rtol=2e-4)
    for t in range(5, 9):
        logits, cache = forward_with_cache(params, tokens[:, t:t+1], cache, cfg,
                                           compute_dtype=jnp.float32)
        np.testing.assert_allclose(np.asarray(logits[:, 0]), np.asarray(full[:, t]),
                                   atol=2e-4, rtol=2e-4)


def test_gpt2_position_table_bounds():
    """Out-of-table positions must raise, not silently clamp."""
    cfg, params, _ = _setup(name="gpt2-tiny")
    long_cfg = cfg.with_(max_seq_len=8)
    params8 = tfm.init_params(jax.random.PRNGKey(0), long_cfg)
    toks = jnp.zeros((1, 16), jnp.int32)
    with pytest.raises(ValueError, match="position table"):
        tfm.forward(params8, toks, long_cfg, compute_dtype=jnp.float32)
    with pytest.raises(ValueError, match="position table"):
        generate(params8, toks[:, :4], long_cfg, max_new_tokens=8,
                 compute_dtype=jnp.float32)


def test_int8_kv_cache_close_to_full_precision():
    """Quantised (int8 + per-(position, head) scales) cache: logits within
    ~1% of the full-precision cache, half the storage."""
    cfg, params, tokens = _setup()
    B, S = tokens.shape
    c_full = init_cache(cfg, B, S, dtype=jnp.float32)
    c_q = init_cache(cfg, B, S, dtype=jnp.float32, kv_quant=True)
    assert c_q.k.dtype == jnp.int8 and c_q.quantized
    assert c_q.k_scale.shape == c_q.k.shape[:-1] + (1,)
    l_full, _ = forward_with_cache(params, tokens, c_full, cfg, jnp.float32)
    l_q, _ = forward_with_cache(params, tokens, c_q, cfg, jnp.float32)
    scale = float(jnp.max(jnp.abs(l_full)))
    assert float(jnp.max(jnp.abs(l_full - l_q))) < 0.02 * scale


def test_int8_kv_cache_greedy_generation_matches():
    cfg, params, _ = _setup()
    prompt = jnp.asarray([[1, 2, 3, 4, 5, 6, 7, 8]], jnp.int32)
    full = generate(params, prompt, cfg, max_new_tokens=10,
                    compute_dtype=jnp.float32)
    q = generate(params, prompt, cfg, max_new_tokens=10,
                 compute_dtype=jnp.float32, kv_quant=True)
    # Random-init logit gaps dwarf the ~1% quantisation error, so greedy
    # decode must agree exactly here.
    assert np.array_equal(np.asarray(full), np.asarray(q))


def test_int8_kv_cache_windowed_ring():
    """Quantised cache composes with the sliding-window ring buffer: the
    scale rows wrap with the code rows."""
    cfg, params, _ = _setup()
    cfgw = cfg.with_(sliding_window=6)
    prompt = jnp.asarray([[3, 1, 4, 1, 5, 9, 2, 6]], jnp.int32)
    full = generate(params, prompt, cfgw, max_new_tokens=12,
                    compute_dtype=jnp.float32)
    q = generate(params, prompt, cfgw, max_new_tokens=12,
                 compute_dtype=jnp.float32, kv_quant=True)
    assert np.asarray(q).shape == np.asarray(full).shape
    assert (np.asarray(q) == np.asarray(full)).mean() > 0.9


# Compile-heavy module: excluded from the fast core run (pytest -m "not slow").
pytestmark = pytest.mark.slow


def test_qwen_decode_matches_forward():
    """Qwen3 decode (per-head qk-norm before RoPE, decoupled head_dim, GQA)
    must match the training forward position-for-position."""
    cfg, params, tokens = _setup(name="qwen-tiny")
    B, S = tokens.shape
    full = tfm.forward(params, tokens, cfg, compute_dtype=jnp.float32)
    cache = init_cache(cfg, B, S, dtype=jnp.float32)
    logits, cache = forward_with_cache(params, tokens[:, :5], cache, cfg,
                                       compute_dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(full[:, :5]),
                               atol=2e-4, rtol=2e-4)
    for t in range(5, 9):
        logits, cache = forward_with_cache(params, tokens[:, t:t+1], cache, cfg,
                                           compute_dtype=jnp.float32)
        np.testing.assert_allclose(np.asarray(logits[:, 0]), np.asarray(full[:, t]),
                                   atol=2e-4, rtol=2e-4)
