"""Fleet prefix plane: radix index, host-RAM KV tier, cache-aware routing.

Three tiers in one file, mirroring ``test_disagg.py``:

- **Unit properties** — trie insert/remove/prune and the longest-holder
  walk; the host tier's byte ledger and its reuse-scored (NOT least-
  recently-used) eviction; plane routing hints, admission bookkeeping,
  spill-to-host and replica teardown; the HBM estimator's host-tier term
  and its structured over-budget rejection.
- **Real-engine round trips** — ``_PrefixCache`` reuse telemetry;
  ``export_prefix``/``install_prefix``; and all four KVHandoff wire x
  pool conversions round-tripping store -> host tier -> rehydrate within
  the documented one-token int8 bound.
- **Twin lane** — the seeded many-tenant lane is deterministic and the
  A/B gates (p99 TTFT >= 2x, throughput no worse, host tier absorbs
  overflow) hold at reduced duration.
"""

import numpy as np
import pytest

from tests.test_disagg import MAX_NEW, PROMPT, drive, extract, tiny_spec
from tpu_engine.hbm_estimate import HostBudgetExceeded, estimate_serving_hbm
from tpu_engine.historian import MetricHistorian
from tpu_engine.prefix_plane import (
    HIT_TOKENS_SERIES,
    HOST_HOLDER,
    HostKVTier,
    PrefixPlane,
    PrefixTrieIndex,
    plane_stats,
    quantize_handoff,
)

# ---------------------------------------------------------------------------
# PrefixTrieIndex
# ---------------------------------------------------------------------------


def test_trie_longest_holder_walk():
    idx = PrefixTrieIndex()
    idx.insert([1, 2, 3, 4], "a")
    idx.insert([1, 2], "b")
    idx.insert([7, 8], "c")
    # Deepest marked node wins; shallower holders are shadowed.
    matched, holders = idx.longest_holders([1, 2, 3, 4, 99])
    assert (matched, holders) == (4, {"a"})
    # A prompt diverging after 2 tokens falls back to the shallower mark.
    matched, holders = idx.longest_holders([1, 2, 9])
    assert (matched, holders) == (2, {"b"})
    assert idx.longest_holders([5, 5]) == (0, set())
    # exclude filters holders without disturbing depth preference.
    matched, holders = idx.longest_holders([1, 2, 3, 4], exclude={"a"})
    assert (matched, holders) == (2, {"b"})


def test_trie_remove_prunes_empty_tail():
    idx = PrefixTrieIndex()
    idx.insert([1, 2, 3], "a")
    idx.insert([1, 2], "b")
    n_full = idx.nodes
    assert n_full == 4  # root + 3
    idx.remove([1, 2, 3], "a")
    # The [.., 3] tail node is unreachable garbage — it must be pruned —
    # while the shared [1, 2] spine survives for "b".
    assert idx.nodes == 3
    assert idx.longest_holders([1, 2, 3]) == (2, {"b"})
    assert idx.n_prefixes == 1
    # Removing an unknown (prefix, holder) pair is a no-op.
    idx.remove([1, 2, 3], "a")
    assert idx.nodes == 3


def test_trie_drop_holder_forgets_everything():
    idx = PrefixTrieIndex()
    idx.insert([1, 2], "a")
    idx.insert([3, 4], "a")
    idx.insert([1, 2], "b")
    idx.drop_holder("a")
    assert idx.prefixes("a") == set()
    assert idx.longest_holders([3, 4]) == (0, set())
    assert idx.longest_holders([1, 2]) == (2, {"b"})


# ---------------------------------------------------------------------------
# HostKVTier
# ---------------------------------------------------------------------------


def _tier(budget, **kw):
    kw.setdefault("historian", MetricHistorian())
    return HostKVTier(budget_bytes=budget, **kw)


def test_host_tier_byte_ledger_and_refresh():
    tier = _tier(250, clock=lambda: 0.0)
    assert tier.put([1, 1], nbytes=100)
    assert tier.put([2, 2], nbytes=100)
    assert tier.total_bytes == 200
    # Refreshing an entry re-charges, not double-charges.
    assert tier.put([1, 1], nbytes=120)
    assert tier.total_bytes == 220
    assert tier.contains([1, 1]) and tier.contains([2, 2])
    # A payload larger than the whole budget is refused outright.
    assert not tier.put([3, 3], nbytes=251)
    assert tier.stats()["occupancy"] == round(220 / 250, 4)
    tier.pop([1, 1])
    assert tier.total_bytes == 100


def test_host_tier_evicts_by_reuse_not_recency():
    """The eviction victim is the LOWEST historian-scored prefix: a
    frequently re-hit entry survives even when another entry was touched
    more recently (plain LRU would evict the old hot entry)."""
    now = [0.0]
    tier = _tier(250, clock=lambda: now[0], reuse_window_s=600.0)
    hot, cold = (1, 2, 3), (4, 5, 6)
    assert tier.put(hot, nbytes=100, now=0.0)
    assert tier.put(cold, nbytes=100, now=1.0)
    for t in (2.0, 3.0, 4.0):
        assert tier.get(hot, now=t) is None  # capacity entry, hit counted
    tier.get(cold, now=5.0)  # cold touched LAST -> LRU would keep it
    assert tier.put((7, 8, 9), nbytes=100, now=6.0)
    assert tier.contains(hot)
    assert not tier.contains(cold)
    assert tier.evictions == 1
    st = tier.stats()
    assert st["entries"] == 2 and st["hits"] == 4


def test_host_tier_reuse_score_falls_back_without_series():
    """With no historian coverage the tier's own lifetime hit counters
    drive the same decision (telemetry loss must not randomize
    eviction)."""

    class _Deaf:
        def record(self, *a, **kw):
            raise RuntimeError("down")

        def query(self, *a, **kw):
            raise RuntimeError("down")

    tier = HostKVTier(budget_bytes=250, historian=_Deaf(),
                      clock=lambda: 0.0)
    assert tier.put((1,), nbytes=100, now=0.0)
    assert tier.put((2,), nbytes=100, now=1.0)
    tier.get((1,), now=2.0)
    tier.get((1,), now=3.0)
    tier.get((2,), now=4.0)
    assert tier.put((3,), nbytes=100, now=5.0)
    assert tier.contains((1,)) and not tier.contains((2,))


def test_host_tier_hits_feed_historian_series():
    hist = MetricHistorian()
    tier = HostKVTier(budget_bytes=1000, historian=hist, clock=lambda: 0.0)
    prefix = (9, 9, 9)
    tier.put(prefix, nbytes=10, now=0.0)
    tier.get(prefix, now=1.0)
    q = hist.query(
        HIT_TOKENS_SERIES, t0=0.0, t1=10.0, agg="sum",
        labels={"prefix": HostKVTier.prefix_label(prefix)},
    )
    assert q["count"] == 1 and q["value"] == len(prefix)


# ---------------------------------------------------------------------------
# PrefixPlane
# ---------------------------------------------------------------------------


def _plane(**kw):
    kw.setdefault("historian", MetricHistorian())
    kw.setdefault("clock", lambda: 0.0)
    kw.setdefault("host", HostKVTier(
        budget_bytes=1 << 20, historian=kw["historian"], clock=kw["clock"]
    ))
    return PrefixPlane(**kw)


def test_plane_route_hint_prefers_longest_then_free():
    plane = _plane(prefix_tokens=8)
    plane.index.insert([1, 2], "r_short")
    plane.index.insert([1, 2, 3, 4], "r_long")
    plane.index.insert([1, 2, 3, 4], HOST_HOLDER)
    rid, matched = plane.route_hint([1, 2, 3, 4, 5], {"r_short": 4,
                                                      "r_long": 4})
    assert (rid, matched) == ("r_long", 4)  # host sentinel never routed to
    # The longest holder being slot-full yields (None, matched): the
    # caller falls through to WRR but knows the host tier may still help.
    rid, matched = plane.route_hint([1, 2, 3, 4, 5], {"r_long": 0})
    assert (rid, matched) == (None, 4)
    # Free-slot count breaks ties between equal-depth holders.
    plane.index.insert([1, 2, 3, 4], "r_other")
    rid, _ = plane.route_hint([1, 2, 3, 4], {"r_long": 1, "r_other": 3})
    assert rid == "r_other"


def test_plane_admission_lifecycle_and_spill():
    """cold -> replica hit -> mirror overflow spills to the host tier ->
    a different replica's admission rehydrates from it."""
    spilled = []

    def spill(prefix, rid):
        spilled.append((prefix, rid))
        return 64  # capacity model: 64 bytes per prefix

    plane = _plane(prefix_tokens=2, replica_prefix_budget=1, spill=spill)
    assert plane.observe_admit([1, 1, 9], "r0", now=0.0)["kind"] == "cold"
    assert plane.observe_admit([1, 1, 8], "r0", now=1.0)["kind"] == "replica"
    # A second prefix overflows r0's single-entry mirror: (1, 1) must
    # spill to the host tier, not vanish.
    obs = plane.observe_admit([2, 2, 9], "r0", now=2.0)
    assert obs["kind"] == "cold" and obs["evicted"] == [(1, 1)]
    assert spilled == [((1, 1), "r0")]
    assert plane.host.contains((1, 1))
    assert HOST_HOLDER in plane.index.longest_holders([1, 1])[1]
    # Another replica admitting the spilled prefix is a host rehydration.
    obs = plane.observe_admit([1, 1, 7], "r1", now=3.0)
    assert obs["kind"] == "host" and obs["payload"] is None
    st = plane.stats()
    assert st["host_rehydrations"] == 1
    assert st["host"]["stores"] == 1
    # The rehydrated replica now serves route hints for the prefix.
    assert plane.route_hint([1, 1, 5], {"r0": 4, "r1": 4})[0] == "r1"


def test_plane_spill_skipped_while_another_replica_holds():
    plane = _plane(prefix_tokens=2, replica_prefix_budget=1,
                   spill=lambda p, r: 64)
    plane.observe_admit([1, 1, 9], "r0", now=0.0)
    plane.observe_admit([1, 1, 9], "r1", now=1.0)  # r1 holds it too
    plane.observe_admit([2, 2, 9], "r0", now=2.0)  # evicts r0's copy
    # r1 still holds the prefix on-device: no host bytes spent on it.
    assert not plane.host.contains((1, 1))
    assert plane.route_hint([1, 1, 5], {"r0": 4, "r1": 4})[0] == "r1"


def test_plane_drop_replica_keeps_host_copy():
    plane = _plane(prefix_tokens=2, replica_prefix_budget=4)
    plane.observe_admit([3, 3, 1], "r0", now=0.0)
    plane.store_host([3, 3], nbytes=64, now=1.0)
    plane.drop_replica("r0")
    # No replica holds it any more (matched counts replica holders only)
    # but the host copy survives the teardown and stays discoverable.
    assert plane.route_hint([3, 3, 1], {"r1": 4}) == (None, 0)
    assert plane.host_prefix_for([3, 3, 1]) == (3, 3)
    assert plane.stats()["replicas_tracked"] == 0


def test_plane_module_counters_track_activity():
    from tpu_engine.prefix_plane import _reset_stats_for_tests

    _reset_stats_for_tests()
    try:
        plane = _plane(prefix_tokens=2, replica_prefix_budget=1,
                       spill=lambda p, r: 64)
        plane.observe_admit([1, 1, 9], "r0", now=0.0)
        plane.observe_admit([2, 2, 9], "r0", now=1.0)  # spills (1, 1)
        plane.observe_admit([1, 1, 7], "r1", now=2.0)  # host rehydration
        plane.route_hint([2, 2, 5], {"r0": 4})
        st = plane_stats()
        assert st["lookups_total"] == 1
        assert st["index_hits_total"] == 1
        assert st["host_stores_total"] == 1
        assert st["rehydrations_total"] == 1
        assert st["host_hits_total"] == 1
        assert st["index_prefixes"] >= 1
    finally:
        _reset_stats_for_tests()


# ---------------------------------------------------------------------------
# HBM estimator: host-tier term + structured rejection
# ---------------------------------------------------------------------------


def test_estimate_host_tier_term_and_budget():
    base = estimate_serving_hbm("llama-1b", 8, 2048)
    assert base.host_gib == 0.0
    est = estimate_serving_hbm(
        "llama-1b", 8, 2048, host_prefix_tokens=100_000, host_budget_gib=8.0
    )
    assert est.host_gib > 0
    # The host tier lives in host RAM: the device-side totals are
    # untouched by promising host-resident prefix tokens.
    assert est.device_total_gib == base.device_total_gib
    assert any("host" in n for n in est.notes)


def test_estimate_rejects_oversubscribed_host_budget():
    with pytest.raises(HostBudgetExceeded) as ei:
        estimate_serving_hbm(
            "llama-1b", 8, 2048,
            host_prefix_tokens=1 << 30, host_budget_gib=1.0,
        )
    reason = ei.value.reason
    assert reason["kind"] == "host_budget_exceeded"
    assert reason["model_name"] == "llama-1b"
    assert reason["required_gib"] > reason["budget_gib"] == 1.0


def test_plan_host_tier_sizes_through_estimator():
    tier = PrefixPlane.plan_host_tier("llama-1b", 8, 2048,
                                      host_prefix_tokens=10_000,
                                      host_budget_gib=2.0)
    assert tier.budget_bytes == int(2.0 * (1 << 30))
    with pytest.raises(HostBudgetExceeded):
        PrefixPlane.plan_host_tier("llama-1b", 8, 2048,
                                   host_prefix_tokens=1 << 30,
                                   host_budget_gib=1.0)


# ---------------------------------------------------------------------------
# Real-engine round trips (gpt-tiny, like test_disagg)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def engines():
    from tpu_engine.serving_fleet import build_replica_engine

    return {
        "prefill": build_replica_engine(tiny_spec()),
        "decode": build_replica_engine(tiny_spec()),
        "decode_kvq": build_replica_engine(tiny_spec(kv_quant=True)),
    }


@pytest.fixture(scope="module")
def baseline_tokens(engines):
    out = drive(engines["decode"], engines["decode"].submit(PROMPT, MAX_NEW))
    assert len(out["tokens"]) == MAX_NEW
    return list(out["tokens"])


@pytest.mark.parametrize("pool", ["decode", "decode_kvq"])
@pytest.mark.parametrize("wire_quant", [False, True])
def test_host_tier_roundtrip_all_wire_pool_pairs(
    engines, baseline_tokens, wire_quant, pool
):
    """All four wire x pool conversions survive the host tier: extract
    (fp or int8 wire) -> HostKVTier.put (always stores int8) -> get ->
    submit_prefilled into an fp or int8 slot pool, within the documented
    one-token bound of the single-replica baseline."""
    pre, dec = engines["prefill"], engines[pool]
    out = drive(pre, pre.submit(PROMPT, max_new_tokens=1, hold_kv=True))
    h = extract(pre, out["id"], quantize=wire_quant)
    assert h.quantized == wire_quant

    tier = HostKVTier(budget_bytes=1 << 20, historian=MetricHistorian(),
                      clock=lambda: 0.0)
    key = tuple(h.prompt)
    assert tier.put(key, handoff=h, now=0.0)
    stored = tier.get(key, now=1.0)
    assert stored is not None and stored.quantized  # host form is int8
    if wire_quant:
        assert stored is h  # already-int8 payloads pass through untouched

    got = drive(dec, dec.submit_prefilled(stored,
                                          max_new_tokens=MAX_NEW - 1))
    stitched = [out["tokens"][0], *got["tokens"]]
    assert len(stitched) == len(baseline_tokens)
    mismatches = sum(a != b for a, b in zip(stitched, baseline_tokens))
    assert mismatches <= 1


def test_quantize_handoff_matches_wire_quantizer(engines):
    pre = engines["prefill"]
    out = drive(pre, pre.submit(PROMPT, max_new_tokens=1, hold_kv=True))
    fp = extract(pre, out["id"])
    q = quantize_handoff(fp)
    assert q.quantized and q.dtype == "int8"
    assert q.k.dtype == np.int8 and q.k_scale.shape == (*q.k.shape[:-1], 1)
    assert q.wire_bytes() < fp.wire_bytes()
    # Round-trip bound: absmax int8 error is half a code step.
    deq = q.k.astype(np.float32) * q.k_scale
    assert np.all(np.abs(deq - fp.k) <= q.k_scale / 2 + 1e-6)


def _drain(engine, prompts, max_new=4, steps=200):
    rids = [engine.submit(p, max_new_tokens=max_new) for p in prompts]
    for _ in range(steps):
        if all(engine.result(r)["status"] == "done" for r in rids):
            break
        engine.step()
    return [engine.result(r)["tokens"] for r in rids]


def test_prefix_cache_reuse_telemetry():
    """Satellite: ``_PrefixCache`` reports hit-token totals and per-entry
    hit counts through the batcher's stats surface."""
    eng = _fresh_cached_engine()
    rng = np.random.default_rng(5)
    # Longer than one prefill chunk (64 on the replica build) so the
    # shared prefix crosses a cacheable boundary.
    system = rng.integers(1, 250, 80).tolist()
    tails = [[1, 2], [3, 4], [5, 6]]
    _drain(eng, [system + t for t in tails])
    st = eng.stats()["prefix_cache"]
    assert st["hits"] >= 2
    # Every hit pasted >= one full chunk of the shared system prompt.
    assert st["hit_tokens_total"] >= 64 * st["hits"]
    assert isinstance(st["entry_hits"], list) and st["entry_hits"]
    assert sum(e["hits"] for e in st["entry_hits"]) == st["hits"]
    assert all(e["prefix_tokens"] > 0 for e in st["entry_hits"])


def _fresh_cached_engine(**kw):
    from tpu_engine.serving_fleet import build_replica_engine

    return build_replica_engine(
        tiny_spec(prefix_cache_tokens=512, **kw)
    )


def test_export_install_prefix_cross_replica():
    """A prefix exported from one replica's cache installs into another
    replica and serves its first warm admission without re-prefilling the
    shared tokens — the live rehydration path ``_observe_plane`` uses."""
    src, dst = _fresh_cached_engine(), _fresh_cached_engine()
    rng = np.random.default_rng(7)
    system = rng.integers(1, 250, 80).tolist()
    ref = _drain(src, [system + [9, 9], system + [8, 8]])
    assert src.stats()["prefix_cache"]["entries"] >= 1

    key = max(src._prefix_cache._entries, key=len)
    h = src.export_prefix(list(key))
    assert h is not None
    assert h.length == len(key) and list(h.prompt) == list(key)
    assert h.emitted == []
    # Prefix-export payloads are deliberately NOT decodable — they lack
    # the emitted token submit_prefilled needs to resume decoding from.
    with pytest.raises(ValueError):
        dst.submit_prefilled(h)

    assert dst.install_prefix(list(key), h)
    st = dst.stats()["prefix_cache"]
    assert st["entries"] == 1 and st["tokens"] >= len(key)
    # Warm admissions on the installed prefix hit AND stream identically.
    got = _drain(dst, [system + [9, 9], system + [8, 8]])
    assert got == ref
    st = dst.stats()["prefix_cache"]
    assert st["hits"] >= 1 and st["hit_tokens_total"] >= len(key)
    # Re-installing a resident prefix is an idempotent no-op.
    assert dst.install_prefix(list(key), h)


def test_export_prefix_unknown_key_is_none():
    eng = _fresh_cached_engine()
    assert eng.export_prefix([1, 2, 3]) is None


# ---------------------------------------------------------------------------
# Twin lane: determinism + the measured A/B gates
# ---------------------------------------------------------------------------


def _short_params(**kw):
    from tpu_engine.twin import PrefixPlaneLaneParams

    base = dict(duration_s=200.0, warmup_s=30.0, n_replicas=3,
                n_prefixes=24, replica_cache_prefixes=4,
                host_budget_entries=48, burst_every_s=60.0)
    base.update(kw)
    return PrefixPlaneLaneParams(**base)


def test_twin_lane_deterministic():
    from tpu_engine.twin import prefix_plane_lane

    p = _short_params(duration_s=90.0)
    a = prefix_plane_lane(seed=3, plane=True, params=p)
    b = prefix_plane_lane(seed=3, plane=True, params=p)
    assert a == b
    c = prefix_plane_lane(seed=4, plane=True, params=p)
    assert c != a


def test_twin_ab_gates_hold():
    from tpu_engine.twin import prefix_plane_ab, prefix_plane_bench_line

    res = prefix_plane_ab(seed=0, params=_short_params())
    assert res["gates"]["plane_beats_baseline_p99_ttft_2x"], res["gates"]
    assert res["gates"]["tokens_per_sec_no_worse"]
    assert res["gates"]["deterministic_repeat"]
    assert res["gates"]["host_tier_absorbs_overflow"]
    assert res["gates"]["host_budget_rejected"]
    assert res["ok"]
    assert res["host_budget_rejection"]["kind"] == "host_budget_exceeded"
    # The bench line the sentinel gates carries the same verdict.
    line = prefix_plane_bench_line(seed=0, ab=res)
    assert line["metric"] == "prefix_plane"
    assert line["ok"] and line["value"] >= 2.0
    assert line["host_stores"] > 0 and line["host_rehydrations"] > 0
