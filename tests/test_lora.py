"""LoRA fine-tuning: adapter-only training state, frozen base, merge
semantics, sharding, checkpointing, and generation from adapted weights."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_engine import TPULauncher, TPUTrainConfig
from tpu_engine.lora import lora_param_count, merge_lora
from tpu_engine.mesh_runtime import MeshConfig
from tpu_engine.models import transformer as tfm
from tpu_engine.sharding import Precision, ShardingStage
from tpu_engine.train import build_train_program


def _cfg(**kw):
    base = dict(
        model_name="gpt-tiny",
        sharding_stage=ShardingStage.FULL_PARTITIONING,
        mesh=MeshConfig(data=2, fsdp=4),
        micro_batch_size=1,
        gradient_accumulation_steps=2,
        seq_len=32,
        precision=Precision.FP32,
        learning_rate=1e-2,
        warmup_steps=2,
        total_steps=100,
        activation_checkpointing=False,
        lora_rank=4,
    )
    base.update(kw)
    return TPUTrainConfig(**base)


def test_trainable_state_is_adapter_sized():
    prog = build_train_program(_cfg())
    state = prog.init(jax.random.PRNGKey(0))
    # Only the adapter tree trains.
    assert set(state["params"].keys()) == {"layers"}
    assert set(state["params"]["layers"].keys()) == {"q", "k", "v", "o"}
    n = sum(x.size for x in jax.tree.leaves(state["params"]))
    assert n == lora_param_count(prog.model_config, 4, ("q", "k", "v", "o"))
    assert n < tfm.param_count(prog.model_config) // 20
    # Adam moments are adapter-sized too (the memory win).
    mu = state["opt_state"][1].mu
    n_mu = sum(x.size for x in jax.tree.leaves(mu))
    assert n_mu == n
    # B starts at zero → adapted model == base model at step 0.
    assert float(jnp.sum(jnp.abs(state["params"]["layers"]["q"]["B"]))) == 0.0


def test_lora_loss_decreases_and_base_frozen():
    prog = build_train_program(_cfg())
    state = prog.init(jax.random.PRNGKey(0))
    base_q_before = np.asarray(jax.device_get(prog.base_params["layers"]["q"]["kernel"]))
    batch = prog.synthetic_batch(0)
    losses = []
    for _ in range(8):
        state, m = prog.step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0], losses
    np.testing.assert_array_equal(
        base_q_before, np.asarray(jax.device_get(prog.base_params["layers"]["q"]["kernel"]))
    )
    # Training moved the adapters: merged weights now differ from base.
    merged = prog.merged_params(state["params"])
    assert not np.array_equal(
        np.asarray(jax.device_get(merged["layers"]["q"]["kernel"])), base_q_before
    )
    # ...but only on adapted targets.
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(merged["layers"]["gate"]["kernel"])),
        np.asarray(jax.device_get(prog.base_params["layers"]["gate"]["kernel"])),
    )


def test_step_zero_matches_base_model():
    # B=0 at init → the first-step loss equals full-model training's loss
    # with identical base weights... verified via eval on the merged params.
    cfg = _cfg()
    prog = build_train_program(cfg)
    state = prog.init(jax.random.PRNGKey(0))
    batch = prog.synthetic_batch(0)
    lora_eval = float(jax.device_get(prog.eval_step(state, batch)))
    # Full forward on the (unadapted) merged params must agree — averaged
    # over the accumulation microbatches like eval_step does.
    merged = prog.merged_params(state["params"])
    from tpu_engine.train import lm_loss

    host_batch = jax.device_get(batch)
    direct = float(np.mean([
        float(lm_loss(
            tfm.forward(merged, mb, prog.model_config, compute_dtype=jnp.float32), mb
        ))
        for mb in host_batch
    ]))
    np.testing.assert_allclose(lora_eval, direct, rtol=1e-4)


def test_adapter_sharding_specs():
    prog = build_train_program(_cfg())
    state = prog.init(jax.random.PRNGKey(0))
    A = state["params"]["layers"]["q"]["A"]
    B = state["params"]["layers"]["q"]["B"]
    # A inherits (layers, embed) → (pipe, fsdp); rank never sharded
    # (trailing Nones are normalised away by PartitionSpec).
    assert A.sharding.spec == jax.sharding.PartitionSpec("pipe", "fsdp")
    assert B.sharding.spec == jax.sharding.PartitionSpec("pipe", None, "model")


def test_merge_lora_math():
    cfg = tfm.MODEL_CONFIGS["gpt-tiny"]
    base = tfm.init_params(jax.random.PRNGKey(0), cfg)
    from tpu_engine.lora import init_lora_params

    adapters = init_lora_params(jax.random.PRNGKey(1), cfg, 2, ("q",))
    adapters["layers"]["q"]["B"] = jnp.ones_like(adapters["layers"]["q"]["B"])
    merged = merge_lora(base, adapters, alpha=8.0, rank=2)
    expect = base["layers"]["q"]["kernel"] + 4.0 * jnp.einsum(
        "lir,lro->lio", adapters["layers"]["q"]["A"], adapters["layers"]["q"]["B"]
    )
    np.testing.assert_allclose(
        np.asarray(merged["layers"]["q"]["kernel"]), np.asarray(expect), rtol=1e-6
    )


def test_moe_expert_targets_rejected():
    with pytest.raises(ValueError, match="lora_targets"):
        build_train_program(_cfg(model_name="moe-tiny", lora_targets=("gate",)))


def test_lora_with_pipeline_rejected():
    with pytest.raises(ValueError, match="pipeline"):
        build_train_program(
            _cfg(mesh=MeshConfig(data=1, fsdp=2, pipe=2, model=2))
        )


def test_supervised_lora_job_checkpoints_and_resumes(tmp_path):
    ckpt = str(tmp_path / "ck")
    cfg = _cfg(total_steps=4, checkpoint_dir=ckpt, checkpoint_interval_steps=2)
    launcher = TPULauncher()
    res = launcher.launch(cfg, dry_run=False, block=True)
    job = launcher.get_job(res.job_id)
    assert job.describe()["status"] == "completed", job.describe()
    # Sampling uses the merged (base+adapter) weights.
    out = job.generate_sample([[1, 2, 3]], max_new_tokens=4)
    assert len(out[0]) == 7
    # Resume from the adapter-sized checkpoint.
    cfg2 = _cfg(total_steps=6, checkpoint_dir=ckpt, checkpoint_interval_steps=2)
    res2 = launcher.launch(cfg2, dry_run=False, block=True)
    d2 = launcher.get_job(res2.job_id).describe()
    assert d2["status"] == "completed", d2
    assert d2["resumed_from_step"] == 4


def test_supervised_lora_job_from_hf_base(tmp_path):
    transformers = pytest.importorskip("transformers")
    import torch

    torch.manual_seed(0)
    hf_cfg = transformers.LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
        tie_word_embeddings=False,
    )
    ckpt_dir = str(tmp_path / "hf_base")
    transformers.LlamaForCausalLM(hf_cfg).save_pretrained(ckpt_dir)

    cfg = _cfg(total_steps=3, lora_base_hf_checkpoint=ckpt_dir, seq_len=32)
    launcher = TPULauncher()
    res = launcher.launch(cfg, dry_run=False, block=True)
    job = launcher.get_job(res.job_id)
    d = job.describe()
    assert d["status"] == "completed", d
    # The program's model config came from the checkpoint, not model_name.
    assert job.program.model_config.vocab_size == 256
    assert job.program.model_config.d_model == 64
    out = job.generate_sample([[1, 2, 3]], max_new_tokens=3)
    assert len(out[0]) == 6


def test_lora_job_exports_merged_hf_checkpoint(tmp_path):
    transformers = pytest.importorskip("transformers")
    import torch

    cfg = _cfg(total_steps=3)
    launcher = TPULauncher()
    res = launcher.launch(cfg, dry_run=False, block=True)
    job = launcher.get_job(res.job_id)
    assert job.describe()["status"] == "completed"
    out, step = job.export_hf_checkpoint(str(tmp_path / "export"))
    assert step == 3
    reloaded = transformers.LlamaForCausalLM.from_pretrained(out).eval()
    # Reloaded HF logits must match our merged (base+adapter) forward.
    tokens = np.asarray([[1, 2, 3, 4, 5, 6]])
    with torch.no_grad():
        hf_logits = reloaded(torch.tensor(tokens)).logits.numpy()
    merged = job.program.merged_params(job._state["params"])
    ours = np.asarray(tfm.forward(
        merged, jnp.asarray(tokens, jnp.int32), job.program.model_config,
        compute_dtype=jnp.float32,
    ))
    np.testing.assert_allclose(ours, hf_logits, atol=2e-3, rtol=2e-3)


def test_gpt2_lora_targets():
    """GPT-2 LoRA: fc/proj are the MLP targets (not gate/up/down), and an
    adapter-only training step runs."""
    from tpu_engine.lora import target_shapes, validate_targets
    from tpu_engine.models import transformer as tfm

    cfg = tfm.MODEL_CONFIGS["gpt2-tiny"]
    shapes = target_shapes(cfg)
    assert "fc" in shapes and "proj" in shapes and "gate" not in shapes
    with pytest.raises(ValueError, match="lora_targets"):
        validate_targets(cfg, ("q", "gate"))

    tcfg = TPUTrainConfig(
        model_name="gpt2-tiny", sharding_stage=ShardingStage.FULL_PARTITIONING,
        mesh=MeshConfig(data=2, fsdp=4), micro_batch_size=1,
        gradient_accumulation_steps=2, seq_len=32, precision=Precision.FP32,
        learning_rate=1e-2, warmup_steps=2, total_steps=50,
        activation_checkpointing=True, lora_rank=4,
        lora_targets=("q", "v", "fc", "proj"),
    )
    prog = build_train_program(tcfg)
    state = prog.init(jax.random.PRNGKey(0))
    losses = []
    for _ in range(6):
        state, m = prog.step(state, prog.synthetic_batch(0))
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0], losses


# Compile-heavy module: excluded from the fast core run (pytest -m "not slow").
pytestmark = pytest.mark.slow
