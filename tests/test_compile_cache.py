"""Persistent XLA compilation cache (SURVEY.md §7 hard part c — warm-start
compiles bound resume MTTR)."""

import os

import jax
import jax.numpy as jnp

from tpu_engine import compile_cache


def test_enable_populates_cache(tmp_path, monkeypatch):
    d = str(tmp_path / "xla-cache")
    monkeypatch.setattr(compile_cache, "_enabled_dir", None)
    # force=True: the CPU test backend is normally excluded (see below).
    assert compile_cache.enable_compilation_cache(d, force=True) == d
    # Lower the threshold so this test's trivial compile qualifies.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    f = jax.jit(lambda x: jnp.tanh(x @ x).sum())
    f(jnp.ones((64, 64))).block_until_ready()
    assert os.listdir(d), "no cache entries written"
    # Idempotent re-enable keeps the directory.
    assert compile_cache.enable_compilation_cache(d, force=True) == d
    assert compile_cache.cache_dir_in_use() == d


def test_env_var_resolution(tmp_path, monkeypatch):
    d = str(tmp_path / "from-env")
    monkeypatch.setattr(compile_cache, "_enabled_dir", None)
    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", d)
    assert compile_cache.enable_compilation_cache(None, force=True) == d
    assert os.path.isdir(d)


def test_cpu_backend_is_excluded_by_default(tmp_path, monkeypatch):
    """XLA:CPU AOT reloads don't round-trip machine features (observed
    interpreter SIGILLs in the CPU test mesh) — the cache only enables on
    accelerator backends unless forced."""
    d = str(tmp_path / "cpu-skip")
    monkeypatch.setattr(compile_cache, "_enabled_dir", None)
    assert compile_cache.enable_compilation_cache(d) is None
    assert not os.path.exists(d)
    assert compile_cache.cache_dir_in_use() is None


def test_supervisor_enables_without_crashing(tmp_path, monkeypatch):
    """The supervised job's enable call is a safe no-op on the CPU backend
    (and points the cache at the configured dir on TPU)."""
    from tpu_engine.mesh_runtime import MeshConfig
    from tpu_engine.sharding import Precision, ShardingStage, TPUTrainConfig
    from tpu_engine.supervisor import TrainingJob

    d = str(tmp_path / "job-cache")
    monkeypatch.setattr(compile_cache, "_enabled_dir", None)
    cfg = TPUTrainConfig(
        model_name="gpt-tiny",
        sharding_stage=ShardingStage.DISABLED,
        mesh=MeshConfig(data=8),
        micro_batch_size=1,
        seq_len=16,
        precision=Precision.FP32,
        activation_checkpointing=False,
        compilation_cache_dir=d,
    )
    job = TrainingJob("cache-test", cfg, max_steps=1)
    job.start()
    job.join(timeout=300)
    assert job.status.value == "completed", job.error
    # CPU backend: skipped by design; the config threading is covered by
    # the force-path tests above.
    assert compile_cache.cache_dir_in_use() is None


def test_enable_after_prior_compile_still_caches(tmp_path, monkeypatch):
    """JAX memoizes a cache-unused verdict at the process's FIRST compile
    (``is_cache_used``): a worker that jitted anything before calling
    ``enable_compilation_cache`` — telemetry probe, eval_shape warm-up —
    would silently get no cache. Enabling must clear the latch."""
    from jax._src import compilation_cache as _cc

    jax.config.update("jax_compilation_cache_dir", None)
    _cc.reset_cache()  # pristine: no verdict yet
    # First compile with no dir configured latches the cache-OFF verdict.
    jax.jit(lambda x: x * 2)(jnp.ones(4)).block_until_ready()

    d = str(tmp_path / "late-enable")
    monkeypatch.setattr(compile_cache, "_enabled_dir", None)
    assert compile_cache.enable_compilation_cache(d, force=True) == d
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.jit(lambda x: jnp.cos(x @ x).sum())(
        jnp.ones((32, 32))
    ).block_until_ready()
    assert os.listdir(d), "cache-unused latch survived enable"
