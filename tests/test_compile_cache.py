"""Persistent XLA compilation cache (SURVEY.md §7 hard part c — warm-start
compiles bound resume MTTR): structured enable results, resolution order,
CPU-backend exclusion, the cache-unused latch, and explicit re-points."""

import os

import jax
import jax.numpy as jnp
import pytest

from tpu_engine import compile_cache, compile_index
from tpu_engine.compile_cache import CacheEnableResult


@pytest.fixture(autouse=True)
def _fresh_index():
    """Each test gets a pristine process-wide compile index — the enable
    path attaches the index sidecar to the cache dir as a side effect."""
    compile_index.reset_index()
    yield
    compile_index.reset_index()


def test_enable_populates_cache(tmp_path, monkeypatch):
    d = str(tmp_path / "xla-cache")
    monkeypatch.setattr(compile_cache, "_enabled_dir", None)
    # force=True: the CPU test backend is normally excluded (see below).
    res = compile_cache.enable_compilation_cache(d, force=True)
    assert res == d  # CacheEnableResult compares equal to its dir string
    assert res.enabled and res.changed and not res.repointed
    assert res.skipped_reason is None
    # Lower the threshold so this test's trivial compile qualifies.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    f = jax.jit(lambda x: jnp.tanh(x @ x).sum())
    f(jnp.ones((64, 64))).block_until_ready()
    assert os.listdir(d), "no cache entries written"
    # Idempotent re-enable keeps the directory and reports changed=False.
    again = compile_cache.enable_compilation_cache(d, force=True)
    assert again == d and again.enabled and not again.changed
    assert compile_cache.cache_dir_in_use() == d
    # Enabling attached the fleet index's sidecar next to the executables.
    assert compile_index.get_index().stats()["sidecar_path"] == os.path.join(
        d, compile_index.SIDECAR_NAME
    )


def test_env_var_resolution(tmp_path, monkeypatch):
    d = str(tmp_path / "from-env")
    monkeypatch.setattr(compile_cache, "_enabled_dir", None)
    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", d)
    assert compile_cache.enable_compilation_cache(None, force=True) == d
    assert os.path.isdir(d)


def test_resolution_order_explicit_beats_env_beats_default(tmp_path, monkeypatch):
    """Explicit argument > JAX_COMPILATION_CACHE_DIR > the local default."""
    explicit = str(tmp_path / "explicit")
    env = str(tmp_path / "env")
    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", env)
    # Explicit argument wins over the env var.
    monkeypatch.setattr(compile_cache, "_enabled_dir", None)
    assert compile_cache.enable_compilation_cache(explicit, force=True) == explicit
    # Env var wins over the default.
    monkeypatch.setattr(compile_cache, "_enabled_dir", None)
    assert compile_cache.enable_compilation_cache(None, force=True) == env
    # Neither → the local default (no mkdir assertion: HOME is real).
    monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR")
    monkeypatch.setattr(compile_cache, "_enabled_dir", None)
    assert (
        compile_cache.enable_compilation_cache(None, force=True)
        == compile_cache.DEFAULT_CACHE_DIR
    )


def test_cpu_backend_is_excluded_by_default(tmp_path, monkeypatch):
    """XLA:CPU AOT reloads don't round-trip machine features (observed
    interpreter SIGILLs in the CPU test mesh) — the cache only enables on
    accelerator backends unless forced. The skip is a structured result
    now, falsy and naming its reason."""
    d = str(tmp_path / "cpu-skip")
    monkeypatch.setattr(compile_cache, "_enabled_dir", None)
    res = compile_cache.enable_compilation_cache(d)
    assert isinstance(res, CacheEnableResult)
    assert not res  # nothing enabled → falsy
    assert res == None  # noqa: E711 — dir comparison, the legacy contract
    assert res.skipped_reason == "cpu-backend"
    assert not os.path.exists(d)
    assert compile_cache.cache_dir_in_use() is None


def test_cpu_skip_preserves_prior_enable(tmp_path, monkeypatch):
    """A later un-forced call on CPU must not disturb an earlier forced
    enable: the result still reports the active dir and stays truthy."""
    d = str(tmp_path / "forced")
    monkeypatch.setattr(compile_cache, "_enabled_dir", None)
    assert compile_cache.enable_compilation_cache(d, force=True) == d
    res = compile_cache.enable_compilation_cache(str(tmp_path / "other"))
    assert res.skipped_reason == "cpu-backend"
    assert res and res == d  # prior enable intact
    assert compile_cache.cache_dir_in_use() == d


def test_explicit_repoint_resets_and_flags(tmp_path, monkeypatch, caplog):
    """Enabling with a *different* explicit dir is a deliberate re-point:
    new executables land in the new dir, the transition is logged, and the
    result carries repointed=True. Old entries are not migrated."""
    a = str(tmp_path / "cache-a")
    b = str(tmp_path / "cache-b")
    monkeypatch.setattr(compile_cache, "_enabled_dir", None)
    assert compile_cache.enable_compilation_cache(a, force=True) == a
    with caplog.at_level("WARNING", logger=compile_cache.log.name):
        res = compile_cache.enable_compilation_cache(b, force=True)
    assert res == b and res.changed and res.repointed
    assert compile_cache.cache_dir_in_use() == b
    assert any("re-pointed" in r.message for r in caplog.records)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.jit(lambda x: jnp.sinh(x @ x).sum())(
        jnp.ones((48, 48))
    ).block_until_ready()
    assert os.listdir(b), "post-re-point compile did not land in the new dir"


def test_supervisor_enables_without_crashing(tmp_path, monkeypatch):
    """The supervised job's enable call is a safe no-op on the CPU backend
    (and points the cache at the configured dir on TPU)."""
    from tpu_engine.mesh_runtime import MeshConfig
    from tpu_engine.sharding import Precision, ShardingStage, TPUTrainConfig
    from tpu_engine.supervisor import TrainingJob

    d = str(tmp_path / "job-cache")
    monkeypatch.setattr(compile_cache, "_enabled_dir", None)
    cfg = TPUTrainConfig(
        model_name="gpt-tiny",
        sharding_stage=ShardingStage.DISABLED,
        mesh=MeshConfig(data=8),
        micro_batch_size=1,
        seq_len=16,
        precision=Precision.FP32,
        activation_checkpointing=False,
        compilation_cache_dir=d,
    )
    job = TrainingJob("cache-test", cfg, max_steps=1)
    job.start()
    job.join(timeout=300)
    assert job.status.value == "completed", job.error
    # CPU backend: skipped by design; the config threading is covered by
    # the force-path tests above.
    assert compile_cache.cache_dir_in_use() is None


def test_enable_after_prior_compile_still_caches(tmp_path, monkeypatch):
    """JAX memoizes a cache-unused verdict at the process's FIRST compile
    (``is_cache_used``): a worker that jitted anything before calling
    ``enable_compilation_cache`` — telemetry probe, eval_shape warm-up —
    would silently get no cache. Enabling must clear the latch."""
    from jax._src import compilation_cache as _cc

    jax.config.update("jax_compilation_cache_dir", None)
    _cc.reset_cache()  # pristine: no verdict yet
    # First compile with no dir configured latches the cache-OFF verdict.
    jax.jit(lambda x: x * 2)(jnp.ones(4)).block_until_ready()

    d = str(tmp_path / "late-enable")
    monkeypatch.setattr(compile_cache, "_enabled_dir", None)
    assert compile_cache.enable_compilation_cache(d, force=True) == d
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.jit(lambda x: jnp.cos(x @ x).sum())(
        jnp.ones((32, 32))
    ).block_until_ready()
    assert os.listdir(d), "cache-unused latch survived enable"
