"""Fleet manager: health classification, aggregation, selection, test seams."""

import json

from tpu_engine.tpu_manager import TPUDevice, TPUHealthStatus, TPUManager


def _chip(i=0, **kw):
    base = {
        "index": i,
        "device_kind": "TPU v5e",
        "hbm_total_gb": 16.0,
        "hbm_used_gb": 4.0,
        "duty_cycle_pct": 50.0,
        "temperature_c": 50.0,
        "power_draw_w": 100.0,
        "power_limit_w": 192.0,
    }
    base.update(kw)
    return base


def test_healthy_chip():
    mgr = TPUManager()
    (dev,) = mgr.parse_metrics([_chip()])
    assert dev.health_status == TPUHealthStatus.HEALTHY
    assert dev.is_available
    assert dev.hbm_free_gb == 12.0


def test_temperature_thresholds():
    mgr = TPUManager()
    warn, crit = mgr.parse_metrics([_chip(temperature_c=82.0), _chip(1, temperature_c=91.0)])
    assert warn.health_status == TPUHealthStatus.WARNING
    assert crit.health_status == TPUHealthStatus.CRITICAL
    assert not crit.is_available


def test_hbm_thresholds():
    mgr = TPUManager()
    warn, crit = mgr.parse_metrics(
        [_chip(hbm_used_gb=14.0), _chip(1, hbm_used_gb=15.5)]  # 87.5%, 96.9%
    )
    assert warn.health_status == TPUHealthStatus.WARNING
    assert crit.health_status == TPUHealthStatus.CRITICAL


def test_duty_and_power_warnings():
    mgr = TPUManager()
    duty, power = mgr.parse_metrics(
        [_chip(duty_cycle_pct=96.0), _chip(1, power_draw_w=180.0)]  # 93.75% of 192
    )
    assert duty.health_status == TPUHealthStatus.WARNING
    assert power.health_status == TPUHealthStatus.WARNING


def test_availability_rules():
    mgr = TPUManager()
    busy_mem, busy_duty = mgr.parse_metrics(
        [_chip(hbm_used_gb=13.0), _chip(1, duty_cycle_pct=92.0)]  # 81.25% HBM; 92% duty
    )
    assert not busy_mem.is_available
    assert not busy_duty.is_available


def test_fleet_aggregation_and_alert_rollup():
    fleet = TPUManager.get_mock_fleet()
    assert fleet.total_devices == 8
    assert fleet.available_devices == 7
    assert fleet.total_hbm_gb == 128.0
    assert any("chip 5" in a for a in fleet.fleet_alerts)
    assert fleet.average_temperature_c is not None


def test_no_devices_available_banner():
    mgr = TPUManager()
    fleet = mgr.get_fleet_status(metrics=[_chip(hbm_used_gb=15.8)])
    assert "No TPU devices available for new work" in fleet.fleet_alerts


def test_injectable_json_telemetry():
    mgr = TPUManager()
    raw = json.dumps({"devices": [_chip(), _chip(1, hbm_used_gb=2.0)]})
    fleet = mgr.get_fleet_status(metrics_json=raw)
    assert fleet.total_devices == 2


def test_select_best_device_prefers_free_hbm():
    mgr = TPUManager()
    metrics = [_chip(0, hbm_used_gb=8.0), _chip(1, hbm_used_gb=2.0), _chip(2, hbm_used_gb=15.8)]
    best = mgr.select_best_device(metrics=metrics)
    assert best.index == 1
    assert mgr.select_best_device(min_free_hbm_gb=15.0, metrics=metrics) is None


def test_live_runtime_fleet_on_cpu_backend():
    # On the CPU test backend the manager still produces a coherent fleet.
    fleet = TPUManager().get_fleet_status()
    assert fleet.total_devices == 8
    assert all(d.platform == "cpu" for d in fleet.devices)


def test_fleet_surfaces_foreign_chip_holder():
    """A chip held by a pid this control plane never launched (tpu-info's
    chips-table PID column) appears in the device's process list with
    foreign=True; our own pid reads foreign=False with a resolved name
    (reference foreign-process table, gpu_manager.py:174-184)."""
    import os

    from tpu_engine import telemetry

    me = os.getpid()
    foreign = 999_999_999  # no such pid → name stays None
    canned = f"""\
TPU Chips
│ /dev/accel0 │ TPU v5 lite │ 1 │ {foreign} │
│ /dev/accel1 │ TPU v5 lite │ 1 │ {me} │
"""
    telemetry.set_sources(
        [telemetry.TpuInfoCliSource(runner=lambda: canned)]
    )
    try:
        fleet = TPUManager().get_fleet_status()
        d0, d1 = fleet.devices[0], fleet.devices[1]
        assert [p.pid for p in d0.processes] == [foreign]
        assert d0.processes[0].foreign is True
        assert d0.processes[0].name is None
        assert [p.pid for p in d1.processes] == [me]
        assert d1.processes[0].foreign is False
        assert d1.processes[0].name  # /proc/<self>/comm resolves
        assert not fleet.devices[2].processes  # no PID row → no holder
    finally:
        telemetry.set_sources(None)


def test_fleet_cli_renders_table(capsys):
    from tpu_engine.tpu_manager import main

    assert main(["--mock"]) == 0
    out = capsys.readouterr().out
    assert "devices: 8 (7 available)" in out
    assert "warning" in out
    assert any(line.startswith("!") for line in out.splitlines())
    assert main(["--mock", "--json"]) == 0
    assert '"total_devices":8' in capsys.readouterr().out.replace(" ", "")


# ---------------------------------------------------------------------------
# _assess_health edge cases: corrupt / degenerate telemetry, recovery
# ---------------------------------------------------------------------------


def test_nan_telemetry_is_sanitized_not_propagated():
    mgr = TPUManager()
    nan = float("nan")
    (dev,) = mgr.parse_metrics([_chip(duty_cycle_pct=nan, hbm_used_gb=nan)])
    # Corrupt fields are discarded, never classified against thresholds.
    assert dev.duty_cycle_pct is None
    assert dev.hbm_used_gb == 0.0
    assert dev.hbm_utilization_pct == 0.0
    assert any("non-finite telemetry" in a for a in dev.alerts)
    # Not *known* healthy, but not known bad → stays schedulable.
    assert dev.health_status == TPUHealthStatus.UNKNOWN
    assert dev.is_available


def test_nan_chip_does_not_poison_fleet_aggregates():
    import math

    mgr = TPUManager()
    fleet = mgr.get_fleet_status(
        metrics=[_chip(0), _chip(1, hbm_used_gb=float("nan"),
                                 temperature_c=float("inf"))]
    )
    assert math.isfinite(fleet.used_hbm_gb)
    assert fleet.average_temperature_c is None or math.isfinite(
        fleet.average_temperature_c
    )
    assert fleet.available_devices == 2  # UNKNOWN chip stays eligible


def test_zero_and_missing_hbm_never_divide_or_alert():
    mgr = TPUManager()
    zero, missing = mgr.parse_metrics([
        _chip(0, hbm_total_gb=0.0, hbm_used_gb=0.0),
        {"index": 1, "device_kind": "TPU v5e"},  # no HBM keys at all
    ])
    for dev in (zero, missing):
        assert dev.hbm_utilization_pct == 0.0
        assert not any("HBM" in a for a in dev.alerts)
    assert missing.health_status == TPUHealthStatus.HEALTHY


def test_duplicate_indices_are_parsed_independently():
    mgr = TPUManager()
    devs = mgr.parse_metrics([_chip(3), _chip(3, temperature_c=91.0)])
    assert [d.index for d in devs] == [3, 3]
    assert devs[0].health_status == TPUHealthStatus.HEALTHY
    assert devs[1].health_status == TPUHealthStatus.CRITICAL


def test_health_recovers_when_telemetry_clears():
    mgr = TPUManager()
    (dev,) = mgr.parse_metrics([_chip(temperature_c=91.0)])
    assert dev.health_status == TPUHealthStatus.CRITICAL
    # Same chip, next poll: back under every threshold → fully HEALTHY.
    (dev,) = mgr.parse_metrics([_chip(temperature_c=50.0)])
    assert dev.health_status == TPUHealthStatus.HEALTHY
    assert dev.alerts == []
    assert dev.is_available


def test_injected_chip_faults_overlay_fleet_snapshot():
    from tpu_engine import faults
    from tpu_engine.faults import FaultKind, FaultPlan, FaultSpec

    mgr = TPUManager()
    inj = faults.activate(FaultPlan(specs=[
        FaultSpec(kind=FaultKind.CHIP_UNHEALTHY, at_step=1, device_index=0),
        FaultSpec(kind=FaultKind.TELEMETRY_NAN, at_step=1, device_index=1),
    ]))
    try:
        inj.observe_step(1)
        fleet = mgr.get_fleet_status(metrics=[_chip(0), _chip(1), _chip(2)])
        bad, nan, ok = fleet.devices
        assert bad.health_status == TPUHealthStatus.CRITICAL
        assert any("injected fault: chip-unhealthy" in a for a in bad.alerts)
        assert nan.health_status == TPUHealthStatus.UNKNOWN
        assert any("non-finite telemetry" in a for a in nan.alerts)
        assert ok.health_status == TPUHealthStatus.HEALTHY
    finally:
        faults.clear_active()
