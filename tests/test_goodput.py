"""Goodput ledger: decomposition invariants, SLO burn-rate, chaos e2e.

Property-style checks on :mod:`tpu_engine.goodput`:

- the boundary-sweep decomposition's categories are disjoint and sum to
  the wall window *by construction* — asserted over randomized overlap
  soups, not one hand-picked trace;
- preempt → requeue → re-admit boundaries account drain and queue wait
  without double counting;
- the incremental ledger is idempotent (refresh-per-scrape == one-shot);
- the multi-window burn-rate alerter escalates ok → warning → page on a
  degrading history and fires structured events on the recorder's
  ``fleet`` timeline;
- the chaos benchmark's end-to-end account sums to its wall clock within
  1% and its alert progression is deterministic, with the alerts visible
  in both the ``/api/v1/goodput`` payload and the Perfetto export.
"""

import asyncio
import json
import random

from tpu_engine.goodput import (
    CATEGORIES,
    FLEET_TRACE_ID,
    GoodputLedger,
    SLOBurnRateAlerter,
    decompose_trace,
    set_alerter,
    set_ledger,
)
from tpu_engine.scheduler import WAIT_BUCKETS_S, _observe_hist
from tpu_engine.telemetry import DerivedDutySource
from tpu_engine.tracing import FlightRecorder

NOW = 1_000_000.0


def _rec():
    return FlightRecorder(clock=lambda: NOW)


# ---------------------------------------------------------------------------
# decompose_trace invariants
# ---------------------------------------------------------------------------


def _assert_invariants(d, wall):
    assert set(d["categories"]) == set(CATEGORIES)
    for c, v in d["categories"].items():
        assert v >= -1e-9, f"negative {c}: {v}"
    total = sum(d["categories"].values())
    assert abs(total - wall) < 1e-6 * max(wall, 1.0), (
        f"sum {total} != wall {wall}"
    )
    assert abs(d["sum_error_s"]) < 1e-6 * max(wall, 1.0)


def test_decompose_sum_to_wall_randomized_overlap():
    """Fuzz: arbitrary soups of overlapping overlay spans, fault events,
    and attempt windows still sum to the wall window exactly."""
    kinds = [
        "compile", "checkpoint_save", "checkpoint_restore",
        "emergency_save", "admission", "fault", "final_save",
    ]
    for seed in range(25):
        rng = random.Random(seed)
        rec = _rec()
        tid = rec.new_trace_id()
        wall = rng.uniform(50.0, 500.0)
        root = rec.start_span("job:fuzz", kind="job", trace_id=tid, t0=0.0)
        n_attempts = rng.randint(0, 3)
        cursor = rng.uniform(0, wall * 0.1)
        for _ in range(n_attempts):
            a0 = cursor
            a1 = min(wall, a0 + rng.uniform(1.0, wall / 2))
            rec.record_span(
                "attempt", kind="attempt", trace_id=tid, t0=a0, t1=a1
            )
            cursor = a1 + rng.uniform(0.0, wall * 0.1)
        for _ in range(rng.randint(0, 12)):
            k = rng.choice(kinds)
            t0 = rng.uniform(-10.0, wall)
            rec.record_span(
                k, kind=k, trace_id=tid, t0=t0,
                t1=t0 + rng.uniform(0.0, wall / 3),
            )
        for _ in range(rng.randint(0, 4)):
            rec.event(
                "host_slow", kind="fault", trace_id=tid,
                ts=rng.uniform(0, wall),
                attrs={"penalty_s": rng.uniform(0, 20.0)},
            )
        root.end(t1=wall)
        d = decompose_trace(rec, tid)
        assert d["wall_s"] == wall
        _assert_invariants(d, wall)


def test_decompose_overlay_priority_disjoint():
    """Overlapping compile and checkpoint spans: every second is charged
    to exactly one category, the higher-priority overlay winning."""
    rec = _rec()
    tid = rec.new_trace_id()
    root = rec.start_span("job:x", kind="job", trace_id=tid, t0=0.0)
    rec.record_span("compile", kind="compile", trace_id=tid, t0=10, t1=30)
    rec.record_span(
        "save", kind="checkpoint_save", trace_id=tid, t0=20, t1=40
    )
    root.end(t1=100.0)
    d = decompose_trace(rec, tid)
    _assert_invariants(d, 100.0)
    c = d["categories"]
    assert abs(c["compile"] - 10.0) < 1e-9          # [10,20) only
    assert abs(c["checkpoint_save"] - 20.0) < 1e-9  # [20,40) wins overlap
    assert abs(c["productive"] - 70.0) < 1e-9


def test_preempt_requeue_boundaries():
    """Preempt drain runs to the end of the attempt; the requeue's queue
    wait runs to the end of the next admission pass; no double counting."""
    rec = _rec()
    tid = rec.new_trace_id()
    root = rec.start_span("job:p", kind="job", trace_id=tid, t0=0.0)
    rec.record_span("attempt-1", kind="attempt", trace_id=tid, t0=0, t1=40)
    rec.event("preempt", kind="preempt_drain", trace_id=tid, ts=35.0)
    rec.event("requeue", kind="scheduler", trace_id=tid, ts=40.0)
    rec.record_span(
        "admission", kind="admission", trace_id=tid, t0=58, t1=60
    )
    rec.record_span("attempt-2", kind="attempt", trace_id=tid, t0=60, t1=100)
    root.end(t1=100.0)
    d = decompose_trace(rec, tid)
    _assert_invariants(d, 100.0)
    c = d["categories"]
    assert abs(c["productive"] - 75.0) < 1e-9    # [0,35) + [60,100)
    assert abs(c["preempt_drain"] - 5.0) < 1e-9  # [35,40)
    assert abs(c["queue_wait"] - 20.0) < 1e-9    # [40,60)
    assert c["idle_unknown"] == 0.0


def test_attempt_step_s_cap_spills_to_idle():
    """The supervisor's measured per-step total caps productive time; the
    untraced remainder is idle/unknown, not goodput."""
    rec = _rec()
    tid = rec.new_trace_id()
    root = rec.start_span("job:s", kind="job", trace_id=tid, t0=0.0)
    rec.record_span(
        "attempt-1", kind="attempt", trace_id=tid, t0=0, t1=100,
        attrs={"step_s": 60.0},
    )
    root.end(t1=100.0)
    d = decompose_trace(rec, tid)
    _assert_invariants(d, 100.0)
    assert abs(d["categories"]["productive"] - 60.0) < 1e-9
    assert abs(d["categories"]["idle_unknown"] - 40.0) < 1e-9


def test_shrink_degraded_capacity_split():
    """After a shrink admission, the running baseline splits into
    productive × mesh/full plus the shrink-degraded deficit."""
    rec = _rec()
    tid = rec.new_trace_id()
    root = rec.start_span(
        "job:d", kind="job", trace_id=tid, t0=0.0, attrs={"n_chips": 8}
    )
    rec.record_span(
        "shrink_admit", kind="admission", trace_id=tid, t0=49, t1=50,
        attrs={"mesh": 4},
    )
    root.end(t1=100.0)
    d = decompose_trace(rec, tid)
    _assert_invariants(d, 100.0)
    c = d["categories"]
    assert abs(c["queue_wait"] - 1.0) < 1e-9          # the admission pass
    assert abs(c["productive"] - (49 + 50 * 0.5)) < 1e-9
    assert abs(c["shrink_degraded"] - 25.0) < 1e-9


def test_async_checkpoint_save_not_charged():
    """blocking=False saves overlap training — they must not displace
    productive time."""
    rec = _rec()
    tid = rec.new_trace_id()
    root = rec.start_span("job:a", kind="job", trace_id=tid, t0=0.0)
    rec.record_span(
        "save", kind="checkpoint_save", trace_id=tid, t0=10, t1=30,
        attrs={"blocking": False},
    )
    root.end(t1=100.0)
    d = decompose_trace(rec, tid)
    assert d["categories"]["checkpoint_save"] == 0.0
    assert abs(d["categories"]["productive"] - 100.0) < 1e-9


def test_host_slow_reconciles_with_injector_counter():
    """The invariant promised next to ``host_slow_penalty_s_total`` in
    faults.py: every stall second the injector accrues must land in the
    ledger's host_slow category. Drive a seeded HOST_SLOW plan through
    the real ``take_host_slow`` seam, mirror each consumed penalty as the
    supervisor's ``kind="fault"`` + ``penalty_s`` event, and reconcile
    the decomposition against the injector's counter exactly."""
    from tpu_engine.faults import (
        FaultInjector,
        FaultKind,
        FaultPlan,
        FaultSpec,
    )

    plan = FaultPlan(seed=3, specs=[
        FaultSpec(kind=FaultKind.HOST_SLOW, at_step=5, device_index=0,
                  slow_s=0.5, count=3),
        FaultSpec(kind=FaultKind.HOST_SLOW, at_step=40, device_index=1,
                  slow_s=0.75),
    ])
    inj = FaultInjector(plan)
    inj.arm()
    rec = _rec()
    tid = rec.new_trace_id()
    root = rec.start_span("job:h", kind="job", trace_id=tid, t0=0.0)
    rec.record_span("attempt-1", kind="attempt", trace_id=tid, t0=0, t1=100)
    t = 0.0
    for step in range(1, 101):
        t += 1.0  # one virtual second per step keeps penalties disjoint
        spec = inj.take_host_slow(step)
        if spec is not None:
            rec.event(
                "host-slow", kind="fault", trace_id=tid, ts=t,
                attrs={"step": step, "penalty_s": float(spec.slow_s)},
            )
    root.end(t1=100.0)
    assert abs(inj.host_slow_penalty_s_total - (3 * 0.5 + 0.75)) < 1e-9
    d = decompose_trace(rec, tid)
    _assert_invariants(d, 100.0)
    assert abs(
        d["categories"]["host_slow"] - inj.host_slow_penalty_s_total
    ) < 1e-6


# ---------------------------------------------------------------------------
# GoodputLedger
# ---------------------------------------------------------------------------


def _busy_trace(rec):
    tid = rec.new_trace_id()
    root = rec.start_span("job:l", kind="job", trace_id=tid, t0=0.0)
    rec.record_span("compile", kind="compile", trace_id=tid, t0=0, t1=20)
    rec.record_span("save", kind="checkpoint_save", trace_id=tid,
                    t0=100, t1=110)
    root.end(t1=200.0)
    return tid


def test_ledger_incremental_matches_one_shot():
    """refresh-per-scrape accounting == a single final accounting: the
    per-trace cursor makes repeated passes idempotent."""
    rec = _rec()
    tid = _busy_trace(rec)

    one = GoodputLedger(clock=lambda: 200.0)
    one.track(tid, tenant="t", workload="w")
    one.finalize(rec, tid, now=200.0)

    inc = GoodputLedger(clock=lambda: 200.0)
    inc.track(tid, tenant="t", workload="w")
    for now in (50.0, 120.0, 120.0, 200.0):  # repeated + stalled scrapes
        inc.refresh(rec, now=now)
    inc.finalize(rec, tid, now=200.0)

    a, b = one.snapshot(), inc.snapshot()
    for c in CATEGORIES:
        assert abs(a["categories"][c] - b["categories"][c]) < 1e-6, c
    assert a["wall_s"] == b["wall_s"]
    assert b["traces_accounted"] == 1
    assert b["invariant_violations"] == 0
    assert b["by_tenant"]["t"]["compile"] == a["by_tenant"]["t"]["compile"]


def test_ledger_note_and_window_fraction():
    """Explicit-timestamp accounting feeds the same history rings the
    burn-rate windows read."""
    led = GoodputLedger(clock=lambda: 120.0, bucket_s=60.0)
    led.note("productive", 60.0, ts=60.0)
    led.note("queue_wait", 60.0, ts=120.0)
    assert abs(led.window_fraction(120.0, now=120.0) - 0.5) < 1e-9
    # Only the second bucket in view -> all queue wait.
    assert led.window_fraction(60.0, now=120.0) < 0.01
    snap = led.snapshot()
    assert snap["wall_s"] == 120.0
    assert snap["goodput_fraction"] == 0.5


def test_ledger_tenant_overflow_folds_to_other():
    led = GoodputLedger(clock=lambda: 10.0, max_tenants=2)
    for i in range(4):
        led.note("productive", 1.0, tenant=f"t{i}", ts=float(i + 1))
    snap = led.snapshot()
    assert set(snap["by_tenant"]) == {"t0", "t1", "~other"}
    assert snap["by_tenant"]["~other"]["productive"] == 2.0


# ---------------------------------------------------------------------------
# SLO burn-rate alerting
# ---------------------------------------------------------------------------


def test_alerter_escalates_and_fires_fleet_events():
    """A degrading goodput history walks ok → warning → page; each
    transition lands a structured slo_alert event on the fleet timeline;
    recovery resolves back down."""
    rec = _rec()
    led = GoodputLedger(clock=lambda: 0.0, bucket_s=60.0, history_buckets=512)
    al = SLOBurnRateAlerter(
        led, goodput_target=0.9, short_window_s=120.0, long_window_s=360.0,
        warning_burn=1.5, page_burn=3.0, recorder=rec, clock=lambda: 0.0,
    )
    seen = ["ok"]

    def feed_and_eval(t, productive_frac):
        led.note("productive", 60.0 * productive_frac, ts=t)
        if productive_frac < 1.0:
            led.note("queue_wait", 60.0 * (1 - productive_frac), ts=t)
        out = al.evaluate(now=t)
        if out["goodput"]["state"] != seen[-1]:
            seen.append(out["goodput"]["state"])

    t = 0.0
    for frac in [1.0] * 6 + [0.8] * 6 + [0.3] * 6 + [1.0] * 8:
        t += 60.0
        feed_and_eval(t, frac)
    assert seen[:3] == ["ok", "warning", "page"]
    assert seen[-1] == "ok"  # the clean tail drains the windows
    alerts = [e for e in rec.events(limit=0) if e["kind"] == "slo_alert"]
    assert alerts and all(e["trace_id"] == FLEET_TRACE_ID for e in alerts)
    assert alerts[0]["attrs"]["severity"] == "warning"
    assert alerts[0]["attrs"]["short_burn"] >= 1.5
    assert al.alerts_total["warning"] >= 1
    assert al.alerts_total["page"] >= 1


def test_alerter_serving_p99_slo():
    led = GoodputLedger(clock=lambda: 0.0)
    al = SLOBurnRateAlerter(
        led, p99_slo_ms=100.0, serving_target=0.75,
        short_window_s=60.0, long_window_s=120.0, clock=lambda: 0.0,
    )
    for i in range(20):
        al.observe_p99(500.0, ts=float(i))  # every sample breaches
    out = al.evaluate(now=20.0)
    assert out["serving_p99"]["state"] == "page"
    assert out["serving_p99"]["short_burn"] == 4.0  # 1.0 bad / 0.25 budget
    al2_state = al.evaluate(now=500.0)  # samples age out of both windows
    assert al2_state["serving_p99"]["state"] == "ok"


def test_counter_events_render_as_perfetto_counter_track():
    rec = _rec()
    tid = rec.new_trace_id()
    rec.record_span("job:c", kind="job", trace_id=tid, t0=0.0, t1=1.0)
    rec.counter("goodput_burn", {"burn": 2.5, "label": "oops"},  # non-numeric dropped
                trace_id=tid, ts=0.5)
    doc = rec.export_chrome_trace()
    counters = [e for e in doc["traceEvents"] if e.get("ph") == "C"]
    assert len(counters) == 1
    assert counters[0]["name"] == "goodput_burn"
    assert counters[0]["args"] == {"burn": 2.5}


# ---------------------------------------------------------------------------
# chaos end-to-end + the /api/v1/goodput payload
# ---------------------------------------------------------------------------


def test_chaos_breakdown_sums_and_alerts_everywhere():
    """The chaos virtual-clock account: categories sum to wall within 1%,
    productive equals the analytic 500 step-seconds, the alerter walks
    ok → warning → page deterministically, and the alerts/counters are
    visible in the Perfetto export of the same recorder."""
    from benchmarks.chaos import TOTAL_STEPS, STEP_TIME_S, run_trace

    rec = FlightRecorder(clock=lambda: 0.0)
    trace = run_trace(seed=0, recorder=rec)
    gp = trace["goodput"]
    assert gp["sum_error_pct"] < 1.0
    assert abs(gp["breakdown_s"]["productive"]
               - TOTAL_STEPS * STEP_TIME_S) < 1.0
    assert gp["slo"]["progression"][:3] == ["ok", "warning", "page"]
    assert gp["slo"]["alert_count"] >= 2
    doc = rec.export_chrome_trace()
    names = [str(e.get("name", "")) for e in doc["traceEvents"]]
    assert any(n.startswith("slo_alert:goodput:warning") for n in names)
    assert any(n.startswith("slo_alert:goodput:page") for n in names)
    assert any(e.get("ph") == "C" for e in doc["traceEvents"])


def test_goodput_router_payload():
    """GET /api/v1/goodput returns the ledger snapshot + SLO view with
    the recent alerts inline (the handler ignores the request object)."""
    from backend.routers.goodput import goodput_view

    rec = _rec()
    tid = _busy_trace(rec)
    led = GoodputLedger(clock=lambda: 200.0)
    led.track(tid, tenant="api", workload="training")
    al = SLOBurnRateAlerter(led, recorder=rec, clock=lambda: 200.0)
    al._transition("goodput", "warning", {"short_burn": 2.0}, now=150.0)
    set_ledger(led)
    set_alerter(al)
    try:
        import tpu_engine.tracing as tracing_mod

        old_rec = tracing_mod.get_recorder()
        tracing_mod.set_recorder(rec)
        try:
            resp = asyncio.run(goodput_view(None))
        finally:
            tracing_mod.set_recorder(old_rec)
        body = json.loads(resp.text)
        assert body["categories"] == list(CATEGORIES)
        assert body["refreshed_traces"] == 1
        assert body["ledger"]["by_tenant"]["api"]["compile"] > 0
        assert body["slo"]["goodput"]["target"] == al.goodput_target
        # The injected warning is in the alert history; the handler's own
        # evaluate pass then correctly resolves it (burns don't support
        # it), so the resolve transition is recorded too.
        alerts = body["slo"]["recent_alerts"]
        assert any(a["severity"] == "warning" for a in alerts)
        assert alerts[-1]["previous"] == "warning"
    finally:
        set_ledger(None)
        set_alerter(None)


# ---------------------------------------------------------------------------
# satellites: wait histograms + telemetry staleness
# ---------------------------------------------------------------------------


def test_wait_histogram_cumulative_and_in_stats():
    hist = {b: 0 for b in WAIT_BUCKETS_S}
    for v in (0.05, 0.3, 2.0, 100.0, 10_000.0):
        _observe_hist(hist, v)
    counts = [hist[b] for b in WAIT_BUCKETS_S]
    assert counts == sorted(counts), "buckets must be cumulative"
    assert hist[0.1] == 1 and hist[1800.0] == 4  # 10k only in +Inf

    from tpu_engine.scheduler import FleetScheduler

    s = FleetScheduler(poll_interval_s=0.05)
    try:
        stats = s.stats()
        h = stats["admission_wait_histogram"]
        assert set(h["buckets"]) == {str(b) for b in WAIT_BUCKETS_S}
        assert h["count"] == 0 and h["sum"] == 0.0
    finally:
        s.shutdown()


def test_telemetry_staleness_surface():
    src = DerivedDutySource(window=4, max_age_s=0.0)
    fresh = src.staleness()
    assert fresh["last_sample_age_s"] is None
    assert fresh["scopes"] == 0 and fresh["dropped_stale_total"] == 0

    src.observe(0.5, 1.0, device_ids=[0, 1])
    st = src.staleness()
    assert st["last_sample_age_s"] is not None and st["last_sample_age_s"] < 5
    assert st["scope_ages_s"].keys() == {"0,1"}
    # max_age_s=0 -> the scope is already stale; sampling drops it and
    # counts the drop.
    assert src.sample(n_chips=2) is None
    assert src.staleness()["dropped_stale_total"] == 1
    assert src.staleness()["last_sample_age_s"] is not None  # survives drop
