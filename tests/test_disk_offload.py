"""Disk-tier optimizer offload (``tpu_engine/disk_offload.py``): the
NVMe-analogue spill. Parity with the in-memory optax path is the
load-bearing pin — the host AdamW must implement the exact update chain
(clip → scale_by_adam → decayed weights → -lr) or disk-tier training
silently trains a different model."""

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_engine.mesh_runtime import MeshConfig
from tpu_engine.sharding import (
    OffloadDevice, Precision, ShardingStage, TPUTrainConfig,
)
from tpu_engine.train import build_train_program


def _cfg(spill_dir=None, **kw):
    base = dict(
        model_name="gpt-tiny",
        mesh=MeshConfig(),
        micro_batch_size=2,
        gradient_accumulation_steps=2,
        seq_len=16,
        precision=Precision.FP32,
        param_dtype=Precision.FP32,
        total_steps=8,
        warmup_steps=2,
        activation_checkpointing=False,
        learning_rate=1e-2,
        weight_decay=0.1,
    )
    if spill_dir is not None:
        base.update(
            optimizer_offload=OffloadDevice.DISK,
            optimizer_spill_dir=str(spill_dir),
        )
    base.update(kw)
    return TPUTrainConfig(**base)


def _run(prog, steps, state=None, start=0):
    if state is None:
        state = prog.init(jax.random.PRNGKey(prog.config.seed))
    losses = []
    for i in range(start, start + steps):
        state, metrics = prog.step(state, prog.synthetic_batch(i))
        losses.append(float(metrics["loss"]))
    return state, losses


def test_disk_tier_matches_in_memory_adamw(tmp_path):
    """Step-for-step parity: same losses, same final params (fp32, so the
    only drift is float rounding in the host-vs-device update order)."""
    ref_prog = build_train_program(_cfg())
    ref_state, ref_losses = _run(ref_prog, 4)

    prog = build_train_program(_cfg(tmp_path / "spill"))
    state, losses = _run(prog, 4)

    np.testing.assert_allclose(losses, ref_losses, rtol=1e-5)
    ref_flat = jax.tree.leaves(ref_state["params"])
    got_flat = jax.tree.leaves(state["params"])
    for r, g in zip(ref_flat, got_flat):
        np.testing.assert_allclose(
            np.asarray(g, np.float32), np.asarray(r, np.float32),
            rtol=2e-5, atol=2e-6,
        )
    # The whole point: no optimizer state on device, params at compute dtype.
    assert "opt_state" not in state
    assert state["params"]["lm_head"]["kernel"].dtype == jnp.float32


def test_disk_tier_bf16_device_params(tmp_path):
    """With bf16 compute the device tree is bf16 (half the param HBM of
    the in-memory path's fp32 masters) while masters stay fp32 on disk."""
    prog = build_train_program(
        _cfg(tmp_path / "s", precision=Precision.BF16)
    )
    state, losses = _run(prog, 2)
    assert state["params"]["lm_head"]["kernel"].dtype == jnp.bfloat16
    assert np.isfinite(losses).all()
    spill = os.listdir(tmp_path / "s")
    assert any(f.endswith(".master.f32") for f in spill)
    assert any(f.endswith(".mu.f32") for f in spill)
    assert any(f.endswith(".nu.f32") for f in spill)


def test_disk_tier_persistence_across_programs(tmp_path):
    """Kill the program after 3 steps, rebuild on the same spill dir, run
    2 more — identical to 5 continuous steps (exact masters AND moments
    re-attach; a restart costs nothing)."""
    spill = tmp_path / "spill"
    cont_prog = build_train_program(_cfg(tmp_path / "cont"))
    _, cont_losses = _run(cont_prog, 5)

    prog1 = build_train_program(_cfg(spill))
    state1, losses_a = _run(prog1, 3)

    prog2 = build_train_program(_cfg(spill))
    state2 = prog2.init(jax.random.PRNGKey(prog2.config.seed))
    # The supervisor restores `step` from its checkpoint; emulate that.
    state2 = dict(state2, step=state1["step"])
    _, losses_b = _run(prog2, 2, state=state2, start=3)

    np.testing.assert_allclose(losses_a + losses_b, cont_losses, rtol=1e-5)


def test_disk_tier_rollback_reseeds_masters(tmp_path):
    """Feeding an OLDER state (supervisor divergence rollback) reseeds
    the masters from it (moments zeroed, bias-correction counter reset —
    exactly loading a checkpoint without optimizer state): the continued
    trajectory starts at the restored weights, not the spill's newer
    ones."""
    prog = build_train_program(_cfg(tmp_path / "spill"))
    state0 = prog.init(jax.random.PRNGKey(prog.config.seed))
    state1, _ = prog.step(state0, prog.synthetic_batch(0))

    reseeds = []
    orig = prog.disk_store.reseed_masters

    def spy(*a, **k):
        reseeds.append(1)
        return orig(*a, **k)

    prog.disk_store.reseed_masters = spy
    state2, _ = prog.step(state1, prog.synthetic_batch(1))
    assert not reseeds  # sequential steps never reseed

    # Roll back to state1 and step with batch 1 again.
    redo, _ = prog.step(state1, prog.synthetic_batch(1))
    assert reseeds, "rollback was not detected"
    assert int(redo["step"]) == 2
    # Post-update masters ARE the redone params (trajectory restarted
    # from state1's weights, fp32 end to end here).
    masters = prog.disk_store.masters()
    from tpu_engine.disk_offload import flatten_with_paths

    for path, leaf in flatten_with_paths(redo["params"]).items():
        np.testing.assert_array_equal(
            np.asarray(leaf, np.float32), masters[path]
        )


def test_disk_tier_sharded_mesh_parity(tmp_path):
    """fsdp-sharded grads gather to the host, update on disk, and the new
    params scatter back with their shardings — parity with the sharded
    in-memory path."""
    kw = dict(
        mesh=MeshConfig(data=2, fsdp=4),
        sharding_stage=ShardingStage.FULL_PARTITIONING,
        micro_batch_size=1,
    )
    ref_state, ref_losses = _run(build_train_program(_cfg(**kw)), 3)
    prog = build_train_program(_cfg(tmp_path / "spill", **kw))
    state, losses = _run(prog, 3)
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-5)
    leaf = state["params"]["layers"]["q"]["kernel"]
    assert leaf.sharding.spec == ref_state["params"]["layers"]["q"]["kernel"].sharding.spec
    for r, g in zip(jax.tree.leaves(ref_state["params"]),
                    jax.tree.leaves(state["params"])):
        np.testing.assert_allclose(
            np.asarray(g, np.float32), np.asarray(r, np.float32),
            rtol=2e-5, atol=2e-6,
        )


def test_disk_tier_config_validation(tmp_path):
    with pytest.raises(ValueError, match="optimizer_spill_dir"):
        _cfg(**{"optimizer_offload": OffloadDevice.DISK})
    with pytest.raises(ValueError, match="adamw"):
        _cfg(tmp_path, optimizer="adafactor")
    with pytest.raises(ValueError, match="moment_dtype"):
        _cfg(tmp_path, moment_dtype=Precision.BF16)
    with pytest.raises(ValueError, match="only applies"):
        _cfg(optimizer_spill_dir=str(tmp_path))
    with pytest.raises(ValueError, match="param_offload"):
        _cfg(tmp_path, param_offload=OffloadDevice.HOST)
    with pytest.raises(ValueError, match="spill optimizer state"):
        _cfg(param_offload=OffloadDevice.DISK)


def test_disk_adamw_spill_accounting(tmp_path):
    from tpu_engine.disk_offload import DiskAdamW

    store = DiskAdamW(str(tmp_path / "s"), b1=0.9, b2=0.95,
                      weight_decay=0.0)
    params = {"w": np.ones((8, 4), np.float32)}
    assert store.initialize(params, {"w": True}) is False
    assert store.spill_bytes() == 3 * 8 * 4 * 4
    # Re-attach on identical layout.
    store2 = DiskAdamW(str(tmp_path / "s"), b1=0.9, b2=0.95,
                       weight_decay=0.0)
    assert store2.initialize(params, {"w": True}) is True
    # Hyperparameter mismatch -> fresh spill, not a bogus attach.
    store3 = DiskAdamW(str(tmp_path / "s"), b1=0.8, b2=0.95,
                       weight_decay=0.0)
    assert store3.initialize(params, {"w": True}) is False


def test_disk_tier_stage2_sharded_grads(tmp_path):
    """Stage-2 on a multi-device mesh: grads reduce-scatter over fsdp
    while the params the slabs mirror stay replicated — the grad fetch
    falls back to materialise+slice (single-process only; cross-process
    stage-2 disk is rejected at build time). Step-for-step parity with
    the in-memory stage-2 chain."""
    kw = dict(mesh=MeshConfig(fsdp=4),
              sharding_stage=ShardingStage.GRADIENT_PARTITIONING)
    ref_prog = build_train_program(_cfg(**kw))
    ref_state, ref_losses = _run(ref_prog, 3)
    disk_prog = build_train_program(_cfg(tmp_path / "s2", **kw))
    disk_state, disk_losses = _run(disk_prog, 3)
    np.testing.assert_allclose(disk_losses, ref_losses, rtol=1e-6)
    assert disk_prog.disk_store.step_on_disk == 3


def test_multihost_disk_requires_stage3():
    import jax

    from unittest import mock

    with mock.patch.object(jax, "process_count", return_value=2):
        with pytest.raises(ValueError, match="sharding_stage=3"):
            build_train_program(_cfg(
                "/tmp/nope",
                sharding_stage=ShardingStage.GRADIENT_PARTITIONING,
            ))


def test_overlap_semantics(tmp_path):
    """Delayed parameter update (``disk_update_overlap``): the returned
    state lags the host walk by exactly one step — step k returns params
    P_{k-1} — and ``flush`` folds the in-flight walk in. The FIRST walk
    is identical to the serial tier (both compute g1 on P0), which pins
    the pipelined path against the serial one where they must agree."""
    ov = build_train_program(_cfg(tmp_path / "a", disk_update_overlap=True))
    assert ov.flush is not None
    s0 = ov.init(jax.random.PRNGKey(ov.config.seed))
    p0 = jax.device_get(s0["params"])

    s1, m1 = ov.step(s0, ov.synthetic_batch(0))
    # Step 1 returns P0 verbatim (its walk is still in flight).
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), b),
        jax.device_get(s1["params"]), p0,
    )
    assert int(s1["step"]) == 1

    s2, _ = ov.step(s1, ov.synthetic_batch(1))
    # Step 2 returns P1 = adam(P0, g1) — identical to the serial tier's
    # first step (same seed, same batch, g1 computed on P0 either way).
    serial = build_train_program(_cfg(tmp_path / "b"))
    r0 = serial.init(jax.random.PRNGKey(serial.config.seed))
    r1, _ = serial.step(r0, serial.synthetic_batch(0))
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=0, atol=0),
        jax.device_get(s2["params"]), jax.device_get(r1["params"]),
    )

    # flush folds the in-flight walk (update 2): params change, the spill
    # says step 2 was applied, and flushed params == the disk masters.
    flushed = ov.flush(s2)
    assert ov.disk_store.step_on_disk == 2
    masters = ov.disk_store.masters()
    from tpu_engine.disk_offload import flatten_with_paths

    flat = flatten_with_paths(jax.device_get(flushed["params"]))
    for path, w in masters.items():
        np.testing.assert_allclose(
            flat[path], w.astype(flat[path].dtype), rtol=0, atol=0)
    # flush is idempotent.
    again = ov.flush(flushed)
    assert again is flushed or jax.tree.all(jax.tree.map(
        lambda a, b: bool((np.asarray(a) == np.asarray(b)).all()),
        again["params"], flushed["params"],
    ))


def test_overlap_discards_walk_on_rollback(tmp_path):
    """Feeding a state that is NOT the continuation of the in-flight walk
    (supervisor rollback) abandons the walk and reseeds: moments zeroed,
    trajectory restarts from the incoming params."""
    ov = build_train_program(_cfg(tmp_path / "a", disk_update_overlap=True))
    s0 = ov.init(jax.random.PRNGKey(0))
    s1, _ = ov.step(s0, ov.synthetic_batch(0))
    s2, _ = ov.step(s1, ov.synthetic_batch(1))   # walk 2 in flight
    # Roll back to s1 (step label 1); pending walk says step 2 -> discard.
    s_rb, _ = ov.step(s1, ov.synthetic_batch(2))
    flushed = ov.flush(s_rb)
    assert int(flushed["step"]) == 2
    # The reseed zeroed moments: bias correction restarted (the walk for
    # the rollback step ran with moment_steps 1).
    assert ov.disk_store.moment_steps == 1
    assert ov.disk_store.step_on_disk == 2
    # Training continues cleanly after the discard.
    s3, m = ov.step(flushed, ov.synthetic_batch(3))
    assert np.isfinite(float(m["loss"]))


def test_overlap_losses_decrease(tmp_path):
    ov = build_train_program(_cfg(tmp_path / "a", disk_update_overlap=True))
    _, losses = _run(ov, 6)
    assert losses[-1] < losses[0]
    assert all(np.isfinite(l) for l in losses)


def test_overlap_supervised_job_checkpoint_consistent(tmp_path):
    """Through the supervisor: checkpoints of an overlap job are flushed
    (params include every update the step label claims), so a resume
    continues without a reseed discontinuity."""
    from tpu_engine.launcher import TPULauncher

    cfg = _cfg(
        tmp_path / "spill", total_steps=4, log_every_steps=1,
        disk_update_overlap=True,
        checkpoint_dir=str(tmp_path / "ck"), checkpoint_interval_steps=2,
    )
    launcher = TPULauncher()
    res = launcher.launch(cfg, dry_run=False, block=True)
    job = launcher.get_job(res.job_id)
    assert job.status == "completed", job.error
    assert job.current_step == 4
    # The final save was flushed: the spill's applied step matches.
    assert job.program.disk_store.step_on_disk == 4
    # Saved params equal the disk masters at step 4 (flushed, not stale).
    from tpu_engine.checkpoint import abstract_state_like

    step, restored = job.ckpt.restore(
        abstract_state_like(
            job.program.state_shardings,
            jax.eval_shape(lambda: job.program.init(jax.random.PRNGKey(0))),
        ),
    )
    assert step == 4
    from tpu_engine.disk_offload import flatten_with_paths

    flat = flatten_with_paths(jax.device_get(restored["params"]))
    for path, w in job.program.disk_store.masters().items():
        np.testing.assert_allclose(
            flat[path], w.astype(flat[path].dtype), rtol=0, atol=0)


def test_overlap_config_validation(tmp_path):
    with pytest.raises(ValueError, match="disk_update_overlap"):
        _cfg(disk_update_overlap=True)  # no disk offload -> invalid


def test_disk_tier_supervised_job(tmp_path):
    """End-to-end through the launcher/supervisor: the disk-tier program
    survives eval_shape(init) (the supervisor traces init for checkpoint
    state shapes), the step loop, and completion."""
    from tpu_engine.launcher import TPULauncher

    cfg = _cfg(tmp_path / "spill", total_steps=3, log_every_steps=1)
    launcher = TPULauncher()
    res = launcher.launch(cfg, dry_run=False, block=True)
    job = launcher.get_job(res.job_id)
    assert job.status == "completed", job.error
    assert job.current_step == 3
    assert job.program.disk_store.step_on_disk == 3
    assert job.program.disk_store.spill_bytes() > 0


def test_is_replicated_upload_guard():
    """The uploader's single-transfer broadcast path is only safe when the
    emitted block IS the whole leaf and this process addresses every
    device holding it. The original gate compared device counts only, so
    on multi-host meshes a process's PARTIAL block (its local shard of a
    leaf replicated across hosts) was broadcast as if it were the full
    leaf."""
    from tpu_engine.disk_offload import is_replicated_upload

    # Single-process replicated leaf: block == leaf, all devices local.
    assert is_replicated_upload((16, 4), (16, 4), 8, 8)
    # Multi-host regression: the emitted block is this process's LOCAL
    # slice — the shape mismatch must force the per-device path even
    # when the leaf's devices all happen to be addressable here.
    assert not is_replicated_upload((8, 4), (16, 4), 2, 2)
    # Devices on other hosts hold replicas: no sharding-aware transfer
    # from this process can cover them.
    assert not is_replicated_upload((16, 4), (16, 4), 8, 4)
    # Single-device leaves gain nothing from the broadcast path.
    assert not is_replicated_upload((16, 4), (16, 4), 1, 1)


def test_uploader_replicated_fast_path_and_sharded_stitch():
    """Replicated leaves still take the one-transfer fast path after the
    multi-host guard, and fsdp-sharded leaves stitch per-device blocks —
    both reassemble the exact master values."""
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P

    from tpu_engine.disk_offload import AsyncShardUploader

    devs = jax.devices()
    mesh = Mesh(np.array(devs), ("fsdp",))
    full = np.arange(16 * 4, dtype=np.float32).reshape(16, 4)
    per = 16 // len(devs)

    key_devices = {"rep:0": ("rep", list(devs))}
    for i, d in enumerate(devs):
        key_devices[f"shard:{i}"] = ("shard", [d])
    up = AsyncShardUploader(
        key_devices,
        {"rep": (16, 4), "shard": (16, 4)},
        {"rep": NamedSharding(mesh, P()),
         "shard": NamedSharding(mesh, P("fsdp"))},
        jnp.float32,
    )
    up.emit("rep:0", full)
    for i in range(len(devs)):
        up.emit(f"shard:{i}", full[i * per:(i + 1) * per])
    out = up.result()

    assert "rep" in up._complete and "rep" not in up._blocks
    assert "shard" in up._blocks and "shard" not in up._complete
    np.testing.assert_array_equal(np.asarray(out["rep"]), full)
    np.testing.assert_array_equal(np.asarray(out["shard"]), full)
    assert out["rep"].sharding.is_fully_replicated


def test_consensus_check_hoisted_from_hot_loop(tmp_path):
    """The discontinuity consensus (a cross-host allgather per call) runs
    once after attach and is then cached: steady sequential steps never
    re-enter it. Rollbacks and fresh attaches invalidate the cache."""
    prog = build_train_program(_cfg(tmp_path / "spill"))
    state = prog.init(jax.random.PRNGKey(prog.config.seed))
    assert prog.disk_store.consensus_checks == 0

    saved = None
    for i in range(4):
        state, _ = prog.step(state, prog.synthetic_batch(i))
        if i == 0:
            saved = state
    assert prog.disk_store.consensus_checks == 1  # first step only

    # Supervisor rollback: the incoming state is older than the spill —
    # cached continuity no longer holds, the consensus must rerun (and
    # reseed), then steady state re-caches.
    state, _ = prog.step(saved, prog.synthetic_batch(1))
    assert prog.disk_store.consensus_checks == 2
    state, _ = prog.step(state, prog.synthetic_batch(2))
    assert prog.disk_store.consensus_checks == 2

    # A fresh program attaching to the same spill re-establishes
    # consensus exactly once.
    prog2 = build_train_program(_cfg(tmp_path / "spill"))
    state2 = prog2.init(jax.random.PRNGKey(prog2.config.seed))
    state2 = dict(state2, step=state["step"])
    for i in range(2):
        state2, _ = prog2.step(state2, prog2.synthetic_batch(3 + i))
    assert prog2.disk_store.consensus_checks == 1


def test_consensus_cached_with_overlap(tmp_path):
    """Same hoist under delayed-parameter-update overlap: the in-flight
    walk marks its target step verified at dispatch, so the next
    sequential step skips the consensus."""
    prog = build_train_program(
        _cfg(tmp_path / "spill", disk_update_overlap=True)
    )
    state, losses = _run(prog, 5)
    assert prog.disk_store.consensus_checks == 1
    assert np.isfinite(losses).all()
