"""Host offload (params + optimizer state) and gradient-communication dtype.

The reference emits ZeRO-3 CPU offload and ``communication_data_type`` as
DeepSpeed JSON (``deepspeed_launcher.py:60-62,167-169,197-212``); here both
are real engine behavior:

- ``param_offload=host``: master params live in pinned host memory, layers
  stream to device one at a time inside the remat-wrapped scan body
  (``tpu_engine/models/transformer.py:remat_scan_body``), update shards
  transit device memory (``tpu_engine/train.py``);
- ``optimizer_offload=host``: optimizer state resident in pinned host;
- ``grad_allreduce_dtype``: reduced-precision mode differentiates wrt the
  compute-dtype params so the cotangent chain (and the gradient collectives
  XLA inserts in it) carries the comm dtype.
"""

import jax
import jax.numpy as jnp
import pytest

from tpu_engine.mesh_runtime import MeshConfig
from tpu_engine.sharding import (
    OffloadDevice,
    ShardingStage,
    TPUTrainConfig,
    host_memory_kind_available,
)
from tpu_engine.train import build_train_program


def _cfg(**kw):
    base = dict(
        model_name="gpt-tiny",
        sharding_stage=ShardingStage.FULL_PARTITIONING,
        mesh=MeshConfig(data=2, fsdp=4),
        micro_batch_size=1,
        seq_len=32,
        warmup_steps=1,
        learning_rate=1e-2,
        activation_checkpointing=True,
    )
    base.update(kw)
    return TPUTrainConfig(**base)


def _kinds(tree):
    return {leaf.sharding.memory_kind for leaf in jax.tree.leaves(tree)}


def test_host_memory_kind_available_on_cpu_backend():
    # The CPU backend supports pinned_host placement (probed, not
    # introspected) — this is what lets the offload paths run in CI at all.
    prog = build_train_program(_cfg(param_offload=OffloadDevice.NONE))
    assert host_memory_kind_available(prog.mesh)


def test_param_offload_placement_and_numerics():
    """Params live in pinned host memory and the training trajectory matches
    the non-offloaded program bit-for-bit-close (fp32 determinism)."""
    kw = dict(precision="fp32", seed=3)
    off = build_train_program(
        _cfg(param_offload=OffloadDevice.HOST,
             optimizer_offload=OffloadDevice.HOST, **kw)
    )
    ref = build_train_program(_cfg(**kw))

    s_off = off.init(jax.random.PRNGKey(0))
    s_ref = ref.init(jax.random.PRNGKey(0))
    assert _kinds(s_off["params"]) == {"pinned_host"}
    assert _kinds(s_ref["params"]) == {None} or _kinds(s_ref["params"]) == {"device"}
    # Param-shaped optimizer leaves are host-resident too.
    assert "pinned_host" in _kinds(s_off["opt_state"])

    losses_off, losses_ref = [], []
    for i in range(3):
        batch = ref.synthetic_batch(i)
        s_off, m_off = off.step(s_off, batch)
        s_ref, m_ref = ref.step(s_ref, batch)
        losses_off.append(float(m_off["loss"]))
        losses_ref.append(float(m_ref["loss"]))
    assert losses_off == pytest.approx(losses_ref, abs=1e-5)
    # Updated params return to pinned host after every step.
    assert _kinds(s_off["params"]) == {"pinned_host"}
    # And the trajectory actually moved (lr warms up after step 1).
    assert losses_off[2] != pytest.approx(losses_off[0], abs=1e-9)


def test_param_offload_eval_step_runs():
    prog = build_train_program(
        _cfg(param_offload=OffloadDevice.HOST, precision="fp32")
    )
    state = prog.init(jax.random.PRNGKey(0))
    loss = float(prog.eval_step(state, prog.synthetic_batch(0)))
    assert jnp.isfinite(loss)


def test_param_offload_rejects_lora():
    with pytest.raises(ValueError, match="param_offload is not supported with LoRA"):
        build_train_program(
            _cfg(param_offload=OffloadDevice.HOST, lora_rank=4)
        )


def test_param_offload_rejects_pipeline():
    with pytest.raises(ValueError, match="pipeline"):
        build_train_program(
            _cfg(param_offload=OffloadDevice.HOST,
                 mesh=MeshConfig(data=1, fsdp=4, pipe=2))
        )


def test_param_offload_rejects_reduced_comm():
    with pytest.raises(ValueError, match="grad_allreduce_dtype"):
        build_train_program(
            _cfg(param_offload=OffloadDevice.HOST, grad_allreduce_dtype="bf16")
        )


def test_grad_allreduce_dtype_must_match_precision():
    with pytest.raises(ValueError, match="grad_allreduce_dtype"):
        _cfg(grad_allreduce_dtype="fp16")  # bf16 compute
    # fp32 and the compute dtype itself are always legal.
    _cfg(grad_allreduce_dtype="fp32")
    _cfg(grad_allreduce_dtype="bf16")


def test_reduced_comm_executes_and_tracks_default():
    """bf16 gradient communication: runs green; the loss trajectory tracks
    the default config (grads differ only by the cast boundary at the
    master-param edge)."""
    red = build_train_program(_cfg(grad_allreduce_dtype="bf16", seed=5))
    ref = build_train_program(_cfg(seed=5))
    s_red = red.init(jax.random.PRNGKey(1))
    s_ref = ref.init(jax.random.PRNGKey(1))
    for i in range(2):
        batch = ref.synthetic_batch(i)
        s_red, m_red = red.step(s_red, batch)
        s_ref, m_ref = ref.step(s_ref, batch)
    assert float(m_red["loss"]) == pytest.approx(float(m_ref["loss"]), rel=2e-2)
    assert jnp.isfinite(float(m_red["grad_norm"]))


@pytest.mark.slow
@pytest.mark.tpu_aot
def test_tpu_hlo_gradient_collectives_ride_bf16():
    """AOT-compile the train step for a described v5e:2x4 topology (libtpu
    compile-only — no chip needed) and assert the layer-gradient collectives
    ride bf16. Measured reality on TPU: with bf16 compute, XLA places the
    gradient psum at the bf16 dot output, so the dominant gradient traffic
    is half-width with or without ``grad_allreduce_dtype`` — the knob makes
    the boundary dtype explicit rather than changing the collective."""
    import re

    from jax.experimental import topologies

    from tpu_engine.mesh_runtime import MeshRuntime

    try:
        topo = topologies.get_topology_desc("v5e:2x4", platform="tpu")
    except Exception as e:  # no libtpu in this environment
        pytest.skip(f"TPU AOT topology unavailable: {e}")
    cfg = _cfg(grad_allreduce_dtype="bf16",
               sharding_stage=ShardingStage.GRADIENT_PARTITIONING,
               activation_checkpointing=False)
    runtime = MeshRuntime(cfg.mesh, devices=topo.devices)
    prog = build_train_program(cfg, runtime=runtime)
    state_shape = jax.eval_shape(prog.init, jax.random.PRNGKey(0))
    batch = jax.ShapeDtypeStruct(prog.global_batch_shape(), jnp.int32)
    txt = prog.step.lower(state_shape, batch).compile().as_text()
    colls = re.findall(
        r"(bf16|f32)\[[\d,]*\][^\n]*\b(all-reduce|reduce-scatter)\(", txt
    )
    bf16_reduces = [c for c in colls if c[0] == "bf16"]
    assert bf16_reduces, f"expected bf16 gradient collectives, got {colls}"


# Compile-heavy module: excluded from the fast core run (pytest -m "not slow").
pytestmark = pytest.mark.slow
