"""End-to-end sequence-parallel training: a >1 'sequence' mesh axis trains
with ring attention and matches the non-SP trajectory."""

import jax
import numpy as np

from tpu_engine.mesh_runtime import MeshConfig
from tpu_engine.sharding import Precision, ShardingStage, TPUTrainConfig
from tpu_engine.train import build_train_program


def _cfg(**kw):
    base = dict(
        model_name="gpt-tiny",
        sharding_stage=ShardingStage.FULL_PARTITIONING,
        mesh=MeshConfig(data=2, fsdp=4),
        micro_batch_size=1,
        gradient_accumulation_steps=1,
        seq_len=64,
        precision=Precision.FP32,
        learning_rate=1e-2,
        warmup_steps=2,
        total_steps=100,
        activation_checkpointing=False,
    )
    base.update(kw)
    return TPUTrainConfig(**base)


def _run(cfg, n=3):
    prog = build_train_program(cfg)
    state = prog.init(jax.random.PRNGKey(0))
    losses = []
    for _ in range(n):
        state, m = prog.step(state, prog.synthetic_batch(0))
        losses.append(float(m["loss"]))
    return prog, losses


def test_sequence_parallel_training_matches_baseline():
    # Same global batch (8×64 tokens): SP mesh dp=2 × micro 4 vs ref mesh
    # dp=8 × micro 1 — synthetic_batch depends only on shape+seed, so the
    # trajectories must agree numerically.
    prog_sp, losses_sp = _run(
        _cfg(mesh=MeshConfig(data=1, fsdp=2, sequence=4), micro_batch_size=4)
    )
    assert prog_sp.model_config.attention_impl == "ring"
    _, losses_ref = _run(_cfg(mesh=MeshConfig(data=2, fsdp=4), micro_batch_size=1))
    np.testing.assert_allclose(losses_sp, losses_ref, rtol=1e-3)
    assert losses_sp[-1] < losses_sp[0]


def test_sequence_parallel_batch_sharded_over_sequence():
    prog, _ = _run(_cfg(mesh=MeshConfig(data=1, fsdp=2, sequence=4)), n=1)
    assert prog.batch_sharding.spec == jax.sharding.PartitionSpec(
        None, ("data", "fsdp"), "sequence"
    )


import pytest

# Compile-heavy module: excluded from the fast core run (pytest -m "not slow").
pytestmark = pytest.mark.slow
