"""Profiler subsystem: step breakdown stats, MFU accounting, trace session,
and the /api/v1/profile routes."""

import time

import pytest

from tpu_engine.profiler import (
    PEAK_FLOPS_BF16,
    StepProfiler,
    TraceSession,
    mfu,
    pipeline_tick_account,
)


def test_step_profiler_phases_and_stats():
    prof = StepProfiler(window=10, tokens_per_step=1000, n_devices=2)
    for _ in range(3):
        prof.begin_step()
        time.sleep(0.01)
        prof.mark("data")
        time.sleep(0.02)
        prof.mark("dispatch")
        time.sleep(0.005)
        prof.mark("device")
        total = prof.end_step()
        assert total >= 0.035

    s = prof.summary()
    assert s["steps_seen"] == 3
    assert s["window"] == 3
    assert s["phases"]["data"]["mean_ms"] == pytest.approx(10, rel=0.8)
    assert s["phases"]["dispatch"]["mean_ms"] > s["phases"]["device"]["mean_ms"]
    # Fractions cover the whole step.
    fracs = sum(s["phases"][p]["fraction"] for p in StepProfiler.PHASES)
    assert fracs == pytest.approx(1.0, abs=0.02)
    # Throughput is derived from mean total.
    assert s["tokens_per_sec"] > 0
    # Both values are rounded to 0.1 independently.
    assert s["tokens_per_sec_per_chip"] == pytest.approx(s["tokens_per_sec"] / 2, abs=0.06)


def test_step_profiler_window_bounded():
    prof = StepProfiler(window=5)
    for _ in range(20):
        prof.begin_step()
        prof.end_step()
    s = prof.summary()
    assert s["steps_seen"] == 20
    assert s["window"] == 5  # deque bounded — no unbounded growth


def test_mfu_accounting():
    # On the CPU test mesh there is no known peak → None.
    assert mfu(1e9, 1e4) is None or isinstance(mfu(1e9, 1e4), float)

    # Against a known chip entry the math is exact.
    class FakeDev:
        device_kind = "TPU v5e"

    v = mfu(1e9, 88_650.0, device=FakeDev())  # 88650 tok/s × 1 GF/tok / 197 TF
    assert v == pytest.approx(88_650e9 / PEAK_FLOPS_BF16["v5e"], rel=1e-6)


def test_pipeline_tick_account():
    # Off the pipelined path there is nothing to account.
    assert pipeline_tick_account("gpipe", 1, 8) is None
    zb = pipeline_tick_account("zb", 4, 16)
    f1b = pipeline_tick_account("1f1b", 4, 16)
    assert 0 < zb["busy_fraction"] <= 1
    assert zb["busy_fraction"] > f1b["busy_fraction"]
    # Growing M amortises the fixed bubble: busy fraction rises.
    assert (
        pipeline_tick_account("zb", 4, 32)["busy_fraction"]
        > zb["busy_fraction"]
    )


def test_bubble_adjusted_mfu_in_summary():
    """With a pipeline account attached the summary exposes the schedule's
    tick/busy accounting, and — when an MFU is computable — divides it by
    the busy fraction so pipelined runs stop being under-reported."""
    acct = pipeline_tick_account("zb", 4, 16)
    prof = StepProfiler(window=4, tokens_per_step=1000,
                        flops_per_token=1e6, pipeline_account=acct)
    for _ in range(2):
        prof.begin_step()
        time.sleep(0.005)
        prof.mark("device")
        prof.end_step()
    s = prof.summary()
    pipe = s["pipeline"]
    assert pipe["schedule"] == "zb"
    assert pipe["ticks"] == acct["ticks"]
    assert pipe["busy_fraction"] == pytest.approx(acct["busy_fraction"], abs=1e-4)
    assert pipe["bubble_fraction"] == pytest.approx(1 - pipe["busy_fraction"], abs=1e-3)
    # On the CPU test mesh mfu is None → no adjusted figure either.
    if s.get("mfu") is not None:
        assert s["mfu_bubble_adjusted"] == pytest.approx(
            s["mfu"] / pipe["busy_fraction"], rel=1e-3
        )
    else:
        assert "mfu_bubble_adjusted" not in s


def test_trace_session_lifecycle(tmp_path):
    ts = TraceSession()
    assert ts.status() == {"active": False}
    with pytest.raises(RuntimeError):
        ts.stop()
    info = ts.start(str(tmp_path / "trace"))
    assert info["active"] and ts.active
    with pytest.raises(RuntimeError):
        ts.start(str(tmp_path / "other"))  # one at a time
    out = ts.stop()
    assert out["active"] is False
    assert not ts.active
