"""Fault-injection unit surface: plans, triggers, seams, hardened probes.

The chaos *round trip* (inject → detect → emergency-save → shrink → resume)
lives in ``test_chaos.py``; this file pins down the deterministic pieces it
is built from — spec/trigger semantics, the checkpoint save/restore seams,
the retry+quarantine path, the crash-atomic stable pointer, and the
metadata-probe/watcher hardening.
"""

import json
import os
import threading

import jax
import numpy as np
import pytest

from tpu_engine import faults
from tpu_engine.checkpoint import TrainCheckpointManager
from tpu_engine.faults import (
    FaultInjector,
    FaultKind,
    FaultPlan,
    FaultSpec,
)
from tpu_engine.preemption import PreemptionWatcher, probe_gce_preempted


@pytest.fixture(autouse=True)
def _no_process_injector():
    """Each test arms its own injector explicitly; never leak one."""
    faults.clear_active()
    yield
    faults.clear_active()


# ---------------------------------------------------------------------------
# plans and specs
# ---------------------------------------------------------------------------


def test_random_plan_is_reproducible():
    a = FaultPlan.random(seed=7, n_faults=8)
    b = FaultPlan.random(seed=7, n_faults=8)
    assert [s.model_dump() for s in a.specs] == [s.model_dump() for s in b.specs]
    c = FaultPlan.random(seed=8, n_faults=8)
    assert [s.model_dump() for s in a.specs] != [s.model_dump() for s in c.specs]


def test_random_plan_never_draws_excluded_kinds():
    """PRECOMPILE_ERROR and CONTROLPLANE_CRASH are injected only through
    explicit specs — seeded chaos draws must never contain them, or every
    historical seeded storm would change byte-for-byte."""
    excluded = {FaultKind.PRECOMPILE_ERROR, FaultKind.CONTROLPLANE_CRASH}
    assert faults._NON_RANDOM_KINDS == frozenset(excluded)
    for seed in range(50):
        plan = FaultPlan.random(seed=seed, n_faults=32, max_step=500)
        drawn = {s.kind for s in plan.specs}
        assert not (drawn & excluded), f"seed={seed} drew {drawn & excluded}"


def test_spec_requires_a_trigger_and_chip_faults_a_device():
    with pytest.raises(ValueError):
        FaultSpec(kind=FaultKind.HOST_SLOW)  # neither at_step nor after_s
    with pytest.raises(ValueError):
        FaultSpec(kind=FaultKind.CHIP_UNHEALTHY, at_step=3)  # no device_index
    FaultSpec(kind=FaultKind.CHIP_UNHEALTHY, at_step=3, device_index=0)
    FaultSpec(kind=FaultKind.PREEMPTION_SIGNAL, after_s=0.5)


def test_step_trigger_and_count_consumption():
    inj = FaultInjector(FaultPlan(specs=[
        FaultSpec(kind=FaultKind.CHECKPOINT_SAVE_IOERROR, at_step=3, count=2),
    ]))
    inj.arm()
    assert not inj.take_save_fault(2)       # not due yet
    assert inj.take_save_fault(3)           # fires
    assert inj.take_save_fault(3)           # second budget unit
    assert not inj.take_save_fault(4)       # exhausted
    assert inj.counters[FaultKind.CHECKPOINT_SAVE_IOERROR.value] == 2


def test_preemption_and_host_slow_triggers():
    inj = FaultInjector(FaultPlan(specs=[
        FaultSpec(kind=FaultKind.PREEMPTION_SIGNAL, at_step=5),
        FaultSpec(kind=FaultKind.HOST_SLOW, at_step=2, slow_s=1.25, count=2),
    ]))
    inj.arm()
    assert inj.host_slow_penalty_s(1) == 0.0
    assert inj.host_slow_penalty_s(2) == 1.25
    assert inj.host_slow_penalty_s(2) == 1.25
    assert inj.host_slow_penalty_s(3) == 0.0  # count exhausted
    assert not inj.preempt_due(4)
    assert inj.preempt_due(5)
    assert not inj.preempt_due(6)  # consumed


def test_chip_overlay_duration_window_and_heal():
    inj = FaultInjector(FaultPlan(specs=[
        FaultSpec(kind=FaultKind.CHIP_UNHEALTHY, at_step=2, device_index=1,
                  duration_steps=2),
        FaultSpec(kind=FaultKind.TELEMETRY_NAN, at_step=2, device_index=1),
        FaultSpec(kind=FaultKind.TELEMETRY_NAN, at_step=3, device_index=4),
    ]))
    inj.arm()
    inj.observe_step(1)
    assert inj.chip_overlay() == {}
    inj.observe_step(2)
    # chip-unhealthy wins over telemetry-nan on the same chip.
    assert inj.chip_overlay()[1] is FaultKind.CHIP_UNHEALTHY
    inj.observe_step(3)
    assert inj.chip_overlay()[4] is FaultKind.TELEMETRY_NAN
    inj.observe_step(4)  # duration_steps=2 window [2, 4) has closed
    overlay = inj.chip_overlay()
    assert overlay.get(1) is FaultKind.TELEMETRY_NAN  # no-duration fault persists
    healed = inj.heal(1)
    assert healed >= 1
    assert 1 not in inj.chip_overlay()
    assert any(e.kind == "heal" for e in inj.events)


def test_describe_full_and_specs_active():
    inj = FaultInjector(FaultPlan(seed=3, specs=[
        FaultSpec(kind=FaultKind.CHIP_UNHEALTHY, at_step=1, device_index=2),
    ]))
    inj.arm()
    assert inj.specs_active() == 1
    inj.observe_step(1)
    out = inj.describe_full()
    assert out["armed"] and out["seed"] == 3
    assert out["active_chip_faults"] == {"2": "chip-unhealthy"}
    assert any(e["kind"] == "chip-unhealthy" for e in out["events"])


def test_process_active_registry():
    assert faults.get_active() is None
    inj = faults.activate(FaultPlan(seed=1, specs=[
        FaultSpec(kind=FaultKind.HOST_SLOW, at_step=1),
    ]))
    assert faults.get_active() is inj
    faults.clear_active()
    assert faults.get_active() is None


# ---------------------------------------------------------------------------
# checkpoint seams: save IOError, retry+quarantine, restore corruption
# ---------------------------------------------------------------------------


def _np_state():
    return {"w": np.arange(8, dtype=np.float32), "step": np.zeros((), np.int32)}


def _abstract(state):
    return jax.tree.map(lambda a: jax.ShapeDtypeStruct(np.shape(a), np.asarray(a).dtype), state)


def test_injected_save_fault_raises_and_retry_recovers(tmp_path):
    inj = FaultInjector(FaultPlan(specs=[
        FaultSpec(kind=FaultKind.CHECKPOINT_SAVE_IOERROR, at_step=1, count=2),
    ]))
    inj.arm()
    mgr = TrainCheckpointManager(str(tmp_path), async_save=False, fault_injector=inj)
    with pytest.raises(OSError, match="injected fault"):
        mgr.save(1, _np_state(), wait=True)
    # One budget unit left → first retry attempt fails, second succeeds.
    attempts = []
    ok = mgr.save_with_retry(
        1, _np_state(), retries=3, backoff_base_s=0.001,
        on_attempt=lambda n, err: attempts.append((n, err)),
    )
    assert ok
    assert len(attempts) == 1 and "injected fault" in attempts[0][1]
    assert mgr.all_steps() == [1]
    assert mgr.quarantined_steps() == []


def test_persistent_save_failure_quarantines_and_never_raises(tmp_path):
    inj = FaultInjector(FaultPlan(specs=[
        FaultSpec(kind=FaultKind.CHECKPOINT_SAVE_IOERROR, at_step=2, count=100),
    ]))
    inj.arm()
    mgr = TrainCheckpointManager(str(tmp_path), async_save=False, fault_injector=inj)
    attempts = []
    ok = mgr.save_with_retry(
        2, _np_state(), retries=2, backoff_base_s=0.001,
        on_attempt=lambda n, err: attempts.append(n),
    )
    assert not ok
    assert attempts == [1, 2, 3]  # initial try + 2 retries, all observed
    assert mgr.quarantined_steps() == [2]


def test_injected_restore_corruption_falls_back_to_older_step(tmp_path):
    mgr = TrainCheckpointManager(str(tmp_path), async_save=False)
    state = _np_state()
    mgr.save(1, state, wait=True)
    state2 = {"w": np.arange(8, dtype=np.float32) + 1.0,
              "step": np.full((), 2, np.int32)}
    mgr.save(2, state2, wait=True)
    inj = FaultInjector(FaultPlan(specs=[
        FaultSpec(kind=FaultKind.CHECKPOINT_RESTORE_CORRUPTION, at_step=2),
    ]))
    inj.arm()
    mgr._fault_injector = inj
    step, restored = mgr.restore(_abstract(state))
    # Step 2 "corrupted" → quarantined → step 1 restored instead.
    assert step == 1
    np.testing.assert_allclose(np.asarray(restored["w"]), state["w"])
    assert 2 in mgr.quarantined_steps()
    assert mgr.latest_step() == 1


# ---------------------------------------------------------------------------
# mark_stable crash atomicity
# ---------------------------------------------------------------------------


def test_mark_stable_survives_torn_write(tmp_path):
    mgr = TrainCheckpointManager(str(tmp_path), async_save=False)
    mgr.save(3, _np_state(), wait=True)
    mgr.mark_stable(3)
    assert mgr.last_stable_step() == 3
    pointer = os.fspath(mgr._stable_path())
    # A crash mid-write leaves garbage in the temp file; the pointer itself
    # must still read the last committed value.
    with open(pointer + ".tmp", "w") as f:
        f.write('{"step": 99')  # torn JSON
    assert mgr.last_stable_step() == 3
    # And a failed replace (ENOSPC etc.) must not corrupt the pointer.
    orig_replace = os.replace

    def exploding_replace(src, dst):
        if dst == pointer:
            raise OSError(28, "No space left on device")
        return orig_replace(src, dst)

    mgr.save(5, _np_state(), wait=True)
    try:
        os.replace = exploding_replace
        with pytest.raises(OSError):
            mgr.mark_stable(5)
    finally:
        os.replace = orig_replace
    with open(pointer) as f:
        assert json.load(f)["step"] == 3
    assert mgr.last_stable_step() == 3


# ---------------------------------------------------------------------------
# GCE metadata probe + watcher backoff
# ---------------------------------------------------------------------------


class _FakeResponse:
    def __init__(self, body: bytes, status: int = 200):
        self._body = body
        self.status = status

    def read(self, n: int = -1) -> bytes:
        return self._body[:n] if n >= 0 else self._body

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def test_probe_tri_state(monkeypatch):
    import urllib.request as _req

    monkeypatch.setattr(_req, "urlopen", lambda *a, **k: _FakeResponse(b"TRUE"))
    assert probe_gce_preempted() is True
    monkeypatch.setattr(_req, "urlopen", lambda *a, **k: _FakeResponse(b"FALSE\n"))
    assert probe_gce_preempted() is False
    monkeypatch.setattr(_req, "urlopen", lambda *a, **k: _FakeResponse(b"TRUE", status=503))
    assert probe_gce_preempted() is None  # HTTP error → unknown, not False
    def _boom(*a, **k):
        raise OSError("no route to metadata.google.internal")
    monkeypatch.setattr(_req, "urlopen", _boom)
    assert probe_gce_preempted() is None


def test_watcher_backoff_on_probe_failure():
    w = PreemptionWatcher(
        on_preemption=lambda reason: None,
        check_interval_s=0.5,
        metadata_check=lambda: None,
        max_backoff_s=8.0,
    )
    assert w._wait_s() == 0.5
    for _ in range(3):
        assert w._poll_once() is None
    assert w.metadata_failures == 3
    assert w._wait_s() == 4.0       # 0.5 * 2**3
    for _ in range(10):
        w._poll_once()
    assert w._wait_s() == 8.0       # capped
    # A successful probe resets the backoff.
    w.metadata_check = lambda: False
    assert w._poll_once() is None
    assert w.metadata_failures == 0
    assert w._wait_s() == 0.5


def test_raising_metadata_check_does_not_kill_watcher():
    fired = threading.Event()

    def exploding_check():
        raise RuntimeError("metadata server melted")

    w = PreemptionWatcher(
        on_preemption=lambda reason: fired.set(),
        check_interval_s=0.01,
        metadata_check=exploding_check,
        max_backoff_s=0.02,
    )
    w.start()
    try:
        # The old code died on the first raise; the hardened loop keeps
        # polling (with backoff) and still honours the simulation seam.
        assert not fired.wait(0.05)
        assert w._thread.is_alive()
        assert w.metadata_failures >= 1
        w.simulate_interruption()
        assert fired.wait(2.0)
    finally:
        w.stop()


def test_watcher_fires_on_metadata_true():
    fired = []
    w = PreemptionWatcher(
        on_preemption=fired.append,
        check_interval_s=0.01,
        metadata_check=lambda: True,
    )
    w.start()
    try:
        deadline = threading.Event()
        deadline.wait(0.0)
        for _ in range(200):
            if fired:
                break
            deadline.wait(0.01)
        assert fired == ["gce-metadata"]
    finally:
        w.stop()
