"""Fleet speculative decoding pools: spill rule, paired fleet, twin lane.

Four tiers in one file:

- **Spill controller units** — sustained-α spill/restore over a real
  :class:`MetricHistorian` with explicit timestamps: streak hysteresis,
  the recover-margin band, per-tenant cooldown, no-data freeze, and the
  PR-15 audit contract (every consult that could fire leaves a
  byte-stable :class:`DecisionRecord`).
- **Paired fleet on stubs** — :class:`SpecServingFleet` through the real
  :class:`FleetScheduler`: draft-propose + target-verify legs, the
  authoritative-target correctness contract, acceptance EMAs feeding the
  historian, spill → plain chunked decode with canary probes, and
  draft-replica prefix-cache invalidation.
- **Admission/placement** — ``estimate_serving_hbm(draft_model_name=...)``
  draft terms + structured :class:`SpecHBMOversubscribed`, and
  ``plan_serving_pool(role="draft")`` propose-latency ranking.
- **Distill smoke** — the only end-to-end draft-production recipe
  (``benchmarks/spec_decode_distill.py``) at tiny dims on CPU, so the
  path that makes real drafts cannot silently rot.
"""

import time

import pytest

from tests.test_serving_fleet import (
    StubEngine,
    StubTrainJob,
    mock_fleet_fn,
    wait_until,
)
from tpu_engine.hbm_estimate import (
    SpecHBMOversubscribed,
    estimate_serving_hbm,
)
from tpu_engine.historian import MetricHistorian
from tpu_engine.placement import plan_serving_pool
from tpu_engine.scheduler import FleetScheduler
from tpu_engine.serving_fleet import (
    AutoscalerConfig,
    ReplicaAutoscaler,
    ServingReplicaSpec,
)
from tpu_engine.spec_pool import (
    SpecServingFleet,
    SpecSpillConfig,
    SpecSpillController,
    _reset_stats_for_tests,
    spec_pool_stats,
)

SERIES = "serving.spec.accept_rate"


@pytest.fixture
def sched_factory():
    created = []

    def make(**kw):
        jobs = []

        def factory(sub):
            job = StubTrainJob(sub)
            jobs.append(job)
            return job

        kw.setdefault("job_factory", factory)
        kw.setdefault("poll_interval_s", 0.01)
        kw.setdefault("grow_back_cooldown_s", 0.0)
        s = FleetScheduler(**kw)
        s._stub_jobs = jobs
        created.append(s)
        return s

    yield make
    for s in created:
        for j in getattr(s, "_stub_jobs", []):
            j.finish()
        s.shutdown()


def _one():
    return ReplicaAutoscaler(
        AutoscalerConfig(min_replicas=1, max_replicas=1))


# ---------------------------------------------------------------------------
# SpecSpillController: the audited sustained-α rule
# ---------------------------------------------------------------------------


def _feed(hist, tenant, alpha, t0, n=5, dt=1.0):
    for i in range(n):
        hist.record(SERIES, alpha, ts=t0 + i * dt,
                    labels={"tenant": tenant})


def _ctl(hist, **kw):
    base = dict(accept_floor=0.35, recover_margin=0.15, window_s=60.0,
                sustain_consults=3, cooldown_s=0.0, canary_every=8)
    base.update(kw)
    return SpecSpillController(hist, SpecSpillConfig(**base))


def test_spill_fires_only_when_sustained():
    hist = MetricHistorian()
    ctl = _ctl(hist)
    _feed(hist, "junk", 0.05, t0=100.0)
    # Two consults build the streak (each audited as suppressed); the
    # third fires.
    assert ctl.consult(["junk"], now=110.0) == []
    assert ctl.consult(["junk"], now=111.0) == []
    assert ctl.consult(["junk"], now=112.0) == ["junk"]
    assert ctl.is_spilled("junk")
    outs = [d.outcome for d in ctl.decisions]
    assert outs == ["suppressed", "suppressed", "fired"]
    assert all(d.rule == "spill_low_acceptance" for d in ctl.decisions)
    assert ctl.decisions[0].suppressed_reason == "trend-not-sustained"
    fired = ctl.decisions[-1]
    assert fired.action == {"verb": "spill", "tenant": "junk",
                            "alpha": 0.05}
    assert fired.inputs["queries"][0]["series"] == SERIES
    assert fired.hysteresis["required"] == 3
    # Audit records are byte-stable dicts.
    assert fired.to_dict()["decision_id"].startswith("spd-")


def test_spill_streak_resets_on_healthy_alpha():
    hist = MetricHistorian()
    ctl = _ctl(hist)
    _feed(hist, "t", 0.1, t0=100.0)
    ctl.consult(["t"], now=110.0)
    ctl.consult(["t"], now=111.0)
    # A healthy window wipes the streak — two breaches then recovery is
    # not "sustained".
    _feed(hist, "t", 0.9, t0=112.0)
    assert ctl.consult(["t"], now=115.0) == []
    assert ctl.status()["streaks"]["t"] == 0
    assert not ctl.is_spilled("t")


def test_restore_needs_margin_and_cooldown():
    hist = MetricHistorian()
    ctl = _ctl(hist, sustain_consults=2, cooldown_s=50.0, window_s=10.0)
    _feed(hist, "t", 0.05, t0=100.0)
    ctl.consult(["t"], now=110.0)
    assert ctl.consult(["t"], now=111.0) == ["t"]  # spilled at t=111
    # α inside the hysteresis band (floor < α < floor+margin) must NOT
    # restore — the band is what stops flapping.
    _feed(hist, "t", 0.45, t0=115.0)
    ctl.consult(["t"], now=122.0)
    ctl.consult(["t"], now=123.0)
    assert ctl.is_spilled("t")
    # Recovered α above the band: sustained, but inside cooldown →
    # suppressed with the audited reason; after cooldown it fires.
    _feed(hist, "t", 0.9, t0=130.0)
    ctl.consult(["t"], now=136.0)
    ctl.consult(["t"], now=137.0)
    assert ctl.is_spilled("t")
    assert ctl.decisions[-1].suppressed_reason == "cooldown-active"
    assert ctl.decisions[-1].rule == "restore_speculation"
    _feed(hist, "t", 0.9, t0=155.0)
    assert ctl.consult(["t"], now=162.0) == []
    assert not ctl.is_spilled("t")
    assert ctl.decisions[-1].action["verb"] == "restore"


def test_no_data_freezes_the_streak():
    hist = MetricHistorian()
    ctl = _ctl(hist)
    _feed(hist, "t", 0.1, t0=100.0, n=2)
    ctl.consult(["t"], now=103.0)
    assert ctl.status()["streaks"]["t"] == 1
    # Window slides past every sample: no evidence either way — the
    # streak must neither advance nor reset, and the consult is audited.
    ctl.consult(["t"], now=500.0)
    assert ctl.status()["streaks"]["t"] == 1
    assert ctl.decisions[-1].suppressed_reason == "no-data"
    assert not ctl.is_spilled("t")


# ---------------------------------------------------------------------------
# SpecServingFleet on stubs through the real scheduler
# ---------------------------------------------------------------------------


class MisdraftEngine(StubEngine):
    """Draft stand-in whose proposals never match the target stream
    (StubEngine emits 1s; this emits 2s) → measured α = 0."""

    def step(self):
        out = 0
        with self._lock:
            for r in self._reqs.values():
                if len(r["tokens"]) < r["need"]:
                    r["tokens"].append(2)
                    out += 1
        return out


def _spec(**kw):
    base = dict(model_name="gpt-tiny", max_slots=4, max_len=128)
    base.update(kw)
    return ServingReplicaSpec(**base)


def make_spec_fleet(sched, engine_factory=StubEngine, **kw):
    kw.setdefault("verify_autoscaler", _one())
    kw.setdefault("draft_autoscaler", _one())
    return SpecServingFleet(
        sched, _spec(), _spec(max_slots=2), engine_factory=engine_factory,
        **kw)


def _pools_up(fleet):
    return (len(fleet.draft.running_replicas()) == 1
            and len(fleet.verify.running_replicas()) == 1)


def test_spec_fleet_pairs_draft_and_verify_pools(sched_factory):
    _reset_stats_for_tests()
    s = sched_factory(max_concurrent_jobs=4, fleet_fn=mock_fleet_fn)
    hist = MetricHistorian()
    fleet = make_spec_fleet(s, historian=hist)
    # The pairing forces the roles: drafts are first-class draft-pool
    # tenants, verify is an ordinary decode pool.
    assert fleet.draft.spec.pool_role == "draft"
    assert fleet.verify.spec.pool_role == "decode"
    fleet.start()
    assert wait_until(lambda: _pools_up(fleet))
    fid = fleet.submit_request([3, 1, 4], max_new_tokens=5, tenant="good")
    out = fleet.wait(fid, timeout=10.0)
    # The emitted stream is the TARGET's own tokens (StubEngine 1s), and
    # both legs ran on distinct pools.
    assert out["status"] == "done" and out["tokens"] == [1] * 5
    assert out["speculated"] and not out["canary"]
    assert out["draft_replica"] is not None
    assert out["verify_replica"] is not None
    st = fleet.status()
    assert st["draft_legs_total"] == 1 and st["plain_legs_total"] == 0
    # Stub draft emits the same 1s → perfect acceptance, recorded to the
    # historian under the tenant label.
    assert fleet.tenant_accept_rates()["good"] == 1.0
    q = hist.query(SERIES, 0.0, time.time() + 1.0, agg="last",
                   labels={"tenant": "good"})
    assert q["value"] == 1.0 and q["count"] >= 1
    mod = spec_pool_stats()
    assert mod["requests_total"] == 1 and mod["draft_legs_total"] == 1
    assert mod["accepted_tokens_total"] == mod["proposed_tokens_total"] > 0
    fleet.stop()


def test_spec_fleet_spills_low_alpha_tenant_with_canary(sched_factory):
    _reset_stats_for_tests()
    s = sched_factory(max_concurrent_jobs=4, fleet_fn=mock_fleet_fn)
    hist = MetricHistorian()

    def mixed(spec):
        # Factory sees the spec it builds for: junk proposals on the
        # draft pool only.
        return (MisdraftEngine(spec) if spec.pool_role == "draft"
                else StubEngine(spec))

    fleet = make_spec_fleet(
        s, engine_factory=mixed, historian=hist,
        spill_config=SpecSpillConfig(
            accept_floor=0.35, recover_margin=0.15, window_s=60.0,
            sustain_consults=2, cooldown_s=0.0, canary_every=2),
    )
    fleet.start()
    assert wait_until(lambda: _pools_up(fleet))
    out = fleet.wait(
        fleet.submit_request([7, 7], max_new_tokens=4, tenant="junk"),
        timeout=10.0)
    # Mismatched proposal can never corrupt output — the verify stream
    # is authoritative.
    assert out["tokens"] == [1] * 4
    assert fleet.tenant_accept_rates()["junk"] == 0.0
    fleet.tick()
    fleet.tick()
    assert fleet.spill.is_spilled("junk")
    fired = [d for d in fleet.spill.decisions if d.outcome == "fired"]
    assert fired and fired[-1].rule == "spill_low_acceptance"
    # Spilled tenant: next request rides plain chunked decode, the one
    # after is the canary probe back down the draft leg.
    plain = fleet.wait(
        fleet.submit_request([7, 8], max_new_tokens=4, tenant="junk"),
        timeout=10.0)
    assert not plain["speculated"] and not plain["canary"]
    assert plain["draft_replica"] is None and plain["tokens"] == [1] * 4
    canary = fleet.wait(
        fleet.submit_request([7, 9], max_new_tokens=4, tenant="junk"),
        timeout=10.0)
    assert canary["speculated"] and canary["canary"]
    assert canary["draft_replica"] is not None
    st = fleet.status()
    assert st["plain_legs_total"] == 1
    assert st["tenants"]["junk"]["spilled"]
    mod = spec_pool_stats()
    assert mod["spills_total"] == 1 and mod["canary_probes_total"] == 1
    assert mod["plain_legs_total"] == 1 and mod["tenants_spilled"] == 1
    fleet.stop()


class FakePrefixPlane:
    def __init__(self):
        self.dropped = []

    def drop_replica(self, sid):
        self.dropped.append(sid)


def test_draft_replica_loss_drops_prefix_cache(sched_factory):
    _reset_stats_for_tests()
    s = sched_factory(max_concurrent_jobs=4, fleet_fn=mock_fleet_fn)
    fleet = make_spec_fleet(s)
    plane = FakePrefixPlane()
    fleet.prefix_plane = plane
    fleet.start()
    assert wait_until(lambda: _pools_up(fleet))
    fleet.tick()  # seeds the seen-set with the live draft replica
    # A draft replica that vanished since the last pump (preempt /
    # migrate / scale-down) must have its cache entries dropped.
    with fleet._lock:
        fleet._draft_sids_seen = set(fleet._draft_sids_seen) | {"ghost"}
    fleet.tick()
    assert plane.dropped == ["ghost"]
    assert spec_pool_stats()["draft_cache_invalidations_total"] == 1
    fleet.stop()


# ---------------------------------------------------------------------------
# Admission + placement: draft HBM terms and draft-pool plans
# ---------------------------------------------------------------------------


def test_estimate_serving_hbm_draft_terms():
    plain = estimate_serving_hbm("llama-1b", max_slots=8, max_len=2048)
    spec = estimate_serving_hbm("llama-1b", max_slots=8, max_len=2048,
                                draft_model_name="gpt-tiny")
    assert plain is not None and spec is not None
    # Colocated draft = weights + a second KV pool: strictly more HBM.
    assert spec.device_total_gib > plain.device_total_gib
    assert any("draft" in n for n in spec.notes)
    # Unknown draft model → no estimate, same contract as the target.
    assert estimate_serving_hbm("llama-1b", max_slots=8, max_len=2048,
                                draft_model_name="nope") is None


def test_estimate_serving_hbm_rejects_oversubscribed_draft():
    with pytest.raises(SpecHBMOversubscribed) as ei:
        estimate_serving_hbm("llama-1b", max_slots=8, max_len=2048,
                             draft_model_name="gpt-tiny",
                             device_budget_gib=0.5)
    err = ei.value
    assert isinstance(err, ValueError)
    assert err.reason["kind"] == "spec_hbm_oversubscribed"
    assert err.draft_model_name == "gpt-tiny"
    assert err.required_gib > err.budget_gib == 0.5
    assert err.draft_gib > 0
    # A sane budget admits the same geometry.
    est = estimate_serving_hbm("llama-1b", max_slots=8, max_len=2048,
                               draft_model_name="gpt-tiny",
                               device_budget_gib=64.0)
    assert est is not None


def test_plan_serving_pool_draft_role():
    plans = plan_serving_pool("gpt-tiny", "draft", 4,
                              hbm_free_gib=2.0, max_len=2048)
    feasible = [p for p in plans if p.feasible]
    assert feasible
    assert all(p.role == "draft" for p in plans)
    assert all(p.predicted_propose_s > 0 for p in feasible)
    # Ranked by draft-propose latency (γ sequential memory-bound steps),
    # ties toward fewer chips — drafts backfill fragmented headroom.
    keys = [(p.predicted_propose_s, p.tensor_parallel, -p.max_slots)
            for p in feasible]
    assert keys == sorted(keys)
    assert "draft" in feasible[0].label
    with pytest.raises(ValueError, match="role"):
        plan_serving_pool("gpt-tiny", "oracle", 4)


# ---------------------------------------------------------------------------
# Twin lane: deterministic A/B machinery (full gates ride the slow tier
# and benchmarks/spec_pool_sim.py)
# ---------------------------------------------------------------------------

_FAST_LANE = dict(duration_s=90.0, warmup_s=30.0, spill_window_s=10.0,
                  cooldown_s=20.0)


def test_spec_pool_lane_deterministic_and_spills():
    from tpu_engine.twin import SpecPoolLaneParams, spec_pool_lane

    p = SpecPoolLaneParams(**_FAST_LANE)
    a = spec_pool_lane(0, spec=True, params=p)
    b = spec_pool_lane(0, spec=True, params=p)
    assert a == b  # byte-identical repeat, same seed
    # The junk-draft tenant (α ≈ 0.06) is spilled by the real controller
    # consulting the real historian even on the short trace.
    assert a["spill"]["spilled"] == ["t3"]
    assert len(a["spill_decisions_fired"]) >= 1
    assert a["metrics"]["completed"] > 0
    plain = spec_pool_lane(0, spec=False, params=p)
    assert plain["mode"] == "plain" and "spill" not in plain
    assert plain["total_chips"] == a["total_chips"]


@pytest.mark.slow
def test_spec_pool_ab_gates():
    from tpu_engine.twin import spec_pool_ab, spec_pool_bench_line

    res = spec_pool_ab(seed=0)
    assert res["ok"], res["gates"]
    assert res["tokens_per_sec_per_chip_ratio"] >= 1.2
    line = spec_pool_bench_line(seed=0, ab=res)
    assert line["metric"] == "spec_pool" and line["ok"]


# ---------------------------------------------------------------------------
# Distill smoke: the draft-production recipe at tiny scale on CPU
# ---------------------------------------------------------------------------


def test_spec_decode_distill_smoke():
    from benchmarks.spec_decode_distill import run

    rep = run(
        vocab=64, seq=64, gamma=2, train_steps=6, distill_steps=6,
        target_kw=dict(name="smoke-target", vocab_size=64, d_model=32,
                       n_layers=2, n_heads=2, n_kv_heads=2, d_ff=64,
                       max_seq_len=64),
        draft_kw=dict(name="smoke-draft", vocab_size=64, d_model=16,
                      n_layers=1, n_heads=2, n_kv_heads=2, d_ff=32,
                      max_seq_len=64),
        micro_batch=8, prompt_len=8, n_kd_prompts=4, n_eval_prompts=2,
        max_new=8,
    )
    assert rep["metric"] == "spec_decode_distilled_draft"
    assert rep["spec_rounds"] > 0
    assert rep["spec_tokens_proposed"] >= rep["spec_tokens_accepted"] >= 0
    assert 0.0 <= rep["alpha_accept_rate"] <= 1.0
    # Speculation must not change the stream: greedy target output is
    # authoritative in both modes.
    assert rep["stream_agreement"] >= 0.99
    assert rep["gamma"] == 2 and rep["draft"]["layers"] == 1
