"""The ``comm.py`` knobs must observably change the compiled TPU schedule.

Round-2 VERDICT item 2: the async-collective / latency-hiding flag surface
(``tpu_engine/comm.py:29-37``) had no measurement behind it. This test AOT
compiles one and the same lowered train step twice — knobs ON vs OFF, via
per-compile ``compiler_options`` — and asserts the knobs do real work:
overlap (scheduled start→done distance) expands by at least 2x and the
async-collective fusion pairs appear only in the ON build. Numbers and the
methodology live in ``benchmarks/comm_overlap.py`` + RESULTS.md.

A smaller model than the benchmark's 7B keeps the two compiles test-sized.
"""

from __future__ import annotations

import pytest

from benchmarks.aot import aot_lowered
from benchmarks.comm_overlap import COMM_OFF, COMM_ON, overlap_stats

pytestmark = [pytest.mark.slow, pytest.mark.tpu_aot]


def test_comm_knobs_change_schedule():
    from benchmarks.aot import TopologyUnavailable

    try:
        lowered = aot_lowered(
            "llama-1b", "v5e:2x4", dict(data=1, fsdp=8), seq=2048,
            overrides={"attention_impl": "flash"},
        )
    except TopologyUnavailable as e:  # only missing libtpu skips
        pytest.skip(f"TPU AOT topology unavailable: {e}")

    on = overlap_stats(lowered.compile(compiler_options=COMM_ON).as_text())
    off = overlap_stats(lowered.compile(compiler_options=COMM_OFF).as_text())

    # There are collectives to overlap in the first place (ZeRO-3 gathers).
    assert on["async_total"] + on["async_fusion_pairs"] + on["blocking_total"] > 0
    # The OFF build must not carry async-collective fusion pairs...
    assert off["async_fusion_pairs"] == 0
    # ...and the ON build must overlap at least twice as far as OFF.
    assert on["overlap_distance_mean"] >= 2 * max(off["overlap_distance_mean"], 1), (
        on,
        off,
    )
