"""Comm-tuning surface: flag construction and safe application."""

import os

from tpu_engine.comm import apply_comm_flags, xla_flags_for
from tpu_engine.sharding import TPUTrainConfig


def test_default_flags_enable_overlap():
    flags = xla_flags_for(TPUTrainConfig())
    assert "--xla_tpu_enable_async_collective_fusion=true" in flags
    assert "--xla_tpu_enable_latency_hiding_scheduler=true" in flags


def test_flags_toggle_off():
    cfg = TPUTrainConfig(async_collectives=False, latency_hiding_scheduler=False)
    assert xla_flags_for(cfg) == ""
    cfg2 = TPUTrainConfig(
        async_collectives=False, latency_hiding_scheduler=False,
        xla_extra_flags="--xla_foo=1",
    )
    assert xla_flags_for(cfg2) == "--xla_foo=1"


def test_apply_skips_without_tpu_runtime():
    # Off-TPU, XLA aborts the process on unknown xla_tpu_* flags — apply
    # must leave the environment untouched in this CPU test process.
    before = os.environ.get("XLA_FLAGS", "")
    cfg = TPUTrainConfig(xla_extra_flags="--xla_never_applied=1")
    applied = apply_comm_flags(cfg)
    assert "--xla_never_applied=1" in applied
    assert os.environ.get("XLA_FLAGS", "") == before


def test_apply_warns_with_live_backend(monkeypatch, caplog):
    import logging

    import tpu_engine.comm as comm

    monkeypatch.setattr(comm, "_tpu_runtime_available", lambda: True)
    import jax

    jax.devices()  # ensure initialised
    before = os.environ.get("XLA_FLAGS", "")
    with caplog.at_level(logging.WARNING, logger="tpu_engine.comm"):
        comm.apply_comm_flags(TPUTrainConfig(xla_extra_flags="--xla_never_applied=1"))
    assert os.environ.get("XLA_FLAGS", "") == before
    assert any("already initialised" in r.message for r in caplog.records)


def test_apply_idempotent_when_present(monkeypatch):
    cfg = TPUTrainConfig(
        async_collectives=False, latency_hiding_scheduler=False,
        xla_extra_flags="--xla_already_there=1",
    )
    monkeypatch.setenv("XLA_FLAGS", "--xla_already_there=1")
    applied = apply_comm_flags(cfg)
    assert applied == "--xla_already_there=1"
    assert os.environ["XLA_FLAGS"] == "--xla_already_there=1"


def test_apply_respects_operator_value(monkeypatch):
    # Operator's explicit --flag=false must not be overridden by our =true.
    import tpu_engine.comm as comm

    monkeypatch.setattr(comm, "_tpu_runtime_available", lambda: True)
    monkeypatch.setattr(comm, "_backend_initialized", lambda: False)
    monkeypatch.setenv(
        "XLA_FLAGS", "--xla_tpu_enable_latency_hiding_scheduler=false"
    )
    comm.apply_comm_flags(TPUTrainConfig(async_collectives=False))
    flags = os.environ["XLA_FLAGS"]
    assert flags.count("--xla_tpu_enable_latency_hiding_scheduler") == 1
    assert "--xla_tpu_enable_latency_hiding_scheduler=false" in flags
    # But genuinely-new flags were appended.
    assert "--xla_latency_hiding_scheduler_rerun=1" in flags
