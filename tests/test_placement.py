"""Placement planner: layout enumeration against the config interaction
matrix, the HBM feasibility gate, cost-model ranking, and grow-back
targets — all analytic (no JAX compute), so everything here is tier-1.
"""

import json
from types import SimpleNamespace

import pytest

from tpu_engine.hbm_estimate import HBMEstimate, estimate_job_hbm
from tpu_engine.mesh_runtime import MeshConfig
from tpu_engine.placement import (
    PlacementPlanner,
    _mirror_build_checks,
)
from tpu_engine.models import transformer as tfm
from tpu_engine.sharding import (
    ShardingStage,
    TPUTrainConfig,
    resolve_pipeline_schedule,
)


def cfg(**kw):
    base = dict(
        model_name="gpt-tiny",
        mesh=MeshConfig(data=2, fsdp=4),
        micro_batch_size=2,
        gradient_accumulation_steps=2,
        seq_len=64,
    )
    base.update(kw)
    return TPUTrainConfig(**base)


def chips(n, free=12.0, total=16.0, **kw):
    return [
        SimpleNamespace(index=i, hbm_free_gb=free, hbm_total_gb=total, **kw)
        for i in range(n)
    ]


def fixed_estimate(total_gib):
    def est(c, n=None):
        return HBMEstimate(
            model_name=c.model_name, gang_devices=8,
            params_gib=total_gib, grads_gib=0.0, opt_gib=0.0,
            working_gib=0.0, activations_gib=0.0, logits_gib=0.0,
            device_total_gib=total_gib, host_gib=0.0,
        )

    return est


# ---------------------------------------------------------------------------
# enumerate: the interaction matrix, mirrored
# ---------------------------------------------------------------------------


def test_every_emitted_plan_revalidates():
    """Property: any layout the planner emits survives a FRESH config
    construction (the full validator interaction matrix) plus the
    mirrored build-time checks — the planner can never hand the
    scheduler a config ``build_train_program`` would reject."""
    planner = PlacementPlanner()
    plans, _ = planner.enumerate(
        cfg(), 8, consider_quant=True, consider_comm_compress=True
    )
    assert len(plans) >= 40  # the full cross product is a real search
    model_cfg = tfm.MODEL_CONFIGS["gpt-tiny"]
    for p in plans:
        rebuilt = TPUTrainConfig(**p.config.model_dump())  # must not raise
        _mirror_build_checks(rebuilt, model_cfg)  # must not raise
        assert resolve_pipeline_schedule(rebuilt) == p.pipeline_schedule


def test_known_invalid_combos_are_pruned_not_emitted():
    planner = PlacementPlanner()
    plans, pruned = planner.enumerate(
        cfg(), 8, consider_quant=True, consider_comm_compress=True
    )
    for p in plans:
        # 1f1b/zb × quant_training is a validator reject; comm compression
        # is stage-3 (data, fsdp)-only — neither may survive into plans.
        if p.pipeline_schedule in ("1f1b", "zb"):
            assert p.quant_training == "none"
        if p.comm_compress:
            assert p.mesh["pipe"] == 1 and p.mesh["model"] == 1
    reasons = " ".join(r["reason"] for r in pruned).lower()
    assert "quant" in reasons
    assert len(pruned) > len(plans)  # the cross product mostly dies


def test_pipe_must_divide_layers():
    """gpt-tiny has 2 layers: pipe ∈ {4, 8} cannot stage it and must be
    pruned (build_train_program's n_layers % pipe check, mirrored)."""
    planner = PlacementPlanner()
    plans, pruned = planner.enumerate(cfg(), 8)
    assert plans and all(p.mesh["pipe"] in (1, 2) for p in plans)
    assert any(
        "layers" in r["reason"] or "pipe" in r["reason"] for r in pruned
    )


def test_enumeration_keeps_global_batch_constant():
    base = cfg()  # 2 micro × 2 accum × (2 data × 4 fsdp) = 32 samples
    planner = PlacementPlanner()
    plans, _ = planner.enumerate(base, 8)
    for p in plans:
        samples = (
            p.mesh["data"] * p.mesh["fsdp"]
            * p.micro_batch_size * p.gradient_accumulation_steps
        )
        assert samples == 32, p.label


def test_enumerate_unknown_model_raises_structured():
    with pytest.raises(ValueError, match="no_estimate:nope-9b"):
        PlacementPlanner().enumerate(cfg(model_name="nope-9b"), 8)


# ---------------------------------------------------------------------------
# predict / ranking
# ---------------------------------------------------------------------------


def test_predict_costs_an_explicit_layout():
    planner = PlacementPlanner()
    plan = planner.predict(cfg(), gang=8)
    assert plan.predicted_step_time_s > 0
    # step = max(compute, streamed collectives) + exposed collectives —
    # the fsdp/data plane overlaps with compute, the rest cannot.
    streamed = plan.predicted_comm_s - plan.predicted_exposed_comm_s
    assert plan.predicted_step_time_s == pytest.approx(
        max(plan.predicted_compute_s, streamed)
        + plan.predicted_exposed_comm_s
    )
    with pytest.raises(ValueError, match="no_estimate"):
        planner.predict(cfg(model_name="nope-9b"), gang=8)


def test_ranking_prefers_less_comm_and_less_bubble():
    """Cost-model sanity pinned to the in-tree analytics: stage-2 beats
    stage-3 at equal mesh (no per-microbatch weight gathers), and a
    pipelined layout is charged its schedule_account bubble."""
    planner = PlacementPlanner()
    s2 = planner.predict(
        cfg(mesh=MeshConfig(data=1, fsdp=8),
            sharding_stage=ShardingStage.GRADIENT_PARTITIONING), gang=8)
    s3 = planner.predict(
        cfg(mesh=MeshConfig(data=1, fsdp=8),
            sharding_stage=ShardingStage.FULL_PARTITIONING), gang=8)
    assert s2.predicted_comm_s < s3.predicted_comm_s
    # Same global batch (16 samples), with and without a pipeline bubble:
    # the piped layout's compute is divided by its busy fraction.
    flat = planner.predict(
        cfg(mesh=MeshConfig(data=8), micro_batch_size=1,
            gradient_accumulation_steps=2), gang=8)
    piped = planner.predict(
        cfg(mesh=MeshConfig(data=4, pipe=2), micro_batch_size=1,
            gradient_accumulation_steps=4, pipeline_schedule="gpipe"),
        gang=8)
    assert piped.predicted_bubble_fraction > 0
    assert flat.predicted_bubble_fraction == 0
    assert piped.predicted_compute_s > flat.predicted_compute_s


def test_plan_ranks_feasible_by_predicted_time():
    planner = PlacementPlanner()
    result = planner.plan(cfg(), devices=chips(8), gang=8)
    assert result.plans and result.best is result.plans[0]
    times = [p.predicted_step_time_s for p in result.plans]
    assert times == sorted(times)
    rows = result.table(top_k=3)
    assert len(rows) == 3 and rows[0]["rank"] == 1
    assert planner.stats()["plans_evaluated_total"] == result.evaluated


# ---------------------------------------------------------------------------
# HBM feasibility gate
# ---------------------------------------------------------------------------


def test_hbm_filter_rejects_on_headroom_and_reservations():
    planner = PlacementPlanner(estimate_fn=fixed_estimate(10.0))
    # 10 GiB estimate + the 35% compile-temporary margin = 13.5 needed.
    fits = planner.plan(cfg(), devices=chips(8, free=14.0), gang=8)
    assert fits.plans and not fits.infeasible
    # Live headroom below the projection: every layout lands infeasible
    # with a structured reason, none silently dropped.
    starved = planner.plan(cfg(), devices=chips(8, free=4.0), gang=8)
    assert not starved.plans and starved.infeasible
    assert all("headroom" in p.skip_reason for p in starved.infeasible)
    # A reservation ledger eats the headroom the free gauge still shows.
    reserved = planner.plan(
        cfg(), devices=chips(8, free=14.0),
        reserved={i: 5.0 for i in range(8)}, gang=8,
    )
    assert not reserved.plans


def test_hbm_filter_degrades_without_telemetry():
    planner = PlacementPlanner()
    # No fleet view at all → capacity-only (feasible).
    assert planner.plan(cfg(), gang=8).plans
    # Fleet present but no HBM telemetry (CPU chips report 0 total).
    cpu = planner.plan(cfg(), devices=chips(8, free=0.0, total=0.0), gang=8)
    assert cpu.plans
    # Fewer chips than the gang is still a hard reject.
    small = planner.plan(cfg(), devices=chips(4), gang=8)
    assert not small.plans
    assert all("eligible" in p.skip_reason for p in small.infeasible)


def test_plan_unknown_model_refuses_with_structured_reason():
    planner = PlacementPlanner()
    result = planner.plan(cfg(model_name="nope-9b"), gang=8)
    assert result.skip_reason == "no_estimate:nope-9b"
    assert not result.plans and result.evaluated == 0
    assert planner.stats()["no_estimate_refusals_total"] == 1


# ---------------------------------------------------------------------------
# best-available gang search
# ---------------------------------------------------------------------------


def test_plan_best_available_prefers_largest_feasible_gang():
    planner = PlacementPlanner()
    elastic = cfg(mesh=MeshConfig(data=-1, fsdp=2), elastic_min_devices=2)
    result = planner.plan(elastic, devices=chips(8), n_avail=8)
    assert result.best.gang == 8
    # On a 6-chip remainder the same submission lands on 6.
    degraded = planner.plan(elastic, devices=chips(6), n_avail=6)
    assert degraded.best.gang == 6


# ---------------------------------------------------------------------------
# grow-back targets
# ---------------------------------------------------------------------------


def _elastic():
    return cfg(
        mesh=MeshConfig(data=4, fsdp=2), elastic_min_devices=2,
        micro_batch_size=1, gradient_accumulation_steps=1,
    )


def test_grow_target_full_gang_when_it_fits():
    planner = PlacementPlanner()
    assert planner.grow_target(
        _elastic(), chips(8), {}, current_gang=6,
        estimate_fn=estimate_job_hbm,
    ) == 8


def test_grow_target_intermediate_mesh_when_full_does_not_fit():
    """7 healthy chips: the full data=4×fsdp=2 gang cannot be placed, but
    the elastic family's data=3×fsdp=2 on 6 can — the partial grow the
    old full-gang-only logic never found."""
    planner = PlacementPlanner()
    assert planner.grow_target(
        _elastic(), chips(7), {}, current_gang=4,
        estimate_fn=estimate_job_hbm,
    ) == 6


def test_grow_target_none_when_no_larger_mesh_fits():
    planner = PlacementPlanner()
    assert planner.grow_target(
        _elastic(), chips(7), {}, current_gang=6,
        estimate_fn=estimate_job_hbm,
    ) is None


def test_grow_target_is_hbm_gated():
    """Chips exist but their headroom (minus other jobs' reservations)
    cannot hold the projection — growing would only preempt into a
    re-shrink flap, so the target must be None."""
    planner = PlacementPlanner()

    big_est = fixed_estimate(10.0)
    assert planner.grow_target(
        _elastic(), chips(8, free=14.0), {}, current_gang=6,
        estimate_fn=big_est,
    ) == 8
    assert planner.grow_target(
        _elastic(), chips(8, free=14.0), {i: 5.0 for i in range(8)},
        current_gang=6, estimate_fn=big_est,
    ) is None


# ---------------------------------------------------------------------------
# counters
# ---------------------------------------------------------------------------


def test_stats_and_observation_plane():
    planner = PlacementPlanner()
    result = planner.plan(cfg(), devices=chips(8), gang=8)
    planner.note_chosen(result.best)
    planner.record_observation(predicted_s=2.0, observed_s=1.0)
    st = planner.stats()
    assert st["plans_chosen_total"] == 1
    assert st["last_feasible"] == len(result.plans)
    assert st["last_chosen_predicted_s"] == result.best.predicted_step_time_s
    assert st["observations_total"] == 1
    assert st["step_time_abs_rel_error"] == pytest.approx(1.0)
    assert st["prune_reasons"]  # top prune reasons surface for operators


def test_throughput_fn_scales_the_cost_model():
    """PR 11: per-device relative throughput is a cost-model input — a
    degraded gang predicts proportionally slower, and an absent (or
    healthy) throughput feed leaves every prediction byte-identical."""
    base = PlacementPlanner()
    healthy = PlacementPlanner(throughput_fn=lambda: [1.0] * 8)
    slow = PlacementPlanner(throughput_fn=lambda: [0.5] * 8)

    r_base = base.plan(cfg(), devices=chips(8), gang=8)
    r_healthy = healthy.plan(cfg(), devices=chips(8), gang=8)
    r_slow = slow.plan(cfg(), devices=chips(8), gang=8)
    assert r_healthy.best.predicted_step_time_s == r_base.best.predicted_step_time_s
    assert r_base.best.assumed_rel_throughput == 1.0
    assert r_slow.best.assumed_rel_throughput == pytest.approx(0.5)
    assert r_slow.best.predicted_step_time_s > r_base.best.predicted_step_time_s

    # A throughput feed that dies must never take planning down with it.
    def boom():
        raise RuntimeError("hetero plane gone")

    broken = PlacementPlanner(throughput_fn=boom)
    r_broken = broken.plan(cfg(), devices=chips(8), gang=8)
    assert r_broken.best.predicted_step_time_s == r_base.best.predicted_step_time_s
    assert broken.stats()["throughput_fn_attached"] is True


def test_calibration_sidecar_persists_and_reloads(tmp_path):
    """record_observation() calibration survives a planner restart via the
    compile-index-style atomic sidecar, and surfaces in stats()."""
    cache = str(tmp_path)
    planner = PlacementPlanner(calibration_path=cache)
    planner.record_observation(predicted_s=2.0, observed_s=1.0)
    planner.record_observation(predicted_s=1.0, observed_s=1.0)
    st = planner.stats()["calibration"]
    assert st["attached"] is True
    assert st["observations_total"] == 2
    # EMA(alpha=0.3) over rel errors [1.0, 0.0] -> 0.7.
    assert st["ema_rel_error"] == pytest.approx(0.7)
    assert st["persist_errors_total"] == 0
    sidecar = tmp_path / PlacementPlanner.CALIBRATION_SIDECAR
    assert sidecar.exists()

    # A fresh planner (the post-restart scheduler) resumes the EMA.
    reborn = PlacementPlanner(calibration_path=cache)
    st2 = reborn.stats()["calibration"]
    assert st2["ema_rel_error"] == pytest.approx(0.7)
    assert st2["observations_total"] == 2

    # Persistence failures degrade to a counter, never an exception.
    blocker = tmp_path / "blocker"
    blocker.write_text("not a directory")
    fragile = PlacementPlanner(calibration_path=cache)
    fragile._calibration_path = str(blocker / "sub" / "x.json")
    fragile.record_observation(predicted_s=2.0, observed_s=1.0)
    assert fragile.stats()["calibration"]["persist_errors_total"] == 1


def test_calibration_sidecar_tolerates_torn_and_garbage_files(tmp_path):
    """Truncated / garbage calibration sidecars warn + count + start fresh."""
    cache = str(tmp_path)
    sidecar = tmp_path / PlacementPlanner.CALIBRATION_SIDECAR
    # Torn mid-write: a prefix of a JSON document.
    sidecar.write_text('{"version": 1, "ema_rel_error": 0.')
    planner = PlacementPlanner(calibration_path=cache)
    st = planner.stats()["calibration"]
    assert st["load_errors_total"] == 1
    assert st["ema_rel_error"] is None
    # The planner still calibrates and re-persists an intact sidecar.
    planner.record_observation(predicted_s=2.0, observed_s=1.0)
    assert json.loads(sidecar.read_text())["observations_total"] == 1

    # Valid JSON, wrong shape (not an object).
    sidecar.write_text("[0.7, 2]")
    p2 = PlacementPlanner(calibration_path=cache)
    assert p2.stats()["calibration"]["load_errors_total"] == 1
    # Valid object, garbage field types.
    sidecar.write_text('{"ema_rel_error": "NaN-ish", "observations_total": "x"}')
    p3 = PlacementPlanner(calibration_path=cache)
    st3 = p3.stats()["calibration"]
    assert st3["load_errors_total"] == 1
    assert st3["ema_rel_error"] is None
