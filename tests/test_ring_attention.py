"""Ring attention correctness: forward + gradients vs full attention."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_engine.mesh_runtime import MeshConfig, build_mesh
from tpu_engine.ops.flash_attention import mha
from tpu_engine.parallel.ring_attention import ring_mha


def _rand_qkv(key, B=4, S=64, H=4, KV=4, D=16, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, S, H, D), dtype)
    k = jax.random.normal(kk, (B, S, KV, D), dtype)
    v = jax.random.normal(kv, (B, S, KV, D), dtype)
    return q, k, v


@pytest.mark.parametrize("seq_axis", [2, 4])
def test_ring_matches_full_attention(seq_axis):
    mesh = build_mesh(MeshConfig(sequence=seq_axis))
    q, k, v = _rand_qkv(jax.random.PRNGKey(0))
    ref = mha(q, k, v, causal=True, force_xla=True)
    out = jax.jit(lambda q, k, v: ring_mha(q, k, v, mesh=mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ring_gqa():
    mesh = build_mesh(MeshConfig(sequence=4))
    q, k, v = _rand_qkv(jax.random.PRNGKey(1), H=8, KV=2)
    ref = mha(q, k, v, causal=True, force_xla=True)
    out = jax.jit(lambda q, k, v: ring_mha(q, k, v, mesh=mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ring_gradients_match():
    mesh = build_mesh(MeshConfig(sequence=4))
    q, k, v = _rand_qkv(jax.random.PRNGKey(2), S=32)

    def loss_ring(q, k, v):
        return jnp.sum(ring_mha(q, k, v, mesh=mesh) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha(q, k, v, causal=True, force_xla=True) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-4)


def test_ring_with_combined_mesh_axes():
    # sequence parallel composes with data/fsdp/model sharding.
    mesh = build_mesh(MeshConfig(data=1, fsdp=2, sequence=2, model=2))
    q, k, v = _rand_qkv(jax.random.PRNGKey(3), B=4, S=32, H=4, KV=4)
    ref = mha(q, k, v, causal=True, force_xla=True)
    out = jax.jit(lambda q, k, v: ring_mha(q, k, v, mesh=mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


# Compile-heavy module: excluded from the fast core run (pytest -m "not slow").
pytestmark = pytest.mark.slow


# -- Pallas flash kernel per hop (round 3) ------------------------------------


@pytest.mark.parametrize("seq_axis,S", [(2, 256), (4, 256)])
def test_ring_flash_path_matches_full_attention(monkeypatch, seq_axis, S):
    """At tileable local shards (Sq >= 64) the ring routes every hop through
    the Pallas kernel — verify the path is actually taken AND matches full
    attention."""
    import tpu_engine.parallel.ring_attention as ra

    calls = []
    real = ra.flash_fwd_lse
    monkeypatch.setattr(
        ra, "flash_fwd_lse",
        lambda *a, **kw: (calls.append(1) or real(*a, **kw)),
    )
    mesh = build_mesh(MeshConfig(sequence=seq_axis))
    q, k, v = _rand_qkv(jax.random.PRNGKey(5), B=4, S=S, H=4, KV=4, D=64)
    ref = mha(q, k, v, causal=True, force_xla=True)
    out = jax.jit(lambda q, k, v: ring_mha(q, k, v, mesh=mesh))(q, k, v)
    assert calls, "flash kernel path was not taken"
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ring_flash_gradients_match(monkeypatch):
    """Gradients through the per-hop kernel + LSE merge (including the lse
    cotangent folded via the Δ' substitution) match full attention."""
    import tpu_engine.parallel.ring_attention as ra

    calls = []
    real = ra.flash_fwd_lse
    monkeypatch.setattr(
        ra, "flash_fwd_lse",
        lambda *a, **kw: (calls.append(1) or real(*a, **kw)),
    )
    mesh = build_mesh(MeshConfig(sequence=2))
    q, k, v = _rand_qkv(jax.random.PRNGKey(6), B=4, S=128, H=2, KV=2, D=64)

    def loss_ring(q, k, v):
        return jnp.sum(ring_mha(q, k, v, mesh=mesh) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha(q, k, v, causal=True, force_xla=True) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    assert calls, "flash kernel path was not taken"
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


def test_ring_flash_gqa(monkeypatch):
    import tpu_engine.parallel.ring_attention as ra

    calls = []
    real = ra.flash_fwd_lse
    monkeypatch.setattr(
        ra, "flash_fwd_lse",
        lambda *a, **kw: (calls.append(1) or real(*a, **kw)),
    )
    mesh = build_mesh(MeshConfig(sequence=2))
    q, k, v = _rand_qkv(jax.random.PRNGKey(7), B=4, S=128, H=8, KV=2, D=64)
    ref = mha(q, k, v, causal=True, force_xla=True)
    out = jax.jit(lambda q, k, v: ring_mha(q, k, v, mesh=mesh))(q, k, v)
    assert calls
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
