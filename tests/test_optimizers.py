"""Optimizer/schedule surface: adafactor, lion, LR shapes, decay masking."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_engine.mesh_runtime import MeshConfig
from tpu_engine.models import transformer as tfm
from tpu_engine.sharding import Precision, ShardingStage, TPUTrainConfig
from tpu_engine.train import build_train_program, make_optimizer, make_schedule


def _cfg(**kw):
    base = dict(
        model_name="gpt-tiny",
        sharding_stage=ShardingStage.FULL_PARTITIONING,
        mesh=MeshConfig(data=2, fsdp=4),
        micro_batch_size=1,
        gradient_accumulation_steps=2,
        seq_len=32,
        precision=Precision.FP32,
        learning_rate=1e-2,
        warmup_steps=2,
        total_steps=100,
        activation_checkpointing=False,
    )
    base.update(kw)
    return TPUTrainConfig(**base)


def _opt_state_size(state):
    return sum(x.size for x in jax.tree.leaves(state["opt_state"]))


@pytest.mark.parametrize("opt", ["adafactor", "lion"])
def test_alternative_optimizers_train(opt):
    prog = build_train_program(_cfg(optimizer=opt, learning_rate=3e-3))
    state = prog.init(jax.random.PRNGKey(0))
    batch = prog.synthetic_batch(0)
    losses = []
    for _ in range(8):
        state, m = prog.step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0], (opt, losses)


def test_adafactor_state_is_factored_smaller():
    # Factoring needs dims >= optax's 128 threshold — use the 125M shapes.
    import optax

    model_cfg = tfm.MODEL_CONFIGS["gpt-125m"]
    shapes = jax.eval_shape(
        lambda: tfm.init_params(jax.random.PRNGKey(0), model_cfg)
    )
    n_params = tfm.param_count(model_cfg)
    s_fact = sum(
        x.size for x in jax.tree.leaves(
            jax.eval_shape(optax.scale_by_factored_rms().init, shapes)
        )
    )
    s_adam = sum(
        x.size for x in jax.tree.leaves(
            jax.eval_shape(optax.scale_by_adam().init, shapes)
        )
    )
    # Adam keeps mu+nu (2 × params); factored second moments are far smaller.
    assert s_adam >= 2 * n_params
    assert s_fact < 0.1 * n_params


def test_lion_keeps_single_moment():
    prog = build_train_program(_cfg(optimizer="lion"))
    n_params = tfm.param_count(prog.model_config)
    s = _opt_state_size(prog.init(jax.random.PRNGKey(0)))
    assert n_params <= s < 1.1 * n_params


@pytest.mark.parametrize("shape", ["linear", "constant", "rsqrt"])
def test_schedule_shapes(shape):
    cfg = _cfg(lr_schedule=shape, warmup_steps=10, total_steps=100,
               learning_rate=1e-2, min_lr=1e-4)
    sched = make_schedule(cfg)
    lrs = np.asarray([float(sched(s)) for s in range(100)])
    assert lrs[0] < lrs[9]  # warmup ramps
    np.testing.assert_allclose(lrs[10], 1e-2, rtol=1e-2)
    if shape == "constant":
        np.testing.assert_allclose(lrs[10:], 1e-2, rtol=1e-6)
    elif shape == "linear":
        assert lrs[-1] < 3e-4  # heads to min_lr
        assert np.all(np.diff(lrs[10:]) <= 1e-12)
    else:  # rsqrt: monotone decreasing, slower than linear
        assert np.all(np.diff(lrs[11:]) < 0)
        np.testing.assert_allclose(lrs[99], 1e-2 * (10 / 99) ** 0.5, rtol=0.1)


def test_weight_decay_skips_norms_by_default():
    cfg = _cfg(weight_decay=0.1)
    model_cfg = tfm.MODEL_CONFIGS["gpt-tiny"]
    params = tfm.init_params(jax.random.PRNGKey(0), model_cfg)
    tx, _ = make_optimizer(cfg)
    opt_state = tx.init(params)
    zero = jax.tree.map(jnp.zeros_like, params)
    updates, _ = tx.update(zero, opt_state, params)
    # Zero grads → Adam term 0; only the decay term remains.
    assert float(jnp.max(jnp.abs(updates["layers"]["attn_norm"]["scale"]))) == 0.0
    assert float(jnp.max(jnp.abs(updates["final_norm"]["scale"]))) == 0.0
    assert float(jnp.max(jnp.abs(updates["embed"]["embedding"]))) == 0.0
    assert float(jnp.max(jnp.abs(updates["layers"]["q"]["kernel"]))) > 0.0
    assert float(jnp.max(jnp.abs(updates["lm_head"]["kernel"]))) > 0.0
    # decay_all_params=True restores the reference's blanket decay.
    tx_all, _ = make_optimizer(_cfg(weight_decay=0.1, decay_all_params=True))
    upd_all, _ = tx_all.update(zero, tx_all.init(params), params)
    assert float(jnp.max(jnp.abs(upd_all["final_norm"]["scale"]))) > 0.0


def test_adafactor_rejects_moment_dtype():
    with pytest.raises(ValueError, match="adafactor"):
        make_optimizer(_cfg(optimizer="adafactor", moment_dtype=Precision.BF16))


def test_lora_adapters_are_decayed():
    from tpu_engine.lora import init_lora_params

    cfg = _cfg(weight_decay=0.1, lora_rank=4)
    model_cfg = tfm.MODEL_CONFIGS["gpt-tiny"]
    adapters = init_lora_params(jax.random.PRNGKey(0), model_cfg, 4, ("q",))
    tx, _ = make_optimizer(cfg)
    zero = jax.tree.map(jnp.zeros_like, adapters)
    updates, _ = tx.update(zero, tx.init(adapters), adapters)
    # A is nonzero at init → its decay term must appear.
    assert float(jnp.max(jnp.abs(updates["layers"]["q"]["A"]))) > 0.0


def test_rsqrt_respects_min_lr_floor():
    cfg = _cfg(lr_schedule="rsqrt", warmup_steps=10, learning_rate=1e-2,
               min_lr=5e-3, total_steps=100)
    sched = make_schedule(cfg)
    assert float(sched(100_000)) == pytest.approx(5e-3)
