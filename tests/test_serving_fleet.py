"""Serving fleet: KV-gated admission, preempt round trip, router, autoscaler.

Fast tier: replicas run stub engines (no JAX compute) through the real
:class:`~tpu_engine.scheduler.FleetScheduler` +
:class:`~tpu_engine.serving_fleet.ServingFleet` machinery; one test builds
a real tiny :class:`ContinuousBatcher` through the default engine factory.
"""

import threading
import time

import pytest

from tpu_engine.hbm_estimate import estimate_serving_hbm
from tpu_engine.scheduler import FleetScheduler, JobPriority, SubmissionState
from tpu_engine.serving_fleet import (
    AutoscalerConfig,
    FleetRouter,
    ReplicaAutoscaler,
    ServingFleet,
    ServingReplicaSpec,
)
from tpu_engine.sharding import Precision
from tpu_engine.supervisor import JobStatus
from tpu_engine.tpu_manager import TPUManager


def wait_until(pred, timeout=5.0, interval=0.01):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


class StubEngine:
    """ContinuousBatcher stand-in: instant decode, real surface."""

    def __init__(self, spec):
        self.slots = int(spec.max_slots)
        self._reqs = {}
        self._seq = 0
        self._lock = threading.Lock()

    def submit(self, prompt, max_new_tokens=64, temperature=0.0):
        with self._lock:
            self._seq += 1
            self._reqs[self._seq] = {"need": int(max_new_tokens), "tokens": []}
            return self._seq

    def step(self):
        out = 0
        with self._lock:
            for r in self._reqs.values():
                if len(r["tokens"]) < r["need"]:
                    r["tokens"].append(1)
                    out += 1
        return out

    def result(self, rid):
        with self._lock:
            r = self._reqs[rid]
            done = len(r["tokens"]) >= r["need"]
            return {
                "status": "done" if done else "running",
                "tokens": list(r["tokens"]),
            }

    def stats(self):
        with self._lock:
            active = sum(
                1 for r in self._reqs.values() if len(r["tokens"]) < r["need"]
            )
        return {
            "slots": self.slots, "active_slots": active, "prefilling": 0,
            "queued": 0, "tokens_per_sec_recent": 100.0,
        }


class StubWatcher:
    def __init__(self):
        self.fired = threading.Event()

    def simulate_interruption(self):
        self.fired.set()


class StubTrainJob:
    """Thread-backed TrainingJob stand-in (test_scheduler.py idiom)."""

    def __init__(self, sub):
        self.job_id = sub.job_id
        self.config = sub.config
        self.status = JobStatus.PENDING
        self.error = None
        self.current_step = 0
        self.watcher = StubWatcher()
        self._stop = threading.Event()
        self._done = threading.Event()
        self._final = JobStatus.COMPLETED
        self._thread = threading.Thread(target=self._run, daemon=True)

    @property
    def is_alive(self):
        return self._thread.is_alive()

    def start(self):
        self._thread.start()

    def join(self, timeout=None):
        self._thread.join(timeout)

    def describe(self):
        return {"job_id": self.job_id, "status": self.status.value}

    def finish(self, status=JobStatus.COMPLETED):
        self._final = status
        self._done.set()

    def _run(self):
        self.status = JobStatus.RUNNING
        while not self._done.is_set():
            if self._stop.is_set():
                self.status = JobStatus.STOPPED
                return
            if self.watcher.fired.is_set():
                self.status = JobStatus.PREEMPTED
                return
            self._done.wait(0.005)
        self.status = self._final


@pytest.fixture
def sched_factory():
    created = []

    def make(**kw):
        jobs = []

        def factory(sub):
            job = StubTrainJob(sub)
            jobs.append(job)
            return job

        kw.setdefault("job_factory", factory)
        kw.setdefault("poll_interval_s", 0.01)
        kw.setdefault("grow_back_cooldown_s", 0.0)
        s = FleetScheduler(**kw)
        s._stub_jobs = jobs
        created.append(s)
        return s

    yield make
    for s in created:
        for j in getattr(s, "_stub_jobs", []):
            j.finish()
        s.shutdown()


def small_spec(**kw):
    base = dict(model_name="gpt-tiny", max_slots=4, max_len=128)
    base.update(kw)
    return ServingReplicaSpec(**base)


def make_fleet(sched, spec=None, **kw):
    kw.setdefault("engine_factory", StubEngine)
    kw.setdefault(
        "autoscaler",
        ReplicaAutoscaler(AutoscalerConfig(min_replicas=1, max_replicas=4)),
    )
    return ServingFleet(sched, spec or small_spec(), **kw)


def mock_fleet_fn():
    return TPUManager().get_mock_fleet()


# ---------------------------------------------------------------------------
# estimate_serving_hbm: the KV-pool admission plane
# ---------------------------------------------------------------------------


def test_estimate_serving_kv_pool_plane():
    est = estimate_serving_hbm("gpt-tiny", max_slots=8, max_len=256)
    assert est is not None and est.gang_devices == 1
    # Serving has no training planes; the KV pool is first-class.
    assert est.grads_gib == 0 and est.opt_gib == 0 and est.activations_gib == 0
    assert est.kv_pool_gib > 0
    assert est.device_total_gib >= est.params_gib + est.kv_pool_gib
    # KV pool scales with the slot pool.
    est2 = estimate_serving_hbm("gpt-tiny", max_slots=16, max_len=256)
    assert est2.kv_pool_gib == pytest.approx(2 * est.kv_pool_gib, rel=1e-6)


def test_estimate_serving_int8_kv_halves_pool():
    bf16 = estimate_serving_hbm("gpt-125m", max_slots=8, max_len=1024)
    int8 = estimate_serving_hbm(
        "gpt-125m", max_slots=8, max_len=1024, kv_quant=True
    )
    # int8 codes + per-(lane, head) fp32 scales: just over half of bf16.
    assert int8.kv_pool_gib < 0.6 * bf16.kv_pool_gib
    assert int8.kv_pool_gib > 0.5 * bf16.kv_pool_gib
    assert "int8 codes" in " / ".join(int8.notes)


def test_estimate_serving_weight_quant_and_tp():
    bf16 = estimate_serving_hbm("gpt-125m", max_slots=4, max_len=512)
    int8 = estimate_serving_hbm(
        "gpt-125m", max_slots=4, max_len=512, weight_quant="int8"
    )
    assert int8.params_gib < 0.6 * bf16.params_gib
    tp2 = estimate_serving_hbm(
        "gpt-125m", max_slots=4, max_len=512, tensor_parallel=2
    )
    assert tp2.gang_devices == 2
    assert tp2.params_gib == pytest.approx(bf16.params_gib / 2, rel=1e-2)
    # gpt-125m has 12 KV heads: divisible by tp=2 → KV pool shards too.
    assert tp2.kv_pool_gib == pytest.approx(bf16.kv_pool_gib / 2, rel=1e-2)


def test_estimate_serving_unknown_model_is_none():
    assert estimate_serving_hbm("no-such-model", 4, 128) is None


def test_spec_estimate_matches_module_fn():
    spec = small_spec(kv_quant=True, compute_dtype=Precision.BF16)
    est = spec.estimate()
    direct = estimate_serving_hbm(
        "gpt-tiny", max_slots=4, max_len=128, kv_quant=True
    )
    assert est.device_total_gib == direct.device_total_gib


# ---------------------------------------------------------------------------
# Scheduler integration: shared queue, HBM ledger, preempt round trip
# ---------------------------------------------------------------------------


def test_serving_submission_shares_queue_and_ledger(sched_factory):
    s = sched_factory(max_concurrent_jobs=2, fleet_fn=mock_fleet_fn)
    fleet = make_fleet(s)
    fleet.start()
    assert wait_until(lambda: len(fleet.running_replicas()) == 1)
    (sub,) = fleet._replicas.values()
    # First-class submission: same state machine, workload tagged, and the
    # replica's KV pool holds a real per-device HBM reservation.
    assert sub.state == SubmissionState.RUNNING
    assert sub.describe()["workload"] == "serving"
    assert sub.estimate is not None and sub.estimate.kv_pool_gib > 0
    st = s.stats()
    assert st["running_serving"] == 1
    assert st["reserved_hbm_gib"] > 0
    fleet.stop()
    assert wait_until(lambda: sub.state == SubmissionState.CANCELLED)
    assert s.stats()["reserved_hbm_gib"] == 0.0


def test_kv_pool_rejects_oversubscribed_fleet(sched_factory):
    # 64 slots × 8192 lanes of bf16 KV on gpt-125m ≈ 18 GiB/device — more
    # than the mock fleet's 9.6 GiB free per chip. The shared HBM gate must
    # hold the replica in the queue, not admit-and-OOM.
    big = ServingReplicaSpec(model_name="gpt-125m", max_slots=64, max_len=8192)
    assert big.estimate().device_total_gib > 9.6
    s = sched_factory(max_concurrent_jobs=2, fleet_fn=mock_fleet_fn)
    fleet = make_fleet(s, spec=big)
    fleet.start()
    time.sleep(0.15)
    (sub,) = fleet._replicas.values()
    assert sub.state == SubmissionState.QUEUED
    assert "have that headroom" in sub.last_skip_reason
    assert s.stats()["reserved_hbm_gib"] == 0.0
    fleet.stop()


def test_critical_training_preempts_replica_round_trip(sched_factory):
    """Teardown → training admitted → replica re-admitted on drain."""
    from tests.test_scheduler import cfg as train_cfg

    s = sched_factory(max_concurrent_jobs=1, fleet_fn=mock_fleet_fn)
    fleet = make_fleet(s)
    fleet.start()
    assert wait_until(lambda: len(fleet.running_replicas()) == 1)
    (replica,) = fleet._replicas.values()

    # A CRITICAL training job arrives: the replica is preemptible without
    # a checkpoint (stateless above its snapshot) — checkpoint-free
    # teardown, training takes the slot.
    training = s.submit(train_cfg(), priority=JobPriority.CRITICAL)
    assert wait_until(lambda: training.state == SubmissionState.RUNNING)
    assert replica.state == SubmissionState.QUEUED  # requeued, not dead
    assert replica.preemptions == 1
    assert replica.job is None
    assert len(fleet.running_replicas()) == 0
    assert s.stats()["preemptions_total"] == 1

    # A request submitted while evicted holds fleet-side.
    rid = fleet.submit_request([1, 2, 3], max_new_tokens=4)
    assert fleet.result(rid)["status"] == "pending"

    # Training drains → the SAME submission re-admits a fresh engine and
    # the held request completes on it.
    s._stub_jobs[-1].finish()
    assert wait_until(lambda: training.state == SubmissionState.COMPLETED)
    assert wait_until(lambda: replica.state == SubmissionState.RUNNING)
    assert replica.attempts == 2
    assert wait_until(lambda: fleet.result(rid)["status"] == "done")
    fleet.stop()


def test_fleet_scale_to_submits_and_cancels(sched_factory):
    s = sched_factory(max_concurrent_jobs=4, fleet_fn=mock_fleet_fn)
    fleet = make_fleet(s)
    fleet.scale_to(3)
    assert wait_until(lambda: len(fleet.running_replicas()) == 3)
    assert s.stats()["running_serving"] == 3
    fleet.scale_to(1)
    assert wait_until(lambda: len(fleet.running_replicas()) == 1)
    assert wait_until(lambda: s.stats()["running_serving"] == 1)
    fleet.stop()


def test_fleet_routes_requests_across_replicas(sched_factory):
    s = sched_factory(max_concurrent_jobs=4, fleet_fn=mock_fleet_fn)
    fleet = make_fleet(s)
    fleet.scale_to(2)
    assert wait_until(lambda: len(fleet.running_replicas()) == 2)
    rids = [
        fleet.submit_request([i, i + 1], max_new_tokens=3) for i in range(6)
    ]
    assert all(
        wait_until(lambda r=r: fleet.result(r)["status"] == "done")
        for r in rids
    )
    st = fleet.status()
    assert st["completed_total"] == 6
    assert st["tokens_total"] == 18
    assert st["p99_latency_ms"] is not None
    fleet.stop()


# ---------------------------------------------------------------------------
# FleetRouter
# ---------------------------------------------------------------------------


def _stats(tps, free, slots=8):
    return {"tokens_per_sec": tps, "free_slots": free, "slots": slots}


def test_router_weights_follow_throughput():
    r = FleetRouter(affinity_tokens=0)
    r.update({"fast": _stats(90.0, 8), "slow": _stats(10.0, 8)})
    picks = [r.route() for _ in range(100)]
    # Smooth WRR: traffic split tracks the ~9:1 throughput ratio.
    assert picks.count("fast") > 75
    assert picks.count("slow") >= 5  # degraded still serves, gated not binary


def test_router_starves_full_replica():
    r = FleetRouter(affinity_tokens=0)
    r.update({"full": _stats(90.0, 0), "free": _stats(30.0, 8)})
    picks = [r.route() for _ in range(20)]
    # free-slot fraction ≈ 0 crushes the busy replica's weight.
    assert picks.count("free") >= 18


def test_router_prefix_affinity_sticks_and_survives_teardown():
    r = FleetRouter(affinity_tokens=4)
    r.update({"a": _stats(50.0, 8), "b": _stats(50.0, 8)})
    prompt = [7, 7, 7, 7, 99]
    first = r.route(prompt)
    # Same prefix keeps landing on the same replica while it has slots.
    for i in range(5):
        assert r.route([7, 7, 7, 7, 100 + i]) == first
    assert r.affinity_hits == 5
    # The sticky replica disappears (preempted): affinity is dropped and
    # the prefix re-pins to a live replica instead of routing into a void.
    other = "b" if first == "a" else "a"
    r.update({other: _stats(50.0, 8)})
    assert r.route([7, 7, 7, 7, 200]) == other


def test_router_busy_fallthrough_keeps_live_pin():
    """A momentarily-full pinned replica must not lose its pin: the
    fall-through dispatch goes elsewhere, but the NEXT route with a free
    slot returns to the replica that still holds the prefix KV."""
    r = FleetRouter(affinity_tokens=4)
    r.update({"a": _stats(50.0, 8), "b": _stats(50.0, 8)})
    prompt = [3, 3, 3, 3, 1]
    pinned = r.route(prompt)
    other = "b" if pinned == "a" else "a"
    # Alternate: pinned replica full (fall-through) / free again. Before
    # the fix each fall-through re-pinned to the OTHER replica, so the
    # prefix ping-ponged and never re-used its cache.
    for i in range(6):
        r.update({pinned: _stats(50.0, 0), other: _stats(50.0, 8)})
        assert r.route([3, 3, 3, 3, 10 + i]) == other
        r.update({pinned: _stats(50.0, 8), other: _stats(50.0, 8)})
        assert r.route([3, 3, 3, 3, 20 + i]) == pinned
    # The pin is only released when its target actually dies.
    r.update({other: _stats(50.0, 8)})
    assert r.route([3, 3, 3, 3, 99]) == other


def test_router_affinity_hits_pay_wrr_share():
    """Affinity picks run the same smooth-WRR ledger as fair rotation:
    under an interleaved affinity/cold stream on equal-weight replicas,
    long-run total traffic still splits by weight (the old hit path
    skipped the ledger, skewing totals ~75/25)."""
    r = FleetRouter(affinity_tokens=4)
    r.update({"a": _stats(50.0, 8), "b": _stats(50.0, 8)})
    hot = [5, 5, 5, 5, 0]
    pinned = r.route(hot)
    counts = {"a": 1 if pinned == "a" else 0, "b": 1 if pinned == "b" else 0}
    for i in range(200):
        r.update({"a": _stats(50.0, 8), "b": _stats(50.0, 8)})
        counts[r.route([5, 5, 5, 5, i])] += 1   # affinity hit -> pinned
        counts[r.route([i, 1000 + i])] += 1     # cold -> WRR
    total = sum(counts.values())
    assert counts[pinned] == 201  # every hot prompt stuck to its pin
    # Equal weights -> both replicas within 45-55% of total traffic.
    for rid in ("a", "b"):
        assert 0.45 <= counts[rid] / total <= 0.55, counts


# ---------------------------------------------------------------------------
# ReplicaAutoscaler
# ---------------------------------------------------------------------------


def _scaler(**kw):
    base = dict(
        min_replicas=1, max_replicas=4, target_queue_per_replica=4.0,
        low_water_queue_per_replica=0.5, p99_slo_ms=1000.0, window_s=10.0,
        scale_up_cooldown_s=2.0, scale_down_cooldown_s=30.0,
    )
    base.update(kw)
    return ReplicaAutoscaler(AutoscalerConfig(**base))


def test_autoscaler_scales_up_on_queue_and_respects_max():
    a = _scaler()
    n = 1
    for t in range(0, 40):
        n = a.observe(float(t), queue_depth=40.0, p99_ms=None, n_replicas=n)
    assert n == 4  # max, not beyond
    assert a.scale_ups >= 3


def test_autoscaler_scales_up_on_p99_breach():
    a = _scaler()
    assert a.observe(0.0, queue_depth=0.0, p99_ms=5000.0, n_replicas=2) == 3
    assert "SLO" in a.last_reason


def test_autoscaler_scale_down_needs_calm_window_and_cooldown():
    a = _scaler()
    # A p99 breach at t=0 scales up (queue stays 0 so the sliding window
    # holds nothing that could re-trigger an up during the calm phase).
    assert a.observe(0.0, 0.0, 5000.0, 2) == 3
    n = 3
    for t in range(1, 30):
        n = a.observe(float(t), queue_depth=0.0, p99_ms=100.0, n_replicas=n)
        # Calm + full window, but inside the 30 s cooldown: hysteresis
        # holds the replica a traffic dip would otherwise shed.
        assert n == 3
    # Past the cooldown (last event t=0 + 30 s) the scale-down proceeds.
    assert a.observe(31.0, 0.0, 100.0, 3) == 2
    assert a.scale_downs == 1


def test_autoscaler_never_drops_below_min():
    a = _scaler(min_replicas=2, max_replicas=4)
    n = 2
    for t in range(0, 100):
        n = a.observe(float(t), queue_depth=0.0, p99_ms=50.0, n_replicas=n)
    assert n == 2
    assert a.observe(101.0, 0.0, None, 1) == 2  # below min → raise


# ---------------------------------------------------------------------------
# Default engine factory (real ContinuousBatcher) + bench smoke
# ---------------------------------------------------------------------------


def test_default_engine_factory_builds_real_batcher(sched_factory):
    import jax.numpy as jnp

    from tpu_engine.serving_fleet import build_replica_engine

    spec = small_spec(max_slots=2, max_len=64, prefill_chunk=16)
    engine = build_replica_engine(spec)
    rid = engine.submit([1, 2, 3], max_new_tokens=4)
    for _ in range(200):
        if engine.result(rid)["status"] == "done":
            break
        engine.step()
    out = engine.result(rid)
    assert out["status"] == "done" and len(out["tokens"]) >= 1
    assert jnp.asarray(out["tokens"]).dtype.kind == "i"


def test_bench_emits_serving_fleet_line():
    from bench import _serving_fleet_metric

    line = _serving_fleet_metric()
    assert line is not None
    assert line["metric"] == "serving_fleet_throughput_vs_static_1"
    # The acceptance bar: ≥2x aggregate tokens/sec over the static single
    # replica on the bursty trace, with steady-state p99 inside the SLO.
    assert line["value"] >= 2.0
    assert line["p99_within_slo"]
    assert line["p99_ms"] <= line["p99_slo_ms"]
    # Replica-count trace and per-replica routing weights ride the line.
    assert line["replica_trace"][0][1] == 1
    assert line["max_replicas_used"] > 1
    # Weights are the END-of-trace routing plane; scale-downs may have
    # shed replicas since the peak.
    assert 1 <= len(line["router_weights"]) <= line["max_replicas_used"]
    assert line["prefix_hit_rate"] > 0.5
