"""Zero-bubble pipeline schedule: op table, analytic account, gradient
parity, auto-resolution and the interaction matrix.

The schedule-level parity tests run the raw schedule functions on tiny
unsharded shapes (no mesh, seconds to compile) and so stay in the fast
tier; the program-level three-way parity rides the compile-heavy slow tier
next to ``test_pipeline.py``'s other full-program schedule tests.
"""

import collections

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_engine.mesh_runtime import MeshConfig
from tpu_engine.models import transformer as tfm
from tpu_engine.parallel.pipeline import stage_layer_stack
from tpu_engine.parallel.pipeline_1f1b import pipeline_1f1b_grads
from tpu_engine.parallel.pipeline_zb import (
    pipeline_zb_grads,
    schedule_account,
    zb_op_table,
)
from tpu_engine.sharding import (
    Precision,
    ShardingStage,
    TPUTrainConfig,
    resolve_pipeline_schedule,
)

PM_COMBOS = [(2, 2), (2, 4), (4, 4), (4, 8)]


# -- host-side op table -------------------------------------------------------


@pytest.mark.parametrize("P,M", PM_COMBOS + [(4, 2), (3, 5), (8, 16)])
def test_op_table_invariants(P, M):
    """Every (microbatch, stage) pair gets exactly one F, one B and one W;
    stage p defers exactly min(P-1-p, M) weight gradients — the stash
    bound the schedule's memory claim rests on."""
    table = zb_op_table(P, M)
    assert len(table) == M + 3 * (P - 1)
    counts = [collections.Counter() for _ in range(P)]
    for row in table:
        assert len(row) == P
        for p, ops in enumerate(row):
            counts[p].update(ops)
    for p in range(P):
        c = counts[p]
        assert c["F"] == M
        assert c["BW"] + c["B"] == M  # every backward's B half happens once
        assert c["BW"] + c["W"] == M  # ... and its W half
        assert c["B"] == c["W"] == min(P - 1 - p, M)  # deferred set
        assert c["B"] <= P - 1  # stash bound


def test_op_table_phase_structure():
    """Forwards never run after the steady window and deferred W never
    before the tail — the four-scan segmentation is exactly the table."""
    P, M = 4, 8
    table = zb_op_table(P, M)
    for t, row in enumerate(table):
        flat = [op for ops in row for op in ops]
        if t <= P - 2:  # warmup
            assert set(flat) <= {"F"}
        elif t <= M + P - 2:  # steady
            assert "B" not in flat and "W" not in flat
        elif t <= M + 2 * (P - 1) - 1:  # drain
            assert set(flat) <= {"B"}
        else:  # W-tail
            assert set(flat) <= {"W"}


@pytest.mark.parametrize("P,M", PM_COMBOS + [(8, 32)])
def test_schedule_account_zb_strictly_beats_1f1b(P, M):
    zb = schedule_account("zb", P, M)
    f1b = schedule_account("1f1b", P, M)
    gp = schedule_account("gpipe", P, M)
    # Closed forms the docstrings claim, in per-stage lane F-units.
    assert zb["lane_cost"] == 4 * M + 6 * (P - 1)
    assert f1b["lane_cost"] == 4 * M + 8 * (P - 1)
    assert gp["lane_cost"] == 4 * (M + P - 1)
    assert zb["ticks"] == M + 3 * (P - 1) == len(zb_op_table(P, M))
    # The acceptance bar: strictly less busy-burning bubble compute than
    # 1f1b at equal M and P, hence a strictly higher busy fraction.
    assert zb["burned_cost"] < f1b["burned_cost"]
    assert zb["busy_fraction"] > f1b["busy_fraction"]
    assert zb["useful_cost"] == f1b["useful_cost"] == gp["useful_cost"]


def test_schedule_account_degenerate():
    assert schedule_account("zb", 1, 8)["busy_fraction"] == 1.0
    with pytest.raises(ValueError, match="unknown pipeline schedule"):
        schedule_account("interleaved", 4, 8)


# -- gradient parity ----------------------------------------------------------


def _parity_fixtures(P, M, seed=0):
    cfg = tfm.MODEL_CONFIGS["gpt-tiny"].with_(n_layers=4, vocab_size=64)
    params = tfm.init_params(jax.random.PRNGKey(seed), cfg, jnp.float32)
    B, S, D = 1, 8, cfg.d_model
    staged = stage_layer_stack(params["layers"], P, cfg.n_layers)
    x_mb = jax.random.normal(jax.random.PRNGKey(1), (M, B, S, D)) * 0.1
    toks = jax.random.randint(jax.random.PRNGKey(2), (M, B, S), 0, 64)
    positions = jnp.arange(S)[None, :]
    denom = M * B * S

    body = tfm.remat_scan_body(cfg, positions, None, False, "nothing_saveable")

    def stage_fn(x, w):
        y, _aux = jax.lax.scan(body, x, w)
        return y

    def exit_scalar(y):
        return jnp.sum(y * y) / denom

    def exit_fn(y, _toks):
        loss, vjp = jax.vjp(exit_scalar, y)
        (dy,) = vjp(jnp.ones((), jnp.float32))
        return loss, dy, {}

    def ref_loss(staged_w, x):
        # The autodiff reference: the same math every schedule must
        # reproduce — sequential stages, summed exit losses (this is
        # exactly what the GPipe path differentiates).
        total = jnp.zeros((), jnp.float32)
        for m in range(M):
            h = x[m]
            for p in range(P):
                h = stage_fn(h, jax.tree.map(lambda a: a[p], staged_w))
            total = total + exit_scalar(h)
        return total

    sched_kwargs = dict(
        positions=positions, exit_fn=exit_fn, outer_grad_zero={},
        aux_cotangent=0.0,
    )
    return cfg, staged, x_mb, toks, ref_loss, sched_kwargs


@pytest.mark.parametrize("P,M", PM_COMBOS)
def test_gradient_parity_gpipe_1f1b_zb(P, M):
    """The schedules are pure reorderings of the same per-stage vjps:
    loss, layer grads and input cotangents must agree across autodiff
    (gpipe math), 1f1b and zb for every (P, M) combination."""
    cfg, staged, x_mb, toks, ref_loss, kw = _parity_fixtures(P, M)
    ref_val, (ref_dstaged, ref_dx) = jax.value_and_grad(ref_loss, argnums=(0, 1))(
        staged, x_mb
    )
    for fn in (pipeline_1f1b_grads, pipeline_zb_grads):
        loss, _aux, dstaged, _d_outer, dx_mb = fn(staged, x_mb, toks, cfg, **kw)
        np.testing.assert_allclose(loss, ref_val, rtol=1e-5)
        for got, want in zip(jax.tree.leaves(dstaged), jax.tree.leaves(ref_dstaged)):
            np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(dx_mb, ref_dx, rtol=1e-4, atol=1e-6)


@pytest.mark.slow
def test_zb_program_matches_gpipe_and_1f1b():
    """Full-program three-way parity on the 8-virtual-device CPU mesh:
    losses and grad norms agree across all three schedules over steps."""
    from tpu_engine.train import build_train_program

    def run(sched):
        cfg = _train_cfg(MeshConfig(data=2, fsdp=2, pipe=2),
                         pipeline_schedule=sched)
        prog = build_train_program(cfg)
        state = prog.init(jax.random.PRNGKey(0))
        out = []
        for i in range(3):
            state, m = prog.step(state, prog.synthetic_batch(seed=i))
            out.append((float(m["loss"]), float(m["grad_norm"])))
        return out

    zb = run("zb")
    fb = run("1f1b")
    gp = run("gpipe")
    np.testing.assert_allclose([l for l, _ in zb], [l for l, _ in fb], rtol=1e-6)
    np.testing.assert_allclose([g for _, g in zb], [g for _, g in fb], rtol=2e-5)
    np.testing.assert_allclose([l for l, _ in zb], [l for l, _ in gp], rtol=2e-5)
    np.testing.assert_allclose([g for _, g in zb], [g for _, g in gp], rtol=2e-4)


# -- resolution & interaction matrix ------------------------------------------


def _train_cfg(mesh, **kw):
    base = dict(
        model_name="gpt-tiny",
        sharding_stage=ShardingStage.FULL_PARTITIONING,
        mesh=mesh,
        micro_batch_size=2,
        gradient_accumulation_steps=4,
        seq_len=64,
        precision=Precision.FP32,
        param_dtype=Precision.FP32,
        activation_checkpointing=True,
        total_steps=10,
    )
    base.update(kw)
    return TPUTrainConfig(**base)


def test_auto_resolves_to_zb():
    mesh = MeshConfig(data=2, fsdp=2, pipe=2)
    # M=4 > P=2 and nothing gpipe-only requested → zb.
    assert resolve_pipeline_schedule(_train_cfg(mesh)) == "zb"
    # M <= P: warmup/drain overhead with no residency win → gpipe.
    assert resolve_pipeline_schedule(
        _train_cfg(mesh, gradient_accumulation_steps=2)
    ) == "gpipe"
    # No pipe axis → gpipe (schedule irrelevant).
    assert resolve_pipeline_schedule(
        _train_cfg(MeshConfig(data=2, fsdp=2, model=2))
    ) == "gpipe"
    # Explicit choices are honoured verbatim.
    assert resolve_pipeline_schedule(
        _train_cfg(mesh, pipeline_schedule="1f1b")
    ) == "1f1b"
    assert resolve_pipeline_schedule(
        _train_cfg(mesh, pipeline_schedule="gpipe")
    ) == "gpipe"


def test_auto_degrades_to_gpipe_on_unsupported_features():
    mesh = MeshConfig(data=2, fsdp=2, pipe=2)
    assert resolve_pipeline_schedule(
        _train_cfg(mesh, loss_chunk_size=32)
    ) == "gpipe"
    assert resolve_pipeline_schedule(
        _train_cfg(mesh, quant_training="int8")
    ) == "gpipe"
    assert resolve_pipeline_schedule(
        _train_cfg(mesh, precision=Precision.BF16,
                   grad_allreduce_dtype="bf16")
    ) == "gpipe"


def test_zb_rejects_comm_compression():
    with pytest.raises(ValueError, match="comm compression"):
        _train_cfg(MeshConfig(data=2, fsdp=2, pipe=2),
                   pipeline_schedule="zb", comm_quant_weights=True)


def test_zb_rejects_quant_training():
    with pytest.raises(ValueError, match="quant_training"):
        _train_cfg(MeshConfig(data=2, fsdp=2, pipe=2),
                   pipeline_schedule="zb", quant_training="int8")


def test_zb_rejects_loss_chunking():
    from tpu_engine.train import build_train_program

    with pytest.raises(ValueError, match="loss_chunk_size"):
        build_train_program(
            _train_cfg(MeshConfig(data=2, fsdp=2, pipe=2),
                       pipeline_schedule="zb", loss_chunk_size=32)
        )


def test_zb_rejects_reduced_comm_dtype():
    from tpu_engine.train import build_train_program

    with pytest.raises(ValueError, match="grad_allreduce_dtype"):
        build_train_program(
            _train_cfg(MeshConfig(data=2, fsdp=2, pipe=2),
                       pipeline_schedule="zb", precision=Precision.BF16,
                       param_dtype=Precision.FP32,
                       grad_allreduce_dtype="bf16")
        )
