"""Continuous-batching server: slot reuse + exactness vs per-request generate."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_engine.generate import generate
from tpu_engine.models import transformer as tfm
from tpu_engine.serving import ContinuousBatcher, init_slot_cache


@pytest.fixture(scope="module", params=["gpt-tiny", "qwen-tiny", "gpt2-tiny"])
def model(request):
    cfg = tfm.MODEL_CONFIGS[request.param]
    params = tfm.init_params(jax.random.PRNGKey(3), cfg, dtype=jnp.float32)
    return cfg, params


def _ref_greedy(params, cfg, prompt, n):
    out = generate(params, jnp.asarray([prompt], jnp.int32), cfg,
                   max_new_tokens=n, compute_dtype=jnp.float32)
    return np.asarray(out)[0, len(prompt):].tolist()


def test_staggered_requests_match_individual_generate(model):
    """Requests of different lengths admitted at different times, sharing
    the slot pool, must produce token-for-token what generate() produces
    for each prompt alone (greedy, fp32)."""
    cfg, params = model
    srv = ContinuousBatcher(params, cfg, max_slots=2, max_len=96,
                            compute_dtype=jnp.float32, prefill_pad_to=16)
    rng = np.random.default_rng(0)
    p1 = rng.integers(1, cfg.vocab_size, 7).tolist()
    p2 = rng.integers(1, cfg.vocab_size, 13).tolist()
    p3 = rng.integers(1, cfg.vocab_size, 3).tolist()

    r1 = srv.submit(p1, max_new_tokens=6)
    r2 = srv.submit(p2, max_new_tokens=10)
    for _ in range(3):
        srv.step()
    # Third request arrives mid-flight; with 2 slots it queues until one
    # of the first two finishes, then reuses the freed slot.
    r3 = srv.submit(p3, max_new_tokens=5)
    for _ in range(40):
        if all(srv.result(r)["status"] == "done" for r in (r1, r2, r3)):
            break
        srv.step()

    for rid, prompt, n in ((r1, p1, 6), (r2, p2, 10), (r3, p3, 5)):
        got = srv.result(rid)
        assert got["status"] == "done"
        assert got["tokens"] == _ref_greedy(params, cfg, prompt, n), (
            rid, got["tokens"]
        )


def test_slot_reuse_and_stats(model):
    cfg, params = model
    srv = ContinuousBatcher(params, cfg, max_slots=1, max_len=64,
                            compute_dtype=jnp.float32, prefill_pad_to=16)
    a = srv.submit([5, 6, 7], max_new_tokens=3)
    b = srv.submit([9, 10], max_new_tokens=2)
    # One slot: b must wait for a, then run in the SAME slot.
    for _ in range(20):
        if srv.result(b)["status"] == "done":
            break
        srv.step()
    assert srv.result(a)["status"] == "done"
    assert srv.result(b)["status"] == "done"
    st = srv.stats()
    assert st["requests_total"] == 2 and st["tokens_generated"] == 5
    assert st["active_slots"] == 0 and st["queued"] == 0
    # And both match the reference.
    assert srv.result(a)["tokens"] == _ref_greedy(params, cfg, [5, 6, 7], 3)
    assert srv.result(b)["tokens"] == _ref_greedy(params, cfg, [9, 10], 2)


def test_eos_frees_slot(model):
    cfg, params = model
    ref = _ref_greedy(params, cfg, [1, 2, 3, 4], 8)
    eos = ref[2]  # force an early stop at the 3rd generated token
    srv = ContinuousBatcher(params, cfg, max_slots=1, max_len=64,
                            compute_dtype=jnp.float32, eos_id=eos,
                            prefill_pad_to=16)
    r = srv.submit([1, 2, 3, 4], max_new_tokens=8)
    for _ in range(12):
        srv.step()
    got = srv.result(r)
    assert got["status"] == "done"
    # Stops AT the first occurrence of the eos token in the greedy stream
    # (tiny random models may emit it before position 3).
    assert got["tokens"] == ref[:ref.index(eos) + 1]
    assert srv.stats()["active_slots"] == 0


def test_background_thread_serving(model):
    cfg, params = model
    srv = ContinuousBatcher(params, cfg, max_slots=2, max_len=64,
                            compute_dtype=jnp.float32, prefill_pad_to=16)
    stop = threading.Event()
    t = threading.Thread(target=srv.serve_forever, args=(stop,), daemon=True)
    t.start()
    try:
        rid = srv.submit([11, 12, 13], max_new_tokens=4)
        got = srv.wait(rid, timeout=120)
        assert got["status"] == "done"
        assert got["tokens"] == _ref_greedy(params, cfg, [11, 12, 13], 4)
    finally:
        stop.set()
        t.join(timeout=10)


def test_capacity_and_window_guards(model):
    cfg, params = model
    srv = ContinuousBatcher(params, cfg, max_slots=1, max_len=32,
                            compute_dtype=jnp.float32)
    with pytest.raises(ValueError, match="max_len"):
        srv.submit(list(range(1, 30)), max_new_tokens=10)
    # Sliding-window models get a per-row RING pool: O(window) lanes, not
    # O(max_len) (round-3 verdict: serving was blocked outright before).
    ring = init_slot_cache(cfg.with_(sliding_window=8), 2, 64,
                           prefill_chunk=16)
    assert ring.ring and ring.n_lanes == 8 + 16 - 1
    assert ring.pos is not None and ring.pos.shape == (2, 23)


def test_chunked_greedy_matches_per_step(model):
    """chunk_steps > 1 (N tokens per dispatch, in-scan argmax feedback,
    overshoot rewound) must be token-for-token identical to per-step
    serving and to generate(), including slot reuse after an early finish
    inside a chunk."""
    cfg, params = model
    srv = ContinuousBatcher(params, cfg, max_slots=2, max_len=96,
                            compute_dtype=jnp.float32, prefill_pad_to=16,
                            chunk_steps=4)
    rng = np.random.default_rng(21)
    p1 = rng.integers(1, cfg.vocab_size, 5).tolist()
    p2 = rng.integers(1, cfg.vocab_size, 9).tolist()
    p3 = rng.integers(1, cfg.vocab_size, 4).tolist()
    # 6 and 10 are NOT multiples of 4 → both requests overshoot mid-chunk
    # and must be trimmed + rewound; p3 then reuses a rewound slot.
    r1 = srv.submit(p1, max_new_tokens=6)
    r2 = srv.submit(p2, max_new_tokens=10)
    for _ in range(10):
        srv.step()
        if srv.result(r1)["status"] == "done":
            break
    r3 = srv.submit(p3, max_new_tokens=7)
    for _ in range(30):
        if all(srv.result(r)["status"] == "done" for r in (r1, r2, r3)):
            break
        srv.step()
    for rid, prompt, n in ((r1, p1, 6), (r2, p2, 10), (r3, p3, 7)):
        assert srv.result(rid)["tokens"] == _ref_greedy(params, cfg, prompt, n)


def test_sampled_requests_chunk_with_greedy_neighbors(model):
    """temperature>0 requests ride the SAME chunked dispatch as greedy
    ones (in-scan per-slot sampling — round-3 verdict item 2: the fast
    path must not disengage for mixed batches). The greedy stream is
    unaffected by its sampled neighbor, and the sampled stream is
    deterministic for a given seed."""
    cfg, params = model
    def run(order):
        srv = ContinuousBatcher(params, cfg, max_slots=2, max_len=64,
                                compute_dtype=jnp.float32, prefill_pad_to=16,
                                chunk_steps=4, seed=7)
        ids = {}
        for name in order:
            if name == "g":
                ids["g"] = srv.submit([2, 3, 4], max_new_tokens=5)
            else:
                ids["s"] = srv.submit([5, 6], max_new_tokens=5,
                                      temperature=0.8)
        for _ in range(20):
            if all(srv.result(r)["status"] == "done" for r in ids.values()):
                break
            srv.step()
        return {k: srv.result(v)["tokens"] for k, v in ids.items()}

    a = run("gs")
    assert a["g"] == _ref_greedy(params, cfg, [2, 3, 4], 5)
    assert len(a["s"]) == 5
    # Same-seed rerun reproduces the sampled stream exactly. (Request ids
    # feed the fold-in key, so keep the submission order identical.)
    b = run("gs")
    assert b["s"] == a["s"] and b["g"] == a["g"]


def test_sampled_stream_independent_of_batch_composition(model):
    """A sampled request's stream depends only on (seed, request id, its
    own prompt) — not on which other requests share the slot pool. Two
    servers, same seed: one serves the sampled request alone, the other
    alongside two greedy neighbors; streams must match token for token."""
    cfg, params = model
    prompt = [7, 8, 9]

    def sampled_stream(crowded: bool):
        srv = ContinuousBatcher(params, cfg, max_slots=4, max_len=64,
                                compute_dtype=jnp.float32, prefill_pad_to=16,
                                chunk_steps=3, seed=11)
        # Sampled request FIRST in both servers → same request id 0, so
        # the fold-in keys match and only batch composition differs.
        rid = srv.submit(prompt, max_new_tokens=6, temperature=0.9)
        if crowded:
            srv.submit([1, 2], max_new_tokens=8)
            srv.submit([3, 4, 5], max_new_tokens=4)
        for _ in range(30):
            if srv.result(rid)["status"] == "done":
                break
            srv.step()
        assert rid == 0
        return srv.result(rid)["tokens"]

    alone = sampled_stream(False)
    crowded = sampled_stream(True)
    assert len(alone) == 6
    assert crowded == alone


def test_failed_loop_rejects_new_submits(model):
    """After a step failure kills the engine thread, submit() must raise
    instead of queueing requests nobody will ever serve (round-3 advisor)."""
    cfg, params = model
    srv = ContinuousBatcher(params, cfg, max_slots=1, max_len=64,
                            compute_dtype=jnp.float32, prefill_pad_to=16)
    rid = srv.submit([1, 2, 3], max_new_tokens=4)
    srv.step = lambda: (_ for _ in ()).throw(RuntimeError("chip fell over"))
    stop = threading.Event()
    t = threading.Thread(target=srv.serve_forever, args=(stop,), daemon=True)
    t.start()
    t.join(timeout=10)
    assert not t.is_alive()
    got = srv.result(rid)
    assert got["status"] == "failed" and "chip fell over" in got["error"]
    with pytest.raises(RuntimeError, match="serving loop failed"):
        srv.submit([4, 5], max_new_tokens=2)


def test_long_prompt_chunked_prefill_matches_generate(model):
    """A prompt longer than prefill_chunk is ingested across several
    bounded chunks interleaved with decode; the stream must still match
    generate(), and a short request admitted mid-ingestion must keep
    decoding (no head-of-line stall)."""
    cfg, params = model
    srv = ContinuousBatcher(params, cfg, max_slots=2, max_len=192,
                            compute_dtype=jnp.float32, prefill_pad_to=16,
                            prefill_chunk=32, chunk_steps=2)
    rng = np.random.default_rng(5)
    long_p = rng.integers(1, cfg.vocab_size, 90).tolist()   # 3 chunks of 32
    short_p = rng.integers(1, cfg.vocab_size, 4).tolist()
    r_short = srv.submit(short_p, max_new_tokens=6)
    srv.step()  # short admitted + first prefill chunk
    r_long = srv.submit(long_p, max_new_tokens=5)
    for _ in range(40):
        if all(srv.result(r)["status"] == "done" for r in (r_short, r_long)):
            break
        srv.step()
    assert srv.result(r_short)["tokens"] == _ref_greedy(params, cfg, short_p, 6)
    assert srv.result(r_long)["tokens"] == _ref_greedy(params, cfg, long_p, 5)


def test_speculative_serving_matches_greedy_streams():
    """Draft-propose / batched-verify in the slot pool (round-3 verdict
    item 8): streams must be token-identical to plain greedy serving and
    to generate(), across staggered admissions, eos mid-round, slot
    reuse, and a perfect draft (draft == target → near-full acceptance)."""
    cfg = tfm.MODEL_CONFIGS["gpt-tiny"]
    params = tfm.init_params(jax.random.PRNGKey(3), cfg, dtype=jnp.float32)
    draft_cfg = cfg.with_(name="draft-tiny", n_layers=1)
    draft_params = tfm.init_params(jax.random.PRNGKey(9), draft_cfg,
                                   dtype=jnp.float32)
    rng = np.random.default_rng(31)
    p1 = rng.integers(1, cfg.vocab_size, 6).tolist()
    p2 = rng.integers(1, cfg.vocab_size, 11).tolist()
    p3 = rng.integers(1, cfg.vocab_size, 4).tolist()

    def run(dp, dc, gamma):
        srv = ContinuousBatcher(params, cfg, max_slots=2, max_len=96,
                                compute_dtype=jnp.float32, prefill_pad_to=16,
                                draft_params=dp, draft_cfg=dc,
                                spec_gamma=gamma)
        r1 = srv.submit(p1, max_new_tokens=9)
        r2 = srv.submit(p2, max_new_tokens=13)
        for _ in range(6):
            srv.step()
        r3 = srv.submit(p3, max_new_tokens=5)  # queues, reuses a freed slot
        for _ in range(40):
            if all(srv.result(r)["status"] == "done" for r in (r1, r2, r3)):
                break
            srv.step()
        return srv, {r: srv.result(r)["tokens"] for r in (r1, r2, r3)}

    # Weak draft (1 layer, different init): exactness must not depend on
    # the draft being any good.
    srv_w, weak = run(draft_params, draft_cfg, gamma=3)
    refs = [_ref_greedy(params, cfg, p, n)
            for p, n in ((p1, 9), (p2, 13), (p3, 5))]
    assert list(weak.values()) == refs
    st = srv_w.stats()
    assert st["speculative"] is True and 0 < st["spec_accept_rate"] <= 1

    # Perfect draft (the target itself): same streams, high acceptance.
    srv_p, perfect = run(params, cfg, gamma=3)
    assert list(perfect.values()) == refs
    assert srv_p.stats()["spec_accept_rate"] > 0.9

    # eos MID-ROUND: surplus accepted tokens must be dropped, the slot
    # (and draft cache) reset, and the freed slot reusable.
    full = _ref_greedy(params, cfg, p1, 12)
    eos = full[5]  # stream stops at the first occurrence of this token
    srv_e = ContinuousBatcher(params, cfg, max_slots=1, max_len=96,
                              compute_dtype=jnp.float32, prefill_pad_to=16,
                              draft_params=params, draft_cfg=cfg,
                              spec_gamma=3, eos_id=eos)
    re1 = srv_e.submit(p1, max_new_tokens=12)
    re2 = srv_e.submit(p3, max_new_tokens=4)  # reuses the slot after eos
    for _ in range(30):
        if all(srv_e.result(r)["status"] == "done" for r in (re1, re2)):
            break
        srv_e.step()
    assert srv_e.result(re1)["tokens"] == full[: full.index(eos) + 1]
    assert srv_e.result(re2)["tokens"] == _ref_greedy(params, cfg, p3, 4)


def test_speculative_serving_guards():
    cfg = tfm.MODEL_CONFIGS["gpt-tiny"]
    params = tfm.init_params(jax.random.PRNGKey(3), cfg, dtype=jnp.float32)
    draft_cfg = cfg.with_(name="d", n_layers=1)
    dparams = tfm.init_params(jax.random.PRNGKey(4), draft_cfg,
                              dtype=jnp.float32)
    srv = ContinuousBatcher(params, cfg, max_slots=1, max_len=64,
                            compute_dtype=jnp.float32,
                            draft_params=dparams, draft_cfg=draft_cfg)
    with pytest.raises(ValueError, match="greedy-only"):
        srv.submit([1, 2], max_new_tokens=2, temperature=0.7)
    with pytest.raises(ValueError, match="vocab"):
        ContinuousBatcher(params, cfg, draft_params=dparams,
                          draft_cfg=draft_cfg.with_(vocab_size=64))
    with pytest.raises(ValueError, match="sliding-window"):
        ContinuousBatcher(params, cfg.with_(sliding_window=8),
                          draft_params=dparams, draft_cfg=draft_cfg)
    with pytest.raises(ValueError, match="draft_cfg"):
        ContinuousBatcher(params, cfg, draft_params=dparams)


def test_speculative_geometry_errors_are_structured():
    """Construction-time draft geometry failures carry a machine-readable
    ``.reason`` (kind + offending dims) so fleet admission (spec_pool /
    placement) can reject plans without string-matching messages. They
    stay ``ValueError`` subclasses — existing ``match=`` guards hold."""
    from tpu_engine.serving import SpecGeometryError

    cfg = tfm.MODEL_CONFIGS["gpt-tiny"]
    params = tfm.init_params(jax.random.PRNGKey(3), cfg, dtype=jnp.float32)
    draft_cfg = cfg.with_(name="d", n_layers=1)
    dparams = tfm.init_params(jax.random.PRNGKey(4), draft_cfg,
                              dtype=jnp.float32)

    with pytest.raises(SpecGeometryError) as ei:
        ContinuousBatcher(params, cfg, draft_params=dparams)
    assert ei.value.reason["kind"] == "draft_cfg_missing"

    with pytest.raises(SpecGeometryError) as ei:
        ContinuousBatcher(params, cfg, draft_params=dparams,
                          draft_cfg=draft_cfg.with_(vocab_size=64))
    assert ei.value.reason == {
        "kind": "draft_vocab_mismatch", "draft_vocab": 64,
        "target_vocab": cfg.vocab_size,
    }

    with pytest.raises(SpecGeometryError) as ei:
        ContinuousBatcher(params, cfg.with_(sliding_window=8),
                          draft_params=dparams, draft_cfg=draft_cfg)
    assert ei.value.reason["kind"] == "draft_ring_window"
    assert ei.value.reason["target_window"] == 8

    with pytest.raises(SpecGeometryError) as ei:
        ContinuousBatcher(params, cfg, draft_params=dparams,
                          draft_cfg=draft_cfg, spec_gamma=0)
    assert ei.value.reason == {"kind": "spec_gamma_invalid",
                               "spec_gamma": 0}


def test_mesh_sharded_serving_matches_single_device():
    """Round-4 headline: the batcher runs under a mesh — params TP/FSDP
    sharded, the KV pool's kv-heads dim sharded over the ``model`` axis —
    and produces token streams identical to unsharded generate(). This is
    what lets a trained 7B-class model actually be SERVED, not just
    trained (round-3 verdict item 1)."""
    from tpu_engine.mesh_runtime import MeshConfig, build_mesh
    from tpu_engine.sharding import (
        ShardingStage, named_shardings, param_pspecs,
    )
    from tpu_engine.models.transformer import logical_axes

    cfg = tfm.MODEL_CONFIGS["gpt-tiny"]
    params = tfm.init_params(jax.random.PRNGKey(3), cfg, dtype=jnp.float32)
    mesh = build_mesh(MeshConfig(fsdp=2, model=4))
    shardings = named_shardings(
        mesh, param_pspecs(logical_axes(cfg), ShardingStage.FULL_PARTITIONING)
    )
    sharded_params = jax.device_put(params, shardings)

    srv = ContinuousBatcher(sharded_params, cfg, max_slots=4, max_len=96,
                            compute_dtype=jnp.float32, prefill_pad_to=16,
                            chunk_steps=3, mesh=mesh)
    # The pool really is sharded: kv-heads dim carries the model axis.
    assert srv._cache.k.sharding.spec == jax.sharding.PartitionSpec(
        None, None, None, "model", None
    )
    assert srv.stats()["sharded"] is True

    rng = np.random.default_rng(17)
    prompts = [rng.integers(1, cfg.vocab_size, n).tolist() for n in (5, 11, 3)]
    rids = [srv.submit(p, max_new_tokens=m)
            for p, m in zip(prompts, (6, 9, 4))]
    for _ in range(40):
        if all(srv.result(r)["status"] == "done" for r in rids):
            break
        srv.step()
    for rid, p, m in zip(rids, prompts, (6, 9, 4)):
        assert srv.result(rid)["tokens"] == _ref_greedy(params, cfg, p, m)


def test_sliding_window_model_serving_matches_generate():
    """Mistral-family (sliding-window) models serve through the per-row
    ring pool — O(window) lanes — and match generate()'s ring-cache
    streams (round-3 verdict item 5: serving raised for these models)."""
    cfg = tfm.MODEL_CONFIGS["gpt-tiny"].with_(sliding_window=12)
    params = tfm.init_params(jax.random.PRNGKey(3), cfg, dtype=jnp.float32)
    srv = ContinuousBatcher(params, cfg, max_slots=2, max_len=128,
                            compute_dtype=jnp.float32, prefill_pad_to=16,
                            prefill_chunk=16, chunk_steps=3)
    assert srv._cache.ring and srv._cache.n_lanes == 12 + 16 - 1
    rng = np.random.default_rng(9)
    # Prompt + generation crosses the window several times over.
    p1 = rng.integers(1, cfg.vocab_size, 40).tolist()
    p2 = rng.integers(1, cfg.vocab_size, 7).tolist()
    r1 = srv.submit(p1, max_new_tokens=20)
    r2 = srv.submit(p2, max_new_tokens=9)
    for _ in range(60):
        if all(srv.result(r)["status"] == "done" for r in (r1, r2)):
            break
        srv.step()
    assert srv.result(r1)["tokens"] == _ref_greedy(params, cfg, p1, 20)
    assert srv.result(r2)["tokens"] == _ref_greedy(params, cfg, p2, 9)
    # Slot reuse on the ring pool: a third request lands in a freed slot.
    p3 = rng.integers(1, cfg.vocab_size, 30).tolist()
    r3 = srv.submit(p3, max_new_tokens=8)
    for _ in range(30):
        if srv.result(r3)["status"] == "done":
            break
        srv.step()
    assert srv.result(r3)["tokens"] == _ref_greedy(params, cfg, p3, 8)


def _ref_greedy_kvq(params, cfg, prompt, n):
    out = generate(params, jnp.asarray([prompt], jnp.int32), cfg,
                   max_new_tokens=n, compute_dtype=jnp.float32,
                   kv_quant=True)
    return np.asarray(out)[0, len(prompt):].tolist()


def test_kv_quant_pool_matches_generate_kv_quant():
    """int8 KV slot pool (round 4): codes + per-(lane, head) scales ride
    the same per-row scatters as the bf16 pool, and streams match
    generate(kv_quant=True) exactly on CPU — the quantization math is
    per-row, so pool vs single-row layout cannot change the codes."""
    cfg = tfm.MODEL_CONFIGS["gpt-tiny"]
    params = tfm.init_params(jax.random.PRNGKey(3), cfg, dtype=jnp.float32)
    srv = ContinuousBatcher(params, cfg, max_slots=2, max_len=96,
                            compute_dtype=jnp.float32, prefill_pad_to=16,
                            chunk_steps=4, kv_quant=True)
    assert srv._cache.quantized and srv._cache.k.dtype == jnp.int8
    assert srv.stats()["kv_quant"] is True
    rng = np.random.default_rng(21)
    prompts = [rng.integers(1, cfg.vocab_size, n).tolist() for n in (5, 11, 3)]
    rids = [srv.submit(p, max_new_tokens=m)
            for p, m in zip(prompts, (6, 9, 4))]
    for _ in range(40):
        if all(srv.result(r)["status"] == "done" for r in rids):
            break
        srv.step()
    for rid, p, m in zip(rids, prompts, (6, 9, 4)):
        assert srv.result(rid)["tokens"] == _ref_greedy_kvq(params, cfg, p, m)


def test_kv_quant_composes_with_weight_quant_and_sampling():
    from tpu_engine.quant import quantize_params

    cfg = tfm.MODEL_CONFIGS["gpt-tiny"]
    params = tfm.init_params(jax.random.PRNGKey(3), cfg, dtype=jnp.float32)
    qparams = quantize_params(params)
    srv = ContinuousBatcher(qparams, cfg, max_slots=2, max_len=96,
                            compute_dtype=jnp.float32, prefill_pad_to=16,
                            chunk_steps=4, kv_quant=True)
    p = [3, 1, 4, 1, 5, 9]
    rid = srv.submit(p, max_new_tokens=8)
    rs = srv.submit([2, 7, 1], max_new_tokens=6, temperature=0.7)
    for _ in range(40):
        if all(srv.result(r)["status"] == "done" for r in (rid, rs)):
            break
        srv.step()
    assert srv.result(rid)["tokens"] == _ref_greedy_kvq(qparams, cfg, p, 8)
    assert len(srv.result(rs)["tokens"]) == 6
    # Sampled stream is reproducible on a fresh server with the same seed
    # (same submission order: the per-request key folds the request id).
    srv2 = ContinuousBatcher(qparams, cfg, max_slots=2, max_len=96,
                             compute_dtype=jnp.float32, prefill_pad_to=16,
                             chunk_steps=4, kv_quant=True)
    srv2.submit(p, max_new_tokens=8)
    rs2 = srv2.submit([2, 7, 1], max_new_tokens=6, temperature=0.7)
    for _ in range(40):
        if srv2.result(rs2)["status"] == "done":
            break
        srv2.step()
    assert srv2.result(rs2)["tokens"] == srv.result(rs)["tokens"]


def test_kv_quant_ring_pool_serving():
    """int8 pool composes with the sliding-window ring: scale lanes wrap
    with their code lanes."""
    cfg = tfm.MODEL_CONFIGS["gpt-tiny"].with_(sliding_window=12)
    params = tfm.init_params(jax.random.PRNGKey(3), cfg, dtype=jnp.float32)
    srv = ContinuousBatcher(params, cfg, max_slots=2, max_len=128,
                            compute_dtype=jnp.float32, prefill_pad_to=16,
                            prefill_chunk=16, chunk_steps=3, kv_quant=True)
    assert srv._cache.ring and srv._cache.quantized
    rng = np.random.default_rng(9)
    p1 = rng.integers(1, cfg.vocab_size, 40).tolist()
    r1 = srv.submit(p1, max_new_tokens=20)
    for _ in range(60):
        if srv.result(r1)["status"] == "done":
            break
        srv.step()
    assert srv.result(r1)["tokens"] == _ref_greedy_kvq(params, cfg, p1, 20)


def test_kv_quant_sharded_pool():
    from tpu_engine.mesh_runtime import MeshConfig, build_mesh
    from tpu_engine.models.transformer import logical_axes
    from tpu_engine.sharding import (
        ShardingStage, named_shardings, param_pspecs,
    )

    cfg = tfm.MODEL_CONFIGS["gpt-tiny"]
    params = tfm.init_params(jax.random.PRNGKey(3), cfg, dtype=jnp.float32)
    mesh = build_mesh(MeshConfig(fsdp=2, model=4))
    sharded = jax.device_put(params, named_shardings(
        mesh, param_pspecs(logical_axes(cfg), ShardingStage.FULL_PARTITIONING)
    ))
    srv = ContinuousBatcher(sharded, cfg, max_slots=2, max_len=96,
                            compute_dtype=jnp.float32, prefill_pad_to=16,
                            chunk_steps=3, mesh=mesh, kv_quant=True)
    assert srv._cache.k_scale.sharding.spec == jax.sharding.PartitionSpec(
        None, None, None, "model", None
    )
    p = [5, 11, 3, 8, 2]
    rid = srv.submit(p, max_new_tokens=7)
    for _ in range(40):
        if srv.result(rid)["status"] == "done":
            break
        srv.step()
    assert srv.result(rid)["tokens"] == _ref_greedy_kvq(params, cfg, p, 7)


def test_kv_quant_speculative_serving():
    """Speculative rounds on a quantized target pool: the verify write
    quantizes T=gamma+1 rows at once and the per-row rewind leaves stale
    scale lanes masked until overwritten — streams must still match plain
    greedy kv-quant serving."""
    cfg = tfm.MODEL_CONFIGS["gpt-tiny"]
    params = tfm.init_params(jax.random.PRNGKey(3), cfg, dtype=jnp.float32)
    prompts = [[3, 1, 4, 1, 5], [9, 2, 6]]
    plain = ContinuousBatcher(params, cfg, max_slots=2, max_len=64,
                              compute_dtype=jnp.float32, prefill_pad_to=16,
                              chunk_steps=2, kv_quant=True)
    spec = ContinuousBatcher(params, cfg, max_slots=2, max_len=64,
                             compute_dtype=jnp.float32, prefill_pad_to=16,
                             draft_params=params, draft_cfg=cfg, spec_gamma=3,
                             kv_quant=True)
    streams = {}
    for srv in (plain, spec):
        rids = [srv.submit(p, max_new_tokens=8) for p in prompts]
        for _ in range(60):
            if all(srv.result(r)["status"] == "done" for r in rids):
                break
            srv.step()
        streams[srv] = [srv.result(r)["tokens"] for r in rids]
    assert streams[plain] == streams[spec]
    assert spec.stats()["spec_accept_rate"] > 0.9  # draft == target


def test_prefix_cache_streams_identical_and_hits():
    """Shared system prompt: streams with the prefix cache must be
    token-identical to streams without it, and the warm admission must
    actually HIT (its shared chunks never re-prefill)."""
    cfg = tfm.MODEL_CONFIGS["gpt-tiny"]
    params = tfm.init_params(jax.random.PRNGKey(3), cfg, dtype=jnp.float32)
    rng = np.random.default_rng(11)
    system = rng.integers(1, cfg.vocab_size, 40).tolist()  # > 2 chunks of 16
    prompts = [system + rng.integers(1, cfg.vocab_size, n).tolist()
               for n in (5, 9, 3)]

    def serve(**kw):
        srv = ContinuousBatcher(params, cfg, max_slots=2, max_len=128,
                                compute_dtype=jnp.float32, prefill_pad_to=16,
                                prefill_chunk=16, chunk_steps=3, **kw)
        rids = [srv.submit(p, max_new_tokens=6) for p in prompts]
        for _ in range(80):
            if all(srv.result(r)["status"] == "done" for r in rids):
                break
            srv.step()
        return srv, [srv.result(r)["tokens"] for r in rids]

    _, cold = serve()
    srv, warm = serve(prefix_cache_tokens=512)
    assert warm == cold
    st = srv.stats()["prefix_cache"]
    assert st["hits"] >= 2, st           # prompts 2 and 3 reuse the prefix
    assert st["entries"] >= 1 and st["tokens"] <= 512
    # And everything still matches per-request generate().
    for p, toks in zip(prompts, warm):
        assert toks == _ref_greedy(params, cfg, p, 6)


def test_prefix_cache_partial_chunk_reuse():
    """Token-granular reuse (round-4 verdict weakness 6): a prompt
    diverging MID-chunk from a stored prefix reuses every full grain of
    the shared tokens instead of zero, and streams stay identical to a
    cache-off server."""
    cfg = tfm.MODEL_CONFIGS["gpt-tiny"]
    params = tfm.init_params(jax.random.PRNGKey(3), cfg, dtype=jnp.float32)
    rng = np.random.default_rng(17)
    base = rng.integers(1, cfg.vocab_size, 40).tolist()
    p1 = base + [5, 6]
    # Shares 38 of base's 40 tokens — diverges inside the third chunk.
    p2 = base[:38] + [(base[38] + 1) % cfg.vocab_size] + [9, 10, 11]

    def serve(**kw):
        srv = ContinuousBatcher(params, cfg, max_slots=1, max_len=128,
                                compute_dtype=jnp.float32, prefill_pad_to=16,
                                prefill_chunk=16, chunk_steps=2, **kw)
        out = []
        for p in (p1, p2):
            r = srv.submit(p, max_new_tokens=5)
            for _ in range(60):
                srv.step()
                if srv.result(r)["status"] == "done":
                    break
            out.append(srv.result(r)["tokens"])
        return srv, out

    _, cold = serve()
    srv, warm = serve(prefix_cache_tokens=512)
    assert warm == cold
    st = srv.stats()["prefix_cache"]
    # p2 reuses floor(38/16)*16 = 32 of p1's stored 32-token boundary.
    assert st["hits"] >= 1, st
    for p, toks in zip((p1, p2), warm):
        assert toks == _ref_greedy(params, cfg, p, 5)


def test_prefix_cache_aligned_resubmit_hits():
    """Round-4 advisor finding: an identical CHUNK-ALIGNED prompt
    resubmitted must hit (the old boundary-keyed lookup probed only
    strictly-shorter boundaries, so these missed forever)."""
    cfg = tfm.MODEL_CONFIGS["gpt-tiny"]
    params = tfm.init_params(jax.random.PRNGKey(3), cfg, dtype=jnp.float32)
    srv = ContinuousBatcher(params, cfg, max_slots=1, max_len=128,
                            compute_dtype=jnp.float32, prefill_pad_to=16,
                            prefill_chunk=16, chunk_steps=2,
                            prefix_cache_tokens=256)
    prompt = list(range(1, 33))  # exactly 2 chunks of 16
    streams = []
    for _ in range(2):
        r = srv.submit(prompt, max_new_tokens=4)
        for _ in range(40):
            srv.step()
            if srv.result(r)["status"] == "done":
                break
        streams.append(srv.result(r)["tokens"])
    st = srv.stats()["prefix_cache"]
    assert st["hits"] >= 1, st  # reuses floor(31/16)*16 = 16 tokens
    assert streams[0] == streams[1] == _ref_greedy(params, cfg, prompt, 4)


def test_wait_tokens_incremental():
    """The streaming primitive: wait_tokens unblocks on PARTIAL progress
    (each emission batch), not only on completion, and the accumulated
    increments equal the final polled result."""
    cfg = tfm.MODEL_CONFIGS["gpt-tiny"]
    params = tfm.init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    srv = ContinuousBatcher(params, cfg, max_slots=1, max_len=96,
                            compute_dtype=jnp.float32, prefill_pad_to=16,
                            prefill_chunk=16, chunk_steps=2)
    stop = threading.Event()
    t = threading.Thread(target=srv.serve_forever, args=(stop,), daemon=True)
    t.start()
    try:
        rid = srv.submit([1, 2, 3], max_new_tokens=12)
        with pytest.raises(KeyError):
            srv.wait_tokens(9999)
        got: list[int] = []
        snapshots = 0
        while True:
            snap = srv.wait_tokens(rid, have=len(got), timeout=30.0)
            if len(snap["tokens"]) > len(got):
                snapshots += 1
                got = list(snap["tokens"])
            if snap["status"] in ("done", "failed"):
                break
        assert snap["status"] == "done"
        # chunk_steps=2 over 12 tokens → progress arrived in >= 3 batches.
        assert snapshots >= 3
        assert got == srv.result(rid)["tokens"] and len(got) == 12
    finally:
        stop.set()
        t.join(timeout=10)


def test_clean_stop_terminates_inflight_requests():
    """A clean server stop fails in-flight requests (terminal status), so
    an open stream's wait_tokens returns instead of heartbeating forever
    against a request no engine thread will ever advance."""
    cfg = tfm.MODEL_CONFIGS["gpt-tiny"]
    params = tfm.init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    srv = ContinuousBatcher(params, cfg, max_slots=1, max_len=256,
                            compute_dtype=jnp.float32, prefill_pad_to=16,
                            prefill_chunk=16, chunk_steps=1)
    stop = threading.Event()
    t = threading.Thread(target=srv.serve_forever, args=(stop,), daemon=True)
    t.start()
    rid = srv.submit([1, 2, 3], max_new_tokens=200)  # long-running
    srv.wait_tokens(rid, have=0, timeout=30.0)       # at least one token out
    stop.set()
    t.join(timeout=10)
    res = srv.result(rid)
    assert res["status"] == "failed"
    assert "stopped" in res["error"]
    # And a waiter blocked at stop time returns promptly with the terminal
    # snapshot rather than timing out.
    snap = srv.wait_tokens(rid, have=10**6, timeout=5.0)
    assert snap["status"] == "failed"
    # Post-stop submits are rejected — nothing will ever serve them.
    with pytest.raises(RuntimeError, match="stopped"):
        srv.submit([1, 2], max_new_tokens=2)


def test_prefix_cache_inserts_boundary_after_partial_hit():
    """A walk that STARTS mid-chunk (token-granular hit) still stores its
    own chunk-boundary entry — the insert condition covers the boundary
    (t0 < last <= t1) instead of requiring t1 == last, so a popular
    prompt B diverging mid-chunk from cached prompt A gets its own entry
    and later B-requests reuse B's full boundary, not just A's shared
    grains."""
    cfg = tfm.MODEL_CONFIGS["gpt-tiny"]
    params = tfm.init_params(jax.random.PRNGKey(3), cfg, dtype=jnp.float32)
    srv = ContinuousBatcher(params, cfg, max_slots=1, max_len=128,
                            compute_dtype=jnp.float32, prefill_pad_to=16,
                            prefill_chunk=16, chunk_steps=2,
                            prefix_cache_tokens=512)
    rng = np.random.default_rng(23)
    a = rng.integers(1, cfg.vocab_size, 40).tolist()          # prompt A
    b = a[:20] + [(a[20] + 1) % cfg.vocab_size] + \
        rng.integers(1, cfg.vocab_size, 19).tolist()          # diverges @20

    def run(p):
        r = srv.submit(list(p), max_new_tokens=3)
        for _ in range(60):
            srv.step()
            if srv.result(r)["status"] == "done":
                break
        return srv.result(r)["tokens"]

    run(a)                                   # stores A[:32]
    st0 = srv.stats()["prefix_cache"]
    run(b)   # hits A at floor(20/16)*16=16, walk starts mid-chunk at 16
    st1 = srv.stats()["prefix_cache"]
    assert st1["hits"] == st0["hits"] + 1
    # B's own boundary entry was stored despite the misaligned walk.
    assert st1["entries"] == st0["entries"] + 1
    # A later identical B reuses B's boundary (32 tokens, not A's 16).
    run(b)
    st2 = srv.stats()["prefix_cache"]
    assert st2["hits"] == st1["hits"] + 1
    assert st2["entries"] == st1["entries"]  # duplicate insert refused
    # Streams must match the reference throughout.
    assert run(b) == _ref_greedy(params, cfg, b, 3)


def test_prefix_cache_exact_match_only():
    """A prompt differing from every stored entry at token 0 must miss
    (zero common prefix — token-granular reuse has nothing to paste)."""
    cfg = tfm.MODEL_CONFIGS["gpt-tiny"]
    params = tfm.init_params(jax.random.PRNGKey(3), cfg, dtype=jnp.float32)
    srv = ContinuousBatcher(params, cfg, max_slots=2, max_len=128,
                            compute_dtype=jnp.float32, prefill_pad_to=16,
                            prefill_chunk=16, chunk_steps=2,
                            prefix_cache_tokens=256)
    base = list(range(1, 35))
    variant = [99] + base[1:]  # differs at token 0
    r1 = srv.submit(base, max_new_tokens=4)
    for _ in range(40):
        srv.step()
        if srv.result(r1)["status"] == "done":
            break
    r2 = srv.submit(variant, max_new_tokens=4)
    for _ in range(40):
        srv.step()
        if srv.result(r2)["status"] == "done":
            break
    st = srv.stats()["prefix_cache"]
    assert st["hits"] == 0
    assert srv.result(r2)["tokens"] == _ref_greedy(params, cfg, variant, 4)


def test_prefix_cache_eviction_budget():
    cfg = tfm.MODEL_CONFIGS["gpt-tiny"]
    params = tfm.init_params(jax.random.PRNGKey(3), cfg, dtype=jnp.float32)
    srv = ContinuousBatcher(params, cfg, max_slots=1, max_len=128,
                            compute_dtype=jnp.float32, prefill_pad_to=16,
                            prefill_chunk=16, chunk_steps=2,
                            prefix_cache_tokens=48)  # at most 3 chunks
    rng = np.random.default_rng(5)
    for i in range(4):  # distinct 33-token prompts -> 2 fresh chunks each
        p = rng.integers(1, cfg.vocab_size, 33).tolist()
        r = srv.submit(p, max_new_tokens=2)
        for _ in range(40):
            srv.step()
            if srv.result(r)["status"] == "done":
                break
    st = srv.stats()["prefix_cache"]
    assert st["tokens"] <= 48, st


def test_prefix_cache_composes_with_kv_quant_and_sampling():
    cfg = tfm.MODEL_CONFIGS["gpt-tiny"]
    params = tfm.init_params(jax.random.PRNGKey(3), cfg, dtype=jnp.float32)
    system = list(range(1, 36))
    p1, p2 = system + [7, 8], system + [9]

    def serve(**kw):
        srv = ContinuousBatcher(params, cfg, max_slots=2, max_len=96,
                                compute_dtype=jnp.float32, prefill_pad_to=16,
                                prefill_chunk=16, chunk_steps=2,
                                kv_quant=True, **kw)
        a = srv.submit(p1, max_new_tokens=5)
        b = srv.submit(p2, max_new_tokens=5, temperature=0.6)
        for _ in range(60):
            srv.step()
            if all(srv.result(r)["status"] == "done" for r in (a, b)):
                break
        return srv, srv.result(a)["tokens"], srv.result(b)["tokens"]

    _, a0, b0 = serve()
    srv, a1, b1 = serve(prefix_cache_tokens=256)
    assert (a1, b1) == (a0, b0)
    assert srv.stats()["prefix_cache"]["hits"] >= 1


def test_prefix_cache_guards():
    cfg = tfm.MODEL_CONFIGS["gpt-tiny"].with_(sliding_window=12)
    params = tfm.init_params(jax.random.PRNGKey(3), cfg, dtype=jnp.float32)
    with pytest.raises(ValueError, match="sliding-window"):
        ContinuousBatcher(params, cfg, max_slots=2, max_len=128,
                          compute_dtype=jnp.float32, prefill_chunk=16,
                          prefix_cache_tokens=128)
    cfg2 = tfm.MODEL_CONFIGS["gpt-tiny"]
    params2 = tfm.init_params(jax.random.PRNGKey(3), cfg2, dtype=jnp.float32)
    with pytest.raises(ValueError, match="speculative"):
        ContinuousBatcher(params2, cfg2, max_slots=2, max_len=64,
                          compute_dtype=jnp.float32,
                          draft_params=params2, draft_cfg=cfg2,
                          prefix_cache_tokens=128)


def test_prefix_cache_store_policy():
    """One entry per walk (the caller stores only its last cacheable
    boundary): wants() refuses duplicates and over-budget prefixes before
    any device work, and eviction is LRU within the token budget."""
    from tpu_engine.serving import _PrefixCache

    class _E:  # stands in for a KVCache slice
        def __init__(self, n):
            self.max_len = n

    sys_toks = tuple(range(64))
    c = _PrefixCache(budget_tokens=96, chunk=16)
    c.insert(sys_toks[:48], _E(48))
    assert not c.wants(sys_toks[:48])          # duplicate refused
    assert not c.wants(tuple(range(100, 228)))  # 128 > budget refused
    # LRU eviction: inserting 64 on a 96 budget evicts the older 48.
    c.insert(tuple(range(200, 264)), _E(64))
    assert c.tokens == 64 and len(c._entries) == 1
    # Budget-capped lookup: a long prompt probes only up to the budget.
    L, e = c.lookup(list(range(200, 264)) + list(range(500, 600)))
    assert L == 64 and e is not None


def test_prefix_cache_rejects_oversized_entry():
    """An entry whose DEVICE footprint (its lane count) exceeds the whole
    budget is rejected outright — the old behavior evicted every resident
    prefix to admit an entry that could never pay for itself. The ledger
    now charges entry lanes, the same unit eviction credits, so an entry
    with more lanes than key tokens can no longer drive the token count
    negative (which permanently disabled eviction)."""
    from tpu_engine.serving import _PrefixCache

    class _E:  # stands in for a KVCache slice
        def __init__(self, n):
            self.max_len = n

    c = _PrefixCache(budget_tokens=96, chunk=16)
    c.insert(tuple(range(48)), _E(48))
    assert c.tokens == 48
    # Key fits the budget but the KV slice does not (ring lanes can exceed
    # the key length): rejected, the resident working set is untouched.
    c.insert(tuple(range(100, 180)), _E(128))
    assert c.tokens == 48 and len(c._entries) == 1
    assert c.lookup(list(range(48)))[1] is not None
    # Ledger symmetry: a 32-token key over a 90-lane slice charges 90 —
    # inserting it evicts the 48 (48 + 90 > 96) and the count stays exact.
    c.insert(tuple(range(200, 232)), _E(90))
    assert c.tokens == 90 and len(c._entries) == 1
    # Eviction credits the same 90 it charged: never negative, and the
    # budget keeps evicting correctly afterwards.
    c.insert(tuple(range(300, 396)), _E(96))
    assert c.tokens == 96 and len(c._entries) == 1
