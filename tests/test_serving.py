"""Continuous-batching server: slot reuse + exactness vs per-request generate."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_engine.generate import generate
from tpu_engine.models import transformer as tfm
from tpu_engine.serving import ContinuousBatcher, init_slot_cache


@pytest.fixture(scope="module", params=["gpt-tiny", "qwen-tiny", "gpt2-tiny"])
def model(request):
    cfg = tfm.MODEL_CONFIGS[request.param]
    params = tfm.init_params(jax.random.PRNGKey(3), cfg, dtype=jnp.float32)
    return cfg, params


def _ref_greedy(params, cfg, prompt, n):
    out = generate(params, jnp.asarray([prompt], jnp.int32), cfg,
                   max_new_tokens=n, compute_dtype=jnp.float32)
    return np.asarray(out)[0, len(prompt):].tolist()


def test_staggered_requests_match_individual_generate(model):
    """Requests of different lengths admitted at different times, sharing
    the slot pool, must produce token-for-token what generate() produces
    for each prompt alone (greedy, fp32)."""
    cfg, params = model
    srv = ContinuousBatcher(params, cfg, max_slots=2, max_len=96,
                            compute_dtype=jnp.float32, prefill_pad_to=16)
    rng = np.random.default_rng(0)
    p1 = rng.integers(1, cfg.vocab_size, 7).tolist()
    p2 = rng.integers(1, cfg.vocab_size, 13).tolist()
    p3 = rng.integers(1, cfg.vocab_size, 3).tolist()

    r1 = srv.submit(p1, max_new_tokens=6)
    r2 = srv.submit(p2, max_new_tokens=10)
    for _ in range(3):
        srv.step()
    # Third request arrives mid-flight; with 2 slots it queues until one
    # of the first two finishes, then reuses the freed slot.
    r3 = srv.submit(p3, max_new_tokens=5)
    for _ in range(40):
        if all(srv.result(r)["status"] == "done" for r in (r1, r2, r3)):
            break
        srv.step()

    for rid, prompt, n in ((r1, p1, 6), (r2, p2, 10), (r3, p3, 5)):
        got = srv.result(rid)
        assert got["status"] == "done"
        assert got["tokens"] == _ref_greedy(params, cfg, prompt, n), (
            rid, got["tokens"]
        )


def test_slot_reuse_and_stats(model):
    cfg, params = model
    srv = ContinuousBatcher(params, cfg, max_slots=1, max_len=64,
                            compute_dtype=jnp.float32, prefill_pad_to=16)
    a = srv.submit([5, 6, 7], max_new_tokens=3)
    b = srv.submit([9, 10], max_new_tokens=2)
    # One slot: b must wait for a, then run in the SAME slot.
    for _ in range(20):
        if srv.result(b)["status"] == "done":
            break
        srv.step()
    assert srv.result(a)["status"] == "done"
    assert srv.result(b)["status"] == "done"
    st = srv.stats()
    assert st["requests_total"] == 2 and st["tokens_generated"] == 5
    assert st["active_slots"] == 0 and st["queued"] == 0
    # And both match the reference.
    assert srv.result(a)["tokens"] == _ref_greedy(params, cfg, [5, 6, 7], 3)
    assert srv.result(b)["tokens"] == _ref_greedy(params, cfg, [9, 10], 2)


def test_eos_frees_slot(model):
    cfg, params = model
    ref = _ref_greedy(params, cfg, [1, 2, 3, 4], 8)
    eos = ref[2]  # force an early stop at the 3rd generated token
    srv = ContinuousBatcher(params, cfg, max_slots=1, max_len=64,
                            compute_dtype=jnp.float32, eos_id=eos,
                            prefill_pad_to=16)
    r = srv.submit([1, 2, 3, 4], max_new_tokens=8)
    for _ in range(12):
        srv.step()
    got = srv.result(r)
    assert got["status"] == "done"
    # Stops AT the first occurrence of the eos token in the greedy stream
    # (tiny random models may emit it before position 3).
    assert got["tokens"] == ref[:ref.index(eos) + 1]
    assert srv.stats()["active_slots"] == 0


def test_background_thread_serving(model):
    cfg, params = model
    srv = ContinuousBatcher(params, cfg, max_slots=2, max_len=64,
                            compute_dtype=jnp.float32, prefill_pad_to=16)
    stop = threading.Event()
    t = threading.Thread(target=srv.serve_forever, args=(stop,), daemon=True)
    t.start()
    try:
        rid = srv.submit([11, 12, 13], max_new_tokens=4)
        got = srv.wait(rid, timeout=120)
        assert got["status"] == "done"
        assert got["tokens"] == _ref_greedy(params, cfg, [11, 12, 13], 4)
    finally:
        stop.set()
        t.join(timeout=10)


def test_capacity_and_window_guards(model):
    cfg, params = model
    srv = ContinuousBatcher(params, cfg, max_slots=1, max_len=32,
                            compute_dtype=jnp.float32)
    with pytest.raises(ValueError, match="max_len"):
        srv.submit(list(range(1, 30)), max_new_tokens=10)
    with pytest.raises(ValueError, match="sliding-window"):
        init_slot_cache(cfg.with_(sliding_window=8), 2, 32)


def test_chunked_greedy_matches_per_step(model):
    """chunk_steps > 1 (N tokens per dispatch, in-scan argmax feedback,
    overshoot rewound) must be token-for-token identical to per-step
    serving and to generate(), including slot reuse after an early finish
    inside a chunk."""
    cfg, params = model
    srv = ContinuousBatcher(params, cfg, max_slots=2, max_len=96,
                            compute_dtype=jnp.float32, prefill_pad_to=16,
                            chunk_steps=4)
    rng = np.random.default_rng(21)
    p1 = rng.integers(1, cfg.vocab_size, 5).tolist()
    p2 = rng.integers(1, cfg.vocab_size, 9).tolist()
    p3 = rng.integers(1, cfg.vocab_size, 4).tolist()
    # 6 and 10 are NOT multiples of 4 → both requests overshoot mid-chunk
    # and must be trimmed + rewound; p3 then reuses a rewound slot.
    r1 = srv.submit(p1, max_new_tokens=6)
    r2 = srv.submit(p2, max_new_tokens=10)
    for _ in range(10):
        srv.step()
        if srv.result(r1)["status"] == "done":
            break
    r3 = srv.submit(p3, max_new_tokens=7)
    for _ in range(30):
        if all(srv.result(r)["status"] == "done" for r in (r1, r2, r3)):
            break
        srv.step()
    for rid, prompt, n in ((r1, p1, 6), (r2, p2, 10), (r3, p3, 7)):
        assert srv.result(rid)["tokens"] == _ref_greedy(params, cfg, prompt, n)


def test_chunked_mode_defers_to_per_step_for_sampling(model):
    """A batch containing a temperature>0 request must take the per-step
    path (the chunk's in-scan feedback is argmax-only)."""
    cfg, params = model
    srv = ContinuousBatcher(params, cfg, max_slots=2, max_len=64,
                            compute_dtype=jnp.float32, prefill_pad_to=16,
                            chunk_steps=4, seed=7)
    g = srv.submit([2, 3, 4], max_new_tokens=5)             # greedy
    s = srv.submit([5, 6], max_new_tokens=5, temperature=0.8)
    for _ in range(20):
        if all(srv.result(r)["status"] == "done" for r in (g, s)):
            break
        srv.step()
    assert srv.result(g)["tokens"] == _ref_greedy(params, cfg, [2, 3, 4], 5)
    assert len(srv.result(s)["tokens"]) == 5
