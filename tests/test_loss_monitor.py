"""Loss monitor: all five detectors, cooldown, caps, reset, curve feed."""

import math

from tpu_engine.loss_monitor import (
    AlertSeverity,
    LossSpikeMonitor,
    MonitorConfig,
    TrainingMetrics,
)


def m(step, loss, lr=None, gnorm=None):
    return TrainingMetrics(step=step, loss=loss, learning_rate=lr, gradient_norm=gnorm)


def feed_flat(mon, n, loss=2.0, start=0):
    for i in range(start, start + n):
        mon.ingest(m(i, loss + 0.001 * (i % 3)))


def test_nan_divergence_early_return_keeps_window_clean():
    mon = LossSpikeMonitor("j")
    feed_flat(mon, 20)
    alerts = mon.ingest(m(20, float("nan")))
    assert len(alerts) == 1
    assert alerts[0].alert_type == "divergence"
    assert alerts[0].severity == AlertSeverity.CRITICAL
    # NaN never entered the rolling window (reference append-after-check semantics).
    assert not math.isnan(mon.get_summary()["rolling_mean_loss"])


def test_inf_and_threshold_divergence():
    mon = LossSpikeMonitor("j")
    assert mon.ingest(m(0, float("inf")))[0].alert_type == "divergence"
    mon2 = LossSpikeMonitor("j2")
    alerts = mon2.ingest(m(0, 2e6))
    assert alerts and alerts[0].alert_type == "divergence"


def test_spike_detection_with_sigma_levels():
    # Window alternating 1.9/2.1 → mean 2.0, σ 0.1 → 3σ thr 2.3, 5σ thr 2.5.
    mon = LossSpikeMonitor("j")
    for i in range(30):
        mon.ingest(m(i, 1.9 if i % 2 else 2.1))
    warn = mon.ingest(m(30, 2.4))  # between 3σ and 5σ → WARNING
    assert any(a.alert_type == "loss_spike" and a.severity == AlertSeverity.WARNING
               for a in warn)
    crit = mon.ingest(m(55, 3.0))  # past cooldown, above 5σ → CRITICAL
    assert any(a.alert_type == "loss_spike" and a.severity == AlertSeverity.CRITICAL
               for a in crit)


def test_spike_needs_min_history():
    mon = LossSpikeMonitor("j")
    feed_flat(mon, 5)
    assert mon.ingest(m(5, 100.0)) == []  # < min_history_for_spike and < divergence


def test_spike_cooldown():
    cfg = MonitorConfig(alert_cooldown_steps=20)
    mon = LossSpikeMonitor("j", cfg)
    feed_flat(mon, 30)
    a1 = mon.ingest(m(30, 50.0))
    assert a1
    a2 = mon.ingest(m(31, 60.0))  # within cooldown
    assert not any(x.alert_type == "loss_spike" for x in a2)
    a3 = mon.ingest(m(55, 60.0))  # past cooldown
    assert any(x.alert_type == "loss_spike" for x in a3)


def test_plateau_detection():
    cfg = MonitorConfig(plateau_patience_steps=50)
    mon = LossSpikeMonitor("j", cfg)
    mon.ingest(m(0, 1.0))
    for i in range(1, 60):
        alerts = mon.ingest(m(i, 1.0))  # never improves
    assert any(a.alert_type == "plateau" for a in mon.alerts)


def test_gradient_explosion():
    mon = LossSpikeMonitor("j")
    alerts = mon.ingest(m(0, 2.0, gnorm=150.0))
    assert any(a.alert_type == "gradient_explosion"
               and a.severity == AlertSeverity.CRITICAL for a in alerts)


def test_lr_anomaly():
    mon = LossSpikeMonitor("j")
    for i in range(6):
        mon.ingest(m(i, 2.0, lr=1e-4))
    alerts = mon.ingest(m(6, 2.0, lr=5e-3))  # 50× rolling average
    assert any(a.alert_type == "lr_anomaly" for a in alerts)


def test_max_alerts_per_type_enforced():
    cfg = MonitorConfig(max_alerts_per_type=2, alert_cooldown_steps=0)
    mon = LossSpikeMonitor("j", cfg)
    for i in range(5):
        mon.ingest(m(i, 2e6))  # divergence every step
    assert mon.get_summary()["alerts_by_type"]["divergence"] == 2


def test_bounded_history():
    cfg = MonitorConfig(max_history=100)
    mon = LossSpikeMonitor("j", cfg)
    feed_flat(mon, 500)
    assert mon.get_summary()["total_steps_seen"] == 100  # bounded, no leak


def test_summary_and_curve():
    mon = LossSpikeMonitor("job-1")
    for i in range(20):
        mon.ingest(m(i, 3.0 - 0.1 * i, lr=1e-4, gnorm=1.0))
    s = mon.get_summary()
    assert s["job_id"] == "job-1"
    assert s["best_loss"] == min(3.0 - 0.1 * i for i in range(20))
    curve = mon.get_loss_curve()
    assert len(curve["steps"]) == 20
    assert len(curve["losses"]) == 20
    assert curve["learning_rates"][0] == 1e-4


def test_reset():
    mon = LossSpikeMonitor("j")
    feed_flat(mon, 30)
    mon.ingest(m(31, 1e7))
    mon.reset()
    s = mon.get_summary()
    assert s["total_steps_seen"] == 0 and s["total_alerts"] == 0
    assert s["best_loss"] is None
