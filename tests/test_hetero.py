"""Heterogeneity plane: apportionment invariants, tracker EMA semantics,
and the hysteresis guards of the rebalance policy loop (PR 11).

The one invariant that must never bend: a row assignment always sums to
the declared global micro batch exactly — property-tested over random
throughputs, floors, and caps, with :class:`InfeasibleAssignment` raised
(never a silently resized batch) when the constraints cannot be met.
"""

import random
from types import SimpleNamespace

import pytest

from tpu_engine.hetero import (
    MIN_RELATIVE_THROUGHPUT,
    HeteroRebalancer,
    InfeasibleAssignment,
    ThroughputTracker,
    broadcast_agree_fn,
    clear_active,
    get_active,
    hbm_max_rows_fn,
    predicted_goodput,
    set_active,
    solve_row_assignment,
    uniform_assignment,
)
from tpu_engine.tracing import FlightRecorder


# -- apportionment ------------------------------------------------------------


def test_uniform_assignment_spreads_remainder():
    assert uniform_assignment(8, 2) == [4, 4]
    assert uniform_assignment(9, 2) == [5, 4]
    assert uniform_assignment(10, 4) == [3, 3, 2, 2]
    with pytest.raises(ValueError, match="at least one process"):
        uniform_assignment(8, 0)


def test_solver_uniform_rates_gives_uniform_split():
    assert solve_row_assignment([1.0] * 4, 16) == [4, 4, 4, 4]
    assert solve_row_assignment([1.0, 1.0], 9) == [5, 4]


def test_solver_shifts_rows_off_the_slow_process():
    rows = solve_row_assignment([1.0, 1.0, 1.0, 0.5], 16)
    assert sum(rows) == 16
    assert rows[3] < min(rows[:3])
    # And the weighted split predicts strictly better goodput.
    tput = [1.0, 1.0, 1.0, 0.5]
    assert predicted_goodput(rows, tput) > predicted_goodput(
        uniform_assignment(16, 4), tput
    )


def test_solver_exact_sum_property():
    """Sum preservation over random gangs — the invariant the data plane
    relies on (a wrong sum drops or double-reads rows every step)."""
    rng = random.Random(0)
    for trial in range(300):
        n = rng.randint(1, 16)
        min_rows = rng.randint(1, 3)
        total = rng.randint(n * min_rows, n * min_rows + 512)
        tput = [rng.uniform(0.01, 2.0) for _ in range(n)]
        caps = None
        if rng.random() < 0.5:
            # Feasible caps: at least the floor, summing to >= total.
            caps = [
                None if rng.random() < 0.3
                else rng.randint(min_rows, max(min_rows, total))
                for _ in range(n)
            ]
            short = total - sum(c if c is not None else total for c in caps)
            if short > 0:
                caps[0] = (caps[0] or 0) + short
        rows = solve_row_assignment(
            tput, total, min_rows=min_rows, max_rows=caps
        )
        assert sum(rows) == total, (trial, tput, total)
        assert all(r >= min_rows for r in rows), (trial, rows)
        if caps is not None:
            assert all(
                c is None or r <= c for r, c in zip(rows, caps)
            ), (trial, rows, caps)


def test_solver_deterministic():
    tput = [1.0, 0.7, 0.9, 0.7]
    a = solve_row_assignment(tput, 37)
    assert a == solve_row_assignment(list(tput), 37)
    assert sum(a) == 37


def test_solver_infeasible_raises_not_resizes():
    with pytest.raises(InfeasibleAssignment, match="floor"):
        solve_row_assignment([1.0, 1.0], 1, min_rows=1)
    with pytest.raises(InfeasibleAssignment, match="cap below"):
        solve_row_assignment([1.0, 1.0], 8, min_rows=2, max_rows=[1, 8])
    with pytest.raises(InfeasibleAssignment, match="sum to"):
        solve_row_assignment([1.0, 1.0], 8, max_rows=[3, 3])
    with pytest.raises(ValueError, match="non-empty"):
        solve_row_assignment([], 8)


def test_solver_floors_near_zero_throughput():
    # A ~dead process is clamped to MIN_RELATIVE_THROUGHPUT, never starved
    # below the floor and never a division by zero.
    rows = solve_row_assignment([1.0, 0.0], 8)
    assert sum(rows) == 8 and rows[1] >= 1


def test_predicted_goodput():
    assert predicted_goodput([4, 4], [1.0, 1.0]) == pytest.approx(1.0)
    # Uniform split on a 2x-slow host: step gated at 4/0.5 = 8 row-times,
    # ideal is 8/1.5 = 5.33 -> 2/3.
    assert predicted_goodput([4, 4], [1.0, 0.5]) == pytest.approx(2 / 3)
    assert predicted_goodput([], []) == 0.0


# -- HBM row caps -------------------------------------------------------------


class _Cfg:
    def __init__(self, micro):
        self.micro_batch_size = micro

    def model_copy(self, update):
        c = _Cfg(self.micro_batch_size)
        for k, v in update.items():
            setattr(c, k, v)
        return c


def _linear_estimate(cfg):
    # 1 GiB per effective micro-batch row: monotone, easy to reason about.
    return SimpleNamespace(device_total_gib=float(cfg.micro_batch_size))


def test_hbm_max_rows_binary_search():
    cfg = _Cfg(micro=2)
    # budget 2 GiB -> eff micro <= 2 -> rows <= 4 of a 4-row uniform share.
    fn = hbm_max_rows_fn(
        cfg, 2, 2.0, estimate_fn=_linear_estimate, margin_frac=0.0
    )
    assert fn(0, 4) == 4
    # Generous budget: the hi probe fits outright.
    fn = hbm_max_rows_fn(
        cfg, 2, 100.0, estimate_fn=_linear_estimate, margin_frac=0.0
    )
    assert fn(0, 4) == 8  # rows_uniform * n_processes


def test_hbm_max_rows_unpriceable_returns_none():
    cfg = _Cfg(micro=2)
    # Even one row over budget: "no cap known", not an impossible 0.
    fn = hbm_max_rows_fn(
        cfg, 2, 0.25, estimate_fn=_linear_estimate, margin_frac=0.0
    )
    assert fn(0, 4) is None

    def boom(cfg):
        raise RuntimeError("no estimator for this model")

    fn = hbm_max_rows_fn(cfg, 2, 8.0, estimate_fn=boom, margin_frac=0.0)
    assert fn(0, 4) is None
    # micro=0 (unknown config) short-circuits too.
    fn = hbm_max_rows_fn(
        _Cfg(micro=0), 2, 8.0, estimate_fn=_linear_estimate, margin_frac=0.0
    )
    assert fn(0, 4) is None


# -- throughput tracker -------------------------------------------------------


def test_tracker_starts_uniform():
    trk = ThroughputTracker(4)
    assert trk.relative_throughput() == [1.0] * 4
    assert trk.imbalance() == pytest.approx(1.0)


def test_tracker_host_slow_pulls_estimate_down():
    trk = ThroughputTracker(4, alpha=0.25)
    # Penalty equal to the baseline: the host ran at 1/2 speed.
    trk.note_host_slow(2, 1.0, 1.0)
    rel = trk.relative_throughput()
    assert rel[2] == pytest.approx(0.875)  # one EMA step toward 0.5
    assert rel[0] == rel[1] == rel[3] == 1.0
    for _ in range(30):
        trk.note_host_slow(2, 1.0, 1.0)
    assert trk.relative_throughput()[2] == pytest.approx(0.5, abs=0.01)
    assert trk.imbalance() == pytest.approx(2.0, abs=0.05)
    assert trk.slow_signals_total == 31


def test_tracker_decays_back_to_healthy_when_quiet():
    trk = ThroughputTracker(2, alpha=0.25, decay=0.02)
    for _ in range(30):
        trk.note_host_slow(1, 1.0, 1.0)
    # A reinforced estimate does not decay on the step that reinforced it.
    trk.note_host_slow(1, 1.0, 1.0)
    held = trk.relative_throughput()[1]
    trk.observe_step(1.0)
    assert trk.relative_throughput()[1] == pytest.approx(held)
    # Quiet steps relax it back toward 1.0 (transient stalls heal).
    for _ in range(200):
        trk.observe_step(1.0)
    assert trk.relative_throughput()[1] > 0.9


def test_tracker_attribution_seeding_filters():
    trk = ThroughputTracker(3, alpha=0.25)
    # Wrong cause / unsustained / implausible durations: all ignored.
    trk.note_attribution("ici-degraded", {"sustained": True, "duration_s": 2.0, "baseline_s": 1.0}, 1)
    trk.note_attribution("host-slow", {"sustained": False, "duration_s": 2.0, "baseline_s": 1.0}, 1)
    trk.note_attribution("host-slow", {"sustained": True, "duration_s": 0.5, "baseline_s": 1.0}, 1)
    assert trk.relative_throughput() == [1.0, 1.0, 1.0]
    assert trk.attribution_seeds_total == 0
    # A sustained host-slow attribution seeds base/dur.
    trk.note_attribution("host-slow", {"sustained": True, "duration_s": 2.0, "baseline_s": 1.0}, 1)
    assert trk.relative_throughput()[1] == pytest.approx(0.875)
    assert trk.attribution_seeds_total == 1


def test_tracker_baseline_and_index_clamp():
    trk = ThroughputTracker(2)
    trk.observe_step(2.0)
    trk.observe_step(1.0)  # new minimum wins outright
    assert trk.baseline_s() == pytest.approx(1.0)
    trk.observe_step(2.0)  # slower steps drift the baseline up gently
    assert trk.baseline_s() == pytest.approx(0.98 * 1.0 + 0.02 * 2.0)
    # Out-of-range process indices clamp instead of raising mid-step-loop.
    trk.note_host_slow(99, 1.0, 1.0)
    assert trk.relative_throughput()[1] < 1.0
    trk.note_host_slow(-5, 1.0, 1.0)
    assert trk.relative_throughput()[0] < 1.0
    with pytest.raises(ValueError, match="positive"):
        ThroughputTracker(0)


# -- rebalance policy ---------------------------------------------------------


def _slow_tracker(n=2, slow=1, signals=30):
    trk = ThroughputTracker(n)
    for _ in range(signals):
        trk.note_host_slow(slow, 1.0, 1.0)  # -> ~0.5 relative
    return trk


def test_rebalancer_balanced_gang_never_moves():
    t = [0.0]
    reb = HeteroRebalancer(
        ThroughputTracker(4), 16, sustain_consults=1, clock=lambda: t[0],
        recorder=FlightRecorder(clock=lambda: t[0]),
    )
    for step in range(5):
        assert reb.maybe_rebalance(step) is None
    assert reb.skips["balanced"] == 5
    assert reb.assignment == [4, 4, 4, 4]


def test_rebalancer_sustain_then_dry_run_then_live():
    t = [0.0]
    rec = FlightRecorder(clock=lambda: t[0])
    reb = HeteroRebalancer(
        _slow_tracker(), 8, sustain_consults=2, min_gain=0.01,
        cooldown_s=60.0, dry_run=True, clock=lambda: t[0], recorder=rec,
        trace_id="t-hetero",
    )
    # First consult proposing a change is held for sustain.
    assert reb.maybe_rebalance(10) is None
    assert reb.skips["sustain"] == 1
    # Second consecutive proposal fires — but dry-run leaves the gang alone.
    t[0] = 5.0
    plan = reb.maybe_rebalance(20)
    assert plan is not None and plan.dry_run
    assert sum(plan.assignment) == 8
    assert plan.assignment[1] < plan.assignment[0]
    assert plan.goodput_after > plan.goodput_before
    assert reb.assignment == [4, 4]  # unchanged
    assert reb.dry_runs_total == 1 and reb.rebalances_total == 0
    audits = [e for e in rec.events(kind="hetero") if e["name"] == "hetero_rebalance"]
    assert len(audits) == 1
    assert audits[0]["trace_id"] == "t-hetero"
    assert audits[0]["attrs"]["dry_run"] is True

    # Live mode applies the plan (fresh rebalancer, same tracker state).
    live = HeteroRebalancer(
        _slow_tracker(), 8, sustain_consults=1, min_gain=0.01,
        dry_run=False, clock=lambda: t[0], recorder=rec,
    )
    plan = live.maybe_rebalance(30)
    assert plan is not None and not plan.dry_run
    assert live.assignment == plan.assignment
    assert sum(live.assignment) == 8
    assert live.rebalances_total == 1


def test_rebalancer_cooldown_bounds_rebalance_rate():
    t = [0.0]
    trk = _slow_tracker()
    reb = HeteroRebalancer(
        trk, 8, sustain_consults=1, min_gain=0.01, cooldown_s=100.0,
        dry_run=False, clock=lambda: t[0],
        recorder=FlightRecorder(clock=lambda: t[0]),
    )
    assert reb.maybe_rebalance(1) is not None
    # Degrade further: the solver proposes yet another split...
    for _ in range(40):
        trk.note_host_slow(1, 4.0, 1.0)  # -> ~0.2 relative
    assert reb.maybe_rebalance(2) is None  # ...but cooldown holds it
    assert reb.skips["cooldown"] == 1
    t[0] = 200.0  # past the window: now it may act again
    assert reb.maybe_rebalance(3) is not None
    assert reb.rebalances_total == 2
    assert sum(reb.assignment) == 8


def test_rebalancer_gain_floor_skip_is_audited():
    t = [0.0]
    rec = FlightRecorder(clock=lambda: t[0])
    reb = HeteroRebalancer(
        _slow_tracker(), 8, sustain_consults=1, min_gain=0.5,
        imbalance_trigger=1.01, dry_run=False, clock=lambda: t[0],
        recorder=rec,
    )
    assert reb.maybe_rebalance(1) is None
    assert reb.skips["gain"] == 1
    assert reb.assignment == [4, 4]
    skips = [e for e in rec.events(kind="hetero") if e["name"] == "hetero_rebalance_skip"]
    assert skips and skips[-1]["attrs"]["reason"] == "gain-below-floor"


def test_rebalancer_hbm_infeasible_skips_and_audits():
    t = [0.0]
    rec = FlightRecorder(clock=lambda: t[0])
    reb = HeteroRebalancer(
        _slow_tracker(), 8, sustain_consults=1, min_gain=0.01,
        dry_run=False, clock=lambda: t[0], recorder=rec,
        max_rows_fn=lambda i, rows_u: 3,  # caps sum to 6 < 8: infeasible
    )
    assert reb.maybe_rebalance(1) is None
    assert reb.skips["hbm"] == 1
    assert reb.assignment == [4, 4]
    skips = [e for e in rec.events(kind="hetero") if e["name"] == "hetero_rebalance_skip"]
    assert skips and skips[-1]["attrs"]["reason"] == "hbm-infeasible"


def test_rebalancer_hbm_caps_shape_the_plan():
    t = [0.0]
    reb = HeteroRebalancer(
        _slow_tracker(n=4, slow=3), 16, sustain_consults=1, min_gain=0.01,
        dry_run=False, clock=lambda: t[0],
        recorder=FlightRecorder(clock=lambda: t[0]),
        max_rows_fn=lambda i, rows_u: 5,  # no host may exceed 5 rows
    )
    plan = reb.maybe_rebalance(1)
    assert plan is not None
    assert sum(plan.assignment) == 16
    assert max(plan.assignment) <= 5
    assert plan.hbm_capped == [0, 1, 2, 3]


def test_recovered_goodput_fraction():
    t = [0.0]
    reb = HeteroRebalancer(
        _slow_tracker(), 8, sustain_consults=1, min_gain=0.01,
        dry_run=False, clock=lambda: t[0],
        recorder=FlightRecorder(clock=lambda: t[0]),
    )
    assert reb.recovered_goodput_fraction() == 0.0  # still uniform
    assert reb.maybe_rebalance(1) is not None
    assert reb.recovered_goodput_fraction() > 0.1
    st = reb.stats()
    assert st["assignment"] == reb.assignment
    assert st["last_plan"]["step"] == 1
    assert st["tracker"]["n_processes"] == 2


def test_consult_request_is_served_and_cleared_by_any_consult():
    t = [0.0]
    reb = HeteroRebalancer(
        ThroughputTracker(2), 8, sustain_consults=1, clock=lambda: t[0],
        recorder=FlightRecorder(clock=lambda: t[0]),
    )
    assert not reb.consult_pending()
    reb.request_consult()
    assert reb.consult_pending()
    # A balanced gang declines the consult, but the request is still served.
    assert reb.maybe_rebalance(1) is None
    assert not reb.consult_pending()
    assert reb.stats()["consult_requested"] is False


def test_step_based_cooldown_ignores_wall_clock():
    t = [0.0]
    trk = _slow_tracker()
    reb = HeteroRebalancer(
        trk, 8, sustain_consults=1, min_gain=0.01, cooldown_s=0.0,
        cooldown_steps=10, dry_run=False, clock=lambda: t[0],
        recorder=FlightRecorder(clock=lambda: t[0]),
    )
    assert reb.maybe_rebalance(1) is not None
    for _ in range(40):
        trk.note_host_slow(1, 4.0, 1.0)  # degrade further -> new proposal
    # Clock skew must not let one rank act while its peers hold: with
    # cooldown_steps set, an enormous wall-clock jump changes nothing.
    t[0] = 1e6
    assert reb.maybe_rebalance(5) is None
    assert reb.skips["cooldown"] == 1
    assert reb.maybe_rebalance(11) is not None
    assert reb.rebalances_total == 2
    assert reb.stats()["last_rebalance_step"] == 11


def test_agree_fn_aligns_ranks_with_divergent_local_estimates():
    """Two ranks whose local trackers disagree still derive the identical
    plan when both solve from the broadcast (agreed) estimates."""
    t = [0.0]
    agreed = [1.0, 0.5]
    plans = []
    # Rank A saw the slowdown locally; rank B's local tracker is uniform
    # (it would have skipped as "balanced" without the agreement hook).
    for local in (_slow_tracker(), ThroughputTracker(2)):
        reb = HeteroRebalancer(
            local, 8, sustain_consults=1, min_gain=0.01, dry_run=False,
            agree_fn=lambda tput: list(agreed), clock=lambda: t[0],
            recorder=FlightRecorder(clock=lambda: t[0]),
        )
        plans.append(reb.maybe_rebalance(1))
    assert plans[0] is not None and plans[1] is not None
    assert plans[0].assignment == plans[1].assignment
    assert plans[0].throughputs == plans[1].throughputs == agreed


def test_broadcast_agree_fn_is_identity_on_single_process():
    agree = broadcast_agree_fn()
    assert agree([1.0, 0.5, 0.25]) == [1.0, 0.5, 0.25]


def test_revert_restores_assignment_and_audits():
    t = [0.0]
    rec = FlightRecorder(clock=lambda: t[0])
    reb = HeteroRebalancer(
        _slow_tracker(), 8, sustain_consults=1, min_gain=0.01,
        dry_run=False, clock=lambda: t[0], recorder=rec,
    )
    plan = reb.maybe_rebalance(1)
    assert plan is not None and reb.assignment == plan.assignment
    # The data layer refused the windows: the gauge must not keep
    # reporting a split that is not actually feeding the mesh.
    reb.revert(plan)
    assert reb.assignment == plan.previous == [4, 4]
    assert reb.reverts_total == 1
    assert reb.recovered_goodput_fraction() == 0.0
    names = [e["name"] for e in rec.events(kind="hetero")]
    assert "hetero_rebalance_reverted" in names

    # Dry-run plans never moved anything — revert is a no-op.
    dry = HeteroRebalancer(
        _slow_tracker(), 8, sustain_consults=1, min_gain=0.01,
        dry_run=True, clock=lambda: t[0], recorder=rec,
    )
    p2 = dry.maybe_rebalance(1)
    assert p2 is not None and p2.dry_run
    dry.revert(p2)
    assert dry.reverts_total == 0


def test_active_singleton():
    t = [0.0]
    reb = HeteroRebalancer(
        ThroughputTracker(2), 8, clock=lambda: t[0],
        recorder=FlightRecorder(clock=lambda: t[0]),
    )
    prev = get_active()  # tolerate leakage from earlier suite members
    try:
        set_active(reb)
        assert get_active() is reb
        clear_active()
        assert get_active() is None
    finally:
        set_active(prev)
