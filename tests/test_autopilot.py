"""Fleet autopilot: the unified control loop's decision audit trail.

Covers the PR's acceptance surface: hysteresis (sustained-trend consult
counts), the blast-radius guards (per-target cooldown,
max-actions-per-window), dry-run producing byte-identical
DecisionRecords to an armed run on the same seeded fault plan (with
zero actuations), the headless historian tick (no ``/metrics`` scrape
anywhere), the IncidentCorrelator action leg with ``action_source``,
the subsumed scheduler/serving/precompile ticks, the scheduler's
autopilot quarantine lifecycle, the HTTP surface, and the twin chaos
A/B lane's gates."""

import asyncio
import threading

import httpx
import pytest
from aiohttp import web

from tpu_engine import autopilot as autopilot_mod
from tpu_engine.autopilot import (
    RULES,
    SUPPRESSION_REASONS,
    AutopilotConfig,
    FleetAutopilot,
)
from tpu_engine.compile_index import CompileCacheIndex, PrecompileWorker
from tpu_engine.historian import IncidentCorrelator, MetricHistorian
from tpu_engine.tracing import FlightRecorder
from tpu_engine.twin import VirtualClock, deterministic_ids, host_slow_plan
from tpu_engine.faults import FaultInjector

# ---------------------------------------------------------------------------
# rig: scripted planes on a virtual clock
# ---------------------------------------------------------------------------


def make_rig(
    dry_run: bool = False,
    *,
    sustain: int = 3,
    cooldown_s: float = 100.0,
    max_actions: int = 2,
    blame_threshold: int = 2,
    max_decisions: int = 512,
    actuator=None,
):
    clock = VirtualClock(1000.0)
    rec = FlightRecorder(
        max_spans=4096, max_events=4096, clock=clock,
        id_factory=deterministic_ids("t"),
    )
    hist = MetricHistorian(clock=clock)
    corr = IncidentCorrelator(
        clock=clock, merge_window_s=10.0, stale_after_s=1e9
    )
    drained = []
    ap = FleetAutopilot(
        AutopilotConfig(
            trend_window_s=60.0,
            sustain_consults=sustain,
            cooldown_s=cooldown_s,
            max_actions_per_window=max_actions,
            action_window_s=10_000.0,
            fault_blame_threshold=blame_threshold,
            host_health_floor=0.9,
            max_decisions=max_decisions,
        ),
        dry_run=dry_run,
        historian=hist,
        correlator=corr,
        recorder=rec,
        actuators={
            "drain_host": actuator
            or (lambda r: drained.append(r.action["params"]["device_index"]))
        },
        clock=clock,
        id_factory=deterministic_ids("apd"),
        trace_id="fleet",
    )
    return clock, rec, hist, corr, ap, drained


def blame(rec, hist, t: float, idx: int = 3, n: int = 2, health: float = 0.5):
    """Script the drain-rule trigger: n recorder blame events + an
    unhealthy retained health sample for host idx at time t."""
    for i in range(n):
        rec.event(
            "host_slow", kind="fault", trace_id="fleet", ts=t,
            attrs={"device_index": idx, "step": i},
        )
    hist.record("hetero_host_health", health, ts=t, labels={"host": str(idx)})


# ---------------------------------------------------------------------------
# hysteresis + guards
# ---------------------------------------------------------------------------


def test_sustained_trend_consult_counts():
    """The rule fires only on the Nth *consecutive* breaching consult;
    each earlier consult is a recorded trend-not-sustained suppression."""
    clock, rec, hist, corr, ap, drained = make_rig(sustain=3)
    outcomes = []
    for _ in range(3):
        blame(rec, hist, clock.t)
        (d,) = ap.tick(now=clock.t)
        outcomes.append((d.outcome, d.suppressed_reason,
                         d.hysteresis["streak"]))
        clock.advance(5.0)
    assert outcomes == [
        ("suppressed", "trend-not-sustained", 1),
        ("suppressed", "trend-not-sustained", 2),
        ("fired", None, 3),
    ]
    assert drained == [3]


def test_streak_resets_when_signal_goes_quiet():
    clock, rec, hist, corr, ap, _ = make_rig(sustain=3)
    for _ in range(2):
        blame(rec, hist, clock.t)
        ap.tick(now=clock.t)
        clock.advance(5.0)
    # Signal absent for longer than the trend window: no consult at all,
    # and the streak starts over on the next breach.
    clock.advance(120.0)
    assert ap.tick(now=clock.t) == []
    blame(rec, hist, clock.t)
    (d,) = ap.tick(now=clock.t)
    assert d.hysteresis["streak"] == 1
    assert d.suppressed_reason == "trend-not-sustained"


def test_per_target_cooldown():
    clock, rec, hist, corr, ap, drained = make_rig(sustain=1, cooldown_s=100.0)
    blame(rec, hist, clock.t)
    (d1,) = ap.tick(now=clock.t)
    assert d1.outcome == "fired"
    clock.advance(10.0)
    blame(rec, hist, clock.t)
    (d2,) = ap.tick(now=clock.t)
    assert d2.outcome == "suppressed"
    assert d2.suppressed_reason == "cooldown-active"
    assert d2.hysteresis["cooldown_remaining_s"] == pytest.approx(90.0)
    # Past the cooldown the same target may fire again.
    clock.advance(95.0)
    blame(rec, hist, clock.t)
    (d3,) = ap.tick(now=clock.t)
    assert d3.outcome == "fired"
    assert drained == [3, 3]


def test_max_actions_per_window_blast_radius():
    """The budget is loop-wide: a third target's decision is suppressed
    even though its own streak and cooldown would allow it."""
    clock, rec, hist, corr, ap, drained = make_rig(
        sustain=1, max_actions=2, cooldown_s=1.0
    )
    for idx in (1, 2, 5):
        blame(rec, hist, clock.t, idx=idx)
    decisions = ap.tick(now=clock.t)
    assert [d.outcome for d in decisions] == ["fired", "fired", "suppressed"]
    assert decisions[2].suppressed_reason == "blast-radius"
    assert decisions[2].hysteresis["actions_in_window"] == 2
    assert drained == [1, 2]


def test_no_actuator_is_a_structured_suppression():
    clock, rec, hist, corr, ap, _ = make_rig(sustain=1)
    ap.actuators = {}  # nothing wired: the loop must say so, not crash
    blame(rec, hist, clock.t)
    (d,) = ap.tick(now=clock.t)
    assert (d.outcome, d.suppressed_reason) == ("suppressed", "no-actuator")
    assert ap.stats()["actuations_total"] == 0


def test_decision_ring_is_bounded():
    clock, rec, hist, corr, ap, _ = make_rig(sustain=1, max_decisions=4,
                                             cooldown_s=1e9)
    for _ in range(6):
        blame(rec, hist, clock.t)
        ap.tick(now=clock.t)
        clock.advance(5.0)
    s = ap.stats()
    assert s["decisions_retained"] == 4
    assert s["decisions_dropped_total"] == 2
    assert len(ap.decisions(limit=0)) == 4


# ---------------------------------------------------------------------------
# every consult -> exactly one explainable record
# ---------------------------------------------------------------------------


def test_exactly_one_record_per_consult_with_inputs_and_incident_link():
    clock, rec, hist, corr, ap, _ = make_rig(sustain=2)
    # Quiet loop: no signal, no records at all.
    assert ap.tick(now=clock.t) == []
    assert ap.stats()["decisions_total"] == 0
    blame(rec, hist, clock.t)
    (d,) = ap.tick(now=clock.t)
    # Historian range-query inputs: the consulted series, aggregate and
    # window — never an instant sample.
    (q,) = d.inputs["queries"]
    assert q["series"] == "hetero_host_health"
    assert q["labels"] == {"host": "3"}
    assert q["agg"] == "avg"
    assert q["window_s"] == 60.0
    assert q["value"] == pytest.approx(0.5)
    assert q["count"] == 1
    assert d.inputs["evidence"]["blame_events"] == 2
    # The blame events opened an incident before the rules ran; its id
    # is the decision's incident link.
    assert d.inputs["incidents"], "decision carries no incident link"
    inc_id = d.inputs["incidents"][0]
    assert corr.get(inc_id) is not None
    # Mirrored as a kind="autopilot" span on the flight recorder.
    spans = rec.spans(kind="autopilot", limit=0)
    assert len(spans) == 1
    assert spans[0]["attrs"]["decision_id"] == d.decision_id
    assert spans[0]["attrs"]["incident_ids"] == [inc_id]


def test_correlator_attaches_action_leg_with_action_source():
    clock, rec, hist, corr, ap, _ = make_rig(sustain=1)
    blame(rec, hist, clock.t)
    (d,) = ap.tick(now=clock.t)
    assert d.outcome == "fired"
    (inc,) = corr.incidents(limit=0)
    legs = [e for e in inc["timeline"]
            if e["role"] == "action" and e["kind"] == "autopilot"]
    assert len(legs) == 1
    assert legs[0]["action_source"] == "autopilot"
    assert legs[0]["attrs"]["decision_id"] == d.decision_id
    assert inc["state"] == "mitigating"


def test_dry_run_action_leg_is_sourced_dryrun_and_human_stays_human():
    clock, rec, hist, corr, ap, _ = make_rig(sustain=1, dry_run=True)
    blame(rec, hist, clock.t)
    ap.tick(now=clock.t)
    # A human-operated mitigation on the same incident keeps its source.
    rec.event(
        "hetero_quarantine", kind="scheduler", trace_id="fleet", ts=clock.t,
        attrs={"devices": [3]},
    )
    corr.ingest(recorder=rec, now=clock.t)
    (inc,) = corr.incidents(limit=0)
    sources = sorted(
        e["action_source"] for e in inc["timeline"] if e["role"] == "action"
    )
    assert sources == ["autopilot-dryrun", "human"]


# ---------------------------------------------------------------------------
# dry-run: byte-identical stream, zero actuations
# ---------------------------------------------------------------------------


def _replay_plan_through(ap_dry_run: bool, seed: int):
    """Feed the same seeded HOST_SLOW fault plan through a rig. The spy
    actuator records but does not feed back into the observed series, so
    armed and shadow runs see identical inputs end to end."""
    plan = host_slow_plan(seed)
    inj = FaultInjector(plan)
    inj.arm()
    actuations = []
    clock, rec, hist, corr, ap, _ = make_rig(
        ap_dry_run, sustain=3, cooldown_s=30.0,
        actuator=lambda r: actuations.append(r.action["params"]),
    )
    for step in range(1, 61):
        spec = inj.take_host_slow(step)
        if spec is not None:
            idx = int(spec.device_index or 0)
            rec.event(
                "host_slow", kind="fault", trace_id="fleet", ts=clock.t,
                attrs={"step": step, "device_index": idx},
            )
            hist.record(
                "hetero_host_health", 0.75, ts=clock.t,
                labels={"host": str(idx)},
            )
        clock.advance(0.5)
        if step % 5 == 0:
            ap.tick(now=clock.t)
    return ap, actuations


def test_dry_run_byte_identical_to_armed_on_same_seeded_plan():
    armed, armed_actuations = _replay_plan_through(False, seed=0)
    shadow, shadow_actuations = _replay_plan_through(True, seed=0)
    armed_stream = [r.to_json() for r in armed._records]
    shadow_stream = [r.to_json() for r in shadow._records]
    assert armed_stream, "seeded plan produced no decisions"
    # Byte-for-byte: same ids, same inputs, same hysteresis, same
    # outcomes — mode is not part of the serialized record.
    assert armed_stream == shadow_stream
    assert any(r.outcome == "fired" for r in armed._records)
    # ...but only the armed run touched the fleet.
    assert len(armed_actuations) == armed.stats()["fired_total"] > 0
    assert shadow_actuations == []
    assert shadow.stats()["actuations_total"] == 0
    assert shadow.stats()["fired_total"] == armed.stats()["fired_total"]


# ---------------------------------------------------------------------------
# satellite: headless historian tick (no scrape anywhere)
# ---------------------------------------------------------------------------


def test_autopilot_tick_drives_historian_rollup_without_scrape():
    clock, rec, hist, corr, ap, _ = make_rig()
    seen = []
    hist.add_collector(lambda now: seen.append(now) or {"fleet_gauge": 1.0})
    assert hist.stats()["ticks_total"] == 0
    for _ in range(3):
        ap.tick(now=clock.t)
        clock.advance(11.0)
    # The collector ran and the rollup/retention tick advanced — with no
    # /metrics scrape in sight.
    assert hist.stats()["ticks_total"] == 3
    assert len(seen) == 3
    assert hist.query(
        "fleet_gauge", t0=0.0, t1=clock.t, agg="count"
    )["count"] == 3


# ---------------------------------------------------------------------------
# subsumed ticks: scheduler poll, serving tick, precompile pump
# ---------------------------------------------------------------------------


class _SpyScheduler:
    def __init__(self):
        self.polls = 0

    def poll(self):
        self.polls += 1


class _SpyServing:
    def __init__(self):
        self.ticks = []
        self.desired_replicas = 1

    def tick(self, now):
        self.ticks.append(now)


def test_tick_subsumes_the_three_control_loops():
    clock, rec, hist, corr, ap, _ = make_rig()
    sched, serving = _SpyScheduler(), _SpyServing()
    index = CompileCacheIndex(path=None)
    worker = PrecompileWorker(
        index, compile_fn=lambda task: None, clock=clock, background=False
    )
    ap.scheduler, ap.serving_fleet, ap.precompiler = sched, serving, worker
    ap.actuators = {}
    assert worker.request("layout-a", label="grow-back") == "queued"
    assert worker._thread is None, "background=False must not spawn a thread"
    (d,) = ap.tick(now=clock.t)
    # One pass drove all three planes deterministically on the caller's
    # thread: the scheduler polled, the fleet ticked, and the queued
    # precompile ran through the kick_precompile decision's actuator.
    assert sched.polls == 1
    assert serving.ticks == [clock.t]
    assert d.rule == "kick_precompile"
    assert d.outcome == "fired"
    assert worker.stats()["completed_total"] == 1
    assert worker._thread is None
    # The rule consulted the depth *series* the tick itself retains.
    assert {q["series"] for q in d.inputs["queries"]} == {
        "precompile_queue_depth"
    }
    # Queue drained: the next tick has no consult.
    clock.advance(5.0)
    assert ap.tick(now=clock.t) == []


# ---------------------------------------------------------------------------
# scheduler: autopilot quarantine lifecycle
# ---------------------------------------------------------------------------


def test_scheduler_autopilot_quarantine_survives_heal_pass():
    from tpu_engine.scheduler import FleetScheduler

    sched = FleetScheduler(poll_interval_s=3600.0, hetero_quarantine_ttl_s=50.0)
    try:
        assert sched.quarantine_device(2, owner="autopilot", now=0.0)
        assert not sched.quarantine_device(2, now=0.0), "idempotent"
        # The owner-vouch heal pass must NOT release it as owner-gone
        # ("autopilot" is no submission) — only the TTL or an explicit
        # release does.
        sched._heal_quarantine(now=10.0)
        assert 2 in sched._hetero_quarantined
        assert sched.release_quarantine(2)
        assert 2 not in sched._hetero_quarantined
        # TTL expiry path.
        sched.quarantine_device(5, now=0.0)
        sched._heal_quarantine(now=60.0)
        assert 5 not in sched._hetero_quarantined
    finally:
        sched.shutdown()


# ---------------------------------------------------------------------------
# HTTP surface
# ---------------------------------------------------------------------------


@pytest.fixture()
def client():
    from backend.main import create_app

    loop = asyncio.new_event_loop()
    started = threading.Event()
    state = {}

    def run():
        asyncio.set_event_loop(loop)
        runner = web.AppRunner(create_app())
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, "127.0.0.1", 0)
        loop.run_until_complete(site.start())
        state["port"] = runner.addresses[0][1]
        started.set()
        loop.run_forever()
        loop.run_until_complete(runner.cleanup())

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert started.wait(timeout=30)
    prev = autopilot_mod._autopilot
    with httpx.Client(
        base_url=f"http://127.0.0.1:{state['port']}", timeout=60
    ) as c:
        yield c
    autopilot_mod.set_autopilot(prev)
    loop.call_soon_threadsafe(loop.stop)
    t.join(timeout=10)


def test_autopilot_http_surface(client):
    clock, rec, hist, corr, ap, drained = make_rig(sustain=1)
    autopilot_mod.set_autopilot(ap)
    blame(rec, hist, clock.t)
    ap.tick(now=clock.t)

    r = client.get("/api/v1/autopilot")
    assert r.status_code == 200
    body = r.json()
    assert body["mode"] == "armed"
    assert body["rules"] == list(RULES)
    assert body["suppression_reasons"] == list(SUPPRESSION_REASONS)
    assert body["stats"]["decisions_total"] == 1

    r = client.get("/api/v1/autopilot/decisions")
    assert r.status_code == 200
    (dec,) = r.json()["decisions"]
    assert dec["rule"] == "drain_host"
    assert dec["outcome"] == "fired"
    assert dec["inputs"]["queries"] and dec["inputs"]["incidents"]

    # Filters validate and apply.
    assert client.get(
        "/api/v1/autopilot/decisions", params={"rule": "nope"}
    ).status_code == 400
    assert client.get(
        "/api/v1/autopilot/decisions", params={"outcome": "nope"}
    ).status_code == 400
    assert client.get(
        "/api/v1/autopilot/decisions", params={"outcome": "suppressed"}
    ).json()["decisions"] == []

    # POST /tick runs one control pass (quiet: signal aged out of the
    # trend window, so no consult and no new record).
    clock.advance(120.0)
    r = client.post("/api/v1/autopilot/tick")
    assert r.status_code == 200
    assert r.json()["decisions"] == []
    assert r.json()["stats"]["ticks_total"] == 2

    # Mode flip is explicit and validated.
    assert client.post(
        "/api/v1/autopilot/mode", json={"dry_run": "yes"}
    ).status_code == 400
    r = client.post("/api/v1/autopilot/mode", json={"dry_run": True})
    assert r.json()["mode"] == "dry-run"
    assert ap.dry_run is True


# ---------------------------------------------------------------------------
# twin chaos A/B lane
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_autopilot_chaos_lane_gates():
    from tpu_engine.twin import autopilot_bench_line, autopilot_lane

    lane = autopilot_lane(seed=0)
    assert lane["ok"], lane["gates"]
    assert lane["steady_goodput_on"] >= lane["steady_goodput_off"]
    line = autopilot_bench_line(seed=0)
    assert line["ok"]
    assert line["metric"] == "autopilot_chaos_ab"
    assert line["actuations_dry"] == 0
