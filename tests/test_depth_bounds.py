"""Bounded control-plane structures at depth.

Drives 50k+ samples/records through every bounded ring — historian
raw/rollup rings, flight-recorder span/event rings, autopilot decision
ring, incident store — and asserts each holds its configured bound with
exact (byte-stable) eviction counters, mirroring the 10k-tick historian
plateau test. Also pins the scrape-cost contract: a metrics scrape of
the scheduler reads the state indexes, never a ``_subs`` scan, and never
mutates scheduler state.

Everything here runs on the synthetic clock (tier 1, no sleeps).
"""

import math
import os
import random

from tpu_engine.autopilot import AutopilotConfig, DecisionRecord, FleetAutopilot
from tpu_engine.historian import IncidentCorrelator, MetricHistorian
from tpu_engine.journal import ControlPlaneJournal
from tpu_engine.serving_fleet import _PercentileWindow
from tpu_engine.tracing import FlightRecorder


def _forbidden_clock():
    raise AssertionError("wall clock consulted on the synthetic-clock path")


# ---------------------------------------------------------------------------
# Historian rings at 50k batched samples
# ---------------------------------------------------------------------------


def test_historian_rings_plateau_at_50k_batched_samples():
    """50k samples through the batched ingest path: raw + rollup rings
    plateau, and every eviction is accounted for exactly."""
    raw_cap, t10_cap, t60_cap = 64, 32, 16
    hist = MetricHistorian(
        raw_capacity=raw_cap,
        tiers=((10.0, t10_cap), (60.0, t60_cap)),
        max_series=8,
        clock=_forbidden_clock,
    )
    n_series, n_ticks = 4, 12_500  # 50k samples
    steady = None
    for i in range(n_ticks):
        ts = i * 5.0
        hist.observe_batch(
            [(f"depth_{k}", (ts % 97.0) + k) for k in range(n_series)], ts=ts
        )
        if i == n_ticks - 1_250:  # 90% mark
            steady = hist.stats()
    final = hist.stats()

    assert final["samples_total"] == n_series * n_ticks
    assert final["ingest_batch_total"] == n_ticks
    assert final["ingest_batched_samples_total"] == n_series * n_ticks
    assert final["series"] == n_series
    assert final["raw_samples"] == n_series * raw_cap
    assert final["rollup_buckets"]["10s"] == n_series * t10_cap
    assert final["rollup_buckets"]["1m"] == n_series * t60_cap
    # Exact eviction accounting: every bucket ever created either is
    # still retained or bumped the eviction counter — nothing vanishes.
    max_ts = (n_ticks - 1) * 5.0
    created_10s = int(max_ts // 10.0) + 1
    created_1m = int(max_ts // 60.0) + 1
    expected_evictions = n_series * (
        (created_10s - t10_cap) + (created_1m - t60_cap)
    )
    assert final["bucket_evictions_total"] == expected_evictions
    # Plateau: footprint at 90% and 100% of the run is byte-identical.
    assert final["estimated_bytes"] == steady["estimated_bytes"]
    assert final["raw_samples"] == steady["raw_samples"]
    assert final["rollup_buckets"] == steady["rollup_buckets"]


# ---------------------------------------------------------------------------
# Flight-recorder span/event rings at depth
# ---------------------------------------------------------------------------


def test_recorder_rings_hold_bound_at_depth():
    n, cap = 50_000, 128
    seq = iter(range(10_000_000))
    rec = FlightRecorder(
        max_spans=cap,
        max_events=cap,
        clock=_forbidden_clock,
        id_factory=lambda: f"id-{next(seq)}",
    )
    checkpoint = None
    for i in range(n):
        t = float(i)
        rec.record_span("depth_op", kind="depth", trace_id="tr", t0=t, t1=t + 0.5)
        rec.event("depth_ev", kind="depth", trace_id="tr", ts=t)
        if i == n - 5_000 - 1:  # 90% mark
            checkpoint = rec.stats()
    st = rec.stats()

    assert len(rec.spans(limit=0)) == cap
    assert len(rec.events(limit=0)) == cap
    assert st["spans_total"] == n
    assert st["events_total"] == n
    assert st["spans_dropped"] == n - cap
    assert st["events_dropped"] == n - cap
    # Steady state: the last 10% of the run dropped exactly what it
    # recorded — the rings neither grow nor leak.
    assert st["spans_dropped"] - checkpoint["spans_dropped"] == 5_000
    assert st["events_dropped"] - checkpoint["events_dropped"] == 5_000
    assert st["open_spans"] == 0


# ---------------------------------------------------------------------------
# Autopilot decision ring at depth
# ---------------------------------------------------------------------------


def test_autopilot_decision_ring_bound_at_depth():
    n, cap = 50_000, 64
    ap = FleetAutopilot(
        config=AutopilotConfig(max_decisions=cap), clock=_forbidden_clock
    )
    for i in range(n):
        ap._admit(
            DecisionRecord(
                decision_id=f"d-{i}",
                ts=float(i),
                rule="replan_slow_job",
                target="scheduler",
                inputs={},
                hysteresis={},
                action=None,
                suppressed_reason="below_streak",
                outcome="suppressed",
            )
        )
    st = ap.stats()
    assert st["decisions_total"] == n
    assert st["decisions_retained"] == cap
    assert st["decisions_dropped_total"] == n - cap
    assert len(ap.decisions(limit=0)) == cap
    # The ring keeps the newest records.
    newest = ap.decisions(limit=1)[0]
    assert newest["decision_id"] == f"d-{n - 1}"


# ---------------------------------------------------------------------------
# Incident store at depth
# ---------------------------------------------------------------------------


def _fault_resume_pair(i):
    t = i * 10.0
    fault = {
        "record": "event",
        "event_id": f"f-{i}",
        "trace_id": f"tr-{i}",
        "parent_id": None,
        "name": "fault_injected",
        "kind": "fault",
        "ts": t,
        "attrs": {"device": i % 7},
    }
    resume = {
        "record": "event",
        "event_id": f"r-{i}",
        "trace_id": f"tr-{i}",
        "parent_id": f"f-{i}",
        "name": "supervisor_resume",
        "kind": "supervisor",
        "ts": t + 1.0,
        "attrs": {},
    }
    return [fault, resume]


def test_incident_store_bounded_at_depth():
    cap = 16
    corr = IncidentCorrelator(
        max_incidents=cap, stale_after_s=1e9, clock=_forbidden_clock
    )
    n = 2_000
    batch = []
    for i in range(n):
        batch.extend(_fault_resume_pair(i))
        if len(batch) >= 400:
            corr.ingest(records=batch, now=batch[-1]["ts"])
            batch = []
    if batch:
        corr.ingest(records=batch, now=batch[-1]["ts"])
    st = corr.stats()
    assert st["opened_total"] == n
    assert st["resolved_total"] == n
    assert st["correlated_total"] == 2 * n
    assert st["open"] == 0
    # Closed-incident ring holds its bound and keeps the newest.
    retained = corr.incidents(limit=0)
    assert len(retained) == cap
    assert retained[0]["trigger"] == "fault"
    assert retained[0]["t0"] == (n - 1) * 10.0


# ---------------------------------------------------------------------------
# Percentile window: accuracy contract + bound
# ---------------------------------------------------------------------------


def _exact_pct(vals, q):
    vals = sorted(vals)
    return vals[min(int(q * (len(vals) - 1)), len(vals) - 1)]


def test_percentile_window_within_1pct_of_exact():
    """Property test over random latency streams spanning 7 decades: the
    bucketed window's p50/p90/p99 stay within 1% (relative) of the exact
    sorted-window percentile it replaced."""
    window = 512
    for seed in range(25):
        rng = random.Random(seed)
        pw = _PercentileWindow(window=window)
        tail = []
        for _ in range(2_000):
            v = math.exp(rng.uniform(math.log(0.1), math.log(1e6)))
            pw.add(v)
            tail.append(v)
        tail = tail[-window:]
        assert len(pw) == window
        got = pw.percentiles((0.50, 0.90, 0.99))
        for q, approx in zip((0.50, 0.90, 0.99), got):
            exact = _exact_pct(tail, q)
            assert abs(approx - exact) / exact <= 0.01, (seed, q, approx, exact)


def test_percentile_window_empty_and_degenerate():
    pw = _PercentileWindow(window=8)
    assert pw.percentiles((0.5, 0.99)) == [None, None]
    pw.add(3.0)
    p50, p99 = pw.percentiles((0.5, 0.99))
    assert abs(p50 - 3.0) / 3.0 <= 0.01 and p50 == p99
    # Out-of-range values clamp instead of crashing.
    pw.add(0.0)
    pw.add(1e12)
    assert all(v is not None for v in pw.percentiles((0.5, 0.99)))


# ---------------------------------------------------------------------------
# Write-ahead journal ring at depth
# ---------------------------------------------------------------------------


def test_journal_rotation_bounded_at_depth(tmp_path):
    """20k appends through a small journal: the live file never exceeds
    ``max_bytes``, exactly one rotated generation exists (total disk
    <= 2x the cap), and ``stats()`` is O(1) counters — it never opens or
    walks the files."""
    path = str(tmp_path / "journal.jsonl")
    cap = 64 * 1024
    clk = iter(range(10_000_000))
    j = ControlPlaneJournal(path, max_bytes=cap, clock=lambda: float(next(clk)))
    n = 20_000
    for i in range(n):
        j.append("depth.ev", {"i": i, "pad": "x" * 64})
        if i % 200 == 0:
            j.snapshot({"scheduler": {"seq": i}})
    st = j.stats()
    assert st["appends_total"] == n
    assert st["snapshots_total"] == n // 200
    assert st["rotations_total"] > 10
    assert st["append_errors_total"] == 0
    # Disk bound: one live file under the cap, exactly one .1 generation.
    assert os.path.getsize(path) <= cap
    assert os.path.getsize(path + ".1") <= cap
    assert sorted(p.name for p in tmp_path.iterdir()) == [
        "journal.jsonl", "journal.jsonl.1",
    ]
    assert st["bytes"] == os.path.getsize(path)
    # A reader still gets a usable snapshot+suffix from the bounded pair.
    got = j.read()
    assert got["snapshot"] is not None
    assert got["stats"]["skipped"] == 0
    # stats() after the files vanish: pure counters, no file access.
    os.remove(path)
    os.remove(path + ".1")
    st2 = j.stats()
    assert st2["appends_total"] == n and st2["bytes"] == st["bytes"]
