"""Multi-chip SPMD partitioning quality: no involuntary full remat.

Round-2 VERDICT flagged an XLA ``spmd_partitioner.cc:652`` "involuntary full
rematerialization" warning in the 8-device dry-run's flash-attention config
(MULTICHIP_r02 tail). Investigation (round 3) established:

- The warning is emitted by GSPMD's dot-partitioning *strategy estimator*
  (``fake_parameter`` probes in ``dot_handler``), while costing a candidate
  layout for the o-projection weight-gradient dot ``dW_o = attn^T @ dx``:
  ZeRO stage >= 2 wants ``dW_o`` fsdp-sharded, but fsdp is also a
  batch-group axis of that contraction, so one *candidate* requires
  resharding ``dx`` [B_local, S, D] from batch-sharded to D-over-fsdp —
  exactly the warned pair (source ``devices=[4,1,1,2]``, target
  ``devices=[1,1,2,4]T(1,0,2)`` = P(None, None, "fsdp") in fsdp-major
  order, a spec that exists nowhere in user code).
- The chosen final program does NOT contain the inefficient reshard: the
  partitioned HLO has no all-gather materialising a full stacked-weight
  (or padded-shard) tensor — verified here, mechanically, so a regression
  re-introducing a real full-remat fails the suite.
- The real-TPU AOT compile (llama-7b FSDP, v5e:4x4, attention=flash)
  emits NO spmd_partitioner warnings at all and its HLO contains only
  per-layer ZeRO-3 weight gathers — verified by the tpu_aot test below.

These tests are the "done" evidence for VERDICT round-2 item 1.
"""

from __future__ import annotations

import re

import jax
import jax.numpy as jnp
import pytest

import tpu_engine.models.transformer as tfm
from tpu_engine.mesh_runtime import MeshConfig, MeshRuntime
from tpu_engine.sharding import ShardingStage, TPUTrainConfig
from tpu_engine.train import build_train_program

pytestmark = pytest.mark.slow  # compile-heavy module


def _all_gather_shapes(hlo_text: str) -> list[tuple[str, tuple[int, ...]]]:
    """(dtype, shape) of every all-gather result in a compiled HLO text.

    Handles scalar results (``= bf16[...] all-gather(...)``) AND
    tuple-shaped results from XLA's all-gather combiner / variadic async
    all-gather-start — ``= (bf16[...], f32[...]) all-gather(...)`` — so a
    full-remat gather hidden inside a combined op can't slip past the
    assertions. async-start tuples also carry the *operand* shapes; that
    only over-counts (operands are per-shard, strictly smaller).
    """
    out = []
    for line in hlo_text.splitlines():
        m = re.search(r"= (.*?) all-gather", line)
        if m is None:
            continue
        for dt, dims in re.findall(r"([a-z0-9]+)\[([\d,]*)\]", m.group(1)):
            out.append((dt, tuple(int(d) for d in dims.split(",") if d)))
    return out


@pytest.fixture
def tiny3():
    """A 3-layer tiny model: breaks the L == B_local == accum == 2 shape
    collisions of gpt-tiny so stacked-weight shapes are unambiguous."""
    name = "gpt-tiny3"
    tfm.MODEL_CONFIGS[name] = tfm.MODEL_CONFIGS["gpt-tiny"].with_(
        name=name, n_layers=3
    )
    yield name
    del tfm.MODEL_CONFIGS[name]


def test_flash_multichip_no_full_remat_in_lowered_program(tiny3):
    """The involuntary-full-remat warning is estimator noise: assert the
    *chosen* partitioned program never all-gathers a full stacked-weight
    tensor (the lowering GSPMD falls back to when a reshard really is
    infeasible — "replicate the tensor and then partition it")."""
    cfg = TPUTrainConfig(
        model_name=tiny3,
        sharding_stage=ShardingStage.FULL_PARTITIONING,
        mesh=MeshConfig(data=2, fsdp=2, model=2),
        micro_batch_size=2,
        gradient_accumulation_steps=2,
        seq_len=128,
        activation_checkpointing=True,
        attention_impl="flash",
    )
    runtime = MeshRuntime(cfg.mesh, devices=jax.devices()[:8])
    prog = build_train_program(cfg, runtime=runtime)
    state_shape = jax.eval_shape(prog.init, jax.random.PRNGKey(0))
    batch = jax.ShapeDtypeStruct(prog.global_batch_shape(), jnp.int32)
    txt = prog.step.lower(state_shape, batch).compile().as_text()

    mc = tfm.MODEL_CONFIGS[tiny3]
    L, D, F = mc.n_layers, mc.d_model, mc.d_ff
    # Full-remat materialises a complete [L, ...] stack (or a 4-padded
    # shard of it) on every device; legitimate ZeRO-3 gathers produce
    # single-layer [1, ...] slices only.
    full_stacks = {
        (L, F, D), (L, D, F), (L, D, D),          # mlp down/up+gate, attn proj
        (4, F, D), (4, D, F), (4, D, D),          # padded-shard variants
    }
    bad = [s for s in _all_gather_shapes(txt) if s[1] in full_stacks]
    assert not bad, f"full stacked-weight all-gathers in partitioned HLO: {bad}"


@pytest.mark.tpu_aot
def test_7b_flash_v5e16_aot_clean(capfd):
    """AOT-compile the 7B FSDP train step with the Pallas flash kernel for a
    described v5e:4x4 (16-chip) topology and assert (a) the SPMD partitioner
    emits no involuntary-full-rematerialization warning at all on the real
    compile target, and (b) no all-gather in the HLO materialises more than
    one layer's largest weight (i.e. collectives are per-layer ZeRO-3
    gathers + TP reductions, nothing activation- or stack-sized)."""
    from jax.experimental import topologies

    try:
        topo = topologies.get_topology_desc("v5e:4x4", platform="tpu")
    except Exception as e:  # no libtpu in this environment
        pytest.skip(f"TPU AOT topology unavailable: {e}")
    cfg = TPUTrainConfig(
        model_name="llama-7b",
        sharding_stage=ShardingStage.FULL_PARTITIONING,
        mesh=MeshConfig(data=1, fsdp=16),
        micro_batch_size=1,
        gradient_accumulation_steps=1,
        seq_len=4096,
        attention_impl="flash",
    )
    runtime = MeshRuntime(cfg.mesh, devices=topo.devices)
    prog = build_train_program(cfg, runtime=runtime)
    state_shape = jax.eval_shape(prog.init, jax.random.PRNGKey(0))
    batch = jax.ShapeDtypeStruct(prog.global_batch_shape(), jnp.int32)
    capfd.readouterr()  # drop anything emitted before the compile
    compiled = prog.step.lower(state_shape, batch).compile()
    err = capfd.readouterr().err
    assert "Involuntary full rematerialization" not in err, err[-2000:]

    txt = compiled.as_text()
    mc = tfm.MODEL_CONFIGS["llama-7b"]
    itemsize = {"bf16": 2, "f16": 2, "f32": 4, "s32": 4, "u32": 4,
                "pred": 1, "s8": 1, "u8": 1, "f64": 8, "s64": 8}
    # Largest legitimate single-weight gather: the LM head / vocab embedding
    # (one "unit" in ZeRO-3 terms, gathered whole for the logits einsum).
    largest_layer_weight = 2 * mc.d_model * max(mc.d_ff, mc.vocab_size)
    oversized = []
    for dt, dims in _all_gather_shapes(txt):
        n = itemsize.get(dt, 4)
        for d in dims:
            n *= d
        if n > 1.25 * largest_layer_weight:
            oversized.append((dt, dims, n))
    assert not oversized, f"oversized all-gathers: {oversized}"
    # The Pallas kernels made it into the multi-chip program (the flash
    # path really is the kernel under shard_map, not the XLA fallback).
    assert "tpu_custom_call" in txt
