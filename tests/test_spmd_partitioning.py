"""Multi-chip SPMD partitioning quality: no involuntary full remat.

Round-2 VERDICT flagged an XLA ``spmd_partitioner.cc:652`` "involuntary full
rematerialization" warning in the 8-device dry-run's flash-attention config
(MULTICHIP_r02 tail). Investigation (round 3) established:

- The warning is emitted by GSPMD's dot-partitioning *strategy estimator*
  (``fake_parameter`` probes in ``dot_handler``), while costing a candidate
  layout for the o-projection weight-gradient dot ``dW_o = attn^T @ dx``:
  ZeRO stage >= 2 wants ``dW_o`` fsdp-sharded, but fsdp is also a
  batch-group axis of that contraction, so one *candidate* requires
  resharding ``dx`` [B_local, S, D] from batch-sharded to D-over-fsdp —
  exactly the warned pair (source ``devices=[4,1,1,2]``, target
  ``devices=[1,1,2,4]T(1,0,2)`` = P(None, None, "fsdp") in fsdp-major
  order, a spec that exists nowhere in user code).
- The chosen final program does NOT contain the inefficient reshard: the
  partitioned HLO has no all-gather materialising a full stacked-weight
  (or padded-shard) tensor — verified here, mechanically, so a regression
  re-introducing a real full-remat fails the suite.
- The real-TPU AOT compile (llama-7b FSDP, v5e:4x4, attention=flash)
  emits NO spmd_partitioner warnings at all and its HLO contains only
  per-layer ZeRO-3 weight gathers — verified by the tpu_aot test below.

These tests are the "done" evidence for VERDICT round-2 item 1.
"""

from __future__ import annotations

import re

import jax
import jax.numpy as jnp
import pytest

import tpu_engine.models.transformer as tfm

pytestmark = pytest.mark.slow  # compile-heavy module


def _all_gather_shapes(
    hlo_text: str,
) -> list[tuple[str, tuple[int, ...], int]]:
    """(dtype, shape, gather_dim) of every all-gather in a compiled HLO.

    Handles scalar results (``= bf16[...] all-gather(...)``) AND
    tuple-shaped results from XLA's all-gather combiner / variadic async
    all-gather-start — ``= (bf16[...], f32[...]) all-gather(...)`` — so a
    full-remat gather hidden inside a combined op can't slip past the
    assertions. async-start tuples also carry the *operand* shapes; that
    only over-counts (operands are per-shard, strictly smaller).
    """
    out = []
    for line in hlo_text.splitlines():
        m = re.search(r"= (.*?) all-gather", line)
        if m is None:
            continue
        gd = re.search(r"dimensions=\{(\d+)\}", line)
        gather_dim = int(gd.group(1)) if gd else -1
        for dt, dims in re.findall(r"([a-z0-9]+)\[([\d,]*)\]", m.group(1)):
            out.append((dt, tuple(int(d) for d in dims.split(",") if d),
                        gather_dim))
    return out


@pytest.fixture
def tiny3():
    """A 3-layer tiny model: breaks the L == B_local == accum == 2 shape
    collisions of gpt-tiny so stacked-weight shapes are unambiguous."""
    name = "gpt-tiny3"
    tfm.MODEL_CONFIGS[name] = tfm.MODEL_CONFIGS["gpt-tiny"].with_(
        name=name, n_layers=3
    )
    yield name
    del tfm.MODEL_CONFIGS[name]


def test_flash_multichip_no_full_remat_in_lowered_program(tiny3):
    """The involuntary-full-remat warning is estimator noise: assert the
    *chosen* partitioned program never all-gathers a full stacked-weight
    tensor (the lowering GSPMD falls back to when a reshard really is
    infeasible — "replicate the tensor and then partition it")."""
    from benchmarks.aot import build_program

    prog = build_program(
        tiny3, dict(data=2, fsdp=2, model=2), micro=2, accum=2, seq=128,
        overrides={"activation_checkpointing": True, "attention_impl": "flash"},
        devices=jax.devices()[:8],
    )
    state_shape = jax.eval_shape(prog.init, jax.random.PRNGKey(0))
    batch = jax.ShapeDtypeStruct(prog.global_batch_shape(), jnp.int32)
    txt = prog.step.lower(state_shape, batch).compile().as_text()

    mc = tfm.MODEL_CONFIGS[tiny3]
    L, D, F = mc.n_layers, mc.d_model, mc.d_ff
    B, S = 8, 128  # global micro batch (2 × data2 × fsdp2), seq_len
    # Full-remat materialises a complete [L, ...] stack (or a 4-padded
    # shard of it) on every device; legitimate ZeRO-3 gathers produce
    # single-layer [1, ...] slices only. The warned estimator probe was the
    # *activation cotangent* dx [B_local, S, D]: its full-remat lowering
    # would all-gather an [*, S, D] activation over the BATCH dim
    # (un-batch-sharding it) — forbidden at any size. Gathers of the
    # model/feature dim (e.g. the embedding lookup re-assembling a
    # TP-sharded D) are legitimate and stay allowed.
    full_stacks = {
        (L, F, D), (L, D, F), (L, D, D),          # mlp down/up+gate, attn proj
        (4, F, D), (4, D, F), (4, D, D),          # padded-shard variants
    }
    acts = {(b, S, D) for b in range(1, B + 1)}
    bad = [s for s in _all_gather_shapes(txt)
           if s[1] in full_stacks or (s[1] in acts and s[2] == 0)]
    assert not bad, f"full-remat all-gathers in partitioned HLO: {bad}"


@pytest.mark.tpu_aot
def test_7b_flash_v5e16_aot_clean(capfd):
    """AOT-compile the 7B FSDP train step with the Pallas flash kernel for a
    described v5e:4x4 (16-chip) topology and assert (a) the SPMD partitioner
    emits no involuntary-full-rematerialization warning at all on the real
    compile target, and (b) no all-gather in the HLO materialises more than
    one layer's largest weight (i.e. collectives are per-layer ZeRO-3
    gathers + TP reductions, nothing activation- or stack-sized)."""
    from benchmarks.aot import TopologyUnavailable, aot_lowered

    seq = 4096
    try:
        lowered = aot_lowered(
            "llama-7b", "v5e:4x4", dict(data=1, fsdp=16), seq=seq,
            overrides={"attention_impl": "flash"},
        )
    except TopologyUnavailable as e:  # only missing libtpu skips
        pytest.skip(f"TPU AOT topology unavailable: {e}")
    capfd.readouterr()  # drop anything emitted before the compile
    compiled = lowered.compile()
    err = capfd.readouterr().err
    assert "Involuntary full rematerialization" not in err, err[-2000:]

    txt = compiled.as_text()
    mc = tfm.MODEL_CONFIGS["llama-7b"]
    itemsize = {"bf16": 2, "f16": 2, "f32": 4, "s32": 4, "u32": 4,
                "pred": 1, "s8": 1, "u8": 1, "f64": 8, "s64": 8}
    # Largest legitimate single-weight gather: the LM head / vocab embedding
    # (one "unit" in ZeRO-3 terms, gathered whole for the logits einsum).
    largest_layer_weight = 2 * mc.d_model * max(mc.d_ff, mc.vocab_size)
    # Global batch = micro(1) × data(1) × fsdp(16); an activation-shaped
    # gather ([b, S, D]) over the BATCH dim indicates the full-remat
    # lowering of the estimator-probed cotangent reshard — the clean
    # program has none at any size.
    global_batch = 1 * 1 * 16
    act_shapes = {(b, seq, mc.d_model) for b in range(2, global_batch + 1)}
    oversized = []
    for dt, dims, gather_dim in _all_gather_shapes(txt):
        n = itemsize.get(dt, 4)
        for d in dims:
            n *= d
        if n > 1.25 * largest_layer_weight or (
            dims in act_shapes and gather_dim == 0
        ):
            oversized.append((dt, dims, n))
    assert not oversized, f"oversized/activation all-gathers: {oversized}"
    # The Pallas kernels made it into the multi-chip program (the flash
    # path really is the kernel under shard_map, not the XLA fallback).
    assert "tpu_custom_call" in txt
