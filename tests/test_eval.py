"""Held-out evaluation: eval_step semantics + supervised-job integration."""

import jax
import numpy as np
import pytest

from tpu_engine import TPULauncher, TPUTrainConfig
from tpu_engine.mesh_runtime import MeshConfig
from tpu_engine.sharding import Precision, ShardingStage
from tpu_engine.train import build_train_program


def _cfg(**kw):
    base = dict(
        model_name="gpt-tiny",
        sharding_stage=ShardingStage.FULL_PARTITIONING,
        mesh=MeshConfig(data=2, fsdp=4),
        micro_batch_size=1,
        gradient_accumulation_steps=2,
        seq_len=32,
        precision=Precision.FP32,
        learning_rate=1e-2,
        warmup_steps=2,
        total_steps=100,
        activation_checkpointing=False,
    )
    base.update(kw)
    return TPUTrainConfig(**base)


def test_eval_step_matches_train_loss_dense():
    # Dense model: eval loss on a batch == the loss train_step reports for it.
    prog = build_train_program(_cfg())
    state = prog.init(jax.random.PRNGKey(0))
    batch = prog.synthetic_batch(0)
    eval_loss = float(jax.device_get(prog.eval_step(state, batch)))
    _, metrics = prog.step(state, batch)
    np.testing.assert_allclose(eval_loss, float(metrics["loss"]), rtol=1e-5)


def test_eval_step_excludes_moe_aux():
    # MoE: train loss carries the router aux term, eval loss must not.
    prog = build_train_program(_cfg(model_name="moe-tiny"))
    state = prog.init(jax.random.PRNGKey(0))
    batch = prog.synthetic_batch(0)
    eval_loss = float(jax.device_get(prog.eval_step(state, batch)))
    _, metrics = prog.step(state, batch)
    assert eval_loss < float(metrics["loss"])


def test_eval_step_does_not_mutate_state():
    prog = build_train_program(_cfg())
    state = prog.init(jax.random.PRNGKey(0))
    before = jax.device_get(state["params"]["embed"]["embedding"])
    prog.eval_step(state, prog.synthetic_batch(0))
    np.testing.assert_array_equal(
        before, jax.device_get(state["params"]["embed"]["embedding"])
    )
    assert int(jax.device_get(state["step"])) == 0


def test_supervised_job_records_eval_history():
    cfg = _cfg(eval_interval_steps=3, eval_batches=2, total_steps=7)
    launcher = TPULauncher()
    res = launcher.launch(cfg, dry_run=False, block=True)
    job = launcher.get_job(res.job_id)
    d = job.describe()
    assert d["status"] == "completed", d
    assert d["eval"] is not None
    assert d["eval"]["source"] == "synthetic"
    steps = [h["step"] for h in d["eval"]["history"]]
    assert steps == [3, 6]
    assert d["eval"]["latest_step"] == 6
    assert 0 < d["eval"]["latest_loss"] < 20
    assert d["eval"]["latest_perplexity"] > 1


def test_eval_data_fn_is_deterministic(tmp_path):
    # Same call index → identical batch, across repeated eval rounds.
    import numpy as np

    from tpu_engine.data import TokenFileDataset, make_eval_data_fn, write_token_file

    path = str(tmp_path / "eval.bin")
    rng = np.random.default_rng(0)
    write_token_file(rng.integers(0, 512, 20_000).astype(np.uint16), path)
    prog = build_train_program(_cfg())
    ds = TokenFileDataset(path, seq_len=32)
    fn = make_eval_data_fn(prog, ds)
    a0, b0 = jax.device_get(fn(0)), jax.device_get(fn(1))
    a1, b1 = jax.device_get(fn(0)), jax.device_get(fn(1))
    np.testing.assert_array_equal(a0, a1)
    np.testing.assert_array_equal(b0, b1)
    assert not np.array_equal(a0, b0)  # distinct blocks of the file
    ds.close()


def test_supervised_job_evals_from_file(tmp_path):
    import numpy as np

    from tpu_engine.data import write_token_file

    train_path = str(tmp_path / "train.bin")
    eval_path = str(tmp_path / "eval.bin")
    write_token_file((np.arange(30_000) % 512).astype(np.uint16), train_path)
    write_token_file(((np.arange(20_000) * 7) % 512).astype(np.uint16), eval_path)
    cfg = _cfg(
        dataset_path=train_path,
        eval_dataset_path=eval_path,
        eval_interval_steps=2,
        eval_batches=2,
        total_steps=4,
    )
    launcher = TPULauncher()
    res = launcher.launch(cfg, dry_run=False, block=True)
    d = launcher.get_job(res.job_id).describe()
    assert d["status"] == "completed", d
    assert d["eval"]["source"] == "file"
    assert [h["step"] for h in d["eval"]["history"]] == [2, 4]


def test_generate_sample_from_running_job():
    # Sampling mid-training must survive the train step's buffer donation
    # (the dispatch happens under the state lock).
    cfg = _cfg(total_steps=200)
    launcher = TPULauncher()
    res = launcher.launch(cfg, dry_run=False, block=False)
    job = launcher.get_job(res.job_id)
    import time

    deadline = time.time() + 120
    while job.status.value not in ("running", "completed") and time.time() < deadline:
        time.sleep(0.2)
    sampled = 0
    while job.status.value == "running" and sampled < 3:
        out = job.generate_sample([[1, 2, 3]], max_new_tokens=4, temperature=0.8, seed=sampled)
        assert len(out[0]) == 7
        sampled += 1
    job.join(timeout=120)
    assert job.status.value == "completed", job.describe()
    assert sampled >= 1  # at least one sample landed while training ran


def test_metrics_jsonl_log(tmp_path):
    import json

    path = str(tmp_path / "metrics.jsonl")
    cfg = _cfg(total_steps=4, log_every_steps=2, eval_interval_steps=2,
               eval_batches=1, metrics_log_path=path)
    launcher = TPULauncher()
    res = launcher.launch(cfg, dry_run=False, block=True)
    assert launcher.get_job(res.job_id).describe()["status"] == "completed"
    lines = [json.loads(l) for l in open(path)]
    train = [l for l in lines if l["kind"] == "train"]
    evals = [l for l in lines if l["kind"] == "eval"]
    assert [l["step"] for l in train] == [2, 4]
    assert [l["step"] for l in evals] == [2, 4]
    assert all("loss" in l and "ts" in l and l["job_id"] == res.job_id for l in lines)
    assert all("perplexity" in l for l in evals)
    assert all("tokens_per_sec" in l and "grad_norm" in l for l in train)


def test_metrics_log_bad_path_does_not_fail_job(tmp_path):
    cfg = _cfg(total_steps=2, metrics_log_path=str(tmp_path / "no" / "such" / "dir" / "m.jsonl"))
    launcher = TPULauncher()
    res = launcher.launch(cfg, dry_run=False, block=True)
    assert launcher.get_job(res.job_id).describe()["status"] == "completed"


def test_dense_export_while_running_survives_donation():
    # Exporting a RUNNING full-parameter job must not race the train step's
    # buffer donation (params are host-copied under the state lock).
    import tempfile
    import time

    cfg = _cfg(total_steps=150)
    launcher = TPULauncher()
    res = launcher.launch(cfg, dry_run=False, block=False)
    job = launcher.get_job(res.job_id)
    deadline = time.time() + 120
    while job.status.value not in ("running", "completed") and time.time() < deadline:
        time.sleep(0.2)
    exported = 0
    while job.status.value == "running" and exported < 2:
        path, step = job.export_hf_checkpoint(tempfile.mkdtemp() + "/e")
        assert 0 <= step <= 150
        exported += 1
    job.join(timeout=120)
    assert job.status.value == "completed", job.describe()
    assert exported >= 1


def test_run_eval_now():
    cfg = _cfg(total_steps=200, eval_interval_steps=1000, eval_batches=2)
    launcher = TPULauncher()
    res = launcher.launch(cfg, dry_run=False, block=False)
    job = launcher.get_job(res.job_id)
    import time

    deadline = time.time() + 120
    while job.status.value not in ("running", "completed") and time.time() < deadline:
        time.sleep(0.2)
    out = job.run_eval_now()  # on demand, far before the interval fires
    assert 0 < out["loss"] < 20 and out["perplexity"] > 1
    assert job.eval_history and job.eval_history[-1][1] == out["loss"]
    job.stop()
    job.join(timeout=120)
    # Without an eval source, on-demand eval is a clear error.
    cfg2 = _cfg(total_steps=2)
    res2 = launcher.launch(cfg2, dry_run=False, block=True)
    import pytest

    with pytest.raises(RuntimeError, match="eval data source"):
        launcher.get_job(res2.job_id).run_eval_now()
    # Before the train loop starts, the error says retry — not a config nag.
    from tpu_engine.supervisor import TrainingJob

    unstarted = TrainingJob(job_id="x", config=_cfg(eval_interval_steps=5))
    with pytest.raises(RuntimeError, match="retry once it is running"):
        unstarted.run_eval_now()


# Compile-heavy module: excluded from the fast core run (pytest -m "not slow").
pytestmark = pytest.mark.slow
