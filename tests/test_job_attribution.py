"""Per-chip job attribution in the fleet view (VERDICT r2 item 4).

The reference fleet reports, per GPU, the live process table
(``gpu_manager.py:27-33``, populated ``:174-184``) so an operator can see
what occupies a device. TPU runtimes expose no foreign-process table, so
the analogue is the control plane's OWN supervised jobs: each supervisor
claims its mesh's local chip ids while running
(``telemetry.register_job_devices``) and the fleet snapshot attributes
them per device.
"""

from __future__ import annotations

import time

import jax
import pytest

from tpu_engine import telemetry
from tpu_engine.launcher import TPULauncher
from tpu_engine.mesh_runtime import MeshConfig
from tpu_engine.sharding import Precision, TPUTrainConfig
from tpu_engine.supervisor import JobStatus
from tpu_engine.tpu_manager import TPUManager


@pytest.fixture(autouse=True)
def _clean_claims():
    yield
    # Never leak claims across tests.
    for did_jobs in telemetry.job_attribution().values():
        for ref in did_jobs:
            telemetry.unregister_job_devices(ref["job_id"])


def test_registry_attributes_exactly_the_claimed_chips():
    telemetry.register_job_devices("job-a", [0, 2], 0, lambda: "running")
    telemetry.register_job_devices("job-b", [2, 3], 1, lambda: "compiling")
    att = telemetry.job_attribution()
    assert {r["job_id"] for r in att[0]} == {"job-a"}
    assert {r["job_id"] for r in att[2]} == {"job-a", "job-b"}
    assert att[3] == [{"job_id": "job-b", "status": "compiling", "process_index": 1}]
    assert 1 not in att
    telemetry.unregister_job_devices("job-a")
    assert "job-a" not in {r["job_id"] for refs in telemetry.job_attribution().values() for r in refs}


def test_status_fn_failure_reports_unknown():
    def boom():
        raise RuntimeError("job object gone")

    telemetry.register_job_devices("job-x", [1], 0, boom)
    assert telemetry.job_attribution()[1][0]["status"] == "unknown"


def test_fleet_snapshot_attributes_running_job_to_its_mesh_chips():
    """Launch a real (tiny) supervised job on the 8-device CPU mesh and
    assert the LIVE fleet snapshot pins it to exactly its mesh's chips.

    (A mesh must cover every visible device in one process, so here "its
    chips" is the full host; subset exactness — a job claiming 4 of 8 —
    is pinned by ``test_registry_attributes_exactly_the_claimed_chips``,
    and per-process halves by the two-process distributed smoke.)"""
    launcher = TPULauncher()
    cfg = TPUTrainConfig(
        model_name="gpt-tiny", mesh=MeshConfig(data=2, fsdp=4),
        micro_batch_size=1, seq_len=32, precision=Precision.FP32,
        total_steps=5000, warmup_steps=2, activation_checkpointing=False,
    )
    res = launcher.launch(cfg, dry_run=False, block=False)
    assert res.status == "launched"
    job = launcher.get_job(res.job_id)
    manager = TPUManager()
    try:
        deadline = time.time() + 120
        held = []
        while time.time() < deadline:
            fleet = manager.get_fleet_status()
            held = [
                d for d in fleet.devices
                if any(r.job_id == res.job_id for r in d.jobs)
            ]
            if held:
                break
            assert job.status not in (JobStatus.FAILED, JobStatus.COMPLETED), (
                job.status, job.error,
            )
            time.sleep(0.2)
        assert held, "job never appeared in the fleet attribution"
        # Exactly the chips of its mesh, nothing else.
        mesh_ids = {
            int(d.id) for d in job.program.runtime.mesh.devices.flat
        }
        assert {d.index for d in held} == mesh_ids
        ref = next(r for r in held[0].jobs if r.job_id == res.job_id)
        assert ref.status in ("running", "compiling")
        assert ref.process_index == jax.process_index()
    finally:
        launcher.stop_job(res.job_id)
        job.join()

    # Terminal job releases its chips.
    fleet = manager.get_fleet_status()
    assert not any(
        r.job_id == res.job_id for d in fleet.devices for r in d.jobs
    )
