"""Input pipeline: native reader, Python fallback parity, sharded data_fn,
and end-to-end training from a token file."""

import numpy as np
import pytest

from tpu_engine import native
from tpu_engine.data import (
    SyntheticDataset,
    TokenFileDataset,
    _PyTokenReader,
    make_data_fn,
    write_token_file,
)


@pytest.fixture(scope="module")
def token_file(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("data") / "toks.bin")
    tokens = (np.arange(50_000) % 512).astype(np.uint16)
    return write_token_file(tokens, path)


def test_native_builds():
    assert native.ensure_built() is not None, native.build_error()
    assert native.available()


def test_native_host_stats():
    stats = native.host_stats()
    assert stats is not None
    assert stats["mem_total_gb"] > 0
    assert stats["n_cpus"] >= 1


def test_reader_gather(token_file):
    ds = TokenFileDataset(token_file, seq_len=64)
    assert ds.num_tokens == 50_000
    assert ds.num_sequences == 50_000 // 64
    b = ds.read_batch(np.array([0, 2]))
    assert b.dtype == np.int32 and b.shape == (2, 64)
    assert (b[0] == np.arange(64) % 512).all()
    assert (b[1] == (np.arange(128, 192) % 512)).all()
    with pytest.raises(Exception):
        ds.read_batch(np.array([ds.num_sequences]))  # out of range
    ds.close()


def test_native_and_python_streams_identical(token_file):
    """The NumPy fallback must replay the native reader's exact shuffle."""
    if not native.available():
        pytest.skip("no native toolchain")
    nat = TokenFileDataset(token_file, seq_len=64, prefer_native=True)
    py = TokenFileDataset(token_file, seq_len=64, prefer_native=False)
    assert nat.native and not py.native
    nat.start(batch=8, seed=123)
    py.start(batch=8, seed=123)
    for _ in range(200):  # crosses an epoch boundary (781 seqs / 8)
        assert (nat.next_batch() == py.next_batch()).all()
    assert nat.epoch == py.epoch == 2
    nat.close()
    py.close()


def test_stream_deterministic_across_restart(token_file):
    a = TokenFileDataset(token_file, seq_len=64)
    a.start(batch=4, seed=7)
    first = [a.next_batch() for _ in range(10)]
    a.close()
    b = TokenFileDataset(token_file, seq_len=64)
    b.start(batch=4, seed=7)
    for want in first:
        assert (b.next_batch() == want).all()
    b.close()


def test_synthetic_dataset():
    ds = SyntheticDataset(vocab_size=512, seq_len=32)
    ds.start(batch=4, seed=1)
    a = ds.next_batch()
    b = ds.next_batch()
    assert a.shape == (4, 32) and (a < 512).all()
    assert not (a == b).all()


def test_make_data_fn_shapes_and_sharding(token_file):
    from tpu_engine.mesh_runtime import MeshConfig
    from tpu_engine.sharding import ShardingStage, TPUTrainConfig
    from tpu_engine.train import build_train_program

    cfg = TPUTrainConfig(
        model_name="gpt-tiny",
        sharding_stage=ShardingStage.FULL_PARTITIONING,
        mesh=MeshConfig(data=2, fsdp=4),
        micro_batch_size=1,
        gradient_accumulation_steps=2,
        seq_len=64,
        precision="fp32",
        activation_checkpointing=False,
    )
    prog = build_train_program(cfg)
    ds = TokenFileDataset(token_file, seq_len=64)
    fn = make_data_fn(prog, ds, seed=0)
    batch = fn(0)
    assert batch.shape == prog.global_batch_shape() == (2, 8, 64)
    assert batch.sharding == prog.batch_sharding
    # And it steps.
    state = prog.init(__import__("jax").random.PRNGKey(0))
    _, metrics = prog.step(state, batch)
    assert float(metrics["loss"]) > 0
    ds.close()


def test_seq_len_mismatch_rejected(token_file):
    from tpu_engine.sharding import TPUTrainConfig
    from tpu_engine.train import build_train_program

    cfg = TPUTrainConfig(model_name="gpt-tiny", seq_len=32, precision="fp32",
                         activation_checkpointing=False)
    prog = build_train_program(cfg)
    ds = TokenFileDataset(token_file, seq_len=64)
    with pytest.raises(ValueError, match="seq_len"):
        make_data_fn(prog, ds)
    ds.close()


def test_supervised_job_trains_from_token_file(token_file):
    """End-to-end: launcher -> supervisor -> dataset file -> completed job.

    The file's tokens are a repeating 0..511 ramp, so even 5 tiny steps
    must move the loss below ln(512) (synthetic-random stays at ~ln(512))."""
    from tpu_engine import TPULauncher, TPUTrainConfig
    from tpu_engine.mesh_runtime import MeshConfig

    cfg = TPUTrainConfig(
        model_name="gpt-tiny",
        mesh=MeshConfig(data=2, fsdp=4),
        micro_batch_size=2,
        seq_len=64,
        precision="fp32",
        total_steps=5,
        warmup_steps=1,
        learning_rate=3e-3,
        activation_checkpointing=False,
        dataset_path=token_file,
    )
    launcher = TPULauncher()
    res = launcher.launch(cfg, dry_run=False, block=True)
    job = launcher.get_job(res.job_id)
    d = job.describe()
    assert d["status"] == "completed", d["error"]
    assert d["monitor"]["current_loss"] < np.log(512)


def test_tokenize_text_file_roundtrip(tmp_path):
    """Stream-tokenize text into the binary format and train from it."""
    tokenizers = pytest.importorskip("tokenizers")

    # Train a tiny BPE locally (no network): corpus of repeated words.
    text = tmp_path / "corpus.txt"
    lines = ["the quick brown fox jumps over the lazy dog"] * 200 + [
        "pack my box with five dozen liquor jugs"
    ] * 200
    text.write_text("\n".join(lines))
    tok = tokenizers.Tokenizer(tokenizers.models.BPE(unk_token="[UNK]"))
    tok.pre_tokenizer = tokenizers.pre_tokenizers.Whitespace()
    tok.train([str(text)], tokenizers.trainers.BpeTrainer(
        vocab_size=200, special_tokens=["[UNK]"]))

    from tpu_engine.data import TokenFileDataset, tokenize_text_file

    out = str(tmp_path / "toks.bin")
    n = tokenize_text_file(str(text), out, tok)
    assert n > 0
    ds = TokenFileDataset(out, seq_len=16)
    assert ds.num_tokens == n
    batch = ds.read_batch(np.arange(4))
    assert batch.shape == (4, 16)
    assert batch.dtype == np.int32  # reader returns int32 regardless of storage
    # The ids decode back to real text.
    decoded = tok.decode([int(t) for t in batch[0]])
    assert any(w in decoded for w in ("quick", "fox", "box", "jugs", "the"))
    ds.close()


def test_tokenize_rejects_overflow(tmp_path):
    from tpu_engine.data import tokenize_text_file

    class FakeTok:
        eos_token_id = None

        def encode(self, line):
            return [70_000]  # > uint16

    text = tmp_path / "t.txt"
    text.write_text("hello\n")
    with pytest.raises(ValueError, match="int32"):
        tokenize_text_file(str(text), str(tmp_path / "o.bin"), FakeTok())


def test_pack_sft_examples():
    from tpu_engine.data import pack_sft_examples

    rows = pack_sft_examples([([5, 6], [7, 8, 9])], seq_len=8)
    assert rows.dtype == np.int32 and rows.shape == (1, 8)
    # prompt stored as -(t+1), completion as-is, padding as -1
    assert rows[0].tolist() == [-6, -7, 7, 8, 9, -1, -1, -1]
    with pytest.raises(ValueError, match="exceeds seq_len"):
        pack_sft_examples([([1] * 6, [2] * 6)], seq_len=8)
    with pytest.raises(ValueError, match=">= 0"):
        pack_sft_examples([([-1], [2])], seq_len=8)


def test_write_token_file_rejects_out_of_range(tmp_path):
    from tpu_engine.data import pack_sft_examples, write_token_file

    rows = pack_sft_examples([([5], [7, 8])], seq_len=4)
    with pytest.raises(ValueError, match="int32"):
        write_token_file(rows.reshape(-1), str(tmp_path / "bad.bin"))  # uint16
    write_token_file(rows.reshape(-1), str(tmp_path / "ok.bin"), dtype="int32")
    with pytest.raises(ValueError, match="do not fit"):
        write_token_file(np.array([70000]), str(tmp_path / "big.bin"))


def test_row_structured_seq_len_contract(tmp_path):
    """Packed (2-D) files record their row length; opening at any other
    seq_len fails loudly instead of silently misaligning SFT masks
    (round-1 advisor finding). Rewriting the path with a 1-D stream
    clears the sidecar."""
    from tpu_engine.data import TokenFileDataset, pack_sft_examples, write_token_file

    rows = pack_sft_examples([([5], [7, 8])] * 4, seq_len=8)
    path = str(tmp_path / "sft.bin")
    write_token_file(rows, path, dtype="int32")
    # Matching seq_len opens fine.
    ds = TokenFileDataset(path, seq_len=8, dtype="int32")
    assert ds.num_sequences == 4
    ds.close()
    # Any other seq_len is a hard error.
    with pytest.raises(ValueError, match="row_len=8"):
        TokenFileDataset(path, seq_len=16, dtype="int32")
    # A later 1-D rewrite clears the sidecar: any seq_len is valid again.
    write_token_file(np.arange(64, dtype=np.int32), path, dtype="int32")
    ds2 = TokenFileDataset(path, seq_len=16, dtype="int32")
    assert ds2.num_sequences == 4
    ds2.close()


# -- per-process sharded reads (VERDICT r2 weak #5) --------------------------


class _CountingDataset:
    """TokenFileDataset wrapper counting rows actually read."""

    def __init__(self, ds):
        self._ds = ds
        self.rows_read = 0

    def __getattr__(self, name):
        return getattr(self._ds, name)

    def read_batch(self, indices):
        self.rows_read += len(indices)
        return self._ds.read_batch(indices)


def test_sharded_stream_reads_1_over_p_and_reassembles_global(token_file):
    from tpu_engine.data import _ShardedTokenStream

    accum, gm, seq = 2, 8, 64
    # Unsharded reference stream (what a single host reads).
    ref = TokenFileDataset(token_file, seq_len=seq)
    ref.start(accum * gm, seed=7)
    steps = 96  # > one epoch of 781 sequences: exercises the wrap

    shards = []
    counters = []
    for pi in range(2):
        ds = _CountingDataset(TokenFileDataset(token_file, seq_len=seq))
        counters.append(ds)
        shards.append(_ShardedTokenStream(
            ds, accum, gm, pi * (gm // 2), gm // 2, seed=7, prefetch=False,
        ))

    for step in range(steps):
        full = ref.next_batch().reshape(accum, gm, seq)
        local0 = shards[0].next()
        local1 = shards[1].next()
        # The two process blocks tile the exact global batch.
        assert (np.concatenate([local0, local1], axis=1) == full).all(), step

    # Per-process read volume is exactly half the global row count.
    total_rows = steps * accum * gm
    for c in counters:
        assert c.rows_read == total_rows // 2
    ref.close()


def test_sharded_stream_prefetch_matches_sync(token_file):
    from tpu_engine.data import _ShardedTokenStream

    a = _ShardedTokenStream(
        TokenFileDataset(token_file, seq_len=64), 1, 4, 0, 2, seed=3,
        prefetch=False,
    )
    b = _ShardedTokenStream(
        TokenFileDataset(token_file, seq_len=64), 1, 4, 0, 2, seed=3,
        prefetch=True,
    )
    for _ in range(20):
        assert (a.next() == b.next()).all()
    b.close()


def test_sharded_stream_dead_producer_reraises_not_deadlocks(token_file):
    """A producer-thread failure must surface on EVERY subsequent next()
    call (round-3 advisor: after the first raise the producer has exited,
    so a retry loop would block forever on the empty queue)."""
    from tpu_engine.data import _ShardedTokenStream

    ds = TokenFileDataset(token_file, seq_len=64)
    s = _ShardedTokenStream(ds, 1, 4, 0, 2, seed=3, prefetch=True)
    assert s.next().shape == (1, 2, 64)

    def boom(indices):
        raise OSError("disk gone")

    ds.read_batch = boom
    with pytest.raises(OSError, match="disk gone"):
        for _ in range(4):  # drain the one prefetched slab, then hit the error
            s.next()
    # Producer is dead now; next() must re-raise immediately, not block.
    with pytest.raises(OSError, match="disk gone"):
        s.next()
    s.close()


# -- non-uniform row assignments (heterogeneous sharding, PR 11) -------------


def test_sharded_stream_non_uniform_split_exactly_once(token_file):
    """A throughput-weighted [5, 3] split still tiles the global batch:
    every row consumed exactly once, read volume proportional to rows."""
    from tpu_engine.data import _ShardedTokenStream

    accum, gm, seq = 2, 8, 64
    ref = TokenFileDataset(token_file, seq_len=seq)
    ref.start(accum * gm, seed=7)
    steps = 96  # > one epoch: exercises the wrap under unequal windows

    rows = [5, 3]
    shards, counters = [], []
    start = 0
    for r in rows:
        ds = _CountingDataset(TokenFileDataset(token_file, seq_len=seq))
        counters.append(ds)
        shards.append(_ShardedTokenStream(
            ds, accum, gm, start, r, seed=7, prefetch=False,
        ))
        start += r

    for step in range(steps):
        full = ref.next_batch().reshape(accum, gm, seq)
        local0 = shards[0].next()
        local1 = shards[1].next()
        assert local0.shape == (accum, 5, seq)
        assert local1.shape == (accum, 3, seq)
        assert (np.concatenate([local0, local1], axis=1) == full).all(), step

    for c, r in zip(counters, rows):
        assert c.rows_read == steps * accum * r
    ref.close()


def test_sharded_stream_reassign_mid_run_keeps_exact_coverage(token_file):
    """reassign() at a step boundary moves the row windows without
    disturbing the deterministic walk: the tiles keep reassembling the
    reference batch exactly, before and after the rebalance."""
    from tpu_engine.data import _ShardedTokenStream

    accum, gm, seq = 2, 8, 64
    ref = TokenFileDataset(token_file, seq_len=seq)
    ref.start(accum * gm, seed=11)
    shards = [
        _ShardedTokenStream(
            TokenFileDataset(token_file, seq_len=seq),
            accum, gm, pi * (gm // 2), gm // 2, seed=11, prefetch=False,
        )
        for pi in range(2)
    ]

    def check(step):
        full = ref.next_batch().reshape(accum, gm, seq)
        got = np.concatenate([s.next() for s in shards], axis=1)
        assert (got == full).all(), step

    for step in range(10):
        check(step)
    # Rebalance 4/4 -> 5/3 at the boundary, on every process.
    shards[0].reassign(0, 5)
    shards[1].reassign(5, 3)
    for step in range(10, 20):
        check(step)
    # And back the other way, 5/3 -> 2/6.
    shards[0].reassign(0, 2)
    shards[1].reassign(2, 6)
    for step in range(20, 30):
        check(step)
    ref.close()


def test_sharded_stream_reassign_rejects_out_of_range_window(token_file):
    from tpu_engine.data import _ShardedTokenStream

    s = _ShardedTokenStream(
        TokenFileDataset(token_file, seq_len=64), 1, 8, 0, 4, seed=3,
        prefetch=False,
    )
    for bad in [(0, 0), (-1, 4), (5, 4), (0, 9)]:
        with pytest.raises(ValueError, match="row window"):
            s.reassign(*bad)
    # The failed reassigns left the stream usable with its old window.
    assert s.next().shape == (1, 4, 64)


def test_sharded_stream_non_uniform_deterministic_under_seed(token_file):
    """Same seed + same windows => bit-identical streams, so every
    process derives the identical global walk regardless of its share."""
    from tpu_engine.data import _ShardedTokenStream

    def run(seed):
        s = _ShardedTokenStream(
            TokenFileDataset(token_file, seq_len=64), 2, 8, 3, 5, seed=seed,
            prefetch=False,
        )
        return [s.next().copy() for _ in range(12)]

    a, b = run(5), run(5)
    for x, y in zip(a, b):
        assert (x == y).all()
    c = run(6)
    assert any((x != y).any() for x, y in zip(a, c))


def test_validate_row_assignment_rejections():
    from tpu_engine.data import validate_row_assignment

    assert validate_row_assignment([5, 3], 8, 2) == [5, 3]
    assert validate_row_assignment((4.0, 4), 8, 2, accum=2) == [4, 4]
    # Wrong sum: would drop or double-read rows of every step's batch.
    with pytest.raises(ValueError, match="expected accum x global micro"):
        validate_row_assignment([5, 4], 8, 2)
    with pytest.raises(ValueError, match="expected accum x global micro"):
        validate_row_assignment([3, 3], 8, 2, accum=2)
    # Wrong length: one entry per process, always.
    with pytest.raises(ValueError, match="2 entries for 3 processes"):
        validate_row_assignment([4, 4], 8, 3)
    # Zero/negative rows: every process must hold at least one row.
    with pytest.raises(ValueError, match=">= 1"):
        validate_row_assignment([8, 0], 8, 2)


def test_make_data_fn_row_assignment_end_to_end(token_file):
    """make_data_fn(row_assignment=...) rejects bad vectors up front and
    exposes a reassign() hook that revalidates before moving the window."""
    from tpu_engine.mesh_runtime import MeshConfig
    from tpu_engine.sharding import ShardingStage, TPUTrainConfig
    from tpu_engine.train import build_train_program

    cfg = TPUTrainConfig(
        model_name="gpt-tiny", sharding_stage=ShardingStage.FULL_PARTITIONING,
        mesh=MeshConfig(data=2, fsdp=4), micro_batch_size=1,
        gradient_accumulation_steps=1, seq_len=64, precision="fp32",
        activation_checkpointing=False,
    )
    prog = build_train_program(cfg)  # global_micro = 8
    ds = TokenFileDataset(token_file, seq_len=64)
    with pytest.raises(ValueError, match="expected accum x global micro"):
        make_data_fn(
            prog, ds, process_count=2, process_index=0, row_assignment=[5, 4],
        )
    with pytest.raises(ValueError, match="entries for"):
        make_data_fn(
            prog, ds, process_count=2, process_index=0, row_assignment=[8],
        )
    # A valid non-uniform vector builds, and reassign() revalidates.
    fn = make_data_fn(
        prog, ds, process_count=2, process_index=0, row_assignment=[5, 3],
    )
    try:
        assert fn.reassign([6, 2]) == [6, 2]
        with pytest.raises(ValueError, match="expected accum x global micro"):
            fn.reassign([6, 3])
    finally:
        fn.close()


def test_make_data_fn_rejects_indivisible_process_count(token_file):
    from tpu_engine.mesh_runtime import MeshConfig
    from tpu_engine.sharding import ShardingStage, TPUTrainConfig
    from tpu_engine.train import build_train_program

    cfg = TPUTrainConfig(
        model_name="gpt-tiny", sharding_stage=ShardingStage.FULL_PARTITIONING,
        mesh=MeshConfig(data=2, fsdp=4), micro_batch_size=1,
        gradient_accumulation_steps=1, seq_len=64, precision="fp32",
        activation_checkpointing=False,
    )
    prog = build_train_program(cfg)  # global_micro = 8
    ds = TokenFileDataset(token_file, seq_len=64)
    with pytest.raises(ValueError, match="not divisible"):
        make_data_fn(prog, ds, process_count=3, process_index=0)
    ds.close()


# ---------------------------------------------------------------------------
# non-uniform assignments vs the sharding's fixed per-process partition
# ---------------------------------------------------------------------------


class _FakeDev:
    def __init__(self, process_index):
        self.process_index = process_index


class _FakeSharding:
    """Stands in for a NamedSharding on a multi-host mesh: the batch axis
    (dim 1) is split into the fixed per-process row blocks GSPMD places,
    optionally subdivided across each process's devices."""

    def __init__(self, rows_per_proc, dev_per_proc=2):
        self.rows_per_proc = rows_per_proc
        self.dev_per_proc = dev_per_proc

    def devices_indices_map(self, global_shape):
        out = {}
        start = 0
        for p, rows in enumerate(self.rows_per_proc):
            per_dev = rows // self.dev_per_proc
            for _ in range(self.dev_per_proc):
                out[_FakeDev(p)] = (
                    slice(None), slice(start, start + per_dev), slice(None),
                )
                start += per_dev
        return out


def test_sharding_batch_partition_reads_per_process_rows():
    from tpu_engine.data import _sharding_batch_partition

    assert _sharding_batch_partition(_FakeSharding([4, 4]), (2, 8, 16)) == [4, 4]
    assert _sharding_batch_partition(_FakeSharding([5, 3], dev_per_proc=1), (2, 8, 16)) == [5, 3]
    # Mock shardings that cannot answer degrade to None, not an exception.
    class _Opaque:
        pass
    assert _sharding_batch_partition(_Opaque(), (2, 8, 16)) is None


def test_check_stream_assignment_feasible_multiprocess(monkeypatch):
    import jax

    from tpu_engine.data import _check_stream_assignment_feasible

    sh = _FakeSharding([4, 4])
    # Single-process runtime: anything validate() accepted is placeable.
    _check_stream_assignment_feasible([5, 3], sh, (1, 8, 64))
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    # Matching the fixed partition is fine; deviating must fail loudly —
    # a stream process cannot feed rows to devices on another host.
    _check_stream_assignment_feasible([4, 4], sh, (1, 8, 64))
    with pytest.raises(ValueError, match="per-process batch partition"):
        _check_stream_assignment_feasible([5, 3], sh, (1, 8, 64))
    # Unknowable partition (mock sharding): defer to jax's own size check.
    class _Opaque:
        pass
    _check_stream_assignment_feasible([5, 3], _Opaque(), (1, 8, 64))


def test_place_global_falls_back_to_full_batch_off_partition(monkeypatch):
    import jax

    from tpu_engine.data import _place_global

    calls = []

    def fake_make(sharding, local, global_shape=None):
        calls.append(local.shape)
        return local

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    monkeypatch.setattr(jax, "make_array_from_process_local_data", fake_make)
    batch = np.zeros((2, 8, 4), dtype=np.int32)

    # No assignment: the implicit equal split slices this process's block.
    _place_global(batch, _FakeSharding([4, 4]))
    assert calls[-1] == (2, 4, 4)
    # Assignment equal to the partition: sliced per-process block, with
    # the offset from the prefix sum (rows 5..8 for process 1 here).
    _place_global(batch, _FakeSharding([5, 3], dev_per_proc=1), [5, 3])
    assert calls[-1] == (2, 3, 4)
    # Assignment off the partition: the per-process block cannot be
    # assembled (jax would raise, or worse silently misplace rows when
    # only the prefix offsets drift) — every process holds the identical
    # synthetic batch, so the full array is placed and each device slices
    # its own shard.
    _place_global(batch, _FakeSharding([4, 4]), [5, 3])
    assert calls[-1] == (2, 8, 4)
    # ...including the silent-misplacement shape: this process's row
    # COUNT matches its partition entry but an earlier process's does
    # not, so the prefix offset drifts and jax's size check would pass.
    _place_global(batch, _FakeSharding([2, 2, 2, 2], dev_per_proc=1), [1, 2, 3, 2])
    assert calls[-1] == (2, 8, 4)


def test_make_data_fn_rejects_partition_incompatible_stream_assignment(
    token_file, monkeypatch
):
    import jax
    from types import SimpleNamespace

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    prog = SimpleNamespace(
        global_batch_shape=lambda: (1, 8, 64),
        batch_sharding=_FakeSharding([4, 4]),
    )
    ds = TokenFileDataset(token_file, seq_len=64)
    try:
        # Construction rejects a vector the sharding cannot place...
        with pytest.raises(ValueError, match="per-process batch partition"):
            make_data_fn(
                prog, ds, process_count=2, process_index=0,
                row_assignment=[5, 3],
            )
        # ...and a live reassign() is re-checked the same way, keeping the
        # old split (the supervisor audits this as hetero_reassign_rejected).
        fn = make_data_fn(
            prog, ds, process_count=2, process_index=0, row_assignment=[4, 4],
        )
        try:
            with pytest.raises(ValueError, match="per-process batch partition"):
                fn.reassign([5, 3])
            assert fn.reassign([4, 4]) == [4, 4]
        finally:
            fn.close()
    finally:
        ds.close()
