"""Mesh runtime: construction, shape resolution, topology introspection."""

import jax
import pytest

from tpu_engine.mesh_runtime import MeshConfig, MeshRuntime, build_mesh, detect_topology


def test_eight_virtual_devices():
    assert jax.device_count() == 8


def test_default_mesh_absorbs_all_devices():
    mesh = build_mesh()
    assert mesh.axis_names == ("data", "fsdp", "pipe", "sequence", "model")
    assert mesh.devices.shape == (8, 1, 1, 1, 1)


@pytest.mark.parametrize(
    "cfg,expected",
    [
        (MeshConfig(fsdp=8), (1, 8, 1, 1, 1)),
        (MeshConfig(fsdp=4), (2, 4, 1, 1, 1)),
        (MeshConfig(model=2, fsdp=2), (2, 2, 1, 1, 2)),
        (MeshConfig(sequence=4), (2, 1, 1, 4, 1)),
        (MeshConfig(pipe=4), (2, 1, 4, 1, 1)),
        (MeshConfig(pipe=2, model=2), (2, 1, 2, 1, 2)),
        (MeshConfig(data=8), (8, 1, 1, 1, 1)),
    ],
)
def test_mesh_shape_resolution(cfg, expected):
    assert cfg.resolved_shape(8) == expected
    assert build_mesh(cfg).devices.shape == expected


def test_mesh_shape_errors():
    with pytest.raises(ValueError):
        MeshConfig(fsdp=3).resolved_shape(8)  # 3 does not divide 8
    with pytest.raises(ValueError):
        MeshConfig(data=4, fsdp=4).resolved_shape(8)  # 16 != 8


def test_runtime_shardings_and_sizes():
    rt = MeshRuntime(MeshConfig(fsdp=4))
    assert rt.axis_sizes == {"data": 2, "fsdp": 4, "pipe": 1, "sequence": 1, "model": 1}
    assert rt.data_parallel_size() == 8
    assert rt.n_devices == 8
    sh = rt.batch_sharding()
    assert sh.spec[0] == ("data", "fsdp")


def test_topology_report_is_real():
    rt = MeshRuntime()
    report = rt.topology_report()
    assert report["num_devices"] == 8
    assert len(report["devices"]) == 8
    assert report["mesh"]["axes"] == {"data": 8, "fsdp": 1, "pipe": 1, "sequence": 1, "model": 1}
    ids = {d["id"] for d in report["devices"]}
    assert len(ids) == 8  # real device ids, not a canned matrix


def test_detect_topology_standalone():
    t = detect_topology()
    assert t["num_devices"] == 8
    assert t["num_processes"] == 1
