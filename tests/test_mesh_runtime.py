"""Mesh runtime: construction, shape resolution, topology introspection."""

import jax
import pytest

from tpu_engine.mesh_runtime import MeshConfig, MeshRuntime, build_mesh, detect_topology


def test_eight_virtual_devices():
    assert jax.device_count() == 8


def test_default_mesh_absorbs_all_devices():
    mesh = build_mesh()
    assert mesh.axis_names == ("data", "fsdp", "pipe", "sequence", "model")
    assert mesh.devices.shape == (8, 1, 1, 1, 1)


@pytest.mark.parametrize(
    "cfg,expected",
    [
        (MeshConfig(fsdp=8), (1, 8, 1, 1, 1)),
        (MeshConfig(fsdp=4), (2, 4, 1, 1, 1)),
        (MeshConfig(model=2, fsdp=2), (2, 2, 1, 1, 2)),
        (MeshConfig(sequence=4), (2, 1, 1, 4, 1)),
        (MeshConfig(pipe=4), (2, 1, 4, 1, 1)),
        (MeshConfig(pipe=2, model=2), (2, 1, 2, 1, 2)),
        (MeshConfig(data=8), (8, 1, 1, 1, 1)),
    ],
)
def test_mesh_shape_resolution(cfg, expected):
    assert cfg.resolved_shape(8) == expected
    assert build_mesh(cfg).devices.shape == expected


def test_mesh_shape_errors():
    with pytest.raises(ValueError):
        MeshConfig(fsdp=3).resolved_shape(8)  # 3 does not divide 8
    with pytest.raises(ValueError):
        MeshConfig(data=4, fsdp=4).resolved_shape(8)  # 16 != 8


def test_runtime_shardings_and_sizes():
    rt = MeshRuntime(MeshConfig(fsdp=4))
    assert rt.axis_sizes == {"data": 2, "fsdp": 4, "pipe": 1, "sequence": 1, "model": 1}
    assert rt.data_parallel_size() == 8
    assert rt.n_devices == 8
    sh = rt.batch_sharding()
    assert sh.spec[0] == ("data", "fsdp")


def test_topology_report_is_real():
    rt = MeshRuntime()
    report = rt.topology_report()
    assert report["num_devices"] == 8
    assert len(report["devices"]) == 8
    assert report["mesh"]["axes"] == {"data": 8, "fsdp": 1, "pipe": 1, "sequence": 1, "model": 1}
    ids = {d["id"] for d in report["devices"]}
    assert len(ids) == 8  # real device ids, not a canned matrix


def test_detect_topology_standalone():
    t = detect_topology()
    assert t["num_devices"] == 8
    assert t["num_processes"] == 1


def test_dcn_mesh_groups_slices_on_data_axis():
    import jax
    import numpy as np

    devices = jax.devices()[:8]
    mesh = build_mesh(
        MeshConfig(data=4, fsdp=2, dcn_data=2),
        devices=devices,
        slice_assignments=[0, 0, 0, 0, 1, 1, 1, 1],
    )
    assert mesh.devices.shape == (4, 2, 1, 1, 1)
    # Outer data blocks are whole slices: rows 0-1 slice 0, rows 2-3 slice 1.
    first_block = set(d.id for d in mesh.devices[:2].flatten())
    second_block = set(d.id for d in mesh.devices[2:].flatten())
    assert first_block == {d.id for d in devices[:4]}
    assert second_block == {d.id for d in devices[4:]}


def test_dcn_mesh_validation():
    import jax
    import pytest

    devices = jax.devices()[:8]
    with pytest.raises(ValueError, match="divisible by dcn_data"):
        MeshConfig(data=3, dcn_data=2)
    with pytest.raises(ValueError, match="device\\s+slices|found 1 device"):
        # All devices in one slice but dcn_data=2.
        build_mesh(MeshConfig(data=4, fsdp=2, dcn_data=2), devices=devices,
                   slice_assignments=[0] * 8)
    with pytest.raises(ValueError, match="expected 4"):
        build_mesh(MeshConfig(data=4, fsdp=2, dcn_data=2), devices=devices,
                   slice_assignments=[0, 0, 0, 1, 1, 1, 1, 1])


def test_training_on_dcn_mesh_matches_single_slice():
    import jax
    import numpy as np

    from tpu_engine.sharding import Precision, ShardingStage, TPUTrainConfig
    from tpu_engine.train import build_train_program

    def run(mesh_cfg, slice_assignments=None, n=3):
        cfg = TPUTrainConfig(
            model_name="gpt-tiny", sharding_stage=ShardingStage.FULL_PARTITIONING,
            mesh=mesh_cfg, micro_batch_size=1, gradient_accumulation_steps=1,
            seq_len=32, precision=Precision.FP32, learning_rate=1e-2,
            warmup_steps=2, total_steps=100, activation_checkpointing=False,
        )
        runtime = MeshRuntime(mesh_cfg, slice_assignments=slice_assignments)
        prog = build_train_program(cfg, runtime=runtime)
        state = prog.init(jax.random.PRNGKey(0))
        losses = []
        for _ in range(n):
            state, m = prog.step(state, prog.synthetic_batch(0))
            losses.append(float(m["loss"]))
        return losses

    dcn = run(MeshConfig(data=4, fsdp=2, dcn_data=2),
              slice_assignments=[0, 0, 0, 0, 1, 1, 1, 1])
    ref = run(MeshConfig(data=4, fsdp=2))
    np.testing.assert_allclose(dcn, ref, rtol=1e-4)
    assert dcn[-1] < dcn[0]


def test_dcn_without_slice_info_fails_fast():
    import jax
    import pytest

    with pytest.raises(ValueError, match="slice_index"):
        build_mesh(MeshConfig(data=4, fsdp=2, dcn_data=2), devices=jax.devices()[:8])


def test_slice_assignments_rejected_without_dcn():
    import jax
    import pytest

    with pytest.raises(ValueError, match="dcn_data=1"):
        build_mesh(MeshConfig(data=8), devices=jax.devices()[:8],
                   slice_assignments=[0] * 8)
