"""Sharding stages: ZeRO semantics → PartitionSpecs, configs, presets."""

import pytest
from jax.sharding import PartitionSpec as P

from tpu_engine.mesh_runtime import MeshConfig
from tpu_engine.models import transformer as tfm
from tpu_engine.sharding import (
    OffloadDevice,
    ShardingStage,
    TPUTrainConfig,
    grad_pspecs,
    logical_to_mesh_axes,
    opt_state_pspecs,
    param_pspecs,
    presets,
)

LG_2D = ("embed", "heads")  # e.g. an attention projection


def test_tp_axes_always_sharded():
    for fsdp in (False, True):
        spec = logical_to_mesh_axes(LG_2D, shard_fsdp=fsdp)
        assert spec[-1] == "model" or (len(spec) > 1 and spec[1] == "model")


def test_stage_semantics_on_representative_param():
    logical = {"w": LG_2D}
    # Stage 0: params/grads/opt all replicated on fsdp (TP still applies).
    assert param_pspecs(logical, ShardingStage.DISABLED)["w"] == P(None, "model")
    assert grad_pspecs(logical, ShardingStage.DISABLED)["w"] == P(None, "model")
    assert opt_state_pspecs(logical, ShardingStage.DISABLED)["w"] == P(None, "model")
    # Stage 1: only optimizer state is fsdp-sharded.
    assert param_pspecs(logical, ShardingStage.OPTIMIZER_STATE)["w"] == P(None, "model")
    assert grad_pspecs(logical, ShardingStage.OPTIMIZER_STATE)["w"] == P(None, "model")
    assert opt_state_pspecs(logical, ShardingStage.OPTIMIZER_STATE)["w"] == P("fsdp", "model")
    # Stage 2: + gradients reduce-scattered.
    assert grad_pspecs(logical, ShardingStage.GRADIENT_PARTITIONING)["w"] == P("fsdp", "model")
    assert param_pspecs(logical, ShardingStage.GRADIENT_PARTITIONING)["w"] == P(None, "model")
    # Stage 3: full FSDP.
    assert param_pspecs(logical, ShardingStage.FULL_PARTITIONING)["w"] == P("fsdp", "model")


def test_norm_scales_replicate_without_fsdp():
    spec = logical_to_mesh_axes(("embed",), shard_fsdp=False)
    assert spec == P()


def test_model_logical_tree_matches_param_tree():
    import jax

    cfg = tfm.MODEL_CONFIGS["gpt-tiny"]
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    logical = tfm.logical_axes(cfg)
    jax.tree.map(
        lambda p, lg: None if len(p.shape) == len(lg) else pytest.fail(f"{p.shape} vs {lg}"),
        params,
        logical,
        is_leaf=lambda x: isinstance(x, tuple) and all(isinstance(s, (str, type(None))) for s in x),
    )


def test_effective_batch_math():
    cfg = TPUTrainConfig(
        micro_batch_size=2,
        gradient_accumulation_steps=16,
        mesh=MeshConfig(data=1, fsdp=4),
    )
    # micro × accum × dp-world — reference deepspeed_launcher.py:323-328.
    assert cfg.effective_batch_size == 2 * 16 * 4
    # data=-1 resolves against the visible 8-device mesh (data=8, fsdp=1).
    inferred = TPUTrainConfig(micro_batch_size=8, gradient_accumulation_steps=1)
    assert inferred.effective_batch_size == 8 * 8


def test_presets_cover_reference_scales():
    p = presets()
    assert {"125m", "7b", "13b", "70b"} <= set(p)
    # Effective batch sizes match the reference's presets
    # (deepspeed_launcher.py:369-407: 128 / 256 / 1024); mesh shapes are
    # re-tuned for v5e HBM and AOT-verified (benchmarks/RESULTS.md).
    assert p["7b"].effective_batch_size == 128
    assert p["13b"].effective_batch_size == 256
    assert p["70b"].effective_batch_size == 1024
    assert p["70b"].mesh.data * p["70b"].mesh.fsdp == 256  # v5e-256 slice
    assert all(c.sharding_stage == ShardingStage.FULL_PARTITIONING
               for n, c in p.items() if n != "125m")
    # Offload knobs on the big presets are REAL engine behavior now —
    # params stream from pinned host memory (tests/test_offload.py).
    assert p["13b"].param_offload == OffloadDevice.HOST
    assert p["70b"].param_offload == OffloadDevice.HOST


def test_param_count_roughly_right():
    assert 120e6 < tfm.param_count(tfm.MODEL_CONFIGS["gpt-125m"]) < 180e6
    assert 6.0e9 < tfm.param_count(tfm.MODEL_CONFIGS["llama-7b"]) < 7.5e9
    assert 60e9 < tfm.param_count(tfm.MODEL_CONFIGS["llama-70b"]) < 75e9


def test_per_stage_per_device_memory_shrinks():
    """The stage enum produces genuinely different per-device memory — the
    measurable ZeRO semantics, not a forwarded config string (SURVEY §7
    hard part (a))."""
    import jax

    from tpu_engine.train import build_train_program

    def device0_bytes(tree):
        return sum(
            leaf.addressable_shards[0].data.nbytes
            for leaf in jax.tree.leaves(tree)
            if hasattr(leaf, "addressable_shards")
        )

    stats = {}
    for stage in (ShardingStage.DISABLED, ShardingStage.OPTIMIZER_STATE,
                  ShardingStage.FULL_PARTITIONING):
        cfg = TPUTrainConfig(
            model_name="gpt-tiny", sharding_stage=stage,
            mesh=MeshConfig(data=2, fsdp=4), micro_batch_size=1, seq_len=32,
            precision="fp32", activation_checkpointing=False,
        )
        prog = build_train_program(cfg)
        state = prog.init(jax.random.PRNGKey(0))
        stats[stage] = (
            device0_bytes(state["params"]),
            device0_bytes(state["opt_state"]),
        )
    p0, o0 = stats[ShardingStage.DISABLED]
    p1, o1 = stats[ShardingStage.OPTIMIZER_STATE]
    p3, o3 = stats[ShardingStage.FULL_PARTITIONING]
    # Stage 1: optimizer state shards over fsdp=4; params stay replicated.
    assert p1 == p0
    assert o1 < o0 * 0.5
    # Stage 3: params shard too.
    assert p3 < p1 * 0.5
    assert o3 <= o1
