"""AQT-style int8 quantized training (``tpu_engine/quant_train.py``):
quantizer numerics (round-trip bound, stochastic-rounding unbiasedness),
einsum/gradient correctness of the custom_vjp primitive, CPU loss parity
of the end-to-end quantized train step vs the full-precision path,
composition with the ZeRO++ comm compression, and the config interaction
matrix that rejects unsupported combos with actionable errors."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_engine import quant_train as qt
from tpu_engine.mesh_runtime import MeshConfig, MeshRuntime
from tpu_engine.sharding import Precision, ShardingStage, TPUTrainConfig
from tpu_engine.train import build_train_program


# ---------------------------------------------------------------------------
# Quantizer numerics
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "shape, axes",
    [((8, 33), (0,)), ((8, 33), (1,)), ((4, 6, 10), (2,)), ((4, 6, 10), (1, 2))],
)
def test_channel_roundtrip_error_bound(shape, axes):
    """absmax/127 per-channel scales ⇒ round-trip error ≤ half a
    quantization step of the element's own channel scale."""
    x = jax.random.normal(jax.random.PRNGKey(0), shape) * 3.0
    codes, scales = qt.channel_quantize(x, axes)
    assert codes.dtype == jnp.int8 and codes.shape == shape
    # keepdims scales: size 1 exactly on the contraction axes.
    assert all(
        scales.shape[d] == (1 if d in axes else shape[d])
        for d in range(len(shape))
    )
    deq = codes.astype(jnp.float32) * scales
    err = np.abs(np.asarray(deq - x))
    bound = np.broadcast_to(np.asarray(scales) / 2 + 1e-6, shape)
    assert np.all(err <= bound)


def test_channel_roundtrip_exact_on_grid():
    x = jnp.arange(-127, 128, dtype=jnp.float32).reshape(1, 255) * 0.25
    codes, scales = qt.channel_quantize(x, (1,))
    np.testing.assert_allclose(
        np.asarray(codes.astype(jnp.float32) * scales), np.asarray(x),
        rtol=1e-6,
    )


def test_stochastic_rounding_unbiased():
    """Mean dequantized value over many independent draws converges to the
    input (nearest rounding would sit a deterministic fraction of a step
    off). Exercises the explicit-key path; the in-training path derives
    its key from the operand data instead."""
    x = jnp.full((1, 64), 0.3)
    deqs = []
    for i in range(300):
        codes, scales = qt.channel_quantize(x, (1,), key=jax.random.PRNGKey(i))
        deqs.append(codes.astype(jnp.float32) * scales)
    mean = float(jnp.mean(jnp.stack(deqs)))
    step = 0.3 / 127
    assert abs(mean - 0.3) < step / 5, (mean, step)


def test_data_derived_key_decorrelates():
    """The data-derived stochastic rounding is deterministic for the same
    operand and decorrelated across different operands — the property the
    scanned-layer backward relies on (same trace, different data)."""
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 64))
    c1, _ = qt.channel_quantize(x, (1,), stochastic=True)
    c2, _ = qt.channel_quantize(x, (1,), stochastic=True)
    np.testing.assert_array_equal(np.asarray(c1), np.asarray(c2))
    c3, _ = qt.channel_quantize(x * 1.0001, (1,), stochastic=True)
    assert np.any(np.asarray(c1) != np.asarray(c3))


# ---------------------------------------------------------------------------
# int8_einsum: forward accuracy + custom_vjp gradients
# ---------------------------------------------------------------------------

SPECS = [
    ("bsi,io->bso", (2, 8, 16), (16, 32)),     # projections
    ("ebcd,edf->ebcf", (3, 2, 8, 16), (3, 16, 32)),  # MoE gate/up
    ("ebcf,efd->ebcd", (3, 2, 8, 32), (3, 32, 16)),  # MoE down
]


@pytest.mark.parametrize("spec, lshape, rshape", SPECS)
def test_int8_einsum_forward_accuracy(spec, lshape, rshape):
    lhs = jax.random.normal(jax.random.PRNGKey(0), lshape)
    rhs = jax.random.normal(jax.random.PRNGKey(1), rshape)
    out = qt.int8_einsum(spec, lhs, rhs)
    ref = jnp.einsum(spec, lhs, rhs)
    assert out.shape == ref.shape
    rel = float(jnp.max(jnp.abs(out - ref)) / jnp.max(jnp.abs(ref)))
    assert rel < 0.02, rel


@pytest.mark.parametrize("spec, lshape, rshape", SPECS)
def test_int8_einsum_gradients_track_full_precision(spec, lshape, rshape):
    """The straight-through backward's gradients stay aligned with the
    exact full-precision gradients (cosine similarity): the transpose
    specs are derived correctly and the stochastic backward quantization
    is a small perturbation, not a direction change."""
    lhs = jax.random.normal(jax.random.PRNGKey(2), lshape)
    rhs = jax.random.normal(jax.random.PRNGKey(3), rshape)

    def loss(fn):
        return jax.grad(
            lambda a, b: jnp.sum(fn(spec, a, b) ** 2), argnums=(0, 1)
        )(lhs, rhs)

    (ga, gb), (fa, fb) = loss(qt.int8_einsum), loss(jnp.einsum)
    for g, f in ((ga, fa), (gb, fb)):
        g, f = np.asarray(g).ravel(), np.asarray(f).ravel()
        cos = g @ f / (np.linalg.norm(g) * np.linalg.norm(f))
        assert cos > 0.999, cos


def test_int8_einsum_under_jit_and_dtype():
    h = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 8), jnp.bfloat16)
    w = jax.random.normal(jax.random.PRNGKey(1), (8, 6), jnp.bfloat16)
    out = jax.jit(lambda a, b: qt.int8_einsum("bsi,io->bso", a, b))(h, w)
    assert out.dtype == jnp.bfloat16 and out.shape == (2, 4, 6)
    g = jax.jit(jax.grad(
        lambda a: jnp.sum(qt.int8_einsum("bsi,io->bso", a, w)
                          .astype(jnp.float32))
    ))(h)
    assert g.dtype == h.dtype and g.shape == h.shape


def test_transpose_specs():
    assert qt._transpose_specs("bsi,io->bso") == ("bso,io->bsi", "bsi,bso->io")
    assert qt._transpose_specs("ebcd,edf->ebcf") == (
        "ebcf,edf->ebcd", "ebcd,ebcf->edf",
    )
    assert qt._contraction_axes("ebcd,edf->ebcf") == ((3,), (1,))


# ---------------------------------------------------------------------------
# End-to-end loss parity (CPU, single device)
# ---------------------------------------------------------------------------


def _cfg(**kw) -> TPUTrainConfig:
    base = dict(
        model_name="gpt-tiny",
        sharding_stage=ShardingStage.DISABLED,
        mesh=MeshConfig(data=8),
        micro_batch_size=2,
        seq_len=32,
        precision=Precision.FP32,
        param_dtype=Precision.FP32,
        # Sub-chaotic lr: parity measures per-step quantization error,
        # not trajectory divergence (see benchmarks/quant_train.py).
        learning_rate=1e-3,
        warmup_steps=2,
        total_steps=100,
        activation_checkpointing=False,
    )
    base.update(kw)
    return TPUTrainConfig(**base)


def _run(prog, n, seed=0):
    state = prog.init(jax.random.PRNGKey(prog.config.seed))
    batch = prog.synthetic_batch(seed)  # fixed batch → loss must drop
    losses = []
    for _ in range(n):
        state, metrics = prog.step(state, batch)
        losses.append(float(metrics["loss"]))
    return state, losses


@pytest.fixture(scope="module")
def parity_runs():
    runs = {}
    for quant in ("none", "int8"):
        prog = build_train_program(_cfg(quant_training=quant))
        runs[quant] = _run(prog, 9)[1]
    return runs


def test_loss_parity_8_steps(parity_runs):
    """int8 quantized training tracks the fp32 path: same seed, same
    batch, |Δloss| ≤ 0.01 at every one of ≥8 steps — and both actually
    train (the acceptance bar of ISSUE 2)."""
    base, q = parity_runs["none"], parity_runs["int8"]
    assert len(base) >= 8
    assert base[-1] < base[0] and q[-1] < q[0]
    for b, c in zip(base, q):
        assert abs(b - c) <= 0.01, (base, q)


def test_quantized_step_changes_logits(parity_runs):
    """The quantized path is actually active, not a silent no-op: the two
    trajectories must differ at some step (quantization error is small
    but nonzero)."""
    base, q = parity_runs["none"], parity_runs["int8"]
    assert any(b != c for b, c in zip(base, q)), (base, q)


def test_parity_moe_model():
    """MoE expert einsums ride the hook too — parity on moe-tiny."""
    runs = {}
    for quant in ("none", "int8"):
        prog = build_train_program(
            _cfg(model_name="moe-tiny", quant_training=quant)
        )
        runs[quant] = _run(prog, 8)[1]
    base, q = runs["none"], runs["int8"]
    assert base[-1] < base[0] and q[-1] < q[0]
    for b, c in zip(base, q):
        assert abs(b - c) <= 0.05, (base, q)


def test_targets_subset_only_quantizes_selected():
    """quant_train_targets=('mlp',) still trains and still perturbs the
    trajectory (the MLP hook is live even with attn excluded)."""
    prog = build_train_program(
        _cfg(quant_training="int8", quant_train_targets=("mlp",))
    )
    assert prog.model_config.quant_train_targets == ("mlp",)
    _, losses = _run(prog, 6)
    assert losses[-1] < losses[0]


def test_composes_with_comm_compression():
    """Wire quantization (ZeRO++ qwZ) and MXU quantization are orthogonal
    and compose: the int8 einsum is plain jnp inside the full-manual
    shard_map region. Loss must still track the uncompressed bf16 path."""
    kw = dict(
        sharding_stage=ShardingStage.FULL_PARTITIONING,
        mesh=MeshConfig(data=4, fsdp=2, dcn_data=2),
        gradient_accumulation_steps=2,
        comm_quant_weights=True,
        comm_quant_grads=True,
        comm_quant_block_size=64,
    )
    runtime_kw = dict(slice_assignments=[0, 0, 0, 0, 1, 1, 1, 1])
    runs = {}
    for quant in ("none", "int8"):
        cfg = _cfg(quant_training=quant, **kw)
        prog = build_train_program(
            cfg, runtime=MeshRuntime(cfg.mesh, **runtime_kw)
        )
        runs[quant] = _run(prog, 6)[1]
    base, q = runs["none"], runs["int8"]
    assert base[-1] < base[0] and q[-1] < q[0]
    for b, c in zip(base, q):
        assert abs(b - c) <= 0.02, (base, q)


def test_gpipe_pipeline_composes():
    """Autodiff differentiates through the custom_vjp inside the gpipe
    stage scan; 'auto' must resolve AWAY from 1f1b under quantization."""
    cfg = _cfg(
        model_name="gpt-tiny",
        sharding_stage=ShardingStage.FULL_PARTITIONING,
        mesh=MeshConfig(data=2, pipe=2, fsdp=2),
        gradient_accumulation_steps=4,  # would auto-pick 1f1b unquantized
        quant_training="int8",
        pipeline_schedule="auto",
    )
    prog = build_train_program(cfg)
    assert prog.pipeline_schedule == "gpipe"
    _, losses = _run(prog, 6)
    assert losses[-1] < losses[0], losses


# ---------------------------------------------------------------------------
# Config interaction matrix
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "kw, match",
    [
        (dict(quant_training="int8", lora_rank=4), "LoRA"),
        (dict(quant_training="int8", pipeline_schedule="1f1b"), "1f1b"),
        (dict(quant_training="int8", moe_impl="ragged"), "ragged"),
        (dict(quant_training="int8", quant_train_targets=()), "no-op"),
        (dict(quant_train_targets=("attn", "bogus")), "unknown quant_train_targets"),
    ],
)
def test_config_rejections(kw, match):
    base = dict(model_name="gpt-tiny", seq_len=32, mesh=MeshConfig(data=8))
    base.update(kw)
    with pytest.raises(ValueError, match=match):
        TPUTrainConfig(**base)


def test_comm_flags_compose_at_config_level():
    """The PR-1 interaction matrix: every comm_quant_* mechanism composes
    with quant_training (wire vs MXU — orthogonal)."""
    cfg = TPUTrainConfig(
        model_name="gpt-tiny", seq_len=32,
        sharding_stage=ShardingStage.FULL_PARTITIONING,
        mesh=MeshConfig(data=2, fsdp=4),
        quant_training="int8",
        comm_quant_weights=True, comm_secondary_weights=True,
        comm_quant_grads=True,
    )
    assert cfg.quant_training == "int8" and cfg.comm_quant_weights


def test_ragged_without_moe_target_composes():
    cfg = TPUTrainConfig(
        model_name="moe-tiny", seq_len=32, mesh=MeshConfig(data=8),
        quant_training="int8", moe_impl="ragged",
        quant_train_targets=("attn", "mlp"),
    )
    assert cfg.moe_impl == "ragged"


def test_ragged_model_preset_rejected_at_build():
    """cfg.moe_impl=None + a model preset carrying ragged must still be
    rejected — at build, on the RESOLVED model config."""
    from tpu_engine.models import transformer as tfm

    cfg = _cfg(model_name="moe-tiny", quant_training="int8")
    ragged_model = tfm.MODEL_CONFIGS["moe-tiny"].with_(moe_impl="ragged")
    with pytest.raises(ValueError, match="ragged"):
        build_train_program(cfg, model_cfg=ragged_model)


def test_off_by_default():
    cfg = TPUTrainConfig(model_name="gpt-tiny", mesh=MeshConfig(data=8))
    assert cfg.quant_training == "none"
    assert qt.enabled(cfg) is False
    prog = build_train_program(cfg)
    assert prog.model_config.quant_training == "none"


# ---------------------------------------------------------------------------
# Plan / API surface
# ---------------------------------------------------------------------------


def test_training_plan():
    off = qt.training_plan(_cfg())
    assert off["enabled"] is False and off["mode"] == "none"
    on = qt.training_plan(_cfg(quant_training="int8",
                               quant_train_targets=("attn", "mlp")))
    assert on["enabled"] is True
    assert on["targets"] == ["attn", "mlp"]
    assert "mfu_note" in on and "roofline" in on["mfu_note"]


def test_launcher_plan_includes_quant_training():
    from tpu_engine.launcher import TPULauncher

    plan = TPULauncher().generate_plan(_cfg(quant_training="int8"))
    assert plan["quant_training"]["enabled"] is True
    assert plan["quant_training"]["mode"] == "int8"
    off = TPULauncher().generate_plan(_cfg())
    assert off["quant_training"]["enabled"] is False


def test_http_launch_request_fields():
    """The launch API accepts the new knobs, maps them onto the config,
    and surfaces validator failures as a 422, not a job-thread crash."""
    from backend.http import ApiError
    from backend.routers.training import TrainingLaunchRequest, _to_config

    req = TrainingLaunchRequest(
        model_name="gpt-tiny", seq_len=32, mesh=MeshConfig(data=8),
        sharding_stage=0,
        quant_training="int8", quant_train_targets=["attn", "mlp"],
    )
    cfg = _to_config(req)
    assert cfg.quant_training == "int8"
    assert cfg.quant_train_targets == ("attn", "mlp")

    bad = TrainingLaunchRequest(
        model_name="gpt-tiny", seq_len=32, mesh=MeshConfig(data=8),
        sharding_stage=0, quant_training="int8", lora_rank=4,
    )
    with pytest.raises(ApiError):
        _to_config(bad)
