"""Train program: loss decreases, stages change placement, accumulation works."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_engine.mesh_runtime import MeshConfig, MeshRuntime
from tpu_engine.models import transformer as tfm
from tpu_engine.sharding import Precision, ShardingStage, TPUTrainConfig
from tpu_engine.train import build_train_program


def tiny_config(**kw) -> TPUTrainConfig:
    base = dict(
        model_name="gpt-tiny",
        sharding_stage=ShardingStage.FULL_PARTITIONING,
        mesh=MeshConfig(data=2, fsdp=4),
        micro_batch_size=1,
        gradient_accumulation_steps=2,
        seq_len=32,
        precision=Precision.FP32,  # CPU test backend: bf16 is slow & noisy there
        learning_rate=1e-2,
        warmup_steps=2,
        total_steps=100,
        activation_checkpointing=True,
    )
    base.update(kw)
    return TPUTrainConfig(**base)


def run_steps(cfg, n=8, seed=0):
    prog = build_train_program(cfg)
    state = prog.init(jax.random.PRNGKey(cfg.seed))
    losses = []
    for i in range(n):
        batch = prog.synthetic_batch(seed)  # fixed batch → loss must drop fast
        state, metrics = prog.step(state, batch)
        losses.append(float(metrics["loss"]))
    return prog, state, losses


def test_loss_decreases_stage3():
    _, _, losses = run_steps(tiny_config(), n=10)
    assert losses[-1] < losses[0] * 0.7, losses


def test_param_placement_per_stage():
    cfg3 = tiny_config()
    prog3, state3, _ = run_steps(cfg3, n=1)
    q_sh = state3["params"]["layers"]["q"]["kernel"].sharding
    # logical (layers, embed, heads) → (None, fsdp, model-axis-for-TP)
    assert q_sh.spec == jax.sharding.PartitionSpec("pipe", "fsdp", "model")

    cfg1 = tiny_config(sharding_stage=ShardingStage.OPTIMIZER_STATE)
    prog1 = build_train_program(cfg1)
    state1 = prog1.init(jax.random.PRNGKey(0))
    # Params NOT fsdp-sharded at stage 1...
    p_sh = state1["params"]["layers"]["q"]["kernel"].sharding
    assert p_sh.spec == jax.sharding.PartitionSpec("pipe", None, "model")
    # ...but adam mu for the same param is fsdp-sharded (ZeRO-1).
    mu = state1["opt_state"][1].mu["layers"]["q"]["kernel"]
    assert mu.sharding.spec == jax.sharding.PartitionSpec("pipe", "fsdp", "model")


def test_stage0_and_stage3_agree():
    # Same seed + same data → numerically equivalent training trajectories.
    _, _, l0 = run_steps(tiny_config(sharding_stage=ShardingStage.DISABLED), n=3)
    _, _, l3 = run_steps(tiny_config(sharding_stage=ShardingStage.FULL_PARTITIONING), n=3)
    np.testing.assert_allclose(l0, l3, rtol=1e-3)


def test_gradient_accumulation_shapes():
    cfg = tiny_config(gradient_accumulation_steps=4)
    prog = build_train_program(cfg)
    assert prog.global_batch_shape() == (4, 1 * 8, 32)
    batch = prog.synthetic_batch(0)
    assert batch.shape == (4, 8, 32)


def test_lr_schedule_and_metrics():
    cfg = tiny_config(warmup_steps=5, learning_rate=1e-2)
    prog = build_train_program(cfg)
    state = prog.init(jax.random.PRNGKey(0))
    lrs = []
    for i in range(6):
        state, m = prog.step(state, prog.synthetic_batch(i))
        lrs.append(float(m["learning_rate"]))
        assert float(m["grad_norm"]) > 0
    assert lrs[0] < lrs[4]  # warmup ramps
    assert int(jax.device_get(state["step"])) == 6


def test_tensor_parallel_mesh_runs():
    cfg = tiny_config(mesh=MeshConfig(data=2, fsdp=2, model=2))
    _, state, losses = run_steps(cfg, n=3)
    q = state["params"]["layers"]["q"]["kernel"]
    assert q.sharding.spec == jax.sharding.PartitionSpec("pipe", "fsdp", "model")
    # Actually split over 2 fsdp × 2 model devices.
    assert q.addressable_shards[0].data.shape[1] == q.shape[1] // 2
    assert q.addressable_shards[0].data.shape[2] == q.shape[2] // 2
    assert losses[-1] < losses[0]


def test_forward_shapes_and_dtype():
    cfg = tfm.MODEL_CONFIGS["gpt-tiny"]
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jnp.zeros((2, 16), jnp.int32)
    logits = tfm.forward(params, tokens, cfg, compute_dtype=jnp.float32)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32


def test_chunked_loss_matches_unchunked():
    """loss_chunk_size computes CE blockwise; must be numerically identical."""
    ref = run_steps(tiny_config(activation_checkpointing=False), n=2)[2]
    chunked = run_steps(
        tiny_config(activation_checkpointing=False, loss_chunk_size=8), n=2
    )[2]
    np.testing.assert_allclose(ref, chunked, rtol=1e-6)


def test_chunk_size_must_divide_seq_len():
    with pytest.raises(ValueError, match="divide"):
        build_train_program(tiny_config(loss_chunk_size=7))  # 32 % 7 != 0


@pytest.mark.parametrize("policy", ["save_attn_out", "save_qkv_attn_out"])
def test_named_remat_policies_match(policy):
    """Named checkpoint policies change memory, never math."""
    ref = run_steps(tiny_config(activation_checkpointing=False), n=2)[2]
    got = run_steps(
        tiny_config(activation_checkpointing=True, remat_policy=policy), n=2
    )[2]
    np.testing.assert_allclose(ref, got, rtol=1e-6)


def test_unknown_remat_policy_rejected():
    with pytest.raises(ValueError, match="remat_policy"):
        build_train_program(tiny_config(remat_policy="attn_out"))  # typo


def test_offload_dots_policy_rejected_off_tpu():
    # The activation-offload policy exists (TPU-only); off-TPU it is a
    # clear build-time error, not a partitioner crash at first step.
    with pytest.raises(ValueError, match="offload_dots"):
        build_train_program(tiny_config(remat_policy="offload_dots"))


def test_moment_dtype_halves_mu_buffer():
    """moment_dtype=BF16 stores Adam mu in bf16; nu stays at master dtype."""
    _, state, losses = run_steps(tiny_config(moment_dtype=Precision.BF16))
    adam = state["opt_state"][1]
    assert adam.mu["layers"]["q"]["kernel"].dtype == jnp.bfloat16
    assert adam.nu["layers"]["q"]["kernel"].dtype == jnp.float32
    # Training still converges with reduced-precision first moment.
    assert losses[-1] < losses[0] * 0.7


def test_z_loss_stabilizer():
    """z_loss_coef adds the logit-normaliser penalty to the train loss,
    identically for chunked and unchunked CE; eval stays pure CE."""
    ref = run_steps(tiny_config(activation_checkpointing=False), n=2)[2]
    with_z = run_steps(
        tiny_config(activation_checkpointing=False, z_loss_coef=1e-3), n=2
    )[2]
    assert with_z[0] > ref[0]  # penalty is positive
    chunked_z = run_steps(
        tiny_config(activation_checkpointing=False, z_loss_coef=1e-3,
                    loss_chunk_size=8), n=2
    )[2]
    np.testing.assert_allclose(with_z, chunked_z, rtol=1e-6)
    # Eval excludes the regulariser: pure CE equals the no-z run's eval.
    prog_z = build_train_program(
        tiny_config(activation_checkpointing=False, z_loss_coef=1e-3)
    )
    prog_ref = build_train_program(tiny_config(activation_checkpointing=False))
    s_z = prog_z.init(jax.random.PRNGKey(0))
    s_ref = prog_ref.init(jax.random.PRNGKey(0))
    b = prog_z.synthetic_batch(0)
    np.testing.assert_allclose(
        float(prog_z.eval_step(s_z, b)), float(prog_ref.eval_step(s_ref, b)), rtol=1e-6
    )


def test_sliding_window_train_step():
    """A windowed (Mistral-style) model trains end-to-end: loss decreases
    and the window actually changes the function vs full causal."""
    cfg = tiny_config(seq_len=64)
    model_cfg = tfm.MODEL_CONFIGS["gpt-tiny"].with_(sliding_window=16, max_seq_len=64)
    prog = build_train_program(cfg, model_cfg=model_cfg)
    state = prog.init(jax.random.PRNGKey(0))
    losses = []
    for _ in range(8):
        batch = prog.synthetic_batch(0)
        state, metrics = prog.step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.8, losses

    tokens = jnp.asarray(np.random.default_rng(0).integers(0, 512, (1, 64)), jnp.int32)
    params = jax.device_get(state["params"])
    windowed = tfm.forward(params, tokens, model_cfg, compute_dtype=jnp.float32)
    full = tfm.forward(params, tokens, model_cfg.with_(sliding_window=0),
                       compute_dtype=jnp.float32)
    assert not np.allclose(np.asarray(windowed), np.asarray(full), atol=1e-3)


def test_sliding_window_rejects_sequence_parallel():
    """Window + ring/ulysses is a config error, rejected at build time
    (not at first-step trace)."""
    cfg = tiny_config(mesh=MeshConfig(data=1, fsdp=2, sequence=4), seq_len=64,
                      attention_impl="ring")
    model_cfg = tfm.MODEL_CONFIGS["gpt-tiny"].with_(sliding_window=16, max_seq_len=64)
    with pytest.raises(ValueError, match="sliding_window"):
        build_train_program(cfg, model_cfg=model_cfg)


def test_gpt2_arch_trains():
    """GPT-2 family (LayerNorm+bias, learned positions, GELU, tied head)
    trains end-to-end on a sharded mesh; loss decreases."""
    cfg = tiny_config(model_name="gpt2-tiny", mesh=MeshConfig(data=2, fsdp=2, model=2))
    _, _, losses = run_steps(cfg, n=8)
    assert losses[-1] < losses[0] * 0.7, losses


def test_gemma_arch_trains():
    """Gemma family (zero-centred RMSNorm, GeGLU, sqrt(d)-scaled embeddings,
    decoupled head_dim, MQA, tied head) trains end-to-end on a sharded mesh
    with tensor parallelism; loss decreases."""
    cfg = tiny_config(model_name="gemma-tiny",
                      mesh=MeshConfig(data=2, fsdp=2, model=2))
    _, _, losses = run_steps(cfg, n=8)
    assert losses[-1] < losses[0] * 0.7, losses


# -- SFT loss masking --------------------------------------------------------


def _sft_batch(vocab=512, B=8, S=32, accum=2, seed=0):
    """An [accum, B, S] batch of SFT-packed rows (in-band -(t+1) masking)."""
    from tpu_engine.data import pack_sft_examples

    rng = np.random.default_rng(seed)
    pairs = []
    for _ in range(accum * B):
        p = rng.integers(0, vocab, rng.integers(4, 12)).tolist()
        c = rng.integers(0, vocab, rng.integers(4, S - 16)).tolist()
        pairs.append((p, c))
    return jnp.asarray(pack_sft_examples(pairs, S).reshape(accum, B, S))


def test_sft_masked_loss_matches_manual():
    """eval_step on an SFT-packed batch == the GLOBAL valid-target mean CE
    computed by hand — after training, where microbatches have uneven
    valid counts and per-token losses differ, so a mean of per-microbatch
    means would NOT match (the accumulation paths must divide once by the
    batch-wide count, not average per-microbatch means)."""
    cfg = tiny_config(activation_checkpointing=False)
    prog = build_train_program(cfg)
    state = prog.init(jax.random.PRNGKey(0))
    batch = _sft_batch()
    for _ in range(6):  # train so per-token losses are non-uniform
        state, _ = prog.step(state, batch)
    got = float(prog.eval_step(state, batch))

    from tpu_engine.train import decode_masked_tokens

    raw = batch.reshape(-1, batch.shape[-1])
    clean, loss_view = decode_masked_tokens(raw)
    params = jax.device_get(state["params"])
    logits = tfm.forward(params, clean, tfm.MODEL_CONFIGS["gpt-tiny"],
                         compute_dtype=jnp.float32)
    tgt = np.asarray(loss_view[:, 1:])
    logp = jax.nn.log_softmax(np.asarray(logits[:, :-1], np.float32), axis=-1)
    valid = tgt >= 0
    ll = np.take_along_axis(np.asarray(logp), np.maximum(tgt, 0)[..., None], -1)[..., 0]
    manual = -(ll * valid).sum() / valid.sum()
    np.testing.assert_allclose(got, manual, rtol=1e-4)


def test_sft_chunked_matches_unchunked():
    batch = _sft_batch()
    a = build_train_program(tiny_config(activation_checkpointing=False))
    b = build_train_program(tiny_config(activation_checkpointing=False, loss_chunk_size=8))
    sa = a.init(jax.random.PRNGKey(0))
    sb = b.init(jax.random.PRNGKey(0))
    np.testing.assert_allclose(
        float(a.eval_step(sa, batch)), float(b.eval_step(sb, batch)), rtol=1e-6
    )


def test_sft_pipeline_matches_accumulation():
    batch = _sft_batch(accum=2)
    pipe = build_train_program(tiny_config(mesh=MeshConfig(data=2, fsdp=2, pipe=2)))
    ref = build_train_program(tiny_config(mesh=MeshConfig(data=2, fsdp=2, model=2)))
    sp = pipe.init(jax.random.PRNGKey(0))
    sr = ref.init(jax.random.PRNGKey(0))
    np.testing.assert_allclose(
        float(pipe.eval_step(sp, batch)), float(ref.eval_step(sr, batch)), rtol=2e-5
    )


def test_sft_fully_masked_batch_is_finite():
    """A batch with zero valid targets yields loss 0, not NaN."""
    cfg = tiny_config(activation_checkpointing=False)
    prog = build_train_program(cfg)
    state = prog.init(jax.random.PRNGKey(0))
    raw = jnp.full((2, 8, 32), -1, jnp.int32)  # all masked (context token 0)
    assert float(prog.eval_step(state, raw)) == 0.0


def test_sft_training_learns_completions_only():
    """Training on SFT-packed rows drives completion loss down."""
    cfg = tiny_config(activation_checkpointing=False)
    prog = build_train_program(cfg)
    state = prog.init(jax.random.PRNGKey(0))
    batch = _sft_batch()
    losses = []
    for _ in range(8):
        state, m = prog.step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.8, losses


# Compile-heavy module: excluded from the fast core run (pytest -m "not slow").
pytestmark = pytest.mark.slow


def test_qwen_arch_trains():
    """Qwen3 family (per-head qk-norm before RoPE, decoupled head_dim, GQA,
    untied head) trains end-to-end on a sharded mesh with tensor
    parallelism; loss decreases."""
    cfg = tiny_config(model_name="qwen-tiny",
                      mesh=MeshConfig(data=2, fsdp=2, model=2))
    _, _, losses = run_steps(cfg, n=8)
    assert losses[-1] < losses[0] * 0.7, losses
