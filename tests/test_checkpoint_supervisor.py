"""Checkpoint/rollback/resume + supervised jobs: the resiliency core.

These mechanise what the reference only advertises (README.md:14 auto-resume
and corrupt-checkpoint rollback — no code exists; SURVEY.md §5).
"""

import math
import os
import shutil
import time

import jax
import jax.numpy as jnp
import pytest

from tpu_engine.checkpoint import TrainCheckpointManager, abstract_state_like
from tpu_engine.mesh_runtime import MeshConfig
from tpu_engine.scheduler import FleetScheduler, JobPriority, SubmissionState
from tpu_engine.sharding import Precision, ShardingStage, TPUTrainConfig
from tpu_engine.supervisor import JobStatus, TrainingJob
from tpu_engine.train import build_train_program


def tiny_config(tmp, **kw) -> TPUTrainConfig:
    base = dict(
        model_name="gpt-tiny",
        sharding_stage=ShardingStage.FULL_PARTITIONING,
        mesh=MeshConfig(data=2, fsdp=4),
        micro_batch_size=1,
        gradient_accumulation_steps=1,
        seq_len=32,
        precision=Precision.FP32,
        learning_rate=1e-3,
        warmup_steps=2,
        total_steps=1000,
        activation_checkpointing=False,
        checkpoint_dir=str(tmp),
        checkpoint_interval_steps=5,
    )
    base.update(kw)
    return TPUTrainConfig(**base)


def test_save_restore_roundtrip(tmp_path):
    cfg = tiny_config(tmp_path / "ckpt")
    prog = build_train_program(cfg)
    state = prog.init(jax.random.PRNGKey(0))
    state, _ = prog.step(state, prog.synthetic_batch(0))

    mgr = TrainCheckpointManager(str(tmp_path / "ckpt"))
    assert mgr.save(1, state, wait=True)
    assert mgr.all_steps() == [1]

    shape = jax.eval_shape(lambda: prog.init(jax.random.PRNGKey(0)))
    abstract = abstract_state_like(prog.state_shardings, shape)
    step, restored = mgr.restore(abstract)
    assert step == 1
    # Restored params land sharded and equal.
    q0 = jax.device_get(state["params"]["layers"]["q"]["kernel"])
    q1 = jax.device_get(restored["params"]["layers"]["q"]["kernel"])
    assert (q0 == q1).all()
    assert (
        restored["params"]["layers"]["q"]["kernel"].sharding.spec
        == state["params"]["layers"]["q"]["kernel"].sharding.spec
    )
    mgr.close()


def test_stable_pointer_and_corrupt_fallback(tmp_path):
    cfg = tiny_config(tmp_path / "ckpt")
    prog = build_train_program(cfg)
    state = prog.init(jax.random.PRNGKey(0))
    mgr = TrainCheckpointManager(str(tmp_path / "ckpt"), max_to_keep=5)
    for s in (1, 2, 3):
        mgr.save(s, state, wait=True)
    mgr.mark_stable(2)
    assert mgr.last_stable_step() == 2

    # Corrupt the newest checkpoint on disk → restore() quarantines and falls back.
    ckpt_dir = tmp_path / "ckpt" / "3"
    assert ckpt_dir.exists()
    shutil.rmtree(ckpt_dir / "default", ignore_errors=True)
    for extra in ckpt_dir.glob("**/*.json"):
        extra.unlink()

    shape = jax.eval_shape(lambda: prog.init(jax.random.PRNGKey(0)))
    abstract = abstract_state_like(prog.state_shardings, shape)
    step, restored = mgr.restore(abstract)
    assert step in (1, 2)  # 3 was corrupt → quarantined
    assert restored is not None
    assert 3 not in mgr.all_steps()
    mgr.close()


def test_delete_after_purges_newer_checkpoints(tmp_path):
    cfg = tiny_config(tmp_path / "ckpt")
    prog = build_train_program(cfg)
    state = prog.init(jax.random.PRNGKey(0))
    mgr = TrainCheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10)
    for s in (5, 10, 15, 20):
        mgr.save(s, state, wait=True)
    mgr.delete_after(10)
    assert mgr.all_steps() == [5, 10]
    mgr.close()


def test_supervised_job_completes_and_checkpoints(tmp_path):
    cfg = tiny_config(tmp_path / "ckpt", total_steps=12)
    job = TrainingJob("job-a", cfg, stable_margin_steps=5)
    job.start()
    job.join(timeout=300)
    assert job.status == JobStatus.COMPLETED, job.error
    assert job.current_step == 12
    assert job.ckpt.latest_step() == 12
    assert job.ckpt.last_stable_step() is not None
    d = job.describe()
    assert d["monitor"]["total_steps_seen"] == 12
    assert d["tokens_per_sec"] and d["tokens_per_sec"] > 0


def test_auto_resume_from_checkpoint(tmp_path):
    ck = tmp_path / "ckpt"
    cfg = tiny_config(ck, total_steps=10)
    job1 = TrainingJob("job-b1", cfg)
    job1.start()
    job1.join(timeout=300)
    assert job1.status == JobStatus.COMPLETED, job1.error

    # Same checkpoint dir, extended budget → resumes, does not restart at 0.
    cfg2 = tiny_config(ck, total_steps=15)
    job2 = TrainingJob("job-b2", cfg2)
    job2.start()
    job2.join(timeout=300)
    assert job2.status == JobStatus.COMPLETED, job2.error
    assert job2.resumed_from_step == 10
    assert job2.current_step == 15


def test_divergence_triggers_rollback_with_lr_cut(tmp_path):
    cfg = tiny_config(tmp_path / "ckpt", total_steps=40, checkpoint_interval_steps=5)
    prog = build_train_program(cfg)

    real_step = prog.step

    def sabotaged_step(state, batch):
        new_state, metrics = real_step(state, batch)
        step = int(jax.device_get(new_state["step"]))
        if step == 20 and not sabotaged_step.fired:
            sabotaged_step.fired = True
            metrics = dict(metrics, loss=jnp.float32(float("nan")))
        return new_state, metrics

    sabotaged_step.fired = False
    prog.step = sabotaged_step

    job = TrainingJob("job-c", cfg, program=prog, stable_margin_steps=5, max_rollbacks=2)
    job.start()
    job.join(timeout=300)
    assert job.status == JobStatus.COMPLETED, job.error
    assert job.rollback_count == 1
    # LR was cut after the rollback.
    assert float(jax.device_get(job._state["lr_scale"])) == pytest.approx(0.5)
    assert job.current_step == 40


def test_preemption_simulation_emergency_save_and_resume(tmp_path):
    ck = tmp_path / "ckpt"
    cfg = tiny_config(ck, total_steps=500, checkpoint_interval_steps=1000)

    holder = {}

    def check():  # preempt once training has made real progress
        j = holder.get("job")
        return j is not None and j.current_step >= 5

    job = TrainingJob(
        "job-d", cfg, watch_preemption=True, simulate_preemption_check=check
    )
    holder["job"] = job
    job.start()
    job.join(timeout=300)
    assert job.status == JobStatus.PREEMPTED
    assert job.preemption_reason == "gce-metadata"
    saved = job.ckpt.latest_step()
    assert saved and 0 < saved < 500  # emergency save happened mid-run

    # Auto-resume: new job, same dir → picks up at the emergency save (MTTR path).
    t0 = time.monotonic()
    cfg2 = tiny_config(ck, total_steps=saved + 3, checkpoint_interval_steps=1000)
    job2 = TrainingJob("job-d2", cfg2)
    job2.start()
    job2.join(timeout=300)
    mttr = time.monotonic() - t0
    assert job2.status == JobStatus.COMPLETED, job2.error
    assert job2.resumed_from_step == saved
    assert mttr < 90, f"auto-resume took {mttr:.1f}s (north-star target <90s)"


def test_elastic_resume_across_mesh_shapes(tmp_path):
    """TPU slices are fixed-shape, so elasticity = re-launch at a NEW mesh
    shape + resume from checkpoint (SURVEY.md §2.3, reference elasticity
    config ``deepspeed_launcher.py:226-238``). Orbax restores each leaf onto
    the new program's shardings, so a checkpoint written on (data=2, fsdp=4)
    must load into (data=1, fsdp=4, model=2) with identical values."""
    ck = tmp_path / "ckpt"
    cfg_a = tiny_config(ck, total_steps=6)
    job1 = TrainingJob("job-e1", cfg_a)
    job1.start()
    job1.join(timeout=300)
    assert job1.status == JobStatus.COMPLETED, job1.error
    q_before = jax.device_get(job1._state["params"]["layers"]["q"]["kernel"])

    # Re-launch on a different mesh: tensor parallelism instead of pure DP.
    cfg_b = tiny_config(
        ck, total_steps=9, mesh=MeshConfig(data=1, fsdp=4, model=2)
    )
    job2 = TrainingJob("job-e2", cfg_b)
    job2.start()
    job2.join(timeout=300)
    assert job2.status == JobStatus.COMPLETED, job2.error
    assert job2.resumed_from_step == 6
    assert job2.current_step == 9

    # The restored-and-resharded params actually landed tensor-parallel...
    q = job2.program.state_shardings["params"]["layers"]["q"]["kernel"]
    assert "model" in tuple(q.spec)

    # ...and the pre-resume values match what mesh A trained (restore first
    # happens before new steps mutate them, so compare via a fresh restore).
    from tpu_engine.checkpoint import abstract_state_like

    prog_b = build_train_program(cfg_b)
    shape = jax.eval_shape(lambda: prog_b.init(jax.random.PRNGKey(0)))
    abstract = abstract_state_like(prog_b.state_shardings, shape)
    step, restored = job2.ckpt.restore(abstract, step=6)
    assert step == 6
    q_after = jax.device_get(restored["params"]["layers"]["q"]["kernel"])
    assert (q_before == q_after).all()


# Compile-heavy module: excluded from the fast core run (pytest -m "not slow").
pytestmark = pytest.mark.slow


def test_elastic_bounds_auto_resume_on_smaller_slice(tmp_path):
    """Reference elasticity bounds (``deepspeed_launcher.py:226-238``), TPU
    reading: a job declares it may run between 2 and 8 chips; preempted on
    8 and resumed where only 4 are visible, the supervisor auto-selects the
    largest admissible mesh (data halves, fsdp kept) and cross-mesh
    restores — loss/param continuity intact."""
    ck = tmp_path / "ckpt"
    cfg = tiny_config(
        ck, total_steps=6, elastic_min_devices=2, elastic_max_devices=8,
    )  # mesh (data=2, fsdp=4) = 8 devices
    job1 = TrainingJob("job-el1", cfg)
    job1.start()
    job1.join(timeout=300)
    assert job1.status == JobStatus.COMPLETED, job1.error
    assert job1.elastic_mesh is None  # exact fit: no resize
    q_before = jax.device_get(job1._state["params"]["layers"]["q"]["kernel"])

    # "Resume" with only 4 visible devices: the configured 8-device mesh
    # cannot fit; the bounds admit 4 → (data=1, fsdp=4).
    job2 = TrainingJob(
        "job-el2", cfg.model_copy(update={"total_steps": 9}),
        devices=jax.devices()[:4],
    )
    job2.start()
    job2.join(timeout=300)
    assert job2.status == JobStatus.COMPLETED, job2.error
    assert job2.elastic_mesh == {
        "data": 1, "fsdp": 4, "pipe": 1, "sequence": 1, "model": 1,
        "dcn_data": 1,
    }
    assert job2.resumed_from_step == 6
    assert job2.current_step == 9
    assert job2.describe()["elastic_mesh"]["data"] == 1
    # The program really runs on the 4-device mesh.
    assert job2.program.runtime.n_devices == 4
    # Effective batch preserved (round-4 verdict gap 2 / reference
    # min/max-batch elasticity): dp halved 8 -> 4, so accumulation
    # doubled 1 -> 2 — micro x accum x dp is invariant across the shrink.
    accum, global_micro, _ = job2.program.global_batch_shape()
    assert accum == 2
    assert accum * global_micro == cfg.effective_batch_size == 8

    # Param continuity: a fresh restore of step 6 on the NEW mesh matches
    # what the 8-device run trained.
    from tpu_engine.checkpoint import abstract_state_like

    step, restored = job2.ckpt.restore(
        abstract_state_like(
            job2.program.state_shardings,
            jax.eval_shape(lambda: job2.program.init(jax.random.PRNGKey(0))),
        ),
        step=6,
    )
    assert step == 6
    q_after = jax.device_get(restored["params"]["layers"]["q"]["kernel"])
    assert (q_before == q_after).all()


def test_checkpoint_dir_scheme_handling(tmp_path):
    """"GCS-ready" paths, pinned (round-4 verdict weakness 7): URL-scheme
    directories pass through VERBATIM — ``os.path.abspath`` would mangle
    ``gs://bucket/x`` into ``<cwd>/gs:/bucket/x`` — while local paths
    expand and absolutise; the stable pointer rides etils.epath, which
    resolves local and object-store paths through one interface."""
    from etils import epath

    from tpu_engine.checkpoint import TrainCheckpointManager, resolve_checkpoint_dir

    assert resolve_checkpoint_dir("gs://bucket/ck") == "gs://bucket/ck"
    assert resolve_checkpoint_dir("s3://bucket/ck/x") == "s3://bucket/ck/x"
    assert resolve_checkpoint_dir("~/ck").startswith("/")
    assert "~" not in resolve_checkpoint_dir("~/ck")
    assert resolve_checkpoint_dir("rel/ck").startswith("/")

    # The epath-backed stable pointer round-trips on a real manager.
    mgr = TrainCheckpointManager(str(tmp_path / "ck"), async_save=False)
    assert isinstance(mgr._stable_path(), epath.Path)
    prog = build_train_program(tiny_config(tmp_path / "ck"))
    state = prog.init(jax.random.PRNGKey(0))
    mgr.save(3, state, force=True, wait=True)
    mgr.mark_stable(3)
    assert mgr.last_stable_step() == 3


def test_elastic_batch_bounds_gate_admission(tmp_path):
    """Declared effective-batch bounds (reference elasticity min/max batch
    sizes) gate an elastic resume: a shrink whose rescaled batch cannot
    land inside the bounds fails admission instead of training at an
    undeclared batch."""
    cfg = tiny_config(
        tmp_path / "ckb", total_steps=4,
        elastic_min_devices=2, elastic_max_devices=8,
        # dp=8 at launch, accum=1, micro=1 -> declared batch 8. On 4
        # devices the rescale achieves 8 again (accum 2) — which these
        # bounds refuse (max 4), so admission must fail.
        elastic_min_batch_size=1, elastic_max_batch_size=4,
    )
    job = TrainingJob("job-elb", cfg, devices=jax.devices()[:4])
    job.start()
    job.join(timeout=120)
    assert job.status == JobStatus.FAILED
    assert "no admissible effective batch" in (job.error or "")


def test_elastic_batch_bounds_validator():
    with pytest.raises(ValueError, match="elastic_max_batch_size"):
        TPUTrainConfig(
            model_name="gpt-tiny", mesh=MeshConfig(data=-1),
            elastic_min_batch_size=64, elastic_max_batch_size=8,
        )


def test_elastic_bounds_reject_below_minimum(tmp_path):
    """Fewer visible chips than elastic_min_devices is an admission error,
    not a silent tiny-mesh run."""
    cfg = tiny_config(
        tmp_path / "ck2", total_steps=4, elastic_min_devices=8,
    )
    job = TrainingJob("job-el3", cfg, devices=jax.devices()[:4])
    job.start()
    job.join(timeout=120)
    assert job.status == JobStatus.FAILED
    assert "no admissible mesh" in (job.error or "")


def test_no_bounds_means_exact_fit_only(tmp_path):
    cfg = tiny_config(tmp_path / "ck3", total_steps=4)
    job = TrainingJob("job-el4", cfg, devices=jax.devices()[:4])
    job.start()
    job.join(timeout=120)
    assert job.status == JobStatus.FAILED
    assert "needs" in (job.error or "")


def test_elastic_min_enforced_even_when_mesh_would_fit(tmp_path):
    """data=-1 absorbs any device count, so a fitting mesh must STILL
    respect the declared minimum — below it is an admission error."""
    cfg = tiny_config(
        tmp_path / "ck4", total_steps=4, mesh=MeshConfig(data=-1, fsdp=1),
        elastic_min_devices=8,
    )
    job = TrainingJob("job-el5", cfg, devices=jax.devices()[:4])
    job.start()
    job.join(timeout=120)
    assert job.status == JobStatus.FAILED
    assert "no admissible mesh" in (job.error or "")


def _wait_for(pred, timeout=300.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def test_scheduler_preempt_requeue_auto_resume_zero_lost_steps(tmp_path):
    """The full fleet-scheduler round trip on REAL jobs: a HIGH submission
    evicts a running LOW job through the emergency-save seam; the LOW
    submission requeues and auto-resumes from exactly the step the
    emergency checkpoint captured — zero lost steps."""
    cfg_low = tiny_config(
        tmp_path / "low", total_steps=30,
        checkpoint_interval_steps=1000,  # ONLY the emergency save persists
    )
    cfg_high = tiny_config(
        tmp_path / "high", total_steps=4, checkpoint_interval_steps=1000
    )
    sched = FleetScheduler(max_concurrent_jobs=1, poll_interval_s=0.05)
    holder = {}

    def slow_data(step):
        # ~20 ms/step: keeps the LOW run alive long enough for the
        # eviction to land mid-run (gpt-tiny steps are ~2 ms once warm).
        time.sleep(0.02)
        return holder["low"].job.program.synthetic_batch(0)

    try:
        low = sched.submit(
            cfg_low, priority=JobPriority.LOW,
            job_kwargs={"data_fn": slow_data},
        )
        holder["low"] = low
        assert _wait_for(
            lambda: low.job is not None and low.job.current_step >= 3
        ), "LOW job never got going"
        attempt1 = low.job

        high = sched.submit(cfg_high, priority=JobPriority.HIGH)
        high = sched.wait(high.submission_id, timeout=300)
        assert high.state == SubmissionState.COMPLETED, high.describe()

        low = sched.wait(low.submission_id, timeout=300)
        assert low.state == SubmissionState.COMPLETED, low.describe()
        assert low.preemptions == 1 and low.attempts == 2
        # Attempt 1 died PREEMPTED after its synchronous force-save...
        assert attempt1.status == JobStatus.PREEMPTED
        saved = attempt1.current_step
        assert saved >= 3
        # ...and attempt 2 resumed from exactly that step: zero lost work.
        assert low.job.resumed_from_step == saved
        assert low.job.current_step == 30
        assert sched.preemptions_total == 1 and sched.requeues_total == 1
    finally:
        sched.shutdown()


def test_corrupt_emergency_checkpoint_quarantined_on_readmission(tmp_path):
    """A preempted submission whose emergency checkpoint was corrupted on
    disk must not wedge the queue on re-admission: restore quarantines the
    bad step and falls back to the last good interval save."""
    ck = tmp_path / "low"
    cfg_low = tiny_config(ck, total_steps=40, checkpoint_interval_steps=5)
    cfg_high = tiny_config(
        tmp_path / "high", total_steps=4, checkpoint_interval_steps=1000
    )
    sched = FleetScheduler(max_concurrent_jobs=1, poll_interval_s=0.05)
    holder = {}

    def slow_data(step):
        time.sleep(0.02)
        return holder["low"].job.program.synthetic_batch(0)

    try:
        low = sched.submit(
            cfg_low, priority=JobPriority.LOW,
            job_kwargs={"data_fn": slow_data},
        )
        holder["low"] = low
        # Let interval saves (5, 10) land before forcing the eviction.
        assert _wait_for(
            lambda: low.job is not None and low.job.current_step >= 12
        ), "LOW job never reached step 12"
        attempt1 = low.job

        sched.submit(cfg_high, priority=JobPriority.HIGH)
        assert _wait_for(
            lambda: low.state in (
                SubmissionState.PREEMPTING, SubmissionState.QUEUED
            )
        )
        # Freeze admission so the requeued LOW cannot restart before the
        # corruption is in place.
        sched.drain()
        assert _wait_for(lambda: low.state == SubmissionState.QUEUED)
        saved = attempt1.current_step  # the emergency-save step

        # Corrupt the newest checkpoint on disk IN PLACE: garbage every file
        # but keep the item-directory layout. (Deleting whole item dirs would
        # leave the step with a different item set than its siblings, and the
        # fresh CheckpointManager of attempt 2 would then demand Composite
        # args for every later interval save.)
        steps = sorted(int(p.name) for p in ck.iterdir() if p.name.isdigit())
        assert steps and steps[-1] == saved
        newest = ck / str(saved)
        for f in newest.glob("**/*"):
            if f.is_file():
                f.write_bytes(b"\x00corrupt\x00")

        sched.resume_admission()
        low = sched.wait(low.submission_id, timeout=300)
        assert low.state == SubmissionState.COMPLETED, low.describe()
        # Restore quarantined the corrupt step and fell back to a good
        # interval save — strictly before the emergency save.
        assert low.job.resumed_from_step is not None
        assert low.job.resumed_from_step < saved
        assert low.job.resumed_from_step % 5 == 0
        assert low.job.current_step == 40  # still ran to completion
    finally:
        sched.shutdown()


def test_elastic_max_caps_to_device_subset(tmp_path):
    """max_devices below the visible count: the job runs on a SUBSET of the
    host (derived mesh paired with concrete devices), not on all chips."""
    cfg = tiny_config(
        tmp_path / "ck5", total_steps=4, mesh=MeshConfig(data=-1, fsdp=2),
        elastic_min_devices=2, elastic_max_devices=4,
    )
    job = TrainingJob("job-el6", cfg)  # 8 visible
    job.start()
    job.join(timeout=300)
    assert job.status == JobStatus.COMPLETED, job.error
    assert job.program.runtime.n_devices == 4
    assert job.elastic_mesh == {
        "data": 2, "fsdp": 2, "pipe": 1, "sequence": 1, "model": 1,
        "dcn_data": 1,
    }
