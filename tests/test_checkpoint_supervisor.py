"""Checkpoint/rollback/resume + supervised jobs: the resiliency core.

These mechanise what the reference only advertises (README.md:14 auto-resume
and corrupt-checkpoint rollback — no code exists; SURVEY.md §5).
"""

import math
import os
import shutil
import time

import jax
import jax.numpy as jnp
import pytest

from tpu_engine.checkpoint import TrainCheckpointManager, abstract_state_like
from tpu_engine.mesh_runtime import MeshConfig
from tpu_engine.sharding import Precision, ShardingStage, TPUTrainConfig
from tpu_engine.supervisor import JobStatus, TrainingJob
from tpu_engine.train import build_train_program


def tiny_config(tmp, **kw) -> TPUTrainConfig:
    base = dict(
        model_name="gpt-tiny",
        sharding_stage=ShardingStage.FULL_PARTITIONING,
        mesh=MeshConfig(data=2, fsdp=4),
        micro_batch_size=1,
        gradient_accumulation_steps=1,
        seq_len=32,
        precision=Precision.FP32,
        learning_rate=1e-3,
        warmup_steps=2,
        total_steps=1000,
        activation_checkpointing=False,
        checkpoint_dir=str(tmp),
        checkpoint_interval_steps=5,
    )
    base.update(kw)
    return TPUTrainConfig(**base)


def test_save_restore_roundtrip(tmp_path):
    cfg = tiny_config(tmp_path / "ckpt")
    prog = build_train_program(cfg)
    state = prog.init(jax.random.PRNGKey(0))
    state, _ = prog.step(state, prog.synthetic_batch(0))

    mgr = TrainCheckpointManager(str(tmp_path / "ckpt"))
    assert mgr.save(1, state, wait=True)
    assert mgr.all_steps() == [1]

    shape = jax.eval_shape(lambda: prog.init(jax.random.PRNGKey(0)))
    abstract = abstract_state_like(prog.state_shardings, shape)
    step, restored = mgr.restore(abstract)
    assert step == 1
    # Restored params land sharded and equal.
    q0 = jax.device_get(state["params"]["layers"]["q"]["kernel"])
    q1 = jax.device_get(restored["params"]["layers"]["q"]["kernel"])
    assert (q0 == q1).all()
    assert (
        restored["params"]["layers"]["q"]["kernel"].sharding.spec
        == state["params"]["layers"]["q"]["kernel"].sharding.spec
    )
    mgr.close()


def test_stable_pointer_and_corrupt_fallback(tmp_path):
    cfg = tiny_config(tmp_path / "ckpt")
    prog = build_train_program(cfg)
    state = prog.init(jax.random.PRNGKey(0))
    mgr = TrainCheckpointManager(str(tmp_path / "ckpt"), max_to_keep=5)
    for s in (1, 2, 3):
        mgr.save(s, state, wait=True)
    mgr.mark_stable(2)
    assert mgr.last_stable_step() == 2

    # Corrupt the newest checkpoint on disk → restore() quarantines and falls back.
    ckpt_dir = tmp_path / "ckpt" / "3"
    assert ckpt_dir.exists()
    shutil.rmtree(ckpt_dir / "default", ignore_errors=True)
    for extra in ckpt_dir.glob("**/*.json"):
        extra.unlink()

    shape = jax.eval_shape(lambda: prog.init(jax.random.PRNGKey(0)))
    abstract = abstract_state_like(prog.state_shardings, shape)
    step, restored = mgr.restore(abstract)
    assert step in (1, 2)  # 3 was corrupt → quarantined
    assert restored is not None
    assert 3 not in mgr.all_steps()
    mgr.close()


def test_delete_after_purges_newer_checkpoints(tmp_path):
    cfg = tiny_config(tmp_path / "ckpt")
    prog = build_train_program(cfg)
    state = prog.init(jax.random.PRNGKey(0))
    mgr = TrainCheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10)
    for s in (5, 10, 15, 20):
        mgr.save(s, state, wait=True)
    mgr.delete_after(10)
    assert mgr.all_steps() == [5, 10]
    mgr.close()


def test_supervised_job_completes_and_checkpoints(tmp_path):
    cfg = tiny_config(tmp_path / "ckpt", total_steps=12)
    job = TrainingJob("job-a", cfg, stable_margin_steps=5)
    job.start()
    job.join(timeout=300)
    assert job.status == JobStatus.COMPLETED, job.error
    assert job.current_step == 12
    assert job.ckpt.latest_step() == 12
    assert job.ckpt.last_stable_step() is not None
    d = job.describe()
    assert d["monitor"]["total_steps_seen"] == 12
    assert d["tokens_per_sec"] and d["tokens_per_sec"] > 0


def test_auto_resume_from_checkpoint(tmp_path):
    ck = tmp_path / "ckpt"
    cfg = tiny_config(ck, total_steps=10)
    job1 = TrainingJob("job-b1", cfg)
    job1.start()
    job1.join(timeout=300)
    assert job1.status == JobStatus.COMPLETED, job1.error

    # Same checkpoint dir, extended budget → resumes, does not restart at 0.
    cfg2 = tiny_config(ck, total_steps=15)
    job2 = TrainingJob("job-b2", cfg2)
    job2.start()
    job2.join(timeout=300)
    assert job2.status == JobStatus.COMPLETED, job2.error
    assert job2.resumed_from_step == 10
    assert job2.current_step == 15


def test_divergence_triggers_rollback_with_lr_cut(tmp_path):
    cfg = tiny_config(tmp_path / "ckpt", total_steps=40, checkpoint_interval_steps=5)
    prog = build_train_program(cfg)

    real_step = prog.step

    def sabotaged_step(state, batch):
        new_state, metrics = real_step(state, batch)
        step = int(jax.device_get(new_state["step"]))
        if step == 20 and not sabotaged_step.fired:
            sabotaged_step.fired = True
            metrics = dict(metrics, loss=jnp.float32(float("nan")))
        return new_state, metrics

    sabotaged_step.fired = False
    prog.step = sabotaged_step

    job = TrainingJob("job-c", cfg, program=prog, stable_margin_steps=5, max_rollbacks=2)
    job.start()
    job.join(timeout=300)
    assert job.status == JobStatus.COMPLETED, job.error
    assert job.rollback_count == 1
    # LR was cut after the rollback.
    assert float(jax.device_get(job._state["lr_scale"])) == pytest.approx(0.5)
    assert job.current_step == 40


def test_preemption_simulation_emergency_save_and_resume(tmp_path):
    ck = tmp_path / "ckpt"
    cfg = tiny_config(ck, total_steps=500, checkpoint_interval_steps=1000)

    holder = {}

    def check():  # preempt once training has made real progress
        j = holder.get("job")
        return j is not None and j.current_step >= 5

    job = TrainingJob(
        "job-d", cfg, watch_preemption=True, simulate_preemption_check=check
    )
    holder["job"] = job
    job.start()
    job.join(timeout=300)
    assert job.status == JobStatus.PREEMPTED
    assert job.preemption_reason == "gce-metadata"
    saved = job.ckpt.latest_step()
    assert saved and 0 < saved < 500  # emergency save happened mid-run

    # Auto-resume: new job, same dir → picks up at the emergency save (MTTR path).
    t0 = time.monotonic()
    cfg2 = tiny_config(ck, total_steps=saved + 3, checkpoint_interval_steps=1000)
    job2 = TrainingJob("job-d2", cfg2)
    job2.start()
    job2.join(timeout=300)
    mttr = time.monotonic() - t0
    assert job2.status == JobStatus.COMPLETED, job2.error
    assert job2.resumed_from_step == saved
    assert mttr < 90, f"auto-resume took {mttr:.1f}s (north-star target <90s)"


def test_elastic_resume_across_mesh_shapes(tmp_path):
    """TPU slices are fixed-shape, so elasticity = re-launch at a NEW mesh
    shape + resume from checkpoint (SURVEY.md §2.3, reference elasticity
    config ``deepspeed_launcher.py:226-238``). Orbax restores each leaf onto
    the new program's shardings, so a checkpoint written on (data=2, fsdp=4)
    must load into (data=1, fsdp=4, model=2) with identical values."""
    ck = tmp_path / "ckpt"
    cfg_a = tiny_config(ck, total_steps=6)
    job1 = TrainingJob("job-e1", cfg_a)
    job1.start()
    job1.join(timeout=300)
    assert job1.status == JobStatus.COMPLETED, job1.error
    q_before = jax.device_get(job1._state["params"]["layers"]["q"]["kernel"])

    # Re-launch on a different mesh: tensor parallelism instead of pure DP.
    cfg_b = tiny_config(
        ck, total_steps=9, mesh=MeshConfig(data=1, fsdp=4, model=2)
    )
    job2 = TrainingJob("job-e2", cfg_b)
    job2.start()
    job2.join(timeout=300)
    assert job2.status == JobStatus.COMPLETED, job2.error
    assert job2.resumed_from_step == 6
    assert job2.current_step == 9

    # The restored-and-resharded params actually landed tensor-parallel...
    q = job2.program.state_shardings["params"]["layers"]["q"]["kernel"]
    assert "model" in tuple(q.spec)

    # ...and the pre-resume values match what mesh A trained (restore first
    # happens before new steps mutate them, so compare via a fresh restore).
    from tpu_engine.checkpoint import abstract_state_like

    prog_b = build_train_program(cfg_b)
    shape = jax.eval_shape(lambda: prog_b.init(jax.random.PRNGKey(0)))
    abstract = abstract_state_like(prog_b.state_shardings, shape)
    step, restored = job2.ckpt.restore(abstract, step=6)
    assert step == 6
    q_after = jax.device_get(restored["params"]["layers"]["q"]["kernel"])
    assert (q_before == q_after).all()


# Compile-heavy module: excluded from the fast core run (pytest -m "not slow").
pytestmark = pytest.mark.slow
