"""HF Llama checkpoint conversion: logit-for-logit parity with transformers.

Builds a tiny randomly-initialised ``LlamaForCausalLM`` locally (no network)
and checks that the converted weights produce the same logits through this
framework's forward pass — pinning the RoPE convention, head layout, GQA
grouping, norm placement, and every transpose in the converter.
"""

import numpy as np
import pytest

pytest.importorskip("transformers")
import torch  # noqa: E402
from transformers import LlamaConfig, LlamaForCausalLM  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from tpu_engine.models import transformer as tfm  # noqa: E402
from tpu_engine.models.convert import (  # noqa: E402
    config_from_hf,
    from_hf,
    from_hf_llama,
    to_hf_llama,
)


def _tiny_hf(n_heads=4, n_kv_heads=4, seed=0):
    torch.manual_seed(seed)
    hf_cfg = LlamaConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=n_heads,
        num_key_value_heads=n_kv_heads,
        max_position_embeddings=128,
        rms_norm_eps=1e-5,
        rope_theta=10_000.0,
        attention_bias=False,
        tie_word_embeddings=False,
    )
    model = LlamaForCausalLM(hf_cfg).eval()
    return hf_cfg, model


@pytest.mark.parametrize("n_heads,n_kv", [(4, 4), (8, 2)])
def test_hf_to_ours_logit_parity(n_heads, n_kv):
    hf_cfg, model = _tiny_hf(n_heads, n_kv)
    cfg = config_from_hf(hf_cfg)
    assert cfg.n_heads == n_heads and cfg.n_kv_heads == n_kv
    params = from_hf_llama(model.state_dict(), cfg)

    tokens = np.random.default_rng(0).integers(0, 256, (2, 16))
    with torch.no_grad():
        hf_logits = model(torch.tensor(tokens)).logits.numpy()
    ours = np.asarray(
        tfm.forward(params, jnp.asarray(tokens, jnp.int32), cfg, compute_dtype=jnp.float32)
    )
    np.testing.assert_allclose(ours, hf_logits, atol=2e-3, rtol=2e-3)


def test_roundtrip_ours_to_hf():
    hf_cfg, model = _tiny_hf()
    cfg = config_from_hf(hf_cfg)
    params = from_hf_llama(model.state_dict(), cfg)
    sd = to_hf_llama(params, cfg)
    # Load back into a fresh HF model: must accept every key and reproduce
    # the original logits.
    model2 = LlamaForCausalLM(hf_cfg).eval()
    missing, unexpected = model2.load_state_dict(
        {k: torch.tensor(v) for k, v in sd.items()}, strict=False
    )
    assert not unexpected, unexpected
    # rotary inv_freq buffers may be "missing" — they are derived, not weights
    assert all("rotary" in m or "inv_freq" in m for m in missing), missing
    tokens = torch.arange(12).reshape(1, 12) % 256
    with torch.no_grad():
        a = model(tokens).logits.numpy()
        b = model2(tokens).logits.numpy()
    np.testing.assert_allclose(a, b, atol=1e-5)


def test_converted_model_generates():
    hf_cfg, model = _tiny_hf()
    cfg = config_from_hf(hf_cfg)
    params = from_hf_llama(model.state_dict(), cfg)
    from tpu_engine.generate import generate

    prompt = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    out = generate(params, prompt, cfg, max_new_tokens=5, compute_dtype=jnp.float32)
    assert out.shape == (1, 9)
    # Greedy continuation must match HF's greedy decode.
    with torch.no_grad():
        hf_out = model.generate(
            torch.tensor([[1, 2, 3, 4]]), max_new_tokens=5, do_sample=False
        )
    np.testing.assert_array_equal(np.asarray(out), hf_out.numpy())


def test_bias_checkpoints_rejected():
    hf_cfg = LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
        attention_bias=True, tie_word_embeddings=False,
    )
    model = LlamaForCausalLM(hf_cfg)
    with pytest.raises(ValueError, match="drop"):
        from_hf_llama(model.state_dict(), config_from_hf(hf_cfg))


def test_rope_scaling_rejected():
    hf_cfg = LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=1, num_attention_heads=2,
        rope_scaling={"rope_type": "linear", "factor": 2.0},
    )
    with pytest.raises(ValueError, match="rope_scaling"):
        config_from_hf(hf_cfg)


def test_decoupled_head_dim_rejected():
    hf_cfg = LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=1, num_attention_heads=2,
    )
    hf_cfg.head_dim = 32  # != 32 // 2
    with pytest.raises(ValueError, match="head_dim"):
        config_from_hf(hf_cfg)


def test_save_hf_checkpoint_roundtrip(tmp_path):
    from tpu_engine.models.convert import save_hf_checkpoint

    cfg = tfm.MODEL_CONFIGS["gpt-tiny"]
    params = tfm.init_params(jax.random.PRNGKey(5), cfg)
    out = save_hf_checkpoint(params, cfg, str(tmp_path / "export"))
    reloaded = LlamaForCausalLM.from_pretrained(out).eval()
    tokens = np.random.default_rng(2).integers(0, cfg.vocab_size, (1, 12))
    with torch.no_grad():
        hf_logits = reloaded(torch.tensor(tokens)).logits.numpy()
    ours = np.asarray(
        tfm.forward(params, jnp.asarray(tokens, jnp.int32), cfg, compute_dtype=jnp.float32)
    )
    np.testing.assert_allclose(ours, hf_logits, atol=2e-3, rtol=2e-3)


def test_moe_export_rejected():
    from tpu_engine.models.convert import hf_config_from

    with pytest.raises(ValueError, match="MoE"):
        hf_config_from(tfm.MODEL_CONFIGS["moe-tiny"])


# ---------------------------------------------------------------------------
# Mistral (sliding-window) family
# ---------------------------------------------------------------------------


def _tiny_mistral(window=8, seed=0):
    from transformers import MistralConfig, MistralForCausalLM

    torch.manual_seed(seed)
    hf_cfg = MistralConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rms_norm_eps=1e-5, rope_theta=10_000.0,
        sliding_window=window, tie_word_embeddings=False,
        attn_implementation="eager",
    )
    return hf_cfg, MistralForCausalLM(hf_cfg).eval()


def test_mistral_to_ours_logit_parity():
    """Sliding-window parity: seq 32 > window 8, so the window mask must
    actually engage for logits to agree."""
    hf_cfg, model = _tiny_mistral(window=8)
    cfg = config_from_hf(hf_cfg)
    assert cfg.sliding_window == 8 and cfg.n_kv_heads == 2
    params = from_hf_llama(model.state_dict(), cfg)

    tokens = np.random.default_rng(3).integers(0, 256, (2, 32))
    with torch.no_grad():
        hf_logits = model(torch.tensor(tokens)).logits.numpy()
    ours = np.asarray(
        tfm.forward(params, jnp.asarray(tokens, jnp.int32), cfg, compute_dtype=jnp.float32)
    )
    np.testing.assert_allclose(ours, hf_logits, atol=2e-3, rtol=2e-3)


def test_mistral_export_roundtrip(tmp_path):
    from transformers import MistralForCausalLM

    from tpu_engine.models.convert import save_hf_checkpoint

    cfg = tfm.MODEL_CONFIGS["gpt-tiny"].with_(sliding_window=16, n_kv_heads=2)
    params = tfm.init_params(jax.random.PRNGKey(7), cfg)
    out = save_hf_checkpoint(params, cfg, str(tmp_path / "mistral-export"))
    reloaded = MistralForCausalLM.from_pretrained(
        out, attn_implementation="eager").eval()
    assert reloaded.config.sliding_window == 16
    tokens = np.random.default_rng(4).integers(0, cfg.vocab_size, (1, 48))
    with torch.no_grad():
        hf_logits = reloaded(torch.tensor(tokens)).logits.numpy()
    ours = np.asarray(
        tfm.forward(params, jnp.asarray(tokens, jnp.int32), cfg, compute_dtype=jnp.float32)
    )
    np.testing.assert_allclose(ours, hf_logits, atol=2e-3, rtol=2e-3)


# ---------------------------------------------------------------------------
# GPT-2 family
# ---------------------------------------------------------------------------


def _tiny_gpt2(seed=0):
    from transformers import GPT2Config, GPT2LMHeadModel

    torch.manual_seed(seed)
    hf_cfg = GPT2Config(
        vocab_size=256, n_embd=64, n_layer=2, n_head=4, n_inner=128,
        n_positions=64, layer_norm_epsilon=1e-5, activation_function="gelu_new",
        attn_implementation="eager",
    )
    return hf_cfg, GPT2LMHeadModel(hf_cfg).eval()


def test_gpt2_to_ours_logit_parity():
    """GPT-2 parity pins LayerNorm+bias, learned positions, fused-c_attn
    split, Conv1D orientation, gelu_new, and the tied head."""
    from tpu_engine.models.convert import from_hf_gpt2

    hf_cfg, model = _tiny_gpt2()
    cfg = config_from_hf(hf_cfg)
    assert cfg.arch == "gpt2" and cfg.d_ff == 128
    params = from_hf_gpt2(model.state_dict(), cfg)

    tokens = np.random.default_rng(5).integers(0, 256, (2, 24))
    with torch.no_grad():
        hf_logits = model(torch.tensor(tokens)).logits.numpy()
    ours = np.asarray(
        tfm.forward(params, jnp.asarray(tokens, jnp.int32), cfg, compute_dtype=jnp.float32)
    )
    np.testing.assert_allclose(ours, hf_logits, atol=2e-3, rtol=2e-3)


def test_gpt2_export_roundtrip(tmp_path):
    from transformers import GPT2LMHeadModel

    from tpu_engine.models.convert import save_hf_checkpoint

    cfg = tfm.MODEL_CONFIGS["gpt2-tiny"]
    params = tfm.init_params(jax.random.PRNGKey(11), cfg)
    out = save_hf_checkpoint(params, cfg, str(tmp_path / "gpt2-export"))
    reloaded = GPT2LMHeadModel.from_pretrained(out, attn_implementation="eager").eval()
    tokens = np.random.default_rng(6).integers(0, cfg.vocab_size, (1, 20))
    with torch.no_grad():
        hf_logits = reloaded(torch.tensor(tokens)).logits.numpy()
    ours = np.asarray(
        tfm.forward(params, jnp.asarray(tokens, jnp.int32), cfg, compute_dtype=jnp.float32)
    )
    np.testing.assert_allclose(ours, hf_logits, atol=2e-3, rtol=2e-3)


def test_gpt2_unsupported_variants_rejected():
    from transformers import GPT2Config

    with pytest.raises(ValueError, match="activation_function"):
        config_from_hf(GPT2Config(activation_function="relu"))
    with pytest.raises(ValueError, match="scale_attn_by_inverse_layer_idx"):
        config_from_hf(GPT2Config(scale_attn_by_inverse_layer_idx=True))


# ---------------------------------------------------------------------------
# Gemma family
# ---------------------------------------------------------------------------


def _tiny_gemma(seed=0, n_kv=1):
    from transformers import GemmaConfig, GemmaForCausalLM

    torch.manual_seed(seed)
    hf_cfg = GemmaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=n_kv,
        head_dim=32, max_position_embeddings=128, rms_norm_eps=1e-6,
        rope_theta=10_000.0, attn_implementation="eager",
    )
    return hf_cfg, GemmaForCausalLM(hf_cfg).eval()


@pytest.mark.parametrize("n_kv", [1, 4])  # MQA (gemma-2b) and MHA (gemma-7b)
def test_gemma_to_ours_logit_parity(n_kv):
    """Pins the whole Gemma recipe against transformers: sqrt(d)-scaled
    embeddings, zero-centred RMSNorm, GeGLU, decoupled head_dim=32
    (!= 64/4 = 16), tied head, MQA grouping."""
    hf_cfg, model = _tiny_gemma(n_kv=n_kv)
    cfg = config_from_hf(hf_cfg)
    assert cfg.arch == "gemma" and cfg.head_dim == 32 and cfg.n_kv_heads == n_kv
    params = from_hf(model.state_dict(), cfg)
    assert "lm_head" not in params  # tied

    tokens = np.random.default_rng(8).integers(0, 256, (2, 16))
    with torch.no_grad():
        hf_logits = model(torch.tensor(tokens)).logits.numpy()
    ours = np.asarray(
        tfm.forward(params, jnp.asarray(tokens, jnp.int32), cfg, compute_dtype=jnp.float32)
    )
    np.testing.assert_allclose(ours, hf_logits, atol=2e-3, rtol=2e-3)


def test_gemma_export_roundtrip(tmp_path):
    from transformers import GemmaForCausalLM

    from tpu_engine.models.convert import save_hf_checkpoint

    cfg = tfm.MODEL_CONFIGS["gemma-tiny"]
    params = tfm.init_params(jax.random.PRNGKey(13), cfg)
    out = save_hf_checkpoint(params, cfg, str(tmp_path / "gemma-export"))
    reloaded = GemmaForCausalLM.from_pretrained(out, attn_implementation="eager").eval()
    assert reloaded.config.head_dim == 32
    tokens = np.random.default_rng(9).integers(0, cfg.vocab_size, (1, 24))
    with torch.no_grad():
        hf_logits = reloaded(torch.tensor(tokens)).logits.numpy()
    ours = np.asarray(
        tfm.forward(params, jnp.asarray(tokens, jnp.int32), cfg, compute_dtype=jnp.float32)
    )
    np.testing.assert_allclose(ours, hf_logits, atol=2e-3, rtol=2e-3)


def test_gemma2_features_rejected():
    from transformers import GemmaConfig

    cfg = GemmaConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=1, num_attention_heads=2,
                      num_key_value_heads=1, head_dim=16)
    cfg.final_logit_softcapping = 30.0
    with pytest.raises(ValueError, match="softcapping"):
        config_from_hf(cfg)


# Compile-heavy module: excluded from the fast core run (pytest -m "not slow").
pytestmark = pytest.mark.slow


# ---------------------------------------------------------------------------
# Qwen3 family (per-head qk-norm, decoupled head_dim)
# ---------------------------------------------------------------------------


def _tiny_qwen3(seed=0, n_kv=2, tied=False):
    from transformers import Qwen3Config, Qwen3ForCausalLM

    torch.manual_seed(seed)
    hf_cfg = Qwen3Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=n_kv,
        head_dim=32, max_position_embeddings=128, rms_norm_eps=1e-6,
        rope_theta=1_000_000.0, tie_word_embeddings=tied,
        attn_implementation="eager",
    )
    return hf_cfg, Qwen3ForCausalLM(hf_cfg).eval()


@pytest.mark.parametrize("n_kv,tied", [(2, False), (4, True)])
def test_qwen3_to_ours_logit_parity(n_kv, tied):
    """Pins the whole Qwen3 recipe against transformers: per-head qk-norm
    before RoPE, decoupled head_dim=32 (!= 64/4 = 16), GQA grouping, and
    the tied-embedding import (0.6B–4B variants materialise the tie)."""
    hf_cfg, model = _tiny_qwen3(n_kv=n_kv, tied=tied)
    cfg = config_from_hf(hf_cfg)
    assert cfg.arch == "qwen" and cfg.head_dim == 32 and cfg.n_kv_heads == n_kv
    params = from_hf(model.state_dict(), cfg)
    assert params["layers"]["q_norm"]["scale"].shape == (2, 32)
    assert "lm_head" in params  # tied variants materialise the tie

    tokens = np.random.default_rng(11).integers(0, 256, (2, 16))
    with torch.no_grad():
        hf_logits = model(torch.tensor(tokens)).logits.numpy()
    ours = np.asarray(
        tfm.forward(params, jnp.asarray(tokens, jnp.int32), cfg, compute_dtype=jnp.float32)
    )
    np.testing.assert_allclose(ours, hf_logits, atol=2e-3, rtol=2e-3)


def test_qwen3_export_roundtrip(tmp_path):
    from transformers import Qwen3ForCausalLM

    from tpu_engine.models.convert import save_hf_checkpoint

    cfg = tfm.MODEL_CONFIGS["qwen-tiny"]
    params = tfm.init_params(jax.random.PRNGKey(17), cfg)
    out = save_hf_checkpoint(params, cfg, str(tmp_path / "qwen-export"))
    reloaded = Qwen3ForCausalLM.from_pretrained(
        out, attn_implementation="eager"
    ).eval()
    assert reloaded.config.head_dim == 32
    tokens = np.random.default_rng(12).integers(0, cfg.vocab_size, (1, 24))
    with torch.no_grad():
        hf_logits = reloaded(torch.tensor(tokens)).logits.numpy()
    ours = np.asarray(
        tfm.forward(params, jnp.asarray(tokens, jnp.int32), cfg, compute_dtype=jnp.float32)
    )
    np.testing.assert_allclose(ours, hf_logits, atol=2e-3, rtol=2e-3)


def test_qwen2_rejected():
    from transformers import Qwen2Config

    with pytest.raises(ValueError, match="qwen2"):
        config_from_hf(Qwen2Config(vocab_size=64, hidden_size=32,
                                   intermediate_size=64, num_hidden_layers=1,
                                   num_attention_heads=2))
