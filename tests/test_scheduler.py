"""Fleet scheduler: queue order, HBM-aware admission, preempt-requeue.

Fast tier: jobs are thread-backed stubs (no JAX compute) driven through the
real :class:`~tpu_engine.scheduler.FleetScheduler` state machine; the real
end-to-end checkpoint round trip lives in ``test_checkpoint_supervisor.py``
(slow tier) and ``benchmarks/scheduler_sim.py``.
"""

import threading
import time

import pytest

from tpu_engine.hbm_estimate import (
    HBMEstimate,
    estimate_job_hbm,
    gang_size,
)
from tpu_engine.mesh_runtime import MeshConfig
from tpu_engine.scheduler import (
    FleetScheduler,
    JobPriority,
    QuotaExceeded,
    SubmissionState,
)
from tpu_engine.sharding import OffloadDevice, ShardingStage, TPUTrainConfig
from tpu_engine.supervisor import JobStatus
from tpu_engine.tpu_manager import TPUManager


def cfg(**kw):
    base = dict(
        model_name="gpt-tiny",
        mesh=MeshConfig(data=1, fsdp=2),
        micro_batch_size=1,
        seq_len=32,
        precision="fp32",
        total_steps=5,
        activation_checkpointing=False,
        checkpoint_dir="/tmp/sched_test",  # preemptibility flag only
    )
    base.update(kw)
    return TPUTrainConfig(**base)


def wait_until(pred, timeout=5.0, interval=0.01):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


class StubWatcher:
    def __init__(self):
        self.fired = threading.Event()

    def simulate_interruption(self):
        self.fired.set()


class StubJob:
    """Thread-backed TrainingJob stand-in: runs until the test calls
    ``finish()`` (or the scheduler stops/preempts it)."""

    def __init__(self, sub):
        self.job_id = sub.job_id
        self.config = sub.config
        self.status = JobStatus.PENDING
        self.error = None
        self.current_step = 0
        self.watcher = StubWatcher()
        self._stop = threading.Event()
        self._done = threading.Event()
        self._final = JobStatus.COMPLETED
        self._thread = threading.Thread(target=self._run, daemon=True)

    @property
    def is_alive(self):
        return self._thread.is_alive()

    def start(self):
        self._thread.start()

    def join(self, timeout=None):
        self._thread.join(timeout)

    def describe(self):
        return {"job_id": self.job_id, "status": self.status.value}

    def finish(self, status=JobStatus.COMPLETED):
        self._final = status
        self._done.set()

    def _run(self):
        self.status = JobStatus.RUNNING
        while not self._done.is_set():
            if self._stop.is_set():
                self.status = JobStatus.STOPPED
                return
            if self.watcher.fired.is_set():
                self.status = JobStatus.PREEMPTED  # the "emergency save"
                return
            self._done.wait(0.005)
        self.status = self._final


@pytest.fixture
def sched_factory():
    created = []

    def make(**kw):
        jobs = []

        def factory(sub):
            job = StubJob(sub)
            jobs.append(job)
            return job

        kw.setdefault("job_factory", factory)
        kw.setdefault("poll_interval_s", 0.01)
        # Hysteresis off by default so resize tests run at test speed; the
        # flap-plan regression test opts in with a real cooldown.
        kw.setdefault("grow_back_cooldown_s", 0.0)
        s = FleetScheduler(**kw)
        s._stub_jobs = jobs
        created.append(s)
        return s

    yield make
    for s in created:
        for j in getattr(s, "_stub_jobs", []):
            j.finish()
        s.shutdown()


# ---------------------------------------------------------------------------
# hbm_estimate
# ---------------------------------------------------------------------------


def test_gang_size_explicit_and_elastic():
    assert gang_size(cfg(mesh=MeshConfig(data=2, fsdp=4))) == 8
    elastic = cfg(mesh=MeshConfig(data=-1, fsdp=2))
    assert gang_size(elastic) == 2  # no hint → smallest legal gang
    assert gang_size(elastic, available=7) == 6  # largest multiple of fsdp
    assert gang_size(elastic, available=1) == 2  # below one block → one block


def test_estimate_known_model_breakdown():
    est = estimate_job_hbm(cfg(mesh=MeshConfig(data=2, fsdp=4)))
    assert est is not None and est.gang_devices == 8
    parts = (
        est.params_gib + est.grads_gib + est.opt_gib + est.working_gib
        + est.activations_gib + est.logits_gib
    )
    assert est.device_total_gib == pytest.approx(parts, abs=1e-3)
    assert est.device_total_gib > 0 and est.host_gib == 0


def test_estimate_unknown_model_is_none():
    assert estimate_job_hbm(cfg(model_name="nope-9b")) is None


def test_estimate_sharding_shrinks_params():
    full = estimate_job_hbm(
        cfg(mesh=MeshConfig(data=1, fsdp=4),
            sharding_stage=ShardingStage.FULL_PARTITIONING)
    )
    rep = estimate_job_hbm(
        cfg(mesh=MeshConfig(data=4, fsdp=1),
            sharding_stage=ShardingStage.DISABLED)
    )
    assert full.params_gib < rep.params_gib
    assert full.grads_gib < rep.grads_gib


def test_estimate_offload_moves_state_to_host():
    on_dev = estimate_job_hbm(cfg())
    off = estimate_job_hbm(cfg(optimizer_offload=OffloadDevice.HOST))
    assert off.opt_gib == 0 and off.host_gib > 0
    assert off.device_total_gib < on_dev.device_total_gib
    assert any("offloaded" in n for n in off.notes)


def test_estimate_is_pipeline_schedule_aware():
    """Regression: activation residency must follow the SCHEDULE — O(M+P)
    stage boundary buffers for gpipe vs O(P) for 1f1b/zb — or the
    admission gate over-rejects 1F1B/ZB gangs that actually fit (and
    under-charges GPipe at large M)."""

    def est(sched, accum):
        return estimate_job_hbm(cfg(
            mesh=MeshConfig(data=1, fsdp=2, pipe=2),
            gradient_accumulation_steps=accum,
            pipeline_schedule=sched,
        ))

    # GPipe's boundary-buffer term grows with the microbatch count; the
    # manual-vjp schedules' does not (O(P) ring, M-independent).
    assert est("gpipe", 32).activations_gib > est("gpipe", 4).activations_gib
    assert est("1f1b", 32).activations_gib == est("1f1b", 4).activations_gib
    assert est("zb", 32).activations_gib == est("zb", 4).activations_gib
    # At large M the O(P) schedules project strictly below GPipe; zb pays
    # only its bounded deferred-W stash on top of the 1f1b ring.
    assert est("zb", 32).activations_gib < est("gpipe", 32).activations_gib
    assert est("1f1b", 32).activations_gib <= est("zb", 32).activations_gib
    # "auto" resolves (M > P → zb) before projecting, same answer.
    assert est("auto", 32).activations_gib == est("zb", 32).activations_gib
    assert any("pipeline schedule" in n for n in est("auto", 32).notes)
    # Non-pipelined configs carry no schedule term or note.
    flat = estimate_job_hbm(cfg(mesh=MeshConfig(data=1, fsdp=2)))
    assert not any("pipeline schedule" in n for n in flat.notes)


# ---------------------------------------------------------------------------
# queue order / capacity
# ---------------------------------------------------------------------------


def test_priority_then_fifo_order(sched_factory):
    s = sched_factory(max_concurrent_jobs=0)  # nothing admits: pure queue
    low = s.submit(cfg(), priority=JobPriority.LOW)
    norm1 = s.submit(cfg(), priority=JobPriority.NORMAL)
    high = s.submit(cfg(), priority=JobPriority.HIGH)
    norm2 = s.submit(cfg(), priority=JobPriority.NORMAL)
    crit = s.submit(cfg(), priority=JobPriority.CRITICAL)
    order = [q["submission_id"] for q in s.queue_state()["queued"]]
    assert order == [
        crit.submission_id, high.submission_id,
        norm1.submission_id, norm2.submission_id, low.submission_id,
    ]
    assert s.queue_position(crit.submission_id) == 1
    assert s.queue_position(low.submission_id) == 5


def test_capacity_admission_and_stats(sched_factory):
    s = sched_factory(max_concurrent_jobs=2)
    subs = [s.submit(cfg()) for _ in range(3)]
    assert wait_until(lambda: len(s._stub_jobs) == 2)
    s.poll()
    assert subs[2].state == SubmissionState.QUEUED
    assert s.queue_position(subs[2].submission_id) == 1
    assert subs[2].last_skip_reason == "at max_concurrent_jobs capacity"

    s._stub_jobs[0].finish()
    assert wait_until(lambda: subs[2].state == SubmissionState.RUNNING)
    for j in s._stub_jobs:
        j.finish()
    assert wait_until(
        lambda: all(sub.state == SubmissionState.COMPLETED for sub in subs)
    )
    st = s.stats()
    assert st["submitted_total"] == 3 and st["admitted_total"] == 3
    assert st["completed_total"] == 3 and st["queue_depth"] == 0
    assert all(sub.wait_s is not None for sub in subs)


# ---------------------------------------------------------------------------
# HBM-aware gang admission against the (mock) fleet
# ---------------------------------------------------------------------------


def test_gang_larger_than_healthy_fleet_never_admits(sched_factory):
    # Mock fleet: 8 chips, chip 5 hot (88% HBM, 97% duty) → 7 healthy.
    s = sched_factory(max_concurrent_jobs=4, fleet_fn=TPUManager.get_mock_fleet)
    big = s.submit(cfg(mesh=MeshConfig(data=2, fsdp=4)), priority=JobPriority.HIGH)
    small = s.submit(cfg(mesh=MeshConfig(data=1, fsdp=2)))
    assert wait_until(lambda: small.state == SubmissionState.RUNNING)
    # Backfill admitted the small job past the unplaceable head...
    assert big.state == SubmissionState.QUEUED
    assert "gang of 8 device(s) > 7 healthy chip(s)" in big.last_skip_reason
    # ...and an unplaceable head never evicts anyone.
    assert s.preemptions_total == 0


def test_hbm_reservation_serialises_big_jobs(sched_factory):
    # Healthy mock chips have 9.6 GiB free; two 6 GiB/device gangs of 4
    # cannot coexist (7 chips, each fits ONE such job's reservation).
    def est(config, n_avail):
        return HBMEstimate(
            model_name=config.model_name,
            gang_devices=gang_size(config, n_avail),
            params_gib=6.0, grads_gib=0, opt_gib=0, working_gib=0,
            activations_gib=0, logits_gib=0, device_total_gib=6.0, host_gib=0,
        )

    s = sched_factory(
        max_concurrent_jobs=4, fleet_fn=TPUManager.get_mock_fleet,
        estimate_fn=est,
    )
    first = s.submit(cfg(mesh=MeshConfig(data=1, fsdp=4)))
    assert wait_until(lambda: first.state == SubmissionState.RUNNING)
    assert len(first.placement) == 4
    second = s.submit(cfg(mesh=MeshConfig(data=1, fsdp=4)))
    s.poll()
    assert second.state == SubmissionState.QUEUED
    assert "only 3 have that headroom" in second.last_skip_reason
    assert s.stats()["reserved_hbm_gib"] == pytest.approx(24.0)

    s._stub_jobs[0].finish()
    assert wait_until(lambda: second.state == SubmissionState.RUNNING)
    # The finished job's reservation was released before re-placement.
    assert s.stats()["reserved_hbm_gib"] == pytest.approx(24.0)


def test_estimate_none_degrades_to_capacity_only(sched_factory):
    s = sched_factory(
        max_concurrent_jobs=1, fleet_fn=TPUManager.get_mock_fleet,
        estimate_fn=lambda config, n_avail: None,
    )
    sub = s.submit(cfg(model_name="gpt-tiny"))
    assert wait_until(lambda: sub.state == SubmissionState.RUNNING)
    assert sub.estimate is None and len(sub.placement) == 2


# ---------------------------------------------------------------------------
# preempt-requeue
# ---------------------------------------------------------------------------


def test_preempt_requeue_and_priority_resume(sched_factory):
    s = sched_factory(max_concurrent_jobs=1)
    low = s.submit(cfg(), priority=JobPriority.LOW)
    assert wait_until(lambda: low.state == SubmissionState.RUNNING)
    low_job_1 = low.job

    high = s.submit(cfg(), priority=JobPriority.HIGH)
    # The head cannot be admitted at capacity → the LOW victim is told to
    # emergency-save (watcher seam), then requeued at its original seq.
    assert wait_until(lambda: low_job_1.watcher.fired.is_set())
    assert wait_until(lambda: high.state == SubmissionState.RUNNING)
    assert low.state == SubmissionState.QUEUED
    assert low.preemptions == 1 and low.attempts == 1
    assert s.requeues_total == 1 and s.preemptions_total == 1

    s._stub_jobs[-1].finish()  # high completes
    assert wait_until(lambda: low.state == SubmissionState.RUNNING)
    assert low.attempts == 2
    assert low.job is not low_job_1  # fresh attempt
    assert low.job_id == low.job.job_id  # same durable job identity
    s._stub_jobs[-1].finish()
    assert wait_until(lambda: low.state == SubmissionState.COMPLETED)


def test_requeued_victim_goes_to_front_of_its_class(sched_factory):
    s = sched_factory(max_concurrent_jobs=1)
    victim = s.submit(cfg(), priority=JobPriority.LOW)
    assert wait_until(lambda: victim.state == SubmissionState.RUNNING)
    later_low = s.submit(cfg(), priority=JobPriority.LOW)
    high = s.submit(cfg(), priority=JobPriority.HIGH)
    assert wait_until(lambda: high.state == SubmissionState.RUNNING)
    # Requeued victim keeps its ORIGINAL seq → ahead of the later LOW.
    order = [q["submission_id"] for q in s.queue_state()["queued"]]
    assert order == [victim.submission_id, later_low.submission_id]


def test_equal_priority_never_preempts(sched_factory):
    s = sched_factory(max_concurrent_jobs=1)
    first = s.submit(cfg(), priority=JobPriority.NORMAL)
    assert wait_until(lambda: first.state == SubmissionState.RUNNING)
    second = s.submit(cfg(), priority=JobPriority.NORMAL)
    time.sleep(0.1)
    s.poll()
    assert second.state == SubmissionState.QUEUED
    assert s.preemptions_total == 0
    assert first.state == SubmissionState.RUNNING


def test_non_preemptible_job_is_never_evicted(sched_factory):
    s = sched_factory(max_concurrent_jobs=1)
    # No checkpoint_dir → no emergency-save path → not preemptible.
    low = s.submit(cfg(checkpoint_dir=None), priority=JobPriority.LOW)
    assert wait_until(lambda: low.state == SubmissionState.RUNNING)
    s.submit(cfg(), priority=JobPriority.CRITICAL)
    time.sleep(0.1)
    s.poll()
    assert low.state == SubmissionState.RUNNING
    assert s.preemptions_total == 0


def test_one_eviction_frees_exactly_one_slot(sched_factory):
    s = sched_factory(max_concurrent_jobs=2)
    lows = [s.submit(cfg(), priority=JobPriority.LOW) for _ in range(2)]
    assert wait_until(
        lambda: all(x.state == SubmissionState.RUNNING for x in lows)
    )
    crit = s.submit(cfg(), priority=JobPriority.CRITICAL)
    assert wait_until(lambda: crit.state == SubmissionState.RUNNING)
    # One LOW was evicted for the one missing slot; the other kept running.
    assert s.preemptions_total == 1
    assert sum(1 for x in lows if x.state == SubmissionState.RUNNING) == 1


# ---------------------------------------------------------------------------
# quotas / cancel / drain
# ---------------------------------------------------------------------------


def test_per_submitter_quota(sched_factory):
    s = sched_factory(max_concurrent_jobs=0, default_quota=2,
                      quotas={"vip": 3})
    s.submit(cfg(), submitter="alice")
    s.submit(cfg(), submitter="alice")
    with pytest.raises(QuotaExceeded, match="alice"):
        s.submit(cfg(), submitter="alice")
    s.submit(cfg(), submitter="bob")  # separate budget
    for _ in range(3):
        s.submit(cfg(), submitter="vip")  # per-submitter override
    with pytest.raises(QuotaExceeded):
        s.submit(cfg(), submitter="vip")


def test_quota_frees_on_terminal_state(sched_factory):
    s = sched_factory(max_concurrent_jobs=0, default_quota=1)
    first = s.submit(cfg(), submitter="alice")
    with pytest.raises(QuotaExceeded):
        s.submit(cfg(), submitter="alice")
    assert s.cancel(first.submission_id)
    s.submit(cfg(), submitter="alice")  # slot freed


def test_cancel_queued_and_running(sched_factory):
    s = sched_factory(max_concurrent_jobs=1)
    running = s.submit(cfg())
    queued = s.submit(cfg())
    assert wait_until(lambda: running.state == SubmissionState.RUNNING)
    assert s.cancel(queued.submission_id)
    assert queued.state == SubmissionState.CANCELLED

    assert s.cancel(running.submission_id)
    assert wait_until(lambda: running.state == SubmissionState.CANCELLED)
    assert not s.cancel(running.submission_id)  # already terminal
    assert not s.cancel("sub_nope")
    assert s.stats()["cancelled_total"] == 2


def test_drain_pauses_admission(sched_factory):
    s = sched_factory(max_concurrent_jobs=2)
    s.drain()
    sub = s.submit(cfg())
    time.sleep(0.1)
    s.poll()
    assert sub.state == SubmissionState.QUEUED and s.draining
    s.resume_admission()
    assert wait_until(lambda: sub.state == SubmissionState.RUNNING)


def test_fleet_exception_degrades_to_capacity_only(sched_factory):
    def broken_fleet():
        raise RuntimeError("telemetry source down")

    s = sched_factory(max_concurrent_jobs=1, fleet_fn=broken_fleet)
    sub = s.submit(cfg())
    assert wait_until(lambda: sub.state == SubmissionState.RUNNING)


def test_failed_job_is_terminal_not_requeued(sched_factory):
    s = sched_factory(max_concurrent_jobs=1)
    sub = s.submit(cfg())
    assert wait_until(lambda: sub.state == SubmissionState.RUNNING)
    s._stub_jobs[0].finish(JobStatus.FAILED)
    assert wait_until(lambda: sub.state == SubmissionState.FAILED)
    assert sub.attempts == 1 and s.stats()["failed_total"] == 1


def test_job_factory_exception_fails_submission(sched_factory):
    def exploding(sub):
        raise RuntimeError("bad mesh")

    s = sched_factory(max_concurrent_jobs=1, job_factory=exploding)
    sub = s.submit(cfg())
    assert wait_until(lambda: sub.state == SubmissionState.FAILED)
    assert "bad mesh" in sub.last_skip_reason


def test_fleet_hbm_utilization_view(sched_factory):
    s = sched_factory(fleet_fn=TPUManager.get_mock_fleet)
    view = s.fleet_hbm_utilization()
    assert view is not None
    assert view["total_gib"] == pytest.approx(128.0)
    assert 0 < view["utilization_pct"] <= 100
    # No fleet source → no honest utilization number.
    assert sched_factory().fleet_hbm_utilization() is None


# ---------------------------------------------------------------------------
# elastic-shrink admission / grow-back / ledger release
# ---------------------------------------------------------------------------


def _chip(i, **kw):
    base = dict(
        index=i, device_kind="TPU v5e", hbm_total_gb=16.0, hbm_used_gb=4.0,
        duty_cycle_pct=50.0, temperature_c=50.0,
    )
    base.update(kw)
    return base


def _degraded_fleet():
    """8 chips, chip 0 thermally CRITICAL → 7 healthy."""
    mgr = TPUManager()
    return mgr.get_fleet_status(
        metrics=[_chip(0, temperature_c=91.0)] + [_chip(i) for i in range(1, 8)]
    )


def _healthy_fleet():
    mgr = TPUManager()
    return mgr.get_fleet_status(metrics=[_chip(i) for i in range(8)])


def elastic_cfg(**kw):
    base = dict(mesh=MeshConfig(data=4, fsdp=2), elastic_min_devices=2)
    base.update(kw)
    return cfg(**base)


def test_elastic_shrink_admission_on_degraded_fleet(sched_factory):
    s = sched_factory(max_concurrent_jobs=1, fleet_fn=_degraded_fleet)
    sub = s.submit(elastic_cfg())
    assert wait_until(lambda: sub.state == SubmissionState.RUNNING)
    # Gang 8 > 7 healthy, but elastic bounds admit data=3 × fsdp=2 on 6.
    assert sub.admitted_gang == 6
    assert sub.shrunk_mesh["data"] == 3 and sub.shrunk_mesh["fsdp"] == 2
    # The CRITICAL chip is never in the placement.
    assert 0 not in sub.placement and len(sub.placement) == 6
    st = s.stats()
    assert st["elastic_shrinks_total"] == 1
    assert st["running_shrunk"] == 1
    assert st["reserved_hbm_gib"] > 0


def test_non_elastic_job_still_skips_on_degraded_fleet(sched_factory):
    s = sched_factory(max_concurrent_jobs=1, fleet_fn=_degraded_fleet)
    sub = s.submit(cfg(mesh=MeshConfig(data=4, fsdp=2)))  # no elastic bounds
    time.sleep(0.1)
    assert sub.state == SubmissionState.QUEUED
    assert "gang of 8 device(s) > 7 healthy chip(s)" in sub.last_skip_reason
    assert s.stats()["elastic_shrinks_total"] == 0


def test_ledger_release_on_cancel_of_elastic_job(sched_factory):
    s = sched_factory(max_concurrent_jobs=1, fleet_fn=_degraded_fleet)
    sub = s.submit(elastic_cfg())
    assert wait_until(lambda: sub.state == SubmissionState.RUNNING)
    assert s.stats()["reserved_hbm_gib"] > 0
    assert s.cancel(sub.submission_id)
    assert wait_until(lambda: sub.state == SubmissionState.CANCELLED)
    # Every per-device reservation of the shrunk placement is returned.
    assert s.stats()["reserved_hbm_gib"] == 0.0
    assert s.stats()["running_shrunk"] == 0


def test_grow_back_when_fleet_heals(sched_factory):
    fleet_holder = {"fleet": _degraded_fleet()}
    s = sched_factory(
        max_concurrent_jobs=1, fleet_fn=lambda: fleet_holder["fleet"],
    )
    sub = s.submit(elastic_cfg())
    assert wait_until(lambda: sub.state == SubmissionState.RUNNING)
    assert sub.admitted_gang == 6
    # Chip 0 cools down → the full gang fits again → preempt-requeue-regrow.
    fleet_holder["fleet"] = _healthy_fleet()
    assert wait_until(
        lambda: sub.state == SubmissionState.RUNNING and sub.admitted_gang == 8,
        timeout=10.0,
    )
    assert sub.shrunk_mesh is None
    assert sub.attempts == 2
    st = s.stats()
    assert st["grow_backs_total"] == 1
    assert st["requeues_total"] == 1
    assert st["running_shrunk"] == 0
    # The ledger re-reserved for the full gang exactly once: all 8 chips,
    # and everything is returned when the job finishes.
    s._stub_jobs[-1].finish()
    assert wait_until(lambda: sub.state == SubmissionState.COMPLETED)
    assert s.stats()["reserved_hbm_gib"] == 0.0


def test_grow_back_waits_for_queued_work(sched_factory):
    """Queued submissions have first claim on freed chips — a shrunk job is
    not grown while anything is waiting in the queue."""
    fleet_holder = {"fleet": _degraded_fleet()}
    s = sched_factory(
        max_concurrent_jobs=1, fleet_fn=lambda: fleet_holder["fleet"],
    )
    sub = s.submit(elastic_cfg())
    assert wait_until(lambda: sub.state == SubmissionState.RUNNING)
    blocked = s.submit(cfg())  # queued: max_concurrent_jobs=1
    fleet_holder["fleet"] = _healthy_fleet()
    time.sleep(0.2)
    assert sub.admitted_gang == 6  # no grow-back while the queue is non-empty
    assert s.stats()["grow_backs_total"] == 0
    s._stub_jobs[0].finish()
    assert wait_until(lambda: blocked.state == SubmissionState.RUNNING)


def test_grow_back_hysteresis_rides_out_chip_flap(sched_factory):
    """A chip flapping healthy/unhealthy faster than the cooldown costs the
    job ONE elastic shrink — not a preempt-requeue storm. Regression for
    the pre-cooldown behavior where every heal window fired a grow-back
    that the next fault immediately re-shrank."""
    from tpu_engine import faults as faults_mod
    from tpu_engine.faults import FaultInjector, FaultKind, FaultPlan, FaultSpec

    # Chip 0 flaps: unhealthy for one injector step at steps 0, 2, 4, ...
    plan = FaultPlan(specs=[
        FaultSpec(
            kind=FaultKind.CHIP_UNHEALTHY, at_step=at, device_index=0,
            duration_steps=1,
        )
        for at in (0, 2, 4, 6, 8)
    ])
    inj = FaultInjector(plan)
    faults_mod.set_active(inj)
    try:
        inj.observe_step(0)  # chip 0 down at admission time
        mgr = TPUManager()
        s = sched_factory(
            max_concurrent_jobs=1,
            fleet_fn=lambda: mgr.get_fleet_status(
                metrics=[_chip(i) for i in range(8)]
            ),
            grow_back_cooldown_s=3600.0,  # cooldown >> the whole flap train
        )
        sub = s.submit(elastic_cfg())
        assert wait_until(lambda: sub.state == SubmissionState.RUNNING)
        assert sub.admitted_gang == 6 and 0 not in sub.placement
        # Drive the flap train: each odd step heals chip 0, each even step
        # re-faults it, with several scheduler passes inside every phase.
        for step in range(1, 10):
            inj.observe_step(step)
            time.sleep(0.06)
        st = s.stats()
        assert st["grow_backs_total"] == 0
        assert st["requeues_total"] == 0
        assert sub.attempts == 1 and sub.admitted_gang == 6
        assert sub.state == SubmissionState.RUNNING
        # Flap train exhausted (chip stays healthy). Once the operator's
        # cooldown has elapsed the ONE grow-back proceeds as usual.
        s.grow_back_cooldown_s = 0.0
        assert wait_until(
            lambda: sub.state == SubmissionState.RUNNING
            and sub.admitted_gang == 8,
            timeout=10.0,
        )
        assert s.stats()["grow_backs_total"] == 1
    finally:
        faults_mod.set_active(None)


def test_per_submitter_wait_and_goodput_stats(sched_factory):
    """Multi-tenant observability: queue wait and device-holding goodput
    are attributed per submitter, so a noisy neighbour shows up as THEIR
    numbers, not an anonymous fleet average."""
    s = sched_factory(max_concurrent_jobs=1)
    a = s.submit(cfg(), submitter="alice")
    assert wait_until(lambda: a.state == SubmissionState.RUNNING)
    b = s.submit(cfg(), submitter="bob")  # queued behind alice
    time.sleep(0.05)
    per = s.stats()["per_submitter"]
    assert per["alice"]["running"] == 1 and per["alice"]["queued"] == 0
    assert per["bob"]["queued"] == 1 and per["bob"]["running"] == 0

    s._stub_jobs[0].finish()
    assert wait_until(lambda: a.state == SubmissionState.COMPLETED)
    assert wait_until(lambda: b.state == SubmissionState.RUNNING)
    s._stub_jobs[1].finish()
    assert wait_until(lambda: b.state == SubmissionState.COMPLETED)
    per = s.stats()["per_submitter"]
    assert per["alice"]["completed_total"] == 1
    assert per["bob"]["completed_total"] == 1
    # Goodput: both held the device for a measurable interval.
    assert per["alice"]["goodput_busy_s"] > 0
    assert per["bob"]["goodput_busy_s"] > 0
    # Bob queued behind alice's run; alice was admitted immediately.
    assert per["bob"]["mean_wait_s"] >= per["alice"]["mean_wait_s"]


# ---------------------------------------------------------------------------
# placement planner wiring: mesh="auto", structured no_estimate, partial grow
# ---------------------------------------------------------------------------


def test_auto_placement_admits_predicted_fastest(sched_factory):
    s = sched_factory(max_concurrent_jobs=1, fleet_fn=_healthy_fleet)
    sub = s.submit(cfg(mesh=MeshConfig(data=-1, fsdp=2)), mesh="auto")
    assert sub.auto_place
    assert wait_until(lambda: sub.state == SubmissionState.RUNNING)
    # data=-1 means best available: the planner lands on the full fleet.
    assert sub.admitted_gang == 8
    plan = sub.placement_plan
    assert plan and plan["feasible"] > 0 and plan["label"]
    assert plan["chosen"]["mesh"]["data"] * plan["chosen"]["mesh"]["fsdp"] * \
        plan["chosen"]["mesh"]["pipe"] * plan["chosen"]["mesh"]["model"] == 8
    assert sub.predicted_step_time_s > 0
    st = s.stats()
    assert st["auto_admissions_total"] == 1
    assert st["placement"]["plans_chosen_total"] == 1
    # The queue surface carries the chosen plan for operators.
    running = s.queue_state()["running"]
    assert running[0]["placement_plan"]["label"] == plan["label"]


def test_auto_placement_resizes_on_degraded_fleet(sched_factory):
    s = sched_factory(max_concurrent_jobs=1, fleet_fn=_degraded_fleet)
    sub = s.submit(cfg(mesh=MeshConfig(data=-1, fsdp=1)), mesh="auto")
    assert wait_until(lambda: sub.state == SubmissionState.RUNNING)
    # 7 healthy chips: the plan is sized to the healthy remainder and the
    # CRITICAL chip is never in the placement.
    assert sub.admitted_gang == 7
    assert 0 not in sub.placement and len(sub.placement) == 7


def test_auto_placement_refuses_unknown_model(sched_factory):
    s = sched_factory(max_concurrent_jobs=1)
    with pytest.raises(ValueError, match="no_estimate:nope-9b"):
        s.submit(cfg(model_name="nope-9b"), mesh="auto")
    assert s.stats()["placement"]["no_estimate_refusals_total"] == 1
    # The refusal never entered the queue.
    assert s.stats()["submitted_total"] == 0


def test_auto_placement_rejects_bad_mesh_arg(sched_factory):
    s = sched_factory(max_concurrent_jobs=1)
    with pytest.raises(ValueError, match="mesh must be"):
        s.submit(cfg(), mesh="magic")


def test_unknown_model_explicit_mesh_gets_structured_reason(sched_factory):
    """estimate_job_hbm → None for an unknown model: admission still
    proceeds capacity-only (missing telemetry must not brick the queue)
    but the queue surface names WHY there is no HBM estimate."""
    s = sched_factory(max_concurrent_jobs=1, fleet_fn=_healthy_fleet)
    sub = s.submit(cfg(model_name="nope-9b", mesh=MeshConfig(data=1, fsdp=2)))
    assert wait_until(lambda: sub.state == SubmissionState.RUNNING)
    assert sub.last_skip_reason == "no_estimate:nope-9b"
    running = s.queue_state()["running"]
    assert running[0]["last_skip_reason"] == "no_estimate:nope-9b"
    assert s.stats()["no_estimate_skips_total"] == 1


def _three_down_fleet():
    """8 chips, chips 0-2 thermally CRITICAL → 5 healthy."""
    mgr = TPUManager()
    return mgr.get_fleet_status(
        metrics=[_chip(i, temperature_c=91.0) for i in range(3)]
        + [_chip(i) for i in range(3, 8)]
    )


def test_partial_grow_back_with_chip_still_unhealthy(sched_factory):
    """Regression (ROADMAP carry-over): when SOME of the sick chips heal,
    the shrunk job grows to the largest feasible INTERMEDIATE mesh — the
    full-gang-only logic waited for a perfectly healthy fleet."""
    fleet_holder = {"fleet": _three_down_fleet()}
    s = sched_factory(
        max_concurrent_jobs=1, fleet_fn=lambda: fleet_holder["fleet"],
    )
    sub = s.submit(elastic_cfg())
    assert wait_until(lambda: sub.state == SubmissionState.RUNNING)
    assert sub.admitted_gang == 4  # data=2 × fsdp=2 on the 5 healthy
    # Chips 1-2 heal; chip 0 stays CRITICAL → 7 healthy. Full gang (8)
    # still cannot be placed, but data=3 × fsdp=2 on 6 can.
    fleet_holder["fleet"] = _degraded_fleet()
    assert wait_until(
        lambda: sub.state == SubmissionState.RUNNING
        and sub.admitted_gang == 6,
        timeout=10.0,
    )
    assert sub.shrunk_mesh["data"] == 3 and sub.shrunk_mesh["fsdp"] == 2
    assert 0 not in sub.placement
    assert s.stats()["grow_backs_total"] == 1
    # The last chip heals → the second grow reaches the full gang.
    fleet_holder["fleet"] = _healthy_fleet()
    assert wait_until(
        lambda: sub.state == SubmissionState.RUNNING
        and sub.admitted_gang == 8,
        timeout=10.0,
    )
    assert sub.shrunk_mesh is None
    assert s.stats()["grow_backs_total"] == 2


def test_grow_back_is_hbm_gated(sched_factory):
    """Healed chips whose HBM headroom cannot hold the job's projection
    must not trigger a grow-back — preempting into an admission that
    re-shrinks is a flap, not a grow."""

    def big_est(c, available=None):
        # 8 GiB/device: with the planner's 35% compile margin the grow
        # needs 10.8 GiB headroom — the 12 GiB-free healthy chips clear
        # it, the nearly-full healed chip below cannot.
        return HBMEstimate(
            model_name=c.model_name, gang_devices=8,
            params_gib=8.0, grads_gib=0.0, opt_gib=0.0, working_gib=0.0,
            activations_gib=0.0, logits_gib=0.0,
            device_total_gib=8.0, host_gib=0.0,
        )

    fleet_holder = {"fleet": _degraded_fleet()}
    s = sched_factory(
        max_concurrent_jobs=1, fleet_fn=lambda: fleet_holder["fleet"],
    )
    sub = s.submit(elastic_cfg(), estimate_fn=big_est)
    assert wait_until(lambda: sub.state == SubmissionState.RUNNING)
    assert sub.admitted_gang == 6
    # Chip 0 heals but comes back nearly full: 1 GiB free < the job's
    # margined 10.8 GiB/device projection — the full gang cannot be placed.
    mgr = TPUManager()
    fleet_holder["fleet"] = mgr.get_fleet_status(
        metrics=[_chip(0, hbm_used_gb=15.0)] + [_chip(i) for i in range(1, 8)]
    )
    time.sleep(0.3)
    assert sub.admitted_gang == 6 and sub.attempts == 1
    assert s.stats()["grow_backs_total"] == 0
    # Once the chip's HBM actually drains, the grow-back proceeds.
    fleet_holder["fleet"] = _healthy_fleet()
    assert wait_until(
        lambda: sub.state == SubmissionState.RUNNING
        and sub.admitted_gang == 8,
        timeout=10.0,
    )
    assert s.stats()["grow_backs_total"] == 1


# ---------------------------------------------------------------------------
# heterogeneity policy: rebalance-over-shrink consults, quarantine lifecycle
# ---------------------------------------------------------------------------


def _slow_rebalancer(n=2, slow=1, signals=40, **kw):
    """A live-mode rebalancer whose tracker reads process 1 at ~0.5 —
    imbalance 2.0, best rebalance goodput ~0.89 (above the 0.80 floor)."""
    from tpu_engine import hetero as hetero_mod

    trk = hetero_mod.ThroughputTracker(n)
    for _ in range(signals):
        trk.note_host_slow(slow, 1.0, 1.0)
    kw.setdefault("sustain_consults", 1)
    kw.setdefault("min_gain", 0.01)
    kw.setdefault("dry_run", False)
    return hetero_mod.HeteroRebalancer(trk, 8, **kw)


def test_hetero_prefers_consult_over_shrink_and_settles_later(sched_factory):
    s = sched_factory(max_concurrent_jobs=1, fleet_fn=_healthy_fleet,
                      poll_interval_s=60.0, hetero_cooldown_s=0.0)
    sub = s.submit(cfg())
    assert wait_until(lambda: sub.state == SubmissionState.RUNNING)
    reb = _slow_rebalancer()
    s._stub_jobs[0]._hetero = reb
    s.poll()
    # The scheduler never moves rows itself: it requests a consult that
    # the supervisor serves at its next step boundary.
    assert reb.consult_pending()
    assert reb.rebalances_total == 0
    assert sub.state == SubmissionState.RUNNING  # every chip kept
    assert s._hetero_quarantined == {}
    st = s.stats()["hetero"]
    assert st["rebalance_preferred_total"] == 1
    assert st["shrinks_avoided_total"] == 0  # nothing has settled yet
    assert st["rebalances_total"] == 0
    # Re-polling while the consult is outstanding must not double-count.
    s.poll()
    assert s.stats()["hetero"]["rebalance_preferred_total"] == 1
    # The job's rebalancer serves the consult (what the supervisor does at
    # the step boundary) — only then does the shrink count as avoided.
    plan = reb.maybe_rebalance(10)
    assert plan is not None and not plan.dry_run
    assert not reb.consult_pending()
    s.poll()
    st = s.stats()["hetero"]
    assert st["shrinks_avoided_total"] == 1
    assert st["rebalances_total"] == 1
    assert st["shrinks_total"] == 0


def test_hetero_declined_consult_is_not_counted_as_avoided(sched_factory):
    s = sched_factory(max_concurrent_jobs=1, fleet_fn=_healthy_fleet,
                      poll_interval_s=60.0, hetero_cooldown_s=0.0)
    sub = s.submit(cfg())
    assert wait_until(lambda: sub.state == SubmissionState.RUNNING)
    # min_gain=1.0: the rebalancer will always decline on the gain floor.
    reb = _slow_rebalancer(min_gain=1.0)
    s._stub_jobs[0]._hetero = reb
    s.poll()
    assert reb.consult_pending()
    assert s.stats()["hetero"]["rebalance_preferred_total"] == 1
    assert reb.maybe_rebalance(10) is None  # consult served, declined
    s.poll()
    st = s.stats()["hetero"]
    # Forgotten, not a win — and since the imbalance persists, the same
    # pass opens a fresh consult rather than silently giving up.
    assert st["shrinks_avoided_total"] == 0
    assert st["rebalances_total"] == 0
    assert st["rebalance_preferred_total"] == 2
    assert reb.consult_pending()


def test_hetero_shrink_quarantines_with_owner_and_ttl_backstop(sched_factory):
    # Fixed gang 8 so the preempted job cannot re-admit on the 4 chips
    # left after quarantine — the entries must then expire via TTL.
    s = sched_factory(max_concurrent_jobs=1, fleet_fn=_healthy_fleet,
                      poll_interval_s=60.0, grow_back=False,
                      hetero_cooldown_s=0.0, hetero_goodput_floor=2.0,
                      hetero_quarantine_ttl_s=0.05)
    sub = s.submit(cfg(mesh=MeshConfig(data=4, fsdp=2)))
    assert wait_until(lambda: sub.state == SubmissionState.RUNNING)
    job = s._stub_jobs[0]
    job._hetero = _slow_rebalancer()
    s.poll()
    # Floor unreachable -> shrink: the slow host's chips are quarantined
    # with their owner recorded, and the job is preempt-requeued.
    assert sub.state == SubmissionState.PREEMPTING
    assert set(s._hetero_quarantined) == {4, 5, 6, 7}
    assert all(e["owner"] == sub.submission_id
               for e in s._hetero_quarantined.values())
    assert s.stats()["hetero"]["shrinks_total"] == 1
    assert wait_until(lambda: not job.is_alive)
    s.poll()  # reap -> requeue; gang 8 > 4 eligible -> stays QUEUED
    assert sub.state == SubmissionState.QUEUED
    assert set(s._hetero_quarantined) == {4, 5, 6, 7}
    # TTL is the backstop for exactly this shape: the requeued attempt has
    # no tracker that could ever vouch for the quarantined chips.
    time.sleep(0.06)
    s.poll()  # heal runs after _admit: this pass only releases the chips
    assert s._hetero_quarantined == {}
    s.poll()  # ...and the next one admits the full gang again
    assert sub.state == SubmissionState.RUNNING
    assert sub.admitted_gang == 8


def test_hetero_quarantine_released_when_owner_reaches_terminal_state(sched_factory):
    s = sched_factory(max_concurrent_jobs=1, fleet_fn=_healthy_fleet,
                      poll_interval_s=60.0, grow_back=False,
                      hetero_cooldown_s=0.0, hetero_goodput_floor=2.0)
    sub = s.submit(cfg(mesh=MeshConfig(data=4, fsdp=2)))
    assert wait_until(lambda: sub.state == SubmissionState.RUNNING)
    job = s._stub_jobs[0]
    job._hetero = _slow_rebalancer()
    s.poll()
    assert set(s._hetero_quarantined) == {4, 5, 6, 7}
    # The owner is cancelled while quarantined: terminal submissions stay
    # in scheduler history forever, so the entries must not wait for them.
    s.cancel(sub.submission_id)
    assert wait_until(lambda: not job.is_alive)
    s.poll()  # reap -> CANCELLED (terminal, but kept in history)
    assert wait_until(lambda: sub.state == SubmissionState.CANCELLED)
    s.poll()
    assert s._hetero_quarantined == {}


def test_hetero_quarantine_no_tracker_release_on_readmission(sched_factory):
    # Elastic gang: after the shrink the job re-admits on the remaining 4
    # chips — the fresh attempt has no heterogeneity plane, so nothing can
    # ever vouch for the quarantined chips and they are released at once
    # (the detector re-quarantines if the host is still slow).
    s = sched_factory(max_concurrent_jobs=1, fleet_fn=_healthy_fleet,
                      poll_interval_s=60.0, grow_back=False,
                      hetero_cooldown_s=0.0, hetero_goodput_floor=2.0)
    sub = s.submit(elastic_cfg())
    assert wait_until(lambda: sub.state == SubmissionState.RUNNING)
    job = s._stub_jobs[0]
    job._hetero = _slow_rebalancer()
    s.poll()
    assert set(s._hetero_quarantined) == {4, 5, 6, 7}
    assert wait_until(lambda: not job.is_alive)
    s.poll()  # reap -> requeue -> shrunk re-admit -> heal (no tracker)
    assert sub.state == SubmissionState.RUNNING
    assert sub.admitted_gang == 4
    assert 4 not in sub.placement  # admitted around the quarantine
    assert s._hetero_quarantined == {}


def test_hetero_quarantine_heals_per_process_estimate(sched_factory):
    s = sched_factory(max_concurrent_jobs=1, fleet_fn=_healthy_fleet,
                      poll_interval_s=60.0, grow_back=False)
    sub = s.submit(cfg())
    assert wait_until(lambda: sub.state == SubmissionState.RUNNING)
    s._stub_jobs[0]._hetero = _slow_rebalancer()  # proc 0 at 1.0, proc 1 ~0.5
    now = time.time()
    s._hetero_quarantined[0] = {"owner": sub.submission_id, "ts": now}
    s._hetero_quarantined[7] = {"owner": sub.submission_id, "ts": now}
    s.poll()
    # Chip 0 belongs to the healthy process (1.0 >= heal threshold 0.95);
    # chip 7's process still reads ~0.5 and stays out of admission.
    assert 0 not in s._hetero_quarantined
    assert 7 in s._hetero_quarantined


# ---------------------------------------------------------------------------
# Metrics scrape cost: index-backed, read-only
# ---------------------------------------------------------------------------


def test_metrics_scrape_is_readonly_and_index_backed(sched_factory):
    """A scrape (``stats()``) reads the state indexes: it never iterates
    ``_subs`` — so its cost is O(queued + running + tenants), not O(every
    submission the scheduler has ever seen) — and never mutates state."""
    s = sched_factory(max_concurrent_jobs=4)
    done = [s.submit(cfg()) for _ in range(12)]
    for _ in range(200):
        for j in s._stub_jobs:
            j.finish()
        if all(d.state == SubmissionState.COMPLETED for d in done):
            break
        time.sleep(0.02)
    assert all(d.state == SubmissionState.COMPLETED for d in done)
    s.max_concurrent_jobs = 0  # freeze admission: deterministic queue
    queued = [s.submit(cfg(), priority=JobPriority.LOW) for _ in range(6)]

    class CountingSubs(dict):
        scans = 0

        def values(self):
            CountingSubs.scans += 1
            return super().values()

        def items(self):
            CountingSubs.scans += 1
            return super().items()

        def __iter__(self):
            CountingSubs.scans += 1
            return super().__iter__()

    states_before = {sid: sub.state for sid, sub in s._subs.items()}
    s._subs = CountingSubs(s._subs)
    CountingSubs.scans = 0
    try:
        first = s.stats()
        second = s.stats()
        assert CountingSubs.scans == 0, (
            "stats() scanned _subs — scrape cost grew with terminal history"
        )
        # queue_state() reads the queued/running/finished indexes too:
        # rendering "finished" is O(terminal) because that is the size of
        # the answer, never a _subs scan.
        qs = s.queue_state()
        assert CountingSubs.scans == 0, (
            "queue_state() scanned _subs — history surface lost its index"
        )
    finally:
        s._subs = dict(s._subs)
    # Read-only: repeated scrapes agree (modulo the wall-clock age of the
    # oldest queued entry) and no submission changed state.
    first.pop("oldest_queued_wait_s")
    second.pop("oldest_queued_wait_s")
    assert first == second
    assert {sid: sub.state for sid, sub in s._subs.items()} == states_before
    assert [q["submission_id"] for q in qs["queued"]] == [
        q.submission_id for q in queued
    ]
    assert len(qs["finished"]) == 12
